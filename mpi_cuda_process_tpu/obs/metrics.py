"""In-process metrics registry: the live face of the telemetry stream.

The obs/ layer built in rounds 10-13 is strictly post-hoc — manifests,
chunk stats, heartbeat verdicts, and supervisor restart trails are JSONL
files you read *after* (or tail by hand during) a run.  This module is
the in-memory aggregate those files already imply: counters, gauges and
bounded-reservoir histograms populated **purely from the events the
recorder already emits at chunk boundaries** — nothing here touches jax
tracing, the jitted step, or the run loop (the zero-ops invariant of
``tests/test_obs.py`` extends to a served run by construction: the
registry only ever sees records that were going to be written anyway).

Two layers:

* :class:`MetricsRegistry` — a generic, pure-stdlib metric store.
  Every mutation and every read happens under ONE registry lock, so a
  :meth:`~MetricsRegistry.snapshot` (and the ``/metrics`` scrape built
  on it) is **snapshot-consistent**: a reader can never observe half of
  a multi-metric update (pinned by a concurrent-ingest test).
  Histograms keep a bounded reservoir of the newest observations (count
  / sum / min / max remain exact over the full stream) and report
  nearest-rank p50/p90/p99.

* :class:`RunMetrics` — the obs-vocabulary ingester: feed it manifest /
  chunk / costmodel / heartbeat / launch / restart / label / summary
  records (:meth:`RunMetrics.ingest`) and it maintains both the
  Prometheus-facing registry (steps/s, Gcells/s, compile vs steady
  split, recompile count, device-memory peak, exchange mode, heartbeat
  verdict, supervisor restart count, roofline predicted-vs-measured
  gap) and the structured :meth:`RunMetrics.status` payload — the
  remote answer to "is it wedged?" that ``obs/serve.py`` exposes as
  ``/status.json``.

Pure stdlib: importable from anywhere (including the supervisor parent
watching a wedged child) without dragging a jax backend in.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

_QUANTILES = (0.5, 0.9, 0.99)


def quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile of an already-sorted list (NaN when empty)."""
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _prom_name(name: str) -> str:
    return "".join(c if (c.isalnum() or c in "_:") else "_" for c in name)


def _prom_label_value(v: Any) -> str:
    s = str(v)
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{_prom_name(str(k))}="{_prom_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _prom_value(v: Any) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Counter:
    """Monotone counter.  Mutate only through the owning registry's lock
    (the registry's ``inc`` helper, or inside ``with registry.lock``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def render(self) -> List[str]:
        return [f"{_prom_name(self.name)} {_prom_value(self.value)}"]

    def snap(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value": self.value}


class Gauge:
    """Last-value (or peak, via :meth:`set_max`) gauge."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)

    def set_max(self, v: float) -> None:
        v = float(v)
        if self.value is None or v > self.value:
            self.value = v

    def render(self) -> List[str]:
        if self.value is None:
            return []
        return [f"{_prom_name(self.name)} {_prom_value(self.value)}"]

    def snap(self) -> Dict[str, Any]:
        return {"kind": self.kind, "value": self.value}


class Info:
    """Constant-1 gauge whose payload is its labels (the Prometheus
    ``_info`` idiom): run identity, exchange mode, heartbeat verdict."""

    kind = "info"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.labels: Dict[str, Any] = {}

    def set(self, **labels: Any) -> None:
        self.labels = {k: v for k, v in labels.items() if v is not None}

    def render(self) -> List[str]:
        if not self.labels:
            return []
        return [f"{_prom_name(self.name)}{_prom_labels(self.labels)} 1"]

    def snap(self) -> Dict[str, Any]:
        return {"kind": self.kind, "labels": dict(self.labels)}


class GaugeFamily:
    """Labeled gauge family: one value per label set, rendered as one
    Prometheus line each (``name{label="..",backend=".."} v``) — the
    shape the ledger's ``best_known`` table exports as (label x backend
    baselines on ``/metrics``, so the live console and the ledger stop
    being separate surfaces)."""

    kind = "gauge_family"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.values: "collections.OrderedDict[Tuple, Tuple[Dict[str, Any], float]]" = \
            collections.OrderedDict()

    def set(self, value: float, **labels: Any) -> None:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        self.values[key] = (dict(labels), float(value))

    def render(self) -> List[str]:
        name = _prom_name(self.name)
        return [f"{name}{_prom_labels(labels)} {_prom_value(v)}"
                for labels, v in self.values.values()]

    def snap(self) -> Dict[str, Any]:
        return {"kind": self.kind,
                "values": [{"labels": dict(labels), "value": v}
                           for labels, v in self.values.values()]}


class Histogram:
    """Bounded-reservoir histogram: newest ``bound`` observations.

    ``count``/``sum``/``min``/``max`` stay exact over the whole stream;
    the quantiles (nearest-rank p50/p90/p99) are computed over the
    reservoir — for the chunk-cadence streams this serves (hundreds of
    observations per run) the reservoir usually IS the stream, and for
    multi-day runs the sliding window is the more useful statistic
    anyway (a throughput regression three hours ago should not hide in
    a lifetime median).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "", bound: int = 512):
        self.name = name
        self.help = help
        self.bound = max(1, int(bound))
        self.reservoir: Deque[float] = collections.deque(maxlen=self.bound)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.reservoir.append(v)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantiles(self) -> Dict[float, float]:
        vals = sorted(self.reservoir)
        return {q: quantile(vals, q) for q in _QUANTILES}

    def render(self) -> List[str]:
        name = _prom_name(self.name)
        out = []
        for q, v in self.quantiles().items():
            out.append(f'{name}{{quantile="{q}"}} {_prom_value(v)}')
        out.append(f"{name}_count {_prom_value(self.count)}")
        out.append(f"{name}_sum {_prom_value(self.sum)}")
        return out

    def snap(self) -> Dict[str, Any]:
        return {"kind": self.kind, "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max,
                "quantiles": {str(q): v
                              for q, v in self.quantiles().items()}}


# Prometheus TYPE vocabulary for each metric class (Info renders as a
# gauge; the bounded-reservoir histogram renders as a summary — it
# exposes quantiles, not cumulative buckets).
_PROM_TYPE = {"counter": "counter", "gauge": "gauge", "info": "gauge",
              "gauge_family": "gauge", "histogram": "summary"}


class MetricsRegistry:
    """Ordered, lock-consistent metric store.

    All get-or-create accessors take the lock themselves; bulk updates
    that must be atomic as a GROUP (one ingested event touching several
    metrics) wrap themselves in ``with registry.lock`` — the accessors
    use an RLock so both patterns compose.
    """

    def __init__(self):
        self.lock = threading.RLock()
        self._metrics: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()

    def _get(self, cls, name: str, help: str, **kw: Any):
        with self.lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def info(self, name: str, help: str = "") -> Info:
        return self._get(Info, name, help)

    def gauge_family(self, name: str, help: str = "") -> GaugeFamily:
        return self._get(GaugeFamily, name, help)

    def histogram(self, name: str, help: str = "",
                  bound: int = 512) -> Histogram:
        return self._get(Histogram, name, help, bound=bound)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """A consistent point-in-time view of every metric."""
        with self.lock:
            return {name: m.snap() for name, m in self._metrics.items()}

    def to_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of the registry."""
        with self.lock:
            lines: List[str] = []
            for name, m in self._metrics.items():
                body = m.render()
                if not body:
                    continue
                if m.help:
                    lines.append(f"# HELP {_prom_name(name)} {m.help}")
                lines.append(f"# TYPE {_prom_name(name)} "
                             f"{_PROM_TYPE[m.kind]}")
                lines.extend(body)
            return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------- obs ingester

def _grid_cells(run: Dict[str, Any]) -> Optional[int]:
    grid = run.get("grid")
    if isinstance(grid, (list, tuple)) and grid and \
            all(isinstance(g, int) for g in grid):
        cells = 1
        for g in grid:
            cells *= g
        ens = run.get("ensemble")
        if isinstance(ens, int) and ens > 0:
            cells *= ens
        return cells
    return None


class RunMetrics:
    """The obs-event vocabulary, folded into a registry + status payload.

    One instance aggregates an arbitrary MERGED stream of obs records —
    a single CLI run, or a supervisor log interleaved with its
    children's logs across restarts, or a whole campaign directory.
    The first manifest seen is the run's identity; later manifests
    (child attempts, campaign labels) are counted and tracked as
    sources.  Every :meth:`ingest` holds the registry lock for the
    whole record, so a concurrent snapshot sees each event's metrics
    either fully applied or not at all.
    """

    def __init__(self, max_chunks: int = 240, max_errors: int = 20):
        self.registry = MetricsRegistry()
        self.manifest: Optional[Dict[str, Any]] = None
        self.manifests_seen = 0
        self.events_seen = 0
        self.latest_chunk: Optional[Dict[str, Any]] = None
        self.chunks_recent: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=max_chunks)
        self.costmodel: Optional[Dict[str, Any]] = None
        self.exchange: Optional[Dict[str, Any]] = None
        self.heartbeat: Optional[Dict[str, Any]] = None
        # numerics sentinel (round 17): latest health check + audit —
        # a DIVERGED health verdict dominates the status verdict (a
        # fast, alive, WRONG run must never read as healthy)
        self.health: Optional[Dict[str, Any]] = None
        self.halo_audit: Optional[Dict[str, Any]] = None
        # run doctor (obs/anomaly.py): recent performance findings —
        # any finding turns the status verdict DEGRADED (dominated by
        # every harder verdict: a slow run is not a dead run)
        self.anomalies: Deque[Dict[str, Any]] = collections.deque(maxlen=32)
        self.anomalies_total = 0
        self.anomaly_kinds: Dict[str, int] = {}
        self.summary: Optional[Dict[str, Any]] = None
        # cooperative cancel (cancellation.py): a third terminal state
        # — neither summary nor error; the status verdict reports it
        self.cancelled: Optional[Dict[str, Any]] = None
        # serving-scheduler aggregate (serving/scheduler.py events):
        # queue depth, slot occupancy, per-op and per-tenant counters —
        # rendered under status()["scheduler"] and the obs_top panel
        self.scheduler: Optional[Dict[str, Any]] = None
        # fleet-router aggregate (serving/router.py events): replica
        # liveness, routing/rebalance counters — status()["router"]
        # and the obs_top fleet panel
        self.router: Optional[Dict[str, Any]] = None
        # elastic-engine trail (policy/select.py + parallel/reshard.py):
        # the active auto-policy decision and every live migration, so
        # an operator can see what the engine decided and why
        self.policy: Optional[Dict[str, Any]] = None
        self.migrations: List[Dict[str, Any]] = []
        self.launches: List[Dict[str, Any]] = []
        self.restarts: List[Dict[str, Any]] = []
        self.give_up: Optional[Dict[str, Any]] = None
        self.resumed_from_step: Optional[int] = None
        self.labels: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        # coupled-run group table (round 18, parallel/groups.py): one
        # row per device group, seeded from the manifest's ``groups``
        # block and refreshed by group_chunk / per-group health events
        self.groups: "collections.OrderedDict[str, Dict[str, Any]]" = \
            collections.OrderedDict()
        self.errors: Deque[Dict[str, Any]] = \
            collections.deque(maxlen=max_errors)
        self._cells: Optional[int] = None
        self._members: int = 0  # ensemble size (0 = unbatched run)
        # span tracing (round 16): the trace identity this stream
        # belongs to, and the serving-side request latency accounting
        self.trace_id: Optional[str] = None
        self.spans_seen = 0
        self.time_to_first_chunk_s: Optional[float] = None

    # -- ingestion ------------------------------------------------------

    def ingest(self, rec: Dict[str, Any]) -> None:
        """Fold one obs record (manifest or event) into the aggregate.

        Unknown kinds are counted but otherwise ignored — the registry
        must survive anything a future schema rev appends.  Never
        raises on a well-formed-but-unexpected record; a malformed one
        (non-dict fields where dicts are expected) is skipped.
        """
        if not isinstance(rec, dict):
            return
        with self.registry.lock:
            try:
                self._ingest_locked(rec)
            except Exception:  # noqa: BLE001 — an observer never raises
                self.registry.counter(
                    "obs_ingest_errors_total",
                    "records the ingester could not fold").inc()

    def _ingest_locked(self, rec: Dict[str, Any]) -> None:
        kind = rec.get("kind")
        self.events_seen += 1
        self.registry.counter(
            "obs_events_total", "obs records ingested").inc()
        handler = getattr(self, f"_on_{kind}", None)
        if handler is not None:
            handler(rec)

    def _set_trace_id(self, trace_id: Any) -> None:
        if self.trace_id is None and isinstance(trace_id, str) and trace_id:
            self.trace_id = trace_id
            self.registry.info(
                "obs_trace_info",
                "causal trace identity this stream belongs to").set(
                trace_id=trace_id)

    def _on_manifest(self, rec: Dict[str, Any]) -> None:
        self.manifests_seen += 1
        self.registry.counter(
            "obs_manifests_total",
            "manifests seen (supervised runs: 1 + one per attempt)").inc()
        self._set_trace_id((rec.get("trace") or {}).get("trace_id"))
        if self.manifest is not None:
            return
        self.manifest = rec
        run = rec.get("run") or {}
        prov = rec.get("provenance") or {}
        self._cells = _grid_cells(run)
        ens = run.get("ensemble")
        if isinstance(ens, int) and ens > 0:
            # a batched run must be distinguishable from a fast single
            # run at a glance: the size is a gauge AND an identity label
            self._members = ens
            self.registry.gauge(
                "obs_ensemble_size",
                "simultaneous simulations in the batched step").set(ens)
        for g in rec.get("groups") or ():
            # seed the group table from the manifest's plan describe():
            # the panel shows every group's identity before the first
            # group_chunk lands
            if isinstance(g, dict) and isinstance(g.get("group"), str):
                # round 23: mode tokens + interface transport ride the
                # manifest block, so the panel names each group's
                # execution path, not just its physics
                self.groups.setdefault(g["group"], {}).update(
                    {k: g.get(k) for k in ("op", "ratio", "dtype",
                                           "devices", "grid", "modes",
                                           "transport")
                     if g.get(k) is not None})
        self.registry.info(
            "obs_run_info", "identity of the (primary) run").set(
            tool=rec.get("tool"), stencil=run.get("stencil"),
            grid=",".join(map(str, run.get("grid") or [])) or None,
            mesh=",".join(map(str, run.get("mesh") or [])) or None,
            ensemble=ens if ens else None,
            backend=prov.get("backend"),
            device_kind=prov.get("device_kind"),
            hostname=prov.get("hostname"),
            process_index=prov.get("process_index"),
            git_sha=str(prov.get("git_sha", ""))[:12] or None)

    def _on_chunk(self, rec: Dict[str, Any]) -> None:
        steps = int(rec.get("steps") or 0)
        wall = float(rec.get("wall_s") or 0.0)
        ms = rec.get("ms_per_step")
        self.latest_chunk = rec
        self.chunks_recent.append(
            {"chunk": rec.get("chunk"), "steps": steps, "wall_s": wall,
             "ms_per_step": ms, "recompiled": bool(rec.get("recompiled")),
             "t": rec.get("t")})
        self.registry.counter("obs_chunks_total", "chunks completed").inc()
        self.registry.counter("obs_steps_total",
                              "real steps completed").inc(steps)
        if rec.get("recompiled"):
            self.registry.counter(
                "obs_recompiles_total",
                "chunks that recompiled mid-run (shape drift)").inc()
        first = rec.get("chunk") == 0
        if first and ms is not None:
            self.registry.gauge(
                "obs_first_chunk_ms_per_step",
                "compile+warmup chunk ms/step").set(ms)
        if first and self.time_to_first_chunk_s is None \
                and self.manifest is not None:
            # request-latency accounting (round 16): wall seconds from
            # the stream's FIRST manifest (the run/request open) to the
            # first completed chunk — the serving-engine SLO number
            created = (self.manifest or {}).get("created_at")
            t_end = rec.get("t")
            if isinstance(created, (int, float)) and \
                    isinstance(t_end, (int, float)) and t_end >= created:
                self.time_to_first_chunk_s = round(t_end - created, 6)
                self.registry.gauge(
                    "obs_time_to_first_chunk_s",
                    "seconds from run open to the first completed "
                    "chunk (compile + warmup + first results)").set(
                    self.time_to_first_chunk_s)
        if not first and ms is not None and not rec.get("recompiled"):
            self.registry.histogram(
                "obs_chunk_ms_per_step",
                "steady-state ms/step (compile chunk excluded)").observe(ms)
        if wall > 0 and steps > 0:
            rate = steps / wall
            self.registry.gauge("obs_steps_per_s",
                                "latest chunk steps/s").set(rate)
            if self._cells:
                agg = self._cells * rate / 1e9
                self.registry.gauge(
                    "obs_gcells_per_s",
                    "latest chunk AGGREGATE throughput, Gcells/s "
                    "(all ensemble members)").set(agg)
                if self._members:
                    self.registry.gauge(
                        "obs_member_gcells_per_s",
                        "latest chunk per-member throughput, "
                        "Gcells/s").set(agg / self._members)
        mem = rec.get("memory") or {}
        peak = mem.get("peak_bytes_in_use")
        if peak is not None:
            self.registry.gauge(
                "obs_device_memory_peak_bytes",
                "max device memory peak over all chunks").set_max(peak)
        self._update_roofline_gap()

    def _on_group_chunk(self, rec: Dict[str, Any]) -> None:
        """Fold one per-group chunk of a coupled run (cli._run_coupled):
        each device group's own op/resolution/dtype identity and its
        throughput, keyed by the group name (``g0:wave3d``)."""
        name = rec.get("group")
        if not isinstance(name, str) or not name:
            return
        self.registry.counter("obs_group_chunks_total",
                              "coupled-run group chunks ingested").inc()
        entry = self.groups.setdefault(name, {})
        for k in ("op", "ratio", "dtype"):
            if rec.get(k) is not None:
                entry[k] = rec[k]
        entry["last_step"] = rec.get("step")
        entry["steps_total"] = (entry.get("steps_total") or 0) + \
            int(rec.get("steps") or 0)
        mc = rec.get("mcells_per_s")
        if isinstance(mc, (int, float)):
            entry["mcells_per_s"] = mc
            self.registry.gauge_family(
                "obs_group_mcells_per_s",
                "latest per-group throughput of the coupled run, "
                "Mcells/s").set(mc, group=name,
                                op=str(rec.get("op") or ""))

    def _on_costmodel(self, rec: Dict[str, Any]) -> None:
        self.costmodel = rec
        roof = rec.get("roofline") or {}
        t_hbm = roof.get("predicted_ms_per_step_hbm")
        t_ici = roof.get("predicted_ms_per_step_exchange") or 0.0
        if t_hbm is not None:
            self.registry.gauge(
                "obs_predicted_ms_per_step_overlapped",
                "roofline ms/step, exchange fully hidden").set(
                max(t_hbm, t_ici))
            self.registry.gauge(
                "obs_predicted_ms_per_step_serial",
                "roofline ms/step, exchange on the critical path").set(
                t_hbm + t_ici)
        self._update_roofline_gap()

    def _update_roofline_gap(self) -> None:
        """measured p50 / predicted-overlapped — the attribution gap."""
        roof = (self.costmodel or {}).get("roofline") or {}
        t_hbm = roof.get("predicted_ms_per_step_hbm")
        if t_hbm is None:
            return
        t_ici = roof.get("predicted_ms_per_step_exchange") or 0.0
        pred = max(t_hbm, t_ici)
        steady = sorted(c["ms_per_step"] for c in self.chunks_recent
                        if c.get("chunk") != 0
                        and not c.get("recompiled")
                        and c.get("ms_per_step") is not None)
        if not steady or pred <= 0:
            return
        self.registry.gauge(
            "obs_roofline_gap_ratio",
            "measured steady p50 ms/step over the overlapped roofline "
            "prediction (1.0 = at the roofline)").set(
            quantile(steady, 0.5) / pred)

    def _on_heartbeat(self, rec: Dict[str, Any]) -> None:
        self.heartbeat = rec
        verdict = rec.get("verdict")
        self.registry.counter("obs_heartbeat_events_total",
                              "heartbeat verdict events").inc()
        self.registry.info("obs_heartbeat_verdict",
                           "latest heartbeat verdict").set(verdict=verdict)
        self.registry.gauge(
            "obs_stalled",
            "1 while the latest heartbeat verdict is STALLED/WEDGED").set(
            1.0 if verdict in ("STALLED", "WEDGED") else 0.0)

    def _on_health(self, rec: Dict[str, Any]) -> None:
        """Fold one numerics-sentinel check (obs/health.py)."""
        self.health = rec
        verdict = rec.get("verdict")
        group = rec.get("group")
        if isinstance(group, str) and group:
            # coupled runs health-check per group: the named group's
            # row carries its own verdict (a DIVERGED group still
            # dominates the run verdict through self.health below)
            self.groups.setdefault(group, {})["verdict"] = verdict
        self.registry.counter("obs_health_checks_total",
                              "health sentinel checks ingested").inc()
        self.registry.info(
            "obs_health_verdict",
            "latest simulation-health verdict").set(
            verdict=verdict,
            invariant=(rec.get("invariant") or {}).get("name"),
            reason=(str(rec.get("reason"))[:120]
                    if rec.get("reason") else None))
        self.registry.gauge(
            "obs_health_diverged",
            "1 while the latest health verdict is DIVERGED").set(
            1.0 if verdict == "DIVERGED" else 0.0)
        nf = rec.get("nonfinite_total")
        if isinstance(nf, (int, float)):
            self.registry.gauge(
                "obs_health_nonfinite_values",
                "NaN/Inf count across all fields, latest check").set(nf)
        inv = rec.get("invariant") or {}
        d = inv.get("drift")
        if isinstance(d, list):
            d = max((x for x in d if isinstance(x, (int, float))),
                    default=None)
        if isinstance(d, (int, float)) and math.isfinite(d):
            self.registry.gauge(
                "obs_health_invariant_drift",
                "registered-invariant drift vs the chunk-0 baseline "
                "(worst member)").set(d)
        wf = rec.get("worst_field") or {}
        if isinstance(wf.get("drift"), (int, float)):
            self.registry.gauge(
                "obs_health_worst_field_drift",
                "worst per-field mean drift vs the chunk-0 baseline "
                "(informational)").set(wf["drift"])

    def _on_anomaly(self, rec: Dict[str, Any]) -> None:
        """Fold one run-doctor finding (obs/anomaly.py): counted per
        kind, the suspect kept whole — /status.json must NAME the slow
        (host | group | member), not just count findings."""
        self.anomalies.append(rec)
        self.anomalies_total += 1
        kind = str(rec.get("anomaly") or "unknown")
        self.anomaly_kinds[kind] = self.anomaly_kinds.get(kind, 0) + 1
        self.registry.counter("obs_anomalies_total",
                              "run-doctor findings ingested").inc()
        self.registry.counter(
            f"obs_anomaly_{_prom_name(kind)}_total",
            f"'{kind}' anomaly findings").inc()
        self.registry.gauge(
            "obs_degraded",
            "1 once any performance anomaly was flagged").set(1.0)
        suspect = rec.get("suspect") or {}
        if isinstance(suspect, dict) and suspect.get("name"):
            self.registry.info(
                "obs_anomaly_suspect",
                "latest straggler/collapse attribution").set(
                kind=suspect.get("kind"), name=suspect.get("name"),
                lag_ratio=suspect.get("lag_ratio"), anomaly=kind)
        ratio = (rec.get("evidence") or {}).get("ratio")
        if isinstance(ratio, (int, float)):
            self.registry.gauge(
                "obs_anomaly_collapse_ratio",
                "latest ms/step over the run's own steady baseline").set(
                ratio)

    def _on_halo_audit(self, rec: Dict[str, Any]) -> None:
        self.halo_audit = rec
        self.registry.counter("obs_halo_audits_total",
                              "halo-exchange audit passes").inc()
        mm = rec.get("mismatch_total")
        if isinstance(mm, (int, float)) and mm:
            self.registry.counter(
                "obs_halo_audit_mismatches_total",
                "bit-mismatched received-slab words found by the "
                "halo audit").inc(mm)
        self.registry.gauge(
            "obs_halo_audit_ok",
            "1 while the latest halo audit bit-matched everywhere").set(
            1.0 if rec.get("ok") else 0.0)

    def _on_launch(self, rec: Dict[str, Any]) -> None:
        self.launches.append(rec)
        self.registry.gauge("obs_supervisor_attempts",
                            "supervised launches so far").set(
            len(self.launches))
        step = rec.get("resumed_from_step")
        if step is not None:
            self.resumed_from_step = int(step)
            self.registry.gauge(
                "obs_resumed_from_step",
                "checkpoint step the latest attempt resumed from").set(step)

    def _on_restart(self, rec: Dict[str, Any]) -> None:
        self.restarts.append(rec)
        self.registry.counter(
            "obs_supervisor_restarts_total",
            "supervisor kill+relaunch decisions").inc()

    def _on_give_up(self, rec: Dict[str, Any]) -> None:
        self.give_up = rec
        self.registry.gauge(
            "obs_supervisor_gave_up",
            "1 once the supervisor stopped restarting").set(1.0)

    def _on_resume(self, rec: Dict[str, Any]) -> None:
        step = rec.get("resumed_from_step")
        if step is not None:
            self.resumed_from_step = int(step)
            self.registry.gauge(
                "obs_resumed_from_step",
                "checkpoint step the latest attempt resumed from").set(step)

    def _on_exchange(self, rec: Dict[str, Any]) -> None:
        self.exchange = rec
        self.registry.info(
            "obs_exchange_mode",
            "halo-exchange transport and its honest backend tag").set(
            mode=rec.get("mode"), backend=rec.get("backend"))

    def _on_policy(self, rec: Dict[str, Any]) -> None:
        """Fold the auto-policy decision (policy/select.py): what the
        engine chose to run and WHY — measured ledger winner or
        costmodel prediction — plus any explicit-flag overrides."""
        self.policy = rec
        self.registry.counter("obs_policy_decisions_total",
                              "auto-policy resolutions ingested").inc()
        self.registry.info(
            "obs_policy_decision",
            "active execution-policy decision and its provenance").set(
            provenance=rec.get("provenance"), label=rec.get("label"),
            backend=rec.get("backend"),
            overrides=",".join(sorted(rec.get("overrides") or ())) or None)
        v = rec.get("value")
        if isinstance(v, (int, float)):
            self.registry.gauge(
                "obs_policy_winner_mcells_per_s",
                "the chosen config's ranked value (measured Mcells/s "
                "or roofline prediction)").set(v)

    def _on_migrate(self, rec: Dict[str, Any]) -> None:
        """Fold one live mesh migration (parallel/reshard.py adoption):
        the run re-sharded to a new winner mid-flight."""
        self.migrations.append(rec)
        self.registry.counter(
            "obs_policy_migrations_total",
            "live mesh migrations adopted mid-flight").inc()
        step = rec.get("step")
        if isinstance(step, (int, float)):
            self.registry.gauge(
                "obs_policy_last_migration_step",
                "absolute step of the latest live migration").set(step)

    def _on_label(self, rec: Dict[str, Any]) -> None:
        label = rec.get("label")
        if not isinstance(label, str):
            return
        self.labels[label] = rec
        self.registry.counter(
            "obs_campaign_label_events_total",
            "campaign label progress events").inc()

    def _on_span(self, rec: Dict[str, Any]) -> None:
        """Fold one finished span: per-name duration histograms (the
        ``request`` spans of the engine become the per-request latency
        histogram on ``/metrics``) + the trace identity."""
        self.spans_seen += 1
        self.registry.counter("obs_spans_total",
                              "finished spans ingested").inc()
        self._set_trace_id(rec.get("trace_id"))
        name = rec.get("name")
        dur = rec.get("dur_s")
        if isinstance(name, str) and name and \
                isinstance(dur, (int, float)):
            safe = _prom_name(name)[:48]
            self.registry.histogram(
                f"obs_span_{safe}_seconds",
                f"duration of '{name}' spans").observe(dur)

    def _on_error(self, rec: Dict[str, Any]) -> None:
        self.errors.append(rec)
        self.registry.counter("obs_errors_total", "error events").inc()

    def _on_abort(self, rec: Dict[str, Any]) -> None:
        self.errors.append(rec)
        self.registry.counter("obs_errors_total", "error events").inc()

    def _on_cancelled(self, rec: Dict[str, Any]) -> None:
        self.cancelled = rec
        self.registry.counter("obs_run_cancelled_total",
                              "cooperative run cancellations").inc()
        self.registry.gauge(
            "obs_run_cancelled",
            "1 once the run was cancelled (not errored)").set(1.0)

    # gauges a scheduler event may carry; each becomes an obs_sched_*
    # gauge and a key of status()["scheduler"]
    _SCHED_GAUGES = (
        ("queue_depth", "jobs waiting for a member slot"),
        ("slots_total", "member slots across resident size classes"),
        ("slots_busy", "member slots currently running a job"),
        ("classes", "resident size classes (compiled steps kept hot)"),
    )

    def _on_scheduler(self, rec: Dict[str, Any]) -> None:
        """Fold one serving-scheduler event (serving/scheduler.py).

        Every event carries an ``op`` (submit/admit/reject/join/retire/
        evict/preempt/cancel/class_build) plus the scheduler's current
        occupancy gauges; per-tenant ops are counted under the tenant's
        name so starvation is visible from ``/status.json`` alone.
        """
        op = str(rec.get("op") or "event")
        sched = self.scheduler
        if sched is None:
            sched = self.scheduler = {"counts": {}, "tenants": {}}
        sched["counts"][op] = sched["counts"].get(op, 0) + 1
        self.registry.counter(
            f"obs_sched_{_prom_name(op)}_total",
            f"scheduler '{op}' decisions").inc()
        for g, help_text in self._SCHED_GAUGES:
            v = rec.get(g)
            if isinstance(v, (int, float)):
                sched[g] = v
                self.registry.gauge(f"obs_sched_{g}", help_text).set(v)
        tenant = rec.get("tenant")
        if isinstance(tenant, str) and tenant:
            t = sched["tenants"].setdefault(tenant, {})
            t[op] = t.get(op, 0) + 1
            self.registry.gauge_family(
                "obs_sched_tenant_ops",
                "per-tenant scheduler decision counts").set(
                t[op], tenant=tenant, op=op)
        sc = rec.get("size_class")
        if isinstance(sc, str) and sc:
            # the per-class table the obs_top fleet panel renders: op
            # counts plus the last-known capacity/occupancy carried by
            # class_build/grow/shrink events
            entry = sched.setdefault("size_classes", {}).setdefault(
                sc, {"ops": {}})
            entry["ops"][op] = entry["ops"].get(op, 0) + 1
            for k in ("capacity", "occupied"):
                v = rec.get(k)
                if isinstance(v, int):
                    entry[k] = v
        if op == "reject":
            # structured admission refusal: the reason is the payload
            sched["last_reject"] = {
                "tenant": tenant, "reason": rec.get("reason"),
                "size_class": rec.get("size_class"), "t": rec.get("t")}
        sched["last_event"] = {
            "op": op, "tenant": tenant, "job": rec.get("job"),
            "size_class": rec.get("size_class"), "t": rec.get("t")}

    # gauges a router event may carry; each becomes an obs_router_*
    # gauge and a key of status()["router"]
    _ROUTER_GAUGES = (
        ("replicas_alive", "engine replicas currently routable"),
        ("replicas_total", "engine replicas configured"),
        ("jobs_inflight", "router jobs not yet resolved"),
    )

    def _on_router(self, rec: Dict[str, Any]) -> None:
        """Fold one fleet-router event (serving/router.py).

        Every event carries an ``op`` (route/reject/rebalance/
        replica_up/replica_dead/resolve) plus the router's liveness
        gauges; the last event and last death are kept whole so the
        fleet panel can say WHICH replica died without reading logs.
        """
        op = str(rec.get("op") or "event")
        rt = self.router
        if rt is None:
            rt = self.router = {"counts": {}}
        rt["counts"][op] = rt["counts"].get(op, 0) + 1
        self.registry.counter(
            f"obs_router_{_prom_name(op)}_total",
            f"router '{op}' decisions").inc()
        for g, help_text in self._ROUTER_GAUGES:
            v = rec.get(g)
            if isinstance(v, (int, float)):
                rt[g] = v
                self.registry.gauge(f"obs_router_{g}", help_text).set(v)
        if op == "replica_dead":
            rt["last_death"] = {
                "replica": rec.get("replica"), "t": rec.get("t"),
                "orphans": rec.get("orphans")}
        rt["last_event"] = {
            "op": op, "replica": rec.get("replica"),
            "job": rec.get("job"), "t": rec.get("t")}

    def _on_summary(self, rec: Dict[str, Any]) -> None:
        self.summary = rec
        self.registry.gauge("obs_run_complete",
                            "1 once a summary event landed").set(1.0)
        mc = rec.get("mcells_per_s")
        if isinstance(mc, (int, float)):
            self.registry.gauge("obs_summary_mcells_per_s",
                                "run-level throughput at exit").set(mc)

    # -- status ---------------------------------------------------------

    def _throughput(self) -> Dict[str, Any]:
        steady = sorted(c["ms_per_step"] for c in self.chunks_recent
                        if c.get("chunk") != 0 and not c.get("recompiled")
                        and c.get("ms_per_step") is not None)
        out: Dict[str, Any] = {}
        if self._members:
            out["ensemble"] = self._members
        last = self.chunks_recent[-1] if self.chunks_recent else None
        if last and last.get("wall_s") and last.get("steps"):
            rate = last["steps"] / last["wall_s"]
            out["steps_per_s"] = round(rate, 3)
            if self._cells:
                agg = self._cells * rate / 1e9
                out["gcells_per_s"] = round(agg, 4)
                if self._members:
                    # aggregate AND per-member: the batched-vs-fast
                    # ambiguity resolved in one read
                    out["gcells_per_s_per_member"] = round(
                        agg / self._members, 4)
        if steady:
            out["steady_ms_per_step_p50"] = quantile(steady, 0.5)
            out["steady_ms_per_step_p90"] = quantile(steady, 0.9)
        return out

    def _campaign(self) -> Optional[Dict[str, Any]]:
        if not self.labels:
            return None
        counts: Dict[str, int] = {}
        for rec in self.labels.values():
            status = str(rec.get("status") or "unknown")
            counts[status] = counts.get(status, 0) + 1
        return {
            "counts": counts,
            "labels": {label: {
                "status": rec.get("status"),
                "mcells_per_s": rec.get("mcells_per_s"),
                "compute": rec.get("compute"),
                "attempts": rec.get("attempts"),
                "wall_s": rec.get("wall_s"),
                "error": rec.get("error"),
            } for label, rec in self.labels.items()},
        }

    def status(self) -> Dict[str, Any]:
        """The ``/status.json`` payload: one consistent dict.

        Everything a remote "is it wedged?" needs without reading any
        log file: provenance, the latest chunk, the heartbeat verdict,
        and the supervisor restart trail (launches carry
        ``resumed_from_step``).
        """
        with self.registry.lock:
            hb = self.heartbeat
            verdict = hb.get("verdict") if hb else None
            if self.cancelled is not None and verdict is None:
                # a deliberate stop, distinct from DONE and from any
                # failure verdict (which all dominate it below)
                verdict = "CANCELLED"
            if (self.health or {}).get("verdict") == "DIVERGED" or any(
                    g.get("verdict") == "DIVERGED"
                    for g in self.groups.values()):
                # correctness dominates liveness: a run that diverged
                # is lost no matter what the heartbeat says (coupled
                # runs: ANY group's divergence is the run's)
                verdict = "DIVERGED"
            if verdict is None and self.anomalies:
                # performance findings degrade the verdict only when
                # nothing harder (heartbeat/cancel/diverge) claimed it
                # — and they outrank DONE: a run that finished slow
                # finished DEGRADED, so obs_top --once still exits
                # nonzero after the fact
                verdict = "DEGRADED"
            out: Dict[str, Any] = {
                "generated_at": time.time(),
                "manifest": self.manifest,
                "manifests_seen": self.manifests_seen,
                "events_seen": self.events_seen,
                "verdict": verdict or ("DONE" if self.summary else "ALIVE"),
                "latest_chunk": self.latest_chunk,
                "chunks_recent": list(self.chunks_recent),
                "throughput": self._throughput(),
                "heartbeat": hb,
                # always present (None before any check): the stable
                # contract a scheduler reads to evict diverged members
                # without parsing logs (engine.RunHandle.status too)
                "health": self.health,
                "launches": list(self.launches),
                "restarts": list(self.restarts),
                "give_up": self.give_up,
                "resumed_from_step": self.resumed_from_step,
                "exchange": self.exchange,
                "summary": self.summary,
                "errors": list(self.errors),
            }
            if self.groups:
                rank = {"DIVERGED": 0, "HEALTHY": 1}
                rows = [{"group": name, **entry}
                        for name, entry in self.groups.items()]
                rows.sort(key=lambda r: rank.get(r.get("verdict"), 3))
                worst = min(
                    (r.get("verdict") for r in rows
                     if r.get("verdict") is not None),
                    key=lambda v: rank.get(v, 3), default=None)
                out["groups"] = {"n_groups": len(rows), "rows": rows,
                                 "worst_verdict": worst}
            if self.anomalies:
                last = self.anomalies[-1]
                out["anomalies"] = {
                    "count": self.anomalies_total,
                    "kinds": dict(self.anomaly_kinds),
                    "last": last,
                    "suspect": last.get("suspect"),
                }
            if self.halo_audit is not None:
                out["halo_audit"] = self.halo_audit
            if self.cancelled is not None:
                out["cancelled"] = self.cancelled
            if self.scheduler is not None:
                out["scheduler"] = self.scheduler
            if self.router is not None:
                out["router"] = self.router
            if self.policy is not None or self.migrations:
                pol = dict(self.policy or {})
                pol.pop("kind", None)
                pol.pop("table", None)  # ranked table stays in the log
                out["policy"] = {
                    **pol,
                    "migrations": len(self.migrations),
                    "last_migration": (self.migrations[-1]
                                       if self.migrations else None),
                }
            if self.trace_id is not None:
                out["trace_id"] = self.trace_id
            if self.time_to_first_chunk_s is not None:
                out["time_to_first_chunk_s"] = self.time_to_first_chunk_s
            if self.spans_seen:
                out["spans_seen"] = self.spans_seen
            roof = (self.costmodel or {}).get("roofline")
            if roof:
                out["roofline"] = roof
            campaign = self._campaign()
            if campaign:
                out["campaign"] = campaign
            return out
