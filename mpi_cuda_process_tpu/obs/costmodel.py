"""Static per-step cost counters: flops, HBM bytes, ppermute rounds/bytes.

Every telemetry manifest carries a roofline prediction next to its
measurement (the attribution discipline of the TPU CFD framework,
arXiv:2108.11076 §5, and the MPMD overlap-accounting of
arXiv:2412.14374): ``scripts/obs_report.py`` renders predicted vs
measured per phase, so a run that is slower than its own static model
says WHERE (interior bandwidth, exchange, compile).

Two kinds of counter, deliberately separate:

* **jaxpr extraction** (:func:`flops_from_jaxpr`,
  :func:`comm_stats_from_jaxpr`): counts read off a traced program —
  exact for the program traced, usable wherever tracing is possible
  (tests trace small sharded steps on virtual devices).
* **analytic model** (:func:`comm_stats`, :func:`hbm_bytes_per_step`):
  closed-form counts for configurations whose device population does
  not exist on this box (config 5's 64-chip meshes).  The analytic
  exchange model is CROSS-CHECKED two ways: against the jaxpr counts on
  traceable configs, and against ``utils/budget.py``'s byte-pinned slab
  accounting (:func:`budget_crosscheck`) — tests pin both to the byte,
  so the three models (jaxpr reality, this module, the HBM budget)
  cannot drift apart silently.

Nothing here executes device code: tracing is shape-level, the analytic
paths are pure arithmetic.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..utils.jaxprcheck import iter_jaxprs

# v5e anchors (docs/STATE.md): HBM peak per chip; ICI per link.  The
# measured Mosaic DMA envelope (~330 GB/s) is reported alongside, not
# substituted — the roofline is an upper bound, not a fit.
V5E_HBM_GBS = 819.0
V5E_ICI_GBS = 45.0

# Elementwise primitives counted as one flop per output element.  A
# MODEL, not a lowering simulator: comparisons, selects, copies, pads,
# and layout ops are free; transcendentals count 1 (they dominate no
# stencil here).  The counter's job is a stable, pinned, comparable
# number per program — tests assert exact values so drift is loud.
_FLOP_PRIMS = frozenset({
    "add", "add_any", "sub", "mul", "div", "rem", "pow", "integer_pow",
    "max", "min", "neg", "abs", "exp", "log", "tanh", "sqrt", "rsqrt",
    "sign", "floor", "ceil", "round", "erf", "logistic", "sin", "cos",
})


def flops_from_jaxpr(closed) -> int:
    """Weighted elementwise-arithmetic count across all nested jaxprs.

    Counts each eqn once (do not feed scanned/looped programs unless
    one iteration is what you mean to count).
    """
    total = 0
    for jx in iter_jaxprs(closed.jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name in _FLOP_PRIMS:
                total += max(
                    (math.prod(ov.aval.shape) for ov in eqn.outvars),
                    default=0)
    return total


def comm_stats_from_jaxpr(closed) -> Dict[str, int]:
    """ppermute rounds and per-device bytes read off a traced program.

    Each ``ppermute`` eqn is one exchange round; its operand aval is
    what every participating device sends (and receives) — summing aval
    bytes gives the per-device ICI payload per call of the traced
    function.
    """
    rounds = 0
    bytes_ = 0
    for jx in iter_jaxprs(closed.jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name == "ppermute":
                rounds += 1
                aval = eqn.invars[0].aval
                bytes_ += math.prod(aval.shape) * aval.dtype.itemsize
    return {"ppermute_rounds": rounds, "ppermute_bytes": bytes_}


def step_flops(stencil, shape: Sequence[int], periodic: bool = False) -> int:
    """Flops of ONE reference jnp step on ``shape`` (trace-only).

    The counter of record for every execution strategy: the fused/raw
    kernels compute the same update (plus margin redundancy the model
    deliberately ignores), so one number is comparable across paths.
    """
    from ..driver import make_step

    step = make_step(stencil, tuple(int(s) for s in shape),
                     periodic=periodic)
    abstract = tuple(
        jax.ShapeDtypeStruct(tuple(int(s) for s in shape), stencil.dtype)
        for _ in range(stencil.num_fields))
    return flops_from_jaxpr(jax.make_jaxpr(step)(abstract))


def _local_shape(grid: Sequence[int],
                 mesh: Sequence[int]) -> Tuple[int, ...]:
    counts = tuple(mesh) + (1,) * (len(grid) - len(mesh)) if mesh else \
        (1,) * len(grid)
    return tuple(int(g) // int(c) for g, c in zip(grid, counts))


def hbm_bytes_per_step(stencil, local_shape: Sequence[int],
                       fuse: int = 0, batch: int = 1) -> int:
    """Minimum per-device HBM traffic per REAL step: one read + one
    write of every field, divided by the temporal-blocking depth (k
    steps per HBM pass is exactly what ``--fuse`` buys)."""
    cells = max(1, int(batch)) * math.prod(int(s) for s in local_shape)
    item = jnp.dtype(stencil.dtype).itemsize
    return (2 * stencil.num_fields * cells * item) // max(1, int(fuse))


def rdma_stats_from_jaxpr(closed) -> Dict[str, int]:
    """Remote-DMA exchange counters read off a traced program: remote
    ``dma_start`` eqns (each is one chunk crossing the ICI) and the
    residual ``ppermute`` count (pinned 0 for an rdma step).  The
    jaxpr-reality half of the rdma cross-check."""
    from ..utils.jaxprcheck import count_primitive, count_remote_dma

    return {
        "remote_dma": count_remote_dma(closed),
        "ppermute_rounds": count_primitive(closed, "ppermute"),
    }


def _rdma_sites(stencil, local: Sequence[int], m: int,
                counts: Sequence[int], nslots: int = 0,
                prefer_nc: int = 0) -> List[Dict[str, Any]]:
    """The per-field ring-exchange sites of one slab-kind pass under
    ``exchange="rdma"``, with their chunk geometry — read from the SAME
    ``remote.pick_chunks`` the kernel builder uses, so the analytic DMA
    counts cross-check against the kernel's actual grid by
    construction.  Mirrors ``halo.exchange_slabs_2axis``: one call per
    z-slab pair, one per y-slab pair, two per corner set (the two-pass
    composition exchanges zlo and zhi separately along y).
    ``nslots``/``prefer_nc`` (0 = kernel defaults) re-pin the table
    under an rdma kernel variant's ring geometry (policy/autotune.py),
    so kernel and model read the same constants."""
    from ..ops.pallas.remote import ring_exchange_stats

    lz, ly, lx = local
    kw = {"nslots": nslots or None, "prefer_nc": prefer_nc}
    sites = []
    if counts[0] > 1:
        sites.append(ring_exchange_stats((m, ly, lx), stencil.dtype,
                                         **kw))
    if counts[1] > 1:
        sites.append(ring_exchange_stats((lz, m, lx), stencil.dtype,
                                         **kw))
        corner = ring_exchange_stats((m, m, lx), stencil.dtype, **kw)
        sites += [corner, dict(corner)]
    return sites


def comm_stats(
    stencil,
    grid: Sequence[int],
    mesh: Sequence[int] = (),
    fuse: int = 0,
    fuse_kind: str = "auto",
    periodic: bool = False,
    exchange: str = "ppermute",
    batch: int = 1,
    variant=None,
) -> Optional[Dict[str, Any]]:
    """Analytic ppermute rounds + bytes per device, or None (unsharded).

    Mirrors the exchange the steppers actually issue (pinned against
    traced jaxprs in tests/test_obs.py):

    * slab-operand fused kinds (``padfree``/``stream``): width-``m``
      face slabs per field — z-only meshes 2 rounds/field of
      ``(m, ly, lx)``; meshes that shard y add 2 y-rounds of
      ``(lz, m, lx)`` and 4 two-pass corner rounds of ``(m, m, lx)``
      (``halo.exchange_slabs_2axis``).  ``slab_operand_bytes`` prices
      the kernel's operand STORAGE for the same set (2-axis kernels
      duplicate/align the y-facing operands) and must equal
      ``utils/budget.py``'s slab part to the byte.
    * padded fused kind / plain jnp step: the two-pass
      ``exchange_and_pad`` scheme — axis d's slabs span axes < d
      already padded; the plain step exchanges only fields with a
      nonzero ``field_halo`` at width ``halo``, the fused kinds every
      field at width ``m``.

    ``batch=N`` (the ensemble engine): the ROUND COUNT is unchanged —
    vmap folds the member axis into each collective operand, the
    structural pin of the batched steppers — while every per-device
    byte quantity (ICI payloads, slab operand storage) scales by the N
    members the device holds.

    ``exchange="rdma"`` (streaming kind): the same slab set crosses the
    ICI, but as in-kernel remote-DMA chunks instead of ppermutes — the
    counters become ``rdma_exchange_calls_per_pass`` (ring-kernel
    invocations: one per z-slab pair, one per y-slab pair, two per
    corner set) and ``rdma_dma_per_pass`` (remote ``dma_start`` count:
    2 directions x nchunks per call, chunk geometry from
    ``remote.pick_chunks`` — the SAME function the kernel builds from,
    so the count cross-checks against the kernel grid by construction;
    pinned against traced jaxprs in tests and re-checked per manifest
    by :func:`rdma_crosscheck`).  ``ppermute_rounds_per_pass`` is 0 by
    definition (the zero-collective gate), ici bytes are unchanged
    (the ring carries the same payloads), and ``slab_operand_bytes`` is
    None — the recv side stages through VMEM rings, so budget has no
    HBM slab part to compare (see utils/budget.py).
    """
    ndim = stencil.ndim
    counts = (tuple(int(c) for c in mesh) + (1,) * ndim)[:ndim]
    if math.prod(counts) <= 1:
        return None
    local = _local_shape(grid, mesh)
    item = jnp.dtype(stencil.dtype).itemsize
    nf = stencil.num_fields
    batch = max(1, int(batch))

    if fuse:
        from ..ops.pallas.fused import _halo_per_micro

        m = int(fuse) * _halo_per_micro(stencil)
        widths = (m,) * nf
        per_pass_steps = int(fuse)
    else:
        widths = tuple(stencil.field_halos)
        per_pass_steps = 1

    # slab-operand kinds exist for 3D only (2D fused runs use the
    # whole-local-block kernel behind the padded-style exchange)
    kind = fuse_kind if (fuse and ndim == 3
                         and fuse_kind in ("padfree", "stream")) \
        else ("padded" if fuse else "plain")

    rdma = exchange == "rdma" and kind == "stream"
    rounds = 0
    ici = 0
    operand: Optional[int] = None
    rdma_sites: Optional[List[Dict[str, Any]]] = None
    if kind in ("padfree", "stream"):
        lz, ly, lx = local
        m = widths[0]
        two_axis = counts[1] > 1
        z_sharded = counts[0] > 1
        z_bytes = m * ly * lx * item
        if rdma:
            # an rdma-family kernel variant (policy/autotune.py) changes
            # the ring geometry the kernel builds — the chunk table must
            # be read under the same constants or the crosscheck would
            # compare different schedules
            v_nslots = int(getattr(variant, "nslots", 0) or 0) \
                if getattr(variant, "family", "") == "rdma" else 0
            v_nc = int(getattr(variant, "prefer_nc", 0) or 0) \
                if getattr(variant, "family", "") == "rdma" else 0
            rdma_sites = _rdma_sites(stencil, local, m, counts,
                                     nslots=v_nslots, prefer_nc=v_nc)
        if z_sharded:
            rounds += nf * 2
            ici += nf * 2 * z_bytes
        if two_axis:
            y_bytes = lz * m * lx * item
            c_bytes = m * m * lx * item
            rounds += nf * (2 + 4)
            ici += nf * (2 * y_bytes + 4 * c_bytes)
            # operand storage: the 2-axis kernels carry the y-facing
            # operands duplicated (pad-free: 2m rows) or sublane-aligned
            # (stream: m + m_a) — exactly budget.py's slab accounting
            from ..ops.pallas.fused import _sublane

            if kind == "stream":
                m_a = -(-m // _sublane(item)) * _sublane(item)
                dup = m + m_a
            else:
                dup = 2 * m
            operand = nf * item * (2 * m * ly * lx
                                   + 2 * dup * lz * lx
                                   + 4 * m * dup * lx)
        else:
            operand = nf * 2 * z_bytes
    else:
        # two-pass exchange_and_pad: axis d exchanged after axes < d are
        # padded, so its slab spans the already-grown extents
        for i in range(nf):
            w = widths[i]
            if not w:
                continue
            for d in range(ndim):
                if counts[d] <= 1:
                    continue
                slab_cells = w
                for j in range(ndim):
                    if j == d:
                        continue
                    slab_cells *= local[j] + (2 * w if j < d else 0)
                rounds += 2
                ici += 2 * slab_cells * item

    out: Dict[str, Any] = {
        "kind": kind,
        "exchange": "rdma" if rdma else "ppermute",
        "per_pass_steps": per_pass_steps,
        "width_m": max(widths),
        "sharded_counts": list(counts),
        "members_per_device": batch,
        # round count is BATCH-INDEPENDENT (the vmap collective-batching
        # pin); bytes scale with the members each device holds
        "ppermute_rounds_per_pass": 0 if rdma else rounds,
        "ici_bytes_per_pass": batch * ici,
        "ici_bytes_per_step": batch * ici / per_pass_steps,
        "slab_operand_bytes": None if rdma else (
            None if operand is None else batch * operand),
    }
    if rdma:
        # one ring-kernel invocation per site PER FIELD; the DMA count
        # is what a traced step must reproduce exactly
        out["rdma_exchange_calls_per_pass"] = nf * len(rdma_sites)
        out["rdma_dma_per_pass"] = nf * sum(
            s["remote_dma_per_call"] for s in rdma_sites)
        out["rdma_chunks"] = rdma_sites
    return out


def rdma_crosscheck(
    stencil,
    grid: Sequence[int],
    mesh: Sequence[int],
    fuse: int,
    periodic: bool = False,
    variant=None,
) -> Optional[Dict[str, Any]]:
    """Analytic rdma DMA count vs a TRACED compiled rdma step.

    The rdma analogue of :func:`budget_crosscheck`: the analytic chunk
    model (``remote.pick_chunks``) against the remote ``dma_start``
    count of the actual step jaxpr (``interpret=False`` — the kernel a
    TPU run compiles; tracing is shape-level, nothing executes), plus
    the zero-ppermute pin.  Returns None when this box cannot host the
    mesh (config 5's 64-chip population) — the analytic side still
    rides the manifest via ``comm["rdma_dma_per_pass"]``; tests pin the
    match on traceable meshes.
    """
    cs = comm_stats(stencil, grid, mesh, fuse=fuse, fuse_kind="stream",
                    periodic=periodic, exchange="rdma", variant=variant)
    if cs is None or "rdma_dma_per_pass" not in cs:
        return None
    try:
        from ..parallel.mesh import make_mesh
        from ..parallel.stepper import make_sharded_fused_step

        mesh_obj = make_mesh(tuple(mesh))
        step = make_sharded_fused_step(
            stencil, mesh_obj, tuple(int(g) for g in grid), int(fuse),
            interpret=False, kind="stream", periodic=periodic,
            exchange="rdma", variant=variant)
        if step is None:
            return None
        abstract = tuple(
            jax.ShapeDtypeStruct(tuple(int(g) for g in grid),
                                 stencil.dtype)
            for _ in range(stencil.num_fields))
        traced = rdma_stats_from_jaxpr(jax.make_jaxpr(step)(abstract))
    except Exception:  # noqa: BLE001 — mesh too big for this box, or
        return None    # any trace-environment limitation: no cross-check
    return {
        "analytic_remote_dma": cs["rdma_dma_per_pass"],
        "traced_remote_dma": traced["remote_dma"],
        "traced_ppermute": traced["ppermute_rounds"],
        "match": (traced["remote_dma"] == cs["rdma_dma_per_pass"]
                  and traced["ppermute_rounds"] == 0),
    }


def budget_crosscheck(
    stencil,
    grid: Sequence[int],
    mesh: Sequence[int],
    fuse: int,
    fuse_kind: str,
    periodic: bool = False,
    ensemble: int = 0,
    ensemble_mesh: int = 0,
) -> Optional[Dict[str, Any]]:
    """Assert-by-record: this module's slab-operand bytes vs budget.py's.

    Returns ``{"slab_operand_bytes", "budget_bytes", "match"}`` for the
    slab-operand kinds, None where budget has no slab part to compare.
    The pair rides the manifest so a drift between the two byte models
    is visible in every event log, and tests pin ``match == True`` for
    config 5 on both mesh families.
    """
    members = (max(1, int(ensemble)) // max(1, int(ensemble_mesh))
               if ensemble else 1)
    cs = comm_stats(stencil, grid, mesh, fuse=fuse, fuse_kind=fuse_kind,
                    periodic=periodic, batch=members)
    if cs is None or cs.get("slab_operand_bytes") is None:
        return None
    from ..utils import budget

    _, parts = budget.estimate_run_bytes(
        stencil, grid, mesh=mesh, fuse=fuse, fuse_kind=fuse_kind,
        periodic=periodic, ensemble=ensemble,
        ensemble_mesh=ensemble_mesh)
    slab = [b for label, b in parts
            if "operands only" in label and b > 0]
    if not slab:
        return None
    return {
        "slab_operand_bytes": cs["slab_operand_bytes"],
        "budget_bytes": slab[0],
        "match": cs["slab_operand_bytes"] == slab[0],
    }


def static_cost(
    stencil,
    grid: Sequence[int],
    mesh: Sequence[int] = (),
    fuse: int = 0,
    fuse_kind: str = "auto",
    periodic: bool = False,
    ensemble: int = 0,
    hbm_gbs: float = V5E_HBM_GBS,
    ici_gbs: float = V5E_ICI_GBS,
    exchange: str = "ppermute",
    ensemble_mesh: int = 0,
    variant=None,
) -> Dict[str, Any]:
    """The manifest's static cost block: counters + roofline prediction.

    Per-device flops (jaxpr-counted on the local block), minimum HBM
    traffic per step, the exchange model, the budget cross-check, and
    two throughput predictions: ``overlapped`` prices the paper's core
    claim (exchange hidden behind interior compute — step time is the
    HBM bound alone) and ``serial`` the unhidden schedule; the measured
    number landing between them is the overlap win, quantified.
    ``variant`` (a ``policy.autotune.KernelVariant`` or None) re-pins
    the rdma chunk tables and the traced cross-check under that
    variant's ring geometry — model and kernel read the same constants.
    """
    grid = tuple(int(g) for g in grid)
    local = _local_shape(grid, mesh)
    # per-DEVICE members (time-side terms) vs TOTAL members (cell
    # throughput): an ensemble mesh axis spreads the batch over device
    # groups, so a device pays for ensemble/ensemble_mesh members while
    # the machine advances all of them
    total_members = max(1, int(ensemble))
    members = (total_members // max(1, int(ensemble_mesh))
               if ensemble else 1)
    comm = comm_stats(stencil, grid, mesh, fuse=fuse, fuse_kind=fuse_kind,
                      periodic=periodic, exchange=exchange, batch=members,
                      variant=variant)
    flops = members * step_flops(stencil, local, periodic=periodic)
    hbm_b = hbm_bytes_per_step(stencil, local, fuse=fuse, batch=members)
    t_hbm_ms = hbm_b / (hbm_gbs * 1e9) * 1e3
    t_ici_ms = (comm["ici_bytes_per_step"] / (ici_gbs * 1e9) * 1e3
                if comm else 0.0)
    cells = total_members * math.prod(grid)

    def _mcells(t_ms: float) -> float:
        return cells / (t_ms * 1e-3) / 1e6 if t_ms > 0 else float("inf")

    out: Dict[str, Any] = {
        "grid": list(grid),
        "mesh": list(mesh),
        "local_shape": list(local),
        "batch": total_members,
        "ensemble": int(ensemble),
        "ensemble_mesh": int(ensemble_mesh),
        "members_per_device": members,
        "fuse": int(fuse),
        "fuse_kind": comm["kind"] if comm else (fuse_kind if fuse else None),
        "dtype": str(jnp.dtype(stencil.dtype)),
        "flops_per_step_per_device": int(flops),
        "hbm_bytes_per_step_per_device": int(hbm_b),
        "comm": comm,
        "roofline": {
            "hbm_gbs": hbm_gbs,
            "ici_gbs": ici_gbs,
            "predicted_ms_per_step_hbm": round(t_hbm_ms, 6),
            "predicted_ms_per_step_exchange": round(t_ici_ms, 6),
            "predicted_mcells_per_s_overlapped": round(
                _mcells(t_hbm_ms), 1),
            "predicted_mcells_per_s_serial": round(
                _mcells(t_hbm_ms + t_ici_ms), 1),
            "basis": "minimum HBM traffic at peak bandwidth; 'overlapped'"
                     " assumes the exchange fully hidden (the paper's "
                     "claim), 'serial' adds it to the critical path",
        },
    }
    if comm and comm.get("slab_operand_bytes") is not None:
        try:
            out["budget_crosscheck"] = budget_crosscheck(
                stencil, grid, mesh, fuse, fuse_kind, periodic=periodic,
                ensemble=ensemble, ensemble_mesh=ensemble_mesh)
        except Exception:  # noqa: BLE001 — the cross-check must never
            out["budget_crosscheck"] = None  # block a manifest write
    if comm and comm.get("exchange") == "rdma":
        try:
            # traced remote-DMA count vs the analytic chunk model —
            # rides every rdma manifest so obs_report attributes the
            # in-kernel traffic (None when this box can't host the mesh)
            out["rdma_crosscheck"] = rdma_crosscheck(
                stencil, grid, mesh, fuse, periodic=periodic,
                variant=variant)
        except Exception:  # noqa: BLE001 — never block a manifest write
            out["rdma_crosscheck"] = None
    return out


def coupled_cost(plans, hbm_gbs: float = V5E_HBM_GBS,
                 ici_gbs: float = V5E_ICI_GBS,
                 transport: str = "") -> Dict[str, Any]:
    """The coupled (``--groups``) run's static cost block.

    Per-group :func:`static_cost` (each group's interior step is the
    unmodified stepper on its own sub-mesh — round 23: its clause mode
    tokens flow into ``fuse``/``fuse_kind``, so a fused/stream group is
    priced exactly like the monolithic run it mirrors) plus an EXPLICIT
    interface sub-block: the cross-group band refresh is the only new
    traffic, and it is priced by name — rounds per step, bytes per
    direction, ratios and dtypes per interface — so obs_report can
    attribute the coupling cost separately from each group's own
    exchange.  ``transport`` prices the two band paths apart:
    ``device_put`` moves the RECEIVER-side resampled band
    (``bytes_per_round`` = sum of recv parts), ``collective`` moves the
    RAW sender rows over ICI and resamples on the receiver
    (``bytes_per_round`` = sum of send parts — the wire's actual
    payload).  The budget cross-check: ``interface.bytes_per_round``
    must equal the sum of ``utils/budget.py``'s matching per-group
    interface parts (tests pin it), so the cost model and the HBM
    budget cannot drift apart.
    """
    from ..parallel import groups as groups_lib

    transport = transport or groups_lib.TRANSPORT_BACKEND
    group_costs = []
    for p in plans:
        s = p.spec
        c = static_cost(p.stencil, p.grid, mesh=p.mesh_shape,
                        fuse=s.fuse_k if s.fuse_k > 1 else 0,
                        fuse_kind=s.kind or "auto",
                        hbm_gbs=hbm_gbs, ici_gbs=ici_gbs)
        c["group"] = p.name
        c["ratio"] = p.ratio
        c["devices"] = [p.spec.dev_lo, p.spec.dev_hi]
        c["cells_per_round"] = p.cells
        c["owned_cells"] = p.owned_cells
        c["modes"] = list(s.modes)
        group_costs.append(c)
    traffic = groups_lib.interface_traffic(plans)
    recv_bytes = sum(t[d]["recv_bytes"] for t in traffic
                     for d in ("up", "down"))
    send_bytes = sum(t[d]["send_bytes"] for t in traffic
                     for d in ("up", "down"))
    wire_bytes = send_bytes if transport == "collective" else recv_bytes
    return {
        "coupled": True,
        "n_groups": len(plans),
        "cell_updates_per_round": int(sum(p.cells for p in plans)),
        "groups": group_costs,
        "interface": {
            "n_interfaces": len(traffic),
            # one wholesale band refresh per coupled round — the whole
            # coupling protocol, by construction
            "rounds_per_step": 1,
            "transport": transport,
            "bytes_per_round": int(wire_bytes),
            "staged_bytes_per_round": int(send_bytes),
            "predicted_ms_per_round": round(
                wire_bytes / (ici_gbs * 1e9) * 1e3, 6),
            "interfaces": traffic,
        },
    }
