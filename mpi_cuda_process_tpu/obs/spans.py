"""Distributed span tracing: one causal timeline across processes.

The obs stack before this module recorded flat per-process events: a
supervised, restarted, multi-host run left N disjoint JSONL logs whose
only ordering was wall-clock guesswork.  This module adds the missing
causal spine — a **span** (trace_id / span_id / parent_id, wall start,
monotonic-measured duration, attributes) emitted as an ordinary
schema-compatible event into the telemetry stream the tools already
write — plus **cross-process propagation**: a parent (the supervisor,
the engine, a future multi-host launcher) exports ``OBS_TRACE_CONTEXT``
into a child's environment, and the child's obs session adopts that
trace_id and parents its spans under the exporter's span.  Every
attempt of a restarted run, and every process of a multi-host run,
then shares ONE trace_id — ``scripts/obs_trace_export.py`` folds the
logs into a single Chrome-trace/Perfetto timeline.

The span vocabulary (the contract the ROADMAP item-1 scheduler and the
item-5 multi-host launch path will emit into):

=============  =====================================================
name           emitted by
=============  =====================================================
``run``/tool   the session root span (``Session.close``; named after
               the emitting tool — ``cli``, ``supervisor``, ...)
``compile``    ``RuntimeRecorder`` around chunk 0 (compile + warmup)
``checkpoint`` the CLI around every checkpoint save
``resume``     the CLI around a resuming build (attrs carry
               ``resumed_from_step``)
``attempt``    the supervisor around one child's whole life
``kill``       the supervisor around killpg + reap
``restart``    the supervisor between two attempts (attrs carry the
               ``resumed_from_step`` the next attempt will use)
``backoff``    the supervisor's exponential-backoff sleep (nested in
               ``restart``)
``request``    the engine around one submitted run (children:
               ``queue_wait``, ``result``)
=============  =====================================================

Design constraints, inherited from the obs layer:

* **Zero ops in the jitted step** — spans are host-side wall clocks at
  the same boundaries events already fire; the step jaxpr is
  byte-identical with spans on vs off (pinned by test).
* **Never load-bearing** — emission failures are swallowed; a closed
  trace drops late spans silently.
* **Pure stdlib** — importable by the supervisor parent on a wedged
  box without dragging a jax backend in.
* Disable with ``OBS_SPANS=0`` (events keep flowing; only spans stop).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

ENV_VAR = "OBS_TRACE_CONTEXT"
SPAN_KIND = "span"

_tls = threading.local()


def new_id() -> str:
    """A 16-hex-char random id (span ids; trace ids use the same)."""
    return uuid.uuid4().hex[:16]


def spans_enabled() -> bool:
    """Span emission gate: ``OBS_SPANS=0`` turns spans off (events keep
    flowing — the gate exists so the on-vs-off jaxpr pin is testable)."""
    return os.environ.get("OBS_SPANS", "1") != "0"


class SpanContext:
    """Where in the one causal timeline we are: (trace_id, span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}"

    @classmethod
    def decode(cls, s: str) -> Optional["SpanContext"]:
        parts = str(s).split(":")
        if len(parts) != 2 or not parts[0] or not parts[1]:
            return None
        return cls(parts[0], parts[1])

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"SpanContext({self.encode()})"


# ------------------------------------------------------- propagation

def from_env() -> Optional[SpanContext]:
    """The context a parent process exported, or None."""
    raw = os.environ.get(ENV_VAR)
    return SpanContext.decode(raw) if raw else None


def push_thread_context(ctx: SpanContext) -> None:
    """Set THIS thread's pending context (the in-process analogue of the
    env var — the engine sets it on a handle's thread before the run
    opens its session, so the session parents under the request span
    without any environment mutation)."""
    stack = getattr(_tls, "pending", None)
    if stack is None:
        stack = _tls.pending = []
    stack.append(ctx)


def pop_thread_context() -> None:
    stack = getattr(_tls, "pending", None)
    if stack:
        stack.pop()


def thread_context() -> Optional[SpanContext]:
    stack = getattr(_tls, "pending", None)
    return stack[-1] if stack else None


def resolve_context() -> Optional[SpanContext]:
    """The inherited context for a new session: this thread's pending
    context first (in-process parent, e.g. the engine), then the
    environment (cross-process parent, e.g. the supervisor)."""
    return thread_context() or from_env()


def env_extra(session: Any) -> Dict[str, str]:
    """The env block a launcher passes to a child so the child's spans
    join this session's trace under the CURRENT span (call inside the
    span that brackets the child's life — the supervisor's ``attempt``
    span).  Empty when the session has no live emitter."""
    emitter = getattr(session, "spans", None)
    if emitter is None or not emitter.enabled:
        return {}
    return {ENV_VAR: emitter.current().encode()}


# ------------------------------------------------------------ records

def make_span_record(name: str, trace_id: str, span_id: str,
                     parent_id: Optional[str], start: float, dur_s: float,
                     attrs: Optional[Dict[str, Any]] = None,
                     t: Optional[float] = None) -> Dict[str, Any]:
    """One span as an obs event record (the single schema definition —
    the emitter and the engine's post-run appender both build these)."""
    from . import trace as trace_lib

    rec: Dict[str, Any] = {
        "schema": trace_lib.SCHEMA_VERSION,
        "kind": SPAN_KIND,
        "t": float(t) if t is not None else float(start) + float(dur_s),
        "name": str(name),
        "trace_id": str(trace_id),
        "span_id": str(span_id),
        "parent_id": str(parent_id) if parent_id else None,
        "start": float(start),
        "dur_s": float(dur_s),
    }
    if attrs:
        rec["attrs"] = dict(attrs)
    return rec


def append_span_records(path: str, records: List[Dict[str, Any]]) -> int:
    """Append finished span records to an existing (closed) telemetry
    log — the engine's post-run request accounting.  Never raises; the
    return value is the number of lines written."""
    try:
        with open(path, "a") as fh:
            for rec in records:
                fh.write(json.dumps(rec, default=str) + "\n")
        return len(records)
    except OSError:
        return 0


# ------------------------------------------------------------ emitter

class SpanEmitter:
    """Per-session span factory bound to one TraceWriter.

    The emitter owns the session's trace identity: a fresh ``trace_id``
    when no context was inherited (this session is a trace root), the
    parent's ``trace_id`` otherwise.  A long-lived **root span** (named
    after the tool) brackets the whole session; it is emitted by
    :meth:`close` — exporters see it last in the log but its ``start``
    is the session open.  :meth:`span` is the context manager for
    everything else; nesting is tracked per thread (a span opened on
    the heartbeat thread parents to the root, not to whatever the main
    thread happens to be inside).
    """

    def __init__(self, trace: Any, context: Optional[SpanContext] = None,
                 root_name: str = "run",
                 root_attrs: Optional[Dict[str, Any]] = None,
                 enabled: Optional[bool] = None):
        self.trace = trace
        self.enabled = spans_enabled() if enabled is None else bool(enabled)
        self.inherited = context
        self.trace_id = context.trace_id if context else new_id()
        self.root_id = new_id()
        self.root_name = str(root_name)
        self.root_attrs = dict(root_attrs) if root_attrs else {}
        self._root_start = time.time()
        self._root_t0 = time.monotonic()
        self._root_emitted = False
        self._stacks = threading.local()

    # -- context ------------------------------------------------------

    def _stack(self) -> List[SpanContext]:
        stack = getattr(self._stacks, "stack", None)
        if stack is None:
            stack = self._stacks.stack = []
        return stack

    def current(self) -> SpanContext:
        """This thread's innermost open span (the root when none is)."""
        stack = self._stack()
        return stack[-1] if stack else SpanContext(self.trace_id,
                                                   self.root_id)

    def manifest_block(self) -> Dict[str, Any]:
        """The ``trace`` block stamped into the session manifest: how a
        reader joins this log to its parents without parsing spans."""
        return {
            "trace_id": self.trace_id,
            "root_span_id": self.root_id,
            "parent_span_id": (self.inherited.span_id
                               if self.inherited else None),
        }

    # -- emission -----------------------------------------------------

    def _write(self, rec: Dict[str, Any]) -> None:
        # TraceWriter.event() rebuilds schema/t; write through it so the
        # manifest-first rule and thread-safe locking apply unchanged.
        try:
            payload = {k: v for k, v in rec.items()
                       if k not in ("schema", "kind", "t")}
            self.trace.event(SPAN_KIND, **payload)
        except Exception:  # noqa: BLE001 — never load-bearing
            pass

    def emit(self, name: str, start: float, dur_s: float,
             parent_id: Optional[str] = None, span_id: Optional[str] = None,
             **attrs: Any) -> Optional[str]:
        """Record an already-measured span (no context manager — the
        caller timed it; e.g. the recorder's compile span, the CLI's
        resume span).  Parents to this thread's current span unless an
        explicit ``parent_id`` is given.  Returns the span id."""
        if not self.enabled or self.trace is None:
            return None
        sid = span_id or new_id()
        rec = make_span_record(
            name, self.trace_id, sid,
            parent_id if parent_id is not None else self.current().span_id,
            start, dur_s, attrs or None)
        self._write(rec)
        return sid

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Optional[SpanContext]]:
        """Open a span around a code block; emitted at exit with the
        measured duration.  Yields the span's context (what a launcher
        encodes into a child's ``OBS_TRACE_CONTEXT``)."""
        if not self.enabled or self.trace is None:
            yield None
            return
        parent = self.current().span_id
        ctx = SpanContext(self.trace_id, new_id())
        stack = self._stack()
        stack.append(ctx)
        start = time.time()
        t0 = time.monotonic()
        try:
            yield ctx
        finally:
            if stack and stack[-1] is ctx:
                stack.pop()
            rec = make_span_record(name, self.trace_id, ctx.span_id,
                                   parent, start, time.monotonic() - t0,
                                   attrs or None)
            self._write(rec)

    def close(self, **attrs: Any) -> None:
        """Emit the root span (idempotent).  Call BEFORE the trace
        writer closes — a post-close emission is dropped silently."""
        if self._root_emitted or not self.enabled or self.trace is None:
            return
        self._root_emitted = True
        merged = dict(self.root_attrs)
        merged.update(attrs)
        rec = make_span_record(
            self.root_name, self.trace_id, self.root_id,
            self.inherited.span_id if self.inherited else None,
            self._root_start, time.monotonic() - self._root_t0,
            merged or None)
        self._write(rec)


def maybe_span(emitter: Optional[SpanEmitter], name: str, **attrs: Any):
    """``emitter.span(...)`` or a null context when there is no emitter
    — the one-liner call sites (cli, supervisor) use."""
    if emitter is not None:
        return emitter.span(name, **attrs)
    return contextlib.nullcontext()
