"""Numerics sentinel: on-device simulation-health observability.

The obs stack (rounds 10-16) can say a run is *fast* (profile
attribution, roofline gap, ledger/perf-gate) and *alive* (heartbeat,
supervisor, spans, live console) — but nothing could say it is
*correct*: a NaN blow-up, a drifting conservation invariant, or a
corrupted halo exchange produced a healthy-looking manifest with great
Gcells/s right up to the garbage final field.  This module is the
correctness half of observability:

* :func:`make_health_fn` — a separately-jitted, fully sharded health
  reduction: per-field global min/max/mean + NaN/Inf counts, plus the
  op's REGISTERED conservation invariant
  (:class:`~..ops.stencil.HealthInvariant` — heat's total heat, wave's
  exactly-conserved leapfrog energy, SOR's decreasing residual norm;
  registered per op in ``ops/``, never hardcoded here).  All reductions
  are jnp over the (possibly sharded) global view, so XLA inserts the
  cross-device combines — no host gather of field state, and the whole
  stat dict is fetched in ONE ``jax.device_get`` like the diagnostics
  path.  For ensembles the reductions keep the member axis (per-member
  values) and the monitor adds cross-member divergence stats.

* :class:`HealthMonitor` — the trend detector: relative drift vs the
  chunk-0 baseline with the op's registered tolerance (two-sided for
  conserved quantities, one-sided for relaxation residuals, an
  absolute ``scale`` floor for quantities that saturate toward a known
  value) turns the stats into a ``health`` event stream and a
  ``DIVERGED`` verdict that flows everywhere WEDGED already flows: the
  supervisor treats it as NON-restartable (resuming into the same
  blow-up is waste), ledger auto-ingest quarantines the row with
  reason ``diverged``, ``/status.json``//``/metrics``//``obs_top``
  render it, and the session's bracketing root span gains a ``health``
  attribute.

* :class:`HaloAuditor` (``--halo-audit K``) — the opt-in debug mode
  that would have localized an exchange bug in minutes: every K chunks
  it re-exchanges the ghost slabs through the RUN'S configured
  transport (ppermute or the in-kernel remote-DMA ring) and
  bit-compares every received slab against the neighbor interior it
  must equal (computed independently from the global array view),
  reporting any mismatch as the exact (field, axis, direction,
  ring-shard) site.

Cost rule: reductions run only at chunk boundaries (the existing
host-side hook — the zero-ops-in-the-jitted-step invariant is pinned
by extending the jaxpr-invariance tests), the audit only every K
chunks.  Nothing here touches jax tracing of the step.
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.stencil import Fields, Stencil

log = logging.getLogger("mpi_cuda_process_tpu.obs.health")

VERDICT_HEALTHY = "HEALTHY"
VERDICT_DIVERGED = "DIVERGED"

# Drift denominators never divide by zero: an identically-zero baseline
# (an all-zero simulation) makes any later nonzero value read as a huge
# drift, which is the right answer.
_EPS = 1e-12


class SimulationDiverged(RuntimeError):
    """The run's state failed a health check; carries the record."""

    def __init__(self, message: str, record: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.record = record


def _spatial_axes(arr_ndim: int, ensemble: bool):
    """Reduction axes: everything (None) unbatched, spatial-only batched."""
    return tuple(range(1, arr_ndim)) if ensemble else None


def make_health_fn(stencil: Stencil, ensemble: int = 0):
    """The jitted health reduction: fields -> dict of device scalars.

    Separately jitted (never part of the step program); the caller
    fetches the whole dict with one ``jax.device_get``.  With
    ``ensemble`` the entries are per-member vectors instead of scalars
    (reductions keep the leading member axis; the registered invariant
    is vmapped over it).
    """
    inv = stencil.invariant
    ens = int(ensemble) > 0

    def staged(fields: Fields) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        for i, f in enumerate(fields):
            axes = _spatial_axes(f.ndim, ens)
            inexact = jnp.issubdtype(f.dtype, jnp.inexact)
            g = f.astype(jnp.float32)
            out[f"field{i}_min"] = jnp.min(g, axis=axes)
            out[f"field{i}_max"] = jnp.max(g, axis=axes)
            out[f"field{i}_mean"] = jnp.mean(g, axis=axes)
            if inexact:
                out[f"field{i}_nonfinite"] = jnp.sum(
                    (~jnp.isfinite(f)).astype(jnp.int32), axis=axes)
        if inv is not None:
            fn = jax.vmap(inv.fn) if ens else inv.fn
            out["invariant"] = fn(tuple(fields))
        return out

    return jax.jit(staged)


def _tolist(v) -> List[float]:
    a = np.asarray(v)
    return [float(x) for x in a.reshape(-1)]


def _round(x: float, nd: int = 8) -> float:
    try:
        return round(float(x), nd)
    except (TypeError, ValueError, OverflowError):
        return float(x)


def drift(value: float, baseline: float, scale: Optional[float],
          mode: str) -> float:
    """Relative drift of ``value`` vs ``baseline``.

    ``conserve``: two-sided |v - v0| / denom.  ``decrease``: one-sided —
    only an increase counts (a shrinking residual is progress, not
    drift).  ``denom = max(|v0|, scale, eps)``: the registered scale
    floor keeps legitimately-saturating quantities (Dirichlet heat)
    measured against their physical ceiling, not a near-zero start.
    NaN values return inf (non-finite is always maximal drift).
    """
    if not math.isfinite(value):
        return float("inf")
    denom = max(abs(baseline), scale or 0.0, _EPS)
    d = (value - baseline) / denom
    return abs(d) if mode == "conserve" else max(0.0, d)


class HealthMonitor:
    """Chunk-cadence trend detector over the jitted health reduction.

    ``check(step, fields)`` runs the reduction, compares against the
    chunk-0 baseline (the FIRST check's values), writes one ``health``
    event into the trace (when given one), stamps the verdict onto the
    session's root span (``spans.root_attrs['health']``), and returns
    the record.  Divergence rules, in order of hardness:

    1. any NaN/Inf count > 0 in any inexact field — the hard trigger;
    2. a non-finite invariant value;
    3. invariant drift beyond the op's registered tolerance (per
       member, for ensembles — one diverged member diverges the run:
       its slots are garbage either way, and the engine needs the
       verdict to evict it).

    Ops without a registered invariant get rules 1-2 plus the
    informational per-field drift (never a trigger — field means move
    legitimately).  ``raise_on_diverged`` callers use
    :meth:`check_or_raise`.

    ``open_system=True`` demotes rule 3 to informational: a coupled
    device group (``--groups``) exchanges its invariant quantity
    through the interface bands by construction, so conservation drift
    is expected physics there, not divergence — the drift still lands
    in the invariant block (tagged ``"open_system": true``) for
    obs_top/report, but only rules 1-2 can flip the verdict.
    """

    def __init__(self, stencil: Stencil, trace=None, ensemble: int = 0,
                 spans=None, open_system: bool = False):
        self.stencil = stencil
        self.trace = trace
        self.spans = spans
        self.open_system = bool(open_system)
        self.ensemble = int(ensemble)
        self._fn = make_health_fn(stencil, ensemble=ensemble)
        self.baseline: Optional[Dict[str, Any]] = None
        self.last: Optional[Dict[str, Any]] = None
        self.verdict = VERDICT_HEALTHY
        self.checks = 0

    # -- core -----------------------------------------------------------

    def check(self, step: int, fields: Fields,
              chunk: Optional[int] = None) -> Dict[str, Any]:
        vals = jax.device_get(self._fn(tuple(fields)))
        rec = self._evaluate(step, chunk, vals)
        self.checks += 1
        self.last = rec
        self.verdict = rec["verdict"]
        self._emit(rec)
        return rec

    def check_or_raise(self, step: int, fields: Fields,
                       chunk: Optional[int] = None) -> Dict[str, Any]:
        rec = self.check(step, fields, chunk=chunk)
        if rec["verdict"] == VERDICT_DIVERGED:
            raise SimulationDiverged(
                f"simulation DIVERGED at step {step}: {rec['reason']}",
                record=rec)
        return rec

    # -- evaluation -----------------------------------------------------

    def _evaluate(self, step, chunk, vals) -> Dict[str, Any]:
        inv = self.stencil.invariant
        ens = self.ensemble > 0
        reasons: List[str] = []

        field_stats: List[Dict[str, Any]] = []
        nonfinite_total = 0
        for i in range(self.stencil.num_fields):
            entry: Dict[str, Any] = {}
            for stat in ("min", "max", "mean"):
                v = vals[f"field{i}_{stat}"]
                entry[stat] = ([_round(x) for x in _tolist(v)] if ens
                               else _round(v))
            key = f"field{i}_nonfinite"
            if key in vals:
                nf = int(np.sum(np.asarray(vals[key])))
                entry["nonfinite"] = ([int(x) for x in _tolist(vals[key])]
                                      if ens else nf)
                nonfinite_total += nf
                if nf:
                    reasons.append(
                        f"field {i} holds {nf} non-finite value(s) "
                        "(NaN/Inf blow-up or poisoned cell)")
            field_stats.append(entry)

        inv_block: Optional[Dict[str, Any]] = None
        worst_drift: Optional[float] = None
        if inv is not None:
            values = _tolist(vals["invariant"])
            base = (self.baseline or {}).get("_invariant", values)
            drifts = [drift(v, b, inv.scale, inv.mode)
                      for v, b in zip(values, base)]
            worst_drift = max(drifts) if drifts else None
            inv_block = {
                "name": inv.name,
                "mode": inv.mode,
                "rtol": inv.rtol,
                "value": ([_round(v) for v in values] if ens
                          else _round(values[0])),
                "baseline": ([_round(b) for b in base] if ens
                             else _round(base[0])),
                "drift": ([_round(d, 6) for d in drifts] if ens
                          else _round(drifts[0], 6)),
            }
            if self.open_system:
                inv_block["open_system"] = True
            bad = [j for j, v in enumerate(values) if not math.isfinite(v)]
            if bad:
                reasons.append(
                    f"invariant '{inv.name}' non-finite"
                    + (f" for member(s) {bad}" if ens else ""))
            elif inv.rtol is not None and not self.open_system:
                over = [j for j, d in enumerate(drifts) if d > inv.rtol]
                if over:
                    reasons.append(
                        f"invariant '{inv.name}' drifted "
                        f"{max(drifts):.3g}x vs the chunk-0 baseline "
                        f"(tolerance {inv.rtol:g}, mode {inv.mode})"
                        + (f" for member(s) {over}" if ens else ""))

        # informational per-field drift (never a trigger): the worst
        # relative movement of any field mean vs baseline — what obs_top
        # renders as "worst-field drift"
        worst_field = None
        if self.baseline is not None:
            base_means = self.baseline["_means"]
            for i in range(self.stencil.num_fields):
                cur = _tolist(vals[f"field{i}_mean"])
                ds = [drift(v, b, None, "conserve")
                      for v, b in zip(cur, base_means[i])]
                d = max(ds) if ds else 0.0
                if worst_field is None or d > worst_field["drift"]:
                    worst_field = {"field": i, "drift": _round(d, 6)}

        verdict = VERDICT_DIVERGED if reasons else VERDICT_HEALTHY
        rec: Dict[str, Any] = {
            "step": int(step),
            "verdict": verdict,
            "reason": "; ".join(reasons) or None,
            "nonfinite_total": nonfinite_total,
            "fields": field_stats,
            "invariant": inv_block,
        }
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if worst_drift is not None:
            rec["worst_drift"] = _round(worst_drift, 6)
        if worst_field is not None:
            rec["worst_field"] = worst_field
        if ens:
            rec["ensemble"] = self._member_spread(vals)

        if self.baseline is None:
            # chunk-0 baseline: the first check's values anchor the
            # trend detector (a run that is ALREADY non-finite at its
            # first boundary still diverges via the NaN rule above)
            self.baseline = {
                "_invariant": _tolist(vals["invariant"])
                if inv is not None else None,
                "_means": [_tolist(vals[f"field{i}_mean"])
                           for i in range(self.stencil.num_fields)],
                "step": int(step),
            }
            rec["baseline_step"] = int(step)
        return rec

    def _member_spread(self, vals) -> Dict[str, Any]:
        """Cross-member divergence stats for a batched run."""
        out: Dict[str, Any] = {"members": self.ensemble}
        src = vals.get("invariant",
                       vals.get("field0_mean"))
        a = np.asarray(_tolist(src), dtype=np.float64)
        finite = a[np.isfinite(a)]
        if finite.size:
            out["spread"] = _round(float(finite.max() - finite.min()))
            out["std"] = _round(float(finite.std()))
        out["nonfinite_members"] = int(a.size - finite.size)
        return out

    # -- emission -------------------------------------------------------

    def _emit(self, rec: Dict[str, Any]) -> None:
        if self.spans is not None:
            # the bracketing root span carries the run's health verdict
            # onto the causal timeline (obs/spans.py root_attrs)
            try:
                self.spans.root_attrs["health"] = rec["verdict"]
            except Exception:  # noqa: BLE001 — never load-bearing
                pass
        if self.trace is None:
            return
        try:
            self.trace.event("health", **rec)
        except Exception:  # noqa: BLE001 — observer, never load-bearing
            log.debug("health event write failed", exc_info=True)


# ------------------------------------------------------------ poisoning

def apply_nan_poison(fields: Fields) -> Fields:
    """The ``numerics`` fault site's payload: one NaN, deterministically.

    Poisons the CENTER cell of the first inexact field (member 0 of a
    batched run — the leading axis center rounds down).  Host-side at a
    chunk boundary, so the jitted step program is untouched; the
    replacement state flows back into the run through the driver's
    callback-replacement hook.  Raises on an all-integer state (there
    is nothing a NaN can poison — Life runs need a float op instead).
    """
    for i, f in enumerate(fields):
        if not jnp.issubdtype(f.dtype, jnp.inexact):
            continue
        idx = tuple(s // 2 for s in f.shape)
        out = list(fields)
        out[i] = f.at[idx].set(jnp.nan)
        log.warning("[faults] numerics poison: field %d cell %s <- NaN",
                    i, idx)
        return tuple(out)
    raise ValueError(
        "FAULT_INJECT numerics:nan needs an inexact field to poison; "
        "this stencil's state is all-integer")


# ------------------------------------------------------------ halo audit

def _bits(x: jax.Array) -> jax.Array:
    """Bit-pattern view for exact comparison (NaN payloads included)."""
    if jnp.issubdtype(x.dtype, jnp.floating):
        uint = {2: jnp.uint16, 4: jnp.uint32, 8: jnp.uint64}[x.dtype.itemsize]
        return jax.lax.bitcast_convert_type(x, uint)
    return x


class HaloAuditor:
    """Bit-exact ghost-slab audit across the run's exchange transport.

    Construction enumerates the run's exchange SITES — every
    (halo-bearing field, spatially sharded grid axis) pair — and builds
    one jitted program that (a) re-exchanges each site's boundary slabs
    through the configured transport (``lax.ppermute``, or the
    in-kernel remote-DMA ring for ``exchange="rdma"``) inside a
    ``shard_map`` whose outputs are the per-shard RECEIVED slabs, and
    (b) compares them, bit for bit, against what each shard's neighbor
    interior actually holds — computed independently from the global
    array view (a row gather + wall-constant substitution), so the two
    sides share no exchange code.  A mismatch therefore implicates the
    transport (or its neighbor resolution), localized to the exact
    (field, axis, direction, ring-shard).

    ``_corrupt`` is the deterministic test seam: a hook applied to each
    received slab at trace time (``_corrupt(field, axis, direction,
    slab, axis_name) -> slab``), which the audit tests use to prove a
    seeded single-bit corruption is localized exactly.
    """

    DIRECTIONS = ("left", "right")

    def __init__(self, stencil: Stencil, mesh, global_shape: Sequence[int],
                 *, exchange: str = "ppermute", periodic: bool = False,
                 ensemble: int = 0, trace=None,
                 _corrupt: Optional[Callable] = None):
        from ..parallel.stepper import (ensemble_partition_spec,
                                        grid_partition_spec, shard_map)
        from ..parallel.mesh import spatial_axis_names

        self.stencil = stencil
        self.mesh = mesh
        self.global_shape = tuple(int(g) for g in global_shape)
        self.periodic = bool(periodic)
        self.ensemble = int(ensemble)
        self.trace = trace
        ndim = stencil.ndim
        names = spatial_axis_names(ndim)
        self._axis_names = [n if n in mesh.shape else None for n in names]
        self._counts = [int(mesh.shape.get(n, 1)) for n in names]

        self.sites: List[Tuple[int, int, int]] = []  # (field, axis, halo)
        for i, fh in enumerate(stencil.field_halos):
            if fh == 0:
                continue
            for d in range(ndim):
                if self._counts[d] > 1:
                    self.sites.append((i, d, int(fh)))
        if not self.sites:
            raise ValueError(
                "halo audit: no sharded exchange sites (needs a "
                "spatially sharded mesh axis and a halo-bearing field)")

        self.transport = None
        self.backend = "ppermute"
        if exchange == "rdma":
            if ndim != 3:
                raise ValueError("halo audit with exchange='rdma' is "
                                 "3D-only (the remote-DMA ring carries "
                                 "rank-3 slabs)")
            from ..ops.pallas.kernels import _interpret_default
            from ..parallel.halo import RdmaTransport

            self.transport = RdmaTransport(mesh, _interpret_default())
            self.backend = self.transport.backend

        ens = self.ensemble > 0
        spec = ensemble_partition_spec(ndim, mesh) if ens else \
            grid_partition_spec(ndim, mesh)
        nf = stencil.num_fields
        sites = list(self.sites)
        transport = self.transport
        corrupt = _corrupt
        axis_names, counts = self._axis_names, self._counts
        bc = stencil.bc_value

        def local_exchange(*fields):
            from ..parallel.halo import exchange_slabs_axis

            outs = []
            for (i, d, fh) in sites:
                left, right = exchange_slabs_axis(
                    fields[i], d, axis_names[d], counts[d], fh, bc[i],
                    self.periodic, transport=transport)
                if corrupt is not None:
                    left = corrupt(i, d, "left", left, axis_names[d])
                    right = corrupt(i, d, "right", right, axis_names[d])
                outs += [left, right]
            return tuple(outs)

        fn = jax.vmap(local_exchange) if ens else local_exchange
        n_out = 2 * len(sites)
        self._received = shard_map(
            fn, mesh=mesh, in_specs=(spec,) * nf,
            out_specs=(spec,) * n_out, check_vma=False)
        self._fn = jax.jit(self._build_compare())

    # -- expected slabs from the global view ----------------------------

    def _expected(self, x: jax.Array, d: int, fh: int, bc,
                  direction: str) -> jax.Array:
        """What the received-slab global array MUST equal, from ``x``.

        Shard j's left slab is the global rows ``[j*L - fh, j*L)`` along
        grid axis ``d`` (its lower neighbor's border interior); the wall
        shard's rows are the guard constant (or the periodic wrap, which
        the modular gather produces by itself).  Right is symmetric.
        """
        a = d + (1 if self.ensemble else 0)
        cnt = self._counts[d]
        G = self.global_shape[d]
        L = G // cnt
        if direction == "left":
            idx = [(j * L - fh + r) % G
                   for j in range(cnt) for r in range(fh)]
            wall_rows = range(0, fh)  # shard 0's rows
        else:
            idx = [((j + 1) * L + r) % G
                   for j in range(cnt) for r in range(fh)]
            wall_rows = range((cnt - 1) * fh, cnt * fh)  # last shard's
        e = jnp.take(x, jnp.asarray(idx, dtype=jnp.int32), axis=a)
        if not self.periodic:
            mask = np.zeros(cnt * fh, dtype=bool)
            mask[list(wall_rows)] = True
            shape = [1] * e.ndim
            shape[a] = cnt * fh
            e = jnp.where(jnp.asarray(mask).reshape(shape),
                          jnp.asarray(bc, e.dtype), e)
        return e

    def _build_compare(self):
        sites = list(self.sites)

        def staged(fields: Fields) -> Dict[str, jax.Array]:
            received = self._received(*fields)
            out: Dict[str, jax.Array] = {}
            for k, (i, d, fh) in enumerate(sites):
                a = d + (1 if self.ensemble else 0)
                cnt = self._counts[d]
                for w, direction in enumerate(self.DIRECTIONS):
                    r = received[2 * k + w]
                    e = self._expected(fields[i], d, fh,
                                       self.stencil.bc_value[i], direction)
                    neq = (_bits(r) != _bits(e))
                    # per-ring-shard mismatch counts: axis a holds
                    # cnt blocks of fh rows each
                    moved = jnp.moveaxis(neq, a, 0)
                    out[f"s{k}_{direction}"] = jnp.sum(
                        moved.reshape(cnt, -1).astype(jnp.int32), axis=1)
            return out

        return staged

    # -- driver-facing --------------------------------------------------

    def audit(self, fields: Fields, step: int,
              chunk: Optional[int] = None) -> Dict[str, Any]:
        """Run one audit pass; returns (and logs) the site table."""
        vals = jax.device_get(self._fn(tuple(fields)))
        site_rows: List[Dict[str, Any]] = []
        mismatches = 0
        for k, (i, d, fh) in enumerate(self.sites):
            for direction in self.DIRECTIONS:
                counts = [int(c) for c in
                          np.asarray(vals[f"s{k}_{direction}"]).reshape(-1)]
                total = sum(counts)
                row = {"field": i, "axis": d, "direction": direction,
                       "halo": fh, "mismatch_count": total}
                if total:
                    row["mismatch_shards"] = [
                        j for j, c in enumerate(counts) if c]
                    mismatches += total
                site_rows.append(row)
        rec: Dict[str, Any] = {
            "step": int(step),
            "ok": mismatches == 0,
            "backend": self.backend,
            "sites_checked": len(site_rows),
            "mismatch_total": mismatches,
            "sites": site_rows,
        }
        if chunk is not None:
            rec["chunk"] = int(chunk)
        if self.trace is not None:
            try:
                self.trace.event("halo_audit", **rec)
            except Exception:  # noqa: BLE001 — never load-bearing
                pass
        return rec

    def audit_or_raise(self, fields: Fields, step: int,
                       chunk: Optional[int] = None) -> Dict[str, Any]:
        rec = self.audit(fields, step, chunk=chunk)
        if not rec["ok"]:
            where = ", ".join(
                f"field {s['field']} axis {s['axis']} {s['direction']} "
                f"shard(s) {s.get('mismatch_shards')}"
                for s in rec["sites"] if s.get("mismatch_count"))
            raise SimulationDiverged(
                f"halo audit FAILED at step {step}: received ghost "
                f"slabs differ bitwise from neighbor interiors at "
                f"{where} (transport {self.backend})", record=rec)
        return rec
