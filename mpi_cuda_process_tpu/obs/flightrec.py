"""Post-mortem flight recorder: a bounded event ring + one-file bundle.

Telemetry logs answer "what happened" only while the telemetry dir
survives; a wedged real-chip round leaves its evidence on a machine
that may be recycled before anyone reads it.  The flight recorder is
the black box: every session keeps a bounded in-memory ring of its most
recent events (a :class:`~.trace.TraceWriter` mirror — zero extra I/O,
zero ops in the jitted step), and on any terminal verdict
(WEDGED/DIVERGED/DEGRADED-abort/give_up) — or on demand via
``scripts/obs_bundle.py PATH`` — one **self-validating, self-contained
JSON bundle** is written next to the log:

* manifest (provenance, config, trace identity block)
* the last-N events verbatim + how many the ring dropped
* open spans at bundle time (root + the emitting thread's stack)
* anomaly findings (obs/anomaly.py) and the final verdict
* the ledger's ``best_known`` row for this label (what "normal" was)
* a ``diagnose_tunnel`` verdict (opt-in: the probe ladder spawns
  subprocesses — ``OBS_BUNDLE_TUNNEL=1``, default for the on-demand
  script, off for in-run emission so a failing run's teardown stays
  bounded)
* a whitelisted env snapshot (fault injection, backend selection)

``scripts/obs_report.py`` renders a bundle exactly like a log, and a
fresh session can read it **with the original telemetry dir deleted**
(the acceptance pin).  Bundle writes are best-effort everywhere they
are triggered: the recorder must never turn a failing run into a
failing-harder run.
"""

from __future__ import annotations

import collections
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from . import trace as trace_lib

BUNDLE_SCHEMA = 1
BUNDLE_KIND = "flight_bundle"
DEFAULT_CAPACITY = 256
DEFAULT_LAST_N = 120

# env vars worth carrying into a post-mortem: fault harness, backend
# selection, campaign identity — never the whole environment (secrets)
_ENV_WHITELIST_PREFIXES = ("FAULT_", "JAX_", "OBS_", "TPU_", "XLA_FLAGS")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class FlightRecorder:
    """Bounded ring of a session's most recent records.

    Registered as a :class:`~.trace.TraceWriter` mirror: every record
    the writer persists (manifest first, then events) also lands here,
    so the ring is exactly the tail of the on-disk log — no second
    vocabulary, no sampling bias beyond recency.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.ring: collections.deque = collections.deque(maxlen=capacity)
        self.manifest: Optional[Dict[str, Any]] = None
        self.events_seen = 0

    def note(self, rec: Dict[str, Any]) -> None:
        if not isinstance(rec, dict):
            return
        if rec.get("kind") == "manifest":
            self.manifest = rec
            return
        self.events_seen += 1
        self.ring.append(rec)

    def events(self, last_n: int = DEFAULT_LAST_N) -> List[Dict[str, Any]]:
        return list(self.ring)[-last_n:]


# ------------------------------------------------------------- capture

def open_spans(session) -> List[Dict[str, Any]]:
    """Best-effort snapshot of spans still open at bundle time.

    The emitter's stacks are per-thread; what a post-mortem can honestly
    capture is the root span (open for the whole run) plus the calling
    thread's stack.  Each entry carries the ids a reader needs to join
    against the exported timeline.
    """
    em = getattr(session, "spans", None)
    if em is None:
        return []
    out: List[Dict[str, Any]] = []
    try:
        blk = em.manifest_block()
        out.append({"name": getattr(em, "root_name", None), "role": "root",
                    **blk})
        for ctx in list(getattr(em, "_stack", lambda: [])()):
            out.append({"name": getattr(ctx, "name", None),
                        "role": "open",
                        "trace_id": getattr(ctx, "trace_id", None),
                        "span_id": getattr(ctx, "span_id", None)})
    except Exception:  # noqa: BLE001 — best-effort by contract
        pass
    return out


def env_snapshot() -> Dict[str, str]:
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(_ENV_WHITELIST_PREFIXES)}


def tunnel_verdict(run: Optional[bool] = None,
                   timeout_s: float = 90.0) -> Dict[str, Any]:
    """One ``diagnose_tunnel`` probe-ladder verdict for the bundle.

    ``run=None`` consults ``OBS_BUNDLE_TUNNEL`` (default off: the probe
    ladder spawns jax subprocesses, too heavy for every aborted run's
    teardown).  Failure modes collapse to an honest UNAVAILABLE rather
    than blocking the bundle.
    """
    if run is None:
        run = os.environ.get("OBS_BUNDLE_TUNNEL", "0") not in ("0", "")
    if not run:
        return {"verdict": "NOT_RUN",
                "detail": "probe ladder skipped (OBS_BUNDLE_TUNNEL unset)"}
    script = os.path.join(_REPO, "scripts", "diagnose_tunnel.py")
    try:
        out = subprocess.run(
            [sys.executable, script, "--timeout",
             str(max(5.0, timeout_s * 0.4))],
            capture_output=True, text=True, timeout=timeout_s)
        for line in out.stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            rec = json.loads(line)
            return {"verdict": rec.get("verdict", "UNKNOWN"),
                    "detail": rec.get("detail"),
                    "probes": rec.get("probes")}
        return {"verdict": "UNAVAILABLE",
                "detail": f"no verdict line (rc={out.returncode})"}
    except Exception as e:  # noqa: BLE001 — never block the bundle
        return {"verdict": "UNAVAILABLE",
                "detail": f"{type(e).__name__}: {e}"[:300]}


def _best_known_for(manifest: Optional[Dict[str, Any]]) -> Optional[Dict]:
    """The ledger's best_known row for this run's label, best-effort."""
    try:
        from . import ledger as ledger_lib

        if not manifest or manifest.get("tool") != "cli":
            return None
        run = manifest.get("run") or {}
        prov = manifest.get("provenance") or {}
        label = ledger_lib._cli_label(run)
        probe = ledger_lib.make_row(
            label, 1.0, source="flightrec-probe",
            expected_backend=prov.get("backend", "cpu"),
            flags=ledger_lib._flags(run) or None)
        best = ledger_lib.best_known(
            ledger_lib.read_rows(ledger_lib.default_ledger_path()))
        return best.get(ledger_lib.baseline_key(probe))
    except Exception:  # noqa: BLE001 — the ledger may not exist yet
        return None


# -------------------------------------------------------------- bundle

def build_bundle(manifest: Optional[Dict[str, Any]],
                 events: List[Dict[str, Any]],
                 reason: str,
                 verdict: Optional[str] = None,
                 events_seen: Optional[int] = None,
                 open_span_list: Optional[List[Dict[str, Any]]] = None,
                 extra_events: Optional[Dict[str, List[Dict]]] = None,
                 run_tunnel: Optional[bool] = None,
                 last_n: int = DEFAULT_LAST_N) -> Dict[str, Any]:
    """Assemble a self-contained post-mortem; validates before returning.

    ``extra_events`` attaches sibling tails under their own keys (the
    supervisor bundles the final attempt's child log alongside its own
    trail).  ``verdict=None`` is replayed from the events through
    :class:`~.metrics.RunMetrics` — one verdict definition, not two.
    """
    events = [e for e in events if isinstance(e, dict)][-last_n:]
    anomalies = [e for e in events if e.get("kind") == "anomaly"]
    if verdict is None:
        try:
            from . import metrics as metrics_lib

            rm = metrics_lib.RunMetrics()
            if manifest:
                rm.ingest(manifest)
            for e in events:
                rm.ingest(e)
            verdict = rm.status().get("verdict")
        except Exception:  # noqa: BLE001
            verdict = "UNKNOWN"
    bundle: Dict[str, Any] = {
        "schema": BUNDLE_SCHEMA,
        "kind": BUNDLE_KIND,
        "created_at": time.time(),
        "reason": str(reason),
        "verdict": verdict,
        "manifest": manifest,
        "trace": (manifest or {}).get("trace"),
        "events": events,
        "events_seen": int(events_seen if events_seen is not None
                           else len(events)),
        "open_spans": open_span_list or [],
        "anomalies": anomalies,
        "best_known": _best_known_for(manifest),
        "tunnel": tunnel_verdict(run=run_tunnel),
        "env": env_snapshot(),
    }
    if extra_events:
        bundle["sibling_events"] = {
            k: [e for e in v if isinstance(e, dict)][-last_n:]
            for k, v in extra_events.items()}
    validate_bundle(bundle)
    return bundle


def validate_bundle(b: Any) -> Dict[str, Any]:
    """Raise ValueError listing EVERY problem; return ``b`` when valid."""
    if not isinstance(b, dict):
        raise ValueError(f"bundle must be a dict, got {type(b).__name__}")
    problems: List[str] = []
    if b.get("schema") != BUNDLE_SCHEMA:
        problems.append(f"schema must be {BUNDLE_SCHEMA} "
                        f"(got {b.get('schema')!r})")
    if b.get("kind") != BUNDLE_KIND:
        problems.append(f"kind must be {BUNDLE_KIND!r} (got {b.get('kind')!r})")
    if not isinstance(b.get("created_at"), (int, float)) \
            or b.get("created_at", 0) <= 0:
        problems.append("created_at must be a positive unix time")
    if not isinstance(b.get("reason"), str) or not b.get("reason"):
        problems.append("reason must be a nonempty str")
    m = b.get("manifest")
    if m is not None:
        try:
            trace_lib.validate_manifest(m)
        except ValueError as e:
            problems.append(f"manifest: {e}")
    evs = b.get("events")
    if not isinstance(evs, list):
        problems.append("events must be a list")
    else:
        for i, e in enumerate(evs):
            try:
                trace_lib.validate_event(e)
            except ValueError as err:
                problems.append(f"event {i}: {err}")
                break  # one bad event names the class; don't flood
    if not isinstance(b.get("events_seen"), int) or b["events_seen"] < 0:
        problems.append("events_seen must be a nonnegative int")
    for key in ("open_spans", "anomalies"):
        if not isinstance(b.get(key), list):
            problems.append(f"{key} must be a list")
    tun = b.get("tunnel")
    if not isinstance(tun, dict) or not isinstance(tun.get("verdict"), str):
        problems.append("tunnel must be a dict with a str verdict")
    if not isinstance(b.get("env"), dict):
        problems.append("env must be a dict")
    if problems:
        raise ValueError("invalid flight bundle: " + "; ".join(problems))
    return b


def default_bundle_path(log_path: str) -> str:
    """``x.jsonl`` -> ``x.bundle.json`` (``OBS_BUNDLE_DIR`` redirects)."""
    base = os.path.basename(log_path)
    if base.endswith(".jsonl"):
        base = base[:-len(".jsonl")]
    out_dir = os.environ.get("OBS_BUNDLE_DIR") or \
        os.path.dirname(os.path.abspath(log_path))
    return os.path.join(out_dir, base + ".bundle.json")


def write_bundle(bundle: Dict[str, Any], path: str) -> str:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(bundle, fh, default=str, indent=1)
        fh.write("\n")
    return path


def read_bundle(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return validate_bundle(json.load(fh))


def is_bundle_file(path: str) -> bool:
    """Cheap sniff: a JSON object whose kind is ``flight_bundle``."""
    try:
        with open(path) as fh:
            head = fh.read(512).lstrip()
        if not head.startswith("{"):
            return False
        if f'"{BUNDLE_KIND}"' in head:
            return True
        with open(path) as fh:
            obj = json.load(fh)
        return isinstance(obj, dict) and obj.get("kind") == BUNDLE_KIND
    except Exception:  # noqa: BLE001
        return False


def bundle_from_session(session, reason: str,
                        verdict: Optional[str] = None,
                        run_tunnel: Optional[bool] = None,
                        extra_events: Optional[Dict[str, List[Dict]]] = None,
                        ) -> Optional[str]:
    """Emit a bundle from a live session's ring; returns the path or None.

    Best-effort by contract: every failure is swallowed — this runs in
    teardown paths where the run is already dying.
    """
    try:
        flight = getattr(session, "flight", None)
        if flight is None:
            return None
        bundle = build_bundle(
            flight.manifest, flight.events(), reason, verdict=verdict,
            events_seen=flight.events_seen,
            open_span_list=open_spans(session),
            extra_events=extra_events, run_tunnel=run_tunnel)
        return write_bundle(bundle, default_bundle_path(session.path))
    except Exception:  # noqa: BLE001 — never fail the failing run harder
        return None


def bundle_from_log(log_path: str, reason: str = "on-demand",
                    run_tunnel: Optional[bool] = None,
                    out_path: Optional[str] = None) -> str:
    """On-demand bundle from a finished (or abandoned) telemetry log."""
    manifest, events = trace_lib.read_log(log_path)
    if manifest.get("kind") != "manifest":
        raise ValueError(f"{log_path}: first record is not a manifest")
    bundle = build_bundle(manifest, events, reason,
                          events_seen=len(events), run_tunnel=run_tunnel)
    return write_bundle(bundle, out_path or default_bundle_path(log_path))
