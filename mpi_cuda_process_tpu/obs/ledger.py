"""Append-only campaign ledger: every measurement, with provenance, forever.

Three wedged TPU rounds left BENCH_r04/r05 reading 0.0/stale with no
durable record of what the framework HAD measured — the scoreboard
could not distinguish "never measured" from "measured 106 Gcells/s,
tunnel currently dead".  This module is the durable cross-round table:

* every telemetry log (cli/bench/measure/scaling — the obs/ schema) is
  ingested into one append-only, schema-versioned JSONL ledger
  (:func:`ingest_log`; the three benchmark drivers call it
  automatically at the end of a run);
* the historical driver scoreboards (``BENCH_r0*.json``) and campaign
  tables (``benchmarks/results_r0*.json``) enter via a one-shot,
  idempotent :func:`backfill`;
* rows are keyed by label x config x mesh x kind x flags x
  BUILDER_REV (:func:`make_key`), and **quarantine** is first-class:
  0.0/stale/suspect/errored/backend-mismatched values are recorded
  with their reason and heartbeat verdict instead of being scorable —
  a quarantined row can NEVER become a baseline
  (:func:`best_known` filters on ``status == "ok"``);
* :func:`best_known` exposes best-known-value-with-provenance per
  (label, backend) — the table ``scripts/perf_gate.py`` gates against
  and ROADMAP item 4's auto-policy will read.

No jax is imported here; the ledger must be writable/readable on a
wedged box.  ``python -m mpi_cuda_process_tpu.obs.ledger`` offers
``backfill`` / ``ingest PATH`` / ``best`` subcommands (the package
import itself may pull jax; on a wedged box run it under
``JAX_PLATFORMS=cpu`` or use ``scripts/perf_gate.py`` which forces the
CPU backend first).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from . import trace as trace_lib

LEDGER_SCHEMA = 1

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Note-sniffing for replayed/stale bench records: BENCH_r01's cached
# replay predates the ``stale`` flag, so the prose is the only marker.
_STALE_NOTE_MARKERS = ("stale", "cached", "backend unresponsive",
                       "not a fresh measurement")


def default_ledger_path() -> str:
    """``OBS_LEDGER_PATH`` override (tests/tier1), else the committed
    cross-round table next to the campaign results."""
    return os.environ.get("OBS_LEDGER_PATH") or \
        os.path.join(_REPO, "benchmarks", "ledger.jsonl")


# ---------------------------------------------------------------- rows

def make_key(label: str, backend: Optional[str] = None,
             grid: Any = None, mesh: Any = None,
             kind: Optional[str] = None, dtype: Optional[str] = None,
             flags: Optional[Dict[str, Any]] = None,
             builder_rev: Optional[int] = None) -> Dict[str, Any]:
    """The row identity: label x config x mesh x kind x flags x rev."""
    return {
        "label": str(label),
        "backend": backend,
        "grid": list(grid) if grid else None,
        "mesh": list(mesh) if mesh else None,
        "kind": kind,
        "dtype": dtype,
        "flags": dict(flags) if flags else None,
        "builder_rev": builder_rev,
    }


def key_id(key: Dict[str, Any]) -> str:
    return json.dumps(key, sort_keys=True, separators=(",", ":"))


def baseline_key(row: Dict[str, Any]) -> str:
    """Baseline identity for the gate: same label on the same backend
    under the same EXCHANGE MODE and the same ENSEMBLE SIZE.

    Deliberately coarser than :func:`key_id`: a BUILDER_REV bump or a
    flag change must still be COMPARED against the old number (that
    comparison is the regression gate's whole job), but a CPU smoke
    must never be judged against a TPU baseline — and a ppermute
    measurement must never be the baseline an rdma run is scored
    against (the transports are different execution paths; a label that
    exists in the ledger only under the other mode is NO_BASELINE, not
    REGRESSED).  The same rule guards the ensemble axis (round 15): an
    ``ens=8`` row aggregates 8 members' throughput, so judging it
    against a single-sim baseline (or vice versa) would read the batch
    multiplier as an 8x regression/improvement — across ensemble sizes
    the gate reports NO_BASELINE instead.  And the KERNEL VARIANT
    (round 16, policy/autotune.py): a ``|var:<id>`` row runs the same
    kernel family under swept constants, so it must never baseline a
    default-constant row (or vice versa) — a variant adoption would
    otherwise read as a regression of the default.  Mode, ensemble and
    variant ride the flags only when non-default, so every pre-existing
    row keeps its historical baseline key byte-for-byte.
    """
    k = row["key"]
    flags = k.get("flags") or {}
    mode = flags.get("exchange")
    tail = f"|{mode}" if mode else ""
    ens = flags.get("ensemble")
    if ens:
        tail += f"|ens{ens}"
    var = flags.get("kernel_variant")
    if var:
        tail += f"|var:{var}"
    grp = flags.get("groups_sig")
    if grp:
        # the GROUP SIGNATURE (round 18, parallel/groups.py): a coupled
        # --groups row times a heterogeneous multi-program round, so it
        # must never baseline a monolithic row (or vice versa, or a row
        # with a DIFFERENT split) — across group signatures the gate
        # reports NO_BASELINE, not REGRESSED
        tail += f"|grp:{grp}"
    gtx = flags.get("group_transport")
    if gtx:
        # the INTERFACE TRANSPORT (round 23): a collective-transport
        # coupled row moves its ghost bands over ICI ppermute rounds, a
        # device_put row over host-mediated transfers — different
        # execution paths, so one must never baseline the other; across
        # transports the gate reports NO_BASELINE.  Rides the flags
        # only when non-default (device_put), so every pre-existing
        # coupled row keeps its historical baseline key byte-for-byte.
        tail += f"|gtx:{gtx}"
    return f"{k['label']}|{k.get('backend')}{tail}"


def classify(value: Any, *, stale: bool = False, suspect: bool = False,
             error: Optional[str] = None,
             cancelled: bool = False,
             backend: Optional[str] = None,
             expected_backend: Optional[str] = None,
             heartbeat: Optional[str] = None,
             health: Optional[str] = None) -> Tuple[str, Optional[str]]:
    """Quarantine decision for one measurement: ``(status, reason)``.

    Order matters only for which reason is reported; ANY tripped rule
    quarantines.  A value of 0.0 (the wedged scoreboards) is never a
    measurement.  A DIVERGED health verdict (obs/health.py) quarantines
    with reason ``diverged``: the throughput of a run computing garbage
    is not a baseline candidate, however fast it looked.  A cancelled
    run (cancellation.py) quarantines with reason ``cancelled`` —
    checked before ``error`` so a deliberately stopped run can never
    read as ``errored``.
    """
    if cancelled:
        return "quarantined", "cancelled"
    if error:
        return "quarantined", f"errored: {str(error)[:120]}"
    if stale:
        return "quarantined", "stale replay — not a fresh measurement"
    if suspect:
        return "quarantined", "noise-floor suspect"
    if backend and expected_backend and backend != expected_backend:
        return "quarantined", (f"backend mismatch: record says "
                               f"{backend!r}, provenance says "
                               f"{expected_backend!r}")
    if health == "DIVERGED":
        return "quarantined", "diverged"
    if heartbeat in ("WEDGED", "STALLED"):
        return "quarantined", f"heartbeat verdict {heartbeat}"
    if not isinstance(value, (int, float)) or value <= 0.0:
        return "quarantined", f"zero/missing value ({value!r})"
    return "ok", None


def make_row(label: str, value: Any, *, source: str,
             unit: str = "Mcells/s",
             measured_at: Optional[float] = None,
             ms_per_step: Optional[float] = None,
             heartbeat: Optional[str] = None,
             health: Optional[str] = None,
             provenance: Optional[Dict[str, Any]] = None,
             detail: Optional[Dict[str, Any]] = None,
             stale: bool = False, suspect: bool = False,
             error: Optional[str] = None,
             cancelled: bool = False,
             backend: Optional[str] = None,
             expected_backend: Optional[str] = None,
             **key_kw: Any) -> Dict[str, Any]:
    status, reason = classify(
        value, stale=stale, suspect=suspect, error=error,
        cancelled=cancelled,
        backend=backend, expected_backend=expected_backend,
        heartbeat=heartbeat, health=health)
    key = make_key(label, backend=backend or expected_backend, **key_kw)
    row: Dict[str, Any] = {
        "schema": LEDGER_SCHEMA,
        "kind": "ledger_row",
        "ingested_at": time.time(),
        "label": str(label),
        "key": key,
        "key_id": key_id(key),
        "value": value if isinstance(value, (int, float)) else None,
        "unit": unit,
        "ms_per_step": ms_per_step,
        "measured_at": measured_at,
        "status": status,
        "quarantine": reason,
        "heartbeat": heartbeat,
        "source": source,
        "provenance": provenance or None,
        "detail": detail or None,
    }
    if health is not None:
        # only when a health verdict exists: every pre-existing row
        # (and its re-ingest) stays byte-identical
        row["health"] = health
    validate_row(row)
    return row


def validate_row(row: Any) -> Dict[str, Any]:
    """Raise ValueError listing every problem; return ``row`` if valid."""
    if not isinstance(row, dict):
        raise ValueError(f"ledger row must be a dict, got "
                         f"{type(row).__name__}")
    problems: List[str] = []
    if row.get("schema") != LEDGER_SCHEMA:
        problems.append(f"schema must be {LEDGER_SCHEMA} "
                        f"(got {row.get('schema')!r}); bump the reader, "
                        "never the record")
    if row.get("kind") != "ledger_row":
        problems.append(f"kind must be 'ledger_row' (got {row.get('kind')!r})")
    if not isinstance(row.get("label"), str) or not row.get("label"):
        problems.append(f"label must be a nonempty str "
                        f"(got {row.get('label')!r})")
    if not isinstance(row.get("key"), dict):
        problems.append("key must be a dict")
    if row.get("status") not in ("ok", "quarantined"):
        problems.append(f"status must be ok|quarantined "
                        f"(got {row.get('status')!r})")
    if row.get("status") == "ok":
        v = row.get("value")
        if not isinstance(v, (int, float)) or v <= 0:
            problems.append(f"an ok row needs a positive value (got {v!r})")
    elif not row.get("quarantine"):
        problems.append("a quarantined row needs a quarantine reason")
    if not isinstance(row.get("source"), str) or not row.get("source"):
        problems.append("source must be a nonempty str")
    if not isinstance(row.get("ingested_at"), (int, float)) \
            or row.get("ingested_at", 0) <= 0:
        problems.append("ingested_at must be a positive unix time")
    if problems:
        raise ValueError("invalid ledger row: " + "; ".join(problems))
    return row


# ------------------------------------------------------------- file IO

def read_rows(path: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every valid row of the ledger (missing file -> []).

    A corrupt line raises with its line number — an append-only file
    that went bad must be loud, not silently shortened.
    """
    path = path or default_ledger_path()
    if not os.path.exists(path):
        return []
    rows: List[Dict[str, Any]] = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(validate_row(json.loads(line)))
            except ValueError as e:
                raise ValueError(f"{path}:{i + 1}: {e}") from None
    return rows


def _row_uid(row: Dict[str, Any]) -> Tuple[str, Optional[float], str]:
    ts = row.get("measured_at")
    return (row["key_id"],
            round(float(ts), 3) if isinstance(ts, (int, float)) else None,
            row["source"])


def append_rows(rows: Iterable[Dict[str, Any]],
                path: Optional[str] = None) -> int:
    """Append rows not already present (by key x measured_at x source).

    The dedupe makes every ingest/backfill idempotent: re-running a
    backfill or re-ingesting the same log appends nothing.  Returns the
    number of rows actually appended.
    """
    path = path or default_ledger_path()
    seen = {_row_uid(r) for r in read_rows(path)}
    fresh = []
    for r in rows:
        uid = _row_uid(validate_row(r))
        if uid not in seen:
            seen.add(uid)
            fresh.append(r)
    if not fresh:
        return 0
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a") as fh:
        for r in fresh:
            fh.write(json.dumps(r, default=str) + "\n")
    return len(fresh)


# ------------------------------------------------- telemetry ingestion

def _flags(run: Dict[str, Any]) -> Dict[str, Any]:
    out = {k: run.get(k) for k in ("fuse", "fuse_kind", "overlap",
                                   "pipeline")
           if run.get(k)}
    # exchange mode and ensemble size are part of the row identity AND
    # the baseline key (see baseline_key) — recorded only when
    # non-default so every pre-existing key (and its best_known dedupe)
    # stays byte-identical
    if run.get("exchange") and run["exchange"] != "ppermute":
        out["exchange"] = run["exchange"]
    if run.get("ensemble"):
        out["ensemble"] = run["ensemble"]
        if run.get("ensemble_mesh"):
            out["ensemble_mesh"] = run["ensemble_mesh"]
    if run.get("kernel_variant"):
        out["kernel_variant"] = run["kernel_variant"]
    if run.get("groups"):
        # a short stable signature, not the raw spec string: the flag
        # set rides every row and key, and the signature is what the
        # |grp: baseline-key tail needs (config.groups_signature)
        from ..config import groups_signature

        out["groups_sig"] = groups_signature(run["groups"])
        if run.get("group_transport") and \
                run["group_transport"] != "device_put":
            # non-default interface transport (round 23): part of the
            # identity AND the |gtx: baseline-key tail
            out["group_transport"] = run["group_transport"]
    return out


def _cli_label(run: Dict[str, Any]) -> str:
    parts = [str(run.get("stencil") or "run"),
             "x".join(map(str, run.get("grid") or ()))]
    if run.get("dtype"):
        parts.append(str(run["dtype"]))
    if run.get("fuse"):
        parts.append(f"fuse{run['fuse']}")
    if run.get("fuse_kind") and run["fuse_kind"] != "auto":
        parts.append(str(run["fuse_kind"]))
    if run.get("mesh"):
        parts.append("mesh" + "x".join(map(str, run["mesh"])))
    if run.get("overlap"):
        parts.append("overlap")
    if run.get("pipeline"):
        parts.append("pipeline")
    if run.get("exchange") and run["exchange"] != "ppermute":
        parts.append(str(run["exchange"]))
    if run.get("ensemble"):
        parts.append(f"ens{run['ensemble']}")
        if run.get("ensemble_mesh"):
            parts.append(f"ensmesh{run['ensemble_mesh']}")
    if run.get("kernel_variant"):
        parts.append(f"var{run['kernel_variant']}")
    if run.get("groups"):
        n = len([c for c in str(run["groups"]).split(",") if c.strip()])
        parts.append(f"grp{n}")
    return "cli_" + "_".join(p for p in parts if p)


def group_label(op: Any) -> str:
    """The per-group ledger row label (round 23): the op alone.

    Deliberately minimal — the clause signature in the flags
    (``groups_sig`` of the single clause's canonical form, with its
    mode tokens folded in) carries the full identity into the baseline
    key, so two clauses differing in ANYTHING (devices, z fraction,
    sub-mesh, dtype, ratio, modes) never share a baseline.  The policy
    resolver (policy/select.py) builds the same label + flags for its
    per-group candidates, so a measured row matches if and only if
    this exact clause was actually run.
    """
    return f"cli_grp_{op}"


def group_flags(clause: str, transport: Optional[str] = None
                ) -> Dict[str, Any]:
    """The per-group ledger row flags for one canonical clause."""
    from ..config import groups_signature

    out: Dict[str, Any] = {"groups_sig": groups_signature(clause)}
    if transport and transport != "device_put":
        out["group_transport"] = transport
    return out


def _group_rows(manifest: Dict[str, Any], events: List[Dict[str, Any]],
                run: Dict[str, Any], prov: Dict[str, Any], source: str,
                hb: Optional[str], health: Optional[str]
                ) -> List[Dict[str, Any]]:
    """Per-group rows for one coupled cli log (round 23).

    One row per group, valued at the group's wall-weighted mean
    Mcells/s over its ``group_chunk`` events — the per-group measured
    table ``--auto-policy --groups`` resolves each group's mode tokens
    against.  Needs the manifest ``groups`` block's ``clause`` entry
    (older logs without it, or runs that died before any chunk, add
    nothing — the main coupled row still lands as before).
    """
    rows: List[Dict[str, Any]] = []
    transport = run.get("group_transport") or None
    for meta in manifest.get("groups") or []:
        if not isinstance(meta, dict) or not meta.get("clause"):
            continue
        name = meta.get("group")
        wall = 0.0
        weighted = 0.0
        last_t = None
        for e in events:
            if e.get("kind") != "group_chunk" or e.get("group") != name:
                continue
            w = e.get("wall_s")
            v = e.get("mcells_per_s")
            if not isinstance(w, (int, float)) or w <= 0 or \
                    not isinstance(v, (int, float)):
                continue
            wall += w
            weighted += v * w
            if e.get("t") is not None:
                last_t = e["t"]
        if wall <= 0:
            continue
        rows.append(make_row(
            group_label(meta.get("op")), round(weighted / wall, 3),
            source=source, measured_at=last_t, heartbeat=hb,
            health=health, expected_backend=prov.get("backend"),
            provenance=_prov_subset(prov),
            grid=meta.get("grid"), mesh=meta.get("mesh") or None,
            dtype=meta.get("dtype"),
            flags=group_flags(meta["clause"], transport),
            builder_rev=prov.get("builder_rev"),
            detail={"group": name, "clause": meta["clause"],
                    "modes": list(meta.get("modes") or [])}))
    return rows


def _scaling_label(run: Dict[str, Any], rung: Dict[str, Any]) -> str:
    parts = ["scaling", str(rung.get("mode") or run.get("mode") or "?"),
             str(rung.get("stencil") or "?"),
             "x".join(map(str, rung.get("grid") or ())),
             "mesh" + "x".join(map(str, rung.get("mesh") or ()))]
    if rung.get("fuse"):
        parts.append(f"fuse{rung['fuse']}")
    if rung.get("fuse_kind"):
        parts.append(str(rung["fuse_kind"]))
    if rung.get("overlap"):
        parts.append("overlap")
    if rung.get("pipeline"):
        parts.append("pipeline")
    if rung.get("exchange") and rung["exchange"] != "ppermute":
        parts.append(str(rung["exchange"]))
    if rung.get("ensemble"):
        parts.append(f"ens{rung['ensemble']}")
    return "_".join(parts)


def _prov_subset(prov: Dict[str, Any]) -> Dict[str, Any]:
    return {k: prov.get(k) for k in ("git_sha", "backend", "device_kind",
                                     "device_count", "builder_rev",
                                     "jax_version")}


def _bench_rows(rec: Dict[str, Any], source: str,
                prov: Optional[Dict[str, Any]] = None,
                measured_at: Optional[float] = None,
                heartbeat: Optional[str] = None) -> List[Dict[str, Any]]:
    """Rows from one bench.py headline record (live event or BENCH_r0*).

    The wedged-path vocabulary is quarantined wholesale: ``stale`` flags,
    ``*_cached``/``*_unmeasured`` metric names, and the pre-flag cached
    replay whose only marker is the note prose.  The
    ``last_real_measurement`` pointer rides in ``detail`` so the
    quarantined row still names the last value that WAS real.
    """
    prov = prov or {}
    note = str(rec.get("note") or "").lower()
    metric = str(rec.get("metric") or "bench")
    stale = bool(rec.get("stale")) \
        or metric.endswith(("_cached", "_unmeasured")) \
        or any(m in note for m in _STALE_NOTE_MARKERS)
    hb = heartbeat
    if hb is None and isinstance(rec.get("heartbeat"), dict):
        hb = rec["heartbeat"].get("verdict")
    detail = {}
    if rec.get("last_real_measurement"):
        detail["last_real_measurement"] = rec["last_real_measurement"]
    if rec.get("note"):
        detail["note"] = rec["note"]
    rows = [make_row(
        metric, rec.get("value"), source=source,
        unit=str(rec.get("unit") or "Mcells/s"),
        measured_at=measured_at,
        stale=stale, suspect=bool(rec.get("suspect")),
        backend=rec.get("backend"),
        expected_backend=prov.get("backend"),
        heartbeat=hb, provenance=_prov_subset(prov) if prov else None,
        detail=detail or None,
        kind=rec.get("compute"), builder_rev=prov.get("builder_rev"))]
    if rec.get("value_512cubed") is not None:
        rows.append(make_row(
            metric + "_512cubed", rec.get("value_512cubed"),
            source=source, measured_at=measured_at, stale=stale,
            suspect=bool(rec.get("suspect_512cubed")),
            backend=rec.get("backend"),
            expected_backend=prov.get("backend"), heartbeat=hb,
            provenance=_prov_subset(prov) if prov else None,
            kind=rec.get("compute_512cubed"),
            builder_rev=prov.get("builder_rev")))
    return rows


def rows_from_log(log_path: str) -> List[Dict[str, Any]]:
    """Ledger rows for one telemetry JSONL (any of the four tools).

    Does NOT append — callers pair this with :func:`append_rows`
    (``ingest_log``) or use the rows directly (the perf gate's "fresh"
    side).
    """
    manifest, events = trace_lib.read_log(log_path)
    trace_lib.validate_manifest(manifest)
    tool = manifest["tool"]
    run = manifest.get("run") or {}
    prov = manifest.get("provenance") or {}
    source = f"telemetry:{os.path.abspath(log_path)}"
    # newest heartbeat verdict anywhere in the log (summary included)
    hb = None
    for e in events:
        if e.get("kind") == "heartbeat":
            hb = e.get("verdict")
        elif e.get("kind") == "summary" and isinstance(
                e.get("heartbeat"), dict):
            hb = e["heartbeat"].get("verdict") or hb
    # health sentinel verdict (obs/health.py): once DIVERGED, the run's
    # numbers are garbage-adjacent — every row of this log quarantines
    # with reason 'diverged' (a later HEALTHY check cannot un-diverge a
    # run; the CLI aborts at the first DIVERGED boundary anyway)
    health = None
    for e in events:
        if e.get("kind") == "health":
            health = e.get("verdict") if health != "DIVERGED" else health
    rows: List[Dict[str, Any]] = []
    # restart trail (resilience/): a resumed run names its resume point
    # in a 'resume' event; the row detail carries it so downstream
    # consumers (perf_gate) can flag an after-restart value as honest
    # but restarted.  Old logs never carried the event, so every
    # pre-existing row detail stays byte-identical.
    resumed_from = None
    for e in events:
        if e.get("kind") == "resume" and \
                e.get("resumed_from_step") is not None:
            resumed_from = e["resumed_from_step"]
    # cooperative cancel (cancellation.py): the run wrote a 'cancelled'
    # event instead of an error — its row must quarantine with reason
    # 'cancelled', never 'errored: ...'
    cancelled_ev = None
    for e in events:
        if e.get("kind") == "cancelled":
            cancelled_ev = e
    if tool == "cli":
        summaries = [e for e in events if e.get("kind") == "summary"]
        # run-doctor findings (obs/anomaly.py, --anomaly): a DEGRADED
        # run's value is honest — the steps ran, the number is real —
        # so the row is NOT quarantined, just flagged.  perf_gate
        # renders the flag as [degraded]; obs_report shows the
        # findings.  Clean runs (N == 0) add no detail key, so every
        # pre-existing row stays byte-identical.
        n_anomalies = sum(1 for e in events if e.get("kind") == "anomaly")
        if run.get("groups"):
            # per-group rows land ALONGSIDE the coupled headline row —
            # the policy resolver reads these, the perf gate the main
            rows.extend(_group_rows(manifest, events, run, prov,
                                    source, hb, health))
        for s in summaries:
            detail = {}
            if resumed_from is not None:
                detail["resumed_from_step"] = resumed_from
            if n_anomalies:
                detail["degraded"] = n_anomalies
            rows.append(make_row(
                _cli_label(run), s.get("mcells_per_s"), source=source,
                measured_at=s.get("t"), heartbeat=hb, health=health,
                expected_backend=prov.get("backend"),
                provenance=_prov_subset(prov),
                grid=run.get("grid"), mesh=run.get("mesh"),
                kind=run.get("fuse_kind"), dtype=run.get("dtype"),
                flags=_flags(run), builder_rev=prov.get("builder_rev"),
                detail=detail or None))
        if cancelled_ev is not None and not summaries:
            # a cancelled run ends before its summary — the row still
            # lands (value-less, quarantined 'cancelled') so the ledger
            # records a deliberate stop, distinct from a crash
            rows.append(make_row(
                _cli_label(run), None, source=source,
                measured_at=cancelled_ev.get("t"),
                heartbeat=hb, health=health, cancelled=True,
                expected_backend=prov.get("backend"),
                provenance=_prov_subset(prov),
                grid=run.get("grid"), mesh=run.get("mesh"),
                kind=run.get("fuse_kind"), dtype=run.get("dtype"),
                flags=_flags(run), builder_rev=prov.get("builder_rev"),
                detail={"cancelled_at_step": cancelled_ev.get("step")}
                if cancelled_ev.get("step") is not None else None))
        if health == "DIVERGED" and not summaries:
            # a diverged run aborts before its summary — the row still
            # lands (value-less, quarantined 'diverged') so the ledger
            # records that this config BLEW UP rather than nothing
            div = [e for e in events if e.get("kind") == "health"
                   and e.get("verdict") == "DIVERGED"]
            detail = {"health_reason": str(div[-1].get("reason"))[:200]} \
                if div and div[-1].get("reason") else None
            rows.append(make_row(
                _cli_label(run), None, source=source,
                measured_at=div[-1].get("t") if div else None,
                heartbeat=hb, health=health,
                expected_backend=prov.get("backend"),
                provenance=_prov_subset(prov),
                grid=run.get("grid"), mesh=run.get("mesh"),
                kind=run.get("fuse_kind"), dtype=run.get("dtype"),
                flags=_flags(run), builder_rev=prov.get("builder_rev"),
                detail=detail))
    elif tool == "bench":
        for e in events:
            if e.get("kind") != "result":
                continue
            rows.extend(_bench_rows(e, source, prov=prov,
                                    measured_at=e.get("t"), heartbeat=hb))
    elif tool == "measure":
        for e in events:
            if e.get("kind") != "label":
                continue
            status = e.get("status")
            detail = {}
            if status:
                detail["status"] = status
            if e.get("attempts"):
                # measured after a supervised retry: attempt count rides
                # the row so the gate can flag the value
                detail["attempts"] = e["attempts"]
            rows.append(make_row(
                str(e.get("label")), e.get("mcells_per_s"), source=source,
                measured_at=e.get("t"), heartbeat=hb, health=health,
                error=(e.get("error") or None) if status in
                      ("error", "timeout", "missing") else None,
                expected_backend=prov.get("backend"),
                provenance=_prov_subset(prov),
                kind=e.get("compute"),
                builder_rev=run.get("builder_rev")
                or prov.get("builder_rev"),
                detail=detail or None))
    elif tool == "scaling":
        for e in events:
            if e.get("kind") != "rung":
                continue
            rows.append(make_row(
                _scaling_label(run, e),
                e.get("mcells_per_s") or e.get("ms_per_step_full"),
                source=source, measured_at=e.get("t"), heartbeat=hb,
                health=health,
                expected_backend=prov.get("backend"),
                provenance=_prov_subset(prov),
                grid=e.get("grid"), mesh=e.get("mesh"),
                kind=e.get("kernel_kind") or e.get("fuse_kind"),
                # the historical flag set PLUS exchange-when-non-default:
                # re-ingesting an old log must reproduce its old key_id
                # byte-for-byte (idempotent append), so the set is only
                # ever extended by fields old logs never carried
                flags={**{k: e.get(k) for k in ("fuse", "overlap",
                                                "pipeline") if e.get(k)},
                       **({"exchange": e["exchange"]}
                          if e.get("exchange")
                          and e["exchange"] != "ppermute" else {}),
                       **({"ensemble": e["ensemble"]}
                          if e.get("ensemble") else {})},
                builder_rev=prov.get("builder_rev"),
                unit=("Mcells/s" if e.get("mcells_per_s") is not None
                      else "ms/step")))
    return rows


def ingest_log(log_path: str, ledger_path: Optional[str] = None) -> int:
    """Parse one telemetry log and append its rows; returns rows added."""
    return append_rows(rows_from_log(log_path), ledger_path)


def record_wedged_bench(rec: Dict[str, Any],
                        ledger_path: Optional[str] = None) -> int:
    """bench.py's wedged-path hook: the stale/0.0 record enters the
    ledger QUARANTINED (with its heartbeat verdict and the
    last_real_measurement pointer) — downstream tooling reading the
    ledger can never mistake it for a baseline.  Never raises."""
    try:
        hb = None
        if isinstance(rec.get("heartbeat"), dict):
            hb = rec["heartbeat"].get("verdict")
        # no measured_at: there was no measurement, and a stable uid
        # keeps the watchdog/main double-fire from writing twice
        rows = _bench_rows(rec, source="bench:wedged-path", heartbeat=hb)
        # belt-and-braces: the wedged path NEVER produces an ok row
        for r in rows:
            if r["status"] == "ok":
                r["status"] = "quarantined"
                r["quarantine"] = "wedged-path record"
        return append_rows(rows, ledger_path)
    except Exception:  # noqa: BLE001 — watchdog-thread safety
        return 0


# ------------------------------------------------------------ backfill

def _backfill_bench_files(repo: str) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))):
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except Exception:  # noqa: BLE001 — skip foreign files
            continue
        rec = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(rec, dict):
            continue
        rows.extend(_bench_rows(rec, source=os.path.basename(path)))
    return rows


def _backfill_results_tables(repo: str) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(
            os.path.join(repo, "benchmarks", "results_r0*.json"))):
        try:
            with open(path) as fh:
                table = json.load(fh)
        except Exception:  # noqa: BLE001
            continue
        if not isinstance(table, dict):
            continue
        src = os.path.basename(path)
        for label, rec in table.items():
            if not isinstance(rec, dict):
                continue
            rows.append(make_row(
                str(label), rec.get("mcells_per_s"), source=src,
                measured_at=rec.get("measured_at")
                if isinstance(rec.get("measured_at"), (int, float))
                else None,
                ms_per_step=rec.get("ms_per_step"),
                suspect=bool(rec.get("suspect")),
                error=rec.get("error"),
                backend=rec.get("backend"),
                grid=rec.get("grid"), dtype=rec.get("dtype"),
                kind=rec.get("compute"),
                builder_rev=rec.get("builder_rev")
                if isinstance(rec.get("builder_rev"), int) else None))
    return rows


def backfill(repo: Optional[str] = None,
             ledger_path: Optional[str] = None) -> Dict[str, int]:
    """One-shot historical ingest: BENCH_r0*.json + results_r0*.json.

    Idempotent (append_rows dedupes), so running it every round is
    safe.  Returns ``{"found", "appended", "quarantined"}``.
    """
    repo = repo or _REPO
    rows = _backfill_bench_files(repo) + _backfill_results_tables(repo)
    appended = append_rows(rows, ledger_path)
    return {"found": len(rows), "appended": appended,
            "quarantined": sum(1 for r in rows
                               if r["status"] == "quarantined")}


def ingest_results(out_path: str,
                   ledger_path: Optional[str] = None) -> int:
    """measure.py's auto-update hook: ingest its results table."""
    try:
        with open(out_path) as fh:
            table = json.load(fh)
    except Exception:  # noqa: BLE001 — a missing table adds nothing
        return 0
    if not isinstance(table, dict):
        return 0
    src = os.path.basename(out_path)
    rows = []
    for label, rec in table.items():
        if not isinstance(rec, dict):
            continue
        rows.append(make_row(
            str(label), rec.get("mcells_per_s"), source=src,
            measured_at=rec.get("measured_at")
            if isinstance(rec.get("measured_at"), (int, float)) else None,
            ms_per_step=rec.get("ms_per_step"),
            suspect=bool(rec.get("suspect")), error=rec.get("error"),
            backend=rec.get("backend"), grid=rec.get("grid"),
            dtype=rec.get("dtype"), kind=rec.get("compute"),
            builder_rev=rec.get("builder_rev")
            if isinstance(rec.get("builder_rev"), int) else None,
            # the supervised-retry trail: a value measured after a
            # restart carries its attempt count into the ledger row
            detail={"restart_attempts": rec["restart_attempts"]}
            if rec.get("restart_attempts") else None))
    return append_rows(rows, ledger_path)


# ----------------------------------------------------------- baselines

def _best_order(row: Dict[str, Any]) -> Tuple[Any, ...]:
    """Total order for best_known: value, then measured_at, then the
    full key identity and source.  The trailing components never
    change WHICH measurement wins on merit — they only make ties
    impossible, so the winner is a pure function of the row SET and
    repeated policy resolution over the same ledger can never flip its
    decision with row order (the auto-policy determinism contract)."""
    return (row["value"], row.get("measured_at") or 0,
            row.get("key_id") or key_id(row["key"]),
            str(row.get("source") or ""))


def best_known(rows: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Best ok value per (label, backend), with full row provenance.

    Quarantined rows are structurally excluded — the function reads
    ``status`` only, so no stale/0.0/wedged record can ever surface as
    a baseline (the acceptance criterion).  Ties are broken by the
    total order of :func:`_best_order`, never by file position.
    """
    best: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        if r.get("status") != "ok":
            continue
        bk = baseline_key(r)
        cur = best.get(bk)
        if cur is None or _best_order(r) > _best_order(cur):
            best[bk] = r
    return best


# ----------------------------------------------------------------- CLI

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mpi_cuda_process_tpu.obs.ledger",
        description=__doc__.split("\n")[0])
    ap.add_argument("--ledger", default=None,
                    help=f"ledger path (default {default_ledger_path()})")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("backfill", help="one-shot historical ingest of "
                                    "BENCH_r0*.json + results_r0*.json "
                                    "(idempotent)")
    p_in = sub.add_parser("ingest", help="ingest one telemetry JSONL")
    p_in.add_argument("log")
    sub.add_parser("best", help="print best-known-value-with-provenance "
                                "per label x backend")
    a = ap.parse_args(argv)
    path = a.ledger or default_ledger_path()
    if a.cmd == "backfill":
        out = backfill(ledger_path=path)
        print(f"ledger backfill: {out['found']} rows found, "
              f"{out['appended']} appended "
              f"({out['quarantined']} quarantined) -> {path}")
        return 0
    if a.cmd == "ingest":
        n = ingest_log(a.log, path)
        print(f"ledger ingest: {n} rows appended from {a.log} -> {path}")
        return 0
    rows = read_rows(path)
    best = best_known(rows)
    quarantined = sum(1 for r in rows if r["status"] == "quarantined")
    print(f"# {path}: {len(rows)} rows ({quarantined} quarantined), "
          f"{len(best)} baselines")
    for bk in sorted(best):
        r = best[bk]
        print(f"{bk:60s} {r['value']:>12} {r['unit']:9s} "
              f"src={r['source']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
