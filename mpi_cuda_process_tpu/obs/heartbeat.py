"""Stall-detecting heartbeat: the tunnel-watch hacks, promoted.

Three rounds of zero scoreboards (BENCH_r03-r05) were diagnosed after
the fact with ad-hoc scripts — ``scripts/diagnose_tunnel.py``'s probe
ladder and ``benchmarks/watch_tunnel.sh``'s polling loop.  This module
makes the same discipline part of the framework: a daemon thread
watches a progress signal (a :class:`~..obs.runtime.RuntimeRecorder`'s
``last_progress`` or any monotonic-time callable) and, when no progress
lands for ``stall_after_s``, writes a STALLED verdict event to the
trace; it then runs a BOUNDED subprocess probe of the backend to
escalate:

* probe says the backend answers  → the stall is in-process (a slow
  compile, a host hang): verdict stays **STALLED** with the backend
  state in the detail;
* probe hangs                     → **WEDGED** (the diagnose_tunnel
  failure class: even trivial ops hang);
* probe env is broken / no TPU    → ENVIRONMENT / NO_TPU detail.

One verdict per stall episode (no event spam); progress landing again
emits RECOVERED and re-arms.  Every probe is a fresh subprocess with a
hard timeout, so the heartbeat itself can never hang the run it
watches.  ``diagnose_ladder`` delegates to scripts/diagnose_tunnel.py's
full five-probe ladder when that file is present (one implementation of
the layer classification, not two).
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, Optional, Union

from ..resilience import faults

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Progress source: an object exposing ``last_progress`` (monotonic
# seconds, RuntimeRecorder) or a zero-arg callable returning the same.
ProgressSource = Union[Callable[[], float], Any]


def _last_progress(source: ProgressSource) -> float:
    if callable(source):
        return float(source())
    return float(source.last_progress)


def _run_code(code: str, timeout_s: float,
              env_extra: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Run a python snippet in a fresh subprocess with a hard timeout."""
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    t0 = time.monotonic()
    try:
        p = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=_REPO)
        return {"ok": p.returncode == 0 and "OK" in p.stdout,
                "hang": False, "rc": p.returncode,
                "stdout": p.stdout.strip()[-200:],
                "wall_s": round(time.monotonic() - t0, 2)}
    except subprocess.TimeoutExpired:
        return {"ok": False, "hang": True,
                "wall_s": round(time.monotonic() - t0, 2)}
    except Exception as e:  # noqa: BLE001 — a probe must not crash
        return {"ok": False, "hang": False,
                "error": f"{type(e).__name__}: {e}",
                "wall_s": round(time.monotonic() - t0, 2)}


def probe_verdict(timeout_s: float = 60.0) -> Dict[str, Any]:
    """Quick two-probe backend verdict (bounded by ~2x ``timeout_s``).

    The verdict vocabulary matches scripts/diagnose_tunnel.py where the
    layers coincide: ENVIRONMENT (this machine's python env is broken),
    NO_TPU (backend answers but is not a TPU), WEDGED (a trivial device
    op hangs — the tunnel failure class), BACKEND_HEALTHY (a TPU
    answered within budget).  Never raises.
    """
    cpu = _run_code(
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import jax.numpy as jnp; print('OK', int(jnp.add(1, 1)))",
        timeout_s)
    if not cpu.get("ok"):
        return {"verdict": "ENVIRONMENT",
                "detail": "the CPU control probe failed — this "
                          "machine/python env is broken independent of "
                          "any backend", "probes": [cpu]}
    dev = _run_code(
        "import jax, jax.numpy as jnp; "
        "print('OK', jax.default_backend(), int(jnp.add(1, 1)))",
        timeout_s)
    if dev.get("hang"):
        return {"verdict": "WEDGED",
                "detail": "a trivial device op hung past the probe "
                          "budget — the backend (axon tunnel) is wedged",
                "probes": [cpu, dev]}
    if dev.get("ok") and "tpu" not in dev.get("stdout", ""):
        return {"verdict": "NO_TPU",
                "detail": "backend answers but is not a TPU — nothing "
                          "to wedge; the stall is in-process",
                "probes": [cpu, dev]}
    if dev.get("ok"):
        return {"verdict": "BACKEND_HEALTHY",
                "detail": "a TPU answered within budget — the stall is "
                          "in-process (slow compile or host hang)",
                "probes": [cpu, dev]}
    return {"verdict": "INCONCLUSIVE",
            "detail": "device probe failed without hanging — read the "
                      "probe records", "probes": [cpu, dev]}


def diagnose_ladder(timeout_s: float = 120.0) -> Dict[str, Any]:
    """Full layer diagnosis via scripts/diagnose_tunnel.py when present.

    Runs its five-probe ladder (cpu_control / discovery /
    discovery_clean / execute / compile) with the same early-stop rules
    and returns ``{"verdict", "detail", "probes"}`` in its H1/H2/H3
    vocabulary.  Falls back to :func:`probe_verdict` on a checkout
    without the script — one classification, not a fork of it.
    """
    path = os.path.join(_REPO, "scripts", "diagnose_tunnel.py")
    try:
        spec = importlib.util.spec_from_file_location("_diag_tunnel", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    except Exception:  # noqa: BLE001
        return probe_verdict(timeout_s)
    results = []
    for name, code, clean, cpu in mod._PROBES:
        rec = mod._run_probe(name, code, clean, cpu, timeout_s)
        results.append(rec)
        if rec.get("hang") and name != "discovery":
            break
        if name == "cpu_control" and not rec.get("ok"):
            break
    verdict, detail = mod._classify(results)
    return {"verdict": verdict, "detail": detail, "probes": results}


class Heartbeat(threading.Thread):
    """Watch a progress source; write STALLED/WEDGED verdicts to a trace.

    ``probe`` (a zero-arg callable returning a ``probe_verdict``-shaped
    dict) runs ONCE per stall episode to escalate; tests inject a stub,
    production uses :func:`probe_verdict`.  ``trace`` receives
    ``heartbeat`` events; ``last_verdict`` always holds the newest one.
    """

    def __init__(self, source: ProgressSource, trace=None,
                 stall_after_s: float = 300.0,
                 poll_s: Optional[float] = None,
                 probe: Optional[Callable[[], Dict[str, Any]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        super().__init__(daemon=True, name="obs-heartbeat")
        self.source = source
        self.trace = trace
        self.stall_after_s = float(stall_after_s)
        self.poll_s = poll_s if poll_s is not None else \
            min(30.0, max(0.05, self.stall_after_s / 4.0))
        self.probe = probe_verdict if probe is None else probe
        self.clock = clock
        self.last_verdict: Dict[str, Any] = {"verdict": "ALIVE",
                                             "detail": "no stall observed"}
        self._stop_evt = threading.Event()
        self._stalled_episode = False

    def _emit(self, verdict: str, detail: str, **payload: Any) -> None:
        self.last_verdict = {"verdict": verdict, "detail": detail, **payload}
        if self.trace is not None:
            try:
                self.trace.event("heartbeat", verdict=verdict,
                                 detail=detail, **payload)
            except Exception:  # noqa: BLE001 — observer, never load-bearing
                pass

    def run(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            idle = self.clock() - _last_progress(self.source)
            if idle < self.stall_after_s:
                if self._stalled_episode:
                    self._stalled_episode = False
                    self._emit("RECOVERED",
                               "progress resumed after a stall",
                               idle_s=round(idle, 2))
                continue
            if self._stalled_episode:
                continue  # one verdict per episode, no event spam
            self._stalled_episode = True
            self._emit("STALLED",
                       f"no progress for {idle:.1f}s "
                       f"(threshold {self.stall_after_s:.1f}s); probing "
                       "the backend", idle_s=round(idle, 2))
            try:
                # Fault point (resilience/faults.py): heartbeat:wedge
                # replaces the subprocess probe with a deterministic
                # WEDGED verdict — the supervisor's kill-on-verdict path
                # gets a reproducible CPU trigger.
                probe = faults.injected_heartbeat_verdict() or self.probe()
            except Exception as e:  # noqa: BLE001
                probe = {"verdict": "INCONCLUSIVE",
                         "detail": f"probe raised {type(e).__name__}: {e}"}
            if probe.get("verdict") == "WEDGED":
                self._emit("WEDGED", probe.get("detail", ""),
                           idle_s=round(self.clock()
                                        - _last_progress(self.source), 2),
                           probe=probe)
            else:
                self._emit("STALLED",
                           "backend probe: "
                           f"{probe.get('verdict')} — "
                           f"{probe.get('detail', '')}",
                           probe=probe)

    def stop(self, join_timeout_s: float = 5.0,
             final_verdict: str = "SUPERVISOR_KILL") -> None:
        """Stop the watcher.  NEVER raises — the supervisor kill path
        runs this while tearing down a wedged run, where a secondary
        exception would mask the wedge it is reporting.

        An open stall episode is CLOSED with a final ``final_verdict``
        event (default ``SUPERVISOR_KILL``: the run was stopped from
        outside while stalled) instead of being left dangling — a trace
        ending mid-episode is indistinguishable from a writer that died.
        The thread cannot outlive a closed trace: ``_emit`` swallows
        writer errors and ``TraceWriter`` drops post-close writes, so
        even a join timeout (a probe still in flight) leaves nothing
        that can raise into the closing run.
        """
        try:
            if self._stalled_episode:
                self._stalled_episode = False
                self._emit(final_verdict,
                           "watcher stopped while a stall episode was "
                           "open (supervisor kill / teardown path)")
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
        try:
            self._stop_evt.set()
            if self.is_alive():
                self.join(join_timeout_s)
        except Exception:  # noqa: BLE001
            pass
