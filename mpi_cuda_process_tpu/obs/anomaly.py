"""Run doctor: continuous performance-anomaly detection at chunk cadence.

``--health`` (obs/health.py) watches the *numerics*; nothing watched the
*performance*: a run that silently dropped to 0.3x its own steady-state
throughput — a straggler host, a recompile storm, memory creep, a
co-tenant squeeze — ran to completion and only the next ``perf_gate``
replay noticed.  :class:`AnomalyMonitor` is the live half: it consumes
the chunk records :class:`~.runtime.RuntimeRecorder` already builds
(``recorder.anomaly = monitor`` — one hook covers the measured CLI
path, coupled groups, and every serving job) and never touches the
jitted step.  Same zero-ops discipline as ``--health``: the detector is
host Python at chunk boundaries only; the step jaxpr is byte-identical
with the detector on vs off (pinned by tests/test_anomaly.py).

Findings, each a structured ``anomaly`` event in the existing telemetry
schema (``anomaly`` = kind, ``severity``, ``evidence``, ``suspect``):

* ``throughput_collapse`` — ms/step above ``collapse_ratio`` x the
  run's OWN rolling steady-state baseline (chunk 0 and recompiled
  chunks never baseline; flagged chunks don't poison the baseline
  either, so one slow chunk can't normalize the next).  When the
  ledger's ``best_known`` row for this label is available the evidence
  carries the roofline-gap ratio too — but the trigger is always the
  run's own baseline, so a stale ledger can't fabricate findings.
* ``roofline_gap`` — sustained throughput below ``roofline_band`` x
  the ledger's ``best_known`` for this exact label|backend key, for two
  consecutive steady chunks (one-shot per episode).
* ``recompile`` — a backend compile landed inside a chunk AFTER chunk
  0 (shape drift / cache invalidation in the hot loop).
* ``memory_creep`` — ``bytes_in_use`` strictly increasing across
  ``creep_chunks`` consecutive chunks by more than ``creep_frac``
  total (a leaked buffer, a growing donation miss).
* ``variance_growth`` — the recent window's coefficient of variation
  exceeds both an absolute floor and 3x the run's early steady CV
  (co-tenant squeeze, thermal throttling: jitter without a single
  collapse).
* ``boundary_stall`` — the wall-clock between consecutive chunk
  records minus the newer chunk's own ``wall_s``: host-side time the
  chunk timer never sees (a stalled exchange teardown, a slow
  checkpoint, an injected ``sleep`` fault — ``resilience/faults.py``
  fires OUTSIDE the timed window, exactly like real boundary trouble).
  Flagged when the stall exceeds both ``min_stall_s`` and the chunk's
  own device time; the first ``baseline_chunks`` boundaries are warmup
  (compile and allocator setup legitimately land there).
* ``straggler`` — from per-member timings (coupled groups via
  :meth:`observe_members`, per-host rows via
  :func:`attribute_straggler`): the slowest (host | group | member)
  named with its lag ratio.  Group lag is measured against each
  member's OWN baseline — heterogeneous groups legitimately differ in
  absolute speed, so "slower than your peers" would false-positive by
  design; "slower than you used to be, while your peers are not" is
  the straggler signal.

Every threshold is deliberately conservative: the contract (pinned by
test) is ZERO findings on a clean constant-throughput log.  A finding
makes the run's verdict DEGRADED (obs/metrics.py) — which warns by
default and never kills anything (``--degraded-action``): a slow run
is not a dead run.

Pure host-side stdlib + the trace writer; no jax import.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Dict, List, Optional

VERDICT_DEGRADED = "DEGRADED"

# severity vocabulary (evidence-bearing, not load-bearing: nothing
# kills a run on severity alone — the supervisor policy decides)
SEV_WARN = "warn"
SEV_CRITICAL = "critical"


def _median(vals: List[float]) -> float:
    return float(statistics.median(vals))


class AnomalyMonitor:
    """Rolling steady-state baseline + conservative anomaly flags.

    ``trace``/``spans`` mirror :class:`~.health.HealthMonitor`: findings
    are emitted as ``anomaly`` trace events and the root span carries an
    ``anomalies`` count; both writes are swallowed — the doctor must
    never kill the patient.  ``ident`` names this process (e.g.
    ``"hostA|p0"``) as the default suspect for single-process findings.
    ``cells`` (grid cell count) + ``best_known`` (a ledger row or plain
    Mcells/s float) enable the roofline-gap band; absent, only the
    own-baseline detectors run.
    """

    def __init__(self, trace=None, spans=None, ident: Optional[str] = None,
                 cells: Optional[int] = None, best_known=None,
                 collapse_ratio: float = 3.0, min_excess_s: float = 0.05,
                 baseline_chunks: int = 3, roofline_band: float = 0.25,
                 creep_chunks: int = 4, creep_frac: float = 0.20,
                 variance_window: int = 8, variance_floor: float = 0.35,
                 straggler_ratio: float = 1.5, min_stall_s: float = 0.3,
                 max_findings: int = 64,
                 clock=time.perf_counter):
        self.trace = trace
        self.spans = spans
        self.ident = ident or "local|p0"
        self.cells = int(cells) if cells else None
        if isinstance(best_known, dict):
            self.best_value = float(best_known.get("value") or 0) or None
            self.best_source = best_known.get("source")
        else:
            self.best_value = float(best_known) if best_known else None
            self.best_source = None
        self.collapse_ratio = float(collapse_ratio)
        self.min_excess_s = float(min_excess_s)
        self.baseline_chunks = max(1, int(baseline_chunks))
        self.roofline_band = float(roofline_band)
        self.creep_chunks = max(2, int(creep_chunks))
        self.creep_frac = float(creep_frac)
        self.variance_window = max(4, int(variance_window))
        self.variance_floor = float(variance_floor)
        self.straggler_ratio = float(straggler_ratio)
        self.min_stall_s = float(min_stall_s)
        self.max_findings = int(max_findings)
        self._clock = clock

        self.findings: List[Dict[str, Any]] = []
        self.counts: Dict[str, int] = {}
        self._steady: List[float] = []     # ms/step, baseline-eligible
        self._mem: List[int] = []          # bytes_in_use per chunk
        self._steps_done = 0
        self._below_band = 0
        self._creep_emitted = False
        self._variance_emitted = False
        self._member_base: Dict[str, List[float]] = {}
        self._straggler_named: set = set()
        self._last_boundary: Optional[float] = None
        self._records_seen = 0

    # ------------------------------------------------------------ core

    @property
    def count(self) -> int:
        return sum(self.counts.values())

    def baseline_ms(self) -> Optional[float]:
        """Current rolling steady-state baseline (median ms/step)."""
        if len(self._steady) < self.baseline_chunks:
            return None
        return _median(self._steady[-32:])

    def observe_chunk(self, rec: Dict[str, Any]) -> List[Dict[str, Any]]:
        """One finished chunk record (RuntimeRecorder's exact shape).

        Called host-side at chunk boundaries only — the recorder hooks
        it right after appending the record.  Returns the new findings
        (already emitted); swallows nothing itself because its inputs
        are plain dicts, but the trace/span writes are guarded.
        """
        chunk = rec.get("chunk")
        ms = rec.get("ms_per_step")
        if not isinstance(chunk, int) or not isinstance(ms, (int, float)):
            return []
        wall = float(rec.get("wall_s") or 0.0)
        steps = int(rec.get("steps") or 0)
        self._steps_done += steps
        found: List[Dict[str, Any]] = []

        # boundary stall: host time BETWEEN chunk records that the
        # chunk timer never measured (the run loops fence only the
        # device work; checkpoint saves, injected faults, a wedged
        # exchange teardown all land in this gap).  Both thresholds
        # must clear — the stall dwarfs the chunk's own device time AND
        # a real absolute floor — so clean-run boundary overhead
        # (logging, health reductions: milliseconds) never flags.  The
        # first ``baseline_chunks`` boundaries are warmup, same as the
        # throughput baseline: early boundaries legitimately carry
        # compile and allocator setup the steady loop never repeats.
        now = self._clock()
        prev, self._last_boundary = self._last_boundary, now
        self._records_seen += 1
        if prev is not None and self._records_seen > self.baseline_chunks + 1:
            stall = (now - prev) - wall
            if stall > self.min_stall_s and stall > wall:
                found.append(self._finding(
                    "boundary_stall", SEV_WARN, chunk,
                    {"chunk": chunk, "stall_s": round(stall, 4),
                     "wall_s": round(wall, 4),
                     "detail": "host-side stall between chunk records "
                               "(outside the fenced device window)"}))

        recompiled = bool(rec.get("recompiled"))
        if recompiled and chunk > 0:
            found.append(self._finding(
                "recompile", SEV_WARN, chunk,
                {"chunk": chunk, "ms_per_step": ms,
                 "detail": "backend compile inside a post-warmup chunk "
                           "(shape drift or cache invalidation)"}))

        mem = rec.get("memory") or {}
        if isinstance(mem.get("bytes_in_use"), int):
            self._mem.append(mem["bytes_in_use"])
            creep = self._check_creep(chunk)
            if creep is not None:
                found.append(creep)

        if chunk > 0:
            baseline = self.baseline_ms()
            collapsed = False
            if baseline is not None and ms > self.collapse_ratio * baseline \
                    and (ms - baseline) * steps / 1e3 > self.min_excess_s:
                collapsed = True
                ev: Dict[str, Any] = {
                    "chunk": chunk, "ms_per_step": ms,
                    "baseline_ms_per_step": round(baseline, 6),
                    "ratio": round(ms / baseline, 2),
                }
                tp = self._mcells(rec, wall, steps)
                if tp is not None and self.best_value:
                    ev["mcells_per_s"] = round(tp, 3)
                    ev["vs_best_known"] = round(tp / self.best_value, 4)
                found.append(self._finding(
                    "throughput_collapse", SEV_CRITICAL, chunk, ev))
            gap = None if collapsed else self._check_roofline(
                rec, chunk, wall, steps)
            if gap is not None:
                found.append(gap)
            if not recompiled and not collapsed:
                self._steady.append(float(ms))
                var = self._check_variance(chunk)
                if var is not None:
                    found.append(var)

        for f in found:
            self._emit(f)
        return found

    def observe_members(self, step: Optional[int],
                        entries: List[Dict[str, Any]],
                        kind: str = "group") -> Optional[Dict[str, Any]]:
        """Per-member timings at one boundary: name the straggler.

        ``entries`` = ``[{"name": ..., "ms_per_step": ...}, ...]`` (one
        per coupled group / ensemble member / host).  Lag is each
        member's current time over its OWN early baseline (first
        ``baseline_chunks`` samples), so heterogeneous members at
        different absolute speeds never read as stragglers; a member
        must be slower than it used to be while its peers are not
        (worst lag >= ``straggler_ratio`` AND >= 2x the peers' median
        lag).  Named at most once per member per run.
        """
        lags: List[Any] = []
        for e in entries:
            name = str(e.get("name"))
            ms = e.get("ms_per_step")
            if not isinstance(ms, (int, float)) or ms <= 0:
                continue
            base = self._member_base.setdefault(name, [])
            if len(base) < self.baseline_chunks:
                base.append(float(ms))
                continue
            lags.append((name, float(ms) / _median(base), float(ms)))
        if len(lags) < 2:
            return None
        lags.sort(key=lambda x: x[1])
        name, lag, ms = lags[-1]
        peers = [x[1] for x in lags[:-1]]
        if lag < self.straggler_ratio or lag < 2.0 * _median(peers):
            return None
        if name in self._straggler_named:
            return None
        self._straggler_named.add(name)
        f = self._finding(
            "straggler", SEV_WARN, None,
            {"step": step, "lag_ratio": round(lag, 2),
             "ms_per_step": round(ms, 6),
             "peers_median_lag": round(_median(peers), 2)},
            suspect={"kind": kind, "name": name,
                     "lag_ratio": round(lag, 2)})
        if step is not None:
            f["step"] = int(step)
        self._emit(f)
        return f

    # ------------------------------------------------------- detectors

    def _mcells(self, rec, wall: float, steps: int) -> Optional[float]:
        if not self.cells or wall <= 0 or steps <= 0:
            return None
        members = max(1, int(rec.get("members") or 0) or 1)
        return self.cells * steps * members / (wall * 1e6)

    def _check_roofline(self, rec, chunk: int, wall: float,
                        steps: int) -> Optional[Dict[str, Any]]:
        tp = self._mcells(rec, wall, steps)
        if tp is None or not self.best_value:
            return None
        if tp < self.roofline_band * self.best_value:
            self._below_band += 1
        else:
            self._below_band = 0
            return None
        if self._below_band != 2:  # one-shot per below-band episode
            return None
        return self._finding(
            "roofline_gap", SEV_WARN, chunk,
            {"chunk": chunk, "mcells_per_s": round(tp, 3),
             "best_known_mcells_per_s": self.best_value,
             "vs_best_known": round(tp / self.best_value, 4),
             "band": self.roofline_band,
             "best_known_source": self.best_source})

    def _check_creep(self, chunk: int) -> Optional[Dict[str, Any]]:
        if self._creep_emitted:
            return None
        win = self._mem[-(self.creep_chunks + 1):]
        if len(win) < self.creep_chunks + 1 or win[0] <= 0:
            return None
        if any(later <= earlier for earlier, later in zip(win, win[1:])):
            return None  # not strictly increasing throughout
        growth = (win[-1] - win[0]) / win[0]
        if growth <= self.creep_frac:
            return None
        self._creep_emitted = True
        return self._finding(
            "memory_creep", SEV_WARN, chunk,
            {"chunk": chunk, "chunks": len(win) - 1,
             "bytes_first": win[0], "bytes_last": win[-1],
             "growth": round(growth, 4)})

    def _check_variance(self, chunk: int) -> Optional[Dict[str, Any]]:
        if self._variance_emitted:
            return None
        w = self.variance_window
        if len(self._steady) < 2 * w:
            return None

        def _cv(vals: List[float]) -> float:
            m = statistics.fmean(vals)
            return statistics.pstdev(vals) / m if m > 0 else 0.0

        early = _cv(self._steady[:w])
        recent = _cv(self._steady[-w:])
        if recent <= max(self.variance_floor, 3.0 * early):
            return None
        self._variance_emitted = True
        return self._finding(
            "variance_growth", SEV_WARN, chunk,
            {"chunk": chunk, "cv_recent": round(recent, 4),
             "cv_early": round(early, 4), "window": w})

    # ------------------------------------------------------- emission

    def _finding(self, kind: str, severity: str, chunk: Optional[int],
                 evidence: Dict[str, Any],
                 suspect: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        f: Dict[str, Any] = {
            "anomaly": kind, "severity": severity,
            "evidence": {k: v for k, v in evidence.items() if v is not None},
            "suspect": suspect or {"kind": "host", "name": self.ident},
        }
        if chunk is not None:
            f["chunk"] = chunk
            f["step"] = self._steps_done
        return f

    def _emit(self, finding: Dict[str, Any]) -> None:
        if len(self.findings) < self.max_findings:
            self.findings.append(finding)
        k = finding["anomaly"]
        self.counts[k] = self.counts.get(k, 0) + 1
        if self.spans is not None:
            try:
                self.spans.root_attrs["anomalies"] = self.count
            except Exception:  # noqa: BLE001 — never load-bearing
                pass
        if self.trace is not None:
            try:
                self.trace.event("anomaly", **finding)
            except Exception:  # noqa: BLE001 — never load-bearing
                pass


def attribute_straggler(entries: List[Dict[str, Any]],
                        ratio: float = 1.5,
                        kind: str = "host") -> Optional[Dict[str, Any]]:
    """Peer-relative straggler among HOMOGENEOUS members (SPMD hosts).

    ``entries`` = ``[{"name": ..., "slowness": ...}, ...]`` where
    slowness is any higher-is-slower figure (ms/step, or 1/throughput).
    Valid only when every member runs the same program — the aggregate
    view across per-host rows, where peer comparison IS the baseline.
    Returns ``{"kind", "name", "lag_ratio"}`` or None.
    """
    vals = [(str(e.get("name")), float(e["slowness"])) for e in entries
            if isinstance(e.get("slowness"), (int, float))
            and e["slowness"] > 0]
    if len(vals) < 2:
        return None
    vals.sort(key=lambda x: x[1])
    peers_median = _median([v for _, v in vals[:-1]])
    name, worst = vals[-1]
    if peers_median <= 0 or worst / peers_median < ratio:
        return None
    return {"kind": kind, "name": name,
            "lag_ratio": round(worst / peers_median, 2)}
