"""Device-trace attribution: what the device ACTUALLY did with a chunk.

Everything else in obs/ predicts or book-keeps: costmodel counts what a
step *should* cost, runtime.py times what the host *saw*.  Whether
``--overlap``/``--pipeline`` really hide the exchange was, until this
module, only a roofline prediction.  This module measures it:

* :class:`ChunkProfiler` — a ``jax.profiler`` session wrapper scoped to
  ONE chunk (by default the first steady-state chunk, after the
  compile+warmup chunk), attached to the
  :class:`~.runtime.RuntimeRecorder` the driver already calls at chunk
  boundaries.  With ``--profile`` off, nothing here is constructed and
  the jitted step jaxpr stays byte-identical (the telemetry invariant,
  extended by tests/test_obs_profile.py); with it on, ``start_trace``/
  ``stop_trace`` run strictly at chunk boundaries — never inside the
  scan.
* a parser for the emitted Chrome-trace events
  (:func:`load_trace_events`) and an attribution pass
  (:func:`attribute_events`) that buckets device time into
  interior-compute vs ppermute/collective (the exchange) and computes
  the **measured overlap efficiency**::

      overlap_efficiency = 1 - exposed_comm / total_comm

  where exposed comm is exchange time NOT covered by concurrent
  compute (interval arithmetic over the device lanes).  Recorded in
  the telemetry log as a ``profile`` event next to costmodel's
  ``overlapped`` vs ``serial`` roofline predictions, so predicted-vs-
  measured hiding is one line in ``scripts/obs_report.py``.

Honesty rule: on CPU (the profiler emits host lanes only) or when the
trace yields no device events, the record says ``attribution:
unavailable`` with the reason — never fabricated zeros.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Event-name classification for the exchange bucket.  ppermute lowers to
# collective-permute on TPU; the rest cover the collectives any future
# stepper might issue.  Lowercased substring match.
_COMM_MARKERS = (
    "ppermute", "collective-permute", "collective_permute",
    "all-reduce", "all_reduce", "all-gather", "all_gather",
    "all-to-all", "all_to_all", "reduce-scatter", "reduce_scatter",
    "send", "recv",
)


def is_comm_event(name: str) -> bool:
    low = str(name).lower()
    return any(m in low for m in _COMM_MARKERS)


# ------------------------------------------------------------ trace IO

def find_trace_files(profile_dir: str) -> List[str]:
    """Chrome-trace files under a ``jax.profiler`` output dir, oldest
    first (the profiler writes ``plugins/profile/<run>/<host>.trace
    .json.gz``; plain ``.trace.json`` accepted for synthetic fixtures)."""
    pats = (os.path.join(profile_dir, "**", "*.trace.json.gz"),
            os.path.join(profile_dir, "**", "*.trace.json"))
    found: List[str] = []
    for pat in pats:
        found.extend(glob.glob(pat, recursive=True))
    return sorted(set(found), key=lambda p: (os.path.getmtime(p), p))


def load_trace_events(profile_dir: str) -> List[Dict[str, Any]]:
    """``traceEvents`` of the NEWEST trace file under ``profile_dir``.

    Returns ``[]`` when no trace file exists (profiler never ran, or a
    jax version that emits only ``.xplane.pb``) — the caller degrades
    to ``attribution: unavailable`` rather than guessing.
    """
    files = find_trace_files(profile_dir)
    if not files:
        return []
    path = files[-1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:  # type: ignore[operator]
        doc = json.load(fh)
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    return events if isinstance(events, list) else []


# -------------------------------------------------- interval arithmetic

def _merge(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    """Sorted union of half-open intervals."""
    out: List[Tuple[float, float]] = []
    for s, e in sorted(intervals):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _total(merged: Sequence[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in merged)


def _intersection_total(a: Sequence[Tuple[float, float]],
                        b: Sequence[Tuple[float, float]]) -> float:
    """Total overlap between two MERGED interval lists (two-pointer)."""
    i = j = 0
    tot = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            tot += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return tot


# ---------------------------------------------------------- attribution

def device_pids(events: Sequence[Dict[str, Any]]) -> List[int]:
    """pids whose ``process_name`` marks a device lane group.

    The TF profiler names processes ``/device:TPU:0`` (device) vs
    ``/host:CPU`` (host python/runtime threads).  Host lanes carry
    python frames and must never be attributed as device compute.
    """
    pids = []
    for e in events:
        if e.get("ph") != "M" or e.get("name") != "process_name":
            continue
        name = str((e.get("args") or {}).get("name", ""))
        _, sep, dev = name.partition("/device:")
        if sep and not dev.upper().startswith("CPU"):
            pids.append(e.get("pid"))
    return sorted({p for p in pids if p is not None})


def attribute_events(events: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Bucket device-lane time: interior compute / exchange / exposed.

    Complete events (``ph == "X"``) on device pids only.  ``comm`` is
    the union of collective-op intervals, ``compute`` the union of
    everything else on the device lanes; ``exposed_comm`` is comm time
    with no concurrent compute — the part of the exchange the schedule
    failed to hide.  All durations in trace microseconds.
    """
    pids = set(device_pids(events))
    if not pids:
        return {"attribution": "unavailable",
                "reason": "no device lanes in the trace (CPU backend, or "
                          "a profiler run that captured host events only)"}
    comm: List[Tuple[float, float]] = []
    compute: List[Tuple[float, float]] = []
    n = 0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in pids:
            continue
        try:
            s = float(e["ts"])
            d = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        if d <= 0:
            continue
        n += 1
        (comm if is_comm_event(e.get("name", "")) else compute).append(
            (s, s + d))
    if n == 0:
        return {"attribution": "unavailable",
                "reason": "device lanes present but carry no complete "
                          "events"}
    comm_m, compute_m = _merge(comm), _merge(compute)
    comm_us = _total(comm_m)
    compute_us = _total(compute_m)
    hidden_us = _intersection_total(comm_m, compute_m)
    exposed_us = comm_us - hidden_us
    busy_us = _total(_merge(list(comm_m) + list(compute_m)))
    out: Dict[str, Any] = {
        "attribution": "ok",
        "n_device_events": n,
        "device_busy_us": round(busy_us, 3),
        "compute_us": round(compute_us, 3),
        "comm_us": round(comm_us, 3),
        "exposed_comm_us": round(exposed_us, 3),
        # 1 - exposed/total: 1.0 = exchange fully hidden behind compute,
        # 0.0 = fully serial.  None when the trace carries no exchange
        # at all (an unsharded run) — "no comm" is not "perfect hiding".
        "overlap_efficiency": (round(1.0 - exposed_us / comm_us, 4)
                               if comm_us > 0 else None),
    }
    return out


def attribution_record(profile_dir: str,
                       profiled_chunk: Optional[int] = None,
                       error: Optional[str] = None) -> Dict[str, Any]:
    """The ``profile`` telemetry event payload for a finished run."""
    rec: Dict[str, Any] = {
        "profile_dir": os.path.abspath(profile_dir),
        "profiled_chunk": profiled_chunk,
    }
    if error:
        rec.update(attribution="unavailable",
                   reason=f"profiler error: {error}")
        return rec
    if profiled_chunk is None:
        rec.update(attribution="unavailable",
                   reason="no chunk reached the profile scope (run ended "
                          "before the target chunk)")
        return rec
    try:
        events = load_trace_events(profile_dir)
    except Exception as e:  # noqa: BLE001 — a corrupt trace must not
        rec.update(attribution="unavailable",  # kill the run epilogue
                   reason=f"trace parse failed: {type(e).__name__}: {e}")
        return rec
    if not events:
        rec.update(attribution="unavailable",
                   reason="no .trace.json emitted under the profile dir")
        return rec
    rec.update(attribute_events(events))
    return rec


def format_attribution(rec: Dict[str, Any]) -> str:
    """One human line for logs/obs_report."""
    if rec.get("attribution") != "ok":
        return f"attribution unavailable ({rec.get('reason')})"
    eff = rec.get("overlap_efficiency")
    parts = [
        f"compute {rec['compute_us'] / 1e3:.3f} ms",
        f"comm {rec['comm_us'] / 1e3:.3f} ms",
        f"exposed {rec['exposed_comm_us'] / 1e3:.3f} ms",
    ]
    parts.append("no exchange in trace" if eff is None
                 else f"measured overlap efficiency {eff:.2%}")
    return "  ".join(parts)


# ------------------------------------------------------- chunk profiler

class ChunkProfiler:
    """Scope one ``jax.profiler`` trace to one chunk of a run.

    Attached as ``recorder.profiler``; the
    :class:`~.runtime.RuntimeRecorder` calls :meth:`begin_chunk` /
    :meth:`end_chunk` with the chunk index at the boundaries the driver
    already observes.  ``target_chunk`` defaults to 1 — the first
    chunk after compile+warmup, i.e. steady state.  One trace per run:
    after the target chunk is captured, later chunks are ignored.

    ``start``/``stop`` are injectable for tests; production uses
    ``jax.profiler.start_trace``/``stop_trace``.  A profiler failure is
    recorded in ``self.error`` and never propagates — observation must
    not kill the run it observes.
    """

    def __init__(self, outdir: str, target_chunk: int = 1,
                 start=None, stop=None):
        if start is None or stop is None:
            import jax

            start = start or jax.profiler.start_trace
            stop = stop or jax.profiler.stop_trace
        self.outdir = outdir
        self.target_chunk = int(target_chunk)
        self._start = start
        self._stop = stop
        self.active = False
        self.profiled_chunk: Optional[int] = None
        self.error: Optional[str] = None

    def begin_chunk(self, chunk_index: int) -> bool:
        """Start the trace iff this is the target chunk (once per run)."""
        if self.active or self.profiled_chunk is not None:
            return False
        if int(chunk_index) != self.target_chunk:
            return False
        try:
            os.makedirs(self.outdir, exist_ok=True)
            self._start(self.outdir)
            self.active = True
        except Exception as e:  # noqa: BLE001
            self.error = f"{type(e).__name__}: {e}"
        return self.active

    def end_chunk(self, chunk_index: int) -> bool:
        """Stop the trace if running; True iff this chunk was captured."""
        if not self.active:
            return False
        try:
            self._stop()
        except Exception as e:  # noqa: BLE001
            self.error = f"{type(e).__name__}: {e}"
        self.active = False
        self.profiled_chunk = int(chunk_index)
        return True

    def close(self) -> None:
        """Abort path: stop a still-open trace so the next run can start
        one (jax refuses nested sessions).  Idempotent."""
        if self.active:
            try:
                self._stop()
            except Exception as e:  # noqa: BLE001
                self.error = f"{type(e).__name__}: {e}"
            self.active = False
