"""JSONL event recorder + provenance-stamped run manifests.

The repo's runtime evidence used to live as one-off ``perf_counter``
pairs scattered across cli.py/bench.py and ad-hoc wedge scripts; three
consecutive zero scoreboards (BENCH_r03-r05) could not say *why* because
no run left a machine-readable trail.  This module is the trail: every
entry point (cli ``--telemetry``, bench.py, benchmarks/measure.py,
benchmarks/scaling.py) opens a trace, writes ONE manifest line — a
versioned, validated record of what ran (config, flags), on what
(backend, device kind/count), and from which code (git sha,
BUILDER_REV, jax version) — then appends events (chunk timings, static
cost counters, heartbeat verdicts, a final summary) as JSON lines.

Design constraints:

* **Zero ops in the jitted step.**  Nothing here touches jax tracing:
  events are written host-side at chunk boundaries only (pinned by
  ``tests/test_obs.py::test_telemetry_adds_zero_ops_to_jitted_step``).
* **One schema for all four tools** — the validator below is the single
  definition; ``scripts/obs_report.py --check`` and the tier-1 smoke run
  it, so a tool drifting off-schema fails the gate, not a reader three
  rounds later.
* **Thread-safe writes** (the heartbeat thread shares the writer).
* **Never load-bearing**: telemetry failures must not kill a run;
  callers wrap session setup in try/except (the writer itself only
  raises on programmer errors like an invalid manifest).
"""

from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

# Schema 2 (round 14) adds multi-host provenance — process_index /
# process_count / hostname — the fields a future aggregator needs to
# merge per-host status pages (ROADMAP item 5 prep).  The validator
# accepts BOTH revisions: new manifests are written at SCHEMA_VERSION,
# old schema-1 logs (without the host fields) still parse, and the
# "bump the reader, never the record" rule holds.
SCHEMA_VERSION = 2
ACCEPTED_SCHEMAS = (1, 2)

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# provenance keys every manifest must carry, with their required types
# (builder_rev may be None on a checkout without the campaign harness)
_PROVENANCE_TYPES = {
    "git_sha": str,
    "jax_version": str,
    "backend": str,
    "device_kind": str,
    "device_count": int,
    "framework_version": str,
}

# schema-2 additions: REQUIRED on schema-2 manifests, absent on schema-1
# (type-checked when a schema-1 writer chose to include them anyway)
_PROVENANCE_V2_TYPES = {
    "process_index": int,
    "process_count": int,
    "hostname": str,
}


def default_telemetry_dir() -> str:
    """Where tools drop event logs when no path is given.

    ``OBS_TELEMETRY_DIR`` overrides (tests point it at a tmpdir); the
    default is ``<repo>/.telemetry`` next to ``.bench_cache.json``.
    """
    return os.environ.get("OBS_TELEMETRY_DIR") or \
        os.path.join(_REPO, ".telemetry")


def _git_sha() -> str:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=_REPO,
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip()
    except Exception:  # noqa: BLE001 — provenance is best-effort
        pass
    return "unknown"


def _builder_rev() -> Optional[int]:
    """The measurement campaign's BUILDER_REV, parsed statically.

    Parsed from benchmarks/measure.py text rather than imported: the
    campaign harness is not a package, and importing it would drag its
    jax-at-module-scope setup into every manifest write.
    """
    try:
        with open(os.path.join(_REPO, "benchmarks", "measure.py")) as fh:
            m = re.search(r"^BUILDER_REV = (\d+)", fh.read(), re.M)
        return int(m.group(1)) if m else None
    except Exception:  # noqa: BLE001
        return None


def provenance() -> Dict[str, Any]:
    """The code+hardware identity block stamped into every manifest."""
    import jax

    from .. import __version__

    try:
        devs = jax.devices()
        device_kind = devs[0].device_kind
        device_count = len(devs)
    except Exception:  # noqa: BLE001 — a wedged backend must not block
        device_kind, device_count = "unknown", 1
    try:
        # the multi-host identity (schema 2): which process of how many
        # wrote this manifest — what lets an aggregator merge per-host
        # status pages instead of guessing from filenames
        process_index = int(jax.process_index())
        process_count = int(jax.process_count())
    except Exception:  # noqa: BLE001 — same wedged-backend discipline
        process_index, process_count = 0, 1
    try:
        hostname = socket.gethostname() or "unknown"
    except Exception:  # noqa: BLE001
        hostname = "unknown"
    return {
        "git_sha": _git_sha(),
        "builder_rev": _builder_rev(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "device_count": device_count,
        "process_index": process_index,
        "process_count": process_count,
        "hostname": hostname,
        "framework_version": __version__,
    }


def build_manifest(tool: str, run: Dict[str, Any],
                   **extra: Any) -> Dict[str, Any]:
    """Assemble and validate a manifest record.

    ``tool`` names the emitting entry point (cli/bench/measure/scaling);
    ``run`` is its config dict (the full RunConfig for the CLI, the
    harness arguments for the benchmark tools).  ``extra`` lands at the
    top level (e.g. ``mesh_devices``).
    """
    m: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "kind": "manifest",
        "tool": tool,
        "created_at": time.time(),
        "run": dict(run),
        "provenance": provenance(),
    }
    m.update(extra)
    validate_manifest(m)
    return m


def validate_manifest(m: Any) -> Dict[str, Any]:
    """Raise ValueError listing EVERY problem; return ``m`` when valid."""
    problems: List[str] = []
    if not isinstance(m, dict):
        raise ValueError(f"manifest must be a dict, got {type(m).__name__}")
    if m.get("schema") not in ACCEPTED_SCHEMAS:
        problems.append(
            f"schema must be one of {ACCEPTED_SCHEMAS} "
            f"(got {m.get('schema')!r}); bump the reader, never the record")
    if m.get("kind") != "manifest":
        problems.append(f"kind must be 'manifest' (got {m.get('kind')!r})")
    if not isinstance(m.get("tool"), str) or not m.get("tool"):
        problems.append(f"tool must be a nonempty str (got {m.get('tool')!r})")
    if not isinstance(m.get("created_at"), (int, float)) \
            or m.get("created_at", 0) <= 0:
        problems.append(
            f"created_at must be a positive unix time "
            f"(got {m.get('created_at')!r})")
    if not isinstance(m.get("run"), dict):
        problems.append(f"run must be a dict (got {type(m.get('run')).__name__})")
    prov = m.get("provenance")
    if not isinstance(prov, dict):
        problems.append("provenance must be a dict")
    else:
        for key, typ in _PROVENANCE_TYPES.items():
            if not isinstance(prov.get(key), typ):
                problems.append(
                    f"provenance.{key} must be {typ.__name__} "
                    f"(got {prov.get(key)!r})")
        # schema 2 requires the multi-host identity; a schema-1 manifest
        # predates it (still parses), but when present the types bind
        for key, typ in _PROVENANCE_V2_TYPES.items():
            present = key in prov
            if m.get("schema") == 2 and not present:
                problems.append(
                    f"provenance.{key} is required at schema 2 "
                    f"({typ.__name__})")
            elif present and not isinstance(prov.get(key), typ):
                problems.append(
                    f"provenance.{key} must be {typ.__name__} "
                    f"(got {prov.get(key)!r})")
        if prov.get("device_count", 0) < 1:
            problems.append("provenance.device_count must be >= 1")
        if "process_count" in prov and \
                isinstance(prov.get("process_count"), int) and \
                prov["process_count"] < 1:
            problems.append("provenance.process_count must be >= 1")
        br = prov.get("builder_rev", None)
        if br is not None and not isinstance(br, int):
            problems.append(
                f"provenance.builder_rev must be int or null (got {br!r})")
    if problems:
        raise ValueError("invalid manifest: " + "; ".join(problems))
    return m


def validate_event(e: Any) -> Dict[str, Any]:
    """Raise ValueError on a malformed event record; return it when valid."""
    if not isinstance(e, dict):
        raise ValueError(f"event must be a dict, got {type(e).__name__}")
    problems: List[str] = []
    if e.get("schema") not in ACCEPTED_SCHEMAS:
        problems.append(f"schema must be one of {ACCEPTED_SCHEMAS} "
                        f"(got {e.get('schema')!r})")
    kind = e.get("kind")
    if not isinstance(kind, str) or not kind:
        problems.append(f"kind must be a nonempty str (got {kind!r})")
    elif kind == "manifest":
        problems.append("'manifest' is reserved for the first record")
    if not isinstance(e.get("t"), (int, float)) or e.get("t", 0) <= 0:
        problems.append(f"t must be a positive unix time (got {e.get('t')!r})")
    if problems:
        raise ValueError("invalid event: " + "; ".join(problems))
    return e


class TraceWriter:
    """Append-only JSONL writer: one manifest first, then events.

    Thread-safe (the heartbeat thread writes verdict events while the
    main thread writes chunks).  Values that are not JSON-native are
    stringified (``default=str``) so a dtype or Path in a config dict
    never kills a run.
    """

    def __init__(self, path: str):
        self.path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._fh = open(path, "w")
        self._lock = threading.Lock()
        self._wrote_manifest = False
        self.last_event_t = time.monotonic()
        # in-process taps: each callable sees every record (manifest and
        # events, heartbeat thread included) right after it hits disk.
        # The flight recorder (obs/flightrec.py) rides here so its ring
        # holds exactly what the log holds, without tailing our own file.
        # A mirror raising must never kill the write path.
        self.mirrors: List[Any] = []

    def write_manifest(self, manifest: Dict[str, Any]) -> None:
        validate_manifest(manifest)
        with self._lock:
            if self._wrote_manifest:
                raise ValueError("manifest already written")
            self._write(manifest)
            self._wrote_manifest = True

    def event(self, kind: str, **payload: Any) -> Dict[str, Any]:
        rec = {"schema": SCHEMA_VERSION, "kind": kind, "t": time.time()}
        rec.update(payload)
        validate_event(rec)
        with self._lock:
            if not self._wrote_manifest:
                raise ValueError("write the manifest before any event")
            self._write(rec)
        return rec

    def _write(self, rec: Dict[str, Any]) -> None:
        if self._fh is None:
            return  # closed: drop silently (late heartbeat verdicts)
        self._fh.write(json.dumps(rec, default=str) + "\n")
        self._fh.flush()
        self.last_event_t = time.monotonic()
        for mirror in self.mirrors:
            try:
                mirror(rec)
            except Exception:  # noqa: BLE001 — taps are never load-bearing
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_log(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Parse a JSONL trace: ``(manifest, events)``.  No validation."""
    manifest: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if manifest is None:
                manifest = rec
            else:
                events.append(rec)
    if manifest is None:
        raise ValueError(f"{path}: empty event log")
    return manifest, events


def validate_log(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """``read_log`` + schema validation of the manifest and every event."""
    manifest, events = read_log(path)
    try:
        validate_manifest(manifest)
    except ValueError as e:
        raise ValueError(f"{path}: first record: {e}") from None
    for i, e in enumerate(events):
        try:
            validate_event(e)
        except ValueError as err:
            raise ValueError(f"{path}: event {i}: {err}") from None
    return manifest, events


class LogTail:
    """Incremental JSONL reader: ``poll()`` returns newly appended records.

    The supervisor's view of a live child: it tails the child's
    telemetry log between polls, consuming only COMPLETE lines (a child
    SIGKILLed mid-write leaves a partial last line, which must stay
    unconsumed until — if ever — its terminator lands, never be parsed
    as garbage).  Reads in binary and tracks a byte offset so a decode
    boundary can't desync the position.  A missing file (child not yet
    started, or dead before its first event) yields no records rather
    than raising; malformed complete lines are counted and skipped —
    the watcher must survive anything a dying process leaves behind.

    Truncation/rotation is detected by size: a file now SHORTER than
    the consumed offset was rewritten from the top (a supervisor
    restart reuses the telemetry path — TraceWriter opens ``"w"`` — or
    a log rotation swapped the inode), so the tail restarts at byte 0
    instead of sticking forever past the new EOF.  ``truncations``
    counts the resets.  A rewrite that has already grown PAST the old
    offset is indistinguishable from an append by size alone and is
    not detected — every writer in this repo starts a fresh file
    empty, so the shrink is observable at the next poll.
    """

    def __init__(self, path: str):
        self.path = path
        self.malformed = 0
        self.truncations = 0
        self._pos = 0

    def poll(self) -> List[Dict[str, Any]]:
        try:
            fh = open(self.path, "rb")
        except OSError:
            return []
        with fh:
            try:
                size = os.fstat(fh.fileno()).st_size
            except OSError:
                size = None
            if size is not None and size < self._pos:
                self._pos = 0
                self.truncations += 1
            fh.seek(self._pos)
            buf = fh.read()
        end = buf.rfind(b"\n")
        if end < 0:
            return []
        self._pos += end + 1
        out: List[Dict[str, Any]] = []
        for line in buf[:end + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8", errors="replace"))
            except ValueError:
                self.malformed += 1
                continue
            if isinstance(rec, dict):
                out.append(rec)
            else:
                self.malformed += 1
        return out


def find_latest_manifest(
    search: Optional[Sequence[str]] = None,
) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Newest valid manifest among ``*.jsonl`` logs in ``search`` dirs.

    Defaults to :func:`default_telemetry_dir`.  Returns ``(path,
    manifest)`` by ``created_at``, or None when nothing valid exists —
    the pointer bench.py's wedged-path record embeds so a ``stale:
    true`` scoreboard names the last run that DID leave evidence.
    """
    dirs = list(search) if search else [default_telemetry_dir()]
    best: Optional[Tuple[str, Dict[str, Any]]] = None
    for d in dirs:
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(d, name)
            try:
                with open(path) as fh:
                    first = fh.readline()
                manifest = validate_manifest(json.loads(first))
            except Exception:  # noqa: BLE001 — skip foreign/corrupt files
                continue
            if best is None or \
                    manifest["created_at"] > best[1]["created_at"]:
                best = (path, manifest)
    return best
