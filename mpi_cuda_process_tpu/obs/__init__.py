"""Unified telemetry layer: manifests, chunk stats, cost counters, heartbeat.

One subsystem shared by every entry point — ``cli --telemetry PATH``,
``bench.py``, ``benchmarks/measure.py``, ``benchmarks/scaling.py`` —
so all four emit the SAME versioned manifest schema (``trace.py``'s
validator is the single definition) and the same event vocabulary:

* ``manifest``   — provenance-stamped run record (first line, always)
* ``costmodel``  — static flop/HBM/ppermute counters + roofline
* ``chunk``      — per-chunk wall time, recompile flag, memory peaks
* ``heartbeat``  — STALLED/WEDGED/RECOVERED verdicts from the watcher
* ``profile``    — device-trace attribution of one profiled chunk
  (``profile.py``: measured overlap efficiency, or an explicit
  ``attribution: unavailable`` — never fabricated zeros)
* ``label`` / ``rung`` — benchmark-harness progress records
* ``span``       — one finished span of the causal timeline
  (``spans.py``: trace_id/span_id/parent_id + wall start + duration;
  the root span closes every log)
* ``health``     — one numerics-sentinel check (``health.py``:
  per-field min/max/mean + NaN/Inf counts, the op's registered
  conservation invariant, and the HEALTHY/DIVERGED verdict that flows
  through supervisor, ledger quarantine, and ``/status.json``)
* ``halo_audit`` — one bit-exact ghost-slab audit pass (``health.py``
  ``--halo-audit``: received slabs vs neighbor interiors, localized
  to (field, axis, direction, ring-shard) on mismatch)
* ``policy``     — the auto-policy decision (``policy/select.py``):
  chosen mode fields, measured-vs-predicted provenance, explicit-flag
  overrides, and the ranked runner-up table
* ``migrate``    — one live mesh migration (``parallel/reshard.py``):
  src/dst mode fields, the adopting step, and the collective round
  count (never a host gather)
* ``scheduler``  — one serving-scheduler decision
  (``serving/scheduler.py``: submit/join/retire/evict/preempt/cancel/
  reject plus the elastic ladder ops ``grow``/``shrink`` — a shrink is
  a live member-repack down a rung, with occupancy gauges riding every
  record)
* ``router``     — one fleet-router decision (``serving/router.py``:
  route/rebalance/reject/replica_up/replica_dead, with replica
  liveness and in-flight gauges riding every record)
* ``anomaly``    — one run-doctor finding (``anomaly.py``: throughput
  collapse vs own baseline or the ledger roofline band, post-warmup
  recompiles, device-memory creep, chunk-time variance growth,
  straggler attribution naming the slowest host/group with its lag
  ratio — the evidence behind the DEGRADED verdict)
* ``error`` / ``summary`` — how the run ended

Sibling stores complete the layer: ``profile.py`` wraps a
``jax.profiler`` session scoped to one steady-state chunk and parses
the emitted trace into interior-compute / exchange / exposed-ICI
buckets; ``ledger.py`` is the append-only cross-round campaign ledger
(every manifest ingested, 0.0/stale/suspect values quarantined with
their heartbeat verdict, best-known-value-with-provenance per label —
what ``scripts/perf_gate.py`` gates against); ``metrics.py`` folds the
event stream into an in-process registry (counters, gauges,
bounded-reservoir histograms) and ``serve.py`` puts the live HTTP face
on it (``--serve``: /metrics, /status.json, /events — rendered by
``scripts/obs_top.py``).

:func:`open_session` is the one-call wiring: trace writer + manifest +
runtime recorder + heartbeat, bundled in a :class:`Session`.  Telemetry
is an observer, never load-bearing: events record only at chunk/label
boundaries (the jitted step is untouched — pinned by jaxpr inspection
in tests), and callers guard session setup so a telemetry failure
cannot kill the run it watches.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from . import flightrec as flightrec_lib
from . import heartbeat as heartbeat_lib
from . import runtime as runtime_lib
from . import spans as spans_lib
from . import trace as trace_lib


class Session:
    """A live telemetry session: trace + recorder + optional heartbeat.

    ``recorder`` is the driver-facing observer
    (``record_chunk(steps, seconds)`` at chunk boundaries);
    ``event``/``finish``/``error`` write to the trace.  ``spans`` is
    the session's :class:`~.spans.SpanEmitter` (one causal timeline:
    the trace context is inherited from ``OBS_TRACE_CONTEXT`` — or the
    spawning thread — when a parent exported one).  ``finish`` and
    ``close`` are idempotent, and ``close`` always stops the heartbeat
    first so no verdict thread outlives its run, then emits the root
    span before the trace writer closes.
    """

    def __init__(self, trace: trace_lib.TraceWriter,
                 recorder: runtime_lib.RuntimeRecorder,
                 heartbeat: Optional[heartbeat_lib.Heartbeat],
                 spans: Optional[spans_lib.SpanEmitter] = None,
                 flight: Optional[flightrec_lib.FlightRecorder] = None):
        self.trace = trace
        self.recorder = recorder
        self.heartbeat = heartbeat
        self.spans = spans
        # the post-mortem ring (obs/flightrec.py): mirrors every trace
        # record in memory so a terminal verdict can emit a bundle even
        # after the telemetry dir is gone
        self.flight = flight
        self._finished = False

    @property
    def path(self) -> str:
        return self.trace.path

    def event(self, kind: str, **payload: Any) -> None:
        self.trace.event(kind, **payload)
        self.recorder.mark()

    def progress(self) -> None:
        """Liveness tick without an event (harness inner loops)."""
        self.recorder.mark()

    def finish(self, **payload: Any) -> None:
        """Write the summary event (once): runtime stats + caller extras."""
        if self._finished:
            return
        self._finished = True
        hb = (self.heartbeat.last_verdict if self.heartbeat is not None
              else None)
        self.trace.event("summary", runtime=self.recorder.summary(),
                         heartbeat=hb, **payload)

    def error(self, exc: BaseException) -> None:
        try:
            self.trace.event(
                "error", error=f"{type(exc).__name__}: {exc}"[:1200],
                runtime=self.recorder.summary())
        except Exception:  # noqa: BLE001 — already failing; don't mask
            pass

    def close(self) -> None:
        if self.heartbeat is not None:
            self.heartbeat.stop()
        if self.spans is not None:
            self.spans.close()  # root span: before the writer closes
        self.trace.close()

    def __enter__(self) -> "Session":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.error(exc)
        else:
            self.finish()
        self.close()


def open_session(
    path: str,
    tool: str,
    run: Dict[str, Any],
    step_unit: int = 1,
    stall_after_s: float = 600.0,
    with_heartbeat: bool = True,
    ensemble: int = 0,
    **manifest_extra: Any,
) -> Session:
    """Open a trace at ``path``, write the manifest, start the heartbeat.

    The shared constructor all four tools call — the mechanism by which
    "same schema" is a property of the code rather than a convention.
    The session's span emitter adopts an inherited ``OBS_TRACE_CONTEXT``
    (or the spawning thread's pending context) so a supervised child's
    — or an engine request's — spans share the parent's trace_id; the
    manifest carries the ``trace`` identity block either way.
    """
    trace = trace_lib.TraceWriter(path)
    flight = flightrec_lib.FlightRecorder()
    trace.mirrors.append(flight.note)
    spans = spans_lib.SpanEmitter(trace, context=spans_lib.resolve_context(),
                                  root_name=tool)
    manifest_extra.setdefault("trace", spans.manifest_block())
    trace.write_manifest(trace_lib.build_manifest(
        tool, run, **manifest_extra))
    recorder = runtime_lib.RuntimeRecorder(trace=trace, step_unit=step_unit,
                                           ensemble=ensemble, spans=spans)
    hb = None
    if with_heartbeat:
        hb = heartbeat_lib.Heartbeat(recorder, trace=trace,
                                     stall_after_s=stall_after_s)
        hb.start()
    return Session(trace, recorder, hb, spans=spans, flight=flight)


__all__ = ["Session", "open_session"]
