"""Benchmark harness: prints ONE JSON line with the headline metric.

Headline: Mcells/s for the 3D 7-point Laplacian on a 256^3 grid, single chip
(BASELINE.json config 2).  The reference publishes no numbers (BASELINE.md),
so ``vs_baseline`` is measured against an A100+NCCL-class working target of
50,000 Mcells/s (~50 Gcell/s — what tuned 7-point fp32 stencil codes reach on
A100-80GB, whose HBM bandwidth bounds the update at ~190 Gcell/s; v5e's
819 GB/s bounds it at ~100 Gcell/s with perfect fusion), per BASELINE.md's
"A100+NCCL-class Mcells/sec" north star.

Extra diagnostics go to stderr; stdout carries exactly one JSON line.
"""

import json
import math
import os
import sys
import threading
import time

BASELINE_MCELLS = 50_000.0  # A100-class 7-point stencil throughput
# N-vs-4N noise floor: the 3N-step delta must exceed this fraction of the
# N-scan time or the measurement is flagged suspect instead of reported.
# Shared with benchmarks/measure.py (which imports it from here).
NOISE_FLOOR_FRAC = 0.05
_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      ".bench_cache.json")
# The axon TPU tunnel can wedge (hangs even trivial ops — see
# .claude/skills/verify/SKILL.md).  A watchdog emits a clearly-marked STALE
# record (distinct metric name + ``stale: true`` + cache age) rather than
# letting the driver's bench run record nothing — stale data must never be
# scorable as a fresh measurement.  Replay is restricted to cache records
# THIS machine's bench actually measured (``local_run: true``, written by
# main() below): a fresh checkout with a wedged backend reports value 0.0
# and cites the committed campaign table in the note instead of replaying
# VCS data as if it were a local measurement (round-3 advisor finding).
# The watchdog is progress-aware: it fires only after _WATCHDOG_S seconds
# with NO progress (a slow-but-advancing run keeps extending its lease).
_WATCHDOG_S = 420.0
_done = threading.Event()
_emit_lock = threading.Lock()
_emitted = False
_progress_t = [time.monotonic()]


def _progress() -> None:
    """Mark liveness; called between compile/measure phases."""
    _progress_t[0] = time.monotonic()


def _emit(rec) -> None:
    """Print the one result line exactly once (watchdog/main race-safe)."""
    global _emitted
    with _emit_lock:
        if _emitted:
            return
        _emitted = True
        print(json.dumps(rec), flush=True)


def _last_real_measurement(cached=None):
    """Provenance pointer at the newest REAL measurement this artifact
    knows about: ``{label, value, measured_at, source}`` or None.

    The driver-visible wedged-path record used to be indistinguishable
    from "never measured" (VERDICT r5 weak #7): the scoreboard read
    0.0/stale whether the repo had measured 102.7 Gcells/s or nothing at
    all.  This field carries the distinction WITHOUT changing the
    scorable ``value`` (which stays 0.0/stale on the honest paths): a
    local bench cache wins; otherwise the newest timestamped row of the
    committed campaign tables (benchmarks/results_r0*.json) is cited,
    explicitly source-marked as VCS data, never replayed as a value.
    NEVER raises (watchdog-thread safety).
    """
    try:
        if cached and cached.get("local_run"):
            return {"label": str(cached.get("metric", "bench")),
                    "value": cached.get("value", 0.0),
                    "measured_at": cached.get("measured_at"),
                    "source": "local bench cache"}
        import glob

        best = None
        bdir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks")
        for path in sorted(glob.glob(os.path.join(bdir,
                                                  "results_r0*.json"))):
            try:
                with open(path) as fh:
                    table = json.load(fh)
            except Exception:
                continue
            if not isinstance(table, dict):
                continue
            for label, r in table.items():
                if not isinstance(r, dict) or r.get("suspect"):
                    continue
                val = r.get("mcells_per_s")
                ts = r.get("measured_at")
                if not isinstance(val, (int, float)) or \
                        not isinstance(ts, (int, float)):
                    continue
                if best is None or ts > best["measured_at"]:
                    best = {"label": label, "value": val,
                            "measured_at": ts,
                            "source": (f"committed campaign table "
                                       f"({os.path.basename(path)}) — "
                                       "not a local measurement")}
        return best
    except Exception:
        return None


def _wedge_context():
    """Heartbeat verdict + newest telemetry manifest for a wedged record.

    The zero scoreboards of rounds 3-5 could not say WHY they were zero;
    every wedged-path record now carries (a) a bounded backend probe
    verdict from the framework's heartbeat (obs/heartbeat.py — WEDGED vs
    NO_TPU vs in-process stall) and (b) the path of the newest telemetry
    manifest on this box, so ``stale: true`` plus the why live in one
    file.  NEVER raises (watchdog-thread safety); the probe is skipped
    under ``BENCH_OBS_PROBE=0`` (tests — it spawns subprocesses).
    """
    out = {}
    try:
        if os.environ.get("BENCH_OBS_PROBE", "1") != "0":
            from mpi_cuda_process_tpu.obs import heartbeat as _hb

            verdict = _hb.probe_verdict(timeout_s=60.0)
            out["heartbeat"] = {"verdict": verdict.get("verdict"),
                                "detail": verdict.get("detail")}
    except Exception:
        pass
    try:
        from mpi_cuda_process_tpu.obs import trace as _tr

        found = _tr.find_latest_manifest()
        if found is not None:
            out["telemetry_manifest"] = found[0]
    except Exception:
        pass
    try:
        # The resume pointer (round-13 satellite): the newest checkpoint
        # dir + step known to the telemetry manifests, in the same JSON
        # that reports the wedge — so a human (or the run supervisor,
        # resilience/supervisor.py) can resume instead of restarting
        # from zero.
        from mpi_cuda_process_tpu.resilience import supervisor as _sup

        ck = _sup.find_latest_checkpoint()
        if ck is not None:
            out["latest_checkpoint"] = {"dir": ck[0], "step": ck[1]}
    except Exception:
        pass
    return out


def _ledger_wedged(rec) -> None:
    """Route a wedged-path record into the campaign ledger, QUARANTINED.

    The stale 0.0 value then exists in the durable cross-round table as
    an explicitly-quarantined row (with its heartbeat verdict and the
    ``last_real_measurement`` pointer) — downstream tooling reading the
    ledger for baselines can never mistake it for a measurement.
    NEVER raises (watchdog-thread safety).
    """
    try:
        from mpi_cuda_process_tpu.obs import ledger as _ledger

        _ledger.record_wedged_bench(rec)
    except Exception:
        pass


def _stale_fallback_record():
    """The watchdog's record when the backend is wedged.  NEVER raises —
    an exception here would kill the watchdog thread and leave the driver
    with no output at all.

    Only a cache record THIS machine measured (``local_run: true``) is
    replayed as a value; anything else yields value 0.0 with a pointer at
    the committed campaign table — VCS data must not impersonate a local
    measurement (round-3 advisor finding on _campaign_record).  Every
    wedged-path record additionally carries ``last_real_measurement``
    (provenance-marked label/value/timestamp), so the driver-visible
    artifact distinguishes "never measured" from "measured, tunnel
    currently dead".
    """
    try:
        with open(_CACHE) as fh:
            cached = json.load(fh)
        if not isinstance(cached, dict) or not cached.get("local_run"):
            cached = None
    except Exception:
        cached = None
    try:
        if cached is not None:
            try:
                cached_at = float(cached.get("measured_at") or 0.0)
            except (TypeError, ValueError):
                cached_at = 0.0
            age_s = round(time.time() - cached_at, 1) if cached_at else None
            rec = {
                "metric": str(cached.get(
                    "metric", "stencil_throughput")) + "_cached",
                "value": cached.get("value", 0.0),
                "unit": cached.get("unit", "Mcells/s"),
                "vs_baseline": cached.get("vs_baseline", 0.0),
                "stale": True,
                "cache_age_s": age_s,
                "note": (
                    f"STALE: cached {cached.get('backend', 'unknown')}"
                    "-backend result measured by a previous LOCAL bench "
                    "run; backend unresponsive this run — not a fresh "
                    "measurement"),
            }
            if cached.get("suspect"):  # belt-and-braces: caches predating
                rec["suspect"] = True  # the no-suspect-writes rule keep it
            last = _last_real_measurement(cached)
            if last is not None:
                rec["last_real_measurement"] = last
            rec.update(_wedge_context())
            _ledger_wedged(rec)
            return rec
    except Exception:
        pass
    rec = {"metric": "stencil_throughput_unmeasured",
           "value": 0.0, "unit": "Mcells/s", "vs_baseline": 0.0,
           "stale": True,
           "note": ("backend unresponsive and no local bench cache; see "
                    "benchmarks/results_r0*.json for the measurement "
                    "campaign's real-chip table (not replayed here)")}
    last = _last_real_measurement()
    if last is not None:
        rec["last_real_measurement"] = last
    try:
        rec.update(_wedge_context())
    except Exception:
        pass
    _ledger_wedged(rec)
    return rec


def _watchdog():
    while True:
        lease = _progress_t[0] + _WATCHDOG_S - time.monotonic()
        if lease > 0:
            if _done.wait(lease):
                return  # measurement finished normally
            continue  # lease may have been extended by _progress()
        break
    _emit(_stale_fallback_record())
    os._exit(0)


if __name__ == "__main__":
    threading.Thread(target=_watchdog, daemon=True).start()


def _tpu_reachable(timeout_s: float = 120.0) -> bool:
    """Probe backend discovery in a CHILD process with a hard timeout.

    On a box with no TPU (or a wedged axon tunnel) ``import jax`` +
    backend discovery itself can hang indefinitely — that is exactly the
    BENCH_r05 failure: the watchdog fired and the scoreboard recorded
    0.0.  A subprocess probe turns "discovery hangs" into "probe times
    out", after which the parent forces the CPU backend BEFORE its own
    first jax use and measures an honest CPU number instead.
    """
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('BACKEND=' + jax.default_backend())"],
            capture_output=True, text=True, timeout=timeout_s)
    except Exception:
        return False
    return out.returncode == 0 and "BACKEND=tpu" in out.stdout


# In-process CPU forcing for smoke tests / wedged-tunnel / no-TPU runs
# (the env var JAX_PLATFORMS alone is overridden by the axon
# sitecustomize); the recipe lives in repo-root cpuforce.py.  Forced
# explicitly via BENCH_FORCE_CPU, or automatically when the probe says no
# healthy TPU backend is reachable.
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
if __name__ == "__main__" and not os.environ.get("BENCH_FORCE_CPU") \
        and not _tpu_reachable():
    print("[bench] no reachable TPU backend (probe); measuring on CPU",
          file=sys.stderr)
    os.environ["BENCH_FORCE_CPU"] = "1"
if os.environ.get("BENCH_FORCE_CPU"):
    from cpuforce import force_cpu  # noqa: E402

    force_cpu()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def _fence(fields) -> float:
    """Device->host read: the only reliable completion fence.

    (On the tunneled axon backend, ``jax.block_until_ready`` can return before
    execution finishes; an actual scalar read cannot.)
    """
    return float(jnp.sum(fields[0]))


def _time_run(run, mk_state, reps) -> float:
    best = math.inf
    for _ in range(reps):
        f = mk_state()
        _fence(f)
        t0 = time.perf_counter()
        _fence(run(f))
        best = min(best, time.perf_counter() - t0)
        _progress()
    return best


def bench_stencil(name, grid, params, timed_steps, reps=3, fuse=0):
    """Per-step throughput with fixed dispatch/readback overhead removed.

    Times scans of N and 4N steps; the difference isolates pure step time
    (the ~66 ms tunnel round-trip and the readback cancel out).  With
    ``fuse=k`` the step is the temporal-blocking fused Pallas kernel (k
    real steps per call — the CLI's ``auto`` path on TPU); falls back to
    the jnp step if the fused kernel cannot be built.
    """
    from mpi_cuda_process_tpu import init_state, make_step, make_stencil
    from mpi_cuda_process_tpu.driver import make_runner

    st = make_stencil(name, **params)
    mk_state = lambda: init_state(st, grid, kind="auto")  # noqa: E731
    step_unit, step, compute = 1, None, "jnp"
    if fuse > 1:
        from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

        step = make_fused_step(st, grid, fuse)  # interpret off-TPU
        if step is not None:
            step_unit, compute = fuse, f"pallas_fused_k{fuse}"
    if step is None:
        step = make_step(st, grid)
    run_a = make_runner(step, timed_steps)
    run_b = make_runner(step, 4 * timed_steps)
    _fence(run_a(mk_state()))  # compile + warm
    _progress()
    _fence(run_b(mk_state()))
    _progress()
    t_a = _time_run(run_a, mk_state, reps)
    t_b = _time_run(run_b, mk_state, reps)
    delta = t_b - t_a
    # t(4N) - t(N) should be ~3x t(N)'s step content; a delta that is
    # non-positive OR tiny relative to t_a means noise swamped the signal —
    # emit it flagged rather than clamped into a plausible-looking number.
    suspect = delta <= NOISE_FLOOR_FRAC * t_a
    per_step = max(delta, 1e-9) / (3 * timed_steps * step_unit)
    cells = math.prod(grid)
    return cells / per_step / 1e6, per_step, compute, suspect


def _bench_safe(name, grid, steps, fuse):
    """Measure, falling back to the jnp path on a fused-Pallas failure."""
    try:
        return bench_stencil(name, grid, {}, steps, fuse=fuse)
    except Exception as e:  # noqa: BLE001 — bench must emit, not crash
        if fuse <= 1:
            raise  # the failing attempt WAS the jnp path; nothing to fall to
        print(f"[bench] fused path failed ({type(e).__name__}); "
              "re-measuring on jnp", file=sys.stderr)
        return bench_stencil(name, grid, {}, steps, fuse=0)


def _write_bench_telemetry(rec, grid, steps, fuse, backend):
    """Emit the round-gate's own telemetry manifest (obs/ schema).

    One small JSONL under the shared telemetry dir: the same manifest
    schema as ``cli --telemetry`` / measure.py / scaling.py, with the
    headline record as its one result event — so the round-end bench is
    itself provenance-stamped evidence, and the wedged-path
    ``telemetry_manifest`` pointer has something local to point at.
    Returns the path, or None (telemetry must never break the bench).
    """
    try:
        from mpi_cuda_process_tpu.obs import trace as obs_trace

        path = os.path.join(obs_trace.default_telemetry_dir(),
                            "bench.jsonl")
        with obs_trace.TraceWriter(path) as w:
            w.write_manifest(obs_trace.build_manifest(
                "bench",
                {"grid": list(grid), "timed_steps": steps, "fuse": fuse,
                 "backend": backend,
                 "baseline_mcells": BASELINE_MCELLS}))
            w.event("result", **rec)
        return path
    except Exception:
        return None


def _maybe_serve():
    """``BENCH_SERVE_PORT``: live console over the telemetry dir.

    bench.py has no CLI (the driver runs it bare), so the live-console
    opt-in is an env var: when set, a campaign aggregator
    (obs/serve.py) serves the shared telemetry directory for the
    duration of the bench — the same /metrics + /status.json +
    /events surface as ``cli --serve``, picking up the manifest this
    run writes at the end (and any concurrent run's).  Never
    load-bearing; returns the server or None.
    """
    port = os.environ.get("BENCH_SERVE_PORT")
    if not port:
        return None
    try:
        from mpi_cuda_process_tpu.obs import serve as serve_lib
        from mpi_cuda_process_tpu.obs import trace as obs_trace

        server = serve_lib.serve_campaign(
            obs_trace.default_telemetry_dir(), port=int(port))
        print(f"[bench] obs console at {server.url}", file=sys.stderr)
        return server
    except Exception as e:
        print(f"[bench] BENCH_SERVE_PORT disabled "
              f"({type(e).__name__}: {e})", file=sys.stderr)
        return None


def main():
    server = _maybe_serve()
    backend = jax.default_backend()
    if backend == "cpu":
        grid, steps, fuse = (128, 128, 128), 10, 0
        grid_lg, steps_lg = None, 0
    else:
        grid, steps, fuse = (256, 256, 256), 50, 4
        # the honest large-grid number: the regime where XLA's fusion
        # collapses (round-2 verdict) and the north star (4096^3) lives
        grid_lg, steps_lg = (512, 512, 512), 15
    mcells, per_step, compute, suspect = _bench_safe(
        "heat3d", grid, steps, fuse)
    print(
        f"[bench] backend={backend} heat3d {'x'.join(map(str, grid))} "
        f"[{compute}]: {per_step*1e3:.3f} ms/step ({mcells:.0f} Mcells/s)",
        file=sys.stderr,
    )
    rec = {
        "metric": f"heat3d_7pt_{grid[0]}cubed_single_chip_throughput",
        "value": round(mcells, 1),
        "unit": "Mcells/s",
        "vs_baseline": round(mcells / BASELINE_MCELLS, 4),
        "compute": compute,
        "backend": backend,
    }
    if backend != "tpu":
        # honest fallback measurement, never a zero scoreboard: a real
        # small-grid CPU number, provenance-tagged, with the pointer at
        # the committed real-chip campaign table
        rec["note"] = (
            "CPU-backend fallback measurement (no reachable TPU this "
            "run); for real-chip numbers see the campaign table in "
            "benchmarks/results_r0*.json")
    if suspect:
        rec["suspect"] = True
        rec["note"] = ("N-vs-4N time delta below the noise floor "
                       "(timing noise)")
    if grid_lg is not None:
        mc_lg, ps_lg, compute_lg, suspect_lg = _bench_safe(
            "heat3d", grid_lg, steps_lg, fuse)
        print(
            f"[bench] backend={backend} heat3d "
            f"{'x'.join(map(str, grid_lg))} [{compute_lg}]: "
            f"{ps_lg*1e3:.3f} ms/step ({mc_lg:.0f} Mcells/s)",
            file=sys.stderr,
        )
        rec["value_512cubed"] = round(mc_lg, 1)
        rec["vs_baseline_512cubed"] = round(mc_lg / BASELINE_MCELLS, 4)
        rec["compute_512cubed"] = compute_lg
        if suspect_lg:
            rec["suspect_512cubed"] = True
    tel = _write_bench_telemetry(rec, grid, steps, fuse, backend)
    if tel:
        rec["telemetry"] = tel
        # every round's headline lands in the durable cross-round ledger
        # (quarantine rules applied on ingest; never breaks the bench)
        try:
            from mpi_cuda_process_tpu.obs import ledger as _ledger

            _ledger.ingest_log(tel)
        except Exception:
            pass
    if backend == "tpu" and not suspect and not rec.get("suspect_512cubed"):
        # Never seed the last-known-good cache with a noise-flagged record
        # (either grid size): the stale-fallback replay is the one path
        # that must stay honest.
        try:
            tmp = _CACHE + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(
                    {**rec, "backend": backend, "measured_at": time.time(),
                     "local_run": True},
                    fh)
            os.replace(tmp, _CACHE)
        except OSError:
            pass
    _done.set()
    _emit(rec)
    if server is not None:
        server.close()  # final drain picks up the manifest written above


if __name__ == "__main__":
    main()
