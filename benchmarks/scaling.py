"""Weak/strong-scaling benchmark harness (BASELINE.json north star).

The reference has no benchmark instrumentation at all (SURVEY.md §6); its
scaling story is fixed at exactly 2 ranks.  This harness measures the
framework's domain-decomposition scaling on any device population:

  * **weak scaling**: per-device block held fixed while the mesh grows; the
    headline metric is Mcells/s/device and efficiency vs the 1-device run
    (target >=90% at 64 chips, BASELINE.md).
  * **strong scaling**: global grid held fixed while the mesh grows.
  * **halo overhead**: per-step cost of the exchange, isolated by timing the
    same local block with and without the sharded exchange path.

Runs identically on a real TPU slice and on virtual CPU devices
(``--virtual N`` forces ``xla_force_host_platform_device_count`` — the
numbers are then only relative, but the harness and its efficiency
accounting are what ship).  Results print as a table plus one JSON line per
config for machine consumption.

Usage::

    python benchmarks/scaling.py --mode weak --stencil heat3d \
        --block 64,64,64 --steps 20 --virtual 8
    python benchmarks/scaling.py --mode strong --stencil heat3d \
        --grid 128,128,128 --steps 20 --virtual 8
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup_devices(virtual: int):
    if virtual:
        # Shared anti-sitecustomize recipe (repo-root cpuforce.py); only
        # effective if the jax backend is not yet initialized.
        from cpuforce import force_cpu

        force_cpu(virtual)
    import jax

    return jax


def _mesh_ladder(n_devices: int, ndim: int):
    """Mesh shapes 1, 2, 4, ... n_devices, factored over ndim axes."""
    from mpi_cuda_process_tpu.parallel.mesh import factor_mesh

    n = 1
    out = []
    while n <= n_devices:
        out.append(factor_mesh(n, ndim))
        n *= 2
    return out


def _time_run(run, fields, reps: int) -> float:
    import jax
    import jax.numpy as jnp

    def fence(fs):
        return float(jnp.sum(fs[0]))

    best = math.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        fence(run(fields))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_config(st, mesh_shape, global_shape, steps, reps=3, overlap=False,
                 fuse=0, fuse_kind=None, pipeline=False,
                 exchange="ppermute", ensemble=0):
    import jax

    from mpi_cuda_process_tpu import (
        init_state, make_mesh, make_sharded_step, make_step, shard_fields,
    )
    from mpi_cuda_process_tpu.driver import make_ensemble_step, make_runner

    n_dev = math.prod(mesh_shape)
    step_unit = 1
    kernel_kind = None  # which slab-operand kernel carried the rung
    if pipeline and n_dev == 1:
        return None  # no exchange to pipeline on the 1-device rung
    if exchange == "rdma" and n_dev == 1:
        return None  # no exchange for the remote-DMA ring to carry
    if n_dev > 1:
        mesh = make_mesh(mesh_shape)
        if fuse > 1:
            # temporal blocking UNDER decomposition: k micro-steps per
            # width-k exchange — the 4096^3-class execution strategy
            # (3D windowed kernel / 2D whole-local-block kernel).  With
            # ``overlap`` the width-m exchange is scheduled concurrently
            # with the interior kernel (interior/boundary split) — the
            # A/B rows this harness emits price exactly that split.
            from mpi_cuda_process_tpu.parallel.stepper import (
                make_sharded_temporal_step,
            )

            step = make_sharded_temporal_step(st, mesh, global_shape, fuse,
                                              kind=fuse_kind,
                                              overlap=overlap,
                                              pipeline=pipeline,
                                              exchange=exchange,
                                              ensemble=ensemble)
            if step is None:
                return None
            if exchange == "rdma" and \
                    getattr(step, "_exchange", None) != "rdma":
                # a row labeled exchange=rdma must not silently price
                # the ppermute transport
                return None
            if overlap and not getattr(step, "_overlap_active", False):
                # a row labeled overlap=true must not silently price the
                # plain step (geometry declined the split)
                return None
            if pipeline and not getattr(step, "_pipeline_active", False):
                # a row labeled pipeline=true must not silently price the
                # per-pass exchange schedule
                return None
            if fuse_kind == "stream" and not str(
                    getattr(step, "_padfree_kind", "")).startswith(
                        "stream"):
                # a stream-labeled rung must not silently price another
                # kernel class
                return None
            if fuse_kind == "padfree" and not str(
                    getattr(step, "_padfree_kind", "")).startswith(
                        ("zslab", "yzslab")):
                # same contract for forced pad-free rungs
                return None
            kernel_kind = getattr(step, "_padfree_kind", None)
            step_unit = fuse
        else:
            step = make_sharded_step(st, mesh, global_shape, overlap=overlap,
                                     ensemble=ensemble)
    elif fuse > 1:
        if st.ndim == 2:
            from mpi_cuda_process_tpu.ops.pallas.fullgrid import (
                make_fullgrid_step,
            )

            step = make_fullgrid_step(st, global_shape, fuse)
        elif fuse_kind == "stream":
            from mpi_cuda_process_tpu.ops.pallas.streamfused import (
                make_stream_fused_step,
            )

            step = make_stream_fused_step(st, global_shape, fuse,
                                          batch=ensemble)
        else:
            from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

            step = make_fused_step(st, global_shape, fuse,
                                   padfree=fuse_kind == "padfree")
        if step is None:
            return None
        step_unit = fuse
    else:
        step = make_step(st, global_shape)
    if ensemble and n_dev == 1 and \
            getattr(step, "_ensemble", 0) != ensemble:
        # 1-device rungs: batch the plain/tiled step by vmap (the
        # streaming builder and the sharded steppers arrive batched)
        step = make_ensemble_step(step)
    fields = init_state(st, global_shape, kind="auto", ensemble=ensemble)
    if n_dev > 1:
        fields = shard_fields(fields, mesh, st.ndim,
                              ensemble=bool(ensemble))
    # No donation: the same input fields are reused across timing reps.
    run_nodonate = make_runner(step, steps, jit=False)
    run = jax.jit(run_nodonate)
    import jax.numpy as jnp

    float(jnp.sum(run(fields)[0]))  # compile + warm
    t = _time_run(run, fields, reps)
    # aggregate cells: a batched rung advances every member each step
    cells = max(1, ensemble) * math.prod(global_shape)
    return (cells * steps * step_unit / t / 1e6, t / (steps * step_unit),
            kernel_kind)


def bench_groups(name, n_dev, n_groups, global_shape, steps, reps=3,
                 transport="device_put"):
    """Coupled device-group rung (--groups): N same-physics groups.

    The rung's devices split into N contiguous equal groups, each on a
    y-sharded (1, H) sub-mesh running the unmodified plain sharded
    stepper, coupled at the interface ghost bands
    (parallel/groups.py).  Mcells/s counts OWNED cell updates only,
    aggregated across groups, and the fence reads a scalar from every
    group — they dispatch on disjoint devices as independent async
    streams.  Returns None when the geometry cannot host the split
    (the caller skips the rung, never silently runs monolithic).
    """
    import jax.numpy as jnp

    from mpi_cuda_process_tpu.parallel import groups as groups_lib

    if n_dev < n_groups or n_dev % n_groups:
        return None
    h = n_dev // n_groups
    gspec = ",".join(
        f"{name}@{g * h}-{(g + 1) * h - 1}:mesh1x{h}"
        for g in range(n_groups))
    try:
        plans = groups_lib.plans_from_config(gspec, global_shape,
                                             n_devices=n_dev)
        runner = groups_lib.CoupledRunner(plans, transport=transport)
    except ValueError:
        # structural decline (z share / y sharding indivisible, or a
        # geometry the collective wire rejects by name)
        return None
    if getattr(runner, "n_groups", 1) != n_groups:
        return None  # must not price a different split under this rung
    if getattr(runner, "transport", "device_put") != transport:
        return None  # must not price one transport under the other's row

    def rounds(n):
        for fs in runner.fields:
            float(jnp.sum(fs[0].astype(jnp.float32)))
        t0 = time.perf_counter()
        runner.run(n)
        for fs in runner.fields:
            float(jnp.sum(fs[0].astype(jnp.float32)))
        return time.perf_counter() - t0

    rounds(1)  # compile + warm every group program and transfer fn
    best = math.inf
    for _ in range(reps):
        best = min(best, rounds(steps))
    cells = sum(pl.owned_cells for pl in runner.plans)
    return cells * steps / best / 1e6, best / steps, gspec


def bench_halo_overhead(st, mesh_shape, global_shape, steps, reps=3):
    """Per-step halo-exchange cost, isolated (SURVEY.md §5.1 attribution).

    Times the sharded step (exchange + update) against an exchange-free
    variant of the same local block update (the BCs-only padding path), on
    the same mesh.  The difference per step is the exchange + boundary-splice
    cost the decomposition adds.
    """
    import jax
    import jax.numpy as jnp

    from mpi_cuda_process_tpu import (
        init_state, make_mesh, make_sharded_step, shard_fields,
    )
    from mpi_cuda_process_tpu.driver import make_runner
    from mpi_cuda_process_tpu.parallel.halo import exchange_and_pad
    from mpi_cuda_process_tpu.parallel.stepper import (
        grid_partition_spec, shard_map,
    )

    mesh = make_mesh(mesh_shape)
    step = make_sharded_step(st, mesh, global_shape)

    # exchange-free control: same local compute, halo from BC constants only
    ndim = st.ndim

    def local_only(fields):
        padded = tuple(
            exchange_and_pad(f, (None,) * ndim, (1,) * ndim, fh, bc)
            for f, bc, fh in zip(fields, st.bc_value, st.field_halos))
        return st.update(padded)

    spec = grid_partition_spec(ndim, mesh)
    nostep = shard_map(local_only, mesh=mesh, in_specs=(spec,),
                       out_specs=spec, check_vma=False)

    fields = shard_fields(
        init_state(st, global_shape, kind="auto"), mesh, ndim)
    r_full = jax.jit(make_runner(step, steps, jit=False))
    r_local = jax.jit(make_runner(nostep, steps, jit=False))
    for r in (r_full, r_local):
        float(jnp.sum(r(fields)[0]))
    t_full = _time_run(r_full, fields, reps) / steps
    t_local = _time_run(r_local, fields, reps) / steps
    return t_full, t_local


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", choices=["weak", "strong", "halo"],
                   default="weak")
    p.add_argument("--stencil", default="heat3d")
    p.add_argument("--block", default="64,64,64",
                   help="per-device block (weak mode)")
    p.add_argument("--grid", default="128,128,128",
                   help="global grid (strong mode)")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--virtual", type=int, default=0,
                   help="force N virtual CPU devices (0 = real devices)")
    p.add_argument("--overlap", action="store_true",
                   help="use the explicit interior/boundary overlap stepper "
                        "(weak/strong modes) — compare against the default "
                        "XLA-scheduled exchange; composes with --fuse to "
                        "emit the overlap A/B ladder for the temporal-"
                        "blocked steppers (rungs that cannot host the "
                        "split are skipped, not silently run plain)")
    p.add_argument("--fuse-kind", default=None,
                   choices=["stream", "padfree"],
                   help="force the streaming (sliding-window manual-DMA) "
                        "or pad-free slab-operand kernels for --fuse "
                        "rungs — A/B vs the default zslab/windowed "
                        "kernels (virtual meshes: relative "
                        "evidence only).  Composes with --mesh-axes 1|2: "
                        "the 1-axis ladder runs the z-slab streaming "
                        "kernel, the 2-axis ladder the round-8 "
                        "y-slab+corner splice variant — run both for the "
                        "kind x mesh A/B pair; rungs that would price a "
                        "different kernel class are skipped")
    p.add_argument("--pipeline", action="store_true",
                   help="cross-pass pipelined exchange rungs (slab-carry "
                        "scan, stepper pipeline=True): pass i+1's "
                        "exchange issued from pass i's shell outputs — "
                        "the A/B against the same ladder without "
                        "--pipeline prices the cross-pass hiding.  Needs "
                        "--fuse; composes with --overlap and --mesh-axes "
                        "1|2; defaults --fuse-kind to padfree (the "
                        "pipeline rides the slab-operand kinds only); "
                        "1-device rungs and rungs that cannot host the "
                        "slab-carry scan are skipped, never silently "
                        "priced as per-pass rows.  Every emitted row "
                        "stamps the pipeline flag, so relative CPU "
                        "evidence and future real-slice rows stay "
                        "distinguishable")
    p.add_argument("--exchange", default="ppermute",
                   choices=["ppermute", "rdma"],
                   help="halo-exchange transport for the --fuse rungs: "
                        "ppermute (default, XLA collective on HBM slabs) "
                        "or rdma — the in-kernel remote-DMA ring "
                        "(ops/pallas/remote.py: boundary slabs through "
                        "double-buffered VMEM rings via "
                        "make_async_remote_copy, zero XLA ppermute in "
                        "the step).  The A/B against the same ladder "
                        "with --exchange ppermute prices the transport. "
                        "Needs --fuse; forces --fuse-kind stream (the "
                        "only rdma host — an explicit different kind "
                        "errors rather than silently re-labeling); "
                        "composes with --overlap/--pipeline and "
                        "--mesh-axes 1|2; 1-device rungs and rungs that "
                        "cannot host the streaming kernel are skipped, "
                        "never silently priced as ppermute rows.  Every "
                        "emitted row stamps the mode, so relative CPU "
                        "evidence (interpret-emulated) and future "
                        "real-slice rows stay distinguishable")
    p.add_argument("--fuse", type=int, default=0,
                   help="temporal blocking: k fused micro-steps per "
                        "width-k exchange (weak/strong modes; meshes keep "
                        "the lane axis whole — untileable rungs are "
                        "skipped)")
    p.add_argument("--ensemble", type=int, default=0, metavar="N",
                   help="batched-engine ladder arm (round 15): every "
                        "rung advances N members through ONE compiled "
                        "batched step (vmapped local update; one "
                        "exchange round per site regardless of N) and "
                        "reports AGGREGATE Mcells/s across members — "
                        "the A/B against the same ladder without "
                        "--ensemble prices the per-pass fixed-cost "
                        "amortization.  Every emitted row stamps the "
                        "ensemble size, so batched rows are never "
                        "confused with single-sim rows (the ledger "
                        "keys them apart)")
    p.add_argument("--groups", type=int, default=0, metavar="N",
                   help="coupled device-group ladder arm (round 18, "
                        "parallel/groups.py): every rung partitions its "
                        "devices into N contiguous same-physics groups "
                        "(y-sharded sub-meshes) coupled at interface "
                        "ghost bands, each group running the UNMODIFIED "
                        "plain sharded stepper — the A/B against the "
                        "same ladder without --groups prices exactly the "
                        "host-orchestrated coupling (interface transfers "
                        "+ per-group dispatch).  Rungs whose device "
                        "count cannot host the split (fewer than N, or "
                        "N does not divide it) are skipped, never "
                        "silently run monolithic; every emitted row "
                        "stamps the groups spec, so coupled rows are "
                        "never confused with monolithic rows (the "
                        "ledger keys them apart |grp:<sig>)")
    p.add_argument("--group-transport", default="device_put",
                   choices=["device_put", "collective"],
                   help="interface-band transport for the --groups "
                        "rungs (round 23, parallel/groups.py): "
                        "device_put (default, host-mediated receiver-"
                        "side band landing) or collective — raw sender "
                        "rows as one ppermute round per interface per "
                        "direction inside a union-mesh shard_map, "
                        "resampled shard-local on the receiver (zero "
                        "host hops; jaxpr-gated by utils/jaxprcheck)."
                        "  The A/B against the same --groups ladder "
                        "under device_put prices exactly the transport "
                        "swap; every emitted row stamps the transport, "
                        "and the ledger keys collective rows apart "
                        "(|gtx:collective), so neither transport can "
                        "baseline the other.  Needs --groups")
    p.add_argument("--telemetry", default=None, metavar="PATH",
                   help="write a JSONL telemetry event log (obs/ "
                        "schema, same manifest as cli --telemetry): "
                        "one 'rung' event per emitted ladder row, "
                        "'skip' events for declined rungs, heartbeat "
                        "verdicts if a rung stalls.  Render with "
                        "scripts/obs_report.py")
    p.add_argument("--mesh-axes", type=int, default=2, choices=[1, 2],
                   help="sharded-fused rung mesh arity (3D --fuse "
                        "ladders): 2 = balanced (z, y, 1) rungs "
                        "(default — the surface-to-volume-minimizing "
                        "decomposition, now pad-free via the 2-axis "
                        "slab-operand kernels); 1 = z-only (n, 1, 1) "
                        "rungs — run both for the decomposition-shape "
                        "A/B against the same grid")
    a = p.parse_args(argv)
    if a.exchange == "rdma":
        # resolved BEFORE the pipeline default below: an rdma ladder
        # must never be silently re-labeled onto the pad-free kind
        if not (a.fuse > 1):
            p.error("--exchange rdma needs --fuse K (the remote-DMA "
                    "ring feeds the streaming temporal-blocking "
                    "kernels)")
        if a.fuse_kind not in (None, "stream"):
            p.error("--exchange rdma rides the streaming kernel family "
                    "only; drop --fuse-kind or set it to stream")
        # pin the kernel class so every rung prices the same kernel
        a.fuse_kind = "stream"
    if a.groups:
        if a.groups < 2:
            p.error("--groups needs N >= 2 (a 1-group run is monolithic "
                    "— run the plain ladder instead)")
        bad = [flag for flag, on in (
            ("--fuse", a.fuse > 1), ("--overlap", a.overlap),
            ("--pipeline", a.pipeline), ("--ensemble", a.ensemble > 0),
            ("--exchange rdma", a.exchange == "rdma"),
            ("--fuse-kind", a.fuse_kind is not None)) if on]
        if bad:
            p.error(f"--groups conflicts with {', '.join(bad)}: coupled "
                    "rungs run each group's plain sharded stepper, so "
                    "the A/B against the monolithic ladder prices the "
                    "coupling and nothing else")
    if a.group_transport != "device_put" and not a.groups:
        p.error("--group-transport prices the coupled interface "
                "transport; it needs --groups N")
    if a.pipeline:
        if not (a.fuse > 1):
            p.error("--pipeline needs --fuse K (the slab-carry scan "
                    "pipelines the fused passes)")
        if a.fuse_kind is None:
            # the pipeline rides the slab-operand kinds; pin the kernel
            # class so every rung of the ladder prices the same kernel
            a.fuse_kind = "padfree"
    # --fuse + --overlap now composes: the temporal-blocked steppers carry
    # their own interior/boundary split (stepper.make_sharded_fused_step
    # overlap=True), so the pair emits the overlap A/B ladder for the
    # fused kind.  Rungs whose geometry declines the split are skipped
    # (never silently priced as plain rows).

    jax = _setup_devices(a.virtual)
    from mpi_cuda_process_tpu.ops.stencil import make_stencil

    st = make_stencil(a.stencil)
    if a.groups and st.ndim != 3:
        p.error("--groups partitions the z axis of a 3-d stencil; "
                f"{a.stencil} is {st.ndim}-d")
    n_devices = len(jax.devices())

    session = None
    if a.telemetry:
        try:
            from mpi_cuda_process_tpu import obs

            session = obs.open_session(
                a.telemetry, tool="scaling",
                run={k: v for k, v in vars(a).items()},
                stall_after_s=600.0)
        except Exception as e:  # noqa: BLE001 — never block the harness
            print(f"[scaling] telemetry disabled "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
            session = None

    def _tel(kind, **payload):
        if session is not None:
            session.event(kind, **payload)

    try:
        rc = _ladder(a, p, jax, st, n_devices, _tel)
    finally:
        if session is not None:
            session.finish()
            session.close()
    if session is not None:
        # rung rows enter the durable cross-round ledger (idempotent;
        # quarantine rules on ingest; never load-bearing)
        try:
            from mpi_cuda_process_tpu.obs import ledger as _ledger

            _ledger.ingest_log(session.path)
        except Exception:  # noqa: BLE001
            pass
    return rc


def _ladder(a, p, jax, st, n_devices, _tel) -> int:
    from mpi_cuda_process_tpu.config import parse_int_tuple

    if a.mode == "halo":
        ladder = _mesh_ladder(n_devices, st.ndim)[1:]
        if not ladder:
            p.error("halo mode needs >= 2 devices (try --virtual 8)")
        for mesh_shape in ladder:
            block = parse_int_tuple(a.block)
            global_shape = tuple(b * m for b, m in zip(block, mesh_shape))
            t_full, t_local = bench_halo_overhead(
                st, mesh_shape, global_shape, a.steps, a.reps)
            overhead = max(t_full - t_local, 0.0)
            rec = {
                "mode": "halo", "stencil": a.stencil,
                "mesh": list(mesh_shape), "grid": list(global_shape),
                "ms_per_step_full": round(t_full * 1e3, 3),
                "ms_per_step_no_exchange": round(t_local * 1e3, 3),
                "halo_overhead_ms": round(overhead * 1e3, 3),
                "halo_overhead_frac": round(overhead / t_full, 4),
            }
            print(json.dumps(rec))
            _tel("rung", **rec)
        return 0

    base = None
    rows = []
    ladder = _mesh_ladder(n_devices, st.ndim)
    if a.fuse > 1 and st.ndim == 3:
        # sharded-fused keeps the lane axis whole: decompose z/y only
        # (--mesh-axes 1 pins the z-ring for the decomposition-shape A/B)
        if a.mesh_axes == 1:
            ladder = [(m1[0], 1, 1) for m1 in _mesh_ladder(n_devices, 1)]
        else:
            ladder = [(*m2, 1) for m2 in _mesh_ladder(n_devices, 2)]
    elif a.fuse > 1 and st.ndim == 2:
        # 2D whole-local-block kernel: row decomposition only
        ladder = _mesh_ladder(n_devices, 1)
    for mesh_shape in ladder:
        n_dev = math.prod(mesh_shape)
        if a.mode == "weak":
            block = parse_int_tuple(a.block)[:st.ndim]
            if len(block) < st.ndim:
                p.error(f"--block needs {st.ndim} extents for {a.stencil}")
            # mesh tuples may be shorter than ndim (trailing axes unsharded)
            counts = (tuple(mesh_shape) + (1,) * st.ndim)[:st.ndim]
            global_shape = tuple(b * m for b, m in zip(block, counts))
        else:
            global_shape = parse_int_tuple(a.grid)
            if any(g % m for g, m in zip(global_shape, mesh_shape)):
                continue
        gspec = None
        if a.groups:
            got = bench_groups(a.stencil, n_dev, a.groups, global_shape,
                               a.steps, a.reps,
                               transport=a.group_transport)
            if got is None:
                print(f"[scaling] skip {mesh_shape}: {n_dev} device(s) "
                      f"cannot host {a.groups} coupled groups "
                      f"({a.group_transport})", file=sys.stderr)
                _tel("skip", mesh=list(mesh_shape),
                     grid=list(global_shape), groups=a.groups,
                     group_transport=a.group_transport,
                     reason="device count or geometry cannot host the "
                            "coupled group split under this transport")
                continue
            mcells, per_step, gspec = got
            kernel_kind = None
        else:
            got = bench_config(
                st, mesh_shape, global_shape, a.steps, a.reps,
                overlap=a.overlap, fuse=a.fuse, fuse_kind=a.fuse_kind,
                pipeline=a.pipeline, exchange=a.exchange,
                ensemble=a.ensemble)
            if got is None:
                print(f"[scaling] skip {mesh_shape}: untileable fused "
                      f"k={a.fuse}"
                      + (" (or cannot host --pipeline)" if a.pipeline
                         else "")
                      + (" (or cannot host --exchange rdma)"
                         if a.exchange == "rdma" else ""),
                      file=sys.stderr)
                _tel("skip", mesh=list(mesh_shape),
                     grid=list(global_shape), fuse=a.fuse,
                     pipeline=a.pipeline, exchange=a.exchange,
                     reason="untileable or cannot host the requested "
                            "overlap/pipeline/kind/exchange contract")
                continue
            mcells, per_step, kernel_kind = got
        per_dev = mcells / n_dev
        if base is None:
            base = per_dev if a.mode == "weak" else mcells
        eff = (per_dev / base if a.mode == "weak"
               else mcells / (base * n_dev))
        rows.append((mesh_shape, global_shape, mcells, per_dev, eff))
        rec = {
            "mode": a.mode, "stencil": a.stencil,
            "overlap": a.overlap, "fuse": a.fuse,
            "pipeline": a.pipeline,
            "fuse_kind": a.fuse_kind,
            "exchange": a.exchange,
            "ensemble": a.ensemble,
            "kernel_kind": kernel_kind,
            "mesh_axes": a.mesh_axes,
            "n_groups": a.groups,
            "groups": gspec,
            "group_transport": a.group_transport if a.groups else None,
            "mesh": list(mesh_shape), "grid": list(global_shape),
            "mcells_per_s": round(mcells, 1),
            "mcells_per_s_per_device": round(per_dev, 1),
            "efficiency": round(eff, 4),
            "ms_per_step": round(per_step * 1e3, 3),
        }
        print(json.dumps(rec))
        _tel("rung", **rec)

    print(f"\n{a.mode} scaling — {a.stencil}"
          f" ({n_devices} devices, {jax.default_backend()})", file=sys.stderr)
    print(f"{'mesh':>12} {'grid':>16} {'Mcells/s':>10}"
          f" {'/device':>10} {'eff':>6}", file=sys.stderr)
    for mesh_shape, g, mc, pd, eff in rows:
        print(f"{'x'.join(map(str, mesh_shape)):>12}"
              f" {'x'.join(map(str, g)):>16}"
              f" {mc:>10.0f} {pd:>10.0f} {eff:>6.1%}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
