"""Single-chip measurement campaign for the BASELINE.md perf table.

Runs the full config matrix on the real TPU and appends each result to
``benchmarks/results_r03.json`` IMMEDIATELY after it is measured, so a
wedged tunnel mid-campaign loses only the in-flight config.  Errored
configs are retried on the next invocation (only successful records are
skip-cached), so a transient tunnel failure heals on re-run.

Timing method (same as bench.py): scan N steps and 4N steps, take the
difference / 3N — cancels the ~66 ms tunnel dispatch + readback overhead
(docs/STATE.md "Infra gotchas").

Usage:  python benchmarks/measure.py [--out FILE] [--only NAME ...]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas import has_pallas_kernel, make_pallas_compute


def _fence(fields) -> float:
    # Actual scalar read: the only reliable completion fence on the tunneled
    # backend (block_until_ready can return early — docs/STATE.md).
    return float(jnp.sum(fields[0].astype(jnp.float32)))


def measure(name, grid, steps, dtype=None, compute="jnp", reps=3,
            params=None):
    """compute: jnp | pallas (compute_fn inside the pad step) |
    raw (whole-step raw kernel) | fusedK (3D windowed temporal blocking,
    K steps/pass) | fullK (2D whole-grid-in-VMEM temporal blocking) |
    copy (harness-calibration 1R+1W elementwise scan).
    """
    kw = dict(params or {})
    if dtype is not None:
        kw["dtype"] = dtype
    step_unit = 1
    if compute == "copy":
        # Harness calibration: a pure 1R+1W elementwise scan.  Converts to
        # GB/s as cells * 2 * itemsize / t — an absolute HBM-bandwidth
        # anchor for sanity-checking stencil Gcells/s numbers against the
        # roofline (a stencil can't beat this by more than its fusion
        # saves).
        dt = jnp.dtype(dtype or "float32")
        c = jnp.asarray(1.000001, dt)

        def step(fields):
            return (fields[0] * c,)

        mk = lambda: (jnp.zeros(grid, dt),)  # noqa: E731
        return _time_scan(step, mk, grid, steps, reps, 1)
    st = make_stencil(name, **kw)
    if compute == "raw":
        from mpi_cuda_process_tpu.ops.pallas.rawstep import make_raw_step
        step = make_raw_step(st, grid)  # interpret mode off-TPU (smoke)
        if step is None:
            raise ValueError(f"no raw step for {name} on {grid}")
    elif compute.startswith("padfree"):
        # pad-free 9-block raw-grid temporal blocking (no pad transient)
        from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step
        step_unit = int(compute[len("padfree"):])
        step = make_fused_step(st, grid, step_unit, padfree=True)
        if step is None:
            raise ValueError(f"untileable padfree k={step_unit} for {grid}")
    elif compute.startswith("fused"):
        from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step
        step_unit = int(compute[len("fused"):])
        step = make_fused_step(st, grid, step_unit)
        if step is None:
            raise ValueError(f"untileable fused k={step_unit} for {grid}")
    elif compute.startswith("full"):
        # whole-grid VMEM temporal blocking (2D families)
        from mpi_cuda_process_tpu.ops.pallas.fullgrid import (
            make_fullgrid_step,
        )
        step_unit = int(compute[len("full"):])
        step = make_fullgrid_step(st, grid, step_unit)
        if step is None:
            raise ValueError(f"untileable fullgrid k={step_unit} for {grid}")
    else:
        compute_fn = None
        if compute == "pallas":
            if not has_pallas_kernel(name):
                raise ValueError(f"no pallas kernel for {name}")
            compute_fn = make_pallas_compute(st, interpret=False)
        step = make_step(st, grid, compute_fn=compute_fn)
    mk = lambda: init_state(st, grid, kind="auto")  # noqa: E731
    return _time_scan(step, mk, grid, steps, reps, step_unit)


def _time_scan(step, mk, grid, steps, reps, step_unit):
    run_a = make_runner(step, steps)
    run_b = make_runner(step, 4 * steps)
    _fence(run_a(mk()))  # compile + warm
    _fence(run_b(mk()))

    def best(run):
        b = math.inf
        for _ in range(reps):
            f = mk()
            _fence(f)
            t0 = time.perf_counter()
            _fence(run(f))
            b = min(b, time.perf_counter() - t0)
        return b

    t_a, t_b = best(run_a), best(run_b)
    from bench import NOISE_FLOOR_FRAC  # repo root is on sys.path (top)

    if t_b - t_a <= NOISE_FLOOR_FRAC * t_a:
        # t(4N) - t(N) should be ~3x t(N)'s step content; a non-positive or
        # tiny-relative delta means noise swamped the signal: report, don't
        # fabricate a plausible-looking Mcells/s from a clamped epsilon.
        return {"error": f"step time below noise floor: t_a={t_a:.4f}s "
                         f"t_b={t_b:.4f}s (timing noise; rerun)",
                "suspect": True}
    per_step = (t_b - t_a) / (3 * steps * step_unit)
    mcells = math.prod(grid) / per_step / 1e6
    return {"ms_per_step": round(per_step * 1e3, 4),
            "mcells_per_s": round(mcells, 1)}


# (label, stencil, grid, steps, dtype, compute)
CONFIGS = [
    # BASELINE.json config 1 + 2 refresh
    ("heat2d_512_f32", "heat2d", (512, 512), 400, "float32", "jnp"),
    ("heat3d_256_f32", "heat3d", (256, 256, 256), 100, "float32", "jnp"),
    # bf16 halves HBM bytes (STATE.md open avenue 2)
    ("heat3d_256_bf16", "heat3d", (256, 256, 256), 100, "bfloat16", "jnp"),
    # larger grid: the round-2 XLA fusion cliff regime
    ("heat3d_512_f32", "heat3d", (512, 512, 512), 30, "float32", "jnp"),
    ("heat3d_512_bf16", "heat3d", (512, 512, 512), 30, "bfloat16", "jnp"),
    # whole-step raw Pallas kernels (round 3; ops/pallas/rawstep.py)
    ("heat3d_256_f32_raw", "heat3d", (256, 256, 256), 100, "float32", "raw"),
    ("heat3d_512_f32_raw", "heat3d", (512, 512, 512), 30, "float32", "raw"),
    ("heat3d27_256_f32_raw", "heat3d27", (256, 256, 256), 50, "float32",
     "raw"),
    ("heat3d27_512_f32_raw", "heat3d27", (512, 512, 512), 20, "float32",
     "raw"),
    ("heat3d4th_256_f32_raw", "heat3d4th", (256, 256, 256), 50, "float32",
     "raw"),
    ("wave3d_256_f32_raw", "wave3d", (256, 256, 256), 50, "float32", "raw"),
    ("wave3d_512_f32_raw", "wave3d", (512, 512, 512), 20, "float32", "raw"),
    # temporal blocking: k real steps per HBM pass (ops/pallas/fused.py);
    # the CLI's auto path for heat3d
    ("heat3d_256_f32_fused4", "heat3d", (256, 256, 256), 25, "float32",
     "fused4"),
    ("heat3d_512_f32_fused4", "heat3d", (512, 512, 512), 10, "float32",
     "fused4"),
    # pad-free 9-block kernel (round 4): same k, no pad transient — does
    # dropping the pad's ~2 HBM passes beat the extra window redundancy?
    ("heat3d_256_f32_padfree4", "heat3d", (256, 256, 256), 25, "float32",
     "padfree4"),
    ("heat3d_512_f32_padfree4", "heat3d", (512, 512, 512), 10, "float32",
     "padfree4"),
    # deeper temporal blocking (fori_loop lowering): k=8/16 multiply the
    # per-pass amortization — the VERDICT-5 ceiling probe
    ("heat3d_512_f32_fused8", "heat3d", (512, 512, 512), 6, "float32",
     "fused8"),
    ("heat3d_512_f32_padfree8", "heat3d", (512, 512, 512), 6, "float32",
     "padfree8"),
    ("heat3d_512_f32_fused16", "heat3d", (512, 512, 512), 3, "float32",
     "fused16"),
    ("heat3d_512_bf16_fused4", "heat3d", (512, 512, 512), 10, "bfloat16",
     "fused4"),
    # bf16 temporal blocking needs k=8 (sublane 16); padfree variant too
    ("heat3d_256_bf16_padfree8", "heat3d", (256, 256, 256), 13, "bfloat16",
     "padfree8"),
    ("heat3d_512_bf16_padfree8", "heat3d", (512, 512, 512), 6, "bfloat16",
     "padfree8"),
    # bf16 needs k=8: tail-block sublane alignment is 16 for 2-byte dtypes
    # (fused._sublane) — k=4's 8-row tails were the round-3 bf16 compile
    # failure; k=4 now correctly reports untileable.  BUT k=8 bf16 HANGS
    # the Mosaic compile even when aligned (heat3d_256_bf16_fused8 hit the
    # 1200 s subprocess budget on 2026-07-30; the kill risks wedging the
    # tunnel) — so bf16 temporal blocking stays OFF the campaign until the
    # compile hang is bisected (smaller tiles / shallower unroll).
    # ("heat3d_256_bf16_fused8", "heat3d", (256, 256, 256), 13, "bfloat16",
    #  "fused8"),
    # fused families (round 3: generalized to 27-point, halo-2, two-field)
    ("heat3d27_256_f32_fused4", "heat3d27", (256, 256, 256), 15, "float32",
     "fused4"),
    ("heat3d27_512_f32_fused4", "heat3d27", (512, 512, 512), 8, "float32",
     "fused4"),
    ("heat3d4th_256_f32_fused2", "heat3d4th", (256, 256, 256), 20, "float32",
     "fused2"),
    ("wave3d_256_f32_fused4", "wave3d", (256, 256, 256), 15, "float32",
     "fused4"),
    ("wave3d_512_f32_fused4", "wave3d", (512, 512, 512), 8, "float32",
     "fused4"),
    ("wave3d_512_f32_padfree4", "wave3d", (512, 512, 512), 8, "float32",
     "padfree4"),
    ("heat3d27_512_f32_padfree4", "heat3d27", (512, 512, 512), 8, "float32",
     "padfree4"),
    # 1024^3: the largest single-chip grids (bf16 2.1 GiB / f32 4.3 GiB per
    # buffer — the closest single-chip proxy for the 4096^3 north star);
    # jnp vs raw vs fused
    # the pad-free kernel is the designed 1024^3 path: two state buffers
    # only (8.6 GiB f32 / 4.3 GiB bf16), no pad transient
    ("heat3d_1024_f32_padfree4", "heat3d", (1024, 1024, 1024), 4, "float32",
     "padfree4"),
    ("heat3d_1024_bf16_padfree8", "heat3d", (1024, 1024, 1024), 4,
     "bfloat16", "padfree8"),
    ("heat3d_1024_bf16", "heat3d", (1024, 1024, 1024), 8, "bfloat16", "jnp"),
    ("heat3d_1024_bf16_raw", "heat3d", (1024, 1024, 1024), 8, "bfloat16",
     "raw"),
    ("heat3d_1024_bf16_fused4", "heat3d", (1024, 1024, 1024), 4, "bfloat16",
     "fused4"),
    ("heat3d_1024_f32_raw", "heat3d", (1024, 1024, 1024), 6, "float32",
     "raw"),
    ("heat3d_1024_f32_fused4", "heat3d", (1024, 1024, 1024), 4, "float32",
     "fused4"),
    # transport + reaction families: raw kernel vs jnp
    # harness calibration: pure 1R+1W elementwise scan (GB/s anchor)
    ("copy_256_f32", None, (256, 256, 256), 100, "float32", "copy"),
    ("copy_512_f32", None, (512, 512, 512), 30, "float32", "copy"),
    ("advect3d_256_f32_jnp", "advect3d", (256, 256, 256), 50, "float32",
     "jnp"),
    # cross-check at a different scan length: the 150 Gcells/s reading
    # implies >1.2 TB/s effective HBM traffic (1R+1W at 4B) — above v5e's
    # physical peak; verify it isn't an N-vs-4N differencing artifact
    ("advect3d_256_f32_jnp_n150", "advect3d", (256, 256, 256), 150,
     "float32", "jnp"),
    ("advect3d_512_f32_jnp", "advect3d", (512, 512, 512), 15, "float32",
     "jnp"),
    ("advect3d_256_f32_fused4", "advect3d", (256, 256, 256), 13, "float32",
     "fused4"),
    ("advect3d_512_f32_fused4", "advect3d", (512, 512, 512), 6, "float32",
     "fused4"),
    ("advect3d_256_f32_raw", "advect3d", (256, 256, 256), 50, "float32",
     "raw"),
    ("grayscott3d_256_f32_jnp", "grayscott3d", (256, 256, 256), 30,
     "float32", "jnp"),
    ("grayscott3d_256_f32_raw", "grayscott3d", (256, 256, 256), 30,
     "float32", "raw"),
    ("grayscott3d_256_f32_fused4", "grayscott3d", (256, 256, 256), 10,
     "float32", "fused4"),
    ("grayscott3d_512_f32_fused4", "grayscott3d", (512, 512, 512), 5,
     "float32", "fused4"),
    # jnp references for the 27-point / 13-point / wave families
    ("heat3d27_256_f32_jnp", "heat3d27", (256, 256, 256), 50, "float32", "jnp"),
    ("heat3d4th_256_f32_jnp", "heat3d4th", (256, 256, 256), 50, "float32",
     "jnp"),
    ("heat3d27_256_bf16_jnp", "heat3d27", (256, 256, 256), 50, "bfloat16",
     "jnp"),
    # large-grid jnp references for the 27-point / 4th-order families (the
    # cliff regime: does XLA's fusion collapse like heat3d's 86->17.6?)
    ("heat3d27_512_f32_jnp", "heat3d27", (512, 512, 512), 15, "float32",
     "jnp"),
    ("heat3d4th_512_f32_jnp", "heat3d4th", (512, 512, 512), 15, "float32",
     "jnp"),
    ("heat3d4th_512_f32_fused2", "heat3d4th", (512, 512, 512), 8, "float32",
     "fused2"),
    # halo-2 at k=2 only amortizes 2 steps/pass; k=4 (margin 8) trades more
    # overlap redundancy for 2x the amortization
    ("heat3d4th_256_f32_fused4", "heat3d4th", (256, 256, 256), 12, "float32",
     "fused4"),
    # two-field wave (BASELINE config 5 family), fp32 vs bf16
    ("wave3d_256_f32", "wave3d", (256, 256, 256), 50, "float32", "jnp"),
    ("wave3d_256_bf16", "wave3d", (256, 256, 256), 50, "bfloat16", "jnp"),
    ("wave3d_512_bf16", "wave3d", (512, 512, 512), 20, "bfloat16", "jnp"),
    # int32 GoL throughput (bit-exact family)
    ("life_2048_i32", "life", (2048, 2048), 200, None, "jnp"),
    # whole-grid VMEM temporal blocking: 2D state fits VMEM entirely, so k
    # steps cost ONE HBM round-trip (ops/pallas/fullgrid.py); k=16/32 are
    # compute-bound probes of the VPU ceiling
    ("life_2048_i32_full16", "life", (2048, 2048), 30, None, "full16"),
    ("life_1024_i32_full32", "life", (1024, 1024), 30, None, "full32"),
    ("heat2d_512_f32_full32", "heat2d", (512, 512), 40, "float32", "full32"),
    ("heat2d_2048_f32_full16", "heat2d", (2048, 2048), 20, "float32",
     "full16"),
    ("wave2d_1024_f32_full16", "wave2d", (1024, 1024), 20, "float32",
     "full16"),
    ("grayscott2d_1024_f32_full16", "grayscott2d", (1024, 1024), 15,
     "float32", "full16"),
    ("sor2d_1024_f32_jnp", "sor2d", (1024, 1024), 100, "float32", "jnp"),
    ("sor2d_1024_f32_full16", "sor2d", (1024, 1024), 15, "float32",
     "full16"),
    # 3D red-black SOR: 2 half-sweeps/step (phase-aware fused margins)
    ("sor3d_256_f32_jnp", "sor3d", (256, 256, 256), 30, "float32", "jnp"),
    ("sor3d_256_f32_fused4", "sor3d", (256, 256, 256), 10, "float32",
     "fused4"),
    # compute_fn z-chunk kernel inside the pad step (M1 kernel, for the
    # record: measured below both jnp and raw — kept as the regression probe
    # for the pad-based pallas integration)
    ("heat3d_256_f32_pallas", "heat3d", (256, 256, 256), 100, "float32",
     "pallas"),
    # LAST on purpose: bf16 k=8 (sublane-16 alignment) hung its unrolled
    # Mosaic compile; k>4 now lowers as a fori_loop (constant program
    # size).  If this still hangs it costs one 1200 s subprocess at the
    # very end of the campaign, nothing else.
    ("heat3d_256_bf16_fused8", "heat3d", (256, 256, 256), 13, "bfloat16",
     "fused8"),
]


# Bumped whenever kernel-builder code changes in a way that can turn a
# previously "untileable" config tileable (new lowering, relaxed alignment
# gate, new kernel variant).  Cached untileable declines from an older
# builder are retried instead of skipped — tileability is a property of the
# CODE, not the config (round-3 advisor finding).
BUILDER_REV = 4


def _measure_one(out_path, label, name, grid, steps, dtype, compute):
    """Measure one config and merge its record into ``out_path``."""
    backend = jax.default_backend()
    t0 = time.time()
    try:
        rec = measure(name, grid, steps, dtype=dtype, compute=compute)
    except Exception as e:  # noqa: BLE001 — record & continue campaign
        msg = f"{type(e).__name__}: {e}"
        if len(msg) > 1200:
            # Mosaic/axon failures bury the real error under proxy log
            # noise; the diagnostic line is near the END of the message.
            msg = msg[:400] + " ...[snip]... " + msg[-800:]
        rec = {"error": msg}
    rec.update({"stencil": name, "grid": list(grid), "dtype": dtype,
                "compute": compute, "backend": backend,
                "builder_rev": BUILDER_REV,
                "wall_s": round(time.time() - t0, 1),
                "measured_at": time.time()})
    results = {}
    if os.path.exists(out_path):
        with open(out_path) as fh:
            results = json.load(fh)
    results[label] = rec
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, out_path)
    print(f"[measure] {label}: {rec}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results_r04.json"))
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--in-process", action="store_true",
                    help="measure in this process instead of one subprocess "
                         "per config (an OOM then poisons later configs)")
    args = ap.parse_args()

    known = {label for label, *_ in CONFIGS}
    unknown = set(args.only or ()) - known
    if unknown:
        ap.error(f"unknown --only labels {sorted(unknown)}; "
                 f"choose from {sorted(known)}")

    default_out = ap.get_default("out")
    if args.out == default_out and not os.path.exists(args.out):
        # Seed the round-4 table from round 3 (default out path ONLY — a
        # user-chosen --out means a deliberately fresh campaign): successful
        # measurements carry over (their measured_at stamps keep
        # provenance); errored labels retry below.
        prev = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "results_r03.json")
        if os.path.exists(prev):
            import shutil

            shutil.copy(prev, args.out)

    results = {}
    if os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)

    consecutive_timeouts = 0
    for label, name, grid, steps, dtype, compute in CONFIGS:
        if args.only and label not in args.only:
            continue
        cached = results.get(label)
        # Skip successes AND deterministic structural declines ("untileable"
        # is a pure-Python ValueError, identical on every run) — only
        # transient failures (tunnel/RPC/OOM) are retried.  An untileable
        # decline recorded by an OLDER builder revision is retried too:
        # kernel-builder changes (new lowerings, relaxed alignment gates)
        # can make it tileable (round-3 advisor finding).
        if cached and not args.only and (
                "error" not in cached
                or ("untileable" in cached.get("error", "")
                    and cached.get("builder_rev") == BUILDER_REV)):
            print(f"[measure] {label}: cached, skip", file=sys.stderr)
            continue
        if args.in_process or args.only:
            _measure_one(args.out, label, name, grid, steps, dtype, compute)
        else:
            # Subprocess isolation: a RESOURCE_EXHAUSTED on one config must
            # not leave the TPU arena poisoned for every config after it
            # (observed in the round-3 campaign: a 1024^3 OOM turned the
            # rest of the matrix into cascade failures).
            import subprocess

            try:
                p = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--only", label, "--out", os.path.abspath(args.out)],
                    cwd=os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))),
                    timeout=1200,
                )
                if p.returncode != 0:
                    print(f"[measure] {label}: subprocess rc={p.returncode}",
                          file=sys.stderr)
                consecutive_timeouts = 0
            except subprocess.TimeoutExpired:
                # a wedged config must cost only itself, not the campaign
                print(f"[measure] {label}: subprocess timeout (1200s), "
                      "skipping", file=sys.stderr)
                consecutive_timeouts += 1
                if consecutive_timeouts >= 2:
                    # Two configs in a row hanging = the tunnel itself is
                    # wedged (recovery is passive and takes hours —
                    # docs/STATE.md); paying 1200s per remaining config
                    # would burn the whole campaign for nothing.
                    print("[measure] 2 consecutive timeouts — tunnel looks "
                          "wedged, aborting campaign (rerun to resume)",
                          file=sys.stderr)
                    break

    if not args.only and os.path.exists(args.out):
        with open(args.out) as fh:
            print(fh.read())


if __name__ == "__main__":
    main()
