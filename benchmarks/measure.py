"""Single-chip measurement campaign for the BASELINE.md perf table.

Runs the full config matrix on the real TPU and appends each result to
``benchmarks/results_r05.json`` IMMEDIATELY after it is measured, so a
wedged tunnel mid-campaign loses only the in-flight config.  Errored
configs are retried on the next invocation (only successful records are
skip-cached), so a transient tunnel failure heals on re-run.

Timing method (same as bench.py): scan N steps and 4N steps, take the
difference / 3N — cancels the ~66 ms tunnel dispatch + readback overhead
(docs/STATE.md "Infra gotchas").

Usage:  python benchmarks/measure.py [--out FILE] [--only NAME ...]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas import has_pallas_kernel, make_pallas_compute


def _fence(fields) -> float:
    # Actual scalar read: the only reliable completion fence on the tunneled
    # backend (block_until_ready can return early — docs/STATE.md).
    return float(jnp.sum(fields[0].astype(jnp.float32)))


def _parse_kspec(spec):
    """``"4"`` -> (4, None); ``"4@16x16"`` -> (4, (16, 16)).

    Explicit tiles are the compile-complexity hedge: the auto-picked
    (64, 32) padfree window at 512^3 hung the Mosaic remote compile past
    the subprocess budget (2026-07-31, heat3d_512_f32_padfree4), and the
    kill wedged the tunnel — smaller explicit windows compile a strictly
    smaller program for the same kernel class.
    """
    if "@" in spec:
        k, t = spec.split("@", 1)
        # 2-tuple (bz, by) for the tiled/padfree kernels; the streaming
        # kernel also accepts a 3rd x-window extent (streamK@BZxBYxBX).
        # Arity is validated HERE so a malformed spec fails at the input
        # boundary, not as an unpack error deep in a kernel builder.
        tiles = tuple(int(v) for v in t.split("x"))
        if len(tiles) not in (2, 3):
            raise ValueError(f"tile spec {t!r}: want BZxBY or BZxBYxBX")
        return int(k), tiles
    return int(spec), None


def _parse_tune(spec):
    """Strip a trailing ``_tuneN`` token: ``"4_shard_tune2"`` ->
    ("4_shard", 2); absent -> 0.

    The kernel-variant sweep label family (Tier-D13, ISSUE 16): N is a
    1-BASED index into the autotuner's per-family campaign order
    (``policy.autotune.STREAM_SWEEP`` / ``RDMA_SWEEP``, append-only),
    resolved through :func:`policy.autotune.tune_variant` — so the
    labels stay stable while the registry grows, and the A/B against
    the same-shape default-constant row prices exactly one swept
    constant set."""
    if "_tune" not in spec:
        return spec, 0
    head, _, num = spec.rpartition("_tune")
    if not num.isdigit():
        raise ValueError(f"malformed _tune token in spec {spec!r}")
    return head, int(num)


def _parse_ens(spec):
    """Strip an ``_ensN`` token: ``"4_ens8"`` -> ("4", 8); absent -> 0.

    The batched-engine label family (round 15): N members advance
    through ONE compiled batched step and the row reports AGGREGATE
    Mcells/s across members — the A/B against the single-sim row with
    the same kernel class prices the per-pass fixed-cost amortization.
    """
    if "_ens" not in spec:
        return spec, 0
    head, _, tail = spec.partition("_ens")
    num = ""
    while tail and tail[0].isdigit():
        num, tail = num + tail[0], tail[1:]
    if not num:
        raise ValueError(f"malformed _ens token in spec {spec!r}")
    return head + tail, int(num)


def measure(name, grid, steps, dtype=None, compute="jnp", reps=3,
            params=None):
    """compute: jnp | pallas (compute_fn inside the pad step) |
    raw (whole-step raw kernel) | fusedK (3D windowed temporal blocking,
    K steps/pass; ``fusedK@BZxBY`` pins explicit tiles) | fullK (2D
    whole-grid-in-VMEM temporal blocking) | shfusedK / overlapK (sharded
    fused step over a z-only mesh of ALL devices, K steps per width-m
    exchange — overlapK adds the communication-overlapped interior/
    boundary split; needs >= 2 devices; a ``_meshZxY`` suffix pins a
    2-axis (Z, Y, 1) mesh instead — the two-axis pad-free A/B against
    the z-ring, needs Z*Y devices) | pipeK / pipeK_meshZxY (overlapK
    PLUS the cross-pass pipelined exchange: the slab-carry scan issues
    pass i+1's exchange from pass i's shell outputs, a full interior
    pass ahead of its consumer; forced pad-free on BOTH mesh families
    so the A/B against overlapK_* prices the pipeline, not a kind
    change) | streamK_shard / streamK_meshZxY
    (the STREAMING kernel sharded: z-only mesh of all devices /
    a pinned 2-axis mesh via the round-8 y-slab+corner splice — the
    kind x mesh A/B rows; an ``_ensN`` token — ``streamK_ensN_shard``,
    ``streamK_ensN_meshZxY``, also on shfused/overlap and unsharded
    stream specs — batches N members through ONE compiled step and
    reports AGGREGATE Mcells/s across members, the round-15 ensemble
    A/B) | rdmaK / rdmaK_meshZxY (the sharded
    STREAMING kernel with the IN-KERNEL remote-DMA exchange,
    stepper exchange='rdma': boundary slabs ride double-buffered VMEM
    rings into the neighbor via make_async_remote_copy, zero XLA
    ppermute in the step — the A/B against streamK_shard /
    streamK_meshZxY prices the exchange transport, same kernel class
    on both rows; a trailing ``_tuneN`` token on sharded stream and
    rdma specs — ``streamK_shard_tuneN``, ``rdmaK_tuneN`` — runs the
    same step under the autotuner registry's Nth campaign variant for
    the family (policy/autotune.py, Tier-D13): bit-exact schedule
    sweeps, keyed ``|var:<id>`` in the ledger) | grp2 / grp2het (the
    COUPLED 2-group split, parallel/groups.py: the device slice
    partitioned into two contiguous mesh groups coupled at interface
    ghost bands, each group running the unmodified sharded stepper on
    its own sub-mesh.  grp2 = same-physics equal split — the A/B
    against the monolithic sharded row prices exactly the host-
    orchestrated coupling; grp2het = the MPMD row, the named op
    2x-refined over the first z quarter plus a base-resolution heat3d
    far-field, reporting aggregate OWNED-cell Mcells/s) | copy
    (harness-calibration 1R+1W elementwise scan).
    """
    kw = dict(params or {})
    if dtype is not None:
        kw["dtype"] = dtype
    step_unit = 1
    if compute == "copy":
        # Harness calibration: a pure 1R+1W elementwise scan.  Converts to
        # GB/s as cells * 2 * itemsize / t — an absolute HBM-bandwidth
        # anchor for sanity-checking stencil Gcells/s numbers against the
        # roofline (a stencil can't beat this by more than its fusion
        # saves).
        dt = jnp.dtype(dtype or "float32")
        c = jnp.asarray(1.000001, dt)

        def step(fields):
            return (fields[0] * c,)

        mk = lambda: (jnp.zeros(grid, dt),)  # noqa: E731
        return _time_scan(step, mk, grid, steps, reps, 1)
    st = make_stencil(name, **kw)
    if compute == "raw":
        from mpi_cuda_process_tpu.ops.pallas.rawstep import make_raw_step
        step = make_raw_step(st, grid)  # interpret mode off-TPU (smoke)
        if step is None:
            raise ValueError(f"no raw step for {name} on {grid}")
    elif compute.startswith("padfree"):
        # pad-free 9-block raw-grid temporal blocking (no pad transient)
        from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step
        step_unit, tiles = _parse_kspec(compute[len("padfree"):])
        if tiles is not None and len(tiles) != 2:
            raise ValueError("tiled kernels take 2 tile extents (BZxBY)")
        step = make_fused_step(st, grid, step_unit, tiles=tiles,
                               padfree=True)
        if step is None:
            raise ValueError(f"untileable padfree k={step_unit} for {grid}")
    elif compute.startswith("stream"):
        # sliding-window manual-DMA temporal blocking: every input plane
        # loaded ONCE per k-step pass (ops/pallas/streamfused.py).
        # ``streamK_shard`` runs it SHARDED over a z-only mesh of all
        # devices (slab operands); ``streamK_meshZxY`` pins a 2-axis
        # (Z, Y, 1) mesh — the round-8 kernel class (y-slab + corner
        # operands spliced into the sliding window), the A/B against the
        # z-ring for the lowest-traffic kind.
        spec = compute[len("stream"):]
        spec, tune = _parse_tune(spec)
        mesh_zy = shard_all = None
        if "_mesh" in spec:
            spec, meshspec = spec.split("_mesh", 1)
            mz, my = meshspec.split("x", 1)
            mesh_zy = (int(mz), int(my))
        elif spec.endswith("_shard"):
            spec, shard_all = spec[:-len("_shard")], True
        spec, ens = _parse_ens(spec)
        step_unit, tiles = _parse_kspec(spec)
        variant = None
        if tune:
            if not (mesh_zy or shard_all):
                raise ValueError(
                    "_tune labels are sharded-only (the variant plumbing "
                    "rides make_sharded_fused_step)")
            if tiles is not None:
                raise ValueError(
                    "_tune labels take no tile spec (the variant IS the "
                    "tile geometry)")
            from mpi_cuda_process_tpu.policy.autotune import tune_variant
            variant = tune_variant("stream", tune)
        if mesh_zy or shard_all:
            if tiles is not None:
                raise ValueError("sharded stream labels take no tile spec")
            from mpi_cuda_process_tpu import make_mesh, shard_fields
            from mpi_cuda_process_tpu.parallel.stepper import (
                make_sharded_fused_step,
            )

            n_dev = len(jax.devices())
            need = mesh_zy[0] * mesh_zy[1] if mesh_zy else 2
            if n_dev < need:
                # environmental, not structural: retried on every run
                raise ValueError(
                    f"sharded stream labels need >= {need} devices "
                    f"(have {n_dev})")
            mesh = make_mesh((mesh_zy[0], mesh_zy[1], 1) if mesh_zy
                             else (n_dev, 1, 1))
            step = make_sharded_fused_step(st, mesh, grid, step_unit,
                                           kind="stream", ensemble=ens,
                                           variant=variant)
            if step is None:
                raise ValueError(
                    f"untileable sharded stream k={step_unit} for {grid} "
                    f"on mesh {tuple(mesh.shape.values())}"
                    + (f" under variant {variant.id}" if variant else ""))
            if not str(getattr(step, "_padfree_kind", "")).startswith(
                    "stream"):
                raise ValueError(
                    "sharded stream label did not build the streaming "
                    f"kernel (got {getattr(step, '_padfree_kind', None)!r})"
                    " — must not price a different kernel under this "
                    "label")
            if variant and getattr(step, "_kernel_variant", "") \
                    != variant.id:
                raise ValueError(
                    "_tune label did not build the swept variant (got "
                    f"{getattr(step, '_kernel_variant', None)!r}, want "
                    f"{variant.id!r}) — must not price the default "
                    "constants under a variant label")
            if ens and getattr(step, "_ensemble", 0) != ens:
                raise ValueError(
                    "ens label did not build the batched step — must "
                    "not price a single-sim step under an ens label")
            mk = lambda: shard_fields(  # noqa: E731
                init_state(st, grid, kind="auto", ensemble=ens), mesh,
                st.ndim, ensemble=bool(ens))
            return _time_scan(step, mk, grid, steps, reps, step_unit,
                              members=ens)
        from mpi_cuda_process_tpu.ops.pallas.streamfused import (
            make_stream_fused_step,
        )
        step = make_stream_fused_step(st, grid, step_unit, tiles=tiles,
                                      batch=ens)
        if step is None:
            raise ValueError(f"untileable stream k={step_unit} for {grid}")
        if ens:
            mk = lambda: init_state(st, grid, kind="auto",  # noqa: E731
                                    ensemble=ens)
            return _time_scan(step, mk, grid, steps, reps, step_unit,
                              members=ens)
    elif compute.startswith("rdma"):
        # sharded STREAMING kernel with the in-kernel remote-DMA
        # exchange (stepper exchange="rdma"): same kernel class as the
        # streamK_shard/_mesh rows, only the transport changes — the
        # A/B pair prices ppermute-on-HBM-slabs vs device-initiated
        # VMEM-ring RDMA.  The built step must really carry rdma (and
        # the streaming kernel) or the label refuses: a transport
        # fallback must never be priced under this label.
        from mpi_cuda_process_tpu import make_mesh, shard_fields
        from mpi_cuda_process_tpu.parallel.stepper import (
            make_sharded_fused_step,
        )

        spec = compute[len("rdma"):]
        spec, tune = _parse_tune(spec)
        mesh_zy = None
        if "_mesh" in spec:
            spec, meshspec = spec.split("_mesh", 1)
            mz, my = meshspec.split("x", 1)
            mesh_zy = (int(mz), int(my))
        step_unit, tiles = _parse_kspec(spec)
        if tiles is not None:
            raise ValueError("rdma labels take no tile spec")
        variant = None
        if tune:
            from mpi_cuda_process_tpu.policy.autotune import tune_variant
            variant = tune_variant("rdma", tune)
        n_dev = len(jax.devices())
        need = mesh_zy[0] * mesh_zy[1] if mesh_zy else 2
        if n_dev < need:
            # environmental, not structural: retried on every run
            raise ValueError(
                f"rdma labels need >= {need} devices (have {n_dev})")
        mesh = make_mesh((mesh_zy[0], mesh_zy[1], 1) if mesh_zy
                         else (n_dev, 1, 1))
        step = make_sharded_fused_step(st, mesh, grid, step_unit,
                                       kind="stream", exchange="rdma",
                                       variant=variant)
        if step is None:
            raise ValueError(
                f"untileable rdma stream k={step_unit} for {grid} on "
                f"mesh {tuple(mesh.shape.values())}"
                + (f" under variant {variant.id}" if variant else ""))
        if getattr(step, "_exchange", None) != "rdma" or not str(
                getattr(step, "_padfree_kind", "")).startswith("stream"):
            raise ValueError(
                "rdma label did not build the remote-DMA streaming "
                f"step (kind={getattr(step, '_padfree_kind', None)!r}, "
                f"exchange={getattr(step, '_exchange', None)!r}) — "
                "must not price a different path under this label")
        if getattr(step, "_rdma_backend", None) != "pallas-rdma":
            # the interpret-emulated path is a CPU test vehicle, never
            # a measurement — the same honesty rule as bench.py's
            # backend-tagged fallbacks
            raise ValueError(
                "rdma label built the interpret-emulated exchange "
                f"({getattr(step, '_rdma_backend', None)!r}) — a "
                "measurement row needs the compiled pallas-rdma path")
        if variant and getattr(step, "_kernel_variant", "") != variant.id:
            raise ValueError(
                "_tune label did not build the swept variant (got "
                f"{getattr(step, '_kernel_variant', None)!r}, want "
                f"{variant.id!r}) — must not price the default ring "
                "under a variant label")
        mk = lambda: shard_fields(  # noqa: E731
            init_state(st, grid, kind="auto"), mesh, st.ndim)
        return _time_scan(step, mk, grid, steps, reps, step_unit)
    elif compute.startswith("grp2"):
        # COUPLED 2-group split (parallel/groups.py, Tier-D14): two
        # contiguous device groups, each its own sub-mesh + unmodified
        # sharded stepper, coupled ONLY at the interface ghost bands.
        # The built runner must really carry >= 2 groups or the label
        # refuses: a monolithic fallback must never be priced here.
        from mpi_cuda_process_tpu.parallel import groups as groups_lib

        n_dev = len(jax.devices())
        if n_dev < 2:
            # environmental, not structural: retried on every run
            raise ValueError(
                f"grp2 labels need >= 2 devices (have {n_dev})")
        # y-sharded group meshes (mesh1xH): the ghost band makes each
        # group's local z extent odd (owned + band), which no z-sharded
        # sub-mesh divides — sharding y keeps the groups' z rows whole
        h = n_dev // 2
        m0, m1 = f":mesh1x{h}", f":mesh1x{n_dev - h}"
        transport = "device_put"
        if compute == "grp2":
            gspec = (f"{name}@0-{h - 1}{m0},"
                     f"{name}@{h}-{n_dev - 1}{m1}")
        elif compute == "grp2het":
            gspec = (f"{name}:fine@0-{h - 1}:z1/4{m0},"
                     f"heat3d:coarse@{h}-{n_dev - 1}{m1}")
        elif compute == "grp2ici":
            # round 23: the SAME equal split as grp2, bands moved as
            # ppermute rounds over the union mesh — the A/B against the
            # grp2 row prices exactly the transport swap
            transport = "collective"
            gspec = (f"{name}@0-{h - 1}{m0},"
                     f"{name}@{h}-{n_dev - 1}{m1}")
        elif compute == "grp2modes":
            # round 23: per-group execution modes — group 0 routed
            # through the overlap stepper, group 1 plain, same split as
            # grp2 so the A/B prices the mode routing alone
            gspec = (f"{name}@0-{h - 1}{m0}:overlap,"
                     f"{name}@{h}-{n_dev - 1}{m1}")
        else:
            raise ValueError(f"unknown grp2 spec {compute!r}")
        plans = groups_lib.plans_from_config(
            gspec, grid, default_dtype=dtype or "float32",
            n_devices=n_dev)
        runner = groups_lib.CoupledRunner(plans, transport=transport)
        if getattr(runner, "n_groups", 1) < 2:
            raise ValueError(
                "grp2 label built a monolithic runner (n_groups="
                f"{getattr(runner, 'n_groups', 1)}) — must not price a "
                "monolithic build under a group label")
        if transport == "collective" and \
                getattr(runner, "transport", "") != "collective":
            raise ValueError(
                "grp2ici label built the device_put transport — must "
                "not price the host path under a collective label")
        rec = _time_coupled(runner, steps, reps)
        rec.setdefault("groups", gspec)
        rec.setdefault("group_transport", transport)
        return rec
    elif compute.startswith("pipe"):
        # CROSS-PASS pipelined sharded temporal blocking: overlap split
        # + the slab-carry scan (pass i+1's exchange issued from pass
        # i's shell outputs).  Forced pad-free on the z-ring AND the
        # pinned 2-axis mesh — the pipeline rides the slab-operand
        # kinds only, and the A/B against the overlapK_* rows must
        # price the pipeline, not a silent kind change (the overlap
        # _mesh rows are forced pad-free already; the z-ring overlap
        # rows are auto — read the pair with that caveat).
        from mpi_cuda_process_tpu import make_mesh, shard_fields
        from mpi_cuda_process_tpu.parallel.stepper import (
            make_sharded_fused_step,
        )

        spec = compute[len("pipe"):]
        mesh_zy = None
        if "_mesh" in spec:
            spec, meshspec = spec.split("_mesh", 1)
            mz, my = meshspec.split("x", 1)
            mesh_zy = (int(mz), int(my))
        step_unit, tiles = _parse_kspec(spec)
        if tiles is not None:
            raise ValueError("pipelined labels take no tile spec")
        n_dev = len(jax.devices())
        need = mesh_zy[0] * mesh_zy[1] if mesh_zy else 2
        if n_dev < need:
            # environmental, not structural: retried on every run
            raise ValueError(
                f"pipelined labels need >= {need} devices (have {n_dev})")
        mesh = make_mesh((mesh_zy[0], mesh_zy[1], 1) if mesh_zy
                         else (n_dev, 1, 1))
        step = make_sharded_fused_step(st, mesh, grid, step_unit,
                                       overlap=True, padfree=True,
                                       pipeline=True)
        if step is None:
            raise ValueError(
                f"untileable pipelined k={step_unit} for {grid} on "
                f"mesh {tuple(mesh.shape.values())}")
        if not getattr(step, "_pipeline_active", False):
            raise ValueError(
                "pipelined label did not build the slab-carry scan — "
                "must not price a different schedule under this label")
        if not getattr(step, "_overlap_active", False):
            raise ValueError(
                "untileable overlap split under a pipelined label "
                "(local extent < 3m) — must not price the non-split "
                "body under this label")
        mk = lambda: shard_fields(  # noqa: E731
            init_state(st, grid, kind="auto"), mesh, st.ndim)
        # make_runner (inside _time_scan) threads the slab carry
        return _time_scan(step, mk, grid, steps, reps, step_unit)
    elif compute.startswith("overlap") or compute.startswith("shfused"):
        # sharded temporal blocking over a z-only mesh of ALL devices:
        # shfusedK = exchange-then-compute (the A row), overlapK = the
        # communication-overlapped interior/boundary split (the B row).
        # The A/B pair prices exactly the ~7%-class serial exchange gap
        # of docs/STATE.md item 6.
        from mpi_cuda_process_tpu import make_mesh, shard_fields
        from mpi_cuda_process_tpu.parallel.stepper import (
            make_sharded_fused_step,
        )

        ov = compute.startswith("overlap")
        spec = compute[len("overlap" if ov else "shfused"):]
        mesh_zy = None
        if "_mesh" in spec:
            # _meshZxY: a pinned 2-axis (Z, Y, 1) mesh — the A/B row
            # against the all-devices z-ring (surface-to-volume cuts
            # face bytes; the 2-axis pad-free kernels keep the path
            # transient-free)
            spec, meshspec = spec.split("_mesh", 1)
            mz, my = meshspec.split("x", 1)
            mesh_zy = (int(mz), int(my))
        spec, ens = _parse_ens(spec)
        step_unit, tiles = _parse_kspec(spec)
        if tiles is not None:
            raise ValueError("sharded fused labels take no tile spec")
        n_dev = len(jax.devices())
        need = mesh_zy[0] * mesh_zy[1] if mesh_zy else 2
        if n_dev < need:
            # environmental, not structural: retried on every run so the
            # first healthy multi-chip session prices these labels
            raise ValueError(
                f"sharded fused labels need >= {need} devices "
                f"(have {n_dev})")
        mesh = make_mesh((mesh_zy[0], mesh_zy[1], 1) if mesh_zy
                         else (n_dev, 1, 1))
        # 2-axis rows force the pad-free slab-operand kernels: at 512^3
        # the local block is below the auto pad-free threshold, and the
        # point of the _mesh labels is to price the NEW kernel class
        # (y-slab + corner operands) on a real chip, not the padded
        # kernel on a different topology
        step = make_sharded_fused_step(st, mesh, grid, step_unit,
                                       overlap=ov,
                                       padfree=True if mesh_zy else None,
                                       ensemble=ens)
        if mesh_zy and step is not None and \
                not str(getattr(step, "_padfree_kind", "")).startswith(
                    "yzslab"):
            raise ValueError(
                "2-axis label did not build the yz-slab pad-free kernel "
                f"(got {getattr(step, '_padfree_kind', None)!r}) — must "
                "not price a different kernel under this label")
        if step is None:
            raise ValueError(
                f"untileable sharded fused k={step_unit} for {grid} on "
                f"{n_dev} devices")
        if ov and not getattr(step, "_overlap_active", False):
            raise ValueError(
                f"untileable overlap split for {grid} on {n_dev} devices "
                "(local z < 3m) — must not price the plain step under an "
                "overlap label")
        mk = lambda: shard_fields(  # noqa: E731
            init_state(st, grid, kind="auto", ensemble=ens), mesh,
            st.ndim, ensemble=bool(ens))
        return _time_scan(step, mk, grid, steps, reps, step_unit,
                          members=ens)
    elif compute.startswith("fused"):
        from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step
        step_unit, tiles = _parse_kspec(compute[len("fused"):])
        if tiles is not None and len(tiles) != 2:
            raise ValueError("tiled kernels take 2 tile extents (BZxBY)")
        step = make_fused_step(st, grid, step_unit, tiles=tiles)
        if step is None:
            raise ValueError(f"untileable fused k={step_unit} for {grid}")
    elif compute.startswith("full"):
        # whole-grid VMEM temporal blocking (2D families)
        from mpi_cuda_process_tpu.ops.pallas.fullgrid import (
            make_fullgrid_step,
        )
        step_unit = int(compute[len("full"):])
        step = make_fullgrid_step(st, grid, step_unit)
        if step is None:
            raise ValueError(f"untileable fullgrid k={step_unit} for {grid}")
    else:
        compute_fn = None
        if compute == "pallas":
            if not has_pallas_kernel(name):
                raise ValueError(f"no pallas kernel for {name}")
            compute_fn = make_pallas_compute(st, interpret=False)
        step = make_step(st, grid, compute_fn=compute_fn)
    mk = lambda: init_state(st, grid, kind="auto")  # noqa: E731
    return _time_scan(step, mk, grid, steps, reps, step_unit)


def _time_scan(step, mk, grid, steps, reps, step_unit, members=0):
    run_a = make_runner(step, steps)
    run_b = make_runner(step, 4 * steps)
    _fence(run_a(mk()))  # compile + warm
    _fence(run_b(mk()))

    def best(run):
        b = math.inf
        for _ in range(reps):
            f = mk()
            _fence(f)
            t0 = time.perf_counter()
            _fence(run(f))
            b = min(b, time.perf_counter() - t0)
        return b

    t_a, t_b = best(run_a), best(run_b)
    from bench import NOISE_FLOOR_FRAC  # repo root is on sys.path (top)

    if t_b - t_a <= NOISE_FLOOR_FRAC * t_a:
        # t(4N) - t(N) should be ~3x t(N)'s step content; a non-positive or
        # tiny-relative delta means noise swamped the signal: report, don't
        # fabricate a plausible-looking Mcells/s from a clamped epsilon.
        return {"error": f"step time below noise floor: t_a={t_a:.4f}s "
                         f"t_b={t_b:.4f}s (timing noise; rerun)",
                "suspect": True}
    per_step = (t_b - t_a) / (3 * steps * step_unit)
    # aggregate cells: a batched row advances every member each step
    mcells = max(1, members) * math.prod(grid) / per_step / 1e6
    rec = {"ms_per_step": round(per_step * 1e3, 4),
           "mcells_per_s": round(mcells, 1)}
    if members:
        rec["ensemble"] = members
        rec["mcells_per_s_per_member"] = round(mcells / members, 1)
    return rec


def _time_coupled(runner, steps, reps):
    """Timing harness for the coupled group rounds (grp2 labels).

    Same N vs 4N differencing as ``_time_scan``, on ONE warmed runner
    (each CoupledRunner builds fresh jitted transfer closures, so a
    fresh runner per rep would re-pay tracing inside the timed region).
    The fence reads a scalar from EVERY group — the groups dispatch on
    disjoint devices as independent async streams, and fencing one
    would leave the others' work unmeasured.  Mcells/s counts OWNED
    cell updates only, aggregated across groups: band rows are coupling
    overhead, never throughput, so the hetero row's number is the
    actual cell-update rate the A/B compares against the monolithic
    row.
    """
    cells = sum(p.owned_cells for p in runner.plans)

    def rounds(n):
        for f in runner.fields:
            _fence(f)
        t0 = time.perf_counter()
        runner.run(n)
        for f in runner.fields:
            _fence(f)
        return time.perf_counter() - t0

    rounds(1)  # compile + warm every group program and transfer fn

    def best(n):
        b = math.inf
        for _ in range(reps):
            b = min(b, rounds(n))
        return b

    t_a, t_b = best(steps), best(4 * steps)
    from bench import NOISE_FLOOR_FRAC  # repo root is on sys.path (top)

    if t_b - t_a <= NOISE_FLOOR_FRAC * t_a:
        return {"error": f"step time below noise floor: t_a={t_a:.4f}s "
                         f"t_b={t_b:.4f}s (timing noise; rerun)",
                "suspect": True}
    per_round = (t_b - t_a) / (3 * steps)
    mcells = cells / per_round / 1e6
    return {"ms_per_step": round(per_round * 1e3, 4),
            "mcells_per_s": round(mcells, 1),
            "n_groups": runner.n_groups,
            "owned_cells_per_round": cells}


# (label, stencil, grid, steps, dtype, compute)
#
# ORDER IS EXECUTION ORDER, and it is risk-tiered (2026-07-31 lesson: the
# auto-tiled heat3d_512_f32_padfree4 compile hung, its 1200 s kill wedged
# the tunnel, and every label after it in file order was lost).  Tiers:
#   A — round-3 measured successes (cache-skipped on rerun);
#   B — safe pending: jnp references, calibration copies, fast structural
#       declines, retries of fast-failing labels;
#   C — 2D whole-grid VMEM kernels (new family on-chip, small programs);
#   D — NEW large Mosaic compiles (padfree >=512, deep k, bf16 k=8):
#       value-ordered, each gets the longer _RISKY budget, and a timeout
#       is RECORDED so a rerun never re-wedges the tunnel on the same
#       label (skip rule in main()).
CONFIGS = [
    # ── Tier A: BASELINE refresh + round-3 measured table ──
    ("heat2d_512_f32", "heat2d", (512, 512), 400, "float32", "jnp"),
    ("heat3d_256_f32", "heat3d", (256, 256, 256), 100, "float32", "jnp"),
    ("heat3d_256_bf16", "heat3d", (256, 256, 256), 100, "bfloat16", "jnp"),
    ("heat3d_512_f32", "heat3d", (512, 512, 512), 30, "float32", "jnp"),
    ("heat3d_512_bf16", "heat3d", (512, 512, 512), 30, "bfloat16", "jnp"),
    ("heat3d_256_f32_raw", "heat3d", (256, 256, 256), 100, "float32", "raw"),
    ("heat3d_512_f32_raw", "heat3d", (512, 512, 512), 30, "float32", "raw"),
    ("heat3d27_256_f32_raw", "heat3d27", (256, 256, 256), 50, "float32",
     "raw"),
    ("heat3d27_512_f32_raw", "heat3d27", (512, 512, 512), 20, "float32",
     "raw"),
    ("heat3d4th_256_f32_raw", "heat3d4th", (256, 256, 256), 50, "float32",
     "raw"),
    ("wave3d_256_f32_raw", "wave3d", (256, 256, 256), 50, "float32", "raw"),
    ("wave3d_512_f32_raw", "wave3d", (512, 512, 512), 20, "float32", "raw"),
    ("heat3d_256_f32_fused4", "heat3d", (256, 256, 256), 25, "float32",
     "fused4"),
    ("heat3d_512_f32_fused4", "heat3d", (512, 512, 512), 10, "float32",
     "fused4"),
    ("heat3d_256_f32_padfree4", "heat3d", (256, 256, 256), 25, "float32",
     "padfree4"),
    ("heat3d27_256_f32_fused4", "heat3d27", (256, 256, 256), 15, "float32",
     "fused4"),
    ("heat3d27_512_f32_fused4", "heat3d27", (512, 512, 512), 8, "float32",
     "fused4"),
    ("heat3d4th_256_f32_fused2", "heat3d4th", (256, 256, 256), 20, "float32",
     "fused2"),
    ("wave3d_256_f32_fused4", "wave3d", (256, 256, 256), 15, "float32",
     "fused4"),
    ("wave3d_512_f32_fused4", "wave3d", (512, 512, 512), 8, "float32",
     "fused4"),
    ("advect3d_256_f32_jnp", "advect3d", (256, 256, 256), 50, "float32",
     "jnp"),
    ("advect3d_256_f32_raw", "advect3d", (256, 256, 256), 50, "float32",
     "raw"),
    ("grayscott3d_256_f32_jnp", "grayscott3d", (256, 256, 256), 30,
     "float32", "jnp"),
    ("grayscott3d_256_f32_raw", "grayscott3d", (256, 256, 256), 30,
     "float32", "raw"),
    ("heat3d27_256_f32_jnp", "heat3d27", (256, 256, 256), 50, "float32",
     "jnp"),
    ("heat3d4th_256_f32_jnp", "heat3d4th", (256, 256, 256), 50, "float32",
     "jnp"),
    ("heat3d27_256_bf16_jnp", "heat3d27", (256, 256, 256), 50, "bfloat16",
     "jnp"),
    ("wave3d_256_f32", "wave3d", (256, 256, 256), 50, "float32", "jnp"),
    ("wave3d_256_bf16", "wave3d", (256, 256, 256), 50, "bfloat16", "jnp"),
    ("wave3d_512_bf16", "wave3d", (512, 512, 512), 20, "bfloat16", "jnp"),
    ("life_2048_i32", "life", (2048, 2048), 200, None, "jnp"),
    ("heat3d_256_f32_pallas", "heat3d", (256, 256, 256), 100, "float32",
     "pallas"),
    # ── Tier B: safe pending — no new Mosaic compile classes ──
    # harness calibration: pure 1R+1W elementwise scan (GB/s anchor)
    ("copy_256_f32", None, (256, 256, 256), 100, "float32", "copy"),
    ("copy_512_f32", None, (512, 512, 512), 30, "float32", "copy"),
    # advect3d 150 Gcells/s suspect resolution: different scan length +
    # larger grid (>1.2 TB/s implied traffic exceeds v5e HBM peak)
    ("advect3d_256_f32_jnp_n150", "advect3d", (256, 256, 256), 150,
     "float32", "jnp"),
    ("advect3d_512_f32_jnp", "advect3d", (512, 512, 512), 15, "float32",
     "jnp"),
    # large-grid jnp references (the cliff regime: does XLA's fusion
    # collapse like heat3d's 86->17.6?)
    ("heat3d27_512_f32_jnp", "heat3d27", (512, 512, 512), 15, "float32",
     "jnp"),
    ("heat3d4th_512_f32_jnp", "heat3d4th", (512, 512, 512), 15, "float32",
     "jnp"),
    ("sor2d_1024_f32_jnp", "sor2d", (1024, 1024), 100, "float32", "jnp"),
    ("sor3d_256_f32_jnp", "sor3d", (256, 256, 256), 30, "float32", "jnp"),
    # 1024^3 jnp/raw retries: r03 failures were FAST errors (OOM / HTTP
    # 500), not hangs; full head+tail stderr is captured this round
    ("heat3d_1024_bf16", "heat3d", (1024, 1024, 1024), 8, "bfloat16", "jnp"),
    ("heat3d_1024_bf16_raw", "heat3d", (1024, 1024, 1024), 8, "bfloat16",
     "raw"),
    ("heat3d_1024_f32_raw", "heat3d", (1024, 1024, 1024), 6, "float32",
     "raw"),
    # pure-Python structural declines (sublane misalignment) — instant
    ("heat3d_512_bf16_fused4", "heat3d", (512, 512, 512), 10, "bfloat16",
     "fused4"),
    ("heat3d_1024_bf16_fused4", "heat3d", (1024, 1024, 1024), 4, "bfloat16",
     "fused4"),
    # padded-fused-class compiles: the same builder/lowering measured on
    # chip at 256^3 AND 512^3 in round 3 (heat3d/heat3d27/wave3d fused4)
    ("advect3d_256_f32_fused4", "advect3d", (256, 256, 256), 13, "float32",
     "fused4"),
    ("advect3d_512_f32_fused4", "advect3d", (512, 512, 512), 6, "float32",
     "fused4"),
    ("grayscott3d_256_f32_fused4", "grayscott3d", (256, 256, 256), 10,
     "float32", "fused4"),
    ("grayscott3d_512_f32_fused4", "grayscott3d", (512, 512, 512), 5,
     "float32", "fused4"),
    ("sor3d_256_f32_fused4", "sor3d", (256, 256, 256), 10, "float32",
     "fused4"),
    ("heat3d4th_256_f32_fused4", "heat3d4th", (256, 256, 256), 12, "float32",
     "fused4"),
    ("heat3d4th_512_f32_fused2", "heat3d4th", (512, 512, 512), 8, "float32",
     "fused2"),
    # padded fused at 1024^3 f32: expected RESOURCE_EXHAUSTED (3x4.3 GiB
    # transient) — a fast allocation error, recorded for the table
    ("heat3d_1024_f32_fused4", "heat3d", (1024, 1024, 1024), 4, "float32",
     "fused4"),
    # ── Tier C: 2D whole-grid VMEM kernels (new family; small programs —
    # the whole grid is one VMEM block, no window assembly) ──
    ("life_2048_i32_full16", "life", (2048, 2048), 30, None, "full16"),
    ("life_1024_i32_full32", "life", (1024, 1024), 30, None, "full32"),
    ("heat2d_512_f32_full32", "heat2d", (512, 512), 40, "float32", "full32"),
    ("heat2d_2048_f32_full16", "heat2d", (2048, 2048), 20, "float32",
     "full16"),
    ("wave2d_1024_f32_full16", "wave2d", (1024, 1024), 20, "float32",
     "full16"),
    ("grayscott2d_1024_f32_full16", "grayscott2d", (1024, 1024), 15,
     "float32", "full16"),
    ("sor2d_1024_f32_full16", "sor2d", (1024, 1024), 15, "float32",
     "full16"),
    # ── Tier D: NEW large Mosaic compiles — value-ordered, _RISKY budget,
    # timeouts recorded.  A hang near the top must not cost the numbers
    # below it on a RERUN (recorded timeouts are skipped) — but a hang's
    # kill can wedge the tunnel and cost everything below it on THIS
    # pass, so the order is (a) VERDICT-r4 value rank (streams > 1024^3
    # > bf16 > padfree generality > halo-2 > deep k) and (b) the suspect
    # compile class (AUTO-tiled padfree at >=512^3, whose kill wedged
    # the tunnel on 2026-07-31) last within its group. ──
    # D1: the STREAMING kernel (ops/pallas/streamfused.py) — sliding-
    # window manual DMA, zero z read amplification: projects ~155
    # Gcells/s at 512^3 even at the 330 GB/s auto rate; decides
    # _AUTO_FUSE_KIND ("the headline question", VERDICT r4 next #2).
    # New compile class (run_scoped + make_async_copy + ANY refs at
    # scale): cheapest grid first to prove the class compiles.
    ("heat3d_256_f32_stream4", "heat3d", (256, 256, 256), 25, "float32",
     "stream4"),
    ("heat3d_512_f32_stream4", "heat3d", (512, 512, 512), 10, "float32",
     "stream4"),
    # the only bf16 k=4 temporal-blocking path (VERDICT r4 next #4)
    ("heat3d_512_bf16_stream4", "heat3d", (512, 512, 512), 10, "bfloat16",
     "stream4"),
    # config-5's family: two-field wave through the same class
    ("wave3d_512_f32_stream4", "wave3d", (512, 512, 512), 8, "float32",
     "stream4"),
    # D2: the >=1024^3 regime (VERDICT r4 next #3) — explicit (16,16)
    # tiles first (smallest window = smallest Mosaic program), then
    # stream; the AUTO-tiled padfree label LAST (the suspect class)
    ("heat3d_1024_f32_padfree4_t16", "heat3d", (1024, 1024, 1024), 4,
     "float32", "padfree4@16x16"),
    ("heat3d_1024_f32_stream4", "heat3d", (1024, 1024, 1024), 4, "float32",
     "stream4"),
    ("heat3d_1024_f32_padfree4", "heat3d", (1024, 1024, 1024), 4, "float32",
     "padfree4"),
    # D3: the bf16 story (VERDICT r4 next #4) at the proven-compile size
    # first; the fori_loop k=8 lowering is the designed fix for the
    # round-3 unrolled-compile hang
    ("heat3d_256_bf16_padfree8", "heat3d", (256, 256, 256), 13, "bfloat16",
     "padfree8"),
    ("heat3d_256_bf16_fused8", "heat3d", (256, 256, 256), 13, "bfloat16",
     "fused8"),
    ("heat3d_512_bf16_padfree8", "heat3d", (512, 512, 512), 6, "bfloat16",
     "padfree8"),
    ("heat3d_1024_bf16_padfree8", "heat3d", (1024, 1024, 1024), 4,
     "bfloat16", "padfree8"),
    # D4: padfree generality at 512^3.  The heat3d t16 hedge FIRST (it
    # discriminates the hang hypotheses in docs/STATE.md); wave/27-point
    # auto-tiled labels after it; the heat3d AUTO label last — it is the
    # exact label whose kill wedged the tunnel (skip-cached at rev
    # parity; runs again only after a BUILDER_REV bump or --only)
    ("heat3d_512_f32_padfree4_t16", "heat3d", (512, 512, 512), 10,
     "float32", "padfree4@16x16"),
    ("wave3d_512_f32_padfree4", "wave3d", (512, 512, 512), 8, "float32",
     "padfree4"),
    ("heat3d27_512_f32_padfree4", "heat3d27", (512, 512, 512), 8, "float32",
     "padfree4"),
    ("heat3d_512_f32_padfree4", "heat3d", (512, 512, 512), 10, "float32",
     "padfree4"),
    # D5: the halo-2 family (VERDICT r4 next #6): fused4 (margin 8) is a
    # NEW halo-2 k=4 compile at 512^3 — Tier D so a hang gets the long
    # budget and cannot cost the safe tiers; stream4's sublane-rounded
    # margins host wm=8
    ("heat3d4th_512_f32_fused4", "heat3d4th", (512, 512, 512), 6, "float32",
     "fused4"),
    ("heat3d4th_512_f32_stream4", "heat3d4th", (512, 512, 512), 6,
     "float32", "stream4"),
    # D6: deeper ceiling probes (k=8/16 per-pass amortization, stream8,
    # 27-point stream)
    ("heat3d_512_f32_fused8", "heat3d", (512, 512, 512), 6, "float32",
     "fused8"),
    ("heat3d_512_f32_stream8", "heat3d", (512, 512, 512), 6, "float32",
     "stream8"),
    ("heat3d27_512_f32_stream4", "heat3d27", (512, 512, 512), 8, "float32",
     "stream4"),
    ("heat3d_512_f32_padfree8", "heat3d", (512, 512, 512), 6, "float32",
     "padfree8"),
    ("heat3d_512_f32_fused16", "heat3d", (512, 512, 512), 3, "float32",
     "fused16"),
    # D7: communication-overlapped temporal blocking A/B (needs a multi-
    # chip slice; on a single chip these decline fast and retry next
    # run).  shfusedK = exchange-then-compute over a z-only mesh of all
    # devices, overlapK = the interior/boundary split — the pair prices
    # the ~7%-class serial exchange gap (docs/STATE.md item 6) that the
    # split is designed to hide.  Mesh = (n_devices, 1, 1).
    ("heat3d_512_f32_shfused4", "heat3d", (512, 512, 512), 10, "float32",
     "shfused4"),
    ("heat3d_512_f32_overlap4", "heat3d", (512, 512, 512), 10, "float32",
     "overlap4"),
    ("heat3d_512_f32_overlap8", "heat3d", (512, 512, 512), 6, "float32",
     "overlap8"),
    ("wave3d_512_f32_shfused4", "wave3d", (512, 512, 512), 8, "float32",
     "shfused4"),
    ("wave3d_512_f32_overlap4", "wave3d", (512, 512, 512), 8, "float32",
     "overlap4"),
    # D8 (round 7): TWO-AXIS decomposition A/B — the same k/grid as the
    # z-ring rows above, on a pinned 8x8x1 mesh (needs a 64-chip slice;
    # fast environmental decline + retry elsewhere).  Surface-to-volume
    # cuts face bytes ~8x vs 64x1x1 (STATE.md ICI arithmetic, item 6),
    # and the 2-axis pad-free kernels (fused.build_yzslab_padfree_call:
    # y-slab + corner operands) keep the path transient-free — these
    # rows decide whether the decomposition shape is chosen by
    # measurement instead of kernel availability.
    ("heat3d_512_f32_shfused4_mesh8x8", "heat3d", (512, 512, 512), 10,
     "float32", "shfused4_mesh8x8"),
    ("heat3d_512_f32_overlap4_mesh8x8", "heat3d", (512, 512, 512), 10,
     "float32", "overlap4_mesh8x8"),
    ("wave3d_512_f32_shfused4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "float32", "shfused4_mesh8x8"),
    ("wave3d_512_f32_overlap4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "float32", "overlap4_mesh8x8"),
    # D9 (round 8): STREAMING x MESH — the sharded streaming kernel on
    # the z-ring (all devices) vs the pinned balanced 8x8x1 mesh (the
    # new 2-axis y-slab+corner splice class, needs a 64-chip slice;
    # fast environmental decline + retry elsewhere).  With D8 these
    # rows complete the kind x mesh measurement matrix: every kernel
    # class now exists on both mesh families, so decomposition shape
    # is chosen purely by these numbers.
    ("heat3d_512_f32_stream4_shard", "heat3d", (512, 512, 512), 10,
     "float32", "stream4_shard"),
    ("heat3d_512_f32_stream4_mesh8x8", "heat3d", (512, 512, 512), 10,
     "float32", "stream4_mesh8x8"),
    ("wave3d_512_f32_stream4_shard", "wave3d", (512, 512, 512), 8,
     "float32", "stream4_shard"),
    ("wave3d_512_f32_stream4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "float32", "stream4_mesh8x8"),
    # the bf16 k=4 story on the balanced mesh (stream is the only k=4
    # bf16 temporal-blocking path; the 2-axis tiled kernels need k=8)
    ("wave3d_512_bf16_stream4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "bfloat16", "stream4_mesh8x8"),
    # D10 (round 9): CROSS-PASS PIPELINED exchange A/B — the slab-carry
    # scan (pass i+1's exchange issued from pass i's shell outputs, one
    # full interior pass of hiding) against the round-6 overlap rows on
    # both mesh families.  Forced pad-free on the z-ring too (the
    # pipeline rides the slab-operand kinds), so read the z-ring pair
    # with the kind caveat in measure()'s docstring; the _mesh8x8 pair
    # is kind-clean (both forced pad-free).  The strong-scaling regime
    # (small per-chip blocks, interior shrinking faster than faces) is
    # where the gap should open — these 512^3 rows on a big slice are
    # exactly that regime.
    ("heat3d_512_f32_pipe4", "heat3d", (512, 512, 512), 10, "float32",
     "pipe4"),
    ("heat3d_512_f32_pipe4_mesh8x8", "heat3d", (512, 512, 512), 10,
     "float32", "pipe4_mesh8x8"),
    ("wave3d_512_f32_pipe4", "wave3d", (512, 512, 512), 8, "float32",
     "pipe4"),
    ("wave3d_512_f32_pipe4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "float32", "pipe4_mesh8x8"),
    # D11 (round 12): IN-KERNEL REMOTE-DMA exchange A/B — the sharded
    # streaming kernel with exchange='rdma' (boundary slabs pushed into
    # the neighbor's VMEM rings by make_async_remote_copy; zero XLA
    # ppermute, no HBM slab transient) against the round-8
    # streamK_shard/_mesh8x8 rows: SAME kernel class both sides, only
    # the transport differs, so the pair prices exactly the exchange
    # path.  New compile class (collective pallas_call: remote DMA +
    # barrier/credit semaphores) — cheapest first to prove it compiles;
    # needs >= 2 devices (z-ring) / a 64-chip slice (_mesh8x8), fast
    # environmental decline + retry elsewhere.
    ("heat3d_512_f32_rdma4", "heat3d", (512, 512, 512), 10, "float32",
     "rdma4"),
    ("wave3d_512_f32_rdma4", "wave3d", (512, 512, 512), 8, "float32",
     "rdma4"),
    ("heat3d_512_f32_rdma4_mesh8x8", "heat3d", (512, 512, 512), 10,
     "float32", "rdma4_mesh8x8"),
    ("wave3d_512_f32_rdma4_mesh8x8", "wave3d", (512, 512, 512), 8,
     "float32", "rdma4_mesh8x8"),
    # ── Tier D12: batched ensemble engine (round 15) — *_ens8 rows:
    # 8 members advance through ONE compiled batched streaming step
    # (vmap folds the member axis into each exchange operand; one batch
    # grid dimension per kernel); the row reports AGGREGATE Mcells/s.
    # A/B against the single-sim stream4_shard/_mesh8x8 rows — same
    # kernel class, only the batching changes — prices the per-pass
    # fixed-cost amortization the ensemble engine claims.  The ledger
    # keys these rows by ensemble size (obs/ledger.baseline_key), so
    # an ens=8 aggregate can never baseline a single-sim row.
    ("heat3d_512_f32_stream4_ens8_shard", "heat3d", (512, 512, 512), 10,
     "float32", "stream4_ens8_shard"),
    ("wave3d_512_f32_stream4_ens8_shard", "wave3d", (512, 512, 512), 8,
     "float32", "stream4_ens8_shard"),
    ("heat3d_512_f32_stream4_ens8_mesh8x8", "heat3d", (512, 512, 512),
     10, "float32", "stream4_ens8_mesh8x8"),
    ("wave3d_512_f32_stream4_ens8_mesh8x8", "wave3d", (512, 512, 512),
     8, "float32", "stream4_ens8_mesh8x8"),
    # ── Tier D13: KERNEL-VARIANT sweeps (round 16, policy/autotune.py)
    # — *_tuneN rows: the same sharded streaming / rdma steps as the
    # D8/D11 rows, but under the autotuner registry's Nth campaign
    # variant for the family (1-based into STREAM_SWEEP / RDMA_SWEEP:
    # stream tune1=bz16y16 tune2=bz8y8; rdma tune1=ring3 tune2=ring4).
    # A/B against the same-shape default-constant row prices exactly
    # one swept constant set; the ledger keys these rows |var:<id>
    # (obs/ledger.baseline_key), so a variant row can never baseline
    # the default.  Each variant is bit-exact vs the default kernel
    # (pinned in tests/test_autotune.py) — these rows measure schedule,
    # never results.
    ("heat3d_512_f32_stream4_tune1_shard", "heat3d", (512, 512, 512),
     10, "float32", "stream4_shard_tune1"),
    ("heat3d_512_f32_stream4_tune2_shard", "heat3d", (512, 512, 512),
     10, "float32", "stream4_shard_tune2"),
    ("wave3d_512_f32_stream4_tune1_shard", "wave3d", (512, 512, 512),
     8, "float32", "stream4_shard_tune1"),
    ("wave3d_512_f32_stream4_tune2_shard", "wave3d", (512, 512, 512),
     8, "float32", "stream4_shard_tune2"),
    ("heat3d_512_f32_rdma4_tune1", "heat3d", (512, 512, 512), 10,
     "float32", "rdma4_tune1"),
    ("heat3d_512_f32_rdma4_tune2", "heat3d", (512, 512, 512), 10,
     "float32", "rdma4_tune2"),
    ("wave3d_512_f32_rdma4_tune1", "wave3d", (512, 512, 512), 8,
     "float32", "rdma4_tune1"),
    ("wave3d_512_f32_rdma4_tune2", "wave3d", (512, 512, 512), 8,
     "float32", "rdma4_tune2"),
    # ── Tier D14: COUPLED device groups (round 18, parallel/groups.py)
    # — *_grp2 rows: the slice partitioned into two contiguous mesh
    # groups coupled at interface ghost bands, every group running the
    # UNMODIFIED sharded stepper on its own sub-mesh.  grp2 = same-
    # physics equal split: the A/B against the monolithic sharded row
    # (same op, same total cells) prices exactly the host-orchestrated
    # coupling (interface transfers + per-group dispatch).  grp2het =
    # the MPMD row: the named op 2x-refined over the first z quarter +
    # a base-resolution heat3d far-field — aggregate owned-cell
    # Mcells/s, the cell-update win the groups engine claims.  The
    # ledger keys these rows |grp:<sig> (obs/ledger.baseline_key), so
    # a coupled row can never baseline a monolithic one.  Needs >= 2
    # devices (fast environmental decline + retry elsewhere).  bf16 and
    # mixed-dtype coupling are pinned bit-exactly on CPU
    # (tests/test_groups.py); no dedicated chip row — Tier D must stay
    # strictly under half the campaign (test_measure_campaign.py).
    ("heat3d_512_f32_grp2", "heat3d", (512, 512, 512), 10, "float32",
     "grp2"),
    ("wave3d_512_f32_grp2", "wave3d", (512, 512, 512), 8, "float32",
     "grp2"),
    ("wave3d_512_f32_grp2het", "wave3d", (512, 512, 512), 8, "float32",
     "grp2het"),
    # ── Tier D15: fast coupled groups (round 23).  *_grp2ici = the same
    # equal split moved over the COLLECTIVE interface transport (one
    # ppermute round per interface per direction inside a union-mesh
    # shard_map — zero host hops, gated by jaxprcheck): the A/B against
    # the *_grp2 row prices exactly the transport swap.  The ledger keys
    # these rows |gtx:collective so neither transport can baseline the
    # other.  *_grp2modes = per-group execution modes (group 0 overlap,
    # group 1 plain) under the default transport: the A/B against *_grp2
    # prices per-group mode routing alone.
    ("heat3d_512_f32_grp2ici", "heat3d", (512, 512, 512), 10, "float32",
     "grp2ici"),
    ("wave3d_512_f32_grp2ici", "wave3d", (512, 512, 512), 8, "float32",
     "grp2ici"),
    ("heat3d_512_f32_grp2modes", "heat3d", (512, 512, 512), 10,
     "float32", "grp2modes"),
]

# Tier-D labels: new large Mosaic compiles.  A hang here is plausibly a
# SLOW compile (the round-3 bf16 k=8 unrolled compile exceeded 20 min);
# killing a live remote compile is what wedges the tunnel, so these get a
# longer leash before the kill.  Derived from CONFIGS order — everything
# at/after the first Tier-D row is risky, so a new Tier-D label can't
# silently get the short budget.
_RISKY_BUDGET_S = 2400
_TIER_D_START = "heat3d_256_f32_stream4"
_RISKY = frozenset(
    label for label, *_ in
    CONFIGS[[label for label, *_ in CONFIGS].index(_TIER_D_START):])


# Bumped whenever kernel-builder code changes in a way that can turn a
# previously "untileable" config tileable (new lowering, relaxed alignment
# gate, new kernel variant).  Cached untileable declines from an older
# builder are retried instead of skipped — tileability is a property of the
# CODE, not the config (round-3 advisor finding).
# rev 7: the 2-axis streaming kernel (build_stream_2axis_call) — forced
# stream on y-sharded meshes went from None to buildable.
# rev 8: the slab-carry pipelined stepper (pipeline=True) — new pipeK
# labels exist, and the pad-free builders are now constructed through
# one more wrapper layer (pipeline bodies), so older declines retry.
# rev 9: the in-kernel remote-DMA exchange (exchange='rdma') — new
# rdmaK labels exist, and the streaming steppers grew the transport
# layer (halo.RdmaTransport threading), so older declines retry.
# rev 11: kernel-variant plumbing (policy/autotune.py) — new *_tuneN
# labels exist, remote.py's ring kernel is parameterized over slot
# count / chunk preference and the streaming builders accept variant
# tiles through the sharded steppers, so older declines retry.
# rev 12: the coupled device-group engine (parallel/groups.py) — new
# *_grp2 labels exist, the streaming builders accept the round-18
# margin/order sweep constants, and the sharded stepper is now also
# constructed per-group over device subsets, so older declines retry.
# rev 13: fast coupled groups (round 23) — new *_grp2ici/*_grp2modes
# labels exist, the coupled engine grew the collective interface
# transport (union-mesh ppermute wire) and per-group mode routing
# through the fused/stream/overlap/pipeline steppers, so older coupled
# declines retry.
BUILDER_REV = 13


def _skip_cached(cached):
    """True iff a cached record needs no re-run — THE skip rule.

    Skips successes AND deterministic-at-this-builder-rev failures:
     - "untileable" structural declines (pure-Python ValueError,
       identical on every run);
     - recorded subprocess TIMEOUTS (presumed Mosaic compile hangs):
       retrying one re-kills a live remote compile, which is exactly
       what wedges the tunnel (2026-07-31) — retry only via --only or a
       BUILDER_REV bump after a builder change.
    Transient failures (tunnel/RPC/OOM) are retried.  A suspect timeout
    (post-kill probe failed, so the hang may not have been this label's
    fault) is treated as transient; the start-of-run probe guarantees
    the retry only ever happens against a healthy tunnel.

    Single definition shared by main(), --count-runnable, and the
    recovery watcher (watch_tunnel.sh) — a round-4 advisor finding: the
    watcher used to re-derive this rule by regex-scraping this file.
    """
    return cached is not None and (
        "error" not in cached
        or (("untileable" in cached.get("error", "")
             or (cached.get("timeout") and not cached.get("suspect")))
            and cached.get("builder_rev") == BUILDER_REV))


def count_runnable(out_path):
    """How many campaign labels a plain run would still execute."""
    results = _read_results(out_path)
    return sum(1 for label, *_ in CONFIGS
               if not _skip_cached(results.get(label)))


def _seed_results(out_path, default_out):
    """Seed this round's table from the previous round's (default out
    path ONLY — a user-chosen --out means a deliberately fresh
    campaign): successful measurements carry over (their measured_at
    stamps keep provenance); errored labels retry via the skip rule."""
    if out_path != default_out or os.path.exists(out_path):
        return
    prev = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "results_r04.json")
    if os.path.exists(prev):
        # atomic (tmp + rename), like _write_results: a copy killed
        # mid-write must not leave a truncated table that os.path.exists
        # would treat as already-seeded on the next run
        import shutil

        tmp = out_path + ".tmp"
        shutil.copy(prev, tmp)
        os.replace(tmp, out_path)


def _read_results(out_path):
    if os.path.exists(out_path):
        with open(out_path) as fh:
            return json.load(fh)
    return {}


def _write_results(out_path, results):
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, out_path)


def _merge_record(out_path, label, rec):
    """Atomically merge one label's record into the results file."""
    results = _read_results(out_path)
    results[label] = rec
    _write_results(out_path, results)
    print(f"[measure] {label}: {rec}", file=sys.stderr)


def _measure_one(out_path, label, name, grid, steps, dtype, compute):
    """Measure one config and merge its record into ``out_path``."""
    # Fault point (resilience/faults.py): label:name=LABEL:hang|sigkill
    # wedges exactly one campaign label deterministically — the CPU
    # trigger for the supervised-retry path (a wedge must cost the
    # in-flight attempt, never the label).
    from mpi_cuda_process_tpu.resilience import faults

    faults.maybe_fire("label", name=label)
    backend = jax.default_backend()
    t0 = time.time()
    try:
        rec = measure(name, grid, steps, dtype=dtype, compute=compute)
    except Exception as e:  # noqa: BLE001 — record & continue campaign
        msg = f"{type(e).__name__}: {e}"
        if len(msg) > 1200:
            # Mosaic/axon failures bury the real error under proxy log
            # noise; the diagnostic line is near the END of the message.
            msg = msg[:400] + " ...[snip]... " + msg[-800:]
        rec = {"error": msg}
    rec.update({"stencil": name, "grid": list(grid), "dtype": dtype,
                "compute": compute, "backend": backend,
                "builder_rev": BUILDER_REV,
                "wall_s": round(time.time() - t0, 1),
                "measured_at": time.time()})
    _merge_record(out_path, label, rec)


def _tunnel_probe_ok(timeout_s=180):
    """Run a trivial op in a subprocess: True iff the backend answers.

    Gates the campaign so no label ever starts against a wedged tunnel —
    a label that times out on a healthy tunnel is genuine evidence about
    its own compile, never confounded by a pre-existing wedge.
    """
    import subprocess

    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "print(float(jnp.ones((8, 8)).sum()))"],
            timeout=timeout_s, capture_output=True)
        return p.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results_r05.json"))
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--in-process", action="store_true",
                    help="measure in this process instead of one subprocess "
                         "per config (an OOM then poisons later configs)")
    ap.add_argument("--count-runnable", action="store_true",
                    help="print how many labels a plain run would still "
                         "execute, then exit (no backend contact — safe on "
                         "a wedged tunnel; used by watch_tunnel.sh)")
    ap.add_argument("--label-restarts", type=int, default=1,
                    help="supervised retries per timed-out label "
                         "(resilience/supervisor.retry_subprocess): on a "
                         "subprocess timeout the child is killed and the "
                         "label retried after a backoff — a wedge costs "
                         "the in-flight ATTEMPT, not the label; the "
                         "attempt count lands in the record and the "
                         "ledger row (default 1; 0 restores the old "
                         "one-shot behavior)")
    ap.add_argument("--restart-backoff", type=float, default=2.0,
                    help="backoff base seconds between label retries "
                         "(doubles per retry, bounded)")
    ap.add_argument("--label-budget", type=float, default=None,
                    help="override the per-label subprocess budget in "
                         "seconds (default: the tier-derived 1200/2400 "
                         "split; test hook for the fault-injection "
                         "suite)")
    ap.add_argument("--telemetry", default=None, metavar="PATH",
                    help="write a JSONL telemetry event log (obs/ "
                         "schema, same manifest as cli --telemetry): "
                         "one 'label' event per campaign config with "
                         "its outcome, plus a stall-detecting heartbeat "
                         "whose STALLED/WEDGED verdicts land in the "
                         "log while a label is still hanging — the "
                         "live view the wedge rounds never had.  "
                         "Render with scripts/obs_report.py")
    ap.add_argument("--serve", type=int, default=None, metavar="PORT",
                    help="campaign live console (obs/serve.py "
                         "serve_campaign): an HTTP aggregator over the "
                         "telemetry directory — /status.json exposes "
                         "per-label progress (status, Mcells/s, "
                         "attempts) while the campaign runs, /metrics "
                         "the Prometheus counters, /events the "
                         "incremental NDJSON tail.  PORT 0 = ephemeral "
                         "(bound address printed + recorded as a "
                         "'serve' event).  Implies --telemetry (a "
                         "default path is derived when unset); watch "
                         "with scripts/obs_top.py URL")
    args = ap.parse_args()

    if args.count_runnable:
        _seed_results(args.out, ap.get_default("out"))
        print(count_runnable(args.out))
        return

    known = {label for label, *_ in CONFIGS}
    unknown = set(args.only or ()) - known
    if unknown:
        ap.error(f"unknown --only labels {sorted(unknown)}; "
                 f"choose from {sorted(known)}")

    _seed_results(args.out, ap.get_default("out"))

    results = _read_results(args.out)

    # Probe whenever child processes will be spawned — INCLUDING --only
    # (the documented retry path for recorded timeouts): a retry against a
    # still-wedged tunnel would time out and blame an innocent compile.
    subprocess_mode = not args.in_process
    if subprocess_mode and not _tunnel_probe_ok():
        print("[measure] tunnel probe failed — backend wedged or "
              "unreachable; aborting before any label (rerun to resume)",
              file=sys.stderr)
        return

    if args.serve is not None and not args.telemetry:
        # the console aggregates label events from the telemetry log;
        # --serve without one would be a blind server
        from mpi_cuda_process_tpu.obs import trace as _trace

        args.telemetry = os.path.join(
            _trace.default_telemetry_dir(),
            f"measure-{os.getpid()}-{int(time.time())}.jsonl")

    session = None
    if args.telemetry:
        try:
            from mpi_cuda_process_tpu import obs

            session = obs.open_session(
                args.telemetry, tool="measure",
                run={"out": os.path.abspath(args.out),
                     "only": args.only, "in_process": args.in_process,
                     "builder_rev": BUILDER_REV,
                     "n_configs": len(CONFIGS),
                     "runnable": count_runnable(args.out)},
                stall_after_s=420.0)
            # NO backend probe on stall: a probe while a campaign child
            # owns the tunnel is the two-process wedge hazard
            # (docs/STATE.md) — the verdict records the stall, unprobed.
            if session.heartbeat is not None:
                session.heartbeat.probe = lambda: {
                    "verdict": "SKIPPED",
                    "detail": "no backend probe while a campaign label "
                              "may own the tunnel (two-process wedge "
                              "hazard)"}
        except Exception as e:  # noqa: BLE001 — never block the campaign
            print(f"[measure] telemetry disabled ({type(e).__name__}: {e})",
                  file=sys.stderr)
            session = None

    server = None
    if args.serve is not None:
        # Campaign aggregator (obs/serve.py): watches the telemetry
        # DIRECTORY (new manifests picked up between polls — child runs
        # that drop logs there appear live) plus this harness's own log
        # for the per-label progress table in /status.json.
        try:
            from mpi_cuda_process_tpu.obs import serve as serve_lib

            server = serve_lib.serve_campaign(
                os.path.dirname(os.path.abspath(args.telemetry)),
                port=args.serve)
            server.console.watch(os.path.abspath(args.telemetry))
            print(f"[measure] campaign console at {server.url} "
                  "(/status.json has the per-label table)",
                  file=sys.stderr)
            if session is not None:
                session.event("serve", url=server.url, port=server.port,
                              endpoints=["/metrics", "/status.json",
                                         "/events"])
        except Exception as e:  # noqa: BLE001 — never block the campaign
            print(f"[measure] --serve disabled ({type(e).__name__}: {e})",
                  file=sys.stderr)
            server = None

    def _tel_label(label, status=None, wall_s=None, attempts=None):
        if session is None:
            return
        rec = _read_results(args.out).get(label) or {}
        if status is None:
            status = "error" if rec.get("error") else \
                ("ok" if rec else "missing")
        payload = {"label": label, "status": status,
                   "compute": rec.get("compute"),
                   "mcells_per_s": rec.get("mcells_per_s"),
                   "error": (rec.get("error") or "")[:300] or None}
        if wall_s is not None:
            payload["wall_s"] = round(wall_s, 1)
        if attempts is not None and attempts > 1:
            # the restart trail: a value measured after a supervised
            # retry is honest but flagged (perf_gate reads this via the
            # ledger row detail)
            payload["attempts"] = attempts
        session.event("label", **payload)

    n_run = 0
    consecutive_timeouts = 0
    for label, name, grid, steps, dtype, compute in CONFIGS:
        if args.only and label not in args.only:
            continue
        # _skip_cached holds the skip rule (and its rationale); --only
        # bypasses it — that is the documented retry path for recorded
        # timeouts and declines.
        if not args.only and _skip_cached(results.get(label)):
            print(f"[measure] {label}: cached, skip", file=sys.stderr)
            _tel_label(label, "cached")
            continue
        n_run += 1
        t_label = time.time()
        if args.in_process:
            _measure_one(args.out, label, name, grid, steps, dtype, compute)
            _tel_label(label, wall_s=time.time() - t_label)
        else:
            # Subprocess + budget even under --only: the documented retry
            # path for recorded timeouts must not reintroduce an unbounded
            # in-session hang (the operator's manual kill of a live remote
            # compile is exactly what wedges the tunnel).
            # Subprocess isolation: a RESOURCE_EXHAUSTED on one config must
            # not leave the TPU arena poisoned for every config after it
            # (observed in the round-3 campaign: a 1024^3 OOM turned the
            # rest of the matrix into cascade failures).
            # Supervised retries (resilience/supervisor.retry_subprocess):
            # a timed-out attempt is killed (whole process group), the
            # tunnel probed, and — probe permitting — the SAME label
            # retried after a backoff, so a transient wedge costs the
            # in-flight attempt, not the label.  Each attempt exports
            # FAULT_ATTEMPT so the fault harness can wedge attempt 0
            # deterministically and prove the retry completes the label.
            from mpi_cuda_process_tpu.resilience import (
                supervisor as sup_lib,
            )

            budget = args.label_budget or (
                _RISKY_BUDGET_S if label in _RISKY else 1200)
            pre_rec = results.get(label)  # snapshot before the spawn
            res = sup_lib.retry_subprocess(
                [sys.executable, os.path.abspath(__file__),
                 "--only", label, "--in-process",
                 "--out", os.path.abspath(args.out)],
                timeout_s=budget,
                max_restarts=args.label_restarts,
                backoff_base_s=args.restart_backoff,
                healthy=_tunnel_probe_ok,
                cwd=os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))))
            if not res["timed_out"]:
                if res["rc"] != 0:
                    print(f"[measure] {label}: subprocess rc={res['rc']}",
                          file=sys.stderr)
                consecutive_timeouts = 0
                if res["attempts"] > 1:
                    # the wedge cost an attempt, not the label: the
                    # restart count rides the record into the results
                    # table and (via ingest) the ledger row, so the
                    # value stays honest-but-flagged downstream
                    child_rec = _read_results(args.out).get(label)
                    if child_rec is not None and child_rec != pre_rec:
                        child_rec["restart_attempts"] = res["attempts"] - 1
                        _merge_record(args.out, label, child_rec)
                _tel_label(label, wall_s=time.time() - t_label,
                           attempts=res["attempts"])
            else:
                # Every attempt burned its budget (or the probe failed):
                # the supervisor gives up on this label.  Recorded like a
                # decline so the NEXT campaign run continues from the
                # ledgered state instead of re-wedging on the same label
                # — UNLESS the killed child already merged a record
                # (success OR a real error diagnosis) before the kill:
                # never clobber what the child actually learned.
                print(f"[measure] {label}: supervised give-up after "
                      f"{res['attempts']} attempt(s) of {budget}s, "
                      "skipping", file=sys.stderr)
                # The probe result decides blame: a healthy post-kill
                # probe means the hang was genuinely this label's
                # compile; a failed probe is ambiguous (its own kill
                # wedged the tunnel, OR the tunnel wedged mid-campaign
                # before the label started) and the record must say so.
                tunnel_ok = res["healthy_after"]
                child_rec = _read_results(args.out).get(label)
                if child_rec == pre_rec:
                    msg = (f"supervised give-up: {res['attempts']} "
                           f"attempt(s) timed out ({budget}s each) — "
                           "presumed Mosaic compile hang; the kill may "
                           "wedge the tunnel.  Not auto-retried: rerun "
                           "with --only after a builder change.")
                    if not tunnel_ok:
                        msg += ("  SUSPECT: the post-kill tunnel probe "
                                "failed, so the tunnel may already have "
                                "been wedged before this label started — "
                                "the hang may not be this compile's "
                                "fault.")
                    rec = {"error": msg, "timeout": True, "stencil": name,
                           "grid": list(grid), "dtype": dtype,
                           "compute": compute, "builder_rev": BUILDER_REV,
                           "attempts": res["attempts"],
                           "wall_s": float(budget) * res["attempts"],
                           "measured_at": time.time()}
                    if not tunnel_ok:
                        rec["suspect"] = True
                    _merge_record(args.out, label, rec)
                _tel_label(label, "timeout", wall_s=time.time() - t_label,
                           attempts=res["attempts"])
                if not tunnel_ok:
                    # don't let the next label run into a wedged tunnel (a
                    # wedged-tunnel timeout would blame an innocent compile)
                    print("[measure] tunnel probe failed after the kill — "
                          "wedged; aborting campaign (rerun to resume)",
                          file=sys.stderr)
                    if session is not None:
                        session.event("abort",
                                      reason="post-kill tunnel probe "
                                             "failed — wedged")
                    break
                consecutive_timeouts += 1
                if consecutive_timeouts >= 2:
                    # Backstop for wedge modes the trivial-op probe can't
                    # see (e.g. only the remote-compile service hung):
                    # two full-budget burns in a row with a "healthy"
                    # probe means something systemic — stop paying the
                    # budget per remaining label.
                    print("[measure] 2 consecutive timeouts despite "
                          "healthy probes — systemic; aborting campaign "
                          "(rerun to resume)", file=sys.stderr)
                    if session is not None:
                        session.event("abort",
                                      reason="2 consecutive timeouts "
                                             "despite healthy probes")
                    break

    if session is not None:
        session.finish(labels_run=n_run,
                       runnable_after=count_runnable(args.out))
        session.close()
    if server is not None:
        server.close()  # final drain happens inside close()

    # Every FULL campaign run updates the durable cross-round ledger from
    # its results table (idempotent append; errored/suspect labels land
    # quarantined).  --only invocations skip it: they are the per-label
    # children (and the surgical manual retry path) — the parent ingests
    # once at campaign end, AFTER annotating supervised-retry records
    # with their attempt counts, so the ledger row carries the restart
    # trail instead of a pre-annotation duplicate winning the dedupe.
    # Never load-bearing for the campaign itself.
    if not args.only:
        try:
            from mpi_cuda_process_tpu.obs import ledger as _ledger

            _ledger.ingest_results(args.out)
        except Exception:  # noqa: BLE001
            pass

    if not args.only and os.path.exists(args.out):
        with open(args.out) as fh:
            print(fh.read())


if __name__ == "__main__":
    main()
