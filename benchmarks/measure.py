"""Single-chip measurement campaign for the BASELINE.md perf table.

Runs the full config matrix on the real TPU and appends each result to
``benchmarks/results_r02.json`` IMMEDIATELY after it is measured, so a
wedged tunnel mid-campaign loses only the in-flight config.

Timing method (same as bench.py): scan N steps and 4N steps, take the
difference / 3N — cancels the ~66 ms tunnel dispatch + readback overhead
(docs/STATE.md "Infra gotchas").

Usage:  python benchmarks/measure.py [--out FILE] [--only NAME ...]
"""

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas import has_pallas_kernel, make_pallas_compute


def _fence(fields) -> float:
    # Actual scalar read: the only reliable completion fence on the tunneled
    # backend (block_until_ready can return early — docs/STATE.md).
    return float(jnp.sum(fields[0].astype(jnp.float32)))


def measure(name, grid, steps, dtype=None, compute="jnp", reps=3,
            params=None):
    kw = dict(params or {})
    if dtype is not None:
        kw["dtype"] = dtype
    st = make_stencil(name, **kw)
    compute_fn = None
    if compute == "pallas":
        if not has_pallas_kernel(name):
            raise ValueError(f"no pallas kernel for {name}")
        compute_fn = make_pallas_compute(st, interpret=False)
    step = make_step(st, grid, compute_fn=compute_fn)
    mk = lambda: init_state(st, grid, kind="auto")  # noqa: E731
    run_a = make_runner(step, steps)
    run_b = make_runner(step, 4 * steps)
    _fence(run_a(mk()))  # compile + warm
    _fence(run_b(mk()))

    def best(run):
        b = math.inf
        for _ in range(reps):
            f = mk()
            _fence(f)
            t0 = time.perf_counter()
            _fence(run(f))
            b = min(b, time.perf_counter() - t0)
        return b

    t_a, t_b = best(run_a), best(run_b)
    per_step = max((t_b - t_a) / (3 * steps), 1e-9)
    mcells = math.prod(grid) / per_step / 1e6
    return {"ms_per_step": round(per_step * 1e3, 4),
            "mcells_per_s": round(mcells, 1)}


# (label, stencil, grid, steps, dtype, compute)
CONFIGS = [
    # BASELINE.json config 1 + 2 refresh
    ("heat2d_512_f32", "heat2d", (512, 512), 400, "float32", "jnp"),
    ("heat3d_256_f32", "heat3d", (256, 256, 256), 100, "float32", "jnp"),
    # bf16 halves HBM bytes (STATE.md open avenue 2)
    ("heat3d_256_bf16", "heat3d", (256, 256, 256), 100, "bfloat16", "jnp"),
    # larger grid: bandwidth bound binding (open avenue 3)
    ("heat3d_512_f32", "heat3d", (512, 512, 512), 30, "float32", "jnp"),
    ("heat3d_512_bf16", "heat3d", (512, 512, 512), 30, "bfloat16", "jnp"),
    # the _PALLAS_WINS question (open avenue 1 / VERDICT item 3)
    ("heat3d27_256_f32_jnp", "heat3d27", (256, 256, 256), 50, "float32", "jnp"),
    ("heat3d27_256_f32_pallas", "heat3d27", (256, 256, 256), 50, "float32",
     "pallas"),
    ("heat3d4th_256_f32_jnp", "heat3d4th", (256, 256, 256), 50, "float32",
     "jnp"),
    ("heat3d4th_256_f32_pallas", "heat3d4th", (256, 256, 256), 50, "float32",
     "pallas"),
    ("heat3d27_256_bf16_jnp", "heat3d27", (256, 256, 256), 50, "bfloat16",
     "jnp"),
    ("heat3d27_256_bf16_pallas", "heat3d27", (256, 256, 256), 50, "bfloat16",
     "pallas"),
    # two-field wave (BASELINE config 5 family), fp32 vs bf16 (VERDICT item 9)
    ("wave3d_256_f32", "wave3d", (256, 256, 256), 50, "float32", "jnp"),
    ("wave3d_256_bf16", "wave3d", (256, 256, 256), 50, "bfloat16", "jnp"),
    ("wave3d_512_bf16", "wave3d", (512, 512, 512), 20, "bfloat16", "jnp"),
    # int32 GoL throughput (bit-exact family)
    ("life_2048_i32", "life", (2048, 2048), 200, None, "jnp"),
    # pallas single-chip 7-point for completeness (M1 kernel)
    ("heat3d_256_f32_pallas", "heat3d", (256, 256, 256), 100, "float32",
     "pallas"),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results_r02.json"))
    ap.add_argument("--only", nargs="*", default=None)
    args = ap.parse_args()

    results = {}
    if os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)

    backend = jax.default_backend()
    print(f"[measure] backend={backend} devices={jax.devices()}",
          file=sys.stderr)

    for label, name, grid, steps, dtype, compute in CONFIGS:
        if args.only and label not in args.only:
            continue
        if label in results and not args.only:
            print(f"[measure] {label}: cached, skip", file=sys.stderr)
            continue
        t0 = time.time()
        try:
            rec = measure(name, grid, steps, dtype=dtype, compute=compute)
        except Exception as e:  # noqa: BLE001 — record & continue campaign
            rec = {"error": f"{type(e).__name__}: {e}"[:500]}
        rec.update({"stencil": name, "grid": list(grid), "dtype": dtype,
                    "compute": compute, "backend": backend,
                    "wall_s": round(time.time() - t0, 1),
                    "measured_at": time.time()})
        results[label] = rec
        tmp = args.out + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(results, fh, indent=1, sort_keys=True)
        os.replace(tmp, args.out)
        print(f"[measure] {label}: {rec}", file=sys.stderr)

    print(json.dumps(results, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
