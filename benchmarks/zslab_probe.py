"""Single-chip probe of the z-slab kernel's REAL VMEM envelope.

The z-slab pad-free sharded kernel is the config-5 memory design, but
two-field wave3d fails `_pick_tiles`' ~7-live-copies-per-field VMEM
estimate at X=4096 lanes on every legal tile (docs/STATE.md).  That
estimate was fit to single-field kernels; wave's ``u_prev`` window has NO
roll temporaries, so the true envelope may be smaller.  This script
answers the question empirically WITHOUT a 64-chip slice: the pallas_call
a shard would run is built here with EXPLICIT tiles (bypassing the
estimate) at a shard-local shape that fits one chip — (64, 2048, 4096):
the VMEM cost depends on (tile x X-lane) geometry, not the Y extent, so
halving Y changes nothing about the question while fitting HBM — and fed
synthetic slab operands + a zero origin.  Mosaic either compiles it (the
model is pessimistic -> recalibrate `_pick_tiles` and unlock config-5
wave temporal blocking) or rejects it with the scoped-vmem error text
(the model is right -> the x-windowed variant or bf16-plain stays the
plan).

Each attempt runs in its own subprocess with a hard timeout (a killed
Mosaic compile can wedge the tunnel — run this on a healthy, idle tunnel
only, AFTER the main campaign).  Results merge into
``benchmarks/zslab_probe.json``.

Usage: python benchmarks/zslab_probe.py [--timeout 600]
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, stencil, dtype, local_shape, k, tiles)
# 2-tuple tiles -> whole-row z-slab kernel; 3-tuple -> wide-X variant.
# Ordered cheapest-question-first; heat3d rungs calibrate the estimate's
# accuracy against a config it PASSES, so a wave-only failure is
# attributable to the second field rather than to the probe harness.
ATTEMPTS = [
    ("heat3d_f32_k4_t8", "heat3d", None, (64, 2048, 4096), 4, (8, 8)),
    ("wave3d_f32_k4_t8", "wave3d", None, (64, 2048, 4096), 4, (8, 8)),
    ("wave3d_f32_k4_t16", "wave3d", None, (64, 2048, 4096), 4, (16, 16)),
    ("wave3d_bf16_k8_t16", "wave3d", "bfloat16", (64, 2048, 4096), 8,
     (16, 16)),
    # wide-X variants: the picker's actual choices for the config-5 local
    # shapes — these measure the 4.5x-amplification kernel's REAL rate
    ("wave3d_f32_k4_xwin", "wave3d", None, (64, 2048, 4096), 4,
     (32, 16, 512)),
    ("wave3d_bf16_k8_xwin", "wave3d", "bfloat16", (64, 2048, 4096), 8,
     (16, 16, 256)),
    ("heat3d_f32_k4_xwin", "heat3d", None, (64, 2048, 4096), 4,
     (32, 32, 512)),
]

_CHILD = """\
import sys, time, math
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from mpi_cuda_process_tpu import make_stencil
from mpi_cuda_process_tpu.ops.pallas.fused import build_zslab_padfree_call

name, dt, local, k, tiles = {name!r}, {dt!r}, {local!r}, {k!r}, {tiles!r}
kw = dict(dtype=jnp.bfloat16) if dt == "bfloat16" else {{}}
st = make_stencil(name, **kw)
gshape = (local[0] * 8, local[1], local[2])  # as if one of 8 z-shards
if len(tiles) == 3:
    from mpi_cuda_process_tpu.ops.pallas.fused import build_zslab_xwin_call
    built = build_zslab_xwin_call(st, local, gshape, k, tiles=tiles,
                                  interpret=False)
    n_core, n_slab = 27, 9
else:
    built = build_zslab_padfree_call(st, local, gshape, k, tiles=tiles,
                                     interpret=False)
    n_core, n_slab = 9, 3
assert built is not None, "builder declined explicit tiles"
call, m, nfields = built
key = jax.random.PRNGKey(0)
fields = [jax.random.uniform(jax.random.fold_in(key, i), local, st.dtype)
          for i in range(nfields)]
slab = jnp.zeros((m, local[1], local[2]), st.dtype)
origins = jnp.array([local[0], 0], jnp.int32)  # pretend shard 1 (interior)
args = []
for f in fields:
    args += [f] * n_core + [slab] * n_slab + [slab] * n_slab
t0 = time.time()
out = call(origins, *args)
s = float(jnp.sum(out[0].astype(jnp.float32)))
t_compile = time.time() - t0
assert math.isfinite(s)
# one timed repeat (compiled): per-pass wall time -> Mcells/s over k steps
t0 = time.time()
float(jnp.sum(call(origins, *args)[0].astype(jnp.float32)))
dt_run = time.time() - t0
print("RESULT", t_compile,
      math.prod(local) * k / dt_run / 1e6, flush=True)
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=600)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "zslab_probe.json"))
    args = ap.parse_args()
    results = {}
    if os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)
    for label, name, dt, local, k, tiles in ATTEMPTS:
        if results.get(label, {}).get("ok"):
            print(f"[zslab] {label}: cached, skip", file=sys.stderr)
            continue
        code = _CHILD.format(repo=_REPO, name=name, dt=dt, local=local,
                             k=k, tiles=tiles)
        t0 = time.time()
        try:
            p = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                               capture_output=True, text=True,
                               timeout=args.timeout)
            out_lines = p.stdout.strip().splitlines()
            if p.returncode == 0 and out_lines and \
                    out_lines[-1].startswith("RESULT"):
                _, t_compile, mcells = out_lines[-1].split()
                results[label] = {"ok": True,
                                  "compile_s": round(float(t_compile), 1),
                                  "mcells_per_s": round(float(mcells), 1)}
            else:
                tail = (p.stderr or "")
                if len(tail) > 900:
                    tail = tail[:200] + " ...[snip]... " + tail[-600:]
                results[label] = {"ok": False, "rc": p.returncode,
                                  "stderr_tail": tail}
        except subprocess.TimeoutExpired:
            results[label] = {"ok": False,
                              "error": f"timeout {args.timeout}s (hang)"}
            results["_aborted"] = ("stopped after first hang to protect "
                                   "the tunnel")
            print(json.dumps(results, indent=1, sort_keys=True))
            break
        results[label]["wall_s"] = round(time.time() - t0, 1)
        print(f"[zslab] {label}: {results[label]}", file=sys.stderr)
        with open(args.out + ".tmp", "w") as fh:
            json.dump(results, fh, indent=1, sort_keys=True)
        os.replace(args.out + ".tmp", args.out)
    print(json.dumps(results, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
