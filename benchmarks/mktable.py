"""Regenerate BASELINE.md's measured table from the campaign record.

Reads ``benchmarks/results_r05.json`` (or ``--in FILE``) and prints the
markdown table body: one row per successful label, grouped by stencil
family then grid size, with the ``--compute auto`` policy pick bolded via
the live cli policy tables — so the measured table and the shipping policy
can never silently disagree.  Errored/suspect labels are listed beneath
the table with their reasons (a pending row is information too).

Usage: python benchmarks/mktable.py [--in FILE]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _auto_pick(stencil: str, grid, dtype: str | None) -> str | None:
    """The compute string cli's auto policy would select (best-effort)."""
    from mpi_cuda_process_tpu.cli import (
        _AUTO_FUSE_K,
        _AUTO_FUSE_K_BF16,
        _CLIFF_CELLS,
        _RAW_ABOVE_CLIFF,
        _RAW_WINS,
    )

    bf16 = dtype == "bfloat16"
    k = (_AUTO_FUSE_K_BF16 if bf16 else _AUTO_FUSE_K).get(stencil)
    if k:
        return f"fused{k}"
    if bf16:
        return "jnp"
    if stencil in _RAW_WINS:
        return "raw"
    if stencil in _RAW_ABOVE_CLIFF and math.prod(grid) >= _CLIFF_CELLS:
        return "raw"
    return "jnp"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--in", dest="inp", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results_r05.json"))
    args = ap.parse_args()
    with open(args.inp) as fh:
        results = json.load(fh)

    rows, problems = [], []
    for label, rec in sorted(results.items()):
        stencil = rec.get("stencil")
        grid = tuple(rec.get("grid") or ())
        dtype = rec.get("dtype")
        compute = rec.get("compute", "?")
        if rec.get("error"):
            problems.append((label, rec["error"].splitlines()[0][:120]))
            continue
        if rec.get("suspect"):
            problems.append((label, "SUSPECT: " + rec.get(
                "error", "noise-floor / cross-check pending")[:100]))
            continue
        if stencil is None:
            # calibration rows (copy_*): report as GB/s context
            mc = rec.get("mcells_per_s")
            if mc:
                gbs = mc * 1e6 * 2 * 4 / 1e9
                rows.append((label, f"| {label} (calibration) | copy | "
                             f"{mc:,.0f} | {gbs:.0f} GB/s |"))
            continue
        mc = rec.get("mcells_per_s")
        ms = rec.get("ms_per_step")
        if mc is None:
            continue
        gstr = "×".join(str(g) for g in grid)
        dshort = {"float32": "f32", "bfloat16": "bf16",
                  None: "i32" if stencil == "life" else "f32"}.get(
            dtype, dtype)
        pick = _auto_pick(stencil, grid, dtype)
        cstr = f"**{compute}**" if compute == pick else compute
        mcstr = f"**{mc:,.0f}**" if compute == pick else f"{mc:,.0f}"
        rows.append((label,
                     f"| {stencil} {gstr} {dshort} | {cstr} | {mcstr} | "
                     f"{ms} |"))

    print("| Config | compute | Mcells/s | ms/step |")
    print("|---|---|---:|---:|")
    for _, row in rows:
        print(row)
    if problems:
        print("\nPending / errored / suspect labels:\n")
        for label, why in problems:
            print(f"- `{label}`: {why}")


if __name__ == "__main__":
    main()
