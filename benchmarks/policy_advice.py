"""Turn the measurement campaign's results into policy-table advice.

Reads ``benchmarks/results_r05.json`` (or ``--in FILE``) and prints, for
every auto-policy decision the cli keeps as an explicit data table, the
recommendation the measured numbers support — with the winning/losing
labels and their Mcells/s cited, so each flip stays a reviewed one-line
edit in ``cli.py`` rather than a blind paste.  Decisions covered
(docs/STATE.md runbook step 2):

- ``_AUTO_FUSE_KIND``  — stream vs tiled/padfree per 3D family;
- ``_AUTO_FUSE_K_BF16`` — whether any bf16 temporal-blocking path beats
  bf16 jnp (and at which k);
- ``_PADFREE_ABOVE_BYTES`` — whether pad-free wins below the current
  6 GiB threshold (drop to 0 if it wins at every measured size);
- ``_AUTO_FULL_K``     — 2D whole-grid blocking per family;
- ``_AUTO_FUSE_K``     — families whose fused labels only landed this
  round (advect3d/grayscott3d/sor3d/heat3d4th);
- the advect3d >roofline suspect (jnp vs n150 rerun vs copy
  calibration).

Pure file-reading + arithmetic: NEVER contacts the backend, safe on a
wedged tunnel.  The output is advice — the cli tables stay the source of
truth and every edit should cite its label, as in rounds 3-4.

Usage: python benchmarks/policy_advice.py [--in FILE] [--json]
"""

import argparse
import json
import math
import os
import re
import sys

# v5e HBM bandwidth roofline for the suspect check (docs/STATE.md: the
# measured pure-copy jnp rate is ~640-710 GB/s; physical peak 819).
_HBM_PEAK_GBS = 819.0

_LABEL_RE = re.compile(
    r"^(?P<family>[a-z0-9]+?)_(?P<size>\d+)_(?P<dtype>f32|bf16|i32)"
    r"(?:_(?P<compute>[a-z0-9@x_]+))?$")


def _parse_label(label):
    m = _LABEL_RE.match(label)
    if not m:
        return None
    d = m.groupdict()
    d["size"] = int(d["size"])
    d["compute"] = d["compute"] or "jnp"
    return d


def _ok(rec):
    return isinstance(rec, dict) and "mcells_per_s" in rec \
        and not rec.get("suspect")


_DTYPE_SHORT = {"float32": "f32", "bfloat16": "bf16", "int32": "i32",
                None: "i32"}


def load(path):
    """(family, size, dtype) -> {compute: (label, record)}.

    Campaign records carry authoritative stencil/grid/dtype/compute
    fields (mktable.py reads them directly) — prefer those; the label
    regex is the fallback for tables without them (tests, hand-built
    files) and supplies the two things records cannot: the family of the
    copy-calibration rows (stencil is None there) and label-only variant
    suffixes like ``_n150`` (the record's compute is just "jnp")."""
    with open(path) as fh:
        results = json.load(fh)
    table = {}
    for label, rec in results.items():
        p = _parse_label(label) or {}
        if not isinstance(rec, dict):
            continue
        family = rec.get("stencil") or p.get("family")
        grid = rec.get("grid")
        size = int(grid[0]) if grid else p.get("size")
        dtype = (_DTYPE_SHORT.get(rec["dtype"], p.get("dtype"))
                 if "dtype" in rec else p.get("dtype"))
        compute = rec.get("compute") or p.get("compute")
        if compute and label.endswith("_n150") \
                and not compute.endswith("_n150"):
            compute += "_n150"
        if not (family and size and dtype and compute):
            continue
        table.setdefault((family, size, dtype), {})[compute] = (label, rec)
    return table


def _best(entries, prefixes):
    """(compute, label, mcells) of the best entry whose compute starts
    with any of ``prefixes`` (measured successes only)."""
    best = None
    for compute, (label, rec) in entries.items():
        if not _ok(rec) or not compute.startswith(tuple(prefixes)):
            continue
        mc = rec["mcells_per_s"]
        if best is None or mc > best[2]:
            best = (compute, label, mc)
    return best


def _size_verdicts(table, family, dtype, pick_a, pick_b):
    """Per-size (size, best_a, best_b) rows wherever BOTH sides measured
    — family-wide flips must survive every measured size (the cli tables
    are per-family, not per-size; cli._AUTO_FUSE_K's own rule is 'the
    fastest measured path at every size')."""
    rows = []
    for (f, size, dt), entries in sorted(table.items()):
        if f != family or dt != dtype:
            continue
        a, b = _best(entries, pick_a), _best(entries, pick_b)
        if a and b:
            rows.append((size, a, b))
    return rows


def _ev(rows):
    return "; ".join(f"{a[1]}={a[2]:.0f} vs {b[1]}={b[2]:.0f} at {s}^3"
                     for s, a, b in rows)


def _winning_k(rows):
    """The k to recommend family-wide, or None when the winning compute's
    k differs across sizes (a family flip then needs a per-size policy,
    not one k — cli's rule is 'fastest measured path at EVERY size')."""
    ks = set()
    for _, a, _ in rows:
        m = re.search(r"(\d+)", a[0])
        if not m:
            return None
        ks.add(m.group(1))
    return ks.pop() if len(ks) == 1 else None


def _sides_measured(table, family, dtype, pick_a, pick_b):
    has_a = has_b = False
    for (f, _, dt), entries in table.items():
        if f != family or dt != dtype:
            continue
        has_a = has_a or _best(entries, pick_a) is not None
        has_b = has_b or _best(entries, pick_b) is not None
    return has_a, has_b


def _resolve_suspects(table):
    """Judge the advect3d >roofline suspect and make the table's policy
    baselines consistent with the verdict, in one place (docs/STATE.md:
    150 Gcells/s f32 implies >1.2 TB/s on an 819 GB/s part).

    The TRUSTED number is the n150 rerun when it disagrees with the
    original by >15% (the original was then timing noise), else the
    original.  If the trusted number is physically impossible, the jnp
    entry is REMOVED from policy consideration (every downstream
    decision would otherwise quietly judge real kernels against a fake
    baseline); if the trusted number is the plausible rerun, it replaces
    the original as the family's jnp baseline.  Returns the advisory
    rows describing what was decided."""
    rows = []
    for (family, size, dtype), entries in sorted(table.items()):
        if family != "advect3d" or dtype != "f32":
            continue
        jnp_e, n150 = entries.get("jnp"), entries.get("jnp_n150")
        if not (jnp_e and _ok(jnp_e[1])):
            continue
        mc = jnp_e[1]["mcells_per_s"]
        ev = (f"{jnp_e[0]}={mc:.0f} Mcells/s -> "
              f"{mc * 8 / 1e3:.0f} GB/s implied")
        trusted, repl = mc, None
        if n150 and _ok(n150[1]):
            mc2 = n150[1]["mcells_per_s"]
            ev += f"; rerun {n150[0]}={mc2:.0f}"
            if abs(mc2 - mc) > 0.15 * max(mc, 1e-9):
                trusted, repl = mc2, n150  # judge the rerun instead
        if trusted * 8 / 1e3 > _HBM_PEAK_GBS:  # 1R+1W f32 GB/s
            # remove EVERY jnp-prefixed entry: jnp_n150 also matches the
            # ('jnp', 'raw', 'pallas') baseline prefixes in _best, so a
            # physically impossible rerun would otherwise keep serving
            # as the family's single-step baseline (ADVICE.md r5 medium)
            for compute in [c for c in entries if c.startswith("jnp")]:
                del entries[compute]
            rows.append(("advect3d suspect",
                         "STILL >roofline — jnp excluded as a policy "
                         "baseline", ev))
        else:
            if repl is not None:
                entries["jnp"] = repl
            rows.append(("advect3d suspect",
                         "resolved (trusted number within the roofline)",
                         ev))
    return rows


def advise(table):
    """Yield (decision, recommendation, evidence) rows.  A decision (or
    a family within one) with no measured comparison yields an explicit
    'no measured data' row — silence must never look like 'no edit
    needed'."""
    fused_like = ("fused", "padfree")
    emitted = set()

    def out(decision, rec, ev):
        emitted.add(decision)
        return decision, rec, ev

    for row in _resolve_suspects(table):
        yield out(*row)
    families = sorted({f for (f, _, _) in table})
    # family -> grid rank, from the records themselves (None when a
    # table carries no grid fields — regex-only fallback tables): the
    # fused/stream/bf16 decisions exist for 3D families only, fullgrid
    # for 2D — a pending row for the wrong rank would send the reader
    # hunting for labels that can never exist (2D has no *_fused4)
    fam_ndim = {}
    for (f, _, _), entries in table.items():
        for _, rec in entries.values():
            grid = rec.get("grid") if isinstance(rec, dict) else None
            if grid:
                fam_ndim[f] = len(grid)
                break
    # -- _AUTO_FUSE_K: f32 temporal blocking vs the best single-step
    # path (jnp/raw/pallas), judged at EVERY measured size --
    single_step = ("jnp", "raw", "pallas")
    for family in families:
        if fam_ndim.get(family) == 2:
            continue
        rows = _size_verdicts(table, family, "f32", fused_like,
                              single_step)
        if not rows:
            has_f, has_s = _sides_measured(table, family, "f32",
                                           fused_like, single_step)
            if has_f != has_s:  # one side measured, the other pending
                yield out("_AUTO_FUSE_K",
                          f"{family}: no measured comparison yet",
                          "pending: " + ("single-step baseline"
                                         if has_f else "fused/padfree"
                                         " labels"))
            continue
        wins = [a[2] > b[2] for _, a, b in rows]
        k = _winning_k(rows)
        if all(wins):
            rec = (f"{family}: fused k={k}" if k else
                   f"{family}: fused wins but the winning k varies by "
                   "size — per-size policy needed")
        elif not any(wins):
            rec = f"{family}: keep single-step"
        else:
            rec = (f"{family}: MIXED across sizes — keep/design a "
                   "size-gated policy, not a family flip")
        yield out("_AUTO_FUSE_K", rec, _ev(rows))
    # -- _AUTO_FUSE_KIND: stream vs the best tiled/padfree fused path,
    # judged at EVERY measured size --
    for family in families:
        if fam_ndim.get(family) == 2:
            continue
        rows = _size_verdicts(table, family, "f32", ("stream",),
                              fused_like)
        if not rows:
            has_st, has_t = _sides_measured(table, family, "f32",
                                            ("stream",), fused_like)
            if has_st != has_t:
                yield out("_AUTO_FUSE_KIND",
                          f"{family}: no measured comparison yet",
                          "pending: " + ("tiled/padfree labels"
                                         if has_st else "stream labels"))
            continue
        wins = [a[2] > b[2] for _, a, b in rows]
        rec = (f"{family}: stream" if all(wins) else
               f"{family}: keep tiled" if not any(wins) else
               f"{family}: MIXED across sizes — no family-wide flip")
        yield out("_AUTO_FUSE_KIND", rec, _ev(rows))
    # -- _AUTO_FUSE_K_BF16: any bf16 blocked path vs bf16 jnp, judged at
    # EVERY measured size --
    blocked_like = fused_like + ("stream",)
    for family in families:
        if fam_ndim.get(family) == 2:
            continue
        rows = _size_verdicts(table, family, "bf16", blocked_like,
                              ("jnp",))
        if not rows:
            has_b, has_j = _sides_measured(table, family, "bf16",
                                           blocked_like, ("jnp",))
            if has_b != has_j:
                yield out("_AUTO_FUSE_K_BF16",
                          f"{family}: no measured comparison yet",
                          "pending: " + ("bf16 jnp baseline" if has_b
                                         else "bf16 blocked labels"))
            continue
        wins = [a[2] > b[2] for _, a, b in rows]
        k = _winning_k(rows)
        # the winning KIND must be consistent at every measured size,
        # exactly like _winning_k's rule for k — deriving it from only
        # the largest-size row would name a kind family-wide even though
        # it lost at a measured size (ADVICE.md r5 low)
        kinds = {"stream" if a[0].startswith("stream") else "tiled/padfree"
                 for _, a, _ in rows}
        kind = kinds.pop() if len(kinds) == 1 else None
        if all(wins):
            if k and kind:
                rec = f"{family}: k={k} via {kind}"
            elif k:
                rec = (f"{family}: blocking wins at k={k} but the "
                       "winning kind is MIXED across sizes — per-size "
                       "kind policy needed")
            else:
                rec = (f"{family}: blocking wins but k varies by size — "
                       "per-size policy needed")
        elif not any(wins):
            rec = f"{family}: keep jnp"
        else:
            rec = f"{family}: MIXED across sizes — no family-wide flip"
        yield out("_AUTO_FUSE_K_BF16", rec, _ev(rows))
    # -- _PADFREE_ABOVE_BYTES: padfree vs padded at every measured size --
    verdicts = []
    for (family, size, dtype), entries in sorted(table.items()):
        pf = _best(entries, ("padfree",))
        padded = _best(entries, ("fused",))
        if pf and padded:
            verdicts.append((family, size, dtype, pf, padded,
                             pf[2] >= 0.97 * padded[2]))
    if verdicts:
        all_win = all(v[-1] for v in verdicts)
        ev = "; ".join(f"{v[3][1]}={v[3][2]:.0f} vs {v[4][1]}={v[4][2]:.0f}"
                       for v in verdicts)
        yield out("_PADFREE_ABOVE_BYTES",
                  "drop to 0 (padfree >= ~padded everywhere measured)"
                  if all_win else "keep 6 GiB threshold",
                  ev)
    # -- _AUTO_FULL_K: 2D whole-grid blocking, judged at EVERY measured
    # (size, dtype) like its siblings --
    for family in families:
        if fam_ndim.get(family) == 3:
            continue
        rows = []
        for (f, size, dt), entries in sorted(table.items()):
            if f != family:
                continue
            full = _best(entries, ("full",))
            jnp_e = entries.get("jnp")
            if full and jnp_e and _ok(jnp_e[1]):
                rows.append((size, full,
                             ("jnp", jnp_e[0], jnp_e[1]["mcells_per_s"])))
        if not rows:
            continue
        wins = [a[2] > b[2] for _, a, b in rows]
        k = _winning_k(rows)
        if all(wins):
            rec = (f"{family}: k={k}" if k else
                   f"{family}: full wins but k varies by size — "
                   "per-size policy needed")
        elif not any(wins):
            rec = f"{family}: keep jnp"
        else:
            rec = f"{family}: MIXED across sizes — no family-wide flip"
        yield out("_AUTO_FULL_K", rec, _ev(rows))
    # -- copy calibration anchor (first size with a measured success) --
    for size in (512, 256):
        c = _best(table.get(("copy", size, "f32"), {}), ("copy", "jnp"))
        if c:
            gbs = c[2] * 8 / 1e3
            yield out("copy calibration",
                      f"harness-implied HBM rate {gbs:.0f} GB/s "
                      f"(roofline {_HBM_PEAK_GBS:.0f})",
                      f"{c[1]}={c[2]:.0f} Mcells/s at {size}^3")
            break
    # -- explicit no-data rows: a decision the campaign has not yet fed
    # must say so, or silence reads as 'no edit needed' --
    for decision, pending in (
            ("_AUTO_FUSE_K", "*_fused*/padfree* + jnp/raw"),
            ("_AUTO_FUSE_KIND", "*_stream4/8"),
            ("_AUTO_FUSE_K_BF16", "*_bf16_fused8/padfree8/stream4"),
            ("_PADFREE_ABOVE_BYTES", "*_padfree* alongside *_fused*"),
            ("_AUTO_FULL_K", "2D *_full16/32"),
            ("advect3d suspect", "advect3d_*_jnp(+_n150)"),
            ("copy calibration", "copy_256/512_f32")):
        if decision not in emitted:
            yield (decision, "no measured data yet",
                   f"pending campaign labels: {pending}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--in", dest="inp", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results_r05.json"))
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args()
    table = load(args.inp)
    rows = list(advise(table))
    if args.json:
        json.dump([{"decision": d, "recommendation": r, "evidence": e}
                   for d, r, e in rows], sys.stdout, indent=1)
        print()
        return
    width = max((len(d) for d, _, _ in rows), default=0)
    for d, r, e in rows:
        print(f"{d:<{width}}  {r}\n{'':<{width}}    ({e})")


if __name__ == "__main__":
    main()
