#!/bin/bash
# Tunnel-recovery watcher: probe the TPU backend every 12 min; on the
# first healthy probe, drain the measurement campaign (measure.py brings
# its own probe-gating, timeout-recording, and wedge-abort logic — see
# the header of benchmarks/measure.py), and when no runnable labels
# remain, refresh bench.py's local cache and exit.
#
# Exactly ONE TPU process may run at a time (docs/STATE.md infra
# gotchas: a second concurrent TPU process wedged the tunnel on
# 2026-07-29), which is why this loop is strictly sequential.
#
# Usage:  nohup benchmarks/watch_tunnel.sh [logfile] &
# The round-3/4 wedges recovered passively after 1-22 h; killing a probe
# that is hanging on an already-wedged tunnel is safe (observed across
# rounds 3-4), unlike killing a live remote compile, which is what
# CAUSES the wedge.
set -u
cd "$(dirname "$0")/.." || exit 1
LOG="${1:-/tmp/watch_tunnel.log}"
echo "[watch] start $(date -u +%H:%M:%S)" >> "$LOG"
while :; do
  if timeout 120 python -c "import jax, jax.numpy as jnp; print(float(jnp.ones((8,8)).sum()))" >/dev/null 2>&1; then
    # bench FIRST: ~5 min on proven-compile-class kernels, so the round
    # has a fresh local headline even if the campaign later re-wedges
    # the tunnel on a new compile (2026-07-31: recovery lasted ~25 min
    # before a killed padfree compile re-wedged it).
    if [ ! -f .bench_cache.json ]; then
      echo "[watch] probe OK $(date -u +%H:%M:%S) — bench first (no local cache)" >> "$LOG"
      timeout 1200 python bench.py >> "${LOG%.log}.bench.log" 2>&1
    fi
    echo "[watch] probe OK $(date -u +%H:%M:%S) — draining campaign" >> "$LOG"
    python benchmarks/measure.py >> "${LOG%.log}.measure.log" 2>&1
    left=$(python - <<'EOF'
import json, re
src = open('benchmarks/measure.py').read()
labels = re.findall(r'^\s*\("([a-z0-9_@]+)",', src, re.M)
rev = int(re.search(r'^BUILDER_REV = (\d+)', src, re.M).group(1))
try:
    r = json.load(open('benchmarks/results_r04.json'))
except Exception:
    r = {}
n = 0
for l in labels:
    c = r.get(l)
    # mirror measure.main's skip rule exactly
    if c is None or ('error' in c and not (
            ('untileable' in c.get('error', '')
             or (c.get('timeout') and not c.get('suspect')))
            and c.get('builder_rev') == rev)):
        n += 1
print(n)
EOF
)
    echo "[watch] campaign pass done, $left runnable labels left" >> "$LOG"
    if [ "$left" = "0" ]; then
      echo "[watch] campaign drained — running bench.py" >> "$LOG"
      timeout 1200 python bench.py >> "${LOG%.log}.bench.log" 2>&1
      # runbook step 5 LAST: the smoke tier includes the newest compile
      # classes, and by now every campaign number is already recorded
      echo "[watch] bench done — TPU smoke tier" >> "$LOG"
      TPU_SMOKE=1 timeout 2400 python -m pytest tests -q -m tpu \
        >> "${LOG%.log}.smoke.log" 2>&1
      echo "[watch] smoke rc=$?; exiting $(date -u +%H:%M:%S)" >> "$LOG"
      exit 0
    fi
  else
    echo "[watch] probe failed $(date -u +%H:%M:%S)" >> "$LOG"
  fi
  sleep 720
done
