#!/bin/bash
# Tunnel-recovery watcher: probe the TPU backend every 12 min; on the
# first healthy probe, drain the measurement campaign (measure.py brings
# its own probe-gating, timeout-recording, and wedge-abort logic — see
# the header of benchmarks/measure.py), and when no runnable labels
# remain, refresh bench.py's local cache and exit.
#
# Exactly ONE TPU process may run at a time (docs/STATE.md infra
# gotchas: a second concurrent TPU process wedged the tunnel on
# 2026-07-29), which is why this loop is strictly sequential.
#
# Usage:  nohup benchmarks/watch_tunnel.sh [logfile] &
# The round-3/4 wedges recovered passively after 1-22 h; killing a probe
# that is hanging on an already-wedged tunnel is safe (observed across
# rounds 3-4), unlike killing a live remote compile, which is what
# CAUSES the wedge.
set -u
cd "$(dirname "$0")/.." || exit 1
LOG="${1:-/tmp/watch_tunnel.log}"
echo "[watch] start $(date -u +%H:%M:%S)" >> "$LOG"
probe_ok() {
  timeout 120 python -c "import jax, jax.numpy as jnp; print(float(jnp.ones((8,8)).sum()))" >/dev/null 2>&1
}
prev_left=-1
while :; do
  if probe_ok; then
    # bench FIRST: ~5 min on proven-compile-class kernels, so the round
    # has a fresh local headline even if the campaign later re-wedges
    # the tunnel on a new compile (2026-07-31: recovery lasted ~25 min
    # before a killed padfree compile re-wedged it).
    if [ ! -f .bench_cache.json ]; then
      echo "[watch] probe OK $(date -u +%H:%M:%S) — bench first (no local cache)" >> "$LOG"
      timeout 1200 python bench.py >> "${LOG%.log}.bench.log" 2>&1
    fi
    echo "[watch] probe OK $(date -u +%H:%M:%S) — draining campaign" >> "$LOG"
    python benchmarks/measure.py >> "${LOG%.log}.measure.log" 2>&1
    # single definition of the skip rule lives in measure.py (advisor r4):
    # --count-runnable never contacts the backend, so it is wedge-safe.
    # stderr goes to the measure log and a non-numeric/empty count is
    # surfaced, not silently looped on (a corrupt results table would
    # otherwise spin the watcher forever with a blank count)
    left=$(python benchmarks/measure.py --count-runnable \
           2>> "${LOG%.log}.measure.log")
    case "$left" in
      ''|*[!0-9]*)
        echo "[watch] count-runnable failed (got '$left') — see" \
             "${LOG%.log}.measure.log" >> "$LOG"
        sleep 720
        continue;;
    esac
    echo "[watch] campaign pass done, $left runnable labels left" >> "$LOG"
    # Drained = zero runnable labels OR no forward progress across two
    # consecutive passes.  Some labels error deterministically but are
    # deliberately retried by the skip rule (expected OOMs, Mosaic
    # INTERNAL — transient-shaped), so the count may never reach 0; a
    # pass that changes nothing means every remaining label is one of
    # those, and re-running them forever would starve bench + smoke.
    # The re-probe guards the other no-progress cause: a pass that
    # aborted at its front gate because the tunnel re-wedged mid-loop.
    if [ "$left" = "0" ] || [ "$left" = "$prev_left" ]; then
      if ! probe_ok; then
        echo "[watch] no progress but tunnel re-wedged — waiting" >> "$LOG"
        sleep 720
        continue
      fi
      echo "[watch] campaign drained ($left permanently-erroring labels" \
           "left) — running bench.py" >> "$LOG"
      timeout 1200 python bench.py >> "${LOG%.log}.bench.log" 2>&1
      # runbook step 5 LAST: the smoke tier includes the newest compile
      # classes, and by now every campaign number is already recorded
      echo "[watch] bench done — TPU smoke tier" >> "$LOG"
      TPU_SMOKE=1 timeout 2400 python -m pytest tests -q -m tpu \
        >> "${LOG%.log}.smoke.log" 2>&1
      echo "[watch] smoke rc=$?; exiting $(date -u +%H:%M:%S)" >> "$LOG"
      exit 0
    fi
    prev_left=$left
  else
    echo "[watch] probe failed $(date -u +%H:%M:%S)" >> "$LOG"
  fi
  sleep 720
done
