"""HBM streaming-rate probes: can anything beat Mosaic's auto-pipeline?

Round-3 finding (docs/STATE.md §4): a pure copy kernel (out = 2*in)
through ``pallas_call``'s automatic pipeline tops out at ~330 GB/s on this
v5e, independent of block shape, grid arity, and dimension_semantics —
while the XLA-fused jnp path streams 640-710 GB/s on the same chip.  That
pipeline rate bounds every single-step Pallas kernel (~40 Gcells/s) and
sets the fused kernels' ceiling.  ``pl.Buffered(buffer_count > 2)`` is not
supported by this toolchain, so the remaining lever is a MANUAL pipeline:
whole-array ANY-memory-space refs + ``pltpu.make_async_copy`` chunk DMAs
with N rotating VMEM slots (the double-buffering pattern in the public
Pallas TPU docs).

Probes (each its own label; run on a HEALTHY, otherwise-idle tunnel):
  auto_copy      pallas_call auto-pipeline baseline (reproduces the 330)
  manual2_copy   manual pipeline, 2 VMEM slots
  manual4_copy   manual pipeline, 4 slots (deeper DMA overlap)
  jnp_copy       XLA's own fused stream (the 640-710 reference point)
  manualNs_copy  store-pipelined variant: rotating OUT slots with async
      VMEM->HBM copies too (the plain manual store is a direct write; if
      Mosaic serializes it against the next chunk's compute, the "s"
      variants measure faster — diagnosing whether the streaming kernel
      needs store rotation).  Chunk auto-halved: 2N slots must fit VMEM.
  autoK_stencil / manualN[s]_stencil_kK — the DECISIVE set for the fused
      ceiling (VERDICT r3 item 5): identical k-micro-step 5-point stencil
      compute per chunk (the fused kernels' arithmetic intensity), auto
      vs manual pipeline.  If manual streams faster AT THIS INTENSITY, a
      manual-pipeline fused kernel is worth building; if both sit at the
      auto rate, the 330 GB/s is the DMA engine, not the scheduler, and
      the writeup closes the avenue.

Usage: python benchmarks/pipeline_probe.py [--probe NAME ...] [--out F]
Writes/merges JSON records (GB/s) into benchmarks/pipeline_probe.json.
Interpret-mode smoke: tests/test_pipeline_probe.py runs every probe tiny
on CPU, so the harness itself is CI-covered before it ever costs tunnel
time.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_cuda_process_tpu.ops.pallas.kernels import (
    _VMEM_LIMIT_BYTES,
    _interpret_default,
)


def _double(block):
    return block * 2.0


def _stencil_transform(k, roll):
    """k micro-steps of an in-chunk 5-point y/x stencil (rolls on the
    minor axes only, so chunking on z stays embarrassingly parallel).
    NOT a correct heat step across chunk boundaries — this is a traffic
    probe at the fused kernels' arithmetic intensity, not a solver.

    ``roll`` is ``pltpu.roll`` on hardware and ``jnp.roll`` in interpret
    mode (pltpu.roll does not lower on the CPU interpreter; for this
    symmetric Laplacian the two are bit-identical), injected so the CI
    equivalence test exercises the SAME body the chip measures.
    """

    def transform(block):
        def micro(_, u):
            lap = (roll(u, 1, 1) + roll(u, -1, 1)
                   + roll(u, 1, 2) + roll(u, -1, 2) - 4.0 * u)
            return u + 0.25 * lap

        return jax.lax.fori_loop(0, k, micro, block)

    return transform


def _auto_pipeline(shape, dtype, bz, interpret, transform):
    """pallas_call auto-pipeline: the measured-330 baseline."""
    Z, Y, X = shape

    def kernel(i_ref, o_ref):
        o_ref[...] = transform(i_ref[...])

    return pl.pallas_call(
        kernel,
        grid=(Z // bz,),
        in_specs=[pl.BlockSpec((bz, Y, X), lambda i: (i, 0, 0))],
        out_specs=pl.BlockSpec((bz, Y, X), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT_BYTES),
    )


def _manual_pipeline_kernel(nslots, bz, nchunks, transform, i_hbm, o_hbm):
    """N-slot rotating DMA pipeline over z-chunks of a whole-array ref.

    Loads overlap compute/stores: slot s starts its load up to nslots-1
    chunks ahead of consumption.  The store is a plain HBM write from
    VMEM (Mosaic lowers it as a DMA); a deeper variant could rotate
    output slots too, but the load path is where the round-3 measured
    pipeline stalled.
    """

    def body(scratch, sems):
        def dma(slot, chunk):
            return pltpu.make_async_copy(
                i_hbm.at[pl.ds(chunk * bz, bz)],
                scratch.at[slot],
                sems.at[slot],
            )

        for s in range(min(nslots - 1, nchunks)):  # warm-up (bounded:
            dma(s, s).start()  # tiny grids must not read past the array)

        def loop(chunk, _):
            slot = jax.lax.rem(chunk, nslots)
            nxt = chunk + nslots - 1

            @pl.when(nxt < nchunks)
            def _():
                dma(jax.lax.rem(nxt, nslots), nxt).start()

            dma(slot, chunk).wait()
            o_hbm[pl.ds(chunk * bz, bz)] = transform(scratch[slot])
            return ()

        jax.lax.fori_loop(0, nchunks, loop, ())

    pl.run_scoped(
        body,
        scratch=pltpu.VMEM(
            (nslots, bz) + tuple(i_hbm.shape[1:]), i_hbm.dtype),
        sems=pltpu.SemaphoreType.DMA((nslots,)),
    )


def _wrap_manual(shape, dtype, interpret, body_fn):
    """The one pallas_call wrapper both manual variants share — identical
    specs/limits so the store-pipelined vs direct-store comparison always
    measures the same conditions."""

    return pl.pallas_call(
        body_fn,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(shape, dtype),
        interpret=interpret,
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT_BYTES),
    )


def _manual_pipeline(shape, dtype, bz, nslots, interpret, transform):
    nchunks = shape[0] // bz

    def kernel(i_hbm, o_hbm):
        _manual_pipeline_kernel(nslots, bz, nchunks, transform, i_hbm,
                                o_hbm)

    return _wrap_manual(shape, dtype, interpret, kernel)


def _manual_store_pipeline_kernel(nslots, bz, nchunks, transform, i_hbm,
                                  o_hbm):
    """Both directions pipelined: rotating load slots AND rotating store
    slots with async VMEM->HBM copies (waited ``nslots`` chunks later).

    The plain manual probes store via a direct ``o_hbm[...] = value``
    write; if Mosaic serializes that store against the next chunk's
    compute, these variants will measure faster — diagnosing whether the
    streaming kernel needs store-slot rotation too.
    """

    def body(inbuf, insems, outbuf, outsems):
        def in_dma(slot, chunk):
            return pltpu.make_async_copy(
                i_hbm.at[pl.ds(chunk * bz, bz)], inbuf.at[slot],
                insems.at[slot])

        def out_dma(slot, chunk):
            return pltpu.make_async_copy(
                outbuf.at[slot], o_hbm.at[pl.ds(chunk * bz, bz)],
                outsems.at[slot])

        for s in range(min(nslots - 1, nchunks)):  # warm-up (bounded)
            in_dma(s, s).start()

        def loop(chunk, _):
            slot = jax.lax.rem(chunk, nslots)
            nxt = chunk + nslots - 1

            @pl.when(nxt < nchunks)
            def _():
                in_dma(jax.lax.rem(nxt, nslots), nxt).start()

            in_dma(slot, chunk).wait()

            # the store slot is reused nslots chunks later: its previous
            # copy must have left the buffer by then
            @pl.when(chunk >= nslots)
            def _():
                out_dma(slot, chunk - nslots).wait()

            outbuf[slot] = transform(inbuf[slot])
            out_dma(slot, chunk).start()
            return ()

        jax.lax.fori_loop(0, nchunks, loop, ())
        for s in range(min(nslots, nchunks)):  # drain the last stores
            chunk = nchunks - 1 - s
            out_dma(chunk % nslots, chunk).wait()

    pl.run_scoped(
        body,
        inbuf=pltpu.VMEM((nslots, bz) + tuple(i_hbm.shape[1:]),
                         i_hbm.dtype),
        insems=pltpu.SemaphoreType.DMA((nslots,)),
        outbuf=pltpu.VMEM((nslots, bz) + tuple(i_hbm.shape[1:]),
                          i_hbm.dtype),
        outsems=pltpu.SemaphoreType.DMA((nslots,)),
    )


def _manual_store_pipeline(shape, dtype, bz, nslots, interpret,
                           transform):
    nchunks = shape[0] // bz

    def kernel(i_hbm, o_hbm):
        _manual_store_pipeline_kernel(nslots, bz, nchunks, transform,
                                      i_hbm, o_hbm)

    return _wrap_manual(shape, dtype, interpret, kernel)


def build_probe(name, shape, dtype=jnp.float32, bz=16, interpret=None):
    """Return a jittable fn implementing the named strategy.

    Copy probes (``*_copy``) compute ``2*x``; stencil probes
    (``autoK_stencil`` / ``manualN_stencil_kK``) run k in-chunk 5-point
    micro-steps per pass — the fused kernels' arithmetic intensity.
    """
    if interpret is None:
        interpret = _interpret_default()
    if name == "jnp_copy":
        return lambda x: x * 2.0
    k = _probe_k(name)
    if k == 1:
        transform = _double
    else:
        roll = jnp.roll if interpret else pltpu.roll
        transform = _stencil_transform(k, roll)
    if name.startswith("auto"):
        return _auto_pipeline(shape, dtype, bz, interpret, transform)
    if name.startswith("manual"):
        nslots, store_pipe = _probe_nslots(name)
        builder = (_manual_store_pipeline if store_pipe
                   else _manual_pipeline)
        return builder(shape, dtype, bz, nslots, interpret, transform)
    raise ValueError(f"unknown probe {name!r}")


def _probe_k(name):
    """Micro-steps per pass encoded in the probe name (1 for copies)."""
    try:
        if name.endswith("_stencil"):
            return int(name[len("auto"):-len("_stencil")])
        if "_stencil_k" in name:
            return int(name[name.index("_stencil_k") + len("_stencil_k"):])
    except ValueError:
        # e.g. "manual2_stencil" / "auto_stencil": fail as a usage error,
        # not a confusing int() traceback in the results record
        raise ValueError(f"unknown probe {name!r}") from None
    return 1


def _probe_nslots(name):
    """(slot count, store-pipelined?) encoded in a manual probe's name —
    ``manual4_copy`` = 4 load slots, direct stores; ``manual4s_copy`` =
    4 load + 4 store slots (async store copies)."""
    spec = name[len("manual"):name.index("_")]
    store_pipe = spec.endswith("s")
    return int(spec.rstrip("s")), store_pipe


PROBES = ("jnp_copy", "auto_copy", "manual2_copy", "manual4_copy",
          "manual2s_copy", "manual4s_copy",
          "auto4_stencil", "manual2_stencil_k4", "manual4_stencil_k4",
          "manual4s_stencil_k4")


def measure_probe(name, shape=(512, 512, 512), bz=16, steps=30, reps=3):
    """GB/s for one probe via the N-vs-4N scan difference (bench.py's
    dispatch-cancelling method)."""
    if name.startswith("manual") and _probe_nslots(name)[1]:
        # store-pipelined variants hold 2*nslots slots: halve the chunk
        # so the scratch stays under the 100 MiB scoped-VMEM limit at
        # the default 512^3 shape (4+4 slots x 8 MiB = 64 MiB)
        bz = min(bz, 8)
    fn = build_probe(name, shape, bz=bz, interpret=False)

    def scan_n(n):
        def run(x):
            return jax.lax.fori_loop(0, n, lambda _, v: fn(v), x)

        return jax.jit(run)

    x = jnp.ones(shape, jnp.float32)
    run_a, run_b = scan_n(steps), scan_n(4 * steps)
    float(jnp.sum(run_a(x)))  # compile+warm
    float(jnp.sum(run_b(x)))

    def best(run):
        b = math.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            float(jnp.sum(run(x)))
            b = min(b, time.perf_counter() - t0)
        return b

    t = (best(run_b) - best(run_a)) / (3 * steps)
    bytes_per_step = 2 * math.prod(shape) * 4  # 1R + 1W f32
    rec = {"gb_per_s": round(bytes_per_step / t / 1e9, 1),
           "ms_per_pass": round(t * 1e3, 3), "bz": bz,
           "shape": list(shape)}
    k = _probe_k(name)
    if k > 1:
        # effective cell rate if a fused kernel streamed at this rate
        rec["mcells_per_s_equiv"] = round(
            math.prod(shape) * k / t / 1e6, 1)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", nargs="*", default=list(PROBES))
    ap.add_argument("--bz", type=int, default=16)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "pipeline_probe.json"))
    args = ap.parse_args()
    results = {}
    if os.path.exists(args.out):
        with open(args.out) as fh:
            results = json.load(fh)
    for name in args.probe:
        try:
            rec = measure_probe(name, bz=args.bz)
        except Exception as e:  # noqa: BLE001 — record & continue
            rec = {"error": f"{type(e).__name__}: {str(e)[:600]}"}
        rec["measured_at"] = time.time()
        results[f"{name}_bz{args.bz}"] = rec
        print(f"[probe] {name}: {rec}", file=sys.stderr)
        with open(args.out + ".tmp", "w") as fh:
            json.dump(results, fh, indent=1, sort_keys=True)
        os.replace(args.out + ".tmp", args.out)
    print(json.dumps(results, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
