"""Bisect the bf16 temporal-blocking Mosaic compile hang.

Round-3 finding (docs/STATE.md): bf16 fused k=4 was structurally
misaligned (sublane tile 16 vs 8 — fixed, now declines cleanly), but the
aligned k=8 variant HANGS the Mosaic compile (>20 min at 256^3 with the
auto-picked 64x64 tiles).  This script walks the candidate ladder —
smaller tiles first (less code after unrolling the 8 micro-steps), then
grid sizes — each attempt in its own subprocess with a hard timeout, so a
hang costs one attempt and the results name the exact frontier.

Run it ONLY when the TPU tunnel is healthy and nothing else is using the
chip (a killed compile can wedge the tunnel — docs/STATE.md).

Usage: python benchmarks/bisect_bf16_fused.py [--timeout 600]
"""

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, grid, k, tiles, padfree) — smallest/cheapest first so the first
# hang gives the tightest bound.  k=8 now lowers as a fori_loop (constant
# program size — the candidate fix for the round-3 unrolled-compile hang);
# the padfree rungs cover the 9-block kernel's compile too.
ATTEMPTS = [
    ("256_k8_t16", (256, 256, 256), 8, (16, 16), False),
    ("256_k8_t32", (256, 256, 256), 8, (32, 32), False),
    ("256_k8_t64", (256, 256, 256), 8, (64, 64), False),  # the known ~hang
    ("256_k8_t32_padfree", (256, 256, 256), 8, (32, 32), True),
    ("512_k8_t32", (512, 512, 512), 8, (32, 32), False),
    ("512_k8_t32_padfree", (512, 512, 512), 8, (32, 32), True),
]

_CHILD = """\
import sys, time, math
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp
from mpi_cuda_process_tpu import init_state, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

grid, k, tiles, padfree = {grid!r}, {k!r}, {tiles!r}, {padfree!r}
st = make_stencil("heat3d", dtype=jnp.bfloat16)
step = make_fused_step(st, grid, k, tiles=tiles, padfree=padfree)
assert step is not None, "untileable"
f = init_state(st, grid, kind="pulse")
t0 = time.time()
out = step(f)
s = float(jnp.sum(out[0].astype(jnp.float32)))
t_compile = time.time() - t0
# quick throughput probe: one scanned pass of 4 calls (32 steps)
run = make_runner(step, 4)
float(jnp.sum(run(init_state(st, grid, kind="pulse"))[0].astype(jnp.float32)))
t0 = time.time()
float(jnp.sum(run(init_state(st, grid, kind="pulse"))[0].astype(jnp.float32)))
dt = time.time() - t0
print("RESULT", t_compile, math.prod(grid) * 4 * k / dt / 1e6, flush=True)
"""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=int, default=600)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bisect_bf16.json"))
    args = ap.parse_args()

    results = {}
    for label, grid, k, tiles, padfree in ATTEMPTS:
        code = _CHILD.format(repo=_REPO, grid=grid, k=k, tiles=tiles,
                             padfree=padfree)
        t0 = time.time()
        try:
            p = subprocess.run([sys.executable, "-c", code], cwd=_REPO,
                               capture_output=True, text=True,
                               timeout=args.timeout)
            out = p.stdout.strip().splitlines()
            if p.returncode == 0 and out and out[-1].startswith("RESULT"):
                _, t_compile, mcells = out[-1].split()
                results[label] = {"ok": True,
                                  "compile_s": round(float(t_compile), 1),
                                  "mcells_per_s": round(float(mcells), 1)}
            else:
                tail = (p.stderr or "")[-600:]
                results[label] = {"ok": False, "rc": p.returncode,
                                  "stderr_tail": tail}
        except subprocess.TimeoutExpired:
            results[label] = {"ok": False,
                              "error": f"timeout {args.timeout}s (hang)"}
            # a killed compile often wedges the tunnel; stop the ladder
            results["_aborted"] = ("stopped after first hang to protect "
                                   "the tunnel")
            break
        results[label]["wall_s"] = round(time.time() - t0, 1)
        print(f"[bisect] {label}: {results[label]}", file=sys.stderr)
    with open(args.out, "w") as fh:
        json.dump(results, fh, indent=1, sort_keys=True)
        fh.write("\n")
    print(json.dumps(results))


if __name__ == "__main__":
    main()
