"""In-kernel remote-DMA exchange == the ppermute schedule, bit for bit.

``make_sharded_fused_step(kind="stream", exchange="rdma")`` replaces
every XLA-level ``ppermute`` of the streaming sharded steppers with the
Pallas ring-exchange kernels (``ops/pallas/remote.py`` via
``halo.RdmaTransport``).  Pinned here:

  * BIT-exact equivalence vs the same configuration with
    ``exchange="ppermute"`` across kinds of traffic (heat3d single
    field, wave3d leapfrog carry, sor3d red-black parity), mesh
    families (z-only, y-only, 2-axis), dtypes (f32, bf16), the
    overlap/pipeline compositions, and call counts 0/1/2 — the
    interpret-mode execution path (the loopback VMEM-ring kernel + the
    documented all_gather ring shift) runs the kernels end-to-end on
    the CPU backend;
  * the ZERO-PPERMUTE jaxpr gate (``jaxprcheck.assert_rdma_step_
    structure``): no collective-permute anywhere in the rdma step; the
    COMPILED build additionally carries zero all_gather and >= 1
    remote ``dma_start`` (the exchange lives inside the kernels);
  * semaphore-pairing / double-buffer structure of the ring kernel
    itself (chunk counts, 2-slot rings, credit accounting — read off
    the traced kernel jaxpr);
  * the never-silently-falls-back contract: non-stream kinds,
    periodic wrap, 2D grids, unsharded runs, and unknown modes raise
    with the reason (stepper AND cli);
  * the costmodel's in-kernel ICI counters cross-check against traced
    steps (the analytic chunk model and the kernel read the SAME
    ``remote.pick_chunks``), and the budget's config-5 rdma rows are
    byte-pinned with the slab-transient terms deleted.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.parallel.stepper import (
    make_sharded_fused_step,
    make_sharded_temporal_step,
)
from mpi_cuda_process_tpu.utils.jaxprcheck import (
    assert_rdma_step_structure,
    check_pipeline_structure,
    count_primitive,
    count_remote_dma,
)


def _build_pair(name, grid, mesh_shape, k, overlap=False, pipeline=False,
                **kw):
    """(stencil, mesh, ppermute_step, rdma_step), both interpret-mode."""
    st = make_stencil(name, **kw)
    mesh = make_mesh(mesh_shape)
    pp = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                 kind="stream", overlap=overlap,
                                 pipeline=pipeline)
    rd = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                 kind="stream", overlap=overlap,
                                 pipeline=pipeline, exchange="rdma")
    assert pp is not None and rd is not None, (name, grid, mesh_shape)
    assert getattr(rd, "_exchange", None) == "rdma"
    assert getattr(rd, "_rdma_backend", None) == "interpret-emulated"
    if overlap:
        assert getattr(rd, "_overlap_active", False), \
            "overlap geometry unexpectedly declined — fix the test shape"
    if pipeline:
        assert getattr(rd, "_pipeline_active", False)
    return st, mesh, pp, rd


def _run_n(step, fields, n, pipeline=False):
    if n == 0:
        return fields
    if pipeline:
        return jax.jit(make_runner(step, n, jit=False))(fields)
    jf = jax.jit(step)
    for _ in range(n):
        fields = jf(fields)
    return fields


def _assert_bitexact(got, ref, ctx):
    for i, (g, r) in enumerate(zip(got, ref)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r),
                                      err_msg=f"field {i} of {ctx}")


# ------------------------------------------------------- equivalence

# The acceptance anchor: every traffic kind on every mesh family, calls
# 0/1/2 in one build (the 2-call run makes the second pass consume
# slabs produced THROUGH the rdma ring — a wrong-neighbor bug cannot
# survive two exchanges).  Heavier redundant combos ride the slow tier.
@pytest.mark.parametrize("name,grid,mesh_shape,kw", [
    ("heat3d", (48, 32, 128), (2, 1, 1), {}),
    # heat3d 2-axis f32 rides slow: the 2-axis ring geometry stays in the
    # default tier via the bf16 leg below (which alone also pins sublane
    # alignment)
    pytest.param("heat3d", (48, 32, 128), (2, 2, 1), {},
                 marks=pytest.mark.slow),
    # wave3d (multi-field carry) default pin is the cheaper z-only mesh;
    # its 2-axis variant rides slow
    ("wave3d", (48, 32, 128), (2, 1, 1), {}),
    ("heat3d", (24, 32, 128), (1, 2, 1), {}),   # y-only: z bc dummies
    # bf16: the ring chunks are sublane-16 aligned (pick_chunks)
    ("heat3d", (48, 32, 128), (2, 2, 1), {"dtype": jnp.bfloat16}),
    # red-black parity across both shard origins through the rdma ring
    pytest.param("sor3d", (96, 32, 128), (2, 2, 1), {},
                 marks=pytest.mark.slow),
    pytest.param("wave3d", (48, 32, 128), (2, 2, 1), {},
                 marks=pytest.mark.slow),
    pytest.param("wave3d", (48, 32, 128), (2, 2, 1),
                 {"dtype": jnp.bfloat16}, marks=pytest.mark.slow),
])
def test_rdma_matches_ppermute_bitexact(name, grid, mesh_shape, kw):
    st, mesh, pp, rd = _build_pair(name, grid, mesh_shape, 4, **kw)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    for n in (0, 1, 2):
        _assert_bitexact(_run_n(rd, fields, n), _run_n(pp, fields, n),
                         (name, mesh_shape, kw, n))


# Default tier covers overlap alone and the full overlap+pipeline
# composition on the z-only mesh; the 2-axis recombinations ride the
# slow tier with a coverage argument — 2-axis rdma value equivalence is
# already default above, and the 2-axis overlap+pipeline DEPENDENCE
# structure is default via test_rdma_pipeline_structure (trace-only).
@pytest.mark.parametrize("name,mesh_shape,overlap,pipeline", [
    # overlap-without-pipeline rides slow: the overlap+pipeline leg below
    # exercises the same overlap splice plus the scan carry on top
    pytest.param("heat3d", (2, 1, 1), True, False,
                 marks=pytest.mark.slow),
    ("heat3d", (2, 1, 1), True, True),
    pytest.param("heat3d", (2, 2, 1), True, False,
                 marks=pytest.mark.slow),
    pytest.param("heat3d", (2, 2, 1), True, True,
                 marks=pytest.mark.slow),
    pytest.param("wave3d", (2, 2, 1), False, True,
                 marks=pytest.mark.slow),
    pytest.param("wave3d", (2, 2, 1), True, True,
                 marks=pytest.mark.slow),
])
def test_rdma_composes_with_overlap_and_pipeline(name, mesh_shape,
                                                 overlap, pipeline):
    grid = (48, 32, 128)
    st, mesh, pp, rd = _build_pair(name, grid, mesh_shape, 4,
                                   overlap=overlap, pipeline=pipeline)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    for n in (1, 2):
        _assert_bitexact(
            _run_n(rd, fields, n, pipeline=pipeline),
            _run_n(pp, fields, n, pipeline=pipeline),
            (name, mesh_shape, overlap, pipeline, n))


# --------------------------------------------------- jaxpr structure

def test_zero_ppermute_gate_interpret_and_compiled():
    """The headline gate: no XLA collective-permute in the rdma step —
    interpret mode (what these tests execute) carries the documented
    all_gather emulation, the compiled build carries NOTHING but the
    in-kernel remote DMAs."""
    grid, mesh_shape = (48, 32, 128), (2, 2, 1)
    st = make_stencil("heat3d")
    mesh = make_mesh(mesh_shape)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    _, _, pp, rd = _build_pair("heat3d", grid, mesh_shape, 4)
    rep = assert_rdma_step_structure(jax.make_jaxpr(rd)(fields),
                                     compiled=False)
    assert rep["n_ppermute"] == 0
    # the ppermute step really does ppermute (the gate is not vacuous)
    assert count_primitive(jax.make_jaxpr(pp)(fields), "ppermute") > 0

    compiled = make_sharded_fused_step(st, mesh, grid, 4,
                                       interpret=False, kind="stream",
                                       exchange="rdma")
    assert compiled._rdma_backend == "pallas-rdma"
    rep = assert_rdma_step_structure(jax.make_jaxpr(compiled)(fields),
                                     compiled=True)
    assert rep["n_remote_dma"] > 0 and rep["n_all_gather"] == 0


@pytest.mark.parametrize("mesh_shape", [(2, 1, 1), (2, 2, 1)])
def test_rdma_pipeline_structure(mesh_shape):
    """One exchange round per scan iteration + two-sided interior
    independence, under the rdma exchange eqns — the same contract the
    ppermute pipeline pins, now transport-agnostic (also run by
    scripts/check_pipeline_structure.py --exchange rdma from tier1)."""
    rep = check_pipeline_structure("heat3d", (48, 32, 128), mesh_shape,
                                   4, exchange="rdma")
    assert rep["n_ppermute"] > 0  # exchange rounds (rdma eqns), per iter
    assert not rep["interior_depends_on_exchange"]
    assert not rep["exchange_depends_on_interior"]
    assert rep["compiled"]["n_ppermute"] == 0
    assert rep["compiled"]["n_remote_dma"] > 0


def test_ring_kernel_semaphore_pairing_and_double_buffering():
    """Protocol accounting of one compiled ring-exchange call, read off
    the traced kernel jaxpr: 2 directions x nchunks remote DMAs; every
    remote send paired with a wait; barrier (2 signals) + one credit
    signal per drained chunk; 2-slot (double-buffered) rings."""
    from mpi_cuda_process_tpu.ops.pallas.remote import (
        _NSLOTS,
        build_ring_exchange_call,
        pick_chunks,
    )

    shape, dtype = (4, 32, 128), jnp.float32
    axis, nc = pick_chunks(shape, 4)
    assert nc > 1, "test shape must exercise double buffering"
    call, meta = build_ring_exchange_call(shape, dtype, remote=True,
                                          interpret=False,
                                          collective_id=3)
    assert meta["nchunks"] == nc and meta["nslots"] == _NSLOTS == 2
    nbr = jnp.zeros((2,), jnp.int32)
    slab = jnp.zeros(shape, dtype)
    closed = jax.make_jaxpr(lambda n, h, l: call(n, h, l))(
        nbr, slab, slab)

    n_remote = count_remote_dma(closed)
    assert n_remote == 2 * nc == meta["remote_dma_per_call"]

    from mpi_cuda_process_tpu.utils.jaxprcheck import iter_jaxprs

    prims = {}
    for jx in iter_jaxprs(closed.jaxpr):
        for e in jx.eqns:
            prims[e.primitive.name] = prims.get(e.primitive.name, 0) + 1
    # one barrier; signals = 2 barrier + 2*nc credits
    assert prims.get("get_barrier_semaphore") == 1
    assert prims.get("semaphore_signal") == 2 + 2 * nc
    # waits = 1 barrier + 2*(nc-2) in-loop credits + 2 epilogue credits
    assert prims.get("semaphore_wait") == 1 + 2 * (nc - 2) + 2
    # dma_start total = per direction (nc loads + nc transfers + nc
    # drains); every one has a matching wait (send waits included)
    assert prims.get("dma_start") == 3 * 2 * nc
    assert prims.get("dma_wait") == 3 * 2 * nc + n_remote  # +wait_send


def test_pick_chunks_alignment_rules():
    from mpi_cuda_process_tpu.ops.pallas.remote import pick_chunks

    # f32 (sublane 8): y axis hosts 4 tile-aligned chunks
    assert pick_chunks((4, 32, 128), 4) == (1, 4)
    # y extent below the sublane tile: fall to the free z axis
    assert pick_chunks((24, 4, 128), 4) == (0, 4)
    # bf16 (sublane 16): y chunking needs 16-row chunks
    assert pick_chunks((4, 64, 128), 2) == (1, 4)
    # y rejected at nc=4 (8-row chunks misalign bf16's sublane-16);
    # the ladder prefers MORE chunks on the offset-free z axis over
    # fewer on y
    assert pick_chunks((4, 32, 128), 2) == (0, 4)
    # nothing divides: single chunk (degenerate ring, still correct)
    assert pick_chunks((3, 5, 128), 4) == (0, 1)


# ------------------------------------------------- forced-mode raises

def test_rdma_raises_off_the_streaming_kind():
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    with pytest.raises(ValueError, match="streaming kernel family"):
        make_sharded_fused_step(st, mesh, (48, 32, 128), 4,
                                interpret=True, kind="padfree",
                                exchange="rdma")
    with pytest.raises(ValueError, match="streaming kernel family"):
        make_sharded_fused_step(st, mesh, (48, 32, 128), 4,
                                interpret=True, exchange="rdma")
    with pytest.raises(ValueError, match="guard-frame"):
        make_sharded_fused_step(st, mesh, (48, 32, 128), 4,
                                interpret=True, kind="stream",
                                periodic=True, exchange="rdma")
    with pytest.raises(ValueError, match="unknown exchange"):
        make_sharded_fused_step(st, mesh, (48, 32, 128), 4,
                                interpret=True, kind="stream",
                                exchange="nvlink")


def test_rdma_raises_on_2d():
    st = make_stencil("heat2d")
    mesh = make_mesh((2,))
    with pytest.raises(ValueError, match="3D-only"):
        make_sharded_temporal_step(st, mesh, (64, 128), 8,
                                   interpret=True, exchange="rdma")


def test_cli_rdma_validation():
    """cli.build: every unsupported --exchange rdma combination raises
    with the reason, before any build work."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.config import RunConfig

    base = dict(stencil="heat3d", grid=(48, 32, 128), iters=8,
                exchange="rdma")
    with pytest.raises(ValueError, match="--fuse"):
        cli.build(RunConfig(**base))
    with pytest.raises(ValueError, match="--mesh"):
        cli.build(RunConfig(**base, fuse=4, fuse_kind="stream"))
    with pytest.raises(ValueError, match="stream"):
        cli.build(RunConfig(**base, fuse=4, mesh=(2, 1, 1)))
    with pytest.raises(ValueError, match="guard-frame"):
        cli.build(RunConfig(**base, fuse=4, fuse_kind="stream",
                            mesh=(2, 1, 1), periodic=True))


def test_cli_rdma_builds_and_tags_the_step():
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.config import RunConfig

    st, step, fields, start = cli.build(RunConfig(
        stencil="heat3d", grid=(48, 32, 128), iters=8, fuse=4,
        fuse_kind="stream", mesh=(2, 1, 1), exchange="rdma"))
    assert getattr(step, "_exchange", None) == "rdma"
    assert getattr(step, "_rdma_backend", None) == "interpret-emulated"


# --------------------------------------------- costmodel and budget

@pytest.mark.parametrize("mesh_shape", [(2, 1, 1), (2, 2, 1)])
def test_costmodel_rdma_counters_crosscheck_traced_step(mesh_shape):
    """The analytic in-kernel ICI counters equal the traced compiled
    step's remote-DMA count exactly (shared pick_chunks — but this
    test pins the WIRING: sites per field per axis, corners included)."""
    from mpi_cuda_process_tpu.obs import costmodel

    st = make_stencil("heat3d")
    cc = costmodel.rdma_crosscheck(st, (48, 32, 128), mesh_shape, 4)
    assert cc is not None and cc["match"], cc
    cs = costmodel.comm_stats(st, (48, 32, 128), mesh_shape, fuse=4,
                              fuse_kind="stream", exchange="rdma")
    assert cs["exchange"] == "rdma"
    assert cs["ppermute_rounds_per_pass"] == 0
    assert cs["slab_operand_bytes"] is None
    # ICI payload identical to the ppermute schedule (same slabs)
    pp = costmodel.comm_stats(st, (48, 32, 128), mesh_shape, fuse=4,
                              fuse_kind="stream")
    assert cs["ici_bytes_per_pass"] == pp["ici_bytes_per_pass"]


def test_costmodel_rdma_crosscheck_degrades_on_unhostable_mesh():
    from mpi_cuda_process_tpu.obs import costmodel

    st = make_stencil("wave3d")
    assert costmodel.rdma_crosscheck(st, (4096,) * 3, (8, 8, 1), 4) \
        is None


def test_budget_config5_rdma_rows_byte_pinned():
    """The acceptance pin: config-5 rdma rows on BOTH mesh families and
    dtypes, slab-transient terms deleted — the totals are mesh-shape
    independent (state + double buffer + 10% only)."""
    from mpi_cuda_process_tpu.utils import budget

    pins = {"float32": 14_173_392_076, "bfloat16": 7_086_696_038}
    for mesh in [(64, 1, 1), (8, 8, 1)]:
        for dt, want in pins.items():
            st = make_stencil("wave3d", dtype=jnp.dtype(dt))
            total, parts = budget.estimate_run_bytes(
                st, (4096,) * 3, mesh=mesh, fuse=4, fuse_kind="stream",
                exchange="rdma")
            assert total == want, (mesh, dt, total)
            labels = [lbl for lbl, _ in parts]
            assert any("VMEM rings" in lbl for lbl in labels), labels
            assert not any("operands only" in lbl and b
                           for lbl, b in parts)
            # strictly below the same config's ppermute estimate
            pp_total, _ = budget.estimate_run_bytes(
                st, (4096,) * 3, mesh=mesh, fuse=4, fuse_kind="stream")
            assert total < pp_total


def test_budget_rdma_pipeline_deletes_carried_slabs():
    from mpi_cuda_process_tpu.utils import budget

    st = make_stencil("wave3d", dtype=jnp.dtype("float32"))
    total, parts = budget.estimate_run_bytes(
        st, (4096,) * 3, mesh=(8, 8, 1), fuse=4, fuse_kind="stream",
        overlap=True, pipeline=True, exchange="rdma")
    labels = [lbl for lbl, b in parts if b]
    assert not any("carried slabs" in lbl for lbl in labels)
    assert total == 14_173_392_076  # same as the non-pipelined rdma row


def test_budget_rdma_off_stream_is_unsupported_not_priced():
    from mpi_cuda_process_tpu.utils import budget

    st = make_stencil("heat3d")
    _, parts = budget.estimate_run_bytes(
        st, (512,) * 3, mesh=(8, 1, 1), fuse=4, fuse_kind="padfree",
        exchange="rdma")
    assert any("UNSUPPORTED" in lbl and b == 0 for lbl, b in parts)


# ---------------------------------------------------- ledger / gate

def test_baseline_key_includes_exchange_mode():
    from mpi_cuda_process_tpu.obs import ledger

    old = ledger.make_row("wave3d_512_f32_stream4_shard", 50.0,
                          source="telemetry:/old", backend="tpu",
                          flags={"fuse": 4})
    new = ledger.make_row("wave3d_512_f32_stream4_shard", 30.0,
                          source="telemetry:/new", backend="tpu",
                          flags={"fuse": 4, "exchange": "rdma"})
    assert ledger.baseline_key(old) != ledger.baseline_key(new)
    # pre-exchange rows keep their historical key verbatim
    assert ledger.baseline_key(old) == \
        "wave3d_512_f32_stream4_shard|tpu"


def test_perf_gate_no_baseline_across_exchange_modes(tmp_path):
    """A label measured only under ppermute must gate an rdma manifest
    as NO_BASELINE, never REGRESSED — mode is part of the baseline
    key.  (An rdma number can legitimately differ from the ppermute
    number by more than any noise band; scoring one against the other
    would be a category error.)"""
    import importlib.util
    import os

    from mpi_cuda_process_tpu.obs import ledger

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "perf_gate_rdma_t", os.path.join(repo, "scripts", "perf_gate.py"))
    gate_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate_mod)
    judge = gate_mod.judge

    row_pp = ledger.make_row("scaling_weak_heat3d_64x64x128_mesh2x1x1",
                             80.0, source="telemetry:/a", backend="cpu",
                             flags={"fuse": 4})
    row_rd = ledger.make_row("scaling_weak_heat3d_64x64x128_mesh2x1x1",
                             40.0, source="telemetry:/b", backend="cpu",
                             flags={"fuse": 4, "exchange": "rdma"})
    ledger_path = tmp_path / "ledger.jsonl"
    ledger.append_rows([row_pp], str(ledger_path))
    baselines = ledger.best_known(ledger.read_rows(str(ledger_path)))
    base = baselines.get(ledger.baseline_key(row_rd))
    verdict, ratio = judge(row_rd, base, 0.10)
    assert verdict == "NO_BASELINE" and ratio is None
    # same-mode rows still gate normally
    verdict_pp, _ = judge(
        dict(row_pp, value=40.0),
        baselines.get(ledger.baseline_key(row_pp)), 0.10)
    assert verdict_pp == "REGRESSED"


def test_scaling_rung_rows_stamp_and_key_the_exchange_mode(tmp_path):
    """scaling.py rung events carry the mode; ledger ingestion lifts it
    into the key flags (non-default only) so rdma ladder rows never
    collide with the historical ppermute keys."""
    from mpi_cuda_process_tpu.obs import ledger, trace

    log = str(tmp_path / "scaling.jsonl")
    with trace.TraceWriter(log) as w:
        w.write_manifest(trace.build_manifest("scaling", {"mode": "weak"}))
        w.event("rung", mode="weak", stencil="heat3d", fuse=4,
                exchange="rdma", fuse_kind="stream",
                kernel_kind="stream", mesh=[2, 1, 1],
                grid=[64, 64, 128], mcells_per_s=12.5, efficiency=1.0)
        w.event("rung", mode="weak", stencil="heat3d", fuse=4,
                exchange="ppermute", fuse_kind="stream",
                kernel_kind="stream", mesh=[2, 1, 1],
                grid=[64, 64, 128], mcells_per_s=14.0, efficiency=1.0)
        w.event("summary")
    rows = ledger.rows_from_log(log)
    assert len(rows) == 2
    rd = [r for r in rows if "rdma" in r["label"]]
    pp = [r for r in rows if "rdma" not in r["label"]]
    assert len(rd) == 1 and len(pp) == 1
    assert rd[0]["key"]["flags"].get("exchange") == "rdma"
    assert "exchange" not in (pp[0]["key"]["flags"] or {})
    assert ledger.baseline_key(rd[0]) != ledger.baseline_key(pp[0])
