"""Whole-step raw Pallas kernels == driver.make_step.

Interpret-mode equivalence (SURVEY.md §4.4's Pallas CI strategy): the raw
kernels replace the ENTIRE pad -> update -> frame-re-pin step, so the
invariant is stronger than the compute_fn kernels' — the whole step function
must match, frame semantics included, over multiple steps.  Tolerance is a
few ULP at the field's scale (not bit-exact: XLA may contract mul+add to FMA
differently in the two graphs), except the frame cells, which both paths
must preserve verbatim.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_process_tpu import driver
from mpi_cuda_process_tpu.ops import make_stencil
from mpi_cuda_process_tpu.ops.pallas import rawstep
from mpi_cuda_process_tpu.utils.init import init_state

CASES = [
    ("heat3d", (16, 18, 130), {}),
    ("heat3d", (8, 10, 12), {"dtype": jnp.bfloat16}),
    ("heat3d27", (16, 12, 14), {}),
    ("heat3d4th", (16, 14, 130), {}),
    ("wave3d", (16, 18, 12), {}),
    ("advect3d", (16, 10, 12), {"cx": 0.3, "cy": -0.2, "cz": 0.25}),
    ("grayscott3d", (16, 12, 130), {}),
]


@pytest.mark.parametrize("name,grid,kw", CASES,
                         ids=[f"{n}-{'x'.join(map(str, g))}"
                              for n, g, kw in CASES])
def test_raw_step_matches_driver(name, grid, kw):
    st = make_stencil(name, **kw)
    raw = rawstep.make_raw_step(st, grid, interpret=True)
    assert raw is not None, "tileable case must build"
    ref = driver.make_step(st, grid)
    a = b = init_state(st, grid, 3, 0.2, "auto")
    for _ in range(4):
        a, b = raw(a), ref(b)
    eps = float(jnp.finfo(st.dtype).eps)
    scale = max(float(jnp.max(jnp.abs(b[0]).astype(jnp.float32))), 1.0)
    for x, y in zip(a, b):
        xn = np.asarray(x, dtype=np.float32)
        yn = np.asarray(y, dtype=np.float32)
        np.testing.assert_allclose(xn, yn, rtol=0, atol=32 * eps * scale)
        # frame cells: verbatim, no tolerance
        h = st.halo
        for d in range(3):
            lo = [slice(None)] * 3
            hi = [slice(None)] * 3
            lo[d], hi[d] = slice(0, h), slice(-h, None)
            np.testing.assert_array_equal(xn[tuple(lo)], yn[tuple(lo)])
            np.testing.assert_array_equal(xn[tuple(hi)], yn[tuple(hi)])


def test_unsupported_returns_none():
    st2d = make_stencil("heat2d")
    assert rawstep.make_raw_step(st2d, (32, 32), interpret=True) is None
    life = make_stencil("life")
    assert not rawstep.raw_step_supported(life)
    st = make_stencil("heat3d")
    # untileable Z (prime) -> None, caller falls back to jnp
    assert rawstep.make_raw_step(st, (7, 16, 16), interpret=True) is None
