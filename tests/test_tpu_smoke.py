"""Real-chip smoke tier: ``TPU_SMOKE=1 python -m pytest tests -q -m tpu``.

Scripts docs/STATE.md's runbook step 5 ("the kernels work on hardware") as
a one-command check instead of folklore.  Every test here runs on the REAL
TPU through the axon tunnel — tiny shapes, a handful of compiles (~20-40s
each cold).  Never part of the default tier (pytest.ini deselects the
``tpu`` marker; tests/conftest.py keeps forcing CPU unless TPU_SMOKE=1).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [
    pytest.mark.tpu,
    pytest.mark.skipif(
        not os.environ.get("TPU_SMOKE"),
        reason="real-chip smoke tier: set TPU_SMOKE=1 on a healthy tunnel"),
]


@pytest.fixture(scope="module", autouse=True)
def _require_tpu():
    if jax.default_backend() != "tpu":
        pytest.skip(f"backend is {jax.default_backend()}, not tpu")


def test_probe_trivial_op():
    """Tunnel-health canary first: a wedged tunnel fails here, fast."""
    x = jnp.ones((128, 128), jnp.float32)
    assert float(jnp.sum(x * 2)) == 2.0 * 128 * 128


def test_cli_auto_selects_temporal_blocking(caplog):
    """`--compute auto` on heat3d must pick the fused kernel ON THE CHIP
    (runbook: the log line proves policy + compile + run end-to-end)."""
    from mpi_cuda_process_tpu.cli import config_from_args, run

    caplog.set_level("INFO", logger="mpi_cuda_process_tpu")
    cfg = config_from_args(
        ["--stencil", "heat3d", "--grid", "64,64,128", "--iters", "8"])
    fields, mcells = run(cfg)
    assert any("auto: temporal blocking" in r.message for r in caplog.records)
    assert np.isfinite(np.asarray(fields[0])).all()
    assert mcells > 0


def test_padfree_kernel_compiles_and_matches_on_chip():
    """The round-4 pad-free 9-block kernel through the REAL Mosaic compile
    (interpret-mode equivalence already holds; this is the hardware leg)."""
    from mpi_cuda_process_tpu import init_state, make_step, make_stencil
    from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

    st = make_stencil("heat3d")
    shape = (64, 64, 128)
    fields = init_state(st, shape, seed=3, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, shape))
    for _ in range(4):
        ref = step(ref)
    padfree = make_fused_step(st, shape, 4, interpret=False, padfree=True)
    assert padfree is not None
    out = jax.jit(padfree)(fields)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), rtol=0, atol=1e-4)


def test_stream_kernel_compiles_and_matches_on_chip():
    """The round-4 STREAMING kernel (manual DMA pipeline: run_scoped +
    make_async_copy + ANY refs) through the REAL Mosaic compile — the
    newest compile class; proving it at tiny size de-risks the campaign's
    *_stream4/8 labels."""
    from mpi_cuda_process_tpu import init_state, make_step, make_stencil
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        make_stream_fused_step,
    )

    st = make_stencil("heat3d")
    shape = (64, 64, 128)
    fields = init_state(st, shape, seed=3, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, shape))
    for _ in range(4):
        ref = step(ref)
    stream = make_stream_fused_step(st, shape, 4, interpret=False)
    assert stream is not None
    out = jax.jit(stream)(fields)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), rtol=0, atol=1e-4)


def test_life_render_on_chip(capsys):
    from mpi_cuda_process_tpu.cli import config_from_args, run

    cfg = config_from_args(
        ["--stencil", "life", "--grid", "40,40", "--iters", "30",
         "--render", "--seed", "2"])
    run(cfg)
    out = capsys.readouterr().out
    assert "0" in out  # alive glyph somewhere after 30 generations


def test_checkpoint_resume_bitmatch_on_chip(tmp_path):
    """SIGKILL-free variant of the fault-injection invariant, on hardware:
    resumed == uninterrupted, bit-for-bit."""
    from mpi_cuda_process_tpu.cli import config_from_args, run

    ck = str(tmp_path / "ck")
    base = ["--stencil", "heat2d", "--grid", "64,128", "--seed", "5"]
    cfg_full = config_from_args(base + ["--iters", "20"])
    full, _ = run(cfg_full)
    run(config_from_args(
        base + ["--iters", "10", "--checkpoint-every", "10",
                "--checkpoint-dir", ck]))
    resumed, _ = run(config_from_args(
        base + ["--iters", "20", "--checkpoint-dir", ck, "--resume"]))
    assert np.array_equal(np.asarray(full[0]), np.asarray(resumed[0]))
