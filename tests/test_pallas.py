"""Pallas kernels vs jnp reference ops, in interpret mode on CPU
(SURVEY.md §4.4): the same kernel code that runs on TPU, executed by the
Pallas interpreter, must match the jnp update exactly.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_cuda_process_tpu import make_step, make_stencil
from mpi_cuda_process_tpu.ops.pallas import has_pallas_kernel, make_pallas_compute


CASES = [
    ("heat2d", (12, 18), {}),
    ("life", (10, 12), {}),
    ("heat3d", (16, 8, 10), {}),       # z divisible by a chunk size
    ("heat3d", (6, 8, 10), {}),        # z NOT divisible: jnp fallback path
    ("heat3d27", (16, 7, 8), {"alpha": 0.1}),
    ("heat3d4th", (16, 9, 10), {"alpha": 0.05}),  # halo-2 z-chunk kernel
    ("heat3d4th", (6, 9, 10), {"alpha": 0.05}),   # bz % 2*halo fails: fallback
    ("wave3d", (16, 8, 8), {"c2dt2": 0.1}),
]


def _random_fields(st, grid, seed=0):
    rng = np.random.default_rng(seed)
    if st.name == "life":
        f = rng.integers(0, 2, grid).astype(np.int32)
        return (jnp.asarray(f),)
    fields = [rng.random(grid).astype(np.float32) * 10
              for _ in range(st.num_fields)]
    return tuple(jnp.asarray(f) for f in fields)


@pytest.mark.parametrize("name,grid,params", CASES)
def test_pallas_matches_jnp(name, grid, params):
    st = make_stencil(name, **params)
    assert has_pallas_kernel(name)
    fields = _random_fields(st, grid)
    ref_step = make_step(st, grid)
    pl_step = make_step(st, grid, compute_fn=make_pallas_compute(st))
    ref, got = fields, fields
    for _ in range(2):
        ref = ref_step(ref)
        got = pl_step(got)
    for r, g in zip(ref, got):
        if np.issubdtype(np.asarray(r).dtype, np.integer):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
        else:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=1e-6, atol=1e-6)


def test_pallas_in_sharded_step():
    """Pallas compute_fn plugs into the shard_map stepper unchanged."""
    from mpi_cuda_process_tpu import (
        init_state, make_mesh, make_sharded_step, shard_fields)

    st = make_stencil("heat3d")
    grid = (16, 8, 8)
    fields = init_state(st, grid, kind="zero")
    mesh = make_mesh((1, 2, 2))  # z unsharded so chunking sees full z
    ref = make_step(st, grid)(fields)
    step = make_sharded_step(
        st, mesh, grid, compute_fn=make_pallas_compute(st))
    got = step(shard_fields(fields, mesh, 3))
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(ref[0]), rtol=1e-6, atol=1e-6)


def test_unknown_stencil_raises():
    st = make_stencil("wave2d")
    with pytest.raises(KeyError, match="no pallas kernel"):
        make_pallas_compute(st)
