"""CLI / config / checkpoint-resume integration tests (SURVEY.md §5.4, §5.6)."""

import numpy as np

import jax.numpy as jnp
import pytest

from mpi_cuda_process_tpu.cli import build, config_from_args, run
from mpi_cuda_process_tpu.config import RunConfig, parse_int_tuple, parse_params
from mpi_cuda_process_tpu.utils import checkpointing


def test_parse_helpers():
    assert parse_int_tuple("512,512") == (512, 512)
    assert parse_int_tuple("256x256x256") == (256, 256, 256)
    assert parse_params(["alpha=0.2", "bc=5", "mode=fast"]) == {
        "alpha": 0.2, "bc": 5, "mode": "fast"}


def test_config_roundtrip():
    cfg = RunConfig(stencil="heat3d", grid=(8, 8, 8), mesh=(2, 2))
    import json
    back = RunConfig.from_dict(json.loads(cfg.to_json()))
    assert back == cfg


def test_cli_args_to_config():
    cfg = config_from_args([
        "--stencil", "life", "--grid", "32,32", "--iters", "3",
        "--mesh", "2,2", "--param", "dtype=int32", "--seed", "5"])
    assert cfg.stencil == "life" and cfg.mesh == (2, 2) and cfg.seed == 5


def test_run_end_to_end_unsharded():
    cfg = RunConfig(stencil="heat2d", grid=(16, 16), iters=5)
    fields, mcells = run(cfg)
    assert np.asarray(fields[0]).shape == (16, 16)
    assert mcells > 0


def test_run_end_to_end_sharded():
    cfg = RunConfig(stencil="life", grid=(16, 16), iters=4, mesh=(2, 2),
                    params={"dtype": "int32"})
    fields, _ = run(cfg)
    ref = run(RunConfig(stencil="life", grid=(16, 16), iters=4,
                        params={"dtype": "int32"}))[0]
    np.testing.assert_array_equal(np.asarray(fields[0]), np.asarray(ref[0]))


def test_checkpoint_resume_bitmatch(tmp_path):
    """A resumed run must bit-match an uninterrupted one (SURVEY.md §5.4)."""
    ck = str(tmp_path / "ckpt")
    base = dict(stencil="life", grid=(16, 16), iters=10, seed=3,
                params={"dtype": "int32"})
    full, _ = run(RunConfig(**base))

    # interrupted at step 6 (checkpoint_every=3 -> checkpoints at 3, 6, 9, 10)
    run(RunConfig(**{**base, "iters": 6},
                  checkpoint_every=3, checkpoint_dir=ck))
    assert checkpointing.latest_step(ck) == 6
    resumed, _ = run(RunConfig(**base, checkpoint_dir=ck, resume=True,
                               checkpoint_every=3))
    np.testing.assert_array_equal(
        np.asarray(resumed[0]), np.asarray(full[0]))


def test_checkpoint_atomic_roundtrip(tmp_path):
    p = str(tmp_path / "c")
    f = (jnp.arange(12, dtype=jnp.float32).reshape(3, 4),)
    checkpointing.save_checkpoint(p, f, 7, {"a": 1})
    fields, step, cfg = checkpointing.load_checkpoint(p)
    assert step == 7 and cfg == {"a": 1}
    np.testing.assert_array_equal(fields[0], np.asarray(f[0]))
    # overwrite is atomic (directory replaced, not merged)
    checkpointing.save_checkpoint(p, f, 9)
    assert checkpointing.latest_step(p) == 9


def test_resume_from_nonmultiple_step_keeps_checkpointing(tmp_path):
    """Resumed runs must keep the absolute checkpoint cadence (not stall)."""
    ck = str(tmp_path / "ck2")
    base = dict(stencil="heat2d", grid=(16, 16), params={})
    # First run ends at step 10 (not a multiple of 4), checkpoints at 4, 8, 10.
    run(RunConfig(**base, iters=10, checkpoint_every=4, checkpoint_dir=ck))
    assert checkpointing.latest_step(ck) == 10
    # Resume to 20: periodic checkpoints must fire again (12, 16, 20).
    seen = []
    orig = checkpointing.save_checkpoint

    def spy(path, fields, step, config=None):
        seen.append(step)
        return orig(path, fields, step, config)

    import mpi_cuda_process_tpu.cli as cli_mod
    old = cli_mod.checkpointing.save_checkpoint
    cli_mod.checkpointing.save_checkpoint = spy
    try:
        run(RunConfig(**base, iters=20, checkpoint_every=4,
                      checkpoint_dir=ck, resume=True))
    finally:
        cli_mod.checkpointing.save_checkpoint = old
    assert 12 in seen and 16 in seen and checkpointing.latest_step(ck) == 20


def test_orbax_checkpoint_resume_bitmatch(tmp_path):
    """Orbax backend: resumed sharded run bit-matches an uninterrupted one."""
    ck = str(tmp_path / "ock")
    base = dict(stencil="life", grid=(16, 16), iters=10, seed=3,
                mesh=(2, 2), params={"dtype": "int32"},
                checkpoint_backend="orbax")
    full, _ = run(RunConfig(**{k: v for k, v in base.items()
                               if k != "checkpoint_backend"}))
    run(RunConfig(**{**base, "iters": 6},
                  checkpoint_every=3, checkpoint_dir=ck))
    assert checkpointing.latest_step(ck) == 6
    resumed, _ = run(RunConfig(**base, checkpoint_dir=ck, resume=True,
                               checkpoint_every=3))
    np.testing.assert_array_equal(
        np.asarray(resumed[0]), np.asarray(full[0]))


def test_resume_autodetects_checkpoint_format(tmp_path):
    """Resume trusts the on-disk format, not the --checkpoint-backend flag."""
    ck = str(tmp_path / "mix")
    base = dict(stencil="life", grid=(16, 16), iters=10, seed=3,
                params={"dtype": "int32"})
    full, _ = run(RunConfig(**base))
    # write with orbax, resume with the default (npy) flag
    run(RunConfig(**{**base, "iters": 6}, checkpoint_every=3,
                  checkpoint_dir=ck, checkpoint_backend="orbax"))
    resumed, _ = run(RunConfig(**base, checkpoint_dir=ck, resume=True,
                               checkpoint_every=3))
    np.testing.assert_array_equal(
        np.asarray(resumed[0]), np.asarray(full[0]))


def test_orbax_sharded_roundtrip(tmp_path):
    """Orbax save/restore of sharded fields preserves values + sharding."""
    import jax

    from mpi_cuda_process_tpu import (
        init_state, make_mesh, make_stencil, shard_fields)

    st = make_stencil("heat3d")
    mesh = make_mesh((2, 2, 2))
    fields = shard_fields(init_state(st, (8, 8, 8), kind="zero"), mesh, 3)
    p = str(tmp_path / "oc")
    checkpointing.orbax_save_checkpoint(p, fields, 5, {"x": 2})
    out, step, cfg = checkpointing.orbax_load_checkpoint(
        p, target_fields=fields)
    assert step == 5 and cfg == {"x": 2}
    assert len(out[0].sharding.device_set) == 8
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(fields[0]))


def test_orbax_restore_reshards_across_meshes(tmp_path):
    """Restore onto a DIFFERENT mesh must land on the target sharding."""
    import jax

    from mpi_cuda_process_tpu import (
        init_state, make_mesh, make_stencil, shard_fields)

    st = make_stencil("heat3d")
    grid = (8, 8, 8)
    mesh8 = make_mesh((2, 2, 2))
    fields8 = shard_fields(init_state(st, grid, kind="zero"), mesh8, 3)
    p = str(tmp_path / "xmesh")
    checkpointing.orbax_save_checkpoint(p, fields8, 3)

    mesh4 = make_mesh((2, 2))
    target = shard_fields(init_state(st, grid, kind="zero"), mesh4, 3)
    out, step, _ = checkpointing.orbax_load_checkpoint(
        p, target_fields=target)
    assert step == 3
    assert out[0].sharding == target[0].sharding  # 4-device target, not 8
    np.testing.assert_array_equal(np.asarray(out[0]), np.asarray(fields8[0]))


def test_checkpoint_format_prefers_newest_step(tmp_path):
    """Backend switch mid-run: the format holding the newest step wins."""
    import jax.numpy as _jnp

    p = str(tmp_path / "both")
    f = (_jnp.zeros((4, 4), _jnp.float32),)
    checkpointing.save_checkpoint(p, f, 6)          # npy at step 6
    checkpointing.orbax_save_checkpoint(p, f, 12)   # orbax at step 12
    assert checkpointing.checkpoint_format(p) == "orbax"
    assert checkpointing.latest_step(p) == 12
    _, step, _ = checkpointing.load_any(p)
    assert step == 12
    checkpointing.save_checkpoint(p, f, 20)         # npy pulls ahead
    assert checkpointing.checkpoint_format(p) == "npy"
    assert checkpointing.latest_step(p) == 20


def test_npy_save_preserves_newer_orbax_steps(tmp_path):
    """An npy save must not destroy co-located (possibly NEWER) orbax steps.

    Scenario: an orbax run checkpointed to step 12; a rerun with the default
    npy backend saves step 6 into the same dir.  The newest-step-wins
    contract of checkpoint_format requires the orbax step to survive.
    """
    import jax.numpy as _jnp

    p = str(tmp_path / "both2")
    f = (_jnp.zeros((4, 4), _jnp.float32),)
    checkpointing.orbax_save_checkpoint(p, f, 12)
    checkpointing.save_checkpoint(p, f, 6)  # older npy into the same dir
    assert checkpointing.orbax_latest_step(p) == 12
    assert checkpointing.checkpoint_format(p) == "orbax"
    _, step, _ = checkpointing.load_any(p)
    assert step == 12
    # Once the npy stream pulls AHEAD, the now-stale orbax step must be
    # dropped (retention: exactly one checkpoint, never re-preserved).
    checkpointing.save_checkpoint(p, f, 20)
    assert checkpointing.orbax_latest_step(p) is None
    assert checkpointing.checkpoint_format(p) == "npy"
    assert checkpointing.latest_step(p) == 20
    # ...and symmetrically: an orbax save past the npy step drops the npy.
    checkpointing.orbax_save_checkpoint(p, f, 30)
    assert checkpointing._npy_step(p) is None
    assert checkpointing.checkpoint_format(p) == "orbax"
    assert checkpointing.latest_step(p) == 30


def test_ensemble_matches_independent_runs():
    """vmapped ensemble == N independent runs with seeds seed..seed+N-1."""
    base = dict(stencil="life", grid=(16, 16), iters=5)
    ens, _ = run(RunConfig(**base, seed=4, ensemble=3))
    assert np.asarray(ens[0]).shape == (3, 16, 16)
    for i in range(3):
        solo, _ = run(RunConfig(**base, seed=4 + i))
        np.testing.assert_array_equal(
            np.asarray(ens[0])[i], np.asarray(solo[0]))


def test_ensemble_plus_mesh_composes():
    """Round 15 deleted the exclusion wall: --ensemble + --mesh builds
    the batched sharded stepper (full equivalence coverage lives in
    tests/test_ensemble_engine.py; this pins that the old raise stays
    gone)."""
    from mpi_cuda_process_tpu.cli import build
    st, step_fn, fields, start = build(
        RunConfig(stencil="life", grid=(16, 16), iters=1,
                  ensemble=2, mesh=(2, 2)))
    assert fields[0].shape == (2, 16, 16)


def test_fuse_matches_plain_run():
    """--fuse K (temporal blocking) must not change results."""
    base = dict(stencil="heat3d", grid=(16, 16, 128), iters=8, init="random",
                seed=2)
    plain, _ = run(RunConfig(**base))
    fused, _ = run(RunConfig(**base, fuse=4))
    np.testing.assert_array_equal(
        np.asarray(fused[0]), np.asarray(plain[0]))


def test_fuse_plus_mesh_matches_plain_run():
    """--fuse K + --mesh: k fused steps per width-k exchange, same results."""
    base = dict(stencil="heat3d", grid=(16, 16, 128), iters=8, init="random",
                seed=2)
    plain, _ = run(RunConfig(**base))
    fused, _ = run(RunConfig(**base, fuse=4, mesh=(2, 2, 1)))
    np.testing.assert_allclose(
        np.asarray(fused[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_fuse_rejects_bad_configs():
    import pytest
    with pytest.raises(ValueError, match="fuse"):
        # sharded lane axis: in-kernel lane rolls need whole x rows
        build(RunConfig(stencil="heat3d", grid=(16, 16, 256), iters=8,
                        fuse=4, mesh=(1, 1, 2)))
    with pytest.raises(ValueError, match="fuse"):
        build(RunConfig(stencil="life", grid=(16, 16), iters=8, fuse=4))


def test_fuse_overlap_mesh_matches_plain_run():
    """--fuse K + --mesh + --overlap: the communication-overlapped split
    composes at the CLI layer and changes no values."""
    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=8,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    over, _ = run(RunConfig(**base, fuse=4, mesh=(2, 1, 1), overlap=True))
    np.testing.assert_allclose(
        np.asarray(over[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_fuse_overlap_requires_mesh():
    with pytest.raises(ValueError, match="overlap"):
        build(RunConfig(stencil="heat3d", grid=(32, 16, 128), iters=8,
                        fuse=4, overlap=True))


def test_pipeline_cli_matches_plain_run():
    """--pipeline --overlap --fuse K --fuse-kind padfree --mesh: the
    slab-carry scan through the whole CLI stack (build -> run's
    pipeline-aware scan runner) changes no values."""
    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=12,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    st, step_fn, _, _ = build(RunConfig(**base, fuse=4,
                                        fuse_kind="padfree",
                                        mesh=(2, 1, 1), overlap=True,
                                        pipeline=True))
    assert getattr(step_fn, "_pipeline_active", False)
    assert getattr(step_fn, "_overlap_active", False)
    pipe, _ = run(RunConfig(**base, fuse=4, fuse_kind="padfree",
                            mesh=(2, 1, 1), overlap=True, pipeline=True))
    np.testing.assert_allclose(
        np.asarray(pipe[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_pipeline_cli_chunked_cadence_matches_unchunked():
    """--pipeline + --log-every (cli's scan-over-remaining/K chunking):
    every chunk re-seeds the carry with its own prologue exchange; the
    final state must match the single-scan run bit-for-bit."""
    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=16,
                init="random", seed=2, fuse=4, fuse_kind="padfree",
                mesh=(2, 1, 1), pipeline=True)
    whole, _ = run(RunConfig(**base))
    chunked, _ = run(RunConfig(**base, log_every=8))
    np.testing.assert_array_equal(
        np.asarray(chunked[0]), np.asarray(whole[0]))


def test_pipeline_cli_flag_parses():
    cfg = config_from_args([
        "--stencil", "heat3d", "--grid", "32,16,128", "--iters", "8",
        "--mesh", "2,1,1", "--fuse", "4", "--fuse-kind", "padfree",
        "--overlap", "--pipeline"])
    assert cfg.pipeline and cfg.overlap and cfg.fuse == 4


def test_pipeline_cli_never_silently_falls_back():
    """A forced --pipeline raises with the reason on every host that
    cannot carry it — no silent fallback anywhere in the chain."""
    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=8)
    with pytest.raises(ValueError, match="pipeline"):
        build(RunConfig(**base, pipeline=True))  # no --fuse
    with pytest.raises(ValueError, match="pipeline"):
        build(RunConfig(**base, fuse=4, pipeline=True))  # no --mesh
    with pytest.raises(ValueError, match="guard-frame"):
        build(RunConfig(**base, fuse=4, mesh=(2, 1, 1),
                        fuse_kind="padfree", periodic=True,
                        pipeline=True))
    with pytest.raises(ValueError, match="slab-operand"):
        # auto kind resolving to the exchange-padded kernel
        build(RunConfig(**base, fuse=4, mesh=(2, 1, 1), pipeline=True))
    with pytest.raises(ValueError, match="3D-only"):
        build(RunConfig(stencil="life", grid=(64, 128), iters=8, fuse=8,
                        mesh=(2,), params={"dtype": "int32"},
                        pipeline=True))
    with pytest.raises(ValueError, match="pipeline"):
        # forced stream on a geometry stream cannot tile: the None from
        # the builder must surface as the --pipeline-aware error
        build(RunConfig(**{**base, "grid": (16, 32, 128)}, fuse=4,
                        fuse_kind="stream", mesh=(2, 2, 1),
                        pipeline=True))


def test_fuse_kind_stream_matches_plain_run():
    """--fuse K --fuse-kind stream (sliding-window manual-DMA kernel) must
    agree with the plain run to the fused-window tolerance."""
    base = dict(stencil="heat3d", grid=(24, 32, 128), iters=8,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    stream, _ = run(RunConfig(**base, fuse=4, fuse_kind="stream"))
    np.testing.assert_allclose(
        np.asarray(stream[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_fuse_kind_padfree_matches_plain_run():
    base = dict(stencil="heat3d", grid=(16, 16, 128), iters=8,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    pf, _ = run(RunConfig(**base, fuse=4, fuse_kind="padfree"))
    np.testing.assert_allclose(
        np.asarray(pf[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_fuse_kind_stream_with_mesh_matches_plain_run():
    """--fuse K --fuse-kind stream --mesh (z-only): the sharded streaming
    kernel through the CLI — the config-5 command shape."""
    base = dict(stencil="heat3d", grid=(48, 32, 128), iters=8,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    stream, _ = run(RunConfig(**base, fuse=4, fuse_kind="stream",
                              mesh=(2, 1, 1)))
    np.testing.assert_allclose(
        np.asarray(stream[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_fuse_kind_padfree_with_two_axis_mesh_matches_plain_run():
    """--fuse K --fuse-kind padfree --mesh z,y: the 2-axis slab-operand
    kernels through the CLI — the lifted z-only gate (round 7).  The
    forced kind must actually run pad-free (builder introspection), not
    silently fall back to the exchange-padded kernel."""
    base = dict(stencil="heat3d", grid=(16, 32, 128), iters=8,
                init="random", seed=2)
    plain, _ = run(RunConfig(**base))
    st, step_fn, _, _ = build(RunConfig(**base, fuse=4,
                                        fuse_kind="padfree",
                                        mesh=(1, 2, 1)))
    assert getattr(step_fn, "_padfree_kind", None) == "yzslab"
    pf, _ = run(RunConfig(**base, fuse=4, fuse_kind="padfree",
                          mesh=(1, 2, 1)))
    np.testing.assert_allclose(
        np.asarray(pf[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_config5_rehearsal_reduced_scale():
    """BASELINE config 5's exact command SHAPE at 1/64 scale: two-field
    wave3d, bf16, z-only 8-way mesh, --fuse 4 --fuse-kind stream,
    --mem-check on — the v5e-64 launch in docs/EXECUTION.md is this
    command with the grid swapped to 4096^3 and the mesh to 64,1,1.
    Pins (a) the command executes end-to-end through the sharded
    streaming kernel on the dryrun-class mesh and (b) equals the plain
    unsharded run (so the rehearsal is a correctness statement, not just
    a smoke)."""
    args = ["--stencil", "wave3d", "--grid", "192,64,128", "--iters", "8",
            "--mesh", "8,1,1", "--fuse", "4", "--fuse-kind", "stream",
            "--dtype", "bfloat16", "--mem-check", "error"]
    fields, mcells = run(config_from_args(args))
    assert mcells > 0
    plain, _ = run(config_from_args(
        ["--stencil", "wave3d", "--grid", "192,64,128", "--iters", "8",
         "--dtype", "bfloat16"]))
    np.testing.assert_allclose(
        np.asarray(fields[0], np.float32), np.asarray(plain[0], np.float32),
        rtol=0, atol=1e-3)


def test_fuse_kind_rejects_bad_configs():
    import pytest

    # stream: guard-frame only (round 15: --ensemble now batches it —
    # the "unbatched only" wall is gone, pinned in
    # tests/test_ensemble_engine.py)
    with pytest.raises(ValueError, match="stream"):
        build(RunConfig(stencil="heat3d", grid=(24, 32, 128), iters=8,
                        fuse=4, fuse_kind="stream", periodic=True))
    # sharded stream is allowed ONLY where the builder can host it: a
    # local block too small for the sliding window raises with the
    # constraint list
    with pytest.raises(ValueError, match="stream"):
        build(RunConfig(stencil="heat3d", grid=(16, 16, 128), iters=8,
                        fuse=4, fuse_kind="stream", mesh=(2, 1, 1)))
    # y-sharded mesh (round 8): stream now BUILDS via the 2-axis
    # sliding-window kernel — the forced kind must actually run it
    # (builder introspection), never silently fall back
    st_y, step_y, _, _ = build(
        RunConfig(stencil="heat3d", grid=(48, 64, 128), iters=8,
                  fuse=4, fuse_kind="stream", mesh=(1, 2, 1)))
    assert getattr(step_y, "_padfree_kind", None) == "stream_yz"
    # ... but stays guard-frame on 2-axis meshes too
    with pytest.raises(ValueError, match="stream"):
        build(RunConfig(stencil="heat3d", grid=(48, 64, 128), iters=8,
                        fuse=4, fuse_kind="stream", mesh=(2, 2, 1),
                        periodic=True))
    # forced padfree under a mesh builds the slab-operand kernels with
    # NO padded fallback: an untileable local block raises (local z = 4
    # is below the 2m=8 tile granularity)
    with pytest.raises(ValueError, match="padfree"):
        build(RunConfig(stencil="heat3d", grid=(8, 16, 128), iters=8,
                        fuse=4, fuse_kind="padfree", mesh=(2, 1, 1)))
    # the padded tiled kind stays unsharded-only
    with pytest.raises(ValueError, match="fuse-kind"):
        build(RunConfig(stencil="heat3d", grid=(48, 32, 128), iters=8,
                        fuse=4, fuse_kind="tiled", mesh=(2, 1, 1)))
    with pytest.raises(ValueError, match="fuse-kind"):
        build(RunConfig(stencil="heat2d", grid=(64, 128), iters=8,
                        fuse=4, fuse_kind="tiled"))
    # too few z chunks for the sliding window
    with pytest.raises(ValueError, match="stream"):
        build(RunConfig(stencil="heat3d", grid=(16, 16, 128), iters=8,
                        fuse=4, fuse_kind="stream"))
    # forced kind without an explicit k: maybe_auto_fuse upgrades must
    # never be routed into a kernel that was never probed
    with pytest.raises(ValueError, match="fuse-kind"):
        build(RunConfig(stencil="heat3d", grid=(24, 32, 128), iters=8,
                        fuse_kind="stream"))


def test_dump_every_writes_snapshots(tmp_path):
    d = str(tmp_path / "dumps")
    run(RunConfig(stencil="heat2d", grid=(16, 16), iters=10,
                  dump_every=4, dump_dir=d))
    import os
    files = sorted(os.listdir(d))
    assert files == ["step_00000004.npy", "step_00000008.npy",
                     "step_00000010.npy"] or files == [
        "step_00000004.npy", "step_00000008.npy"]
    a = np.load(os.path.join(d, files[0]))
    assert a.shape == (16, 16)


def test_auto_fuse_policy_table(monkeypatch):
    """maybe_auto_fuse upgrades exactly the measured fused winners on TPU."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.ops.pallas import fused

    # Patching the shared jax module makes _interpret_default() think it is
    # on TPU too — pin interpret mode explicitly (in fused's namespace,
    # where the name is bound) so the tileability probe never constructs a
    # real TPU pallas_call on the CPU test backend.
    monkeypatch.setattr(cli.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(fused, "_interpret_default", lambda: True)
    base = dict(grid=(16, 16, 128), iters=8)
    # winners upgrade (the builder still validates tileability)
    for name in ("heat3d", "heat3d27", "wave3d"):
        assert cli.maybe_auto_fuse(RunConfig(stencil=name, **base)).fuse == 4
    # non-winners and explicit modes never upgrade
    assert cli.maybe_auto_fuse(RunConfig(stencil="advect3d", **base)).fuse == 0
    assert cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", compute="jnp", **base)).fuse == 0
    # bf16 gated until the k=8 win is measured on the real chip
    assert cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", dtype="bfloat16", **base)).fuse == 0
    # cadence misalignment blocks the upgrade
    assert cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", grid=(16, 16, 128), iters=6)).fuse == 0


def test_auto_fuse_kind_table(monkeypatch):
    """A family flipped into _AUTO_FUSE_KIND routes its auto upgrade
    through the streaming kernel — probing the EXACT kernel build() will
    construct, with a tiled fallback when stream declines the shape."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.ops.pallas import fused, streamfused

    monkeypatch.setattr(cli.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(fused, "_interpret_default", lambda: True)
    monkeypatch.setattr(streamfused, "_interpret_default", lambda: True)
    monkeypatch.setattr(cli, "_AUTO_FUSE_KIND", {"heat3d": "stream"})
    # streamable shape: upgrade carries the kind
    got = cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", grid=(24, 32, 128), iters=8))
    assert (got.fuse, got.fuse_kind) == (4, "stream")
    # stream-untileable shape (two z chunks): falls back to the tiled
    # upgrade instead of hard-erroring in build()
    got = cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", grid=(16, 16, 128), iters=8))
    assert (got.fuse, got.fuse_kind) == (4, "auto")
    # empty table (the shipped default): kind never set by auto
    monkeypatch.setattr(cli, "_AUTO_FUSE_KIND", {})
    got = cli.maybe_auto_fuse(
        RunConfig(stencil="heat3d", grid=(24, 32, 128), iters=8))
    assert (got.fuse, got.fuse_kind) == (4, "auto")
    # a user-forced kind WITHOUT --fuse is never auto-upgraded: it must
    # reach build()'s "--fuse-kind requires an explicit --fuse K" guard
    got = cli.maybe_auto_fuse(RunConfig(
        stencil="heat3d", grid=(24, 32, 128), iters=8,
        fuse_kind="stream"))
    assert (got.fuse, got.fuse_kind) == (0, "stream")


def test_tol_composes_with_fuse():
    """--tol + --fuse: convergence inside the while_loop, k steps per call."""
    base = dict(stencil="sor2d", grid=(16, 128), init="zero")
    plain, _ = run(RunConfig(**base, iters=4000, tol=1e-3,
                             tol_check_every=40))
    fused, _ = run(RunConfig(**base, iters=4000, tol=1e-3,
                             tol_check_every=40, fuse=8))
    # Both must land on the same converged Laplace solution (hot walls).
    np.testing.assert_allclose(
        np.asarray(fused[0]), np.asarray(plain[0]), rtol=0, atol=5e-3)


def test_ensemble_composes_with_fuse():
    """--ensemble N + --fuse K: vmapped temporal blocking, bit-exact per
    universe against independent unfused runs."""
    base = dict(stencil="life", grid=(16, 128), iters=8, seed=3,
                init="random")
    fused, _ = run(RunConfig(**base, ensemble=3, fuse=4))
    plain, _ = run(RunConfig(**base, ensemble=3))
    np.testing.assert_array_equal(np.asarray(fused[0]), np.asarray(plain[0]))


def test_ensemble_composes_with_fuse_3d():
    """The 3D windowed fused kernel under vmap (batched pallas_call grid)."""
    base = dict(stencil="heat3d", grid=(16, 16, 128), iters=4, seed=1,
                init="pulse")
    fused, _ = run(RunConfig(**base, ensemble=2, fuse=4))
    plain, _ = run(RunConfig(**base, ensemble=2))
    np.testing.assert_allclose(
        np.asarray(fused[0]), np.asarray(plain[0]), rtol=0, atol=1e-4)


def test_pallas_failure_heuristic():
    """The auto-retry only re-runs failures that originate in the kernel
    stack — a genuine user/config error surfaces immediately (round-3
    verdict weak #6)."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.ops.pallas import fused

    # plain config errors: no retry
    assert not cli._looks_like_pallas_failure(
        ValueError("unknown stencil 'heat4d'"))
    # compile/runtime markers: retry
    for msg in ("Mosaic failed to compile", "INTERNAL: remote_compile",
                "RESOURCE_EXHAUSTED: allocating 4.3G", "scoped vmem limit"):
        assert cli._looks_like_pallas_failure(RuntimeError(msg)), msg
    # traceback-origin signal: an exception raised INSIDE ops/pallas/*
    try:
        fused._halo_per_micro(None)  # AttributeError inside fused.py
    except Exception as e:  # noqa: BLE001
        assert cli._looks_like_pallas_failure(e)
    else:  # pragma: no cover
        raise AssertionError("expected an exception from fused internals")


def test_auto_full_2d_policy_table(monkeypatch):
    """2D families upgrade via _AUTO_FULL_K (whole-grid VMEM kernel) once
    a family is flipped in; the table ships empty until measured."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.ops.pallas import fullgrid

    monkeypatch.setattr(cli.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(fullgrid, "_interpret_default", lambda: True)
    base = dict(grid=(64, 128), iters=16)
    # empty table: no upgrade
    assert cli.maybe_auto_fuse(RunConfig(stencil="life", **base)).fuse == 0
    # flipped family upgrades (builder still validates alignment/VMEM)
    monkeypatch.setitem(cli._AUTO_FULL_K, "life", 8)
    assert cli.maybe_auto_fuse(RunConfig(stencil="life", **base)).fuse == 8
    # cadence misalignment still blocks
    assert cli.maybe_auto_fuse(
        RunConfig(stencil="life", grid=(64, 128), iters=12)).fuse == 0
    # unaligned width declines at the builder
    assert cli.maybe_auto_fuse(
        RunConfig(stencil="life", grid=(64, 100), iters=16)).fuse == 0


@pytest.mark.parametrize(
    "label,args",
    [
        # Scaled-down analogues of the five BASELINE.json configs: same
        # stencil/mesh STRUCTURE, tiny extents, run end-to-end through the
        # CLI on the virtual device mesh.  What this pins: every north-star
        # config is expressible as one command line and actually executes
        # (SURVEY.md §5.6 'every BASELINE.json config expressible').
        ("config1_2d5pt", ["--stencil", "heat2d", "--grid", "64,128",
                           "--iters", "20"]),
        ("config2_3d7pt_single", ["--stencil", "heat3d",
                                  "--grid", "16,16,128", "--iters", "10"]),
        ("config3_3d7pt_2x2", ["--stencil", "heat3d", "--grid", "16,16,128",
                               "--iters", "10", "--mesh", "2,2,1"]),
        ("config4_27pt_8chip", ["--stencil", "heat3d27",
                                "--grid", "16,16,128", "--iters", "6",
                                "--mesh", "4,2,1"]),
        # bf16's sublane tile (16) requires k=8 temporal blocking
        ("config5_wave_fused_sharded", [
            "--stencil", "wave3d", "--grid", "32,32,128", "--iters", "16",
            "--mesh", "2,1,1", "--fuse", "8", "--dtype", "bfloat16"]),
    ],
)
def test_baseline_config_analogues_run_end_to_end(label, args):
    fields, mcells = run(config_from_args(args))
    arr = np.asarray(fields[0], dtype=np.float32)
    assert np.isfinite(arr).all(), label
    assert mcells > 0, label


def test_tol_composes_with_sharded_fuse():
    """Convergence mode + temporal blocking + decomposition in ONE run:
    the while_loop body advances k fused steps on the sharded state."""
    args = ["--stencil", "heat3d", "--grid", "16,16,128", "--iters", "40",
            "--mesh", "2,1,1", "--fuse", "4", "--tol", "1e-7",
            "--tol-check-every", "8"]
    fields, _ = run(config_from_args(args))
    arr = np.asarray(fields[0])
    assert np.isfinite(arr).all()
    # hot walls diffused inward: interior is strictly above the zero init
    assert arr[1:-1, 1:-1, 1:-1].mean() > 0


def test_auto_fuse_at_1024_probes_padfree_variant(monkeypatch):
    """At 1024^3 the auto-fuse probe must construct the PAD-FREE kernel
    (the padded transient is the measured RESOURCE_EXHAUSTED) — pin that
    maybe_auto_fuse upgrades, i.e. the probe chain doesn't decline."""
    from mpi_cuda_process_tpu import cli
    from mpi_cuda_process_tpu.ops.pallas import fused

    monkeypatch.setattr(cli.jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(fused, "_interpret_default", lambda: True)
    built = {}
    orig = fused.make_fused_step

    def spy(st, grid, k, **kw):
        built.setdefault("padfree", kw.get("padfree"))
        return orig(st, grid, k, **kw)

    monkeypatch.setattr(fused, "make_fused_step", spy)
    # cli imported make_fused_step by name inside the function: patch the
    # module it resolves from (it does a local import of fused each call)
    cfg = RunConfig(stencil="heat3d", grid=(1024, 1024, 1024), iters=8)
    out = cli.maybe_auto_fuse(cfg)
    assert out.fuse == 4
    assert built.get("padfree") is True  # the 1024^3 path, not the padded
