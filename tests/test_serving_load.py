"""Load generation through one resident serving engine (ISSUE 14's
acceptance load test).

200 concurrent submits — two size classes, four tenants, mixed
priorities — through a single :class:`~mpi_cuda_process_tpu.serving
.ServingEngine`.  Pinned:

* every job completes (no starvation under sustained mixed-priority
  load — the fairness acceptance);
* time-to-first-chunk p50/p99 are measured and recorded in the
  scheduler log's summary (the run-manifest record the ops side
  scrapes);
* steady aggregate throughput (cold first-calls excluded on both
  sides) beats the one-job-at-a-time replay of the same workload — the
  whole point of packing the member axis;
* a sample of slot results is bit-identical to solo ``cli.run``s —
  throughput was not bought with physics.

Grids are tiny (the win being measured is batching over the member
axis, identical at any grid size) so the 400 total jobs of the two
engines stay inside the tier-1 budget.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu import serving  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402

N_JOBS = 200
ITERS = 32
TENANTS = ("alice", "bob", "carol", "dave")


def _workload():
    """200 mixed jobs: two size classes (different grids), four
    tenants, priorities 0..2, distinct seeds."""
    jobs = []
    for i in range(N_JOBS):
        grid = (16, 16) if i % 2 == 0 else (16, 24)
        jobs.append((RunConfig(stencil="heat2d", grid=grid, iters=ITERS,
                               seed=i, density=0.1 + (i % 5) * 0.1),
                     TENANTS[i % len(TENANTS)], i % 3))
    return jobs


def _run_through(engine, jobs):
    handles = [engine.submit(cfg, tenant=t, priority=p)
               for cfg, t, p in jobs]
    results = [h.result(timeout=900) for h in handles]
    return handles, results


def test_load_200_jobs_batched_beats_serial_replay(tmp_path):
    jobs = _workload()

    batched = serving.ServingEngine(telemetry_dir=str(tmp_path / "b"),
                                    ladder=(8,), cadence=ITERS)
    handles, results = _run_through(batched, jobs)
    bstats = batched.close()

    # --- everything completed; nobody starved -------------------------
    assert bstats["jobs_done"] == N_JOBS
    assert all(h._phase() == "done" for h in handles)
    by_tenant = {t: 0 for t in TENANTS}
    for h in handles:
        by_tenant[h.tenant] += 1
        assert h.timings.get("time_to_first_chunk_s") is not None
        assert h.timings.get("latency_s") is not None
    assert all(v == N_JOBS // len(TENANTS) for v in by_tenant.values())

    # --- SLOs measured and recorded in the scheduler log --------------
    assert bstats["ttfc_p50_s"] is not None
    assert bstats["ttfc_p99_s"] is not None
    assert bstats["ttfc_p50_s"] <= bstats["ttfc_p99_s"]
    summary = None
    with open(batched.telemetry_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "summary":
                summary = rec
    assert summary is not None
    assert summary["ttfc_p50_s"] == bstats["ttfc_p50_s"]
    assert summary["ttfc_p99_s"] == bstats["ttfc_p99_s"]
    assert summary["aggregate_gcells_per_s"] == \
        bstats["aggregate_gcells_per_s"]
    assert summary["jobs_done"] == N_JOBS

    # --- two resident classes, no extra compiles past the ladder ------
    assert len(bstats["class_table"]) == 2
    for row in bstats["class_table"]:
        assert row["capacity"] == 8
        # one scan length per class (iters == cadence, powers of two)
        assert row["compiles"] == 1

    # --- serial replay baseline: same workload, one member at a time --
    serial = serving.ServingEngine(telemetry_dir=str(tmp_path / "s"),
                                   ladder=(1,), cadence=ITERS)
    _run_through(serial, jobs)
    sstats = serial.close()
    assert sstats["jobs_done"] == N_JOBS
    assert bstats["steady_wall_s"] > 0 and sstats["steady_wall_s"] > 0
    assert bstats["aggregate_gcells_per_s"] > \
        sstats["aggregate_gcells_per_s"], \
        f"continuous batching must beat serial replay " \
        f"(batched {bstats['aggregate_gcells_per_s']} vs serial " \
        f"{sstats['aggregate_gcells_per_s']} Gcells/s)"

    # --- bit-exactness sample: packing never changed the physics ------
    for i in (0, 1, 77, 120, 199):
        cfg, _, _ = jobs[i]
        got, _ = results[i]
        want, _ = cli.run(cfg)
        for a, b in zip(got, want):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                f"job {i} differs from its solo run"


# ------------------------------------------------------------------
# Fleet load (ISSUE 17): the same workload discipline pushed through
# a multi-replica ServingRouter, with a replica SIGKILL injected
# mid-stream.  The default-tier test is the scaled rehearsal; the
# slow-marked test is the 10k-concurrent-submit acceptance run.

import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

from mpi_cuda_process_tpu.serving import ServingRouter  # noqa: E402


def _fleet_workload(n, iters=16):
    jobs = []
    for i in range(n):
        grid = (16, 16) if i % 2 == 0 else (16, 24)
        jobs.append((RunConfig(stencil="heat2d", grid=grid, iters=iters,
                               seed=i, density=0.1 + (i % 5) * 0.1),
                     TENANTS[i % len(TENANTS)], i % 3))
    return jobs


def _router_storm(n_jobs, tmp_path, iters=16, kill_after=None):
    """Submit n_jobs concurrently, optionally SIGKILL one replica once
    a fraction of the stream has resolved; return (router, handles,
    stats, killed)."""
    r = ServingRouter(replicas=3, ladder=(8,), cadence=iters,
                      restart_backoff=0.05, per_job_telemetry=False,
                      telemetry_dir=str(tmp_path))
    jobs = _fleet_workload(n_jobs, iters=iters)
    handles = []
    killed = []

    def _killer():
        target = handles[0].replica
        while sum(1 for h in handles if h.done()) < (kill_after or 0):
            time.sleep(0.02)
        if r.kill_replica(target):
            killed.append(target)

    kt = None
    for cfg, t, p in jobs:
        handles.append(r.submit(cfg, tenant=t, priority=p))
        if kill_after is not None and kt is None and len(handles) >= 8:
            kt = threading.Thread(target=_killer, daemon=True)
            kt.start()
    for h in handles:
        h.result(timeout=1800)
    if kt is not None:
        kt.join(60)
    stats = r.close()
    return jobs, handles, stats, killed


def _check_storm(jobs, handles, stats, killed, n_jobs):
    assert stats["lost_jobs"] == 0
    assert stats["jobs_done"] == n_jobs
    assert stats["jobs_failed"] == 0 and stats["jobs_cancelled"] == 0
    assert killed, "the injected kill must actually have fired"
    assert stats["restarts"] == 1
    assert stats["ttfc_p50_s"] is not None
    assert stats["ttfc_p99_s"] is not None
    assert stats["ttfc_p50_s"] <= stats["ttfc_p99_s"]
    # the load actually spread: the survivors both pulled real
    # weight (the killed slot's row is its RESTARTED generation, which
    # may legitimately have served nothing after the stream drained)
    per = {row["replica"]: row for row in stats["per_replica"]}
    assert len(per) == 3
    survivors = [row for name, row in per.items() if name not in killed]
    assert all(row["jobs_done"] > 0 for row in survivors)
    # bit-exactness sample: rebalance and batching never touch physics
    sample = [0, n_jobs // 3, n_jobs - 1]
    rebalanced = [i for i, h in enumerate(handles) if h.resubmits]
    if rebalanced:
        sample.append(rebalanced[0])
    for i in sample:
        cfg, _, _ = jobs[i]
        got, _ = handles[i].result()
        want, _ = cli.run(cfg)
        for a, b in zip(got, want):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                f"job {i} differs from its solo run"


def test_router_load_with_replica_kill(tmp_path):
    """Default-tier fleet rehearsal: 90 concurrent submits over 3
    replicas, one replica killed mid-stream — zero lost jobs, SLOs
    recorded, survivors and reruns bit-exact."""
    n = 90
    jobs, handles, stats, killed = _router_storm(
        n, tmp_path, iters=16, kill_after=n // 4)
    _check_storm(jobs, handles, stats, killed, n)
    summary = None
    router_log = [p for p in os.listdir(tmp_path)
                  if p.startswith("router-")]
    assert len(router_log) == 1
    with open(os.path.join(str(tmp_path), router_log[0])) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "summary":
                summary = rec
    assert summary is not None
    assert summary["ttfc_p50_s"] == stats["ttfc_p50_s"]
    assert summary["ttfc_p99_s"] == stats["ttfc_p99_s"]
    assert summary["lost_jobs"] == 0


@pytest.mark.slow
def test_router_load_10k_acceptance(tmp_path):
    """The ISSUE 17 acceptance run: 10k concurrent submits across 3
    replicas, one injected replica SIGKILL, zero lost jobs, ttfc
    p50/p99 recorded, steady aggregate beating the single-replica
    SERIAL replay rate (one member at a time — the rate is intensive,
    so it is measured on a 400-job sample).  All replicas share the
    host CPU device here, so the fleet's win over a serial replica is
    the batching; on real hardware each replica owns its slice."""
    n = 10_000
    jobs, handles, stats, killed = _router_storm(
        n, tmp_path, iters=8, kill_after=n // 10)
    _check_storm(jobs, handles, stats, killed, n)

    single = serving.ServingEngine(
        telemetry_dir=str(tmp_path / "single"), ladder=(1,), cadence=8,
        per_job_telemetry=False)
    shandles = [single.submit(cfg, tenant=t, priority=p)
                for cfg, t, p in _fleet_workload(400, iters=8)]
    for h in shandles:
        h.result(timeout=1800)
    sstats = single.close()
    assert stats["aggregate_gcells_per_s"] is not None
    assert sstats["aggregate_gcells_per_s"] is not None
    assert stats["aggregate_gcells_per_s"] >= \
        sstats["aggregate_gcells_per_s"], \
        f"3-replica fleet must beat the single-replica serial " \
        f"replay (router {stats['aggregate_gcells_per_s']} vs serial " \
        f"{sstats['aggregate_gcells_per_s']} Gcells/s)"
