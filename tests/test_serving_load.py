"""Load generation through one resident serving engine (ISSUE 14's
acceptance load test).

200 concurrent submits — two size classes, four tenants, mixed
priorities — through a single :class:`~mpi_cuda_process_tpu.serving
.ServingEngine`.  Pinned:

* every job completes (no starvation under sustained mixed-priority
  load — the fairness acceptance);
* time-to-first-chunk p50/p99 are measured and recorded in the
  scheduler log's summary (the run-manifest record the ops side
  scrapes);
* steady aggregate throughput (cold first-calls excluded on both
  sides) beats the one-job-at-a-time replay of the same workload — the
  whole point of packing the member axis;
* a sample of slot results is bit-identical to solo ``cli.run``s —
  throughput was not bought with physics.

Grids are tiny (the win being measured is batching over the member
axis, identical at any grid size) so the 400 total jobs of the two
engines stay inside the tier-1 budget.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu import serving  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402

N_JOBS = 200
ITERS = 32
TENANTS = ("alice", "bob", "carol", "dave")


def _workload():
    """200 mixed jobs: two size classes (different grids), four
    tenants, priorities 0..2, distinct seeds."""
    jobs = []
    for i in range(N_JOBS):
        grid = (16, 16) if i % 2 == 0 else (16, 24)
        jobs.append((RunConfig(stencil="heat2d", grid=grid, iters=ITERS,
                               seed=i, density=0.1 + (i % 5) * 0.1),
                     TENANTS[i % len(TENANTS)], i % 3))
    return jobs


def _run_through(engine, jobs):
    handles = [engine.submit(cfg, tenant=t, priority=p)
               for cfg, t, p in jobs]
    results = [h.result(timeout=900) for h in handles]
    return handles, results


def test_load_200_jobs_batched_beats_serial_replay(tmp_path):
    jobs = _workload()

    batched = serving.ServingEngine(telemetry_dir=str(tmp_path / "b"),
                                    ladder=(8,), cadence=ITERS)
    handles, results = _run_through(batched, jobs)
    bstats = batched.close()

    # --- everything completed; nobody starved -------------------------
    assert bstats["jobs_done"] == N_JOBS
    assert all(h._phase() == "done" for h in handles)
    by_tenant = {t: 0 for t in TENANTS}
    for h in handles:
        by_tenant[h.tenant] += 1
        assert h.timings.get("time_to_first_chunk_s") is not None
        assert h.timings.get("latency_s") is not None
    assert all(v == N_JOBS // len(TENANTS) for v in by_tenant.values())

    # --- SLOs measured and recorded in the scheduler log --------------
    assert bstats["ttfc_p50_s"] is not None
    assert bstats["ttfc_p99_s"] is not None
    assert bstats["ttfc_p50_s"] <= bstats["ttfc_p99_s"]
    summary = None
    with open(batched.telemetry_path) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "summary":
                summary = rec
    assert summary is not None
    assert summary["ttfc_p50_s"] == bstats["ttfc_p50_s"]
    assert summary["ttfc_p99_s"] == bstats["ttfc_p99_s"]
    assert summary["aggregate_gcells_per_s"] == \
        bstats["aggregate_gcells_per_s"]
    assert summary["jobs_done"] == N_JOBS

    # --- two resident classes, no extra compiles past the ladder ------
    assert len(bstats["class_table"]) == 2
    for row in bstats["class_table"]:
        assert row["capacity"] == 8
        # one scan length per class (iters == cadence, powers of two)
        assert row["compiles"] == 1

    # --- serial replay baseline: same workload, one member at a time --
    serial = serving.ServingEngine(telemetry_dir=str(tmp_path / "s"),
                                   ladder=(1,), cadence=ITERS)
    _run_through(serial, jobs)
    sstats = serial.close()
    assert sstats["jobs_done"] == N_JOBS
    assert bstats["steady_wall_s"] > 0 and sstats["steady_wall_s"] > 0
    assert bstats["aggregate_gcells_per_s"] > \
        sstats["aggregate_gcells_per_s"], \
        f"continuous batching must beat serial replay " \
        f"(batched {bstats['aggregate_gcells_per_s']} vs serial " \
        f"{sstats['aggregate_gcells_per_s']} Gcells/s)"

    # --- bit-exactness sample: packing never changed the physics ------
    for i in (0, 1, 77, 120, 199):
        cfg, _, _ = jobs[i]
        got, _ = results[i]
        want, _ = cli.run(cfg)
        for a, b in zip(got, want):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                f"job {i} differs from its solo run"
