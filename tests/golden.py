"""Pure-numpy golden implementations of every stencil (SURVEY.md §4.1).

Written loop-style, independently of the JAX ops, directly from the
reference's per-cell math: the B3/S23 rule (kernel.cu:66) and the FTCS update
(MDF_kernel.cu:20).  Grids include their guard frame; frame cells never change.
"""

import itertools

import numpy as np


def _neighborhood_sum(grid, offsets, weights=None):
    out = np.zeros_like(grid, dtype=np.float64)
    nd = grid.ndim
    for k, off in enumerate(offsets):
        w = 1.0 if weights is None else weights[k]
        src = tuple(
            slice(max(0, -o), grid.shape[d] - max(0, o)) for d, o in enumerate(off)
        )
        dst = tuple(
            slice(max(0, o), grid.shape[d] - max(0, -o)) for d, o in enumerate(off)
        )
        out[dst] += w * grid[src]
    return out


def life_step(grid: np.ndarray) -> np.ndarray:
    h, w = grid.shape
    new = grid.copy()
    for y in range(1, h - 1):
        for x in range(1, w - 1):
            n = int(grid[y - 1:y + 2, x - 1:x + 2].sum()) - int(grid[y, x])
            new[y, x] = 1 if (n == 3 or (n == 2 and grid[y, x] == 1)) else 0
    return new


def heat_step(grid: np.ndarray, alpha: float) -> np.ndarray:
    """FTCS axis-neighbor diffusion, any ndim; frame pinned."""
    nd = grid.ndim
    new = grid.copy()
    it = [range(1, s - 1) for s in grid.shape]
    for idx in itertools.product(*it):
        u = grid[idx]
        acc = 0.0
        for d in range(nd):
            for s in (-1, 1):
                j = list(idx)
                j[d] += s
                acc += grid[tuple(j)]
        new[idx] = u + alpha * (acc - 2 * nd * u)
    return new


def heat27_step(grid: np.ndarray, alpha: float) -> np.ndarray:
    wf, we, wc, w0 = 14.0 / 30, 3.0 / 30, 1.0 / 30, -128.0 / 30
    new = grid.copy()
    it = [range(1, s - 1) for s in grid.shape]
    for idx in itertools.product(*it):
        acc = w0 * grid[idx]
        for off in itertools.product((-1, 0, 1), repeat=3):
            nz = sum(1 for o in off if o)
            if nz == 0:
                continue
            j = tuple(i + o for i, o in zip(idx, off))
            acc += (wf, we, wc)[nz - 1] * grid[j]
        new[idx] = grid[idx] + alpha * acc
    return new


def wave_step(u: np.ndarray, u_prev: np.ndarray, c2dt2: float):
    nd = u.ndim
    new = u.copy()
    it = [range(1, s - 1) for s in u.shape]
    for idx in itertools.product(*it):
        acc = 0.0
        for d in range(nd):
            for s in (-1, 1):
                j = list(idx)
                j[d] += s
                acc += u[tuple(j)]
        lap = acc - 2 * nd * u[idx]
        new[idx] = 2 * u[idx] - u_prev[idx] + c2dt2 * lap
    return new, u.copy()


def heat4th_step(grid: np.ndarray, alpha: float) -> np.ndarray:
    """4th-order 13-point Laplacian, halo 2; 2-cell frame pinned."""
    nd = grid.ndim
    new = grid.copy()
    it = [range(2, s - 2) for s in grid.shape]
    w = {1: 16.0 / 12.0, 2: -1.0 / 12.0}
    for idx in itertools.product(*it):
        acc = -30.0 / 12.0 * nd * grid[idx]
        for d in range(nd):
            for dist in (1, 2):
                for s in (-dist, dist):
                    j = list(idx)
                    j[d] += s
                    acc += w[dist] * grid[tuple(j)]
        new[idx] = grid[idx] + alpha * acc
    return new
