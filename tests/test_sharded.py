"""The core distributed invariant the reference never tests (SURVEY.md §4.3):

    sharded step over any mesh  ==  unsharded single-device step

bit-exact for the int Life grid, to float tolerance for the diffusion models.
Runs on 8 virtual CPU devices (conftest.py).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)


def _compare(name, grid, mesh_shape, steps=5, periodic=False, **params):
    st = make_stencil(name, **params)
    fields = init_state(st, grid, seed=7, density=0.3,
                        kind="random" if name == "life" else "auto")
    ref_step = make_step(st, grid)
    ref = fields
    for _ in range(steps):
        ref = ref_step(ref)

    mesh = make_mesh(mesh_shape)
    sh_step = make_sharded_step(st, mesh, grid, periodic=periodic)
    got = shard_fields(fields, mesh, st.ndim)
    for _ in range(steps):
        got = sh_step(got)

    for r, g in zip(ref, got):
        if np.issubdtype(np.asarray(r).dtype, np.integer):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
        else:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=1e-5, atol=1e-5)


# Mesh ladders are deliberately minimal: every fresh (stencil, mesh) pair
# costs a shard_map compile (~25-70s on the 8-virtual-device CPU backend),
# and round 2's full ladder put this file alone past a 10-minute CI budget.
# Round-5 trim (the default tier had crept to ~13 min): the default tier
# keeps TWO deterministic anchors — life (2,2) (int bit-exact, corner
# traffic through the two-pass exchange, which is the same per-axis
# compose code in 2D and 3D) and heat3d (2,2,2) (3-axis float, the
# decomposition class the property net is not guaranteed to draw) — plus
# the wave carry-field invariant below.  Float-2D (heat2d), 27-point
# corner CONTENT (the corner compose CODE is already bit-exact via
# life), 1-D, and asymmetric variants are slow tier; random
# stencil x mesh x shape coverage is test_properties.py's sharded
# property net.
@pytest.mark.parametrize("mesh_shape", [
    (2, 2),  # both axes split + corner traffic, bit-exact int path
    pytest.param((2,), marks=pytest.mark.slow),    # 1-D row split
    pytest.param((4, 2), marks=pytest.mark.slow),  # asymmetric 2-D
])
def test_life_sharded_bitexact(mesh_shape):
    _compare("life", (16, 24), mesh_shape, steps=6)


@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(2, 2)])
def test_heat2d_sharded(mesh_shape):
    _compare("heat2d", (16, 16), mesh_shape)


@pytest.mark.parametrize("mesh_shape", [
    (2, 2, 2),
    # asymmetric + unsharded axis: also exercised by the sharded-fused tests
    # and the dryrun's (z, y, 1) mesh — slow tier here
    pytest.param((1, 2, 4), marks=pytest.mark.slow),
])
def test_heat3d_sharded(mesh_shape):
    _compare("heat3d", (8, 8, 8), mesh_shape)


@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(2, 2)])
def test_heat27_sharded_corners(mesh_shape):
    """27-point needs diagonal halo data — exercises the two-pass exchange
    (corner values by axis-wise composition).  Slow tier: the compose CODE
    is dimension-generic and bit-exact via life (2,2) in the default tier;
    this pins the 27-point corner CONTENT end-to-end."""
    _compare("heat3d27", (8, 8, 8), mesh_shape, alpha=0.1)


# (No separate plain wave3d sharded test: test_wave_skips_uprev_exchange_
# below runs the identical (2, 2)-mesh comparison plus the field_halos
# assertion — one shard_map compile instead of two.)


def test_nondivisible_grid_rejected():
    st = make_stencil("heat2d")
    mesh = make_mesh((2,))
    with pytest.raises(ValueError, match="not divisible"):
        make_sharded_step(st, mesh, (15, 16))


def test_life_periodic_sharded_matches_roll():
    """Periodic BCs across shard boundaries: compare against jnp.roll step."""
    st = make_stencil("life")
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, (8, 8)).astype(np.int32)

    def roll_step(x):
        n = sum(
            np.roll(x, (dy, dx), (0, 1))
            for dy in (-1, 0, 1) for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        )
        return ((n == 3) | ((n == 2) & (x == 1))).astype(np.int32)

    want = g
    for _ in range(4):
        want = roll_step(want)

    mesh = make_mesh((2, 2))
    step = make_sharded_step(st, mesh, (8, 8), periodic=True)
    got = shard_fields((jnp.asarray(g),), mesh, 2)
    for _ in range(4):
        got = step(got)
    np.testing.assert_array_equal(np.asarray(got[0]), want)


def test_life_periodic_unsharded_matches_roll():
    """--periodic must be honored on the single-device path too."""
    st = make_stencil("life")
    rng = np.random.default_rng(9)
    g = rng.integers(0, 2, (8, 8)).astype(np.int32)

    def roll_step(x):
        n = sum(
            np.roll(x, (dy, dx), (0, 1))
            for dy in (-1, 0, 1) for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        )
        return ((n == 3) | ((n == 2) & (x == 1))).astype(np.int32)

    want = g
    for _ in range(4):
        want = roll_step(want)
    step = make_step(st, (8, 8), periodic=True)
    got = (jnp.asarray(g),)
    for _ in range(4):
        got = step(got)
    np.testing.assert_array_equal(np.asarray(got[0]), want)


def test_wave_skips_uprev_exchange_but_stays_correct():
    """u_prev has field_halo 0 (no exchange) and results still match."""
    st = make_stencil("wave3d", c2dt2=0.1)
    assert st.field_halos == (1, 0)
    _compare("wave3d", (8, 8, 8), (2, 2), c2dt2=0.1)


# Width-2 halo slabs across shard boundaries: the default tier covers the
# width-k exchange via test_properties.test_sharded_width_k_halo (halo 1/2/3
# vs numpy) and the halo-2 fused margins via test_fused; the end-to-end
# heat3d4th mesh ladder is slow tier (a ~46s shard_map compile per shape).
@pytest.mark.slow
@pytest.mark.parametrize("mesh_shape", [(2,), (2, 2), (2, 2, 2)])
def test_heat4th_halo2_sharded(mesh_shape):
    """Width-2 halo slabs across shard boundaries (k>1 exchange path)."""
    _compare("heat3d4th", (8, 8, 8), mesh_shape, alpha=0.05)
