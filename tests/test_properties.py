"""Property tests (SURVEY.md §4.5): Hypothesis over shapes, halos, meshes, dtypes.

Invariants checked across randomly drawn configurations:

* **unsharded == numpy golden** on ARBITRARY (odd, non-tile-multiple) grid
  shapes — the reference's C17 class of bugs (``n_blocks = size/512``
  truncation silently never computes the tail, kernel.cu:195-196) cannot
  recur: every cell must be computed no matter the shape;
* **sharded == unsharded** over random mesh shapes and per-shard extents
  (bit-exact for int32 Life and bfloat16, tolerance for float32), including
  a synthetic halo-3 stencil so halo widths 1, 2 (heat3d4th) and 3 all cross
  shard boundaries;
* **guard-frame pinning**: frame cells hold their initial values after any
  number of steps, for any halo width — the N-D generalization of the
  reference's 1-cell frame (kernel.cu:137-138).
"""

import numpy as np
import pytest

# Optional dependency: absent in some CI images.  Skip the module as ONE
# named skip instead of dying as a collection error (the same discipline
# as tests/test_compat.py for pltpu drift).
pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings  # noqa: E402
from hypothesis import strategies as hs  # noqa: E402

import jax.numpy as jnp

import golden
from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.ops import stencil as stencil_lib

_SETTINGS = dict(
    deadline=None,  # first call per shape jit-compiles (seconds, not ms)
    derandomize=True,  # deterministic CI: no flaky example discovery
    suppress_health_check=[HealthCheck.too_slow],
)


def _synth_stencil(ndim: int, halo: int) -> stencil_lib.Stencil:
    """Box-cross mean with configurable footprint radius (halo width k).

    No registered stencil has halo 3; this synthetic op exercises width-k
    halo exchange and frame pinning beyond the shipped models.  Weights sum
    to 1 so multi-step values stay bounded.
    """
    w = 1.0 / (2 * ndim * halo + 1)

    def update(padded):
        (p,) = padded
        acc = stencil_lib.interior(p, halo, ndim)
        for off in stencil_lib.axis_offsets(ndim):
            for k in range(1, halo + 1):
                acc = acc + stencil_lib.shifted(
                    p, tuple(o * k for o in off), halo)
        return (acc * w,)

    return stencil_lib.Stencil(
        name=f"synthbox{ndim}d_h{halo}", ndim=ndim, halo=halo, num_fields=1,
        dtype=jnp.float32, bc_value=(0.0,), update=update)


def _np_synth_step(u: np.ndarray, halo: int) -> np.ndarray:
    """Independent numpy implementation of :func:`_synth_stencil`'s update."""
    ndim = u.ndim
    p = np.pad(u.astype(np.float64), halo, constant_values=0.0)
    acc = u.astype(np.float64).copy()
    for off in stencil_lib.axis_offsets(ndim):
        for k in range(1, halo + 1):
            src = tuple(
                slice(halo + o * k, halo + o * k + n)
                for o, n in zip(off, u.shape))
            acc += p[src]
    new = acc / (2 * ndim * halo + 1)
    # frame pinning
    out = u.astype(np.float64).copy()
    inner = tuple(slice(halo, n - halo) for n in u.shape)
    out[inner] = new[inner]
    return out.astype(u.dtype)


# ---------------------------------------------------------------------------
# unsharded == golden on arbitrary shapes (C17 truncation-gap class)
# ---------------------------------------------------------------------------


@settings(max_examples=15, **_SETTINGS)
@given(
    h=hs.integers(4, 13),
    w=hs.integers(4, 13),
    steps=hs.integers(1, 3),
    seed=hs.integers(0, 2**16),
)
def test_life_matches_golden_any_shape(h, w, steps, seed):
    st = make_stencil("life")
    fields = init_state(st, (h, w), seed=seed, density=0.4, kind="random")
    want = np.asarray(fields[0])
    step = make_step(st, (h, w))
    for _ in range(steps):
        want = golden.life_step(want)
        fields = step(fields)
    np.testing.assert_array_equal(np.asarray(fields[0]), want)


@settings(max_examples=15, **_SETTINGS)
@given(
    h=hs.integers(3, 12),
    w=hs.integers(3, 12),
    alpha=hs.floats(0.05, 0.25),
    steps=hs.integers(1, 3),
)
def test_heat2d_matches_golden_any_shape(h, w, alpha, steps):
    st = make_stencil("heat2d", alpha=alpha)
    fields = init_state(st, (h, w), kind="zero")
    want = np.asarray(fields[0]).astype(np.float64)
    step = make_step(st, (h, w))
    for _ in range(steps):
        want = golden.heat_step(want, alpha)
        fields = step(fields)
    np.testing.assert_allclose(
        np.asarray(fields[0]), want, rtol=1e-5, atol=1e-4)


@settings(max_examples=10, **_SETTINGS)
@given(
    shape=hs.tuples(hs.integers(7, 12), hs.integers(7, 12)),
    halo=hs.integers(1, 3),
    steps=hs.integers(1, 2),
    seed=hs.integers(0, 2**16),
)
def test_synth_halo_k_matches_numpy(shape, halo, steps, seed):
    """Width-k footprints compute every cell on any (odd included) shape."""
    st = _synth_stencil(2, halo)
    rng = np.random.default_rng(seed)
    u = rng.uniform(0.0, 1.0, shape).astype(np.float32)
    fields = (jnp.asarray(u),)
    step = make_step(st, shape)
    want = u
    for _ in range(steps):
        want = _np_synth_step(want, halo)
        fields = step(fields)
    np.testing.assert_allclose(
        np.asarray(fields[0]), want, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# frame pinning: guard cells never change, any halo width / dtype
# ---------------------------------------------------------------------------


@settings(max_examples=12, **_SETTINGS)
@given(
    case=hs.sampled_from([
        ("life", None), ("heat2d", None), ("heat2d", "bfloat16"),
        ("heat3d", None), ("heat3d4th", None), ("wave2d", None),
    ]),
    extent=hs.integers(8, 12),
    steps=hs.integers(1, 4),
    seed=hs.integers(0, 2**16),
)
def test_frame_cells_are_pinned(case, extent, steps, seed):
    name, dtype = case
    params = {"dtype": jnp.dtype(dtype)} if dtype else {}
    st = make_stencil(name, **params)
    shape = (extent,) * st.ndim
    fields = init_state(st, shape, seed=seed, density=0.3, kind="auto")
    before = [np.asarray(f).copy() for f in fields]
    step = make_step(st, shape)
    for _ in range(steps):
        fields = step(fields)
    frame = np.zeros(shape, bool)
    for d in range(st.ndim):
        sl = [slice(None)] * st.ndim
        sl[d] = slice(0, st.halo)
        frame[tuple(sl)] = True
        sl[d] = slice(extent - st.halo, extent)
        frame[tuple(sl)] = True
    for b, f in zip(before, fields):
        np.testing.assert_array_equal(np.asarray(f)[frame], b[frame])


# ---------------------------------------------------------------------------
# sharded == unsharded over random meshes, halos 1-3, dtypes
# ---------------------------------------------------------------------------

_MESHES_2D = [(2, 1), (1, 2), (2, 2), (4, 1), (4, 2)]
_MESHES_3D = [(2, 1, 1), (1, 2, 2), (2, 2, 2)]


_CASES = [
    ("life", None, 2), ("heat2d", None, 2), ("heat2d", "bfloat16", 2),
    ("heat3d", None, 3), ("heat3d4th", None, 3), ("wave3d", None, 3),
]


def _check_sharded_case(case, mesh_i, local, steps, seed):
    name, dtype, ndim = case
    params = {"dtype": jnp.dtype(dtype)} if dtype else {}
    st = make_stencil(name, **params)
    meshes = _MESHES_2D if ndim == 2 else _MESHES_3D
    mesh_shape = meshes[mesh_i % len(meshes)]
    # per-shard extent must cover the halo slab a neighbor pulls in one hop
    local = tuple(max(l, st.halo) for l in local[:ndim])
    grid = tuple(l * m for l, m in zip(local, mesh_shape))
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto")

    ref = fields
    ref_step = make_step(st, grid)
    for _ in range(steps):
        ref = ref_step(ref)

    mesh = make_mesh(mesh_shape)
    sh_step = make_sharded_step(st, mesh, grid)
    got = shard_fields(fields, mesh, ndim)
    for _ in range(steps):
        got = sh_step(got)

    for r, g in zip(ref, got):
        r, g = np.asarray(r), np.asarray(g)
        if np.issubdtype(r.dtype, np.integer) or r.dtype == jnp.bfloat16:
            np.testing.assert_array_equal(g, r)
        else:
            np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-5)


@settings(max_examples=6, **_SETTINGS)
@given(
    case=hs.sampled_from(_CASES),
    mesh_i=hs.integers(0, 10),
    # fixed per-shard extents: examples reuse jit-cached programs, keeping
    # the fast tier fast; the slow variant below draws freely
    local=hs.sampled_from([(3, 4, 5), (4, 4, 4)]),
    steps=hs.integers(1, 2),
    seed=hs.integers(0, 2**16),
)
def test_sharded_matches_unsharded_property(case, mesh_i, local, steps, seed):
    _check_sharded_case(case, mesh_i, local, steps, seed)


@pytest.mark.slow
@settings(max_examples=10, **_SETTINGS)
@given(
    case=hs.sampled_from(_CASES),
    mesh_i=hs.integers(0, 10),
    # every fresh (case, mesh, shape) combination costs a shard_map compile
    # (~10s on CPU), so the example budget IS the wall-clock budget: 10
    # free-shape examples ~= 90s, vs 25 at 230s in round 2 (the suite
    # could not finish inside a 10-minute CI slot)
    local=hs.tuples(hs.integers(2, 5), hs.integers(2, 5), hs.integers(2, 5)),
    steps=hs.integers(1, 2),
    seed=hs.integers(0, 2**16),
)
def test_sharded_matches_unsharded_property_wide(case, mesh_i, local, steps,
                                                 seed):
    _check_sharded_case(case, mesh_i, local, steps, seed)


def _check_width_k(halo, mesh_i, local, seed):
    st = _synth_stencil(2, halo)
    mesh_shape = _MESHES_2D[mesh_i % len(_MESHES_2D)]
    local = tuple(max(l, halo) for l in local)
    grid = tuple(l * m for l, m in zip(local, mesh_shape))
    rng = np.random.default_rng(seed)
    u = rng.uniform(0.0, 1.0, grid).astype(np.float32)
    fields = (jnp.asarray(u),)

    ref = fields
    ref_step = make_step(st, grid)
    for _ in range(2):
        ref = ref_step(ref)

    mesh = make_mesh(mesh_shape)
    sh_step = make_sharded_step(st, mesh, grid)
    got = shard_fields(fields, mesh, 2)
    for _ in range(2):
        got = sh_step(got)
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(ref[0]), rtol=1e-5, atol=1e-6)


# halo 1 and 2 are the shipped families' widths (heat3d4th is halo 2);
# halo 3 is synthetic future-proofing — slow tier (round-5 CI trim), and
# the wide property below draws halo 1-3 freely anyway.
@pytest.mark.parametrize(
    "halo", [1, 2, pytest.param(3, marks=pytest.mark.slow)])
def test_sharded_width_k_halo(halo):
    """Halo widths 1-3 cross shard boundaries correctly (synthetic op)."""
    _check_width_k(halo, mesh_i=2, local=(4, 5), seed=11)


@pytest.mark.slow
@settings(max_examples=5, **_SETTINGS)
@given(
    halo=hs.integers(1, 3),
    mesh_i=hs.integers(0, 10),
    local=hs.tuples(hs.integers(3, 6), hs.integers(3, 6)),
    seed=hs.integers(0, 2**16),
)
def test_sharded_width_k_halo_property_wide(halo, mesh_i, local, seed):
    _check_width_k(halo, mesh_i, local, seed)


# ---------------------------------------------------------------------------
# Pallas whole-step builders (rawstep / fused) over free shapes
# ---------------------------------------------------------------------------

_PALLAS_CASES = [
    ("heat3d", {}), ("heat3d27", {"alpha": 0.1}), ("wave3d", {}),
    ("grayscott3d", {}), ("advect3d", {"cx": 0.3, "cy": -0.2, "cz": 0.2}),
]


@pytest.mark.slow
@settings(max_examples=8, **_SETTINGS)
@given(
    case=hs.sampled_from(_PALLAS_CASES),
    z=hs.integers(4, 40),
    y=hs.integers(4, 40),
    x=hs.sampled_from([8, 17, 128, 130]),
    seed=hs.integers(0, 2**16),
)
def test_raw_step_property(case, z, y, x, seed):
    """make_raw_step either declines or matches make_step, any shape."""
    from mpi_cuda_process_tpu.ops.pallas import rawstep

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (z, y, x)
    raw = rawstep.make_raw_step(st, grid, interpret=True)
    if raw is None:
        return  # untileable is a valid answer; never a crash
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto")
    ref = make_step(st, grid)(fields)
    got = raw(fields)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)


@pytest.mark.slow
@settings(max_examples=6, **_SETTINGS)
@given(
    case=hs.sampled_from(_PALLAS_CASES),
    z=hs.sampled_from([8, 16, 24, 40]),
    y=hs.sampled_from([8, 16, 32]),
    x=hs.sampled_from([64, 128]),
    k=hs.sampled_from([4, 8]),
    seed=hs.integers(0, 2**16),
)
def test_fused_step_property(case, z, y, x, k, seed):
    """make_fused_step either declines or matches k plain steps."""
    from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (z, y, x)
    fused = make_fused_step(st, grid, k, interpret=True)
    if fused is None:
        return
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto")
    ref = fields
    step = make_step(st, grid)
    for _ in range(k):
        ref = step(ref)
    got = fused(fields)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)


@pytest.mark.slow
@settings(max_examples=8, **_SETTINGS)
@given(
    case=hs.sampled_from([
        ("life", {}), ("heat2d", {}), ("wave2d", {}),
        ("advect2d", {"cx": -0.4, "cy": 0.2}), ("grayscott2d", {}),
        ("sor2d", {}),
    ]),
    h=hs.sampled_from([8, 15, 16, 24, 100]),
    w=hs.sampled_from([64, 100, 128, 256]),
    k=hs.integers(1, 9),
    periodic=hs.booleans(),
    seed=hs.integers(0, 2**16),
)
def test_fullgrid_step_property(case, h, w, k, periodic, seed):
    """make_fullgrid_step either declines (odd shapes) or matches k steps."""
    from mpi_cuda_process_tpu.ops.pallas.fullgrid import make_fullgrid_step

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (h, w)
    full = make_fullgrid_step(st, grid, k, interpret=True, periodic=periodic)
    if full is None:
        assert h % 8 or w % 128  # aligned shapes this small never decline
        return
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto",
                        periodic=periodic)
    ref = fields
    step = make_step(st, grid, periodic=periodic)
    for _ in range(k):
        ref = step(ref)
    got = full(fields)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)


@pytest.mark.slow
@settings(max_examples=6, **_SETTINGS)
@given(
    case=hs.sampled_from(_PALLAS_CASES),
    z=hs.sampled_from([8, 16, 24, 40]),
    y=hs.sampled_from([8, 16, 32]),
    x=hs.sampled_from([64, 128]),
    k=hs.sampled_from([4, 8]),
    periodic=hs.booleans(),
    seed=hs.integers(0, 2**16),
)
def test_padfree_step_property(case, z, y, x, k, periodic, seed):
    """The 9-block pad-free kernel either declines or matches k plain
    steps — over free shapes, both boundary modes, and both loop
    lowerings (k=8 exercises the fori_loop body)."""
    from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (z, y, x)
    fused = make_fused_step(st, grid, k, interpret=True, periodic=periodic,
                            padfree=True)
    if fused is None:
        return
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto",
                        periodic=periodic)
    ref = fields
    step = make_step(st, grid, periodic=periodic)
    for _ in range(k):
        ref = step(ref)
    got = fused(fields)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)


@pytest.mark.slow
@settings(max_examples=6, **_SETTINGS)
@given(
    case=hs.sampled_from(_PALLAS_CASES),
    nz=hs.sampled_from([2, 4]),
    lz=hs.sampled_from([16, 24]),
    y=hs.sampled_from([16, 32]),
    k=hs.sampled_from([4, 8]),
    periodic=hs.booleans(),
    seed=hs.integers(0, 2**16),
)
def test_zslab_padfree_sharded_property(case, nz, lz, y, k, periodic, seed):
    """The z-slab pad-free sharded step either declines or matches k
    plain steps — free shard counts, local extents, boundary modes."""
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (nz * lz, y, 128)
    mesh = make_mesh((nz, 1, 1))
    fused = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                    periodic=periodic, padfree=True)
    if fused is None:
        return
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto",
                        periodic=periodic)
    ref = fields
    step = make_step(st, grid, periodic=periodic)
    for _ in range(k):
        ref = step(ref)
    got = fused(shard_fields(fields, mesh, 3))
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)


@pytest.mark.slow
@settings(max_examples=8, **_SETTINGS)
@given(
    case=hs.sampled_from(_PALLAS_CASES),
    zchunks=hs.integers(3, 5),
    bz=hs.sampled_from([8, 16]),
    y=hs.sampled_from([24, 32, 48]),
    k=hs.sampled_from([2, 4]),
    seed=hs.integers(0, 2**16),
)
def test_stream_builder_declines_or_matches(case, zchunks, bz, y, k, seed):
    """Free-shape sweep of the STREAMING kernel's gates: for any shape the
    builder either declines (caller falls back) or produces a step that
    matches k plain steps — never a silently-wrong geometry.  The gates
    under test interact: bz >= 2*k*halo*phases, >= 3 chunks, sublane
    alignment of the y strip, and the rounded margin clamp wm_a <= Y."""
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        make_stream_fused_step,
    )

    name, kw = case
    st = make_stencil(name, **kw)
    grid = (zchunks * bz, y, 128)
    stream = make_stream_fused_step(st, grid, k, interpret=True)
    if stream is None:
        return
    fields = init_state(st, grid, seed=seed, density=0.3, kind="auto")
    ref = fields
    step = make_step(st, grid)
    for _ in range(k):
        ref = step(ref)
    got = stream(fields)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(g, np.float32), np.asarray(r, np.float32),
            rtol=0, atol=1e-3)
