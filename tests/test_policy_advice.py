"""benchmarks/policy_advice.py: the campaign-results -> policy-flip
advisor.  A wrong recommendation here costs a wrong one-line edit in
cli.py's auto tables at the end-of-round crunch, so each decision branch
is pinned against synthetic results with known winners.  Pure file
reading — no backend, no kernels."""

import importlib.util
import json
import os

import pytest

_BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")


@pytest.fixture()
def P():
    spec = importlib.util.spec_from_file_location(
        "policy_advice_under_test",
        os.path.join(_BENCH_DIR, "policy_advice.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(mc, **kw):
    return dict({"mcells_per_s": mc}, **kw)


def _advice(P, tmp_path, results):
    p = tmp_path / "r.json"
    p.write_text(json.dumps(results))
    return {d: (r, e) for d, r, e in P.advise(P.load(str(p)))}


def test_label_parse(P):
    assert P._parse_label("heat3d_512_f32_stream4") == {
        "family": "heat3d", "size": 512, "dtype": "f32",
        "compute": "stream4"}
    assert P._parse_label("advect3d_256_f32_jnp_n150")["compute"] == \
        "jnp_n150"
    assert P._parse_label("heat3d_512_f32_padfree4_t16")["compute"] == \
        "padfree4_t16"
    assert P._parse_label("life_2048_i32_full16")["dtype"] == "i32"
    assert P._parse_label("heat3d_256_f32")["compute"] == "jnp"
    assert P._parse_label("not_a_label") is None


def test_stream_win_flips_fuse_kind(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_512_f32_fused4": _rec(107000),
        "heat3d_512_f32_stream4": _rec(155000),
    })
    r, e = adv["_AUTO_FUSE_KIND"]
    assert r == "heat3d: stream"
    assert "155000" in e and "107000" in e


def test_stream_loss_keeps_tiled(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_512_f32_fused4": _rec(107000),
        "heat3d_512_f32_stream4": _rec(90000),
    })
    assert adv["_AUTO_FUSE_KIND"][0] == "heat3d: keep tiled"


def test_suspect_measurements_never_count(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_512_f32_fused4": _rec(107000),
        "heat3d_512_f32_stream4": _rec(900000, suspect=True),
    })
    # no measured stream survives -> the explicit per-family pending
    # row, never a flip recommendation built on a suspect number
    r, e = adv["_AUTO_FUSE_KIND"]
    assert r == "heat3d: no measured comparison yet"
    assert "stream" in e


def test_family_flip_requires_winning_every_measured_size(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_256_f32_fused4": _rec(107000),
        "heat3d_256_f32_stream4": _rec(120000),   # wins at 256
        "heat3d_512_f32_fused4": _rec(107000),
        "heat3d_512_f32_stream4": _rec(90000),    # loses at 512
    })
    r, e = adv["_AUTO_FUSE_KIND"]
    assert r.startswith("heat3d: MIXED")
    assert "256^3" in e and "512^3" in e  # both sizes cited


def test_no_data_rows_name_pending_labels(P, tmp_path):
    adv = _advice(P, tmp_path, {})
    for decision in ("_AUTO_FUSE_K", "_AUTO_FUSE_KIND",
                     "_AUTO_FUSE_K_BF16", "_PADFREE_ABOVE_BYTES",
                     "_AUTO_FULL_K", "advect3d suspect",
                     "copy calibration"):
        r, e = adv[decision]
        assert r == "no measured data yet"
        assert "pending" in e


def test_bf16_blocking_win_names_k_and_kind(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_512_bf16": _rec(35700),
        "heat3d_512_bf16_padfree8": _rec(80000),
    })
    r, _ = adv["_AUTO_FUSE_K_BF16"]
    assert r == "heat3d: k=8 via tiled/padfree"
    adv2 = _advice(P, tmp_path, {
        "heat3d_512_bf16": _rec(35700),
        "heat3d_512_bf16_stream4": _rec(80000),
    })
    assert adv2["_AUTO_FUSE_K_BF16"][0] == "heat3d: k=4 via stream"


def test_bf16_mixed_kind_across_sizes_never_names_one_kind(P, tmp_path):
    """Blocking wins at both sizes with the same k, but via padfree8 at
    256^3 and stream8 at 512^3 — the advice must flag the kind as MIXED
    instead of naming the largest-size winner family-wide (the old
    rows[-1]-only derivation)."""
    adv = _advice(P, tmp_path, {
        "heat3d_256_bf16": _rec(35700),
        "heat3d_256_bf16_padfree8": _rec(80000),
        "heat3d_512_bf16": _rec(35700),
        "heat3d_512_bf16_stream8": _rec(80000),
    })
    r, e = adv["_AUTO_FUSE_K_BF16"]
    assert "MIXED" in r and "k=8" in r
    assert "256^3" in e and "512^3" in e


def test_bf16_loss_keeps_jnp(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "heat3d_512_bf16": _rec(35700),
        "heat3d_512_bf16_fused8": _rec(20000),
    })
    assert adv["_AUTO_FUSE_K_BF16"][0] == "heat3d: keep jnp"


def test_padfree_threshold_drop_needs_every_size(P, tmp_path):
    base = {
        "heat3d_256_f32_fused4": _rec(106978),
        "heat3d_256_f32_padfree4": _rec(106000),  # within 3%
        "heat3d_512_f32_fused4": _rec(107300),
    }
    adv = _advice(P, tmp_path, dict(
        base, heat3d_512_f32_padfree4=_rec(120000)))
    assert adv["_PADFREE_ABOVE_BYTES"][0].startswith("drop to 0")
    adv2 = _advice(P, tmp_path, dict(
        base, heat3d_512_f32_padfree4=_rec(80000)))
    assert adv2["_PADFREE_ABOVE_BYTES"][0].startswith("keep")


def test_fullgrid_win_flips_family(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "life_2048_i32": _rec(53831),
        "life_2048_i32_full16": _rec(90000),
    })
    assert adv["_AUTO_FULL_K"][0] == "life: k=16"
    adv2 = _advice(P, tmp_path, {
        "life_2048_i32": _rec(53831),
        "life_2048_i32_full16": _rec(40000),
    })
    assert adv2["_AUTO_FULL_K"][0] == "life: keep jnp"


def test_auto_fuse_k_win_and_keep(P, tmp_path):
    adv = _advice(P, tmp_path, {
        "grayscott3d_256_f32_jnp": _rec(14400),
        "grayscott3d_256_f32_raw": _rec(22700),
        "grayscott3d_256_f32_fused4": _rec(45000),
    })
    r, e = adv["_AUTO_FUSE_K"]
    assert r == "grayscott3d: fused k=4"
    assert "22700" in e  # compared against the best single-step (raw)
    adv2 = _advice(P, tmp_path, {
        "heat3d4th_256_f32_jnp": _rec(62775),
        "heat3d4th_256_f32_fused2": _rec(52300),
    })
    assert adv2["_AUTO_FUSE_K"][0] == "heat3d4th: keep single-step"


def test_load_prefers_record_fields(P, tmp_path):
    # a label the regex cannot parse still lands via the record's own
    # stencil/grid/dtype/compute fields (the campaign always writes them)
    p = tmp_path / "r.json"
    p.write_text(json.dumps({"WEIRD-Label.v2": {
        "mcells_per_s": 155000, "stencil": "heat3d", "grid": [512] * 3,
        "dtype": "float32", "compute": "stream4"},
        "heat3d_512_f32_fused4": _rec(107000)}))
    table = P.load(str(p))
    assert ("heat3d", 512, "f32") in table
    assert "stream4" in table[("heat3d", 512, "f32")]
    adv = {d: r for d, r, _ in P.advise(table)}
    assert adv["_AUTO_FUSE_KIND"] == "heat3d: stream"


def test_advect_suspect_flagged_and_resolved(P, tmp_path):
    # 150 Gcells/s f32 1R+1W implies >1.2 TB/s: flagged
    adv = _advice(P, tmp_path, {"advect3d_256_f32_jnp": _rec(150454)})
    assert adv["advect3d suspect"][0].startswith("STILL")
    # a disagreeing rerun resolves it (the outlier was noise)
    adv2 = _advice(P, tmp_path, {
        "advect3d_256_f32_jnp": _rec(150454),
        "advect3d_256_f32_jnp_n150": _rec(60000),
    })
    assert adv2["advect3d suspect"][0].startswith("resolved")
    # within-roofline reading was never suspect
    adv3 = _advice(P, tmp_path, {"advect3d_256_f32_jnp": _rec(60000)})
    assert adv3["advect3d suspect"][0].startswith("resolved")
    # a rerun that disagrees but is ITSELF above the roofline resolves
    # nothing (120 Gcells/s f32 -> 960 GB/s implied > 819) — and with a
    # fused label in the table, NEITHER jnp entry may keep serving as
    # the single-step baseline: jnp_n150 also matches the baseline
    # prefix in _best, so leaving it produced a 'keep single-step'
    # verdict cited against a physically impossible number (ADVICE.md
    # r5 medium).  The correct outcome is the explicit pending row.
    adv4 = _advice(P, tmp_path, {
        "advect3d_256_f32_jnp": _rec(150454),
        "advect3d_256_f32_jnp_n150": _rec(120000),
        "advect3d_256_f32_fused4": _rec(45000),
    })
    assert adv4["advect3d suspect"][0].startswith("STILL")
    r, e = adv4["_AUTO_FUSE_K"]
    assert r == "advect3d: no measured comparison yet"
    assert "single-step baseline" in e


def test_copy_calibration_reports_rate(P, tmp_path):
    adv = _advice(P, tmp_path, {"copy_512_f32": _rec(80000)})
    r, _ = adv["copy calibration"]
    assert "640 GB/s" in r  # 80e9 cells/s * 8 B
    # an errored 512 row must not suppress the measured 256 fallback
    adv2 = _advice(P, tmp_path, {
        "copy_512_f32": {"error": "subprocess timeout"},
        "copy_256_f32": _rec(80000),
    })
    assert "256^3" in adv2["copy calibration"][1]


def test_runs_on_the_live_results_file(P):
    # the real (seeded) table must parse without raising, whatever its
    # current mix of successes/errors/timeouts
    path = os.path.join(_BENCH_DIR, "results_r05.json")
    rows = list(P.advise(P.load(path)))
    assert isinstance(rows, list)
