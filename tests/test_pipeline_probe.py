"""Interpret-mode smoke for the HBM pipeline probes.

benchmarks/pipeline_probe.py is a tunnel-time experiment (can the manual
make_async_copy pipeline beat Mosaic's ~330 GB/s auto-pipeline?); these
tests prove every probe BUILDS and computes ``2*x`` correctly on CPU so
the harness never wastes a healthy-tunnel window on a syntax error.
"""

import importlib.util
import os

import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def probe_mod():
    spec = importlib.util.spec_from_file_location(
        "pipeline_probe_smoke",
        os.path.join(REPO, "benchmarks", "pipeline_probe.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", ["jnp_copy", "auto_copy", "manual2_copy",
                                  "manual4_copy"])
def test_probe_builds_and_doubles(probe_mod, name):
    shape = (8, 8, 128)
    fn = probe_mod.build_probe(name, shape, bz=2, interpret=True)
    x = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    np.testing.assert_array_equal(np.asarray(fn(x)), 2.0 * np.asarray(x))
