"""Interpret-mode smoke for the HBM pipeline probes.

benchmarks/pipeline_probe.py is a tunnel-time experiment (can the manual
make_async_copy pipeline beat Mosaic's ~330 GB/s auto-pipeline?); these
tests prove every probe BUILDS and computes ``2*x`` correctly on CPU so
the harness never wastes a healthy-tunnel window on a syntax error.
"""

import importlib.util
import os

import jax.numpy as jnp
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def probe_mod():
    spec = importlib.util.spec_from_file_location(
        "pipeline_probe_smoke",
        os.path.join(REPO, "benchmarks", "pipeline_probe.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", ["jnp_copy", "auto_copy", "manual2_copy",
                                  "manual4_copy", "manual2s_copy",
                                  "manual4s_copy"])
def test_probe_builds_and_doubles(probe_mod, name):
    shape = (8, 8, 128)
    fn = probe_mod.build_probe(name, shape, bz=2, interpret=True)
    x = jnp.arange(np.prod(shape), dtype=jnp.float32).reshape(shape)
    np.testing.assert_array_equal(np.asarray(fn(x)), 2.0 * np.asarray(x))


@pytest.mark.parametrize("name", ["manual2_stencil_k4",
                                  "manual4_stencil_k4",
                                  "manual4s_stencil_k4"])
def test_stencil_probe_pair_equivalent(probe_mod, name):
    """The manual-pipeline stencil probes must compute EXACTLY what the
    auto-pipeline control computes — otherwise the measured pair would
    compare different work and the ceiling verdict would be garbage."""
    shape = (8, 8, 128)
    x = jnp.linspace(0., 1., int(np.prod(shape)),
                     dtype=jnp.float32).reshape(shape)
    auto = probe_mod.build_probe("auto4_stencil", shape, bz=2,
                                 interpret=True)
    manual = probe_mod.build_probe(name, shape, bz=2, interpret=True)
    np.testing.assert_array_equal(np.asarray(auto(x)),
                                  np.asarray(manual(x)))


def test_probe_k_parsing(probe_mod):
    assert probe_mod._probe_k("jnp_copy") == 1
    assert probe_mod._probe_k("auto4_stencil") == 4
    assert probe_mod._probe_k("manual2_stencil_k4") == 4
    # every default probe parses
    for name in probe_mod.PROBES:
        probe_mod._probe_k(name)


def test_zslab_probe_child_template_is_valid():
    """The zslab VMEM probe's child code must be syntactically valid and
    its construction path must work (interpret mode, tiny shape) — a
    healthy-tunnel window must never be spent on a harness bug."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "zslab_probe_smoke", os.path.join(REPO, "benchmarks",
                                          "zslab_probe.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # template formats + compiles for every attempt row
    for label, name, dt, local, k, tiles in mod.ATTEMPTS:
        code = mod._CHILD.format(repo=REPO, name=name, dt=dt, local=local,
                                 k=k, tiles=tiles)
        compile(code, label, "exec")
    # the construction path itself, tiny, interpret mode
    import jax
    import jax.numpy as jnp

    from mpi_cuda_process_tpu import make_stencil
    from mpi_cuda_process_tpu.ops.pallas.fused import (
        build_zslab_padfree_call,
    )

    st = make_stencil("wave3d")
    local = (16, 16, 128)
    built = build_zslab_padfree_call(st, local, (128, 16, 128), 4,
                                     tiles=(8, 8), interpret=True)
    assert built is not None
    call, m, nfields = built
    key = jax.random.PRNGKey(0)
    fields = [jax.random.uniform(jax.random.fold_in(key, i), local,
                                 st.dtype) for i in range(nfields)]
    slab = jnp.zeros((m, 16, 128), st.dtype)
    origins = jnp.array([16, 0], jnp.int32)
    args = []
    for f in fields:
        args += [f] * 9 + [slab] * 3 + [slab] * 3
    out = call(origins, *args)
    assert np.isfinite(np.asarray(out[0])).all()
