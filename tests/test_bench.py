"""Benchmark-harness smoke tests (SURVEY.md §4.6).

The round-gate ``bench.py`` and the weak/strong/halo harness
``benchmarks/scaling.py`` are exactly the scripts with no other CI coverage —
a regression in either would ship silently and surface only in the driver's
round-end run.  These tests execute both in tiny configs and assert a finite,
positive throughput comes out, plus pin the watchdog's stale-fallback record
contract (ADVICE round 1: stale data must not be scorable as fresh).
"""

import importlib.util
import json
import math
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    # Plain import: bench.py's __main__ guards keep the watchdog thread and
    # main() from running; conftest already forced the CPU platform.
    # BENCH_OBS_PROBE=0 keeps the wedged-path records' heartbeat probe
    # (a pair of bounded subprocesses) out of the unit tests — the probe
    # itself is covered in tests/test_obs.py with an injected stub.
    os.environ["BENCH_OBS_PROBE"] = "0"
    sys.path.insert(0, REPO)
    import bench as mod

    return mod


@pytest.fixture(scope="module")
def scaling():
    spec = importlib.util.spec_from_file_location(
        "scaling_smoke", os.path.join(REPO, "benchmarks", "scaling.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_stencil_smoke(bench):
    mcells, per_step, compute, _suspect = bench.bench_stencil(
        "heat3d", (16, 16, 16), {}, 2, reps=1)
    assert math.isfinite(mcells) and mcells > 0
    assert math.isfinite(per_step) and per_step > 0
    assert compute == "jnp"  # fuse=0 default


def test_bench_stencil_fused_accounting(bench):
    # fused path must report per REAL step (k steps per fused call)
    mcells, per_step, compute, _suspect = bench.bench_stencil(
        "heat3d", (32, 32, 128), {}, 2, reps=1, fuse=4)
    assert compute in ("jnp", "pallas_fused_k4")  # jnp if untileable
    assert math.isfinite(mcells) and mcells > 0


def test_stale_fallback_record_is_unscorable(bench):
    rec = bench._stale_fallback_record()
    # Must be valid JSON, explicitly stale, and under a DIFFERENT metric name
    # than a fresh measurement, so the driver can never score it as fresh.
    json.dumps(rec)
    assert rec["stale"] is True
    assert rec["metric"].endswith(("_cached", "_unmeasured"))
    assert "note" in rec


def test_scaling_weak_smoke(scaling, capsys):
    # --virtual is a no-op here (the backend is already initialized by
    # conftest), so derive the expected mesh ladder from the live count.
    import jax

    n = len(jax.devices())
    rc = scaling.main([
        "--mode", "weak", "--stencil", "heat2d", "--block", "16,16",
        "--steps", "2", "--reps", "1", "--virtual", str(n),
    ])
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l]
    assert len(recs) == int(math.log2(n)) + 1  # ladder 1, 2, 4, ... n
    for rec in recs:
        assert rec["mcells_per_s"] > 0
        assert math.isfinite(rec["efficiency"])
    assert recs[0]["efficiency"] == 1.0


@pytest.mark.slow
def test_scaling_halo_smoke(scaling, capsys):
    rc = scaling.main([
        "--mode", "halo", "--stencil", "heat2d", "--block", "16,16",
        "--steps", "2", "--reps", "1", "--virtual", "8",
    ])
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l]
    assert recs, "halo mode emitted no records"
    for rec in recs:
        assert rec["ms_per_step_full"] > 0
        assert 0.0 <= rec["halo_overhead_frac"] <= 1.0


@pytest.mark.slow
def test_scaling_fused_smoke(scaling, capsys):
    """--fuse K: z/y-only mesh ladder, untileable rungs skipped, k-step
    accounting (mcells uses steps*k real steps)."""
    import jax

    n = len(jax.devices())
    rc = scaling.main([
        "--mode", "weak", "--stencil", "heat3d", "--block", "16,16,128",
        "--steps", "2", "--reps", "1", "--fuse", "4", "--virtual", str(n),
    ])
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l]
    assert recs, "fused weak mode emitted no records"
    for rec in recs:
        assert rec["fuse"] == 4
        assert rec["mesh"][2] == 1  # lane axis never sharded
        assert rec["mcells_per_s"] > 0


@pytest.mark.slow
def test_scaling_fused_overlap_ab_rows(scaling, capsys):
    """--fuse K --overlap: the communication-overlap A/B ladder — rows
    carry overlap=true and price the split stepper (rungs whose geometry
    declines the split are skipped, never silently run plain)."""
    import jax

    n = len(jax.devices())
    rc = scaling.main([
        "--mode", "weak", "--stencil", "heat3d", "--block", "32,16,128",
        "--steps", "2", "--reps", "1", "--fuse", "4", "--overlap",
        "--virtual", str(n),
    ])
    assert rc == 0
    recs = [json.loads(l) for l in capsys.readouterr().out.splitlines() if l]
    sharded = [r for r in recs if max(r["mesh"]) > 1]
    assert sharded, "overlap A/B mode emitted no sharded rows"
    for rec in sharded:
        assert rec["fuse"] == 4 and rec["overlap"] is True
        assert rec["mcells_per_s"] > 0


def test_stale_fallback_replays_only_local_measurements(bench, tmp_path):
    """Round-3 advisor (medium): a fresh checkout with a wedged backend
    must NOT replay VCS data as a value.  Only a cache record written by a
    real local bench run (``local_run: true``) is replayed; otherwise the
    record reports 0.0 and points at the campaign table in the note."""
    old = bench._CACHE
    try:
        # no cache at all -> unmeasured, value 0.0, campaign cited in note
        bench._CACHE = str(tmp_path / "absent.json")
        rec = bench._stale_fallback_record()
        assert rec["stale"] is True and rec["value"] == 0.0
        assert "results_r0" in rec["note"]
        # a cache WITHOUT the local_run marker (e.g. committed seed data)
        # is refused too
        unmarked = tmp_path / "unmarked.json"
        unmarked.write_text(json.dumps(
            {"metric": "m", "value": 99999.0, "backend": "tpu",
             "measured_at": 1785358700.0}))
        bench._CACHE = str(unmarked)
        rec = bench._stale_fallback_record()
        assert rec["value"] == 0.0
        # a genuine local record replays, stale-marked
        local = tmp_path / "local.json"
        local.write_text(json.dumps(
            {"metric": "m", "value": 85621.8, "vs_baseline": 1.71,
             "backend": "tpu", "measured_at": 1785358700.0,
             "local_run": True}))
        bench._CACHE = str(local)
        rec = bench._stale_fallback_record()
        assert rec["stale"] is True and rec["value"] == 85621.8
        assert rec["metric"].endswith("_cached")
        # corrupt caches must degrade, not raise (watchdog-thread safety)
        bad = tmp_path / "bad.json"
        bad.write_text('{"measured_at": "yesterday", "local_run": true}')
        bench._CACHE = str(bad)
        rec2 = bench._stale_fallback_record()
        assert rec2["stale"] is True
    finally:
        bench._CACHE = old


def test_stale_record_carries_last_real_measurement(bench, tmp_path):
    """VERDICT r5 weak #7: the wedged-path record must distinguish
    "never measured" from "measured N Gcells/s, tunnel currently dead".
    Both stale paths carry a provenance-marked ``last_real_measurement``
    pointer; the scorable ``value`` stays 0.0/stale on the honest paths
    (VCS data is cited, never replayed as a value)."""
    old = bench._CACHE
    try:
        # no local cache: value stays 0.0, but the committed campaign
        # table's newest timestamped row is cited with an explicit
        # not-a-local-measurement source
        bench._CACHE = str(tmp_path / "absent.json")
        rec = bench._stale_fallback_record()
        assert rec["value"] == 0.0 and rec["stale"] is True
        last = rec["last_real_measurement"]
        assert last["value"] > 0 and last["measured_at"] > 0
        assert "not a local measurement" in last["source"]
        assert last["label"]  # a real campaign label, e.g. heat3d_512_...
        json.dumps(rec)
        # a local cache record: the pointer names the local cache
        local = tmp_path / "local.json"
        local.write_text(json.dumps(
            {"metric": "m", "value": 85621.8, "backend": "tpu",
             "measured_at": 1785358700.0, "local_run": True}))
        bench._CACHE = str(local)
        rec = bench._stale_fallback_record()
        assert rec["last_real_measurement"]["source"] == "local bench cache"
        assert rec["last_real_measurement"]["value"] == 85621.8
    finally:
        bench._CACHE = old


def test_wedged_record_carries_checkpoint_resume_pointer(
        bench, tmp_path, monkeypatch):
    """Round-13 satellite: the wedged-path record names the latest
    checkpoint dir + step next to ``last_real_measurement``, so the same
    JSON that reports the wedge also holds the resume pointer a human
    (or the supervisor) needs."""
    from mpi_cuda_process_tpu.obs import trace as trace_lib
    from mpi_cuda_process_tpu.utils import checkpointing

    tel = tmp_path / "telemetry"
    monkeypatch.setenv("OBS_TELEMETRY_DIR", str(tel))
    ck = str(tmp_path / "ck")
    checkpointing.save_checkpoint(ck, (), 40, {})
    with trace_lib.TraceWriter(str(tel / "run.jsonl")) as w:
        w.write_manifest(trace_lib.build_manifest(
            "cli", {"stencil": "life", "checkpoint_dir": ck}))
    old = bench._CACHE
    try:
        bench._CACHE = str(tmp_path / "absent.json")
        rec = bench._stale_fallback_record()
    finally:
        bench._CACHE = old
    assert rec["latest_checkpoint"] == {"dir": ck, "step": 40}
    json.dumps(rec)  # the record must stay one serializable JSON line


def test_mktable_regenerates_from_campaign(capsys):
    """benchmarks/mktable.py renders the measured table from a results
    file with the LIVE auto-policy picks bolded — the mechanism that
    keeps BASELINE.md and cli.py from silently disagreeing."""
    import subprocess
    import sys as _sys

    out = subprocess.run(
        [_sys.executable, os.path.join(REPO, "benchmarks", "mktable.py"),
         "--in", os.path.join(REPO, "benchmarks", "results_r03.json")],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr[-500:]
    body = out.stdout
    assert "| Config | compute | Mcells/s | ms/step |" in body
    # the r03 auto winners appear bolded per the live policy tables
    assert "**fused4**" in body and "**106,978**" in body
    # errored labels surface as pending, not silently dropped
    assert "Pending / errored / suspect" in body
