"""Wedge-diagnosis classifier (scripts/diagnose_tunnel.py).

The probe ladder runs against real hardware state that CI cannot
reproduce (a wedged tunnel), so what IS testable — and what a regression
would silently break — is the mapping from probe outcomes to the layer
verdict the next session acts on (STATE.md's H1/H2/H3 language), plus
the STATE.md section renderer.  The end-to-end CPU path (NO_TPU verdict
on a TPU-less box) runs in a subprocess to keep the tool honest about
its own environment handling.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def D():
    spec = importlib.util.spec_from_file_location(
        "diagnose_under_test",
        os.path.join(REPO, "scripts", "diagnose_tunnel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _r(probe, **kw):
    return {"probe": probe, "wall_s": 1.0, **kw}


def test_classifier_layer_verdicts(D):
    cpu_ok = _r("cpu_control", ok=True)
    disc_tpu = _r("discovery", ok=True, stdout="OK tpu 8")
    # broken environment dominates everything
    v, _ = D._classify([_r("cpu_control", ok=False, rc=1)])
    assert v == "ENVIRONMENT"
    # no TPU visible: a verdict about the box, not the tunnel
    v, _ = D._classify([cpu_ok, _r("discovery", ok=True,
                                   stdout="OK cpu 1")])
    assert v == "NO_TPU"
    # discovery hangs regardless of cache state -> session layer
    v, _ = D._classify([cpu_ok, _r("discovery", ok=False, hang=True),
                        _r("discovery_clean", ok=False, hang=True)])
    assert v == "SESSION_LAYER"
    # clean cache rescues discovery -> client cache implicated
    v, _ = D._classify([cpu_ok, _r("discovery", ok=False, hang=True),
                        _r("discovery_clean", ok=True, stdout="OK tpu 8")])
    assert v == "CLIENT_CACHE"
    # trivial op hangs past healthy discovery -> execute layer
    v, _ = D._classify([cpu_ok, disc_tpu,
                        _r("discovery_clean", ok=True, stdout="OK tpu 8"),
                        _r("execute", ok=False, hang=True)])
    assert v == "EXECUTE_LAYER"
    # fresh compile hangs past healthy execute -> H3 becomes a finding
    v, d = D._classify([cpu_ok, disc_tpu,
                        _r("discovery_clean", ok=True, stdout="OK tpu 8"),
                        _r("execute", ok=True, stdout="OK tpu 2"),
                        _r("compile", ok=False, hang=True)])
    assert v == "COMPILE_LAYER" and "H3" in d
    # everything answers -> healthy
    v, _ = D._classify([cpu_ok, disc_tpu,
                        _r("discovery_clean", ok=True, stdout="OK tpu 8"),
                        _r("execute", ok=True, stdout="OK tpu 2"),
                        _r("compile", ok=True, stdout="OK tpu 65.0")])
    assert v == "HEALTHY"


def test_state_section_renders_probe_table(D):
    sec = D._state_section("SESSION_LAYER", "detail text", [
        _r("cpu_control", ok=True, stderr_tail=""),
        _r("discovery", hang=True, stderr_tail="rpc error | deadline"),
    ], 1785849271.0)
    assert "## Tunnel wedge diagnosis" in sec
    assert "SESSION_LAYER" in sec and "detail text" in sec
    assert "| discovery | HANG |" in sec
    assert "\\|" in sec  # pipe in stderr escaped for the md table


def test_end_to_end_no_tpu_box():
    """On this TPU-less CI box the full ladder must complete within
    budget and return the NO_TPU verdict with valid JSON on stdout —
    the tool itself must never hang or crash (it diagnoses hangs)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "diagnose_tunnel.py"),
         "--timeout", "90"],
        capture_output=True, text=True, timeout=500, cwd=REPO)
    assert out.returncode == 0, out.stderr[-800:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["verdict"] in ("NO_TPU", "HEALTHY")  # healthy iff real TPU
    assert rec["probes"][0]["probe"] == "cpu_control"
    assert all("timeout_s" in p for p in rec["probes"])
