"""Direct unit tests for the utility layer.

These modules were previously covered only through CLI flows (SURVEY.md
§5.5 diagnostics, C7 renderer, mesh factoring, the round-4 slab
exchange): a regression inside one of them would have surfaced as an
opaque CLI-test failure.  Pin their contracts directly.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.parallel.mesh import factor_mesh
from mpi_cuda_process_tpu.utils import budget, diagnostics, render


def test_factor_mesh_balanced():
    assert factor_mesh(8, 3) == (2, 2, 2)
    assert factor_mesh(4, 2) == (2, 2)
    assert factor_mesh(1, 3) == (1, 1, 1)
    assert factor_mesh(64, 3) == (4, 4, 4)
    # non-power-of-two: product preserved, descending balance
    shape = factor_mesh(6, 3)
    assert np.prod(shape) == 6 and len(shape) == 3


def test_ascii_render_int_glyphs_and_float_ramp():
    ints = np.zeros((8, 8), np.int32)
    ints[2, 3] = 1
    art = render.ascii_render(ints)
    assert "0" in art and art.count("\n") >= 7  # alive glyph, row per line
    floats = np.linspace(0, 100, 64, dtype=np.float32).reshape(8, 8)
    art_f = render.ascii_render(floats)
    assert len(set(art_f) - {"\n"}) > 2  # a ramp, not a binary glyph
    # 3D renders its middle z-slice (index d//2); a gradient there must
    # produce non-blank glyphs, and the other slices must not leak in
    vol = np.zeros((4, 8, 8), np.float32)
    vol[2] = np.linspace(0, 100, 64, dtype=np.float32).reshape(8, 8)
    assert render.ascii_render(vol) == render.ascii_render(vol[2])
    assert render.ascii_render(vol).strip() != ""
    with pytest.raises(ValueError):
        render.ascii_render(np.zeros((2, 2, 2, 2)))


def test_field_diagnostics_per_family():
    life = make_stencil("life")
    f = init_state(life, (16, 128), seed=1, density=0.4, kind="random")
    d = diagnostics.field_diagnostics(life, f)
    assert d["population"] == float(jnp.sum(f[0]))

    wave = make_stencil("wave2d")
    fw = init_state(wave, (16, 128), kind="pulse")
    dw = diagnostics.field_diagnostics(wave, fw)
    assert "velocity_l2" in dw and np.isfinite(dw["velocity_l2"])

    heat = make_stencil("heat2d")
    fh = init_state(heat, (16, 128), kind="zero")
    step = make_step(heat, (16, 128))
    dh = diagnostics.field_diagnostics(heat, fh, step_fn=step)
    assert {"mean", "min", "max", "residual"} <= set(dh)
    assert dh["residual"] > 0  # cold interior vs hot walls: not converged
    line = diagnostics.format_diagnostics(dh)
    assert "residual" in line


def test_residual_norm_vanishes_at_fixed_point():
    heat = make_stencil("heat2d")
    shape = (16, 128)
    # the all-hot state equals the Dirichlet walls: an exact fixed point
    fields = (jnp.full(shape, 100.0, jnp.float32),)
    step = make_step(heat, shape)
    assert diagnostics.residual_norm(step, fields) == 0.0


def test_exchange_slabs_axis_unsharded_contract():
    from mpi_cuda_process_tpu.parallel.halo import exchange_slabs_axis

    x = jnp.arange(12.0, dtype=jnp.float32).reshape(4, 3)
    # unsharded guard-frame: both slabs are the bc constant
    lo, hi = exchange_slabs_axis(x, 0, None, 1, 1, bc_value=7.0)
    assert lo.shape == (1, 3) and float(lo[0, 0]) == 7.0
    assert jnp.array_equal(lo, hi)
    # unsharded periodic: slabs are the wrapped edge rows
    lo_p, hi_p = exchange_slabs_axis(x, 0, None, 1, 1, bc_value=0.0,
                                     periodic=True)
    assert jnp.array_equal(lo_p[0], x[-1])
    assert jnp.array_equal(hi_p[0], x[0])


def test_device_hbm_bytes_and_format():
    # CPU backend reports something or falls back to the v5e default —
    # either way a positive integer the guard can divide by
    assert budget.device_hbm_bytes() > 0
    txt = budget.format_budget(
        3 * 2**30, [("state", 2 * 2**30), ("pad", 2**30)], 16 * 2**30)
    assert "TOTAL per device" in txt and "16.00" in txt
