"""API-drift smoke tier: every module imports, every pallas symbol resolves.

The round-5 seed failure mode was ``pltpu.CompilerParams`` vanishing from
the installed JAX and taking SIX test modules down as opaque collection
errors.  This module turns that class of breakage into one named test
each: (a) every package module imports, (b) the compat resolver found a
compiler-params class, (c) every other ``pltpu`` / jax symbol the package
references still exists.  Runs in milliseconds — it is the first thing to
read when a JAX upgrade lands.
"""

import importlib
import pkgutil

import pytest

import mpi_cuda_process_tpu


def _all_module_names():
    names = ["mpi_cuda_process_tpu"]
    for m in pkgutil.walk_packages(mpi_cuda_process_tpu.__path__,
                                   prefix="mpi_cuda_process_tpu."):
        names.append(m.name)
    return names


@pytest.mark.parametrize("name", _all_module_names())
def test_module_imports(name):
    importlib.import_module(name)


def test_compiler_params_resolves():
    from mpi_cuda_process_tpu.ops.pallas.compat import (
        CompilerParams, compiler_params,
    )

    assert CompilerParams is not None
    p = compiler_params(vmem_limit_bytes=1 << 20,
                        dimension_semantics=("arbitrary",))
    assert p.vmem_limit_bytes == 1 << 20


def test_required_pltpu_symbols_present():
    from mpi_cuda_process_tpu.ops.pallas.compat import (
        REQUIRED_PLTPU_SYMBOLS, missing_pltpu_symbols,
    )

    assert missing_pltpu_symbols() == [], (
        "pltpu API drift: update ops/pallas/compat.py and the call sites")
    assert len(REQUIRED_PLTPU_SYMBOLS) >= 5


def test_shard_map_resolves():
    # stepper.py's try/except import chain must land on a callable
    from mpi_cuda_process_tpu.parallel.stepper import shard_map

    assert callable(shard_map)


def test_pallas_blockspec_memory_space_kwarg():
    # the SMEM/ANY BlockSpec spelling the kernels rely on
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    pl.BlockSpec(memory_space=pltpu.SMEM)
    pl.BlockSpec(memory_space=pl.ANY)
