"""Campaign-hardening logic in benchmarks/measure.py.

A regression in any of these rules costs real hardware time: a retried
compile hang re-kills a live Mosaic remote compile, which wedges the TPU
tunnel for hours (observed 2026-07-30 and 2026-07-31 — docs/STATE.md).
Everything here is pure-Python / CPU-backend; no label is measured on TPU.
"""

import importlib.util
import json
import os
import sys

import pytest

_BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")


@pytest.fixture()
def M():
    """A fresh measure module (CONFIGS edits must not leak across tests)."""
    spec = importlib.util.spec_from_file_location(
        "measure_under_test", os.path.join(_BENCH_DIR, "measure.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_parse_kspec(M):
    assert M._parse_kspec("4") == (4, None)
    assert M._parse_kspec("16") == (16, None)
    assert M._parse_kspec("4@16x16") == (4, (16, 16))
    assert M._parse_kspec("8@32x16") == (8, (32, 16))
    # streaming kernels take an optional 3rd x-window extent
    assert M._parse_kspec("4@8x16x256") == (4, (8, 16, 256))


def test_labels_unique_and_risky_derived(M):
    labels = [label for label, *_ in M.CONFIGS]
    assert len(labels) == len(set(labels))
    # the risky set is positional: everything at/after the Tier-D marker
    start = labels.index(M._TIER_D_START)
    assert M._RISKY == frozenset(labels[start:])
    # Tier D must be non-empty and must not swallow the safe tiers.
    # The risky tail has grown a sub-tier per perf round (D9..D15),
    # so the bound is 2/3 rather than the original half — the safe
    # jnp/raw/copy prefix must stay a substantial minority.
    assert 0 < len(M._RISKY) < len(labels) * 2 / 3
    assert start > 0


def test_risky_labels_are_new_large_compiles(M):
    # every risky label is a fused/padfree/stream variant (the classes
    # with hang history or no on-chip compile history); jnp/raw/copy/full
    # never hang.  rdma joined in round 12: the collective pallas_call
    # class (remote DMA + barrier/credit semaphores) has NO on-chip
    # compile history at all, so it belongs in Tier D by definition.
    # grp2 joined in round 22: each group compiles only the plain
    # sharded stepper (never hangs), but the multi-sub-mesh build +
    # cross-group device_put interface transport has no on-chip history.
    for label, name, grid, steps, dtype, compute in M.CONFIGS:
        if label in M._RISKY:
            assert compute.startswith(
                ("fused", "padfree", "stream", "shfused", "overlap",
                 "pipe", "rdma", "grp2")), label


def _run_single_label(M, out, label="heat2d_512_f32"):
    M.CONFIGS = [c for c in M.CONFIGS if c[0] == label]
    argv = sys.argv
    sys.argv = ["measure.py", "--out", out, "--in-process"]
    try:
        M.main()
    finally:
        sys.argv = argv


def test_recorded_timeout_skipped_at_current_rev(M, tmp_path):
    out = str(tmp_path / "r.json")
    rec = {"error": "subprocess timeout (2400s)", "timeout": True,
           "builder_rev": M.BUILDER_REV}
    (tmp_path / "r.json").write_text(json.dumps({"heat2d_512_f32": rec}))
    _run_single_label(M, out)
    assert json.loads((tmp_path / "r.json").read_text())[
        "heat2d_512_f32"] == rec  # untouched: skipped, not re-measured


def test_recorded_timeout_retried_after_builder_bump(M, tmp_path):
    out = str(tmp_path / "r.json")
    (tmp_path / "r.json").write_text(json.dumps({"heat2d_512_f32": {
        "error": "subprocess timeout (2400s)", "timeout": True,
        "builder_rev": M.BUILDER_REV - 1}}))
    _run_single_label(M, out)
    got = json.loads((tmp_path / "r.json").read_text())["heat2d_512_f32"]
    assert "mcells_per_s" in got  # re-measured under the newer builder


def test_suspect_timeout_retried(M, tmp_path):
    """A timeout whose post-kill probe failed is ambiguous (the wedge may
    have predated the label) — it must be retried, not permanently
    skipped; the start-of-run probe guarantees the retry happens against
    a healthy tunnel."""
    out = str(tmp_path / "r.json")
    (tmp_path / "r.json").write_text(json.dumps({"heat2d_512_f32": {
        "error": "subprocess timeout (2400s) ... SUSPECT", "timeout": True,
        "suspect": True, "builder_rev": M.BUILDER_REV}}))
    _run_single_label(M, out)
    got = json.loads((tmp_path / "r.json").read_text())["heat2d_512_f32"]
    assert "mcells_per_s" in got


def test_transient_error_still_retried(M, tmp_path):
    out = str(tmp_path / "r.json")
    (tmp_path / "r.json").write_text(json.dumps({"heat2d_512_f32": {
        "error": "RESOURCE_EXHAUSTED: ...", "builder_rev": M.BUILDER_REV}}))
    _run_single_label(M, out)
    got = json.loads((tmp_path / "r.json").read_text())["heat2d_512_f32"]
    assert "mcells_per_s" in got


def test_untileable_decline_skipped_at_current_rev(M, tmp_path):
    out = str(tmp_path / "r.json")
    rec = {"error": "ValueError: untileable fused k=4 for (512, 512, 512)",
           "builder_rev": M.BUILDER_REV}
    (tmp_path / "r.json").write_text(json.dumps({"heat2d_512_f32": rec}))
    _run_single_label(M, out)
    assert json.loads((tmp_path / "r.json").read_text())[
        "heat2d_512_f32"] == rec


def test_count_runnable_matches_skip_rule(M, tmp_path):
    """--count-runnable and main() must share one skip-rule definition
    (round-4 advisor: the recovery watcher used to re-derive the rule by
    regex-scraping measure.py and could loop forever on drift)."""
    labels = [label for label, *_ in M.CONFIGS]
    out = str(tmp_path / "r.json")
    rev = M.BUILDER_REV
    (tmp_path / "r.json").write_text(json.dumps({
        labels[0]: {"mcells_per_s": 1.0},                      # success
        labels[1]: {"error": "untileable fused k=4",
                    "builder_rev": rev},                       # decline
        labels[2]: {"error": "subprocess timeout (2400s)",
                    "timeout": True, "builder_rev": rev},      # hang
        labels[3]: {"error": "subprocess timeout (2400s)", "timeout": True,
                    "suspect": True, "builder_rev": rev},      # ambiguous
        labels[4]: {"error": "RESOURCE_EXHAUSTED"},            # transient
    }))
    # skipped: success, current-rev decline, current-rev timeout;
    # runnable: suspect timeout, transient error, every unrecorded label
    assert M.count_runnable(out) == len(labels) - 3
    assert not M._skip_cached(None)
    assert not M._skip_cached({"error": "untileable",
                               "builder_rev": rev - 1})


def test_count_runnable_cli_prints_count(M, tmp_path, capsys):
    out = str(tmp_path / "r.json")
    (tmp_path / "r.json").write_text(json.dumps(
        {label: {"mcells_per_s": 1.0} for label, *_ in M.CONFIGS}))
    argv = sys.argv
    sys.argv = ["measure.py", "--out", out, "--count-runnable"]
    try:
        M.main()
    finally:
        sys.argv = argv
    assert capsys.readouterr().out.strip() == "0"


def test_merge_record_preserves_other_labels(M, tmp_path):
    out = str(tmp_path / "r.json")
    (tmp_path / "r.json").write_text(json.dumps({"other": {"x": 1}}))
    M._merge_record(out, "new", {"y": 2})
    got = json.loads((tmp_path / "r.json").read_text())
    assert got == {"other": {"x": 1}, "new": {"y": 2}}


def test_campaign_survives_one_wedged_label(M, tmp_path, monkeypatch):
    """The round-13 acceptance pin: a campaign with ONE injected wedged
    label (FAULT_INJECT=label:name=...:hang) completes every other
    label, retries the wedged one (the wedge costs the in-flight
    ATTEMPT, not the label), records the restart in the results record
    AND the ledger row, and a re-run re-executes nothing."""
    import time as _time

    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    wedged = "heat2d_512_f32"
    other = "sor2d_1024_f32_jnp"
    M.CONFIGS = [c for c in M.CONFIGS if c[0] in (wedged, other)]
    assert len(M.CONFIGS) == 2
    # attempt 0 of the wedged label hangs (killed at the budget);
    # attempt 1 — FAULT_ATTEMPT=1 in the retried child — runs clean
    monkeypatch.setenv("FAULT_INJECT", f"label:name={wedged}:hang")
    monkeypatch.setenv("FAULT_HANG_S", "120")
    # Budget the WEDGED label only (12s kills the hang fast); the clean
    # label keeps the default budget — a global 12s budget sat ~1s above
    # sor2d's honest wall time on a loaded box and flaked the "other
    # label untouched" pin with a spurious restart.
    monkeypatch.setattr(M, "_RISKY", frozenset({wedged}))
    monkeypatch.setattr(M, "_RISKY_BUDGET_S", 12)
    ledger = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", ledger)
    # the campaign-start probe spawns a subprocess; irrelevant here
    M._tunnel_probe_ok = lambda *a, **kw: True

    out = str(tmp_path / "r.json")
    argv = sys.argv
    sys.argv = ["measure.py", "--out", out, "--restart-backoff", "0.1"]
    try:
        M.main()
    finally:
        sys.argv = argv

    results = json.loads((tmp_path / "r.json").read_text())
    assert "mcells_per_s" in results[other], results[other]
    assert "mcells_per_s" in results[wedged], results[wedged]
    assert results[wedged]["restart_attempts"] == 1
    assert "restart_attempts" not in results[other]

    # the ledger row carries the restart trail (attempt count)
    rows = [r for r in ledger_lib.read_rows(ledger)
            if r["label"] == wedged and r["status"] == "ok"]
    assert rows and rows[-1]["detail"]["restart_attempts"] == 1

    # campaign-level resume: a re-run skips every completed label
    # (identical records — nothing was re-measured)
    before = json.loads((tmp_path / "r.json").read_text())
    assert M.count_runnable(out) == 0
    t0 = _time.time()
    sys.argv = ["measure.py", "--out", out]
    try:
        M.main()
    finally:
        sys.argv = argv
    assert json.loads((tmp_path / "r.json").read_text()) == before
    assert _time.time() - t0 < 10, "cached re-run must spawn no children"


def test_explicit_tile_labels_construct(M):
    """The @BZxBY hedge labels must build a real kernel (interpret mode):
    a typo'd tile pair would otherwise surface only on the real chip."""
    from mpi_cuda_process_tpu import make_stencil
    from mpi_cuda_process_tpu.ops.pallas.fused import make_fused_step

    for label, name, grid, steps, dtype, compute in M.CONFIGS:
        if "@" not in compute:
            continue
        k, tiles = M._parse_kspec(
            compute[len("padfree" if compute.startswith("padfree")
                        else "fused"):])
        # tiles must divide a shard-sized proxy of the grid and pass the
        # builder's own validation on the REAL grid shape
        st = make_stencil(name, dtype=dtype) if dtype else make_stencil(name)
        step = make_fused_step(st, grid, k, tiles=tiles,
                               padfree=compute.startswith("padfree"))
        assert step is not None, f"{label}: hedge tiles rejected"
