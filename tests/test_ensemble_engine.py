"""Batched ensemble engine (round 15): one mesh, N simulations per step.

The contract under test, layer by layer:

* **Bit-exactness** — the batched step (``ensemble=N`` on every sharded
  stepper kind, the batched streaming builder, and the CLI composition
  ``--ensemble + --mesh``) equals N independent single-sim runs per
  member, for each kind x mesh family x dtype x overlap/pipeline/rdma
  where supported.
* **Structure** — the exchange-round count of the batched step is
  INDEPENDENT of N (vmap folds the member axis into each collective
  operand; ``jaxprcheck.assert_ensemble_exchange_invariance``), and the
  batched streaming kernel carries an explicit leading batch grid
  dimension.
* **Walls** — unsupported combinations raise explicitly (forced modes
  never silently fall back), and the OLD walls are gone: budget accepts
  ensemble configs (streaming included), cli accepts --ensemble+--mesh.
* **Money paths** — budget prices ensemble rows to the byte on both
  mesh families, cross-checked against obs/costmodel; the ledger keys
  ensemble rows apart (an ens=8 row can never baseline a single-sim
  row — perf_gate reports NO_BASELINE across ensemble sizes); the
  engine's submit/handle API streams per-member chunk telemetry.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.config import (
    LIFECYCLE_FIELDS,
    RunConfig,
    SIM_FIELDS,
    sim_signature,
)
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.parallel import stepper as stepper_lib
from mpi_cuda_process_tpu.parallel.mesh import ENSEMBLE_AXIS
from mpi_cuda_process_tpu.parallel.stepper import (
    ensemble_members_local,
    ensemble_partition_spec,
    make_sharded_fused_step,
    make_sharded_step,
)
from mpi_cuda_process_tpu.utils import jaxprcheck


def _assert_members_match(batched_out, single_steps, fields, mesh, calls,
                          ensemble, atol=0.0):
    """Run each member independently and compare against the batch."""
    for i in range(ensemble):
        solo = shard_fields(tuple(f[i] for f in fields), mesh, 3)
        ref = make_runner(single_steps, calls)(solo)
        for b, r in zip(batched_out, ref):
            if atol:
                np.testing.assert_allclose(
                    np.asarray(b[i], np.float32),
                    np.asarray(r, np.float32), rtol=0, atol=atol)
            else:
                np.testing.assert_array_equal(np.asarray(b[i]),
                                              np.asarray(r))


# ---------------------------------------------------------------- mesh


def test_ensemble_mesh_axis_layout():
    mesh = make_mesh((2, 2, 1), ensemble=2)
    assert dict(mesh.shape) == {ENSEMBLE_AXIS: 2, "sx": 2, "sy": 2,
                                "sz": 1}
    spec = ensemble_partition_spec(3, mesh)
    assert spec[0] == ENSEMBLE_AXIS
    # without the axis the leading entry is unsharded
    plain = make_mesh((2, 1, 1))
    assert ensemble_partition_spec(3, plain)[0] is None


def test_ensemble_members_local_validation():
    mesh = make_mesh((2, 1, 1), ensemble=2)
    assert ensemble_members_local(mesh, 4) == 2
    with pytest.raises(ValueError, match="not divisible"):
        ensemble_members_local(mesh, 3)
    with pytest.raises(ValueError, match="unbatched"):
        ensemble_members_local(mesh, 0)
    assert ensemble_members_local(make_mesh((2, 1, 1)), 0) == 0


def test_mesh_needs_enough_devices_for_ensemble_axis():
    with pytest.raises(ValueError, match="ensemble"):
        make_mesh((2, 2, 1), ensemble=4)  # 16 > 8 virtual devices


# ------------------------------------------------- batched sharded step


def test_batched_plain_sharded_step_matches_independent():
    st = make_stencil("heat3d")
    grid, N = (32, 16, 128), 3
    mesh = make_mesh((2, 1, 1))
    batched = make_sharded_step(st, mesh, grid, ensemble=N)
    single = make_sharded_step(st, mesh, grid)
    fields = init_state(st, grid, seed=4, ensemble=N)
    out = make_runner(batched, 2)(shard_fields(fields, mesh, 3,
                                               ensemble=True))
    _assert_members_match(out, single, fields, mesh, 2, N)


def test_batched_step_on_ensemble_mesh_axis_matches_independent():
    """The headline topology: ensemble x y x z — members sharded over
    the third mesh axis, spatial exchange within each member group."""
    st = make_stencil("heat3d")
    grid, N = (32, 16, 128), 4
    mesh_e = make_mesh((2, 2, 1), ensemble=2)
    batched = make_sharded_step(st, mesh_e, grid, ensemble=N)
    fields = init_state(st, grid, seed=1, ensemble=N)
    out = make_runner(batched, 2)(shard_fields(fields, mesh_e, 3,
                                               ensemble=True))
    mesh_s = make_mesh((2, 2, 1))
    single = make_sharded_step(st, mesh_s, grid)
    _assert_members_match(out, single, fields, mesh_s, 2, N)


@pytest.mark.parametrize("name,grid,mesh_shape,kind,dtype,atol", [
    ("heat3d", (32, 16, 128), (2, 1, 1), "padfree", None, 0),
    ("heat3d", (32, 32, 128), (2, 2, 1), "padfree", None, 0),
    ("wave3d", (32, 16, 128), (2, 1, 1), "padfree", None, 0),
    ("heat3d", (96, 32, 128), (2, 1, 1), "stream", None, 0),
    ("heat3d", (48, 64, 128), (2, 2, 1), "stream", None, 0),
    ("heat3d", (64, 32, 128), (2, 1, 1), "padfree", "bfloat16", 0),
])
def test_batched_fused_kinds_match_independent(name, grid, mesh_shape,
                                               kind, dtype, atol):
    params = {"dtype": jnp.dtype(dtype)} if dtype else {}
    st = make_stencil(name, **params)
    k = 8 if dtype == "bfloat16" else 4
    N = 2
    mesh = make_mesh(mesh_shape)
    batched = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                      kind=kind, ensemble=N)
    single = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                     kind=kind)
    assert batched is not None and single is not None
    assert batched._ensemble == N
    assert batched._padfree_kind == single._padfree_kind
    fields = init_state(st, grid, seed=7, ensemble=N)
    out = make_runner(batched, 2)(shard_fields(fields, mesh, 3,
                                               ensemble=True))
    _assert_members_match(out, single, fields, mesh, 2, N, atol=atol)


@pytest.mark.parametrize("overlap,pipeline", [
    (True, False), (True, True), (False, True)])
def test_batched_overlap_pipeline_match_independent(overlap, pipeline):
    st = make_stencil("heat3d")
    grid, N = (32, 16, 128), 2
    mesh = make_mesh((2, 1, 1))
    mk = lambda ens: make_sharded_fused_step(  # noqa: E731
        st, mesh, grid, 4, interpret=True, padfree=True, overlap=overlap,
        pipeline=pipeline, ensemble=ens)
    batched, single = mk(N), mk(0)
    if pipeline:
        assert batched._pipeline_active
    if overlap:
        assert batched._overlap_active
    fields = init_state(st, grid, seed=5, ensemble=N)
    out = make_runner(batched, 3)(shard_fields(fields, mesh, 3,
                                               ensemble=True))
    _assert_members_match(out, single, fields, mesh, 3, N)


def test_batched_rdma_stream_matches_independent():
    st = make_stencil("heat3d")
    grid, N = (96, 32, 128), 2
    mesh = make_mesh((2, 1, 1))
    batched = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                      kind="stream", exchange="rdma",
                                      ensemble=N)
    single = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                     kind="stream", exchange="rdma")
    assert batched._exchange == "rdma"
    fields = init_state(st, grid, seed=2, ensemble=N)
    out = make_runner(batched, 2)(shard_fields(fields, mesh, 3,
                                               ensemble=True))
    _assert_members_match(out, single, fields, mesh, 2, N)


# ------------------------------------------------------------ structure


@pytest.mark.parametrize("mesh_shape,grid,exchange", [
    ((2, 1, 1), (32, 16, 128), "ppermute"),
    ((2, 2, 1), (32, 32, 128), "ppermute"),
    ((2, 1, 1), (96, 32, 128), "rdma"),
])
def test_exchange_rounds_independent_of_ensemble(mesh_shape, grid,
                                                 exchange):
    """The headline structural pin: one exchange round per site at ANY
    N — and the count is invariant between N=2 and N=4 too."""
    rep = jaxprcheck.check_ensemble_structure(
        grid=grid, mesh_shape=mesh_shape, ensemble=2, exchange=exchange)
    rep4 = jaxprcheck.check_ensemble_structure(
        grid=grid, mesh_shape=mesh_shape, ensemble=4, exchange=exchange)
    assert rep["n_exchange_batched"] == rep4["n_exchange_batched"]


def test_batched_stream_kernel_has_leading_batch_grid_dim():
    """The vmapped streaming pallas_call must carry an EXPLICIT leading
    batch grid dimension of size N (the 'batch grid dimension' claim,
    checked against the traced grid_mapping, not inferred)."""
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        make_stream_fused_step,
    )

    st = make_stencil("heat3d")
    grid, N = (96, 32, 128), 3
    single = make_stream_fused_step(st, grid, 4, interpret=True)
    batched = make_stream_fused_step(st, grid, 4, interpret=True, batch=N)
    assert batched._ensemble == N
    fields = init_state(st, grid, seed=3, ensemble=N)
    closed = jax.make_jaxpr(batched)(fields)

    grids = []
    for jx in jaxprcheck.iter_jaxprs(closed.jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                gm = eqn.params.get("grid_mapping")
                grids.append(tuple(getattr(gm, "grid", ())))
    assert grids, "no pallas_call in the batched streaming step"
    single_grids = []
    closed_s = jax.make_jaxpr(single)(
        tuple(f[0] for f in fields))
    for jx in jaxprcheck.iter_jaxprs(closed_s.jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name == "pallas_call":
                gm = eqn.params.get("grid_mapping")
                single_grids.append(tuple(getattr(gm, "grid", ())))
    assert grids[0][0] == N and grids[0][1:] == single_grids[0]
    # and the batched step equals per-member runs
    out = batched(fields)
    for i in range(N):
        ref = single(tuple(f[i] for f in fields))
        np.testing.assert_array_equal(np.asarray(out[0][i]),
                                      np.asarray(ref[0]))


def test_ensemble_invariance_rejects_exchange_free_program():
    st = make_stencil("heat3d")
    fields = tuple(
        jax.ShapeDtypeStruct((16, 16, 128), st.dtype)
        for _ in range(st.num_fields))
    ident = jax.make_jaxpr(lambda fs: fs)(fields)
    with pytest.raises(AssertionError, match="no exchange"):
        jaxprcheck.assert_ensemble_exchange_invariance(ident, ident)


# ------------------------------------------------------- explicit walls


def test_unsupported_combos_raise_explicitly():
    from mpi_cuda_process_tpu.cli import build

    base = dict(stencil="heat3d", grid=(96, 32, 128), iters=8)
    # periodic stream stays walled (guard-frame kernel), batched or not
    with pytest.raises(ValueError, match="guard-frame"):
        build(RunConfig(**base, fuse=4, fuse_kind="stream", periodic=True,
                        ensemble=2))
    # ensemble-mesh without ensemble
    with pytest.raises(ValueError, match="needs --ensemble"):
        build(RunConfig(**base, ensemble_mesh=2))
    # non-divisible member count
    with pytest.raises(ValueError, match="divisible"):
        build(RunConfig(**base, ensemble=3, ensemble_mesh=2))
    # perturbation without an ensemble
    with pytest.raises(ValueError, match="perturb"):
        build(RunConfig(**base, ensemble_perturb=0.1))
    # an ensemble mesh axis on an unbatched stepper build
    mesh = make_mesh((2, 1, 1), ensemble=2)
    st = make_stencil("heat3d")
    with pytest.raises(ValueError, match="unbatched"):
        make_sharded_step(st, mesh, (32, 16, 128))


def test_batched_stream_builder_rejects_wrong_shape():
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        make_stream_fused_step,
    )

    st = make_stencil("heat3d")
    step = make_stream_fused_step(st, (96, 32, 128), 4, interpret=True,
                                  batch=2)
    bad = init_state(st, (96, 32, 128), ensemble=3)
    with pytest.raises(ValueError, match="batched streaming step"):
        step(bad)


# ------------------------------------------------------------------ cli


def test_cli_ensemble_composes_with_mesh():
    """The round-15 headline: --ensemble + --mesh builds (the old
    exclusion raise is gone) and matches independent runs."""
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="life", grid=(16, 16), iters=5)
    ens, _ = run(RunConfig(**base, seed=4, ensemble=3, mesh=(2, 1)))
    assert np.asarray(ens[0]).shape == (3, 16, 16)
    for i in range(3):
        solo, _ = run(RunConfig(**base, seed=4 + i))
        np.testing.assert_array_equal(np.asarray(ens[0])[i],
                                      np.asarray(solo[0]))


def test_cli_ensemble_mesh_third_axis():
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=4)
    ens, _ = run(RunConfig(**base, seed=1, ensemble=4, ensemble_mesh=2,
                           mesh=(2, 2, 1)))
    assert np.asarray(ens[0]).shape == (4, 32, 16, 128)
    for i in range(4):
        solo, _ = run(RunConfig(**base, seed=1 + i))
        np.testing.assert_array_equal(np.asarray(ens[0])[i],
                                      np.asarray(solo[0]))


def test_cli_pure_data_parallel_ensemble():
    """--ensemble-mesh with NO spatial mesh: the member axis alone is
    the device decomposition (zero exchange — each group independent)."""
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="life", grid=(16, 16), iters=5)
    ens, _ = run(RunConfig(**base, seed=4, ensemble=4, ensemble_mesh=2))
    for i in range(4):
        solo, _ = run(RunConfig(**base, seed=4 + i))
        np.testing.assert_array_equal(np.asarray(ens[0])[i],
                                      np.asarray(solo[0]))


def test_cli_stream_ensemble_wall_deleted():
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="heat3d", grid=(96, 32, 128), iters=8, seed=2)
    ens, _ = run(RunConfig(**base, ensemble=2, fuse=4,
                           fuse_kind="stream"))
    solo, _ = run(RunConfig(**base, fuse=4, fuse_kind="stream"))
    np.testing.assert_array_equal(np.asarray(ens[0])[0],
                                  np.asarray(solo[0]))


def test_cli_sharded_fused_ensemble_matches_single():
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="heat3d", grid=(32, 16, 128), iters=8, seed=3,
                fuse=4, fuse_kind="padfree", mesh=(2, 1, 1))
    ens, _ = run(RunConfig(**base, ensemble=2, overlap=True,
                           pipeline=True))
    solo, _ = run(RunConfig(**base, overlap=True, pipeline=True))
    np.testing.assert_array_equal(np.asarray(ens[0])[0],
                                  np.asarray(solo[0]))


def test_ensemble_perturb_deterministic_and_distinct():
    st = make_stencil("wave3d")
    a = init_state(st, (16, 16, 128), seed=9, ensemble=3, perturb=0.1)
    b = init_state(st, (16, 16, 128), seed=9, ensemble=3, perturb=0.1)
    for fa, fb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))
    plain = init_state(st, (16, 16, 128), seed=9, ensemble=3)
    # members differ from their unperturbed selves in the interior
    assert not np.array_equal(np.asarray(a[0]), np.asarray(plain[0]))
    # frame stays pinned exactly
    halo = st.halo
    np.testing.assert_array_equal(
        np.asarray(a[0])[:, :halo, :], np.asarray(plain[0])[:, :halo, :])


# ------------------------------------------------------ budget/costmodel


GiB = 2**30

# Config-5-derived ensemble rows (wave3d 2048^3 — one-eighth of config
# 5's cells per member — streaming k=4, 4 members over a 4-way ensemble
# mesh axis, 64 chips): pinned to the byte on BOTH mesh families, and
# cross-checked against obs/costmodel's independently-derived operand
# bytes below.  Re-pin deliberately on any budget-model change.
_ENSEMBLE_ROWS = {
    ("float32", (16, 1, 1)): 7_381_975_040,
    ("float32", (4, 4, 1)): 7_385_435_340,
    ("bfloat16", (16, 1, 1)): 3_690_987_520,
    ("bfloat16", (4, 4, 1)): 3_767_690_854,
}


@pytest.mark.parametrize("dtype,mesh", sorted(
    _ENSEMBLE_ROWS, key=str))
def test_budget_ensemble_rows_pinned_to_the_byte(dtype, mesh):
    from mpi_cuda_process_tpu.obs import costmodel
    from mpi_cuda_process_tpu.utils import budget

    st = make_stencil("wave3d", dtype=jnp.dtype(dtype))
    total, parts = budget.estimate_run_bytes(
        st, (2048,) * 3, mesh=mesh, fuse=4, fuse_kind="stream",
        ensemble=4, ensemble_mesh=4)
    assert total == _ENSEMBLE_ROWS[(dtype, mesh)]
    assert total < 16 * GiB  # fits a v5e chip
    cc = costmodel.budget_crosscheck(
        st, (2048,) * 3, mesh, 4, "stream", ensemble=4, ensemble_mesh=4)
    assert cc is not None and cc["match"], cc


def test_budget_stream_ensemble_wall_deleted():
    from mpi_cuda_process_tpu.utils import budget

    st = make_stencil("heat3d")
    # buildable batched streaming: priced, not walled
    total, parts = budget.estimate_run_bytes(
        st, (256,) * 3, fuse=4, fuse_kind="stream", ensemble=2)
    labels = [label for label, _ in parts]
    assert not any("UNBUILDABLE" in label for label in labels)
    assert any("members batched" in label for label in labels)
    # the state term scales with the members
    t1, _ = budget.estimate_run_bytes(st, (256,) * 3, fuse=4,
                                      fuse_kind="stream")
    assert total > 1.9 * t1
    # periodic stays walled
    _, pp = budget.estimate_run_bytes(
        st, (256,) * 3, fuse=4, fuse_kind="stream", periodic=True)
    assert any("UNBUILDABLE" in label for label, _ in pp)


def test_budget_ensemble_mesh_divides_members():
    from mpi_cuda_process_tpu.utils import budget

    st = make_stencil("heat3d")
    t_all, _ = budget.estimate_run_bytes(st, (256,) * 3, ensemble=8)
    t_split, _ = budget.estimate_run_bytes(st, (256,) * 3, ensemble=8,
                                           ensemble_mesh=4)
    assert t_all > 3.9 * t_split
    with pytest.raises(ValueError, match="divisible"):
        budget.estimate_run_bytes(st, (256,) * 3, ensemble=3,
                                  ensemble_mesh=2)


def test_costmodel_ensemble_rounds_invariant_bytes_scale():
    from mpi_cuda_process_tpu.obs import costmodel

    st = make_stencil("heat3d")
    one = costmodel.comm_stats(st, (64, 64, 128), (2, 2, 1), fuse=4,
                               fuse_kind="stream")
    four = costmodel.comm_stats(st, (64, 64, 128), (2, 2, 1), fuse=4,
                                fuse_kind="stream", batch=4)
    assert four["ppermute_rounds_per_pass"] == \
        one["ppermute_rounds_per_pass"]
    assert four["ici_bytes_per_pass"] == 4 * one["ici_bytes_per_pass"]
    assert four["slab_operand_bytes"] == 4 * one["slab_operand_bytes"]
    sc = costmodel.static_cost(st, (64, 64, 128), (2, 2, 1), fuse=4,
                               fuse_kind="stream", ensemble=8,
                               ensemble_mesh=2)
    assert sc["ensemble"] == 8 and sc["members_per_device"] == 4
    assert sc["comm"]["members_per_device"] == 4


# --------------------------------------------------------------- ledger


def test_ledger_keys_ensemble_rows_apart(tmp_path):
    from mpi_cuda_process_tpu.obs import ledger

    run_single = {"stencil": "heat3d", "grid": [64, 64, 128],
                  "fuse": 4, "fuse_kind": "stream"}
    run_ens = dict(run_single, ensemble=8)
    # flags: ensemble only when set — single-sim flags byte-identical to
    # the historical set
    assert "ensemble" not in ledger._flags(run_single)
    assert ledger._flags(run_ens)["ensemble"] == 8
    row_s = ledger.make_row("lbl", 10.0, source="t", backend="tpu",
                            flags=ledger._flags(run_single))
    row_e = ledger.make_row("lbl", 80.0, source="t", backend="tpu",
                            flags=ledger._flags(run_ens))
    assert ledger.baseline_key(row_s) != ledger.baseline_key(row_e)
    assert ledger.baseline_key(row_e).endswith("|ens8")
    # an ens=8 value can never become the single-sim baseline
    best = ledger.best_known([row_s, row_e])
    assert best[ledger.baseline_key(row_s)]["value"] == 10.0
    # cli labels name the size
    assert ledger._cli_label(run_ens).endswith("_ens8")


def test_perf_gate_no_baseline_across_ensemble_sizes(tmp_path):
    """An ens=8 manifest gated against a single-sim-only ledger must be
    NO_BASELINE, never REGRESSED/IMPROVED."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import perf_gate

    from mpi_cuda_process_tpu.obs import ledger, trace

    ledger_path = str(tmp_path / "ledger.jsonl")
    run_single = {"stencil": "heat3d", "grid": [64, 64, 128], "fuse": 4}
    ledger.append_rows([ledger.make_row(
        ledger._cli_label(run_single), 100.0, source="hist",
        backend="cpu", flags=ledger._flags(run_single),
        measured_at=1.0)], ledger_path)

    log = str(tmp_path / "run.jsonl")
    tw = trace.TraceWriter(log)
    tw.write_manifest(trace.build_manifest(
        "cli", dict(run_single, ensemble=8)))
    tw.event("summary", steps=8, mcells_per_s=12.5)
    tw.close()
    verdicts, _ = perf_gate.gate(log, ledger_path, 0.10)
    assert len(verdicts) == 1
    assert verdicts[0]["verdict"] == "NO_BASELINE"


# ----------------------------------------------------- metrics / status


def test_metrics_report_ensemble_and_per_member_throughput():
    from mpi_cuda_process_tpu.obs.metrics import RunMetrics

    rm = RunMetrics()
    rm.ingest({"kind": "manifest", "schema": 2, "tool": "cli",
               "run": {"stencil": "heat3d", "grid": [64, 64, 128],
                       "ensemble": 8},
               "provenance": {"backend": "cpu"}})
    rm.ingest({"kind": "chunk", "chunk": 0, "steps": 10, "wall_s": 1.0,
               "ms_per_step": 100.0, "members": 8})
    rm.ingest({"kind": "chunk", "chunk": 1, "steps": 10, "wall_s": 1.0,
               "ms_per_step": 100.0, "members": 8})
    snap = rm.registry.snapshot()
    assert snap["obs_ensemble_size"]["value"] == 8
    agg = snap["obs_gcells_per_s"]["value"]
    assert snap["obs_member_gcells_per_s"]["value"] == \
        pytest.approx(agg / 8)
    tp = rm.status()["throughput"]
    assert tp["ensemble"] == 8
    assert tp["gcells_per_s_per_member"] == \
        pytest.approx(tp["gcells_per_s"] / 8, abs=1e-4)


def test_chunk_records_carry_member_count(tmp_path):
    from mpi_cuda_process_tpu.cli import run

    log = str(tmp_path / "t.jsonl")
    run(RunConfig(stencil="life", grid=(16, 16), iters=4, ensemble=2,
                  log_every=2, telemetry=log))
    chunks = [json.loads(line) for line in open(log)
              if '"chunk"' in line]
    chunks = [c for c in chunks if c.get("kind") == "chunk"]
    assert chunks and all(c.get("members") == 2 for c in chunks)


# --------------------------------------------------------------- engine


def test_config_partition_is_total_and_disjoint():
    import dataclasses as dc

    names = {f.name for f in dc.fields(RunConfig)}
    assert SIM_FIELDS | LIFECYCLE_FIELDS == names
    assert not (SIM_FIELDS & LIFECYCLE_FIELDS)
    # lifecycle knobs never move the signature; simulation knobs do
    base = RunConfig(stencil="heat2d", grid=(32, 128), iters=4)
    assert sim_signature(base) == sim_signature(
        dc.replace(base, telemetry="/tmp/x.jsonl", log_every=2))
    assert sim_signature(base) != sim_signature(
        dc.replace(base, ensemble=4))


def test_engine_submit_handle_streams_member_telemetry(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat3d", grid=(16, 16, 64),
                             iters=8, ensemble=2, mesh=(2, 1, 1),
                             log_every=2))
    fields, mcells = h.result(timeout=300)
    assert np.asarray(fields[0]).shape == (2, 16, 16, 64)
    status = h.status()
    assert status["verdict"] == "DONE"
    assert status["request"]["phase"] == "done"
    assert status["throughput"]["ensemble"] == 2
    assert "gcells_per_s_per_member" in status["throughput"]
    # the event stream is the obs vocabulary, seq-cursored
    evs = h.events(after=0)
    kinds = [e["kind"] for e in evs]
    assert kinds[0] == "manifest" and "summary" in kinds
    later = h.events(after=evs[0]["_seq"])
    assert later[0]["_seq"] == evs[1]["_seq"]
    # same simulation, different lifecycle -> same signature
    h2 = eng.submit(RunConfig(stencil="heat3d", grid=(16, 16, 64),
                              iters=8, ensemble=2, mesh=(2, 1, 1)))
    h2.result(timeout=300)
    assert h2.sim_signature == h.sim_signature
    assert eng.status()["pending"] == 0


def test_engine_rejects_supervised_requests(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    with pytest.raises(ValueError, match="supervise"):
        eng.submit(RunConfig(stencil="life", grid=(16, 16), iters=2,
                             supervise=True))


def test_engine_delivers_run_errors(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat3d", grid=(96, 32, 128),
                             iters=8, fuse=4, fuse_kind="stream",
                             periodic=True))
    with pytest.raises(ValueError, match="guard-frame"):
        h.result(timeout=300)
    assert h.status()["request"]["phase"] == "failed"


# -------------------------------------------------------------- resume


def test_batched_sharded_checkpoint_resume_bitmatch(tmp_path):
    from mpi_cuda_process_tpu.cli import run

    base = dict(stencil="heat3d", grid=(32, 16, 128), seed=6, ensemble=2,
                mesh=(2, 1, 1), checkpoint_dir=str(tmp_path / "ck"))
    full, _ = run(RunConfig(**base, iters=6, checkpoint_every=3))
    resumed, _ = run(RunConfig(**base, iters=6, resume=True,
                               checkpoint_every=3))
    for f, r in zip(full, resumed):
        np.testing.assert_array_equal(np.asarray(f), np.asarray(r))
