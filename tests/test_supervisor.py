"""The run supervisor (resilience/): restart, backoff, give-up, bit-match.

Two layers, matching the supervisor's design:

* **unit** — the restart loop (``supervise``/``watch_child``) against
  fake children and injected clocks/sleeps: backoff sequencing,
  kill-on-verdict, kill-on-stall, give-up-after-max-restarts, resume
  flag threading.  No subprocesses, no sleeps — each decision is a pure
  function of the fakes.
* **end-to-end** — a real supervised CLI run with an injected mid-run
  wedge (``FAULT_INJECT=exchange:step=40:hang``): the wedge is
  detected, the child killed, the run resumed from the surviving
  checkpoint, and the FINAL FIELDS BIT-MATCH an uninterrupted run of
  the same config/seed — the acceptance criterion, pinned here in the
  default tier.

Plus the satellites that ride the same machinery: the fault-spec
parser, ``Heartbeat.stop()``'s SUPERVISOR_KILL contract, the
``to_argv`` round-trip (a RunConfig field that forgets its CLI flag
would silently vanish from supervised children), and the LogTail
partial-line discipline (a child SIGKILLed mid-write must not feed the
watcher garbage).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from mpi_cuda_process_tpu.config import RunConfig, to_argv
from mpi_cuda_process_tpu.obs import trace as trace_lib
from mpi_cuda_process_tpu.resilience import faults
from mpi_cuda_process_tpu.resilience import supervisor as sup


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


# ------------------------------------------------------------- faults

def test_fault_spec_parsing_rejects_malformed():
    for bad in ("exchange", "nosite:sigkill", "exchange:noaction",
                "exchange:bogus=1:sigkill", "exchange:wedge",
                "heartbeat:sigkill:wedge:extra=1"):
        with pytest.raises(ValueError):
            faults.parse_specs(bad)
    assert faults.parse_specs("") == []


def test_fault_attempt_gating(monkeypatch):
    monkeypatch.setenv("FAULT_INJECT", "exchange:step=5:raise")
    monkeypatch.setenv("FAULT_ATTEMPT", "1")
    faults.maybe_fire("exchange", step=50)  # attempt 1: spec inactive
    monkeypatch.setenv("FAULT_ATTEMPT", "0")
    faults.maybe_fire("exchange", step=4)  # below the step gate
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fire("exchange", step=5)
    faults.maybe_fire("exchange", step=500)  # one-shot: already fired


def test_fault_always_and_phase_and_name(monkeypatch):
    monkeypatch.setenv(
        "FAULT_INJECT",
        "checkpoint:during_write:always:raise,label:name=tgt:raise")
    monkeypatch.setenv("FAULT_ATTEMPT", "7")  # 'always' ignores attempts
    faults.maybe_fire("checkpoint", step=10, phase="before_write")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fire("checkpoint", step=10, phase="during_write")
    faults.maybe_fire("label", name="other")
    monkeypatch.setenv("FAULT_ATTEMPT", "0")
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fire("label", name="tgt")


def test_fault_injected_heartbeat_verdict(monkeypatch):
    assert faults.injected_heartbeat_verdict() is None
    monkeypatch.setenv("FAULT_INJECT", "heartbeat:wedge")
    v = faults.injected_heartbeat_verdict()
    assert v["verdict"] == "WEDGED"
    monkeypatch.setenv("FAULT_ATTEMPT", "1")  # gated off the relaunch
    assert faults.injected_heartbeat_verdict() is None


# ----------------------------------------------------------- heartbeat

class _Trace:
    def __init__(self, raise_on_event=False):
        self.events = []
        self.raise_on_event = raise_on_event

    def event(self, kind, **payload):
        if self.raise_on_event:
            raise OSError("writer closed")
        self.events.append({"kind": kind, **payload})


def test_heartbeat_stop_closes_open_episode_with_supervisor_kill():
    from mpi_cuda_process_tpu.obs.heartbeat import Heartbeat

    tr = _Trace()
    hb = Heartbeat(lambda: 0.0, trace=tr, stall_after_s=9999)
    hb._stalled_episode = True  # mid-episode, as on the kill path
    hb.stop()
    assert hb.last_verdict["verdict"] == "SUPERVISOR_KILL"
    assert [e["verdict"] for e in tr.events
            if e["kind"] == "heartbeat"] == ["SUPERVISOR_KILL"]
    # idempotent: a second stop must not re-emit
    hb.stop()
    assert len(tr.events) == 1


def test_heartbeat_stop_never_raises():
    from mpi_cuda_process_tpu.obs.heartbeat import Heartbeat

    hb = Heartbeat(lambda: 0.0, trace=_Trace(raise_on_event=True),
                   stall_after_s=9999)
    hb._stalled_episode = True
    hb.stop()  # the raising trace must be swallowed, not propagated
    assert not hb._stalled_episode


def test_heartbeat_uses_injected_wedge_verdict(monkeypatch):
    from mpi_cuda_process_tpu.obs.heartbeat import Heartbeat

    monkeypatch.setenv("FAULT_INJECT", "heartbeat:wedge")
    tr = _Trace()
    calls = []
    hb = Heartbeat(lambda: 0.0, trace=tr, stall_after_s=0.01, poll_s=0.01,
                   probe=lambda: calls.append(1) or {"verdict": "X"})
    hb.start()
    try:
        import time

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and \
                hb.last_verdict["verdict"] != "WEDGED":
            time.sleep(0.01)
        seen = hb.last_verdict["verdict"]
    finally:
        hb.stop()  # closes the open episode with SUPERVISOR_KILL
    assert seen == "WEDGED"
    verdicts = [e["verdict"] for e in tr.events if e["kind"] == "heartbeat"]
    assert "WEDGED" in verdicts and verdicts[-1] == "SUPERVISOR_KILL"
    assert not calls, "the injected verdict must preempt the real probe"


# ------------------------------------------------------------- to_argv

def test_to_argv_roundtrips_through_the_real_parser():
    from mpi_cuda_process_tpu.cli import config_from_args

    cfgs = [
        RunConfig(),
        RunConfig(stencil="life", grid=(64, 64), iters=100, seed=7,
                  checkpoint_every=10, checkpoint_dir="/tmp/ck",
                  telemetry="/tmp/t.jsonl", resume=True),
        RunConfig(stencil="heat3d", grid=(32, 32, 128), iters=8,
                  mesh=(2, 1, 1), fuse=4, fuse_kind="stream",
                  exchange="rdma", overlap=True, pipeline=True,
                  dtype="bfloat16", mem_check="warn", periodic=True,
                  params={"alpha": 0.25, "n": 3}),
    ]
    for cfg in cfgs:
        assert config_from_args(to_argv(cfg)) == cfg, cfg
    # launcher-only fields never reach the child argv (a child that
    # re-supervised would fork a supervision tree)
    sup_cfg = RunConfig(supervise=True, max_restarts=9,
                        restart_backoff=0.1, supervise_stall_s=1.0)
    argv = to_argv(sup_cfg)
    assert "--supervise" not in argv and "--max-restarts" not in argv
    assert config_from_args(argv) == RunConfig()


def test_to_argv_covers_every_runconfig_field():
    """A new RunConfig field must either be a launcher-only field or map
    to a real CLI flag — otherwise supervised children silently drop it."""
    from mpi_cuda_process_tpu.cli import build_parser

    known_flags = {a.dest for a in build_parser()._actions}
    for f in dataclasses.fields(RunConfig):
        if f.name in ("params",):  # repeated --param k=v
            continue
        assert f.name in known_flags, \
            f"RunConfig.{f.name} has no CLI flag (to_argv would drop it)"


# ------------------------------------------------------------- LogTail

def test_logtail_consumes_only_complete_lines(tmp_path):
    p = tmp_path / "log.jsonl"
    tail = trace_lib.LogTail(str(p))
    assert tail.poll() == []  # missing file: no records, no raise
    with open(p, "w") as fh:
        fh.write(json.dumps({"kind": "a"}) + "\n")
        fh.write('{"kind": "b", "trunca')  # killed mid-write
    assert [e["kind"] for e in tail.poll()] == ["a"]
    assert tail.poll() == []  # the partial line stays unconsumed
    with open(p, "a") as fh:
        fh.write('ted": 1}\n' + "not json\n"
                 + json.dumps({"kind": "c"}) + "\n")
    got = tail.poll()
    assert [e["kind"] for e in got] == ["b", "c"]
    assert tail.malformed == 1


# ------------------------------------------------- supervise (unit)

class _FakeHandle:
    """Scripted child: a list of poll() results; records kills."""

    def __init__(self, polls):
        self._polls = list(polls)
        self.killed = False

    def poll(self):
        return self._polls.pop(0) if self._polls else 0

    def kill(self):
        self.killed = True

    def wait(self, timeout_s=30.0):
        return None


class _FakeTail:
    def __init__(self, batches=()):
        self._batches = list(batches)

    def poll(self):
        return self._batches.pop(0) if self._batches else []


class _Session:
    path = "fake.supervisor.jsonl"

    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append({"kind": kind, **payload})


def _npy_checkpoint(tmp_path, step):
    ck = tmp_path / "ck"
    ck.mkdir(exist_ok=True)
    (ck / "meta.json").write_text(json.dumps(
        {"step": step, "num_fields": 0, "config": {}}))
    return str(ck)


def test_supervise_backoff_sequencing_and_resume(tmp_path):
    """Two failures then success: backoffs must follow base*2^n, every
    relaunch must resume from the recorded checkpoint, and the launch
    events must carry resumed_from_step."""
    ck = _npy_checkpoint(tmp_path, 30)
    session = _Session()
    sleeps = []
    launches = []

    def launcher(attempt, resume):
        launches.append((attempt, resume))
        rc = 1 if attempt < 2 else 0
        return _FakeHandle([rc]), [_FakeTail()]

    res = sup.supervise(
        launcher, ck, max_restarts=3, backoff_base_s=0.5,
        backoff_max_s=100.0, stall_timeout_s=60.0, poll_s=0.0,
        session=session, sleep=sleeps.append, clock=lambda: 0.0)
    assert res.ok and res.attempts == 3 and not res.gave_up
    assert sleeps == [0.5, 1.0]  # exponential sequencing
    assert launches == [(0, False), (1, True), (2, True)]
    assert res.resumed_from_step == 30
    resumed = [e.get("resumed_from_step") for e in session.events
               if e["kind"] == "launch" and e.get("resume")]
    assert resumed == [30, 30]
    kinds = [e["kind"] for e in session.events]
    assert kinds == ["launch", "restart", "launch", "restart", "launch",
                     "summary"]
    assert session.events[-1]["ok"] is True


def test_supervise_gives_up_after_max_restarts(tmp_path):
    ck = _npy_checkpoint(tmp_path, 10)
    session = _Session()
    sleeps = []
    res = sup.supervise(
        lambda attempt, resume: (_FakeHandle([3]), [_FakeTail()]),
        ck, max_restarts=2, backoff_base_s=0.25, stall_timeout_s=60.0,
        poll_s=0.0, session=session, sleep=sleeps.append,
        clock=lambda: 0.0)
    assert not res.ok and res.gave_up and res.attempts == 3
    assert res.final_rc == 3
    assert sleeps == [0.25, 0.5]  # backoff between failures, none after
    assert [e["kind"] for e in session.events].count("give_up") == 1
    assert session.events[-1]["kind"] == "summary"
    assert session.events[-1]["ok"] is False


def test_supervise_kills_on_wedged_verdict(tmp_path):
    ck = _npy_checkpoint(tmp_path, 20)
    session = _Session()
    handles = []

    def launcher(attempt, resume):
        if attempt == 0:
            h = _FakeHandle([None, None])  # alive while the verdict lands
            tails = [_FakeTail([[], [{"kind": "heartbeat",
                                      "verdict": "WEDGED",
                                      "detail": "injected"}]])]
        else:
            h = _FakeHandle([0])
            tails = [_FakeTail()]
        handles.append(h)
        return h, tails

    res = sup.supervise(launcher, ck, max_restarts=1, backoff_base_s=0.0,
                        stall_timeout_s=60.0, poll_s=0.0, session=session,
                        sleep=lambda s: None, clock=lambda: 0.0)
    assert res.ok and res.attempts == 2
    assert handles[0].killed and not handles[1].killed
    restart = [e for e in session.events if e["kind"] == "restart"][0]
    assert "WEDGED" in restart["reason"]


def test_supervise_kills_on_wall_clock_stall(tmp_path):
    """No events at all (the compile-hang case): the wall-clock watchdog
    must kill even though the child never wrote a verdict."""
    ck = _npy_checkpoint(tmp_path, 20)
    t = [0.0]

    def clock():
        t[0] += 2.0
        return t[0]

    handles = []

    def launcher(attempt, resume):
        h = _FakeHandle([None] * 50 if attempt == 0 else [0])
        handles.append(h)
        return h, [_FakeTail()]

    res = sup.supervise(launcher, ck, max_restarts=1, backoff_base_s=0.0,
                        stall_timeout_s=5.0, poll_s=0.0,
                        sleep=lambda s: None, clock=clock)
    assert res.ok and res.attempts == 2
    assert handles[0].killed
    assert res.restarts[0]["reason"] == "wall-clock stall"


def test_watch_child_reports_verdict_over_exit_on_final_drain():
    """A child that dies right after writing its WEDGED verdict: the
    richer reason (the verdict) must win over the bare exit code."""
    h = _FakeHandle([1])
    tail = _FakeTail([[{"kind": "heartbeat", "verdict": "WEDGED",
                        "detail": "d"}]])
    # first poll drains nothing (the batch list starts at the exit
    # check), so seed the tail to deliver on the post-exit drain
    outcome, value, _ = sup.watch_child(
        h, [tail], stall_timeout_s=60.0, poll_s=0.0,
        clock=lambda: 0.0, sleep=lambda s: None)
    assert (outcome, value) == ("verdict", "WEDGED")


def test_retry_subprocess_retries_past_a_first_attempt_hang():
    """The campaign-label contract: attempt 0 hangs (killed at the
    budget), attempt 1 — gated by FAULT_ATTEMPT — completes."""
    import sys as _sys

    code = ("import os, time, sys; "
            "time.sleep(60) if os.environ.get('FAULT_ATTEMPT') == '0' "
            "else sys.exit(0)")
    res = sup.retry_subprocess(
        [_sys.executable, "-c", code], timeout_s=2.0, max_restarts=1,
        backoff_base_s=0.05, sleep=lambda s: None)
    assert res["rc"] == 0 and not res["timed_out"]
    assert res["attempts"] == 2
    assert res["history"][0]["outcome"] == "timeout"


def test_retry_subprocess_stops_when_unhealthy():
    import sys as _sys

    res = sup.retry_subprocess(
        [_sys.executable, "-c", "import time; time.sleep(60)"],
        timeout_s=1.0, max_restarts=3, backoff_base_s=0.05,
        healthy=lambda: False, sleep=lambda s: None)
    assert res["timed_out"] and not res["healthy_after"]
    assert res["attempts"] == 1  # environmental: stop burning attempts


# ------------------------------------------------- supervise (e2e)

def _read_events(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def test_supervisor_restarts_injected_wedge_and_bitmatches(
        tmp_path, monkeypatch):
    """THE acceptance pin: an injected mid-run wedge (CPU, FAULT_INJECT)
    is detected, the child killed and relaunched with --resume, the run
    completes, restart + resumed_from_step land in the supervisor's obs
    log, and the final fields bit-match an uninterrupted run of the
    same config/seed."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.utils import checkpointing

    base = dict(stencil="life", grid=(64, 64), iters=100, seed=7)
    # the uninterrupted reference FIRST — before the fault env exists in
    # this process (the in-process run hits the same fault points)
    full, _ = run(RunConfig(**base))

    monkeypatch.setenv("FAULT_INJECT", "exchange:step=40:hang")
    monkeypatch.setenv("FAULT_HANG_S", "120")
    ck = str(tmp_path / "ck")
    tel = str(tmp_path / "run.jsonl")
    rc = sup.run_supervised(RunConfig(
        **base, checkpoint_every=10, checkpoint_dir=ck, telemetry=tel,
        supervise=True, max_restarts=2, restart_backoff=0.2,
        supervise_stall_s=6.0))
    assert rc == 0

    events = _read_events(str(tmp_path / "run.supervisor.jsonl"))
    kinds = [e.get("kind") for e in events]
    assert "restart" in kinds and "give_up" not in kinds
    resumed = [e["resumed_from_step"] for e in events
               if e.get("kind") == "launch" and e.get("resume")]
    assert resumed and all(s == 30 for s in resumed)  # hang at 40 -> 30
    summary = [e for e in events if e.get("kind") == "summary"][-1]
    assert summary["ok"] is True and summary["restarts"] >= 1

    # the resumed child also names its resume point in ITS manifest log
    child1 = _read_events(str(tmp_path / "run.attempt1.jsonl"))
    assert any(e.get("kind") == "resume"
               and e.get("resumed_from_step") == 30 for e in child1)

    # bit-exact final state: the supervised run's final checkpoint vs
    # the uninterrupted in-process run
    fields, step, _ = checkpointing.load_any(ck)
    assert step == 100
    for a, b in zip(fields, full):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_supervisor_restarts_on_child_death(tmp_path, monkeypatch):
    """The child-death branch with real processes: a SIGKILLed child
    (exit path, no verdict, no stall wait) is relaunched and resumes."""
    monkeypatch.setenv("FAULT_INJECT", "exchange:step=40:sigkill")
    ck = str(tmp_path / "ck")
    rc = sup.run_supervised(RunConfig(
        stencil="life", grid=(64, 64), iters=100, seed=7,
        checkpoint_every=10, checkpoint_dir=ck,
        telemetry=str(tmp_path / "run.jsonl"), supervise=True,
        max_restarts=2, restart_backoff=0.2, supervise_stall_s=60.0))
    assert rc == 0
    events = _read_events(str(tmp_path / "run.supervisor.jsonl"))
    restart = [e for e in events if e.get("kind") == "restart"][0]
    assert "exited" in restart["reason"]
    from mpi_cuda_process_tpu.utils import checkpointing

    assert checkpointing.latest_step(ck) == 100


@pytest.mark.slow
def test_supervisor_gives_up_against_a_permanent_wedge(
        tmp_path, monkeypatch):
    """always-hang: every attempt wedges, the supervisor must give up
    loudly (exit 1, give_up event) after max_restarts, never spin."""
    monkeypatch.setenv("FAULT_INJECT", "exchange:step=20:always:hang")
    monkeypatch.setenv("FAULT_HANG_S", "120")
    rc = sup.run_supervised(RunConfig(
        stencil="life", grid=(64, 64), iters=100, seed=7,
        checkpoint_every=10, checkpoint_dir=str(tmp_path / "ck"),
        telemetry=str(tmp_path / "run.jsonl"), supervise=True,
        max_restarts=1, restart_backoff=0.1, supervise_stall_s=5.0))
    assert rc == 1
    events = _read_events(str(tmp_path / "run.supervisor.jsonl"))
    assert any(e.get("kind") == "give_up" for e in events)
