"""Tests for the live run console (obs/metrics.py + obs/serve.py).

Pins the layer's contracts:

* **registry math** — bounded-reservoir histogram quantiles are exact
  on known data; count/sum/min/max stay exact past the bound;
  Prometheus rendering is well-formed.
* **snapshot consistency** — concurrent ingest never lets a scrape see
  half of a multi-metric update (one event's metrics land atomically).
* **HTTP surface** — /metrics, /status.json, and /events?after=SEQ
  answer over stdlib urllib against a real log; /events ordering is
  the log's, the long-poll timeout is bounded, and a new record wakes
  a parked long-poll.
* **supervised status** — /status.json on a supervised run with an
  injected FAULT_INJECT wedge shows the WEDGED verdict, the restart,
  and ``resumed_from_step`` — scraped MID-RUN, remotely, without
  reading any log file (the acceptance criterion).
* **clean shutdown** — close() leaks no ``obs-serve*`` thread and the
  port stops answering.
* **obs_top** — renders a live URL, a telemetry path, and the
  committed campaign ledger without error.
"""

import importlib.util
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu.config import RunConfig, to_argv  # noqa: E402
from mpi_cuda_process_tpu.obs import metrics, serve, trace  # noqa: E402


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _get_json(url, timeout=10):
    return json.loads(_get(url, timeout=timeout))


def _event(kind, **payload):
    return {"schema": trace.SCHEMA_VERSION, "kind": kind,
            "t": time.time(), **payload}


def _wait_for(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


# ------------------------------------------------------------- registry

def test_histogram_quantiles_and_bounded_reservoir():
    h = metrics.Histogram("ms", bound=1000)
    for v in range(1, 101):
        h.observe(float(v))
    q = h.quantiles()
    assert q[0.5] == pytest.approx(51.0, abs=1.0)
    assert q[0.9] == pytest.approx(90.0, abs=1.0)
    assert q[0.99] == pytest.approx(99.0, abs=1.0)
    assert h.count == 100 and h.sum == pytest.approx(5050.0)
    assert h.min == 1.0 and h.max == 100.0

    # past the bound: the reservoir slides, the exact stats do not
    small = metrics.Histogram("ms2", bound=10)
    for v in range(1, 101):
        small.observe(float(v))
    assert small.count == 100 and small.sum == pytest.approx(5050.0)
    assert small.min == 1.0 and small.max == 100.0
    assert len(small.reservoir) == 10
    # quantiles reflect the newest window (91..100), not the lifetime
    assert small.quantiles()[0.5] >= 91.0


def test_registry_prometheus_rendering_and_type_conflicts():
    reg = metrics.MetricsRegistry()
    reg.counter("steps_total", "steps done").inc(5)
    reg.gauge("rate").set(2.5)
    g = reg.gauge("peak")
    g.set_max(10)
    g.set_max(3)  # lower: peak keeps 10
    reg.info("run_info").set(tool="cli", note='quo"te\nnl', skipped=None)
    reg.histogram("ms", bound=8).observe(1.5)
    text = reg.to_prometheus()
    assert "# TYPE steps_total counter\nsteps_total 5" in text
    assert "rate 2.5" in text
    assert "peak 10" in text
    assert 'note="quo\\"te\\nnl"' in text and "skipped" not in text
    assert 'ms{quantile="0.5"} 1.5' in text
    assert "ms_count 1" in text
    # a name cannot change metric class mid-run
    with pytest.raises(ValueError):
        reg.counter("rate")
    snap = reg.snapshot()
    assert snap["steps_total"]["value"] == 5
    assert snap["ms"]["count"] == 1


def test_snapshot_consistent_under_concurrent_ingest():
    """Each chunk event bumps chunks_total AND steps_total (steps=5)
    under one lock hold — a concurrent snapshot must never observe the
    pair out of step."""
    rm = metrics.RunMetrics()
    rm.ingest(trace.build_manifest("cli", {"grid": [16, 16]}))
    n_threads, per_thread, steps = 4, 150, 5
    stop = threading.Event()
    bad = []

    def reader():
        while not stop.is_set():
            snap = rm.registry.snapshot()
            chunks = snap.get("obs_chunks_total", {}).get("value", 0)
            total = snap.get("obs_steps_total", {}).get("value", 0)
            if total != chunks * steps:
                bad.append((chunks, total))

    def writer():
        for i in range(per_thread):
            rm.ingest(_event("chunk", chunk=i + 1, steps=steps,
                             wall_s=0.01, ms_per_step=2.0,
                             recompiled=False))

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer) for _ in range(n_threads)]
    for t in readers + writers:
        t.start()
    for t in writers:
        t.join()
    stop.set()
    for t in readers:
        t.join()
    assert not bad, f"inconsistent snapshots: {bad[:3]}"
    snap = rm.registry.snapshot()
    assert snap["obs_chunks_total"]["value"] == n_threads * per_thread
    assert snap["obs_steps_total"]["value"] == n_threads * per_thread * steps


def test_run_metrics_full_vocabulary_status():
    rm = metrics.RunMetrics()
    rm.ingest(trace.build_manifest(
        "cli", {"stencil": "heat3d", "grid": [64, 64, 64], "iters": 40}))
    rm.ingest(_event("costmodel", roofline={
        "predicted_ms_per_step_hbm": 1.0,
        "predicted_ms_per_step_exchange": 0.25}))
    rm.ingest(_event("exchange", mode="rdma", backend="pallas-rdma"))
    rm.ingest(_event("chunk", chunk=0, steps=10, wall_s=1.0,
                     ms_per_step=100.0, recompiled=False))
    rm.ingest(_event("chunk", chunk=1, steps=10, wall_s=0.02,
                     ms_per_step=2.0, recompiled=False,
                     memory={"peak_bytes_in_use": 1234}))
    rm.ingest(_event("chunk", chunk=2, steps=10, wall_s=0.5,
                     ms_per_step=50.0, recompiled=True))
    rm.ingest(_event("heartbeat", verdict="STALLED", detail="slow"))
    rm.ingest(_event("heartbeat", verdict="WEDGED", detail="probe hung"))
    rm.ingest(_event("launch", attempt=0, resume=False,
                     resumed_from_step=None))
    rm.ingest(_event("restart", attempt=0, reason="heartbeat verdict "
                     "WEDGED", backoff_s=0.2, checkpoint_step=30))
    rm.ingest(_event("launch", attempt=1, resume=True,
                     resumed_from_step=30))
    rm.ingest(_event("summary", mcells_per_s=3.5, runtime={}))

    st = rm.status()
    assert st["manifest"]["tool"] == "cli"
    assert st["verdict"] == "WEDGED"  # latest heartbeat wins
    assert st["latest_chunk"]["chunk"] == 2
    assert len(st["chunks_recent"]) == 3
    assert len(st["restarts"]) == 1 and len(st["launches"]) == 2
    assert st["resumed_from_step"] == 30
    assert st["exchange"]["mode"] == "rdma"
    assert st["summary"]["mcells_per_s"] == 3.5
    # steady p50 over non-first, non-recompiled chunks only
    assert st["throughput"]["steady_ms_per_step_p50"] == 2.0
    # gcells from the manifest grid: 64^3 cells * 10 steps / 0.5 s
    # (the payload rounds to 4 decimals)
    assert st["throughput"]["gcells_per_s"] == \
        round(64 ** 3 * 10 / 0.5 / 1e9, 4)

    snap = rm.registry.snapshot()
    assert snap["obs_recompiles_total"]["value"] == 1
    assert snap["obs_supervisor_restarts_total"]["value"] == 1
    assert snap["obs_resumed_from_step"]["value"] == 30
    assert snap["obs_device_memory_peak_bytes"]["value"] == 1234
    assert snap["obs_first_chunk_ms_per_step"]["value"] == 100.0
    # roofline gap: steady p50 2.0 over overlapped prediction 1.0
    assert snap["obs_roofline_gap_ratio"]["value"] == pytest.approx(2.0)
    assert snap["obs_heartbeat_verdict"]["labels"]["verdict"] == "WEDGED"
    # a malformed record is swallowed, never raises
    rm.ingest(_event("chunk", chunk="x", steps="y"))
    assert rm.registry.snapshot()["obs_ingest_errors_total"]["value"] >= 1


# ------------------------------------------------------------- endpoints

@pytest.fixture()
def served_log(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest(
            "cli", {"stencil": "heat2d", "grid": [32, 128], "iters": 8}))
        w.event("costmodel", roofline={"predicted_ms_per_step_hbm": 0.1})
        w.event("chunk", chunk=0, steps=2, wall_s=0.5, ms_per_step=250.0,
                recompiled=False)
        w.event("chunk", chunk=1, steps=2, wall_s=0.01, ms_per_step=5.0,
                recompiled=False)
        w.event("heartbeat", verdict="STALLED", detail="x")
    server = serve.serve_run(path, port=0, poll_s=0.05)
    try:
        yield server, path
    finally:
        server.close()


def test_http_metrics_status_and_routes(served_log):
    server, _ = served_log
    assert _wait_for(lambda: server.console.seq >= 5)
    text = _get(server.url + "/metrics")
    assert "obs_run_info" in text and "obs_steps_total 4" in text
    assert 'obs_chunk_ms_per_step{quantile="0.5"} 5' in text

    st = _get_json(server.url + "/status.json")
    trace.validate_manifest(st["manifest"])  # provenance rides status
    assert st["manifest"]["tool"] == "cli"
    assert st["verdict"] == "STALLED"
    assert st["latest_chunk"]["chunk"] == 1
    assert st["throughput"]["steady_ms_per_step_p50"] == 5.0

    assert "status.json" in _get(server.url + "/")  # index names routes
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server.url + "/nope")
    assert ei.value.code == 404


def test_events_ordering_incremental_and_longpoll(served_log):
    server, path = served_log
    assert _wait_for(lambda: server.console.seq >= 5)
    lines = _get(server.url + "/events?after=0").strip().splitlines()
    recs = [json.loads(line) for line in lines]
    assert [r["_seq"] for r in recs] == list(range(1, len(recs) + 1))
    assert recs[0]["kind"] == "manifest"  # file order preserved
    assert [r["kind"] for r in recs[1:]] == \
        ["costmodel", "chunk", "chunk", "heartbeat"]

    # incremental: after=N yields exactly the tail
    tail = _get(server.url + f"/events?after={recs[-2]['_seq']}")
    tail_recs = [json.loads(line) for line in tail.strip().splitlines()]
    assert [r["_seq"] for r in tail_recs] == [recs[-1]["_seq"]]

    # bounded long-poll timeout: no new events -> empty after ~wait
    t0 = time.monotonic()
    body = _get(server.url + f"/events?after={server.console.seq}&wait=0.4")
    elapsed = time.monotonic() - t0
    assert body == "" and 0.3 <= elapsed < 5.0

    # a record landing mid-poll wakes the parked request
    result = {}

    def parked():
        result["body"] = _get(
            server.url + f"/events?after={server.console.seq}&wait=10")

    t = threading.Thread(target=parked)
    t.start()
    time.sleep(0.2)
    with open(path, "a") as fh:
        fh.write(json.dumps(_event("chunk", chunk=2, steps=2,
                                   wall_s=0.01, ms_per_step=5.0,
                                   recompiled=False)) + "\n")
    t.join(timeout=8)
    assert not t.is_alive(), "long-poll never woke"
    woke = [json.loads(line)
            for line in result["body"].strip().splitlines()]
    assert len(woke) == 1 and woke[0]["kind"] == "chunk"


def test_server_close_is_clean_and_idempotent(tmp_path):
    path = str(tmp_path / "r.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest("cli", {}))
    server = serve.serve_run(path, port=0, poll_s=0.05)
    url = server.url
    assert _get_json(url + "/status.json")["manifest"]["tool"] == "cli"
    server.close()
    server.close()  # idempotent
    leaked = [t.name for t in threading.enumerate()
              if t.name.startswith("obs-serve")]
    assert not leaked, leaked
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(url + "/status.json", timeout=2)


def test_campaign_console_rescans_directory(tmp_path):
    d = str(tmp_path)
    first = os.path.join(d, "a.jsonl")
    with trace.TraceWriter(first) as w:
        w.write_manifest(trace.build_manifest("measure", {"out": "x"}))
        w.event("label", label="heat2d_tiny", status="ok",
                mcells_per_s=12.5)
    server = serve.serve_campaign(d, port=0, poll_s=0.05)
    try:
        assert _wait_for(lambda: server.console.seq >= 2)
        st = _get_json(server.url + "/status.json")
        assert st["campaign"]["labels"]["heat2d_tiny"]["status"] == "ok"
        assert st["campaign"]["counts"] == {"ok": 1}
        # a log dropped AFTER the server started is picked up live
        second = os.path.join(d, "b.jsonl")
        with trace.TraceWriter(second) as w:
            w.write_manifest(trace.build_manifest("cli", {}))
            w.event("label", label="late_label", status="timeout")
        assert _wait_for(
            lambda: "late_label" in (_get_json(
                server.url + "/status.json").get("campaign") or
                {}).get("labels", {}))
        st = _get_json(server.url + "/status.json")
        assert st["campaign"]["counts"] == {"ok": 1, "timeout": 1}
        assert st["manifests_seen"] == 2
    finally:
        server.close()


# ------------------------------------------- supervised /status.json e2e

def test_supervised_status_shows_wedge_restart_and_resume(
        tmp_path, monkeypatch):
    """THE acceptance pin, live: an injected wedge (FAULT_INJECT) on a
    supervised run with --serve must be visible REMOTELY mid-run —
    /status.json shows the WEDGED verdict, the restart, and
    resumed_from_step, without reading any log file; and the console
    shuts down with the supervisor (no leaked thread)."""
    from mpi_cuda_process_tpu.resilience import supervisor as sup

    monkeypatch.setenv("FAULT_INJECT",
                       "exchange:step=40:hang,heartbeat:wedge")
    monkeypatch.setenv("FAULT_HANG_S", "60")
    # the child's in-process heartbeat must verdict BEFORE the
    # supervisor's wall-clock fallback so the kill reason is the
    # verdict (env inherited by the spawned child)
    monkeypatch.setenv("OBS_STALL_AFTER_S", "3")
    sup_log = str(tmp_path / "run.supervisor.jsonl")
    res = {}

    def scrape():
        url = None
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline and url is None:
            try:
                for line in open(sup_log):
                    rec = json.loads(line)
                    if rec.get("kind") == "serve":
                        url = rec["url"]
            except (OSError, ValueError):
                pass
            time.sleep(0.1)
        if url is None:
            res["err"] = "no serve event in the supervisor log"
            return
        st = None
        while time.monotonic() < deadline:
            try:
                st = _get_json(url + "/status.json", timeout=5)
            except OSError:
                time.sleep(0.2)
                continue
            if st.get("restarts") and st.get("resumed_from_step") == 30 \
                    and (st.get("heartbeat") or {}).get("verdict") == \
                    "WEDGED":
                res["status"] = st
                res["url"] = url
                return
            time.sleep(0.2)
        res["err"] = f"condition never met; last status: {st}"

    t = threading.Thread(target=scrape)
    t.start()
    rc = sup.run_supervised(RunConfig(
        stencil="life", grid=(64, 64), iters=100, seed=7,
        checkpoint_every=10, checkpoint_dir=str(tmp_path / "ck"),
        telemetry=str(tmp_path / "run.jsonl"), supervise=True,
        max_restarts=2, restart_backoff=0.2, supervise_stall_s=30.0,
        serve_port=0))
    t.join()
    assert rc == 0
    assert "err" not in res, res["err"]
    st = res["status"]
    # the remote answer to "is it wedged?": verdict + restart + resume
    assert st["heartbeat"]["verdict"] == "WEDGED"
    assert len(st["restarts"]) >= 1
    assert "heartbeat verdict" in st["restarts"][0]["reason"]
    assert st["resumed_from_step"] == 30
    launches = [ln for ln in st["launches"] if ln.get("resume")]
    assert launches and launches[0]["resumed_from_step"] == 30
    # supervisor manifest is the primary; children counted as sources
    assert st["manifest"]["tool"] == "supervisor"
    assert st["manifests_seen"] >= 2
    # console gone with the run
    leaked = [th.name for th in threading.enumerate()
              if th.name.startswith("obs-serve")]
    assert not leaked, leaked
    with pytest.raises((urllib.error.URLError, OSError)):
        _get(res["url"] + "/status.json", timeout=2)


# -------------------------------------------------------------- wiring

def test_cli_serve_flag_and_launcher_only_config():
    from mpi_cuda_process_tpu.cli import config_from_args

    cfg = config_from_args(["--serve", "0"])
    assert cfg.serve_port == 0
    assert config_from_args([]).serve_port is None
    # launcher-only: a supervised child must never inherit --serve
    argv = to_argv(RunConfig(serve_port=8123, iters=7))
    assert "--serve" not in argv and "8123" not in argv
    assert config_from_args(argv) == RunConfig(iters=7)


# -------------------------------------------------------------- obs_top

def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obs_top():
    return _load_script("obs_top_t", "scripts/obs_top.py")


def test_obs_top_renders_telemetry_path(tmp_path, capsys, obs_top):
    path = str(tmp_path / "run.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest(
            "cli", {"stencil": "heat2d", "grid": [32, 128], "iters": 8}))
        w.event("costmodel", roofline={"predicted_ms_per_step_hbm": 0.1})
        w.event("chunk", chunk=0, steps=2, wall_s=0.5, ms_per_step=250.0,
                recompiled=False)
        w.event("chunk", chunk=1, steps=2, wall_s=0.01, ms_per_step=5.0,
                recompiled=False)
        w.event("heartbeat", verdict="STALLED", detail="slow")
        w.event("summary", mcells_per_s=1.0, runtime={})
    # --once is a health probe (round 16): the latest heartbeat verdict
    # is STALLED, so the exit code is nonzero — CI/campaign scripts
    # gate on it (the frame still renders in full)
    assert obs_top.main([path, "--once"]) == 1
    out = capsys.readouterr().out
    assert "tool=cli" in out and "stencil=heat2d" in out
    assert "rate" in out and "roof" in out
    assert "verdict=STALLED" in out
    assert "mcells_per_s=1.0" in out


def test_obs_top_renders_live_url_and_campaign_deltas(
        tmp_path, capsys, obs_top, monkeypatch):
    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    # a ledger baseline the campaign view computes deltas against
    ledger_path = str(tmp_path / "ledger.jsonl")
    row = ledger_lib.make_row(
        "heat2d_tiny", 10.0, source="test", measured_at=time.time(),
        backend="cpu")
    ledger_lib.append_rows([row], ledger_path)

    path = str(tmp_path / "m.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest("measure", {"out": "x"}))
        w.event("label", label="heat2d_tiny", status="ok",
                mcells_per_s=12.5)
    server = serve.serve_run(path, port=0, poll_s=0.05)
    try:
        assert _wait_for(lambda: server.console.seq >= 2)
        assert obs_top.main([server.url, "--once",
                             "--ledger", ledger_path]) == 0
    finally:
        server.close()
    out = capsys.readouterr().out
    assert "tool=measure" in out
    assert "heat2d_tiny" in out and "+25.0%" in out


def test_obs_top_renders_committed_ledger(capsys, obs_top):
    """Acceptance: the committed campaign ledger renders without error."""
    path = os.path.join(REPO, "benchmarks", "ledger.jsonl")
    assert obs_top.main([path, "--once"]) == 0
    out = capsys.readouterr().out
    assert "baselines" in out and "quarantine reasons" in out


def test_obs_top_sparkline():
    obs_top = _load_script("obs_top_spark", "scripts/obs_top.py")
    assert obs_top.sparkline([]) == "(no samples yet)"
    assert len(obs_top.sparkline([1.0] * 5)) == 5  # flat, no div-by-0
    s = obs_top.sparkline([0, 1, 2, 3])
    assert s[0] == "▁" and s[-1] == "█"
