"""Two-axis pad-free temporal blocking == the plain sharded step.

``make_sharded_fused_step(padfree=True)`` on a mesh that shards y (2-axis
``(2, 2, 1)`` or y-only ``(1, 2, 1)``) now builds the yz-slab-operand
kernels (``fused.build_yzslab_padfree_call`` / ``build_yzslab_xwin_call``:
y slabs + the four two-pass-composed corner pieces as operands, selects
on both wall axes) instead of silently falling back to the
exchange-padded kernel.  These tests pin:

  * value equivalence vs the PLAIN sharded step (``make_sharded_step``
    applied k times on the same mesh) and vs the unsharded reference —
    allclose 1e-6 for the float families (there is no 3D int fused
    family; the int bit-exactness contract is carried by the 2D
    fullgrid overlap tests), including red-black sor3d parity across
    BOTH sharded axes;
  * the same equivalence for ``overlap=True`` (shells on both axes, edge
    strips carrying genuine corner data);
  * structure: the 2-axis overlap interior pallas_call consumes no
    ``ppermute`` output (jaxpr reachability — the whole point of the
    split);
  * the builder chain actually selects the 2-axis kernels
    (``_padfree_kind`` introspection) — a padded fallback must not pass
    these tests by being numerically right for the wrong reason.

Every equivalence case runs >= 2 fused calls, so the second call's slabs
AND corners come from the first call's spliced outputs — a
wrong-corner-neighbor bug cannot survive two exchanges.
"""

import numpy as np
import pytest

import jax

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

from test_overlap_fused import _interior_depends_on_ppermute


def _assert_close(got, ref, atol):
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=0, atol=atol)


def _build_padfree(name, grid, mesh_shape, k, periodic=False, overlap=False,
                   want_kind="yzslab", **kw):
    st = make_stencil(name, **kw)
    mesh = make_mesh(mesh_shape)
    step = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                   padfree=True, periodic=periodic,
                                   overlap=overlap)
    assert step is not None, (name, grid, mesh_shape)
    assert getattr(step, "_padfree_kind", None) == want_kind, \
        "2-axis pad-free builder unexpectedly declined (padded fallback?)"
    if overlap:
        assert getattr(step, "_overlap_active", False), \
            "overlap geometry unexpectedly declined — fix the test shape"
    return st, mesh, step


def _run_fused(st, mesh, step, fields, calls):
    got = shard_fields(fields, mesh, 3)
    jf = jax.jit(step)
    for _ in range(calls):
        got = jf(got)
    return got


def test_yz_padfree_and_overlap_match_plain_sharded_step():
    """The acceptance anchor: on a (2, 2, 1) mesh the 2-axis pad-free
    stepper — with AND without overlap — equals the plain sharded step
    (same mesh, k single steps per fused call) to 1e-6."""
    st = make_stencil("heat3d")
    grid, k, calls = (32, 32, 128), 4, 2
    mesh = make_mesh((2, 2, 1))
    fields = init_state(st, grid, seed=9, kind="pulse")

    plain = jax.jit(make_sharded_step(st, mesh, grid))
    ref = shard_fields(fields, mesh, 3)
    for _ in range(k * calls):
        ref = plain(ref)

    _, _, pf = _build_padfree("heat3d", grid, (2, 2, 1), k)
    _assert_close(_run_fused(st, mesh, pf, fields, calls), ref, 1e-6)
    _, _, ov = _build_padfree("heat3d", grid, (2, 2, 1), k, overlap=True)
    _assert_close(_run_fused(st, mesh, ov, fields, calls), ref, 1e-6)


# Remaining equivalences compare against the unsharded reference step
# (one cheap compile instead of a second shard_map program; sharded ==
# unsharded is already pinned by tests/test_sharded.py).  wave3d carries
# the two-field leapfrog (u_prev exchanged at full width m under
# blocking); sor3d's red-black parity must stay consistent across BOTH
# sharded axes (origins feed the in-kernel coloring on z AND y).
@pytest.mark.parametrize("name,grid,mesh_shape,k,periodic", [
    ("wave3d", (32, 32, 128), (2, 2, 1), 4, False),
    ("sor3d", (32, 32, 128), (2, 2, 1), 4, False),
    # the y-only sor variant is slow tier (round-8 budget trim): its
    # (2, 2, 1) sibling above is a strict superset for the coloring
    # coverage (BOTH shard origins feed the in-kernel parity), and the
    # y-only degenerate path (z bc-dummy slabs) stays covered every
    # round by the dryrun's twoaxis_padfree_yonly leg plus the default
    # heat3d (1, 2, 1) row of tests/test_twoaxis_stream.py
    pytest.param("sor3d", (32, 32, 128), (1, 2, 1), 4, False,
                 marks=pytest.mark.slow),
    pytest.param("heat3d", (32, 32, 128), (1, 2, 1), 4, False,
                 marks=pytest.mark.slow),
    pytest.param("wave3d", (32, 32, 128), (1, 2, 1), 4, False,
                 marks=pytest.mark.slow),
    pytest.param("heat3d", (32, 32, 128), (2, 2, 1), 4, True,
                 marks=pytest.mark.slow),   # wrap slabs + wrap corners
    pytest.param("sor3d", (32, 32, 128), (2, 2, 1), 4, True,
                 marks=pytest.mark.slow),   # wrap parity consistency
])
def test_yz_padfree_matches_unsharded(name, grid, mesh_shape, k, periodic):
    st, mesh, step = _build_padfree(name, grid, mesh_shape, k,
                                    periodic=periodic)
    fields = init_state(st, grid, seed=9,
                        kind="random" if periodic else "pulse",
                        periodic=periodic)
    ref = fields
    ref_step = jax.jit(make_step(st, grid, periodic=periodic))
    for _ in range(2 * k):
        ref = ref_step(ref)
    _assert_close(_run_fused(st, mesh, step, fields, 2), ref, 1e-5)


@pytest.mark.parametrize("name,grid,mesh_shape,k,periodic", [
    pytest.param("heat3d", (32, 32, 128), (1, 2, 1), 4, False,
                 marks=pytest.mark.slow),   # y-only: z dummy slabs
    pytest.param("wave3d", (32, 32, 128), (2, 2, 1), 4, False,
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (64, 64, 128), (2, 2, 1), 4, False,
                 marks=pytest.mark.slow),   # m=8: locals >= 3m for shells
    pytest.param("heat3d", (32, 32, 128), (2, 2, 1), 4, True,
                 marks=pytest.mark.slow),
])
def test_yz_overlap_matches_unsharded(name, grid, mesh_shape, k, periodic):
    st, mesh, step = _build_padfree(name, grid, mesh_shape, k,
                                    periodic=periodic, overlap=True)
    fields = init_state(st, grid, seed=9,
                        kind="random" if periodic else "pulse",
                        periodic=periodic)
    ref = fields
    ref_step = jax.jit(make_step(st, grid, periodic=periodic))
    for _ in range(2 * k):
        ref = ref_step(ref)
    _assert_close(_run_fused(st, mesh, step, fields, 2), ref, 1e-5)


# ---------------------------------------------------------------------------
# wide-X 2-axis kernel (x windowed at lane-tile granularity)
# ---------------------------------------------------------------------------


def _xwin_step(name, grid, mesh_shape, k, tiles, periodic=False,
               overlap=False, **kw):
    """Force the wide-X fallback (whole-row declined) with explicit
    tiles — at test sizes the whole-row kernel always fits VMEM, so the
    fallback is exercised the same way the z-only xwin tests do."""
    from mpi_cuda_process_tpu.ops.pallas import fused as F

    orig_row, orig_x = F.build_yzslab_padfree_call, F.build_yzslab_xwin_call
    F.build_yzslab_padfree_call = lambda *a, **kw2: None
    F.build_yzslab_xwin_call = \
        lambda *a, **kw2: orig_x(*a, tiles=tiles, **kw2)
    try:
        return _build_padfree(name, grid, mesh_shape, k, periodic=periodic,
                              overlap=overlap, want_kind="yzslab_xwin",
                              **kw)
    finally:
        F.build_yzslab_padfree_call = orig_row
        F.build_yzslab_xwin_call = orig_x


@pytest.mark.parametrize("name,tiles", [
    ("heat3d", (8, 8, 128)),
    pytest.param("wave3d", (8, 8, 128), marks=pytest.mark.slow),
    pytest.param("sor3d", (16, 16, 128), marks=pytest.mark.slow),
])
def test_yz_xwin_matches_unsharded(name, tiles):
    grid = (32, 32, 256)  # bx=128 < X: two x-tiles, clamped x shells
    st, mesh, step = _xwin_step(name, grid, (2, 2, 1), 4, tiles)
    fields = init_state(st, grid, seed=21, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, grid))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_fused(st, mesh, step, fields, 2), ref, 1e-5)


@pytest.mark.slow
def test_yz_xwin_overlap_matches_unsharded():
    grid = (32, 32, 256)
    st, mesh, step = _xwin_step("heat3d", grid, (2, 2, 1), 4,
                                (8, 8, 128), overlap=True)
    fields = init_state(st, grid, seed=21, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, grid))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_fused(st, mesh, step, fields, 2), ref, 1e-5)


# ---------------------------------------------------------------------------
# structure: the 2-axis overlap interior consumes no ppermute output
# ---------------------------------------------------------------------------


def test_yz_overlap_interior_free_of_collective_permute():
    """The 2-axis split's whole point, asserted structurally: the
    interior pallas_call of the (2, 2, 1) overlap step is unreachable
    from ANY collective-permute output (z slabs, y slabs, and the
    two-hop corner ppermutes all feed only the boundary shells), while
    the step as a whole does exchange."""
    grid = (32, 32, 128)
    st, mesh, over = _build_padfree("heat3d", grid, (2, 2, 1), 4,
                                    overlap=True)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    # (a) the exported interior path traces with no collective at all
    txt = str(jax.make_jaxpr(over._interior_step)(fields))
    assert "ppermute" not in txt
    # (b) the REAL step's interior pallas_call is unreachable from any
    # ppermute output
    local = (grid[0] // 2, grid[1] // 2, grid[2])
    assert not _interior_depends_on_ppermute(over, fields, local)
    assert "ppermute" in str(jax.make_jaxpr(over)(fields))


def test_yz_forced_kind_has_no_padded_fallback():
    """kind='padfree' must return None (callers raise) when no
    slab-operand builder tiles the shape — never silently measure the
    padded kernel under a pad-free label."""
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 2, 1))
    # local (4, 8, 128): z extent below the 2m=8 tile granularity
    assert make_sharded_fused_step(st, mesh, (8, 16, 128), 4,
                                   interpret=True, kind="padfree") is None
