"""Tests for the unified telemetry layer (mpi_cuda_process_tpu/obs).

Pins the subsystem's four contracts:

* **schema** — manifest round-trip through the writer + validator;
  rejection cases name every problem; all four entry points (cli,
  bench, measure, scaling) emit logs passing ONE validator.
* **runtime** — per-chunk stats recorded at chunk boundaries only, with
  the jitted step jaxpr byte-identical with and without telemetry
  (zero ops in the hot scan — the acceptance criterion).
* **cost model** — static ppermute round/byte counters equal to what a
  TRACED sharded step actually issues (jaxpr cross-check on virtual
  devices) and, for config 5 on both mesh families, equal to
  utils/budget.py's byte-pinned slab accounting to the byte.
* **heartbeat** — an injected hang yields STALLED, an injected wedged
  probe escalates to WEDGED, resumed progress yields RECOVERED.
"""

import importlib.util
import json
import math
import os
import sys
import threading
import time

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu import (  # noqa: E402
    driver, init_state, make_mesh, make_step, make_stencil, shard_fields,
)
from mpi_cuda_process_tpu import cli, obs  # noqa: E402
from mpi_cuda_process_tpu.obs import (  # noqa: E402
    costmodel, heartbeat, runtime, trace,
)
from mpi_cuda_process_tpu.utils import budget  # noqa: E402


# ---------------------------------------------------------------- schema

def test_manifest_roundtrip_and_latest_lookup(tmp_path, monkeypatch):
    monkeypatch.setenv("OBS_TELEMETRY_DIR", str(tmp_path))
    path = str(tmp_path / "run.jsonl")
    with trace.TraceWriter(path) as w:
        m = trace.build_manifest("cli", {"stencil": "heat2d",
                                         "grid": [32, 128]})
        w.write_manifest(m)
        w.event("chunk", chunk=0, steps=4, wall_s=0.1)
        w.event("summary", mcells_per_s=1.0)
    manifest, events = trace.validate_log(path)
    assert manifest == json.loads(json.dumps(m))  # json round-trip clean
    assert [e["kind"] for e in events] == ["chunk", "summary"]
    prov = manifest["provenance"]
    assert prov["backend"] == jax.default_backend()
    assert prov["device_count"] == len(jax.devices())
    assert isinstance(prov["jax_version"], str)
    # the wedged-path pointer finds this log as the newest manifest
    found = trace.find_latest_manifest()
    assert found is not None and found[0] == path
    assert found[1]["tool"] == "cli"


def test_validator_rejects_and_names_every_problem(tmp_path):
    good = trace.build_manifest("bench", {"grid": [16, 16]})
    trace.validate_manifest(good)

    bad = dict(good, schema=99, kind="event")
    with pytest.raises(ValueError) as ei:
        trace.validate_manifest(bad)
    msg = str(ei.value)
    assert "schema" in msg and "kind" in msg  # ALL problems, not first

    for mutate in (
        lambda m: m.pop("tool"),
        lambda m: m.__setitem__("run", "not-a-dict"),
        lambda m: m.__setitem__("created_at", None),
        lambda m: m["provenance"].pop("git_sha"),
        lambda m: m["provenance"].__setitem__("device_count", 0),
        lambda m: m["provenance"].__setitem__("builder_rev", "eight"),
    ):
        m = json.loads(json.dumps(good))
        mutate(m)
        with pytest.raises(ValueError):
            trace.validate_manifest(m)

    with pytest.raises(ValueError):  # events may not masquerade
        trace.validate_event({"schema": 1, "kind": "manifest",
                              "t": time.time()})
    # the writer enforces ordering: manifest first, exactly once
    w = trace.TraceWriter(str(tmp_path / "order.jsonl"))
    with pytest.raises(ValueError):
        w.event("chunk")
    w.write_manifest(good)
    with pytest.raises(ValueError):
        w.write_manifest(good)
    w.close()


def test_manifest_schema2_carries_multihost_provenance():
    """Satellite: schema rev 2 adds process_index / process_count /
    hostname — the multi-host prep a per-host aggregator needs."""
    m = trace.build_manifest("cli", {"grid": [16, 16]})
    assert m["schema"] == 2
    prov = m["provenance"]
    assert isinstance(prov["process_index"], int)
    assert isinstance(prov["process_count"], int) \
        and prov["process_count"] >= 1
    assert isinstance(prov["hostname"], str) and prov["hostname"]

    # the new fields are REQUIRED at schema 2 and type-checked
    for mutate in (
        lambda d: d["provenance"].pop("hostname"),
        lambda d: d["provenance"].__setitem__("process_index", "zero"),
        lambda d: d["provenance"].__setitem__("process_count", 0),
    ):
        bad = json.loads(json.dumps(m))
        mutate(bad)
        with pytest.raises(ValueError):
            trace.validate_manifest(bad)


def test_old_schema1_manifests_still_parse():
    """Satellite: the validator accepts BOTH revisions — a pre-rev log
    (schema 1, no host fields) must keep parsing."""
    old = trace.build_manifest("cli", {"grid": [16, 16]})
    old = json.loads(json.dumps(old))
    old["schema"] = 1
    for k in ("process_index", "process_count", "hostname"):
        old["provenance"].pop(k)
    trace.validate_manifest(old)  # no raise: old manifests still parse
    # schema-1 events validate too (an old log's tail)
    trace.validate_event({"schema": 1, "kind": "chunk", "t": time.time()})
    # but a schema-1 writer that DID include the fields gets them typed
    old["provenance"]["hostname"] = 42
    with pytest.raises(ValueError, match="hostname"):
        trace.validate_manifest(old)


def test_validate_log_rejects_corrupt_event(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest("cli", {}))
    with open(path, "a") as fh:
        fh.write(json.dumps({"kind": "chunk"}) + "\n")  # no schema/t
    with pytest.raises(ValueError, match="event 0"):
        trace.validate_log(path)


# ----------------------------------------------------- entry-point logs

def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def cli_log(tmp_path_factory):
    """A real CLI run with --telemetry: the canonical event log."""
    path = str(tmp_path_factory.mktemp("obs") / "cli.jsonl")
    cfg = cli.config_from_args([
        "--stencil", "heat2d", "--grid", "32,128", "--iters", "8",
        "--log-every", "2", "--telemetry", path])
    cli.run(cfg)
    return path


def test_cli_log_valid_with_chunks_cost_and_summary(cli_log):
    manifest, events = trace.validate_log(cli_log)
    assert manifest["tool"] == "cli"
    assert manifest["run"]["stencil"] == "heat2d"
    kinds = [e["kind"] for e in events]
    assert kinds.count("chunk") == 4  # 8 iters / log-every 2
    assert "costmodel" in kinds
    # the session ROOT SPAN closes every log (round 16 — its duration
    # covers the whole session, so it must be emitted last); the
    # summary is the final non-span record
    assert events[-1]["kind"] == "span" and events[-1]["name"] == "cli"
    non_span = [e for e in events if e["kind"] != "span"]
    assert non_span[-1]["kind"] == "summary"
    summary = non_span[-1]
    assert summary["runtime"]["n_chunks"] == 4
    assert summary["runtime"]["steps"] == 8
    assert summary["runtime"]["steady"]["ms_per_step_p50"] > 0
    # compile separated from steady state: first chunk strictly slower
    assert summary["runtime"]["first_chunk_ms_per_step"] > \
        summary["runtime"]["steady"]["ms_per_step_p50"]
    assert summary["mcells_per_s"] > 0


def test_scaling_emits_same_schema(tmp_path):
    scaling = _load_script("scaling_obs", "benchmarks/scaling.py")
    path = str(tmp_path / "scaling.jsonl")
    rc = scaling.main([
        "--mode", "weak", "--stencil", "heat2d", "--block", "16,16",
        "--steps", "2", "--reps", "1",
        "--virtual", str(len(jax.devices())), "--telemetry", path])
    assert rc == 0
    manifest, events = trace.validate_log(path)
    assert manifest["tool"] == "scaling"
    rungs = [e for e in events if e["kind"] == "rung"]
    assert len(rungs) == int(math.log2(len(jax.devices()))) + 1
    non_span = [e for e in events if e["kind"] != "span"]
    assert non_span[-1]["kind"] == "summary"


def test_measure_emits_same_schema(tmp_path, monkeypatch):
    measure = _load_script("measure_obs", "benchmarks/measure.py")
    monkeypatch.setattr(measure, "CONFIGS", [
        ("heat2d_tiny", "heat2d", (16, 128), 2, "float32", "jnp")])
    out = str(tmp_path / "results.json")
    path = str(tmp_path / "measure.jsonl")
    monkeypatch.setattr(sys, "argv", [
        "measure.py", "--in-process", "--out", out, "--telemetry", path])
    measure.main()
    manifest, events = trace.validate_log(path)
    assert manifest["tool"] == "measure"
    labels = [e for e in events if e["kind"] == "label"]
    assert [e["label"] for e in labels] == ["heat2d_tiny"]
    assert labels[0]["status"] in ("ok", "error")  # noise floor may trip
    non_span = [e for e in events if e["kind"] != "span"]
    assert non_span[-1]["kind"] == "summary"
    assert non_span[-1]["labels_run"] == 1


def test_bench_telemetry_and_wedge_context(tmp_path, monkeypatch):
    """Satellite: the wedged-path record embeds the heartbeat verdict
    and the newest manifest path — ``stale: true`` says WHY in one
    file."""
    monkeypatch.setenv("OBS_TELEMETRY_DIR", str(tmp_path))
    import bench

    # the healthy path drops a manifest under the telemetry dir
    rec = {"metric": "m", "value": 1.0}
    tel = bench._write_bench_telemetry(rec, (16, 16, 16), 2, 0, "cpu")
    assert tel is not None
    manifest, events = trace.validate_log(tel)
    assert manifest["tool"] == "bench"
    assert events[0]["kind"] == "result" and events[0]["value"] == 1.0

    # the wedged path probes (stubbed) and points at that manifest
    monkeypatch.setenv("BENCH_OBS_PROBE", "1")
    monkeypatch.setattr(
        heartbeat, "probe_verdict",
        lambda timeout_s=0: {"verdict": "WEDGED", "detail": "injected"})
    monkeypatch.setattr(bench, "_CACHE", str(tmp_path / "absent.json"))
    stale = bench._stale_fallback_record()
    assert stale["stale"] is True
    assert stale["heartbeat"]["verdict"] == "WEDGED"
    assert stale["telemetry_manifest"] == tel
    json.dumps(stale)  # driver-visible record stays one JSON line


# ------------------------------------------------------------- runtime

def test_telemetry_adds_zero_ops_to_jitted_step(tmp_path):
    """Acceptance criterion: the jitted step/scan is byte-identical with
    and without telemetry — events exist only at chunk boundaries."""
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 128), seed=0, kind="pulse")
    step = make_step(st, (16, 128))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype) for f in fields)
    jaxpr_before = str(jax.make_jaxpr(step)(abstract))
    runner_jaxpr_before = str(
        jax.make_jaxpr(driver.make_runner(step, 4, jit=False))(abstract))

    path = str(tmp_path / "zero.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest("cli", {}))
        rec = runtime.RuntimeRecorder(trace=w)
        out = driver.run_simulation(
            st, fields, 8, step_fn=step, log_every=2,
            callback=lambda done, fs: None, observer=rec)
        assert len(rec.chunks) == 4

    # telemetry active changed NOTHING about the traced program
    assert str(jax.make_jaxpr(step)(abstract)) == jaxpr_before
    runner_jaxpr_after = str(
        jax.make_jaxpr(driver.make_runner(step, 4, jit=False))(abstract))
    assert runner_jaxpr_after == runner_jaxpr_before
    # and no host-callback primitive anywhere in the executed program
    for prim in ("pure_callback", "io_callback", "debug_callback",
                 "outside_call"):
        assert prim not in runner_jaxpr_after
    assert out[0].shape == fields[0].shape


def test_serve_zero_ops_and_scrape_mid_run(tmp_path):
    """Acceptance criterion: --serve adds zero ops to the jitted step
    (the telemetry-invariance pin extended) and the server never blocks
    the run loop — /metrics and /status.json answer MID-RUN, from a
    chunk-boundary callback, while the scan is in flight."""
    import urllib.request

    from mpi_cuda_process_tpu.obs import serve as serve_lib

    st = make_stencil("heat2d")
    fields = init_state(st, (16, 128), seed=0, kind="pulse")
    step = make_step(st, (16, 128))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype) for f in fields)
    jaxpr_before = str(jax.make_jaxpr(step)(abstract))
    runner_jaxpr_before = str(
        jax.make_jaxpr(driver.make_runner(step, 4, jit=False))(abstract))

    path = str(tmp_path / "served.jsonl")
    session = obs.open_session(path, "cli", {"grid": [16, 128]},
                               with_heartbeat=False)
    server = serve_lib.serve_run(path, port=0, poll_s=0.05)
    scraped = {}

    def callback(done, fs):
        if done != 4 or scraped:
            return  # scrape once, mid-run (2 of 4 chunks left)
        deadline = time.time() + 10
        while time.time() < deadline and "metrics" not in scraped:
            try:
                with urllib.request.urlopen(server.url + "/metrics",
                                            timeout=5) as r:
                    scraped["metrics"] = r.read().decode()
                with urllib.request.urlopen(server.url + "/status.json",
                                            timeout=5) as r:
                    scraped["status"] = json.loads(r.read().decode())
            except OSError:
                time.sleep(0.1)

    try:
        driver.run_simulation(st, fields, 8, step_fn=step, log_every=2,
                              callback=callback, observer=session.recorder)
        session.finish()
    finally:
        session.close()
        server.close()

    assert "metrics" in scraped, "mid-run scrape never succeeded"
    assert "obs_run_info" in scraped["metrics"]
    assert scraped["status"]["manifest"]["tool"] == "cli"
    # the served run traced the SAME program: zero ops added
    assert str(jax.make_jaxpr(step)(abstract)) == jaxpr_before
    assert str(jax.make_jaxpr(
        driver.make_runner(step, 4, jit=False))(abstract)) == \
        runner_jaxpr_before


def test_recorder_separates_compile_flags_recompiles_and_percentiles():
    rec = runtime.RuntimeRecorder(step_unit=4)
    rec.begin_chunk()
    rec.record_chunk(2, 1.0)  # compile chunk: 8 real steps
    for s in (0.08, 0.10, 0.12, 0.10):
        rec.begin_chunk()
        rec.record_chunk(2, s)
    s = rec.summary()
    assert s["n_chunks"] == 5 and s["steps"] == 40
    assert s["first_chunk_s"] == 1.0
    assert s["steady"]["chunks"] == 4
    assert s["steady"]["ms_per_step_best"] == pytest.approx(10.0)
    assert s["steady"]["ms_per_step_p50"] == pytest.approx(12.5)
    assert s["recompiles"] == 0
    # an injected compile event mid-steady-state flags that chunk and
    # excludes it from the percentiles
    rec.begin_chunk()
    runtime._compile_events[0] += 3
    chunk = rec.record_chunk(2, 5.0)
    assert chunk["recompiled"] is True
    s2 = rec.summary()
    assert s2["recompiles"] == 3
    assert s2["steady"]["chunks"] == 4  # the recompiled chunk excluded


# ------------------------------------------------------------ heartbeat

class _ListTrace:
    def __init__(self):
        self.events = []

    def event(self, kind, **payload):
        self.events.append(dict(kind=kind, **payload))


def _wait_for(pred, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_heartbeat_stall_escalation_and_recovery():
    """Injected hang -> STALLED -> (wedged probe) -> WEDGED; progress
    resumes -> RECOVERED; one verdict per episode, no event spam."""
    progress = [time.monotonic()]
    tr = _ListTrace()
    probed = threading.Event()

    def probe():
        probed.set()
        return {"verdict": "WEDGED", "detail": "injected wedge"}

    hb = heartbeat.Heartbeat(lambda: progress[0], trace=tr,
                             stall_after_s=0.15, poll_s=0.03, probe=probe)
    hb.start()
    try:
        assert _wait_for(lambda: any(
            e["verdict"] == "WEDGED" for e in tr.events))
        assert probed.is_set()
        verdicts = [e["verdict"] for e in tr.events]
        assert verdicts[0] == "STALLED"  # stall first, then escalation
        assert hb.last_verdict["verdict"] == "WEDGED"
        n_after_episode = len(tr.events)
        time.sleep(0.2)  # still stalled: same episode, no new events
        assert len(tr.events) == n_after_episode
        progress[0] = time.monotonic()  # inject recovery
        assert _wait_for(lambda: any(
            e["verdict"] == "RECOVERED" for e in tr.events))
    finally:
        hb.stop()


def test_heartbeat_healthy_backend_keeps_stalled_verdict():
    progress = [time.monotonic() - 100.0]  # born stalled
    tr = _ListTrace()
    hb = heartbeat.Heartbeat(
        lambda: progress[0], trace=tr, stall_after_s=0.1, poll_s=0.03,
        probe=lambda: {"verdict": "NO_TPU", "detail": "cpu box"})
    hb.start()
    try:
        assert _wait_for(lambda: any(
            "NO_TPU" in str(e.get("detail")) for e in tr.events))
        assert hb.last_verdict["verdict"] == "STALLED"  # not WEDGED
    finally:
        hb.stop()


@pytest.mark.slow
def test_probe_verdict_real_subprocesses():
    """The real (unstubbed) probe on this box: CPU backend answers, so
    the verdict must be NO_TPU — bounded, never raising."""
    v = heartbeat.probe_verdict(timeout_s=120.0)
    assert v["verdict"] == "NO_TPU", v


# ------------------------------------------------------------ costmodel

def test_config5_counters_match_budget_to_the_byte():
    """Acceptance criterion: static ppermute/byte counters for config 5
    (wave3d 4096^3, k=4) equal budget.py's slab accounting exactly, on
    the z-ring AND the balanced mesh, for the stream and padfree kinds."""
    st = make_stencil("wave3d")
    grid = (4096,) * 3
    # (mesh, kind) -> (rounds/pass, ici bytes/pass, operand bytes)
    expect = {
        ((64, 1, 1), "stream"): (4, 1_073_741_824, 1_073_741_824),
        ((64, 1, 1), "padfree"): (4, 1_073_741_824, 1_073_741_824),
        ((8, 8, 1), "stream"): (16, 270_532_608, 543_162_368),
        ((8, 8, 1), "padfree"): (16, 270_532_608, 406_847_488),
    }
    for (mesh, kind), (rounds, ici, operand) in expect.items():
        cs = costmodel.comm_stats(st, grid, mesh, fuse=4, fuse_kind=kind)
        assert cs["ppermute_rounds_per_pass"] == rounds, (mesh, kind)
        assert cs["ici_bytes_per_pass"] == ici, (mesh, kind)
        assert cs["slab_operand_bytes"] == operand, (mesh, kind)
        # equal to budget.py's own arithmetic, extracted from its parts
        _, parts = budget.estimate_run_bytes(
            st, grid, mesh=mesh, fuse=4, fuse_kind=kind)
        slab = [b for label, b in parts if "operands only" in label]
        assert slab == [operand], (mesh, kind)
        cc = costmodel.budget_crosscheck(st, grid, mesh, 4, kind)
        assert cc == {"slab_operand_bytes": operand,
                      "budget_bytes": operand, "match": True}


def _traced_comm(name, grid, mesh_shape, k=0, **kw):
    st = make_stencil(name)
    mesh = make_mesh(mesh_shape)
    if k:
        from mpi_cuda_process_tpu.parallel.stepper import (
            make_sharded_fused_step,
        )

        step = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                       **kw)
    else:
        from mpi_cuda_process_tpu.parallel.stepper import make_sharded_step

        step = make_sharded_step(st, mesh, grid)
    assert step is not None, (name, grid, mesh_shape, kw)
    fields = shard_fields(init_state(st, grid, seed=1, kind="pulse"),
                          mesh, st.ndim)
    return costmodel.comm_stats_from_jaxpr(jax.make_jaxpr(step)(fields))


@pytest.mark.parametrize("name,grid,mesh,k,kw,kind", [
    # z-only slab kernels: 2 rounds per exchanged field of (m, ly, lx)
    ("heat3d", (32, 16, 128), (2, 1, 1), 4, {"padfree": True}, "padfree"),
    ("wave3d", (32, 16, 128), (2, 1, 1), 4, {"padfree": True}, "padfree"),
    # 2-axis slab kernels: + 2 y-rounds and 4 two-pass corner rounds
    ("heat3d", (32, 32, 128), (2, 2, 1), 4, {"padfree": True}, "padfree"),
    ("heat3d", (48, 32, 128), (2, 2, 1), 4, {"kind": "stream"}, "stream"),
    # padded sharded fused: two-pass exchange_and_pad at width m
    ("heat3d", (32, 32, 128), (2, 2, 1), 4, {}, "auto"),
])
def test_comm_model_matches_traced_sharded_fused_step(
        name, grid, mesh, k, kw, kind):
    """The analytic exchange model equals what the built stepper
    actually issues — rounds AND bytes, read off the traced jaxpr."""
    st = make_stencil(name)
    got = _traced_comm(name, grid, mesh, k, **kw)
    want = costmodel.comm_stats(st, grid, mesh, fuse=k, fuse_kind=kind)
    assert got["ppermute_rounds"] == want["ppermute_rounds_per_pass"]
    assert got["ppermute_bytes"] == want["ici_bytes_per_pass"]


def test_comm_model_matches_traced_plain_sharded_step():
    """fuse=0: per-field halo widths (wave's u_prev has halo 0 and must
    not be priced) through the two-pass exchange_and_pad scheme."""
    for name, grid, mesh in (("heat3d", (16, 16, 128), (2, 2, 1)),
                             ("wave3d", (16, 16, 128), (2, 2, 1)),
                             ("heat3d", (16, 16, 128), (2, 1, 1))):
        st = make_stencil(name)
        got = _traced_comm(name, grid, mesh)
        want = costmodel.comm_stats(st, grid, mesh)
        assert got["ppermute_rounds"] == \
            want["ppermute_rounds_per_pass"], (name, mesh)
        assert got["ppermute_bytes"] == want["ici_bytes_per_pass"], \
            (name, mesh)


def test_step_flops_counter_pinned():
    """The flop counter is a pinned model: exact values, linear scaling."""
    h3 = make_stencil("heat3d")
    assert costmodel.step_flops(h3, (8, 8, 128)) == 98_304
    assert costmodel.step_flops(h3, (16, 16, 128)) == 393_216  # 4x cells
    assert costmodel.step_flops(make_stencil("life"), (16, 128)) == 18_432
    # flops land in static_cost per-device (local block), with roofline
    sc = costmodel.static_cost(h3, (16, 16, 128), mesh=(2, 1, 1))
    assert sc["flops_per_step_per_device"] == \
        costmodel.step_flops(h3, (8, 16, 128))
    assert sc["hbm_bytes_per_step_per_device"] == 2 * 8 * 16 * 128 * 4
    assert sc["roofline"]["predicted_mcells_per_s_overlapped"] > 0
    assert sc["comm"]["ppermute_rounds_per_pass"] == 2


def test_static_cost_fuse_divides_hbm_traffic():
    st = make_stencil("heat3d")
    plain = costmodel.static_cost(st, (32, 32, 128))
    fused = costmodel.static_cost(st, (32, 32, 128), fuse=4)
    assert plain["hbm_bytes_per_step_per_device"] == \
        4 * fused["hbm_bytes_per_step_per_device"]


# ----------------------------------------------------- session & report

def test_session_error_event_and_finish_idempotent(tmp_path):
    path = str(tmp_path / "err.jsonl")
    with pytest.raises(RuntimeError):
        with obs.open_session(path, "cli", {"x": 1},
                              with_heartbeat=False):
            raise RuntimeError("boom")
    manifest, events = trace.validate_log(path)
    non_span = [e for e in events if e["kind"] != "span"]
    assert non_span[-1]["kind"] == "error"
    assert "boom" in non_span[-1]["error"]

    path2 = str(tmp_path / "fin.jsonl")
    s = obs.open_session(path2, "cli", {}, with_heartbeat=False)
    s.finish(mcells_per_s=1.0)
    s.finish(mcells_per_s=2.0)  # idempotent: second call is a no-op
    s.close()
    _, events = trace.validate_log(path2)
    # exactly one summary, then the root span (round 16) closes the log
    assert [e["kind"] for e in events] == ["summary", "span"]
    assert events[0]["mcells_per_s"] == 1.0


def test_obs_report_renders_attribution_and_checks(cli_log, tmp_path,
                                                   capsys):
    report = _load_script("obs_report_t", "scripts/obs_report.py")
    assert report.main([cli_log, "--check"]) == 0
    out = capsys.readouterr().out
    assert "obs_report --check: ok" in out
    assert "manifest  tool=cli" in out
    assert "attribution (predicted vs measured)" in out
    assert "TOTAL overlapped" in out
    assert "steady" in out
    # an invalid log fails --check with a nonzero rc
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"kind": "manifest"}\n')
    assert report.main([str(bad), "--check"]) == 1


def test_obs_report_renders_supervisor_trail(tmp_path, capsys):
    """Satellite: a tool="supervisor" log renders its launch/restart/
    give-up trail (with resumed_from_step) instead of the empty and
    misleading chunk-attribution table."""
    report = _load_script("obs_report_sup_t", "scripts/obs_report.py")
    path = str(tmp_path / "sup.supervisor.jsonl")
    with trace.TraceWriter(path) as w:
        w.write_manifest(trace.build_manifest(
            "supervisor", {"stencil": "life", "grid": [64, 64]}))
        w.event("launch", attempt=0, resume=False, resumed_from_step=None)
        w.event("restart", attempt=0, reason="heartbeat verdict WEDGED",
                detail="injected", backoff_s=0.2, checkpoint_step=30)
        w.event("launch", attempt=1, resume=True, resumed_from_step=30)
        w.event("summary", ok=True, attempts=2, restarts=1,
                resumed_from_step=30)
    assert report.main([path]) == 0
    out = capsys.readouterr().out
    assert "supervisor trail (2 launch(es), 1 restart(s))" in out
    assert "heartbeat verdict WEDGED" in out
    assert "resume" in out and "30" in out
    assert "supervisor summary: ok=True" in out
    # the misleading blocks are gone: no empty attribution table
    assert "attribution (predicted vs measured)" not in out
    assert "runtime  chunks=" not in out

    # a give-up trail renders too (the other way a supervisor ends)
    path2 = str(tmp_path / "gu.supervisor.jsonl")
    with trace.TraceWriter(path2) as w:
        w.write_manifest(trace.build_manifest("supervisor", {}))
        w.event("launch", attempt=0, resume=False, resumed_from_step=None)
        w.event("give_up", attempts=1, reason="wall-clock stall",
                restarts=0)
    assert report.main([path2]) == 0
    assert "GIVE UP" in capsys.readouterr().out


def test_obs_report_check_validates_retry_sibling(tmp_path, capsys):
    """Satellite: the pallas-retry sibling log (PATH.retry.jsonl,
    written by cli.run's auto-retry) is validated by --check when
    present — on the same schema, with the same nonzero-exit rule."""
    report = _load_script("obs_report_retry_t", "scripts/obs_report.py")
    main_log = tmp_path / "run.jsonl"
    with trace.TraceWriter(str(main_log)) as w:
        w.write_manifest(trace.build_manifest("cli", {"x": 1}))
        w.event("error", error="Mosaic exploded")
    retry = tmp_path / "run.jsonl.retry.jsonl"
    with trace.TraceWriter(str(retry)) as w:
        w.write_manifest(trace.build_manifest("cli", {"x": 1}))
        w.event("summary", mcells_per_s=1.0)
    assert report.main([str(main_log), "--check"]) == 0
    out = capsys.readouterr().out
    assert "retry sibling" in out

    # an off-schema sibling fails the gate even when the main log is ok
    retry.write_text('{"kind": "manifest"}\n')
    assert report.main([str(main_log), "--check"]) == 1
