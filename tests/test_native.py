"""Native host runtime tests: async .npy writer + C++ differential engines."""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_cuda_process_tpu import make_step, make_stencil
from mpi_cuda_process_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def test_async_npy_roundtrip(tmp_path):
    arrs = {
        "a": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "b": np.arange(6, dtype=np.int32).reshape(3, 2),
        "c": np.random.default_rng(0).random((5,)).astype(np.float64),
    }
    for name, a in arrs.items():
        native.async_write_npy(str(tmp_path / f"{name}.npy"), a)
    native.wait_all()
    for name, a in arrs.items():
        got = np.load(tmp_path / f"{name}.npy")
        np.testing.assert_array_equal(got, a)
        assert got.dtype == a.dtype


def test_async_write_failure_surfaces():
    native.async_write_npy("/nonexistent_dir_xyz/f.npy",
                           np.zeros(3, np.float32))
    with pytest.raises(IOError):
        native.wait_all()


def test_life_differential_native_vs_jax():
    """Three independent implementations agree: C++, numpy golden, JAX."""
    rng = np.random.default_rng(5)
    g = rng.integers(0, 2, (20, 30)).astype(np.int32)
    g[0] = g[-1] = 0
    g[:, 0] = g[:, -1] = 0
    st = make_stencil("life")
    step = make_step(st, g.shape)
    jax_out, cpp_out = (jnp.asarray(g),), g
    for _ in range(5):
        jax_out = step(jax_out)
        cpp_out = native.life_step_native(cpp_out)
    np.testing.assert_array_equal(np.asarray(jax_out[0]), cpp_out)


def test_heat3d_differential_native_vs_jax():
    rng = np.random.default_rng(6)
    g = (rng.random((10, 12, 14)) * 50).astype(np.float32)
    st = make_stencil("heat3d", alpha=1 / 6)
    step = make_step(st, g.shape)
    jax_out, cpp_out = (jnp.asarray(g),), g
    for _ in range(3):
        jax_out = step(jax_out)
        cpp_out = native.heat3d_step_native(cpp_out, 1 / 6)
    np.testing.assert_allclose(
        np.asarray(jax_out[0]), cpp_out, rtol=1e-5, atol=1e-4)


def _differential_2d(name, params, native_fn, steps=3, atol=1e-4):
    rng = np.random.default_rng(7)
    g = (rng.random((12, 18)) * 40).astype(np.float32)
    st = make_stencil(name, **params)
    step = make_step(st, g.shape)
    jax_out, cpp_out = (jnp.asarray(g),), g
    for _ in range(steps):
        jax_out = step(jax_out)
        cpp_out = native_fn(cpp_out)
    np.testing.assert_allclose(
        np.asarray(jax_out[0]), cpp_out, rtol=1e-5, atol=atol)


def test_heat2d_differential_native_vs_jax():
    _differential_2d(
        "heat2d", {"alpha": 0.25},
        lambda g: native.heat2d_step_native(g, 0.25))


def test_advect2d_differential_native_vs_jax():
    _differential_2d(
        "advect2d", {"cx": 0.4, "cy": -0.3},
        lambda g: native.advect2d_step_native(g, -0.3, 0.4))


def test_sor2d_differential_native_vs_jax():
    """Gauss-Seidel semantics match between the multi-phase JAX step and the
    sequential C++ sweep (red values fresh within the step)."""
    _differential_2d(
        "sor2d", {"omega": 1.6},
        lambda g: native.sor2d_step_native(g, 1.6))


def test_wave2d_differential_native_vs_jax():
    """Two-field leapfrog carry: the C++ engine returns new u and the
    caller carries old u as the next u_prev — same contract as the scan."""
    rng = np.random.default_rng(9)
    u = (rng.random((12, 18)) * 2 - 1).astype(np.float32)
    up = (rng.random((12, 18)) * 2 - 1).astype(np.float32)
    st = make_stencil("wave2d", c2dt2=0.25)
    step = make_step(st, u.shape)
    jax_out = (jnp.asarray(u), jnp.asarray(up))
    cpp_u, cpp_up = u, up
    for _ in range(3):
        jax_out = step(jax_out)
        cpp_u, cpp_up = native.wave2d_step_native(cpp_u, cpp_up, 0.25), cpp_u
    np.testing.assert_allclose(np.asarray(jax_out[0]), cpp_u,
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(jax_out[1]), cpp_up,
                               rtol=1e-5, atol=1e-4)


def test_grayscott2d_differential_native_vs_jax():
    """Coupled two-field reaction-diffusion, both fields halo'd."""
    rng = np.random.default_rng(10)
    u = (rng.random((12, 18)) * 0.5 + 0.5).astype(np.float32)
    v = (rng.random((12, 18)) * 0.3).astype(np.float32)
    p = dict(du=0.16, dv=0.08, f=0.035, kappa=0.06)
    st = make_stencil("grayscott2d", **p)
    step = make_step(st, u.shape)
    jax_out = (jnp.asarray(u), jnp.asarray(v))
    cpp_u, cpp_v = u, v
    for _ in range(3):
        jax_out = step(jax_out)
        cpp_u, cpp_v = native.grayscott2d_step_native(cpp_u, cpp_v, **p)
    np.testing.assert_allclose(np.asarray(jax_out[0]), cpp_u,
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(np.asarray(jax_out[1]), cpp_v,
                               rtol=1e-5, atol=1e-4)


def test_heat3d27_differential_native_vs_jax():
    """Full 27-point footprint (face/edge/corner weight classes)."""
    rng = np.random.default_rng(11)
    g = (rng.random((10, 12, 14)) * 50).astype(np.float32)
    st = make_stencil("heat3d27", alpha=0.15)
    step = make_step(st, g.shape)
    jax_out, cpp_out = (jnp.asarray(g),), g
    for _ in range(3):
        jax_out = step(jax_out)
        cpp_out = native.heat3d27_step_native(cpp_out, 0.15)
    np.testing.assert_allclose(
        np.asarray(jax_out[0]), cpp_out, rtol=1e-5, atol=1e-3)
