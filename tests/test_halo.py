"""Unit tests for the ppermute halo exchange (SURVEY.md §4.2).

Strategy: build a globally-known array, shard it over a mesh axis with
shard_map, run the exchange, and check every shard's padded block against
slices of the (constant-padded) global array.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from mpi_cuda_process_tpu.parallel.halo import exchange_and_pad
from mpi_cuda_process_tpu.parallel.mesh import make_mesh

try:
    shard_map = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map


@pytest.mark.parametrize("n_shards,halo", [(2, 1), (4, 1), (2, 2), (4, 3)])
def test_exchange_1d_decomposition(n_shards, halo):
    bc = -7.0
    g = np.arange(16 * 5, dtype=np.float32).reshape(16, 5)
    mesh = make_mesh((n_shards,))
    local = 16 // n_shards

    def f(x):
        return exchange_and_pad(x, ("sx", None), (n_shards, 1), halo, bc)

    out = shard_map(f, mesh=mesh, in_specs=P("sx", None),
                    out_specs=P("sx", None))(jnp.asarray(g))
    # Reassemble per-shard padded blocks and compare to global padded slices.
    gp = np.pad(g, halo, constant_values=bc)
    out = np.asarray(out).reshape(n_shards, local + 2 * halo, 5 + 2 * halo)
    for i in range(n_shards):
        want = gp[i * local:i * local + local + 2 * halo, :]
        np.testing.assert_array_equal(out[i], want)


@pytest.mark.parametrize("mesh_shape", [(2, 2), (2, 4), (4, 2)])
def test_exchange_2d_corners(mesh_shape):
    """Two-pass axis-wise exchange must deliver corner data (27-point needs)."""
    halo, bc = 1, 0.0
    g = np.arange(8 * 8, dtype=np.float32).reshape(8, 8)
    mesh = make_mesh(mesh_shape)
    ly, lx = 8 // mesh_shape[0], 8 // mesh_shape[1]

    def f(x):
        return exchange_and_pad(
            x, ("sx", "sy"), mesh_shape, halo, bc)

    out = shard_map(f, mesh=mesh, in_specs=P("sx", "sy"),
                    out_specs=P("sx", "sy"))(jnp.asarray(g))
    gp = np.pad(g, halo, constant_values=bc)
    out = np.asarray(out).reshape(
        mesh_shape[0], ly + 2, mesh_shape[1], lx + 2).transpose(0, 2, 1, 3)
    for i in range(mesh_shape[0]):
        for j in range(mesh_shape[1]):
            want = gp[i * ly:i * ly + ly + 2, j * lx:j * lx + lx + 2]
            np.testing.assert_array_equal(out[i, j], want)


def test_exchange_periodic_wraps():
    g = np.arange(8, dtype=np.float32).reshape(8, 1)
    mesh = make_mesh((4,))

    def f(x):
        return exchange_and_pad(x, ("sx", None), (4, 1), 1, 0.0, periodic=True)

    out = shard_map(f, mesh=mesh, in_specs=P("sx", None),
                    out_specs=P("sx", None))(jnp.asarray(g))
    out = np.asarray(out).reshape(4, 4, 3)
    # shard 0's left halo is global row 7; shard 3's right halo is global row 0
    assert out[0, 0, 1] == 7.0
    assert out[3, -1, 1] == 0.0
