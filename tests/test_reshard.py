"""parallel/reshard.py: live no-gather relayout between mesh shapes.

The migration seam's whole contract in three pins (ISSUE 15):

1. **Bit-exact movement** — resharding a field from any supported mesh
   onto any other lands exactly the bytes a direct scatter of the host
   array onto the target would, for f32 AND bf16 (pure data movement:
   no arithmetic may touch the values).
2. **No host gather, ever** — the traced relayout contains zero
   ``all_gather`` eqns, exactly ``plan.n_comm_rounds`` ppermutes per
   field, and no shard_map-body intermediate as large as the global
   array (``utils.jaxprcheck.assert_reshard_structure``).  The
   sharded -> unsharded direction is refused outright.
3. **Mid-flight equivalence** — step K times under mesh A, reshard,
   step K more under mesh B == the uninterrupted mesh-B run == the
   unsharded run, for halo-1 (heat3d) and halo-2 (heat3d4th) stencils.

Runs on 8 virtual CPU devices (conftest.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.parallel import (
    make_mesh,
    make_sharded_step,
    plan_reshard,
    reshard_fields,
    shard_fields,
)
from mpi_cuda_process_tpu.parallel.reshard import make_reshard
from mpi_cuda_process_tpu.utils import jaxprcheck

# Every ordered pair of 8-device 2-D decompositions: slab <-> slab,
# slab <-> 2-axis, 2-axis <-> 2-axis (transpose), all directions.
_SHAPES_2D = [(8, 1), (1, 8), (2, 4), (4, 2)]
PAIRS_2D = [(s, d) for s in _SHAPES_2D for d in _SHAPES_2D if s != d]

# 3-D coverage: axis moves, 1-axis <-> 3-axis, asymmetric 2-axis.
PAIRS_3D = [
    ((8, 1, 1), (1, 1, 8)),
    ((1, 8, 1), (2, 2, 2)),
    ((2, 2, 2), (1, 1, 8)),
    ((2, 1, 4), (4, 1, 2)),
]


def _host_fields(shape, dtype, n=2):
    """Fields with every element distinct — any misrouted atom shows."""
    size = int(np.prod(shape))
    return tuple(
        jnp.arange(i * size, (i + 1) * size, dtype=jnp.float32)
        .reshape(shape).astype(dtype)
        for i in range(n))


def _assert_moved_exactly(host, src_mesh, dst_mesh, ndim, ensemble=0):
    src = shard_fields(host, src_mesh, ndim, ensemble=bool(ensemble))
    got = reshard_fields(src, src_mesh, dst_mesh, ndim,
                         ensemble=ensemble)
    want = shard_fields(host, dst_mesh, ndim, ensemble=bool(ensemble))
    for g, w, h in zip(got, want, host):
        assert np.array_equal(np.asarray(g), np.asarray(h))
        assert g.sharding.shard_shape(g.shape) == \
            w.sharding.shard_shape(w.shape)


@pytest.mark.parametrize("src,dst", PAIRS_2D,
                         ids=[f"{s}->{d}" for s, d in PAIRS_2D])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_roundtrip_2d(src, dst, dtype):
    host = _host_fields((16, 16), dtype)
    _assert_moved_exactly(host, make_mesh(src), make_mesh(dst), 2)


@pytest.mark.parametrize("src,dst", PAIRS_3D,
                         ids=[f"{s}->{d}" for s, d in PAIRS_3D])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16],
                         ids=["f32", "bf16"])
def test_roundtrip_3d(src, dst, dtype):
    host = _host_fields((8, 8, 8), dtype)
    _assert_moved_exactly(host, make_mesh(src), make_mesh(dst), 3)


def test_roundtrip_there_and_back():
    """A -> B -> A is the identity on the bytes (f32 and bf16)."""
    for dtype in (jnp.float32, jnp.bfloat16):
        host = _host_fields((16, 16), dtype)
        a, b = make_mesh((8, 1)), make_mesh((2, 4))
        out = reshard_fields(
            reshard_fields(shard_fields(host, a, 2), a, b, 2), b, a, 2)
        for o, h in zip(out, host):
            assert np.array_equal(np.asarray(o), np.asarray(h))


def test_ensemble_repack():
    """The member axis is one more array axis to the planner: spatial
    repacking under a fixed ensemble split, and ensemble -> spatial."""
    host = _host_fields((4, 8, 8), jnp.float32)  # 4 members, 2-D grid
    a = make_mesh((2, 1), ensemble=2)
    b = make_mesh((1, 2), ensemble=2)
    _assert_moved_exactly(host, a, b, 2, ensemble=4)
    c = make_mesh((1, 1), ensemble=4)
    d = make_mesh((2, 2), ensemble=1)
    _assert_moved_exactly(host, c, d, 2, ensemble=4)


def test_identity_is_a_noop_plan():
    a = make_mesh((2, 4))
    b = make_mesh((2, 4))
    assert plan_reshard((16, 16), a, b, 2) is None
    host = _host_fields((16, 16), jnp.float32)
    out = reshard_fields(shard_fields(host, a, 2), a, b, 2)
    for o, h in zip(out, host):
        assert np.array_equal(np.asarray(o), np.asarray(h))


def test_unsharded_edges():
    """None = unsharded: both-None identity, scatter in, gather REFUSED."""
    host = _host_fields((16, 16), jnp.float32)
    assert reshard_fields(host, None, None, 2) == tuple(host)
    mesh = make_mesh((2, 4))
    out = reshard_fields(host, None, mesh, 2)
    for o, h in zip(out, host):
        assert np.array_equal(np.asarray(o), np.asarray(h))
    with pytest.raises(ValueError, match="host gather"):
        reshard_fields(out, mesh, None, 2)


@pytest.mark.parametrize("src,dst", [((8, 1), (1, 8)), ((2, 4), (4, 2)),
                                     ((1, 8), (2, 4))],
                         ids=["slab-flip", "transpose", "slab-to-2axis"])
def test_jaxpr_no_gather_gate(src, dst):
    """The headline gate: zero all_gather, exact ppermute count, no
    full-grid intermediate inside any shard_map body."""
    host = _host_fields((16, 16), jnp.float32)
    a, b = make_mesh(src), make_mesh(dst)
    plan = plan_reshard((16, 16), a, b, 2)
    assert plan is not None and plan.n_comm_rounds > 0
    fields = shard_fields(host, a, 2)
    fn = make_reshard(plan, len(fields))
    closed = jax.make_jaxpr(fn)(fields)
    jaxprcheck.assert_reshard_structure(closed, plan, len(fields))


@pytest.mark.parametrize("stencil,grid,src,dst", [
    ("heat3d", (16, 16, 16), (1, 1, 8), (8, 1, 1)),      # halo 1
    # halo-2 (4th-order) compile is the single slowest item in the
    # default tier; the halo-1 leg pins the seam, depth-2 rides slow
    pytest.param("heat3d4th", (16, 16, 16), (4, 1, 1), (1, 1, 4),
                 marks=pytest.mark.slow),                # halo 2
], ids=["halo1", "halo2"])
def test_midflight_migration_bitexact(stencil, grid, src, dst):
    """step K under A, reshard, step K under B == uninterrupted B run
    == unsharded run — the driver adoption seam's core promise."""
    st = make_stencil(stencil)
    host = init_state(st, grid, seed=11)
    k = 3

    ref_step = make_step(st, grid)
    ref = tuple(host)
    for _ in range(2 * k):
        ref = ref_step(ref)

    mesh_a, mesh_b = make_mesh(src), make_mesh(dst)
    step_a = make_sharded_step(st, mesh_a, grid)
    step_b = make_sharded_step(st, mesh_b, grid)

    un = shard_fields(host, mesh_b, st.ndim)
    for _ in range(2 * k):
        un = step_b(un)

    mig = shard_fields(host, mesh_a, st.ndim)
    for _ in range(k):
        mig = step_a(mig)
    mig = reshard_fields(mig, mesh_a, mesh_b, st.ndim)
    for _ in range(k):
        mig = step_b(mig)

    for m, u, r in zip(mig, un, ref):
        assert np.array_equal(np.asarray(m), np.asarray(u)), \
            "migrated run != uninterrupted target-mesh run (bit-exact)"
        np.testing.assert_allclose(np.asarray(m), np.asarray(r),
                                   rtol=2e-5, atol=2e-6)


def test_mismatched_device_counts_refused():
    a = make_mesh((2, 2))   # 4 devices
    b = make_mesh((8, 1))   # 8 devices
    with pytest.raises(ValueError, match="equal device counts"):
        plan_reshard((16, 16), a, b, 2)


# ------------------------------------------------------------------
# Member-axis repack (ISSUE 17): the serving defrag seam.  Same
# lcm-atom matching machinery, applied to the MEMBER axis: migrate
# occupied slots between capacities without a checkpoint round-trip,
# bit-exact, never a host gather.

from mpi_cuda_process_tpu.parallel import plan_member_repack, \
    repack_members
from mpi_cuda_process_tpu.parallel.reshard import make_member_repack


def _members(n, grid=(8, 8), dtype=jnp.float32):
    """n members with every element distinct across the whole batch."""
    size = int(np.prod(grid))
    return tuple(
        jnp.arange(f * n * size, (f + 1) * n * size, dtype=jnp.float32)
        .reshape((n,) + grid).astype(dtype)
        for f in range(2))


def _check_repack(host, out, slot_map, n_dst):
    """Moved slots carry exactly their source bytes; the rest is
    zero-padded ballast."""
    for h, o in zip(host, out):
        o = np.asarray(o)
        h = np.asarray(h)
        assert o.shape[0] == n_dst
        moved = set(slot_map.values())
        for s, d in slot_map.items():
            assert np.array_equal(o[d], h[s]), f"slot {s}->{d}"
        for d in range(n_dst):
            if d not in moved:
                assert not np.asarray(o[d]).any(), f"ballast slot {d}"


def test_member_repack_local_defrag():
    """No member sharding: shrink 8 -> 4 with a partial-occupancy mask
    is a pure local row shuffle (zero collectives — pinned below)."""
    host = _members(8)
    slot_map = {1: 0, 3: 1, 6: 2}
    out = repack_members(host, slot_map, 4)
    _check_repack(host, out, slot_map, 4)
    plan = plan_member_repack(8, 4, slot_map)
    assert not plan.collective and plan.n_comm_rounds == 0
    closed = jax.make_jaxpr(make_member_repack(plan, len(host)))(host)
    jaxprcheck.assert_member_repack_structure(closed, plan, len(host))


def test_member_repack_local_grow():
    """Up the ladder: 2 occupied of 4 -> capacity 8, slots scattered."""
    host = _members(4)
    slot_map = {0: 5, 2: 1}
    out = repack_members(host, slot_map, 8)
    _check_repack(host, out, slot_map, 8)


def test_member_repack_spatial_mesh():
    """A spatially-sharded class (member axis NOT device-sharded):
    the repack runs inside shard_map over the spatial mesh and is
    still a zero-collective row shuffle."""
    mesh = make_mesh((2, 4))
    host = _members(8, grid=(16, 16))
    fields = shard_fields(host, mesh, 2, ensemble=True)
    slot_map = {0: 0, 5: 1, 7: 2}
    plan = plan_member_repack(8, 4, slot_map, mesh=mesh, grid_ndim=2)
    assert not plan.collective and plan.n_comm_rounds == 0
    out = repack_members(fields, slot_map, 4, mesh=mesh)
    _check_repack(host, out, slot_map, 4)
    closed = jax.make_jaxpr(make_member_repack(plan, len(host)))(fields)
    jaxprcheck.assert_member_repack_structure(
        closed, plan, len(host), grid_shape=(16, 16))


@pytest.mark.parametrize("n_src,n_dst,slot_map", [
    (8, 4, {4: 0, 5: 1, 6: 2, 7: 3}),   # all moves cross groups
    (8, 4, {1: 0, 2: 1, 5: 2, 7: 3}),   # mixed local + cross
    (4, 8, {0: 7, 1: 2, 2: 5}),         # grow, scattered targets
], ids=["cross", "mixed", "grow"])
def test_member_repack_ensemble_sharded(n_src, n_dst, slot_map):
    """Member axis sharded over 4 ensemble groups: cross-group slot
    moves ride ppermute rounds (exact count pinned), dummy-padded
    rounds never clobber occupied destinations, zero all_gather."""
    mesh = make_mesh((2, 1), ensemble=4)
    host = _members(n_src, grid=(8, 8))
    fields = shard_fields(host, mesh, 2, ensemble=True)
    plan = plan_member_repack(n_src, n_dst, slot_map, mesh=mesh,
                              grid_ndim=2)
    assert plan.collective
    out = repack_members(fields, slot_map, n_dst, mesh=mesh)
    _check_repack(host, out, slot_map, n_dst)
    closed = jax.make_jaxpr(make_member_repack(plan, len(host)))(fields)
    info = jaxprcheck.assert_member_repack_structure(
        closed, plan, len(host), grid_shape=(8, 8))
    assert info["n_all_gather"] == 0


def test_member_repack_there_and_back():
    """Shrink A -> B then grow B -> A with the inverse map restores
    every surviving member to its original slot, bit-exact."""
    for mesh, kw in ((None, {}), (make_mesh((2, 1), ensemble=4),
                                  {"grid_ndim": 2})):
        host = _members(8, grid=(8, 8))
        fields = host if mesh is None else \
            shard_fields(host, mesh, 2, ensemble=True)
        down = {1: 0, 4: 1, 6: 2, 7: 3}
        up = {d: s for s, d in down.items()}
        mid = repack_members(fields, down, 4, mesh=mesh, **kw)
        back = repack_members(mid, up, 8, mesh=mesh, **kw)
        for h, b in zip(host, back):
            b = np.asarray(b)
            for s in down:
                assert np.array_equal(b[s], np.asarray(h[s]))
            for s in range(8):
                if s not in down:
                    assert not b[s].any()


def test_member_repack_trajectory_bitexact():
    """Mid-flight defrag: step a partially-occupied batch, repack the
    survivors down, keep stepping — every survivor's trajectory stays
    bit-identical to its uninterrupted solo run (the serving
    scheduler's shrink contract)."""
    st = make_stencil("life")
    grid = (16, 16)
    occupied = {0: 11, 2: 23, 5: 37}          # slot -> seed
    solo_step = make_step(st, grid)
    k = 3

    inits = {s: init_state(st, grid, seed=seed)
             for s, seed in occupied.items()}
    n_f = len(next(iter(inits.values())))
    batch = tuple(
        jnp.stack([np.asarray(inits[s][f]) if s in inits else
                   np.zeros(grid, np.asarray(inits[0][f]).dtype)
                   for s in range(6)])
        for f in range(n_f))
    vstep = jax.vmap(solo_step)
    for _ in range(k):
        batch = vstep(batch)
    slot_map = {s: i for i, s in enumerate(sorted(occupied))}
    batch = repack_members(batch, slot_map, 4)
    for _ in range(k):
        batch = vstep(batch)

    for s, seed in occupied.items():
        ref = inits[s]
        for _ in range(2 * k):
            ref = solo_step(ref)
        for f in range(n_f):
            assert np.array_equal(np.asarray(batch[f][slot_map[s]]),
                                  np.asarray(ref[f])), \
                f"survivor seed={seed} diverged across the repack"


def test_member_repack_validation():
    with pytest.raises(ValueError, match="unique"):
        plan_member_repack(4, 2, {0: 0, 1: 0})
    with pytest.raises(ValueError, match="outside"):
        plan_member_repack(4, 2, {5: 0})
    with pytest.raises(ValueError, match="outside"):
        plan_member_repack(4, 2, {0: 3})
    mesh = make_mesh((1, 1), ensemble=4)
    with pytest.raises(ValueError, match="divide"):
        plan_member_repack(6, 4, {0: 0}, mesh=mesh)
