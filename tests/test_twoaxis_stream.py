"""Two-axis streaming (sliding-window) kernel == the plain sharded step.

``make_sharded_fused_step(kind="stream")`` on a mesh that shards y
(2-axis ``(2, 2, 1)`` or y-only ``(1, 2, 1)``) now builds the 2-axis
sliding-window kernel (``streamfused.build_stream_2axis_call``: y slabs
+ the four two-pass-composed corner pieces spliced into the sliding
window in place of the unsharded clamp) instead of returning None — the
last kind x mesh gap, which silently excluded the lowest-traffic kernel
class from the balanced surface-to-volume decompositions.  Pinned here:

  * value equivalence vs the plain sharded step / the unsharded
    reference on (2, 2, 1) and (1, 2, 1) for heat3d (single field),
    wave3d (leapfrog carry), and sor3d (red-black parity across BOTH
    shard origins), incl. multi-strip grids (traced edge selects) and
    the x-windowed strip variant (the config-5 wave fit);
  * ``overlap=True`` composition: same values, and the interior
    pallas_call provably free of ppermute deps (jaxpr reachability —
    the existing test pattern from test_overlap_fused.py);
  * periodic is DECLINED, never silently fallen back from (the
    streaming kernels are guard-frame only — a forced kind must raise
    at the caller, not measure a different kernel class);
  * the builder chain actually selects the streaming kernel
    (``_padfree_kind == "stream_yz"`` introspection).

Every equivalence case runs >= 2 fused calls, so the second call's
slabs AND corners come from the first call's spliced outputs — a
wrong-corner-neighbor bug cannot survive two exchanges.
"""

import numpy as np
import pytest

import jax

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

from test_overlap_fused import _interior_depends_on_ppermute


def _assert_close(got, ref, atol):
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g, np.float32),
                                   np.asarray(r, np.float32),
                                   rtol=0, atol=atol)


def _build_stream(name, grid, mesh_shape, k, overlap=False, tiles=None,
                  **kw):
    """Forced 2-axis streaming step; ``tiles`` pins explicit strip
    geometry through the builder (the multi-strip / x-window cases the
    auto picker's one-big-strip preference would otherwise never
    exercise at test sizes)."""
    st = make_stencil(name, **kw)
    mesh = make_mesh(mesh_shape)
    if tiles is not None:
        from mpi_cuda_process_tpu.ops.pallas import streamfused as SF

        orig = SF.build_stream_2axis_call
        # the stepper now always passes tiles= (variant plumbing), so the
        # forced geometry must REPLACE it, not collide with it
        SF.build_stream_2axis_call = \
            lambda *a, **k2: orig(*a, **{**k2, "tiles": tiles})
    try:
        step = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                       kind="stream", overlap=overlap)
    finally:
        if tiles is not None:
            SF.build_stream_2axis_call = orig
    assert step is not None, (name, grid, mesh_shape)
    assert getattr(step, "_padfree_kind", None) == "stream_yz", \
        "2-axis stream builder unexpectedly declined"
    if overlap:
        assert getattr(step, "_overlap_active", False), \
            "overlap geometry unexpectedly declined — fix the test shape"
    return st, mesh, step


def _run_stream(st, mesh, step, fields, calls):
    got = shard_fields(fields, mesh, 3)
    jf = jax.jit(step)
    for _ in range(calls):
        got = jf(got)
    return got


def test_yz_stream_matches_plain_sharded_step():
    """The acceptance anchor: on a (2, 2, 1) mesh the forced streaming
    stepper — with AND without overlap — equals the plain sharded step
    (same mesh, k single steps per fused call) to 1e-6."""
    st = make_stencil("heat3d")
    grid, k, calls = (48, 32, 128), 4, 2
    mesh = make_mesh((2, 2, 1))
    fields = init_state(st, grid, seed=9, kind="pulse")

    plain = jax.jit(make_sharded_step(st, mesh, grid))
    ref = shard_fields(fields, mesh, 3)
    for _ in range(k * calls):
        ref = plain(ref)

    _, _, stream = _build_stream("heat3d", grid, (2, 2, 1), k)
    _assert_close(_run_stream(st, mesh, stream, fields, calls), ref, 1e-6)
    _, _, ov = _build_stream("heat3d", grid, (2, 2, 1), k, overlap=True)
    _assert_close(_run_stream(st, mesh, ov, fields, calls), ref, 1e-6)


# Remaining equivalences compare against the unsharded reference step
# (one cheap compile; sharded == unsharded is pinned by
# tests/test_sharded.py).  wave3d carries the two-field leapfrog;
# sor3d's red-black parity must stay consistent across BOTH shard
# origins (z AND y feed the in-kernel coloring).  Shapes respect the
# streaming gates: local z >= 3 chunks of >= 2*wm planes.
@pytest.mark.parametrize("name,grid,mesh_shape,k", [
    ("wave3d", (48, 32, 128), (2, 2, 1), 4),
    # sor3d x 2-axis stream rides the slow tier (a ~12s compile, the
    # file's heaviest): the default tier keeps every ingredient of the
    # composition covered — red-black parity in the STREAMING window
    # via test_streamfused::test_sor3d_parity, parity across BOTH shard
    # origins via test_twoaxis_padfree's default sor3d (2,2,1) row, and
    # 2-axis stream value equivalence via the heat3d/wave3d rows here —
    # so only the triple-composition itself moves out of the budget.
    pytest.param("sor3d", (96, 32, 128), (2, 2, 1), 4,   # wm = 2k = 8
                 marks=pytest.mark.slow),
    ("heat3d", (24, 32, 128), (1, 2, 1), 4),      # y-only: z bc dummies
    pytest.param("wave3d", (24, 32, 128), (1, 2, 1), 4,
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (48, 32, 128), (1, 2, 1), 4,
                 marks=pytest.mark.slow),
])
def test_yz_stream_matches_unsharded(name, grid, mesh_shape, k):
    st, mesh, step = _build_stream(name, grid, mesh_shape, k)
    fields = init_state(st, grid, seed=9, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, grid))
    for _ in range(2 * k):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 2), ref, 1e-5)


def test_yz_stream_multi_strip_edge_selects():
    """ny > 1 strips: the edge splice is select-based on the traced
    strip id (the auto picker prefers one big strip at test sizes, so
    explicit tiles force the multi-strip geometry)."""
    st, mesh, step = _build_stream("heat3d", (48, 64, 128), (2, 2, 1), 4,
                                   tiles=(8, 8))  # local Ly=32 -> ny=4
    fields = init_state(st, (48, 64, 128), seed=11, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, (48, 64, 128)))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 2), ref, 1e-5)


@pytest.mark.slow
def test_yz_stream_xwindowed_strips():
    """x-windowed strips on a 2-axis mesh (the config-5 two-field fit):
    slab, y-slab, AND corner DMAs all slice the lane axis."""
    grid = (48, 64, 768)
    st, mesh, step = _build_stream("heat3d", grid, (2, 2, 1), 4,
                                   tiles=(8, 8, 256))
    fields = init_state(st, grid, seed=21, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, grid))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 2), ref, 1e-5)


@pytest.mark.slow
def test_yz_stream_xwindowed_wave_two_fields():
    grid = (48, 32, 768)
    st, mesh, step = _build_stream("wave3d", grid, (2, 2, 1), 4,
                                   tiles=(8, 16, 256))
    fields = init_state(st, grid, seed=21, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, grid))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 2), ref, 1e-5)


def test_yz_stream_bf16_k4():
    """bf16 at k=4 on a 2-axis mesh: the streaming alignment advantage
    (sublane-rounded margins, no 2m block granularity) carries over —
    the tiled 2-axis kernels need k=8 for bf16."""
    import jax.numpy as jnp

    st, mesh, step = _build_stream("heat3d", (48, 32, 128), (2, 2, 1), 4,
                                   dtype=jnp.bfloat16)
    fields = init_state(st, (48, 32, 128), seed=9, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, (48, 32, 128)))
    for _ in range(4):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 1), ref, 0.05)


@pytest.mark.slow
def test_yz_stream_overlap_matches_unsharded():
    """Overlap on BOTH axes: slab+corner ppermutes feed only the shells,
    the interior streams from bc-dummy slab operands."""
    st, mesh, step = _build_stream("wave3d", (48, 32, 128), (2, 2, 1), 4,
                                   overlap=True)
    fields = init_state(st, (48, 32, 128), seed=9, kind="pulse")
    ref = fields
    ref_step = jax.jit(make_step(st, (48, 32, 128)))
    for _ in range(8):
        ref = ref_step(ref)
    _assert_close(_run_stream(st, mesh, step, fields, 2), ref, 1e-5)


def test_yz_stream_overlap_interior_free_of_collective_permute():
    """The overlap composition's whole point, asserted structurally
    (the existing jaxpr-reachability pattern): the 2-axis streaming
    interior pallas_call is unreachable from ANY collective-permute
    output — z slabs, y slabs, and the two-hop corner ppermutes all
    feed only the boundary shells — while the step as a whole does
    exchange."""
    grid = (48, 32, 128)
    st, mesh, over = _build_stream("heat3d", grid, (2, 2, 1), 4,
                                   overlap=True)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    # (a) the exported interior path traces with no collective at all
    txt = str(jax.make_jaxpr(over._interior_step)(fields))
    assert "ppermute" not in txt
    # (b) the REAL step's interior pallas_call is unreachable from any
    # ppermute output
    local = (grid[0] // 2, grid[1] // 2, grid[2])
    assert not _interior_depends_on_ppermute(over, fields, local)
    assert "ppermute" in str(jax.make_jaxpr(over)(fields))


def test_yz_stream_declines_periodic_and_bad_geometry():
    """A forced kind must never silently fall back: periodic (the
    streaming kernels are guard-frame only) and untileable local shapes
    return None so cli raises."""
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 2, 1))
    assert make_sharded_fused_step(st, mesh, (48, 32, 128), 4,
                                   interpret=True, kind="stream",
                                   periodic=True) is None
    # local z = 8: fewer than 3 chunks of >= 2*wm planes
    assert make_sharded_fused_step(st, mesh, (16, 32, 128), 4,
                                   interpret=True, kind="stream") is None


def test_yz_stream_bf16_multi_strip_gate():
    """Multi-strip grids require by >= wm_a (the splice assumes
    strip-uniform window origins): a bf16 explicit (8, 8) tile
    (wm_a = 16 > by) must be rejected, not silently mis-spliced."""
    import jax.numpy as jnp

    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        build_stream_2axis_call,
    )

    st = make_stencil("heat3d", dtype=jnp.bfloat16)
    assert build_stream_2axis_call(st, (24, 32, 128), (48, 64, 128), 4,
                                   tiles=(8, 8), interpret=True) is None
    # the single-strip candidate at the same shape is fine
    assert build_stream_2axis_call(st, (24, 32, 128), (48, 64, 128), 4,
                                   tiles=(8, 32),
                                   interpret=True) is not None
