"""Every stencil op vs its pure-numpy golden implementation (SURVEY.md §4.1)."""

import numpy as np
import pytest

import jax.numpy as jnp

from mpi_cuda_process_tpu import make_step, make_stencil

import golden


def _rng(seed=0):
    return np.random.default_rng(seed)


def _run_steps(st, fields, n, grid_shape):
    step = make_step(st, grid_shape)
    for _ in range(n):
        fields = step(fields)
    return fields


def test_life_matches_golden():
    g = _rng(1).integers(0, 2, size=(12, 17)).astype(np.int32)
    g[0, :] = g[-1, :] = g[:, 0] = g[:, -1] = 0
    st = make_stencil("life")
    got = _run_steps(st, (jnp.asarray(g),), 4, g.shape)[0]
    want = g
    for _ in range(4):
        want = golden.life_step(want)
    np.testing.assert_array_equal(np.asarray(got), want)


@pytest.mark.parametrize("shape,name,alpha", [
    ((10, 14), "heat2d", 0.25),
    ((6, 7, 9), "heat3d", 1.0 / 6.0),
])
def test_heat_matches_golden(shape, name, alpha):
    g = _rng(2).random(shape).astype(np.float32) * 50
    st = make_stencil(name, alpha=alpha)
    got = _run_steps(st, (jnp.asarray(g),), 3, shape)[0]
    want = g.astype(np.float64)
    for _ in range(3):
        want = golden.heat_step(want, alpha)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-4)


def test_heat27_matches_golden():
    shape = (6, 7, 8)
    g = _rng(3).random(shape).astype(np.float32) * 10
    st = make_stencil("heat3d27", alpha=0.15)
    got = _run_steps(st, (jnp.asarray(g),), 2, shape)[0]
    want = g.astype(np.float64)
    for _ in range(2):
        want = golden.heat27_step(want, 0.15)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-4)


def test_wave_matches_golden():
    shape = (8, 9, 7)
    u = _rng(4).random(shape).astype(np.float32)
    up = _rng(5).random(shape).astype(np.float32)
    # pin frames so the state is self-consistent
    for a in (u, up):
        a[0], a[-1], a[:, 0], a[:, -1] = 0, 0, 0, 0
        a[:, :, 0] = a[:, :, -1] = 0
    st = make_stencil("wave3d", c2dt2=0.1)
    got = _run_steps(st, (jnp.asarray(u), jnp.asarray(up)), 3, shape)
    wu, wup = u.astype(np.float64), up.astype(np.float64)
    for _ in range(3):
        wu, wup = golden.wave_step(wu, wup, 0.1)
    np.testing.assert_allclose(np.asarray(got[0]), wu, rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(np.asarray(got[1]), wup, rtol=2e-5, atol=2e-4)


def test_frame_is_pinned():
    """Frame cells must hold their initial values forever (Dirichlet walls)."""
    shape = (9, 9)
    st = make_stencil("heat2d")
    g = np.zeros(shape, np.float32)
    g[0, :] = g[-1, :] = g[:, 0] = g[:, -1] = 100.0
    got = np.asarray(_run_steps(st, (jnp.asarray(g),), 10, shape)[0])
    np.testing.assert_array_equal(got[0, :], 100.0)
    np.testing.assert_array_equal(got[-1, :], 100.0)
    np.testing.assert_array_equal(got[:, 0], 100.0)
    np.testing.assert_array_equal(got[:, -1], 100.0)
    assert got[1:-1, 1:-1].max() > 0  # heat flowed inward


def test_odd_sizes_fully_computed():
    """Grids not divisible by any tile size still update every interior cell.

    Guards against the reference's silent coverage gap: truncating
    ``n_blocks = size/512`` leaves the last ``size mod 512`` cells never
    computed (kernel.cu:195-196, SURVEY.md C17).
    """
    shape = (13, 19)
    st = make_stencil("heat2d", alpha=0.25)
    g = np.full(shape, 1.0, np.float32)
    g[0, :] = g[-1, :] = g[:, 0] = g[:, -1] = 0.0
    got = np.asarray(_run_steps(st, (jnp.asarray(g),), 1, shape)[0])
    # every interior cell adjacent to the cold frame must have cooled
    assert got[1, 1] < 1.0 and got[-2, -2] < 1.0 and got[-2, 1] < 1.0


def test_heat4th_matches_golden():
    shape = (8, 9, 10)
    g = _rng(6).random(shape).astype(np.float32) * 10
    st = make_stencil("heat3d4th", alpha=0.05)
    got = _run_steps(st, (jnp.asarray(g),), 2, shape)[0]
    want = g.astype(np.float64)
    for _ in range(2):
        want = golden.heat4th_step(want, 0.05)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-4)
    # halo-2 frame: outer TWO cells pinned
    np.testing.assert_array_equal(np.asarray(got)[:2], g[:2])
