"""serving/router.py: the fleet front door (ISSUE 17).

The multi-replica contract in five pins:

1. **Affinity** — the second job of a size class lands on the class's
   affine replica and triggers zero backend compiles there (the
   single-engine resident-step pin, lifted through the router).
2. **Zero lost jobs through a kill** — a replica killed mid-stream
   loses nothing: its unresolved jobs rebalance to survivors from
   their ORIGINAL configs (deterministic rerun => bit-exact result),
   the supervised restart brings the replica back as generation+1,
   and the router's final stats say so in numbers.
3. **Aggregate admission** — a job is rejected only when EVERY live
   replica's admission controller refuses; the reject carries the
   aggregate arithmetic.  ``unsupported`` refusals never fall through.
4. **One fleet status** — the router log + N replica-tagged scheduler
   logs roll up into per-replica rows (the ``obs_top`` fleet panel's
   source) under schema-validated manifests.
5. **SLO hygiene** — a cancelled request (the rebalance mechanism)
   rides its own counter and never lands in the engine's
   ttfc/latency histograms.

Plus the elastic-ladder shrink seam (scheduler side): a class that
outlives its peak live-repacks down the ladder — the ``shrink`` event
fires, gauges reconcile, and the surviving tenant's result stays
bit-exact vs its solo run.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu.cancellation import RunCancelled  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
from mpi_cuda_process_tpu.engine import SimulationEngine  # noqa: E402
from mpi_cuda_process_tpu.obs import aggregate as agg_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import runtime as runtime_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import trace as trace_lib  # noqa: E402
from mpi_cuda_process_tpu import serving  # noqa: E402
from mpi_cuda_process_tpu.serving import (  # noqa: E402
    AdmissionError, ServingRouter)


def _cfg(seed=0, grid=(16, 16), iters=16, **kw):
    return RunConfig(stencil="heat2d", grid=grid, iters=iters,
                     seed=seed, **kw)


def _solo(cfg):
    fields, _ = cli.run(cfg)
    return tuple(np.asarray(f) for f in fields)


def _wait_first_chunk(h, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline and not h.done():
        inner = h._inner
        if inner is not None and \
                inner.timings.get("time_to_first_chunk_s") is not None:
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------ routing

def test_affinity_second_job_zero_compiles(tmp_path):
    """Pin 1: the class's second job hits its warm affine replica."""
    r = ServingRouter(replicas=2, ladder=(2,), cadence=8,
                      telemetry_dir=str(tmp_path))
    try:
        ha = r.submit(_cfg(seed=1), tenant="a")
        ha.result(300)
        seen = runtime_lib.compile_events_seen()
        hb = r.submit(_cfg(seed=2), tenant="b")
        hb.result(300)
        assert hb.replica == ha.replica, \
            "second job of a class must route to its affine replica"
        assert runtime_lib.compile_events_seen() == seen, \
            "second job of a class must compile NOTHING anywhere"
    finally:
        stats = r.close()
    assert stats["jobs_done"] == 2 and stats["lost_jobs"] == 0


def test_kill_rebalances_and_restarts_zero_lost(tmp_path):
    """Pin 2: SIGKILL mid-stream -> rebalance + supervised restart,
    zero lost jobs, and the rerun's bytes match the solo run."""
    r = ServingRouter(replicas=3, ladder=(1, 2), cadence=8,
                      restart_backoff=0.05, telemetry_dir=str(tmp_path))
    try:
        warm = r.submit(_cfg(seed=3))
        warm.result(300)
        victim_cfg = _cfg(seed=4, iters=60000)
        h = r.submit(victim_cfg)
        target = h.replica
        assert _wait_first_chunk(h), "victim never started computing"
        assert not h.done(), "victim finished before the kill"
        assert r.kill_replica(target)
        fields, _ = h.result(600)
        assert h.resubmits >= 1 and h.replica != target
        want = _solo(victim_cfg)
        for a, b in zip(fields, want):
            assert np.array_equal(np.asarray(a), b), \
                "rebalanced rerun must be bit-exact vs the solo run"
        deadline = time.time() + 15
        while time.time() < deadline and \
                not r.replicas()[target]["alive"]:
            time.sleep(0.05)
        rep = r.replicas()[target]
        assert rep["alive"] and rep["generation"] == 1, \
            "supervised restart must bring the replica back"
        after = r.submit(_cfg(seed=5))
        after.result(300)
    finally:
        stats = r.close()
    assert stats["lost_jobs"] == 0
    assert stats["jobs_done"] == 3
    assert stats["rebalanced"] >= 1
    assert stats["restarts"] == 1


def test_kill_dead_or_unknown_replica_is_false(tmp_path):
    r = ServingRouter(replicas=1, max_restarts=0,
                      telemetry_dir=str(tmp_path))
    try:
        assert not r.kill_replica("nope")
        assert r.kill_replica("r0")
        assert not r.kill_replica("r0"), "already dead"
    finally:
        r.close(drain=False, timeout=10)


# ---------------------------------------------------------- admission

def test_aggregate_admission_rejects_only_when_all_refuse(tmp_path):
    """Pin 3: the reject is the AGGREGATE verdict."""
    r = ServingRouter(replicas=2, ladder=(2,), hbm_bytes=1,
                      telemetry_dir=str(tmp_path))
    try:
        with pytest.raises(AdmissionError) as ei:
            r.submit(_cfg(seed=1))
        assert ei.value.reason == "over_budget"
        assert "aggregate" in str(ei.value)
    finally:
        stats = r.close()
    assert stats["rejects"] == 1 and stats["jobs_done"] == 0


def test_unsupported_never_falls_through(tmp_path):
    """A categorical refusal re-raises from the FIRST replica: trying
    the others would just repeat it."""
    r = ServingRouter(replicas=2, telemetry_dir=str(tmp_path))
    try:
        with pytest.raises(AdmissionError) as ei:
            r.submit(_cfg(seed=1, resume="/nonexistent"))
        assert ei.value.reason == "unsupported"
    finally:
        r.close()


# ------------------------------------------------------- fleet status

def test_aggregate_status_has_replica_rows(tmp_path):
    """Pin 4: router + replica logs roll into one hosts table with a
    row per replica, under schema-valid manifests."""
    r = ServingRouter(replicas=3, ladder=(2,), cadence=8,
                      telemetry_dir=str(tmp_path))
    try:
        hs = [r.submit(_cfg(seed=s, grid=(16, 16 + 16 * (s % 2))))
              for s in range(4)]
        for h in hs:
            h.result(300)
        paths = [r.telemetry_path] + [
            rep["telemetry"] for rep in r.replicas().values()]
    finally:
        r.close()
    for p in paths[1:]:
        with open(p) as fh:
            manifest = json.loads(fh.readline())
        trace_lib.validate_manifest(manifest)
        assert manifest["replica"] in ("r0", "r1", "r2")
    status = agg_lib.aggregate_logs(paths)
    rows = [row for row in status["hosts"] if row.get("replica")]
    assert len(rows) == 3, \
        f"one fleet row per replica, got {[r.get('key') for r in rows]}"
    busy = [row for row in rows if row.get("scheduler")]
    assert busy, "replica rows must carry the folded scheduler block"
    sched = busy[0]["scheduler"]
    assert sched.get("size_classes"), \
        "fleet rows must carry the per-class table for the obs_top panel"


def test_router_events_fold_into_status(tmp_path):
    """The router's own log folds: route counters + liveness gauges +
    the last death, rendered by the obs_top fleet panel."""
    from mpi_cuda_process_tpu.obs import metrics as metrics_lib

    r = ServingRouter(replicas=2, ladder=(1, 2), cadence=8,
                      restart_backoff=0.05, max_restarts=0,
                      telemetry_dir=str(tmp_path))
    try:
        h = r.submit(_cfg(seed=7, iters=60000))
        assert _wait_first_chunk(h)
        r.kill_replica(h.replica)
        h.result(600)
    finally:
        r.close()
    rm = metrics_lib.RunMetrics()
    for rec in agg_lib.iter_records(r.telemetry_path):
        rm.ingest(rec)
    rt = rm.status().get("router")
    assert rt, "router events must fold into status()['router']"
    assert rt["counts"].get("route", 0) >= 1
    assert rt["counts"].get("rebalance", 0) >= 1
    assert rt["counts"].get("replica_dead", 0) == 1
    assert rt["last_death"]["replica"] == "r0" or \
        rt["last_death"]["replica"] == "r1"
    assert rt["replicas_total"] == 2


# ------------------------------------------------------- SLO hygiene

def test_cancelled_requests_excluded_from_latency_histograms(tmp_path):
    """Pin 5 (engine level): cancel rides its own counter; the
    ttfc/latency histograms only ever see non-cancelled requests."""
    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    done = eng.submit(_cfg(seed=1, iters=4))
    done.result(timeout=300)
    with eng.metrics.lock:
        lat = eng.metrics.histogram("engine_request_latency_s", "")
        base_lat, base_count = lat.count, \
            eng.metrics.counter("engine_requests_total", "").value
    victim = eng.submit(_cfg(seed=2, iters=200000, log_every=8))
    while victim.started_at is None and not victim.done():
        time.sleep(0.01)
    victim.cancel()
    with pytest.raises(RunCancelled):
        victim.result(timeout=300)
    with eng.metrics.lock:
        assert eng.metrics.counter(
            "engine_requests_cancelled_total", "").value == 1
        assert eng.metrics.counter(
            "engine_requests_total", "").value == base_count + 1
        assert eng.metrics.histogram(
            "engine_request_latency_s", "").count == base_lat, \
            "a cancelled request must NOT land in the latency histogram"
        assert eng.metrics.histogram(
            "engine_time_to_first_chunk_s", "").count <= base_lat


# ------------------------------------------------------ ladder shrink

def test_ladder_shrink_fires_and_survivor_stays_bit_exact(tmp_path):
    """Shrink seam: a class grown for a burst repacks down the ladder
    once occupancy falls — the ``shrink`` event lands, gauges
    reconcile, and the long-lived survivor's bytes never notice."""
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1, 2, 4), cadence=4,
                                shrink_after_rounds=3)
    burst = [eng.submit(_cfg(seed=s, iters=24), tenant=f"b{s}")
             for s in (1, 2, 3)]
    survivor_cfg = _cfg(seed=9, iters=40000)
    survivor = eng.submit(survivor_cfg, tenant="long")
    for h in burst:
        h.result(timeout=300)
    fields, _ = survivor.result(timeout=600)
    stats = eng.close()
    assert stats["shrinks"] >= 1, \
        f"occupancy fell to 1 of 4 with nobody waiting: {stats}"
    assert stats["jobs_done"] == 4
    [cls] = stats["class_table"]
    assert cls["capacity"] < 4, "the ladder must have come back down"
    assert cls["occupied"] == 0
    want = _solo(survivor_cfg)
    for a, b in zip(fields, want):
        assert np.array_equal(np.asarray(a), b), \
            "survivor of a live shrink must stay bit-exact vs solo"
    ops = [e for e in agg_lib.iter_records(eng.telemetry_path)
           if e.get("kind") == "scheduler" and e.get("op") == "shrink"]
    assert ops and all(op.get("capacity") < 4 for op in ops)


def test_shrink_disabled_at_zero(tmp_path):
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1, 2), cadence=4,
                                shrink_after_rounds=0)
    hs = [eng.submit(_cfg(seed=s, iters=24)) for s in (1, 2)]
    long = eng.submit(_cfg(seed=3, iters=20000))
    for h in hs:
        h.result(timeout=300)
    long.result(timeout=600)
    stats = eng.close()
    assert stats["shrinks"] == 0
    [cls] = stats["class_table"]
    assert cls["capacity"] == 2, "shrink_after_rounds=0 must disable"
