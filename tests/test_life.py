"""Game of Life structural tests: still lifes, oscillators, spaceships."""

import numpy as np

import jax.numpy as jnp

from mpi_cuda_process_tpu import make_step, make_stencil


def _grid(shape, coords):
    g = np.zeros(shape, np.int32)
    for y, x in coords:
        g[y, x] = 1
    return g


def _steps(g, n):
    st = make_stencil("life")
    step = make_step(st, g.shape)
    f = (jnp.asarray(g),)
    for _ in range(n):
        f = step(f)
    return np.asarray(f[0])


def test_block_still_life():
    g = _grid((8, 8), [(3, 3), (3, 4), (4, 3), (4, 4)])
    np.testing.assert_array_equal(_steps(g, 5), g)


def test_blinker_oscillates():
    h = _grid((7, 7), [(3, 2), (3, 3), (3, 4)])
    v = _grid((7, 7), [(2, 3), (3, 3), (4, 3)])
    np.testing.assert_array_equal(_steps(h, 1), v)
    np.testing.assert_array_equal(_steps(h, 2), h)


def test_glider_translates():
    glider = [(1, 2), (2, 3), (3, 1), (3, 2), (3, 3)]
    g = _grid((12, 12), glider)
    out = _steps(g, 4)
    want = _grid((12, 12), [(y + 1, x + 1) for y, x in glider])
    np.testing.assert_array_equal(out, want)


def test_dead_frame_kills_edge_growth():
    """The guard frame is dead and stays dead (kernel.cu:137-138 semantics)."""
    g = np.ones((6, 6), np.int32)
    g[0, :] = g[-1, :] = g[:, 0] = g[:, -1] = 0
    out = _steps(g, 3)
    assert out[0, :].sum() == 0 and out[:, 0].sum() == 0
    assert out[-1, :].sum() == 0 and out[:, -1].sum() == 0
