"""Streaming fused kernel == k plain steps (interpret mode).

Same contract as tests/test_fused.py: ``make_stream_fused_step`` must be
semantically identical to k applications of ``driver.make_step`` —
guard-frame pinning, multi-field carries, red-black parity, halo-2
margins, and bf16 at k=4 (the streaming kernel's alignment advantage
over the tiled kernels, which require bf16 k=8).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_cuda_process_tpu import init_state, make_step, make_stencil
from mpi_cuda_process_tpu.ops.pallas.streamfused import (
    make_stream_fused_step,
)


def _equiv(name, grid, k, dtype=None, tiles=None, steps=None, tol=1e-4,
           **params):
    """Same contract as tests/test_fused.py: k>1 windows accumulate in a
    different (window-local) association order, so a few-ULP atol; k=1
    (tol=0) is bit-exact."""
    kw = dict(params)
    if dtype is not None:
        kw["dtype"] = dtype
    st = make_stencil(name, **kw)
    stream = make_stream_fused_step(st, grid, k, tiles=tiles,
                                    interpret=True)
    assert stream is not None, f"stream kernel declined {name} {grid} k={k}"
    plain = make_step(st, grid)
    fields = init_state(st, grid, kind="auto", seed=7)
    ref = fields
    for _ in range(steps or k):
        ref = plain(ref)
    got = fields
    for _ in range((steps or k) // k):
        got = stream(got)
    for g, r in zip(got, ref):
        if tol:
            np.testing.assert_allclose(np.asarray(g, dtype=np.float32),
                                       np.asarray(r, dtype=np.float32),
                                       rtol=0, atol=tol)
        else:
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_heat3d_k1_bitexact():
    _equiv("heat3d", (24, 32, 128), 1, tiles=(8, 16), tol=0.0)


def test_heat3d():
    _equiv("heat3d", (24, 32, 128), 4)


def test_heat3d_two_passes():
    _equiv("heat3d", (24, 32, 128), 4, steps=8)


def test_heat3d_uneven_extents():
    # Z not a multiple of the largest chunk; Y larger than one strip
    _equiv("heat3d", (40, 64, 128), 4)


def test_heat3d_bf16_k4():
    """bf16 at k=4: impossible for the tiled kernels (sublane-16 forces
    k=8 there); the streaming kernel only needs the margin ROUNDED to the
    sublane tile, not the block offsets."""
    _equiv("heat3d", (24, 64, 128), 4, dtype=jnp.bfloat16)


def test_heat3d_explicit_tiles():
    _equiv("heat3d", (24, 32, 128), 4, tiles=(8, 16))


def test_heat3d_rejects_bad_tiles():
    st = make_stencil("heat3d")
    # 2*wm > bz
    assert make_stream_fused_step(st, (24, 32, 128), 4, tiles=(4, 16),
                                  interpret=True) is None
    # fewer than 3 chunks
    assert make_stream_fused_step(st, (16, 32, 128), 4, tiles=(8, 16),
                                  interpret=True) is None


def test_wave3d_two_fields():
    _equiv("wave3d", (24, 32, 128), 4)


@pytest.mark.slow
def test_grayscott3d_coupled_fields():
    _equiv("grayscott3d", (24, 32, 128), 4)


@pytest.mark.slow
def test_advect3d():
    _equiv("advect3d", (24, 32, 128), 4)


@pytest.mark.slow
def test_heat3d27():
    _equiv("heat3d27", (24, 32, 128), 4)


def test_heat3d4th_halo2():
    # halo 2: wm = 2k = 8 -> bz >= 16, Z >= 48
    _equiv("heat3d4th", (48, 32, 128), 4)


def test_sor3d_parity():
    # red-black: wm = 2k (phase-aware margins); parity from global coords
    _equiv("sor3d", (48, 32, 128), 4)


def test_xwindowed_strips_match():
    """Explicit (bz, by, bx) tiles window the lane axis too (the config-5
    two-field fit): clamped x shells, wrap garbage excluded by validity."""
    _equiv("heat3d", (24, 32, 768), 4, tiles=(8, 16, 256))


def test_xwindowed_wave_two_fields():
    _equiv("wave3d", (24, 32, 768), 4, tiles=(8, 16, 256))


@pytest.mark.slow
def test_xwindowed_degenerate_window_covers_whole_x():
    # wx == X exactly: every x program clamps to xlo=0 and re-reads the
    # whole row — redundant but must stay correct
    _equiv("heat3d", (24, 32, 512), 4, tiles=(8, 16, 256))


@pytest.mark.slow
def test_xwindowed_wider_lane_extent():
    _equiv("heat3d", (24, 32, 1024), 4, tiles=(8, 16, 512))


def test_xwindowed_rejects_bad_bx():
    st = make_stencil("heat3d")
    # bx not a lane-tile multiple / no room for the shells
    assert make_stream_fused_step(st, (24, 32, 768), 4, tiles=(8, 16, 200),
                                  interpret=True) is None
    assert make_stream_fused_step(st, (24, 32, 256), 4, tiles=(8, 16, 256),
                                  interpret=True) is None


def test_pick_strip_never_offers_xwindow_past_shell_margin(monkeypatch):
    """_pick_strip must never return an x-windowed strip when the window
    margin exceeds the 128-lane shell (wm > _XSHELL), because
    _stream_gates rejects that class outright instead of retrying other
    geometries (round-4 advisor).  TODAY the bz ladder (max 32) makes
    every wm > 128 candidate fail the 2*wm <= bz gate before x_options
    matters, so the filter is exercised by growing the ladder past
    2*_XSHELL — the exact future change that would make it live."""
    from mpi_cuda_process_tpu.ops.pallas import streamfused as sf

    wm = sf._XSHELL + 8  # margin one step past the shell
    wm_a = wm            # already sublane-aligned for f32
    # current ladder: no z-chunk can host 2*wm planes — no strip at all,
    # so the explicit-tiles path in _stream_gates is the only live check
    assert sf._pick_strip(4096, 4096, 32768, wm, wm_a, 4, 1) is None
    # the one configuration where the filter is load-bearing: a grown
    # ladder hosts the margin, whole-lane strips exceed the VMEM budget
    # (X very wide), and an x-window would FIT — verified: (512, 64, 256)
    # lives at ~4.98 GB vs whole-lane ~318 GB.  The picker must decline
    # rather than offer the x-window _stream_gates rejects outright.
    monkeypatch.setattr(sf, "_BZ_LADDER", (512,))
    monkeypatch.setattr(sf, "_VMEM_LIMIT", 5 * 10**9)
    assert sf._strip_live_bytes(512, 64, 256, 32768, wm, wm_a, 4, 1,
                                False) < 5 * 10**9  # x-window would fit
    assert sf._pick_strip(4096, 4096, 32768, wm, wm_a, 4, 1) is None


def test_config5_wave_constructs_via_x_windowing():
    """The config-5 gap closed: two-field wave3d at the 64-chip local
    shape (64, 4096, 4096) exceeds the whole-lane VMEM gate but tiles
    with an x-windowed strip — total read amplification ~1.9x vs the
    wide-X tiled kernel's 4.5x."""
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        build_stream_sharded_call,
    )

    wave = make_stencil("wave3d")
    built = build_stream_sharded_call(wave, (64, 4096, 4096), (4096,) * 3,
                                      4, interpret=True)
    assert built is not None


def test_declines_2d_and_unknown():
    assert make_stream_fused_step(make_stencil("heat2d"), (64, 128), 4,
                                  interpret=True) is None
    assert make_stream_fused_step(make_stencil("life"), (64, 64), 4,
                                  interpret=True) is None


def _sharded_equiv(name, grid, mesh_shape, k, steps=None, **kw):
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil(name, **kw)
    fields = init_state(st, grid, seed=9, kind="pulse")
    ref = fields
    step = jax.jit(make_step(st, grid))
    n = steps or k
    for _ in range(n):
        ref = step(ref)
    mesh = make_mesh(mesh_shape)
    stream = make_sharded_fused_step(st, mesh, grid, k, interpret=True,
                                     kind="stream")
    assert stream is not None, f"sharded stream declined {name} {grid}"
    got = shard_fields(fields, mesh, 3)
    run = jax.jit(stream)
    for _ in range(n // k):
        got = run(got)
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=0, atol=1e-4)


def test_sharded_stream_matches_unsharded():
    """z-decomposed streaming (slab operands, global-origin frame) must
    match the unsharded plain run — the config-5 execution candidate."""
    _sharded_equiv("heat3d", (48, 32, 128), (2, 1, 1), 4)


def test_sharded_stream_two_passes():
    # slab values must be re-exchanged between passes
    _sharded_equiv("heat3d", (48, 32, 128), (2, 1, 1), 4, steps=8)


@pytest.mark.slow
def test_sharded_stream_four_shards():
    _sharded_equiv("heat3d", (96, 32, 128), (4, 1, 1), 4)


@pytest.mark.slow
def test_sharded_stream_wave_two_fields():
    _sharded_equiv("wave3d", (48, 32, 128), (2, 1, 1), 4)


@pytest.mark.slow
def test_sharded_stream_sor_parity():
    # wm = 2k: global parity must stay consistent across shard origins
    _sharded_equiv("sor3d", (96, 32, 128), (2, 1, 1), 4)


@pytest.mark.slow
def test_sharded_stream_xwindowed():
    """Sharded + x-windowed: slab strips slice the lane axis too."""
    from mpi_cuda_process_tpu import make_mesh, shard_fields
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        build_stream_sharded_call,
    )
    from mpi_cuda_process_tpu.parallel import stepper as stepper_lib

    st = make_stencil("heat3d")
    grid, mesh_shape, k = (48, 32, 768), (2, 1, 1), 4
    mesh = make_mesh(mesh_shape)
    axis_names, counts = stepper_lib._resolve_mesh_axes(3, mesh)
    local = tuple(g // c for g, c in zip(grid, counts))
    # force x-windowed tiles through the internal builder path
    step = stepper_lib._make_zslab_padfree_step(
        st, mesh, grid, local, axis_names, counts, k,
        lambda *a, **kw: build_stream_sharded_call(
            *a, tiles=(8, 16, 256), **kw),
        (1, 1), True, False)
    assert step is not None
    fields = init_state(st, grid, seed=9, kind="pulse")
    ref = fields
    plain = jax.jit(make_step(st, grid))
    for _ in range(k):
        ref = plain(ref)
    got = jax.jit(step)(shard_fields(fields, mesh, 3))
    for g, r in zip(got, ref):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   rtol=0, atol=1e-4)


def test_sharded_stream_y_mesh_builds_and_periodic_declines():
    """Round 8: a y-sharded mesh no longer declines — it routes to the
    2-axis sliding-window kernel (tests/test_twoaxis_stream.py carries
    the equivalence suite); periodic stays a hard decline on every mesh
    (the streaming kernels are guard-frame only)."""
    from mpi_cuda_process_tpu import make_mesh
    from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

    st = make_stencil("heat3d")
    step = make_sharded_fused_step(
        st, make_mesh((1, 2, 1)), (48, 64, 128), 4, interpret=True,
        kind="stream")
    assert step is not None
    assert getattr(step, "_padfree_kind", None) == "stream_yz"
    assert make_sharded_fused_step(
        st, make_mesh((2, 1, 1)), (48, 32, 128), 4, interpret=True,
        kind="stream", periodic=True) is None
