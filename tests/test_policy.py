"""policy/select.py: measurement-driven auto-policy + live adoption.

The ``--auto-policy`` contract, pinned (ISSUE 15):

* **measured beats predicted, categorically** — one modest ledger row
  outranks every roofline prediction; with no measured candidate the
  roofline ranks the field; a ledger that says nothing applicable
  leaves the requested config in place.
* **explicit flags always win** — a non-default mode flag is locked
  through resolution and recorded in ``overrides``.
* **determinism** — ties rank on ``(-value, label)``, and the ledger
  side (``best_known``) has a total tie-order: same winner from any
  row permutation (satellite 1's pin).
* **the decision is a record** — the CLI emits a ``policy`` manifest
  event carrying decision/provenance/n_devices, the serving scheduler
  resolves at admission (resolved == explicit submission, same class),
  and ``perf_gate --policy-check`` replays the record against the
  current ledger.
* **live migration** — ``--policy-recheck`` + ``POLICY_INJECT`` flips
  the measured winner mid-run: a ``migrate`` event fires at a chunk
  boundary and the final fields bit-match the uninterrupted run under
  the target mesh.

Runs on 8 virtual CPU devices (conftest.py).
"""

import dataclasses
import itertools
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu import serving  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.policy import select as ps  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_policy_env(monkeypatch):
    monkeypatch.delenv("POLICY_INJECT", raising=False)
    ps._INJECT_FIRED.clear()
    yield
    ps._INJECT_FIRED.clear()


def _seed(ledger_path, cfg, value, backend="cpu", source="seed",
          measured_at=None):
    """One measured ``ok`` row whose identity matches ``cfg`` exactly."""
    label, _ = ps._ledger_identity(cfg, backend)
    row = ledger_lib.make_row(
        label, value, source=source,
        measured_at=measured_at if measured_at is not None else time.time(),
        backend=backend,
        flags=ledger_lib._flags(dataclasses.asdict(cfg)))
    ledger_lib.append_rows([row], ledger_path)
    return label


def _events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def _cfg(**kw):
    kw.setdefault("stencil", "heat3d")
    kw.setdefault("grid", (16, 16, 16))
    kw.setdefault("iters", 40)
    kw.setdefault("log_every", 10)
    return RunConfig(**kw)


# ------------------------------------------------------------ satellite 1

def test_best_known_tiebreak_total_order():
    """Equal-value rows: winner is the max (measured_at, key_id,
    source) — identical from every permutation of the row list."""
    c = _cfg()
    label, _ = ps._ledger_identity(c, "cpu")
    flags = ledger_lib._flags(dataclasses.asdict(c))
    rows = [ledger_lib.make_row(label, 100.0, source=s, measured_at=t,
                                backend="cpu", flags=flags)
            for s, t in (("run-b", 100.0), ("run-a", 200.0),
                         ("run-b", 200.0))]
    winners = set()
    for perm in itertools.permutations(rows):
        best = ledger_lib.best_known(list(perm))
        assert len(best) == 1
        (w,) = best.values()
        winners.add((w["measured_at"], w["source"]))
    assert winners == {(200.0, "run-b")}


# ------------------------------------------------------------- resolve

def test_measured_beats_predicted(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    winner = dataclasses.replace(_cfg(), mesh=(1, 1, 8))
    # 1 Mcell/s: far below every roofline prediction — measured must
    # still win categorically
    _seed(led, winner, 1.0)
    d = ps.resolve(_cfg(), backend="cpu", ledger_path=led)
    assert d.provenance == "measured"
    assert d.config.mesh == (1, 1, 8)
    assert d.value == 1.0
    assert d.n_devices == 8
    assert d.overrides == {}


def test_predicted_fallback_on_empty_ledger(tmp_path):
    led = str(tmp_path / "none.jsonl")
    d = ps.resolve(_cfg(), backend="cpu", ledger_path=led)
    assert d.provenance == "predicted"
    assert d.value is not None and d.value > 0
    assert d.table and d.table[0]["label"] == d.label


def test_explicit_flags_always_win(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    _seed(led, dataclasses.replace(_cfg(), mesh=(1, 1, 8)), 900.0)
    d = ps.resolve(_cfg(mesh=(2, 2, 2)), backend="cpu", ledger_path=led)
    assert d.config.mesh == (2, 2, 2)
    assert "mesh" in d.overrides and d.overrides["mesh"] == [2, 2, 2]


def test_tie_ranks_on_label(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    la = _seed(led, dataclasses.replace(_cfg(), mesh=(1, 1, 8)), 700.0)
    lb = _seed(led, dataclasses.replace(_cfg(), mesh=(8, 1, 1)), 700.0)
    assert la < lb  # mesh1x1x8 sorts before mesh8x1x1
    d1 = ps.resolve(_cfg(), backend="cpu", ledger_path=led)
    d2 = ps.resolve(_cfg(), backend="cpu", ledger_path=led)
    assert d1.label == d2.label == la
    assert d1.config.mesh == (1, 1, 8)


def test_adoptable_never_changes_fuse(tmp_path):
    led = str(tmp_path / "none.jsonl")
    c = _cfg(fuse=3, iters=39, log_every=39)
    d = ps.resolve(c, backend="cpu", ledger_path=led,
                   locked=frozenset(), adoptable=True)
    assert "fuse" not in ps.ADOPTABLE_FIELDS
    assert d.config.fuse == 3


# ----------------------------------------------------------- cli wiring

def test_cli_records_policy_event(tmp_path, monkeypatch):
    led = str(tmp_path / "ledger.jsonl")
    tel = str(tmp_path / "run.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", led)
    _seed(led, dataclasses.replace(_cfg(), mesh=(8, 1, 1)), 500.0)
    cli.run(_cfg(auto_policy=True, telemetry=tel))
    evs = _events(tel)
    pol = [e for e in evs if e["kind"] == "policy"]
    assert len(pol) == 1
    ev = pol[0]
    assert ev["decision"]["mesh"] == [8, 1, 1]
    assert ev["provenance"] == "measured"
    assert ev["n_devices"] == 8
    assert ev["requested"]["mesh"] == []
    assert ev["overrides"] == {}
    # the manifest records the RESOLVED config — the run that happened
    assert evs[0]["kind"] == "manifest"
    assert list(evs[0]["run"]["mesh"]) == [8, 1, 1]


def test_policy_recheck_requires_auto_policy():
    with pytest.raises(ValueError, match="auto.policy|auto_policy"):
        cli.run(_cfg(policy_recheck=1))


def test_perf_gate_policy_check(tmp_path, monkeypatch):
    """--policy-check: 0 while the decision matches the ledger winner,
    1 after the ledger moves (replayed with the RECORDED n_devices —
    the subprocess itself only sees one CPU device)."""
    led = str(tmp_path / "ledger.jsonl")
    tel = str(tmp_path / "run.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", led)
    _seed(led, dataclasses.replace(_cfg(), mesh=(8, 1, 1)), 500.0)
    cli.run(_cfg(auto_policy=True, telemetry=tel))

    gate = os.path.join(_REPO, "scripts", "perf_gate.py")
    r = subprocess.run([sys.executable, gate, tel, "--policy-check",
                        "--ledger", led], capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr

    _seed(led, dataclasses.replace(_cfg(), mesh=(1, 1, 8)), 900.0)
    r = subprocess.run([sys.executable, gate, tel, "--policy-check",
                        "--ledger", led], capture_output=True, text=True)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "STALE" in r.stdout + r.stderr


# ------------------------------------------------------- live migration

@pytest.mark.slow
def test_injected_winner_migrates_bitexact(tmp_path, monkeypatch):
    """POLICY_INJECT flips the measured winner at step 20: the run
    launches on (8,1,1), migrates at the step-20 boundary, and the
    final fields bit-match an uninterrupted (1,1,8) run."""
    led = str(tmp_path / "ledger.jsonl")
    tel = str(tmp_path / "run.jsonl")
    inj = str(tmp_path / "inject.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", led)
    _seed(led, dataclasses.replace(_cfg(), mesh=(8, 1, 1)), 500.0)
    target = dataclasses.replace(_cfg(), mesh=(1, 1, 8))
    label2, _ = ps._ledger_identity(target, "cpu")
    ledger_lib.append_rows([ledger_lib.make_row(
        label2, 900.0, source="inject", measured_at=time.time(),
        backend="cpu",
        flags=ledger_lib._flags(dataclasses.asdict(target)))], inj)
    monkeypatch.setenv("POLICY_INJECT", f"step=20:{inj}")

    fields, _ = cli.run(_cfg(auto_policy=True, policy_recheck=1,
                             telemetry=tel))
    evs = _events(tel)
    mig = [e for e in evs if e["kind"] == "migrate"]
    assert len(mig) == 1
    assert mig[0]["step"] == 20
    assert mig[0]["dst"]["mesh"] == [1, 1, 8]
    assert mig[0]["rounds"] > 0

    want, _ = cli.run(_cfg(mesh=(1, 1, 8)))
    for g, w in zip(fields, want):
        assert np.array_equal(np.asarray(g), np.asarray(w)), \
            "migrated run != uninterrupted target-mesh run"


# ------------------------------------------------------------- serving

@pytest.mark.slow
def test_serving_resolves_at_admission(tmp_path, monkeypatch):
    """An auto-policy submission resolves BEFORE the class signature:
    it shares the resident class with the equivalent explicit job, and
    the job log records the policy event."""
    led = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("OBS_LEDGER_PATH", led)
    base = RunConfig(stencil="heat2d", grid=(32, 32), iters=8)
    _seed(led, dataclasses.replace(base, mesh=(2, 4)), 250.0)

    eng = serving.ServingEngine(telemetry_dir=str(tmp_path / "serve"))
    ha = eng.submit(dataclasses.replace(base, auto_policy=True))
    hb = eng.submit(dataclasses.replace(base, mesh=(2, 4), seed=5))
    got, _ = ha.result(timeout=300)
    hb.result(timeout=300)
    stats = eng.close()
    assert stats["classes"] == 1, \
        "resolved submission must share the explicit job's size class"

    pol = []
    for name in os.listdir(str(tmp_path / "serve")):
        if name.endswith(".jsonl"):
            pol += [e for e in _events(str(tmp_path / "serve" / name))
                    if e.get("kind") == "policy"]
    assert len(pol) == 1 and pol[0]["decision"]["mesh"] == [2, 4]

    want, _ = cli.run(dataclasses.replace(base, mesh=(2, 4)))
    for g, w in zip(got, want):
        assert np.array_equal(np.asarray(g), np.asarray(w))
