"""--debug-checks: checkify sanitizer with step-localized NaN detection.

SURVEY.md §5.2: the reference ships real races and OOB reads with no
sanitizer; JAX removes those classes structurally, and the remaining
numerical failure mode (NaN/Inf blow-up) gets checkify instrumentation here —
every step checked inside the jitted scan, first failure wins, error message
names the exact failing step.
"""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import checkify

from mpi_cuda_process_tpu import driver
from mpi_cuda_process_tpu.cli import run
from mpi_cuda_process_tpu.config import RunConfig


def test_checked_runner_names_first_failing_step():
    """A synthetic overflow at a known step is reported at THAT step."""

    def step(fields):
        (u,) = fields
        return (u * 1e10,)

    runner = driver.make_checked_runner(step, 8)
    u0 = (jnp.full((4, 4), 1.0, jnp.float32),)
    # 1e10^k: steps 0..2 give 1e10/1e20/1e30 (finite), step 3 gives 1e40=inf
    with pytest.raises(checkify.JaxRuntimeError) as ei:
        runner(u0)
    assert "non-finite after step 3" in str(ei.value)


def test_checked_runner_passes_through_healthy_state():
    def step(fields):
        return (fields[0] * 0.5,)

    runner = driver.make_checked_runner(step, 4)
    out = runner((jnp.full((4, 4), 16.0, jnp.float32),))
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)


def test_checked_runner_uses_absolute_start_step():
    """Chunk/resume offsets must show up in the reported step index."""

    def step(fields):
        return (fields[0] * 1e10,)

    runner = driver.make_checked_runner(step, 8)
    with pytest.raises(checkify.JaxRuntimeError) as ei:
        runner((jnp.full((2, 2), 1.0, jnp.float32),), start=100)
    assert "non-finite after step 103" in str(ei.value)


def test_cli_debug_checks_localizes_blowup():
    """An unstable alpha blows up on the first update; the error names step 0."""
    cfg = RunConfig(stencil="heat2d", grid=(16, 16), iters=10,
                    debug_checks=True, params={"alpha": 1e38})
    with pytest.raises(checkify.JaxRuntimeError) as ei:
        run(cfg)
    assert "non-finite after step 0" in str(ei.value)


def test_cli_debug_checks_healthy_run_matches_plain():
    base = dict(stencil="heat2d", grid=(16, 16), iters=6, seed=1)
    plain, _ = run(RunConfig(**base))
    checked, _ = run(RunConfig(**base, debug_checks=True))
    np.testing.assert_array_equal(
        np.asarray(plain[0]), np.asarray(checked[0]))


def test_cli_debug_checks_sharded_and_chunked():
    """debug-checks composes with a mesh AND interval logging (chunked run)."""
    base = dict(stencil="heat3d", grid=(8, 8, 8), iters=6, seed=2,
                init="pulse")
    plain, _ = run(RunConfig(**base))
    checked, _ = run(RunConfig(**base, mesh=(2, 2, 2), log_every=2,
                               debug_checks=True))
    np.testing.assert_allclose(
        np.asarray(plain[0]), np.asarray(checked[0]), rtol=1e-6)


def test_cli_debug_checks_sharded_blowup_localized():
    """The carry-based tracker (sharded path) names the failing step too."""
    cfg = RunConfig(stencil="heat2d", grid=(16, 16), iters=10, mesh=(2, 2),
                    debug_checks=True, params={"alpha": 1e38})
    with pytest.raises(checkify.JaxRuntimeError) as ei:
        run(cfg)
    assert "non-finite after step 0" in str(ei.value)


def test_debug_checks_excludes_fuse_and_tol():
    with pytest.raises(ValueError, match="--debug-checks excludes --fuse"):
        run(RunConfig(stencil="heat2d", grid=(32, 32), iters=8, fuse=4,
                      debug_checks=True))
    with pytest.raises(ValueError, match="--tol"):
        run(RunConfig(stencil="heat2d", grid=(16, 16), iters=8, tol=1e-3,
                      debug_checks=True))
