"""Golden/property tests for the extended model zoo (advection, Gray-Scott,
mdf alias) and the convergence runner (SURVEY.md §4.1, §4.5)."""

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.driver import make_runner, run_until


# ---------------------------------------------------------------------------
# mdf alias
# ---------------------------------------------------------------------------


def test_mdf_alias_is_reference_heat2d():
    st = make_stencil("mdf")
    assert st.name == "heat2d"
    assert st.params["alpha"] == 0.25  # MDF_kernel.cu:20 coefficient
    assert st.bc_value == (100.0,)  # MDF_kernel.cu:92-93 hot walls


# ---------------------------------------------------------------------------
# advection
# ---------------------------------------------------------------------------


def _np_upwind_2d(u, cy, cx, bc):
    """Independent numpy upwind step (guard-frame semantics)."""
    p = np.pad(u, 1, constant_values=bc)
    c = p[1:-1, 1:-1]
    out = c.copy()
    if cy > 0:
        out = out - cy * (c - p[:-2, 1:-1])
    elif cy < 0:
        out = out - cy * (p[2:, 1:-1] - c)
    if cx > 0:
        out = out - cx * (c - p[1:-1, :-2])
    elif cx < 0:
        out = out - cx * (p[1:-1, 2:] - c)
    res = u.copy()
    res[1:-1, 1:-1] = out[1:-1, 1:-1]
    return res


@pytest.mark.parametrize("cx,cy", [(0.4, 0.3), (-0.4, 0.2), (0.0, -0.5)])
def test_advect2d_matches_numpy_golden(cx, cy):
    st = make_stencil("advect2d", cx=cx, cy=cy)
    rng = np.random.RandomState(0)
    u0 = rng.rand(12, 14).astype(np.float32)
    u0[0, :] = u0[-1, :] = u0[:, 0] = u0[:, -1] = 0.0
    step = jax.jit(make_step(st, u0.shape))
    got = np.asarray(step((jnp.asarray(u0),))[0])
    want = _np_upwind_2d(u0, cy, cx, 0.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_advect2d_transports_pulse_downstream():
    st = make_stencil("advect2d", cx=0.5, cy=0.0)
    shape = (17, 33)
    fields = init_state(st, shape, kind="pulse")
    out = make_runner(make_step(st, shape), 20)(fields)
    # center of mass moved in +x by ~ cx * steps
    u0 = np.asarray(init_state(st, shape, kind="pulse")[0])
    u1 = np.asarray(out[0])
    xs = np.arange(shape[1])
    com0 = (u0.sum(0) * xs).sum() / u0.sum()
    com1 = (u1.sum(0) * xs).sum() / u1.sum()
    assert 7 < com1 - com0 <= 10.5  # 0.5 * 20 = 10 cells, minus wall losses


def test_advect3d_stability_guard():
    with pytest.raises(ValueError, match="unstable"):
        make_stencil("advect3d", cx=0.5, cy=0.5, cz=0.5)


def test_advect_sharded_matches_unsharded():
    st = make_stencil("advect2d", cx=0.4, cy=-0.2)
    shape = (16, 16)
    fields = init_state(st, shape, seed=2, kind="pulse")
    ref = make_runner(make_step(st, shape), 5)(fields)
    mesh = make_mesh((2, 2))
    sf = shard_fields(init_state(st, shape, seed=2, kind="pulse"),
                      mesh, st.ndim)
    out = make_runner(make_sharded_step(st, mesh, shape), 5)(sf)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=1e-6)


# ---------------------------------------------------------------------------
# Gray-Scott
# ---------------------------------------------------------------------------


def test_grayscott_trivial_steady_state():
    """u=1, v=0 is an exact fixed point of the reaction-diffusion system."""
    st = make_stencil("grayscott2d")
    u = jnp.ones((12, 12), st.dtype)
    v = jnp.zeros((12, 12), st.dtype)
    out = jax.jit(make_step(st, (12, 12)))((u, v))
    np.testing.assert_allclose(np.asarray(out[0]), 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out[1]), 0.0, atol=1e-6)


def test_grayscott_patch_activates_and_stays_bounded():
    st = make_stencil("grayscott2d")
    shape = (48, 48)
    fields = init_state(st, shape, seed=1)
    out = make_runner(make_step(st, shape), 200)(fields)
    u, v = (np.asarray(f) for f in out)
    assert np.isfinite(u).all() and np.isfinite(v).all()
    assert v.max() > 1e-3  # reaction is alive
    assert 0.0 <= u.min() and u.max() <= 1.5 and v.max() <= 1.0


def test_grayscott_sharded_both_fields_exchanged():
    st = make_stencil("grayscott2d")
    assert st.field_halos == (1, 1)
    shape = (24, 24)
    ref = make_runner(make_step(st, shape), 8)(
        init_state(st, shape, seed=4))
    mesh = make_mesh((2, 2))
    sf = shard_fields(init_state(st, shape, seed=4), mesh, st.ndim)
    out = make_runner(make_sharded_step(st, mesh, shape), 8)(sf)
    for a, b in zip(out, ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5)


# ---------------------------------------------------------------------------
# convergence runner
# ---------------------------------------------------------------------------


def test_run_until_converges_to_hot_walls():
    """MDF physics: interior relaxes toward the 100.0 Dirichlet walls."""
    st = make_stencil("heat2d")
    shape = (12, 12)
    fields = init_state(st, shape, kind="zero")
    step = make_step(st, shape)
    out, n, res = run_until(step, fields, tol=1e-3, max_steps=10_000,
                            check_every=25)
    assert res <= 1e-3 and n < 10_000
    u = np.asarray(out[0])
    assert u.min() > 95.0  # near-uniform hot steady state


def test_run_until_respects_max_steps():
    st = make_stencil("heat2d")
    shape = (12, 12)
    fields = init_state(st, shape, kind="zero")
    step = make_step(st, shape)
    # check_every does not divide max_steps: the cap must still be exact
    out, n, res = run_until(step, fields, tol=0.0, max_steps=30,
                            check_every=7)
    assert n == 30 and res > 0.0


def test_run_until_matches_fixed_steps():
    """run_until with an unreachable tol == plain scan of max_steps."""
    st = make_stencil("heat2d")
    shape = (10, 10)
    mk = lambda: init_state(st, shape, kind="zero")  # noqa: E731
    step = make_step(st, shape)
    out, n, _ = run_until(step, mk(), tol=0.0, max_steps=20, check_every=5)
    ref = make_runner(step, 20)(mk())
    assert n == 20
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=1e-6)


def test_run_until_sharded():
    st = make_stencil("heat2d")
    shape = (16, 16)
    mesh = make_mesh((2, 2))
    fields = shard_fields(init_state(st, shape, kind="zero"), mesh, st.ndim)
    step = make_sharded_step(st, mesh, shape)
    out, n, res = run_until(step, fields, tol=1e-3, max_steps=10_000,
                            check_every=50)
    assert res <= 1e-3
    assert np.asarray(out[0]).min() > 95.0


def test_check_finite_catches_blowup():
    """--check-finite aborts with the failing step range on NaN/Inf."""
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig

    # wildly unstable wave (c2dt2 >> 1/3) blows up within a few steps
    with pytest.raises(RuntimeError, match="non-finite between steps"):
        run(RunConfig(stencil="wave3d", grid=(16, 16, 16), iters=200,
                      init="pulse", params={"c2dt2": 50.0}, check_finite=20))

    # stable run with the same flag completes untouched
    fields, _ = run(RunConfig(stencil="wave3d", grid=(16, 16, 16), iters=40,
                              init="pulse", check_finite=20))
    assert np.isfinite(np.asarray(fields[0])).all()


def test_cli_tol_path():
    from mpi_cuda_process_tpu.cli import run
    from mpi_cuda_process_tpu.config import RunConfig

    fields, _ = run(RunConfig(stencil="heat2d", grid=(12, 12), iters=10_000,
                              init="zero", tol=1e-3, tol_check_every=25))
    assert np.asarray(fields[0]).min() > 95.0

    import pytest as _pytest
    with _pytest.raises(ValueError, match="tol"):
        run(RunConfig(stencil="heat2d", grid=(12, 12), iters=100,
                      tol=1e-3, log_every=10))
