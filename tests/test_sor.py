"""Red-black SOR: golden correctness, Gauss-Seidel semantics, sharded
equivalence of the multi-phase step, and convergence-rate superiority over
Jacobi (the property that justifies the solver's existence)."""

import numpy as np

import jax
import pytest

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.driver import make_runner, run_until


def _np_redblack_sor(u, omega, steps):
    """Independent numpy red-black SOR (frame fixed, sequential semantics)."""
    u = u.copy()
    h, w = u.shape
    yy, xx = np.mgrid[0:h, 0:w]
    for _ in range(steps):
        for color in (0, 1):
            nsum = (np.roll(u, 1, 0) + np.roll(u, -1, 0)
                    + np.roll(u, 1, 1) + np.roll(u, -1, 1))
            relaxed = (1 - omega) * u + omega / 4.0 * nsum
            mask = ((yy + xx) % 2 == color)
            mask &= (yy > 0) & (yy < h - 1) & (xx > 0) & (xx < w - 1)
            u = np.where(mask, relaxed, u)
    return u


def test_sor2d_matches_numpy_golden():
    import jax.numpy as jnp

    st = make_stencil("sor2d", omega=1.5)
    rng = np.random.RandomState(1)
    u0 = rng.rand(10, 12).astype(np.float32) * 50
    step = jax.jit(make_step(st, u0.shape))
    got = step((jnp.asarray(u0),))
    got = step(got)
    want = _np_redblack_sor(u0, 1.5, 2)
    np.testing.assert_allclose(np.asarray(got[0]), want, rtol=2e-5, atol=1e-4)


def test_sor_black_sees_fresh_red():
    """Gauss-Seidel property: the black half-sweep reads this step's reds."""
    import jax.numpy as jnp

    st = make_stencil("sor2d", omega=1.0, bc=0.0)
    u0 = jnp.zeros((6, 6), jnp.float32).at[2, 2].set(16.0)  # (2+2) even: red
    out = jax.jit(make_step(st, (6, 6)))((u0,))[0]
    # With omega=1 the red cell (2,2) relaxes to mean of zeros = 0; its black
    # neighbors then read the FRESH 0, not the old 16 — Jacobi would give
    # (16)/4 = 4 at (2,3); Gauss-Seidel gives 0.
    assert float(out[2, 2]) == 0.0
    assert float(out[2, 3]) == 0.0


def test_sor_sharded_matches_unsharded():
    st = make_stencil("sor2d")
    shape = (16, 16)  # even per-shard extents: parity-consistent
    fields = init_state(st, shape, kind="zero")
    ref = make_runner(make_step(st, shape), 6)(fields)
    for mesh_shape in [(2,), (2, 2), (4, 2)]:
        mesh = make_mesh(mesh_shape)
        sf = shard_fields(init_state(st, shape, kind="zero"), mesh, st.ndim)
        out = make_runner(make_sharded_step(st, mesh, shape), 6)(sf)
        np.testing.assert_allclose(
            np.asarray(out[0]), np.asarray(ref[0]), rtol=1e-6, atol=1e-5)


def test_sor_converges_faster_than_jacobi():
    shape = (24, 24)
    tol = 1e-3

    def steps_to_converge(name, **params):
        st = make_stencil(name, **params)
        fields = init_state(st, shape, kind="zero")
        step = make_step(st, shape)
        _, n, res = run_until(step, fields, tol=tol, max_steps=20_000,
                              check_every=10)
        assert res <= tol
        return n

    n_jacobi = steps_to_converge("heat2d")       # alpha=0.25 == Jacobi
    n_sor = steps_to_converge("sor2d", omega=1.8)
    assert n_sor < n_jacobi / 3, (n_sor, n_jacobi)


def test_sor3d_runs_and_converges():
    st = make_stencil("sor3d")
    shape = (12, 12, 12)
    fields = init_state(st, shape, kind="zero")
    out, n, res = run_until(make_step(st, shape), fields, tol=1e-3,
                            max_steps=10_000, check_every=20)
    assert res <= 1e-3
    assert np.asarray(out[0]).min() > 90.0


def test_sor_rejects_bad_omega():
    with pytest.raises(ValueError, match="omega"):
        make_stencil("sor2d", omega=2.5)


def test_sor_update_stub_raises():
    st = make_stencil("sor2d")
    with pytest.raises(NotImplementedError, match="multi-phase"):
        st.update((None,))


def test_sor_rejects_parity_breaking_decomposition():
    """Odd per-shard extents would flip colors across shards: loud error."""
    st = make_stencil("sor2d")
    mesh = make_mesh((3,))
    with pytest.raises(ValueError, match="parity"):
        make_sharded_step(st, mesh, (15, 16))
    # even extents are fine
    make_sharded_step(st, mesh, (12, 16))
    # periodic wrap over odd global extent is likewise inconsistent
    with pytest.raises(ValueError, match="parity"):
        make_step(st, (15, 16), periodic=True)


def test_sor_overlap_rejected():
    st = make_stencil("sor2d")
    mesh = make_mesh((2, 2))
    with pytest.raises(ValueError, match="multi-phase"):
        make_sharded_step(st, mesh, (16, 16), overlap=True)
