"""parallel/groups.py: MPMD device groups coupled at interface faces.

The coupling contract, pinned (ISSUE 18):

* **bit-exactness** — a same-physics 2-group split (any group meshes,
  any dtype) assembles to EXACTLY the monolithic run's state after any
  number of coupled rounds: the ghost band absorbs one round's
  staleness, the band refresh is a wholesale overwrite from the
  neighbor's owned rows, and every owned row stays exact;
* **conservation** — face resampling round-trips bitwise
  (``restrict(interpolate(x)) == x``), so a fine|coarse interface
  neither creates nor destroys what the coarse side handed over;
* **isolation** — interface faces are the ONLY cross-group
  communication (the jaxpr gate: zero collectives in the transfers,
  intra-group ppermutes only where a sub-mesh actually shards);
* **identity** — a coupled row's ledger key carries ``|grp:<sig>``, so
  perf_gate reports NO_BASELINE (never REGRESSED) across group
  signatures, and policy replay is deterministic (the group layout IS
  the execution strategy);
* **observability** — the manifest carries a resolved ``groups`` block,
  budget/costmodel price the split per group with explicit interface
  transients, a DIVERGED verdict names the group, and the engine admits
  a coupled config like any tenant.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import cli, init_state, make_runner, make_step, \
    make_stencil
from mpi_cuda_process_tpu.config import RunConfig, groups_signature
from mpi_cuda_process_tpu.parallel import groups as groups_lib

HET_GROUPS = "wave3d:fine@0-3:z1/4:mesh1x4,heat3d:coarse@4-7:mesh1x4"
HET_GRID = (24, 16, 16)


# ------------------------------------------------------------ parsing

def test_parse_groups_named_rejections():
    """Every malformed clause is rejected with the reason, never a
    silently-monolithic run."""
    pg = groups_lib.parse_groups
    with pytest.raises(ValueError, match="at least 2"):
        pg("heat3d@0-7")
    with pytest.raises(ValueError, match="does not match"):
        pg("heat3d,wave3d@4-7")
    with pytest.raises(ValueError, match="unknown qualifier"):
        pg("heat3d:fast@0-3,heat3d@4-7")
    with pytest.raises(ValueError, match="power of two"):
        pg("heat3d:fine3@0-3,heat3d@4-7")
    with pytest.raises(ValueError, match="descending"):
        pg("heat3d@3-0,heat3d@4-7")
    with pytest.raises(ValueError, match="contiguous"):
        pg("heat3d@0-2,heat3d@4-7")
    with pytest.raises(ValueError, match="start at device 0"):
        pg("heat3d@1-3,heat3d@4-7")
    with pytest.raises(ValueError, match="z-fraction"):
        pg("heat3d@0-3:z3/2,heat3d@4-7")
    with pytest.raises(ValueError, match="mesh .* needs"):
        pg("heat3d@0-3:mesh2x4,heat3d@4-7")
    with pytest.raises(ValueError, match="only 8 device"):
        pg("heat3d@0-3,heat3d@4-11", n_devices=8)


def test_plan_groups_geometry_and_describe():
    plans = groups_lib.plans_from_config(HET_GROUPS, HET_GRID,
                                         n_devices=8)
    fine, coarse = plans
    assert fine.spec.ratio == 2 and coarse.spec.ratio == 1
    # z1/4 of 24 base rows = 6, refined 2x = 12 owned + one hi band
    assert (fine.base_z0, fine.base_z1) == (0, 6)
    assert fine.grid[1:] == (32, 32)  # every axis refined
    assert fine.band_lo == 0 and fine.band_hi > 0
    assert coarse.band_lo > 0 and coarse.band_hi == 0
    d = fine.describe()
    for key in ("group", "op", "ratio", "dtype", "devices", "mesh",
                "grid", "base_z", "band"):
        assert key in d
    assert d["devices"] == [0, 3]
    # a sliver group that can't even hold its own ghost bands is
    # rejected by name, with the fix (a larger :z fraction) spelled out
    with pytest.raises(ValueError, match="fewer than its own ghost"):
        groups_lib.plan_groups(
            groups_lib.parse_groups(
                "heat3d@0-3:z1/16,heat3d:fine8@4-7"), (16, 16, 16))


# ------------------------------------------------- face resampling pins

def test_restrict_interpolate_conservation_pin():
    """``restrict(interpolate(x)) == x`` BITWISE — the interface
    conservation pin, for every swept factor and dtype."""
    rng = np.random.default_rng(7)
    for dtype in ("float32", "bfloat16"):
        x = jnp.asarray(rng.standard_normal((6, 8, 8)), dtype)
        for factor in (2, 4):
            back = groups_lib.restrict(
                groups_lib.interpolate(x, factor), factor)
            np.testing.assert_array_equal(np.asarray(back),
                                          np.asarray(x))
    with pytest.raises(ValueError, match="power of two"):
        groups_lib.restrict(jnp.zeros((6, 6)), 3)


def test_interface_dtype_roundtrip_pin():
    """A bf16 band cast to f32 and back is bitwise-identical: f32
    holds every bf16 value exactly, so a mixed-precision interface
    loses nothing on the cast itself."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((4, 8, 8)), "bfloat16")
    back = x.astype("float32").astype("bfloat16")
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


# -------------------------------------------------------- bit-exactness

def _assert_coupled_bit_exact(op, gspec, grid, rounds=6, dtype=None,
                              steps_per_round=1,
                              transport=groups_lib.TRANSPORT_BACKEND):
    """Coupled same-physics split vs the jitted monolithic reference.

    The reference is ``make_runner(step, 1)`` — the same jitted scan
    body the coupled groups run — NOT the eager step (XLA contracts
    FMAs differently under jit, so an eager reference differs in the
    last ulp and would mask real coupling bugs behind a tolerance).

    ``steps_per_round``: the groups' shared ``fuseK`` factor (round 23
    mode tokens) — one coupled round advances K monolithic steps.
    ``transport``: the interface transport under test; both transports
    must hit the SAME bits.
    """
    plans = groups_lib.plans_from_config(
        gspec, grid, default_dtype=dtype, n_devices=8)
    runner = groups_lib.CoupledRunner(plans, transport=transport)
    runner.run(rounds)
    got = runner.assemble()

    kw = {"dtype": dtype} if dtype else {}
    st = make_stencil(op, **kw)
    # make_runner donates its inputs: copy so init stays comparable
    ref = tuple(jnp.copy(f) for f in init_state(st, grid, kind="auto"))
    step1 = make_runner(make_step(st, grid), 1)
    for _ in range(rounds * steps_per_round):
        ref = step1(ref)
    assert len(got) == len(ref)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


def test_coupled_bit_exact_zonly_f32():
    _assert_coupled_bit_exact(
        "heat3d", "heat3d@0-3,heat3d@4-7", (30, 16, 16))


@pytest.mark.slow
def test_coupled_bit_exact_matrix():
    """z-only AND 2-axis group meshes, f32 AND bf16, one- and
    two-field ops — the full same-physics exactness matrix."""
    _assert_coupled_bit_exact(
        "wave3d", "wave3d@0-3,wave3d@4-7", (30, 16, 16))
    _assert_coupled_bit_exact(
        "heat3d", "heat3d:bf16@0-3,heat3d:bf16@4-7", (30, 16, 16),
        dtype="bfloat16")
    _assert_coupled_bit_exact(
        "heat3d", "heat3d@0-3:mesh2x2,heat3d@4-7:mesh2x2", (30, 16, 16))
    _assert_coupled_bit_exact(
        "wave3d", "wave3d:bf16@0-3:mesh2x2,wave3d:bf16@4-7:mesh2x2",
        (30, 16, 16), dtype="bfloat16")


def test_coupled_three_groups_bit_exact():
    """The band math generalizes past one interface: a middle group
    with bands on BOTH sides stays exact."""
    _assert_coupled_bit_exact(
        "heat3d",
        "heat3d@0-1:mesh1x2,heat3d@2-5:mesh1x4,heat3d@6-7:mesh1x2",
        (30, 16, 16), rounds=4)


# ------------------------------------- mode tokens (round 23, ISSUE 19)

def test_parse_mode_tokens_named_rejections():
    pg = groups_lib.parse_groups
    with pytest.raises(ValueError, match="unknown mode word"):
        pg("heat3d@0-3:stream+warp,heat3d@4-7")
    with pytest.raises(ValueError, match="fuse1 is the plain stepper"):
        pg("heat3d@0-3:fuse1,heat3d@4-7")
    with pytest.raises(ValueError, match="bad fuse token"):
        pg("heat3d@0-3:fusex,heat3d@4-7")
    with pytest.raises(ValueError, match="mutually exclusive"):
        pg("heat3d@0-3:stream+padfree,heat3d@4-7")
    with pytest.raises(ValueError, match="cannot combine"):
        pg("heat3d@0-3:plain+overlap,heat3d@4-7")
    with pytest.raises(ValueError, match="pipeline needs fuse"):
        pg("heat3d@0-3:pipeline,heat3d@4-7")
    with pytest.raises(ValueError, match="duplicate mode word"):
        pg("heat3d@0-3:overlap+overlap,heat3d@4-7")
    # the uniform-K contract is enforced at plan time, by name
    with pytest.raises(ValueError, match="fuse factors .* differ"):
        groups_lib.plans_from_config(
            "heat3d@0-3:fuse4,heat3d@4-7:fuse2", (56, 16, 16),
            n_devices=8)
    # a forced mode the builder declines raises, never degrades
    # (local z = 12 is under the streaming kernel's 3-chunk floor)
    with pytest.raises(ValueError, match="forced modes never fall back"):
        groups_lib.CoupledRunner(groups_lib.plans_from_config(
            "heat3d@0-3:fuse4+stream:mesh2x2,"
            "heat3d@4-7:fuse4+stream:mesh2x2", (40, 32, 128),
            n_devices=8))


def test_mode_tokens_canonical_and_views():
    pg = groups_lib.parse_groups
    s = pg("heat3d@0-3:overlap+stream+fuse4,heat3d@4-7")[0]
    assert s.modes == ("fuse4", "stream", "overlap")  # canonical order
    assert s.fuse_k == 4 and s.kind == "stream" and s.overlap_mode
    assert not s.pipeline_mode
    assert s.canonical() == "heat3d@0-3:fuse4+stream+overlap"
    # canonical text reparses to the same spec (the replay contract)
    assert pg(s.canonical() + ",heat3d@4-7")[0] == s
    # with_modes canonicalizes; modes fold into the groups signature
    t = pg("heat3d@0-3,heat3d@4-7")[0].with_modes(("overlap", "stream"))
    assert t.modes == ("stream", "overlap")
    assert groups_signature("heat3d@0-3:overlap,heat3d@4-7") != \
        groups_signature("heat3d@0-3,heat3d@4-7")
    # the hash itself is spelling-sensitive (pure string, no parser);
    # order-insensitivity comes from re-spelling through canonical()
    def canon_sig(raw):
        return groups_signature(
            ",".join(g.canonical() for g in pg(raw)))
    assert canon_sig("heat3d@0-3:stream+overlap,heat3d@4-7") == \
        canon_sig("heat3d@0-3:overlap+stream,heat3d@4-7")
    # plans carry the clause + modes into describe() (the manifest seed)
    d = groups_lib.plans_from_config(
        "heat3d@0-3:overlap,heat3d@4-7", (30, 16, 16),
        n_devices=8)[0].describe()
    assert d["modes"] == ["overlap"]
    assert d["clause"] == "heat3d@0-3:overlap"


def test_mode_routed_group_bit_exact_overlap():
    """An ``:overlap`` group (interior/boundary split stepper) computes
    the exact monolithic bits — the light leg of the mode matrix."""
    _assert_coupled_bit_exact(
        "heat3d", "heat3d@0-3:overlap,heat3d@4-7", (30, 16, 16))


@pytest.mark.slow
def test_mode_routed_groups_bit_exact_fused_matrix():
    """fuseK / stream mode tokens route groups through the temporal-
    blocking steppers: K micro-steps per coupled round, bit-exact
    against K monolithic steps per round."""
    # fuse4: the padded tiled kernels per group, y-sharded sub-meshes
    _assert_coupled_bit_exact(
        "heat3d",
        "heat3d@0-3:z1/2:fuse4:mesh1x4,heat3d@4-7:fuse4:mesh1x4",
        (56, 32, 16), rounds=2, steps_per_round=4)
    # fuse4+stream: the manual-DMA streaming kernels on 2-axis meshes
    _assert_coupled_bit_exact(
        "heat3d",
        "heat3d@0-3:fuse4+stream:mesh2x2,heat3d@4-7:fuse4+stream:mesh2x2",
        (88, 32, 128), rounds=2, steps_per_round=4)


# --------------------------- collective transport (round 23, ISSUE 19)

def test_collective_transport_bit_exact_zonly_f32():
    _assert_coupled_bit_exact(
        "heat3d", "heat3d@0-3,heat3d@4-7", (30, 16, 16),
        transport="collective")


@pytest.mark.slow
def test_collective_transport_bit_exact_matrix():
    """ppermute interface rounds hit the same bits as the device_put
    path: 2 and 3 groups, f32 and bf16, z-only and 2-axis meshes."""
    _assert_coupled_bit_exact(
        "wave3d", "wave3d@0-3,wave3d@4-7", (30, 16, 16),
        transport="collective")
    _assert_coupled_bit_exact(
        "heat3d", "heat3d:bf16@0-3,heat3d:bf16@4-7", (30, 16, 16),
        dtype="bfloat16", transport="collective")
    _assert_coupled_bit_exact(
        "heat3d", "heat3d@0-3:mesh2x2,heat3d@4-7:mesh2x2", (30, 16, 16),
        transport="collective")
    _assert_coupled_bit_exact(
        "heat3d",
        "heat3d@0-1:mesh1x2,heat3d@2-5:mesh2x2,heat3d@6-7:mesh1x2",
        (30, 16, 16), rounds=4, transport="collective")


def test_collective_matches_device_put_hetero():
    """A ratio'd mixed-physics interface (no monolithic reference
    exists) advances to IDENTICAL per-group state under both
    transports — the transports are interchangeable, not just both
    plausible."""
    runners = []
    for transport in groups_lib.TRANSPORTS:
        plans = groups_lib.plans_from_config(HET_GROUPS, HET_GRID,
                                             n_devices=8)
        r = groups_lib.CoupledRunner(plans, transport=transport)
        r.run(4)
        runners.append(r)
    a, b = runners
    assert a.n_groups == b.n_groups == 2
    for ga, gb in zip(a.fields, b.fields):
        for fa, fb in zip(ga, gb):
            np.testing.assert_array_equal(np.asarray(fa),
                                          np.asarray(fb))


def test_unknown_transport_rejected_by_name():
    plans = groups_lib.plans_from_config(
        "heat3d@0-3,heat3d@4-7", (30, 16, 16), n_devices=8)
    with pytest.raises(ValueError, match="--group-transport 'bogus'"):
        groups_lib.CoupledRunner(plans, transport="bogus")


def test_collective_jaxpr_transport_gate():
    """The tier-1 gate as a default-tier test: zero device_put, exactly
    2*interfaces ppermutes, nothing else collective — 2 and 3 groups."""
    from mpi_cuda_process_tpu.utils import jaxprcheck

    rep = jaxprcheck.check_group_transport_structure(
        "heat3d@0-3,heat3d@4-7", (30, 16, 16))
    assert rep["transport"] == "collective"
    assert rep["n_ppermute"] == 2 and rep["n_device_put"] == 0
    rep = jaxprcheck.check_group_transport_structure(
        "heat3d@0-1:mesh1x2,heat3d@2-5:mesh2x2,heat3d@6-7:mesh1x2",
        (30, 16, 16))
    assert rep["n_ppermute"] == 4 and rep["n_device_put"] == 0
    # mismatched y-shard counts across an interface are rejected by
    # name — the collective wire pairs edge shards y-by-y
    plans = groups_lib.plans_from_config(
        "heat3d@0-3:mesh1x4,heat3d@4-7:mesh2x2", (30, 16, 16),
        n_devices=8)
    with pytest.raises(ValueError, match="SAME y-shard count"):
        groups_lib.CoupledRunner(plans, transport="collective")


def test_coupled_checkpoint_resume_bitmatch_collective(tmp_path):
    """Checkpoint/resume under the collective transport: same per-group
    subdirs, resumed state bit-matches the uninterrupted collective
    run (bands rebuilt by the first ppermute round)."""
    ck = str(tmp_path / "ckpt")
    base = dict(stencil="heat3d", grid=(30, 16, 16), iters=8,
                groups="heat3d@0-3,heat3d@4-7",
                group_transport="collective")
    full, _ = cli.run(RunConfig(**base))
    cli.run(RunConfig(**{**base, "iters": 4}, checkpoint_every=4,
                      checkpoint_dir=ck))
    assert os.path.isdir(os.path.join(ck, "group0"))
    resumed, _ = cli.run(RunConfig(**base, checkpoint_dir=ck,
                                   resume=True))
    for a, b in zip(full, resumed):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_diverged_verdict_names_the_group_collective(tmp_path,
                                                     monkeypatch):
    """Fault injection under the collective transport still names the
    poisoned group — the union-mesh ppermutes don't smear the blame."""
    from mpi_cuda_process_tpu.obs import health as health_lib
    from mpi_cuda_process_tpu.resilience import faults

    monkeypatch.setenv("FAULT_INJECT", "numerics:step=2:nan")
    monkeypatch.setenv("FAULT_ATTEMPT", "0")
    faults.reset()
    tel = str(tmp_path / "div.jsonl")
    with pytest.raises(health_lib.SimulationDiverged,
                       match=r"^group g0:heat3d DIVERGED"):
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=8,
                          groups="heat3d@0-3,heat3d@4-7",
                          group_transport="collective", health=True,
                          log_every=2, telemetry=tel))
    faults.reset()
    hv = [e for e in _read_events(tel) if e.get("kind") == "health"]
    div = [e for e in hv if e["verdict"] == "DIVERGED"]
    assert div and div[0]["group"] == "g0:heat3d"


# ----------------------------------------------------------- jaxpr gate

def test_jaxpr_coupling_gate():
    from mpi_cuda_process_tpu.utils import jaxprcheck

    report = jaxprcheck.check_coupled_structure(
        groups="heat3d@0-3,heat3d@4-7", grid=(30, 16, 16))
    assert report["groups"] == ["g0:heat3d", "g1:heat3d"]
    # hetero split through the same gate: still zero cross-group ops
    report = jaxprcheck.check_coupled_structure(
        groups=HET_GROUPS, grid=HET_GRID)
    assert len(report["groups"]) == 2


# ------------------------------------------------- pricing / admission

def test_interface_traffic_budget_and_costmodel():
    from mpi_cuda_process_tpu.obs import costmodel
    from mpi_cuda_process_tpu.utils import budget

    plans = groups_lib.plans_from_config(HET_GROUPS, HET_GRID,
                                         n_devices=8)
    traffic = groups_lib.interface_traffic(plans)
    assert len(traffic) == 1
    up, dn = traffic[0]["up"], traffic[0]["down"]
    assert up["recv_bytes"] > 0 and dn["recv_bytes"] > 0
    worst, details = budget.estimate_coupled_bytes(plans)
    assert worst > 0 and len(details) == 2
    cost = costmodel.coupled_cost(plans)
    assert cost["coupled"] is True and cost["n_groups"] == 2
    assert len(cost["groups"]) == 2
    iface = cost["interface"]
    assert iface["transport"] == groups_lib.TRANSPORT_BACKEND
    # documented cross-check: bytes_per_round == the budget's interface
    # recv transients, so cost model and HBM budget cannot drift apart
    recv = sum(t[d]["recv_bytes"] for t in traffic
               for d in ("up", "down"))
    assert iface["bytes_per_round"] == recv


def test_admission_prices_coupled_config():
    from mpi_cuda_process_tpu.serving import admission

    cfg = RunConfig(stencil="wave3d", grid=HET_GRID, iters=4,
                    groups=HET_GROUPS)
    price = admission.AdmissionController().price(cfg)
    assert price["total_bytes"] > 0
    names = [g["group"] for g in price["coupled_groups"]]
    assert names == ["g0:wave3d", "g1:heat3d"]
    assert price["worst_group"] in names


# ------------------------------------------------------ hetero demo

def _read_events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


def test_hetero_demo_cli_end_to_end(tmp_path):
    """Fine wave3d + coarse heat3d on 8 virtual devices, end-to-end
    through the CLI: >= 2x fewer cell-updates than uniformly-fine,
    manifest groups block + per-group chunk telemetry + coupled
    costmodel all land in the log."""
    tel = str(tmp_path / "het.jsonl")
    # 8 iters on purpose: the fine wave group's energy drifts past its
    # conservation tolerance by then — an OPEN system fed by the coarse
    # heat side — and the open_system monitors must not false-trigger
    cfg = RunConfig(stencil="wave3d", grid=HET_GRID, iters=8,
                    groups=HET_GROUPS, log_every=2, health=True,
                    telemetry=tel)
    fields, mcells = cli.run(cfg)
    assert np.asarray(fields[0]).shape == HET_GRID
    assert mcells > 0

    plans = groups_lib.plans_from_config(HET_GROUPS, HET_GRID,
                                         n_devices=8)
    coupled_cells = sum(p.cells for p in plans)
    fine_everywhere = 8 * int(np.prod(HET_GRID))  # ratio 2 on 3 axes
    assert fine_everywhere >= 2 * coupled_cells

    evs = _read_events(tel)
    man = next(e for e in evs if e.get("kind") == "manifest")
    grp_block = man["groups"]
    assert [g["group"] for g in grp_block] == ["g0:wave3d", "g1:heat3d"]
    assert [g["ratio"] for g in grp_block] == [2, 1]
    cm = next(e for e in evs if e.get("kind") == "costmodel")
    assert cm["coupled"] is True and cm["n_groups"] == 2
    # manifest cross-check: the costmodel prices the SAME resolved split
    assert [g["group"] for g in cm["groups"]] == \
        [g["group"] for g in grp_block]
    gc = [e for e in evs if e.get("kind") == "group_chunk"]
    assert {e["group"] for e in gc} == {"g0:wave3d", "g1:heat3d"}
    hv = [e for e in evs if e.get("kind") == "health"]
    assert hv and all(e.get("group") for e in hv)
    assert all(e["verdict"] == "HEALTHY" for e in hv)
    wave_inv = [e["invariant"] for e in hv
                if e["group"] == "g0:wave3d" and e.get("invariant")]
    assert wave_inv and all(b.get("open_system") for b in wave_inv)
    fin = next(e for e in evs if e.get("kind") == "summary")
    assert fin["coupled"] is True and fin["n_groups"] == 2


def test_hetero_demo_engine_submit(tmp_path):
    """The same coupled config through engine.submit: admitted,
    executed on the cli.run path, per-group stream on the handle."""
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    cfg = RunConfig(stencil="wave3d", grid=HET_GRID, iters=4,
                    groups=HET_GROUPS, health=True, log_every=2)
    handle = eng.submit(cfg)
    fields, mcells = handle.result(timeout=300)
    assert np.asarray(fields[0]).shape == HET_GRID and mcells > 0
    assert handle.health_verdict() == "HEALTHY"
    kinds = {e.get("kind") for e in handle.events()}
    assert "group_chunk" in kinds


# --------------------------------------------- checkpoint / divergence

def test_coupled_checkpoint_resume_bitmatch(tmp_path):
    """A resumed coupled run bit-matches an uninterrupted one: per-group
    checkpoint subdirs, one agreed round, exact band state rebuilt by
    the first exchange of the resumed loop."""
    ck = str(tmp_path / "ckpt")
    base = dict(stencil="heat3d", grid=(30, 16, 16), iters=8,
                groups="heat3d@0-3,heat3d@4-7")
    full, _ = cli.run(RunConfig(**base))

    cli.run(RunConfig(**{**base, "iters": 4}, checkpoint_every=4,
                      checkpoint_dir=ck))
    assert os.path.isdir(os.path.join(ck, "group0"))
    resumed, _ = cli.run(RunConfig(**base, checkpoint_dir=ck,
                                   resume=True))
    for a, b in zip(full, resumed):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_diverged_verdict_names_the_group(tmp_path, monkeypatch):
    """Numeric poison in group 0 -> the eviction verdict names the
    group FIRST, and the health record carries it."""
    from mpi_cuda_process_tpu.obs import health as health_lib
    from mpi_cuda_process_tpu.resilience import faults

    monkeypatch.setenv("FAULT_INJECT", "numerics:step=2:nan")
    monkeypatch.setenv("FAULT_ATTEMPT", "0")
    faults.reset()
    tel = str(tmp_path / "div.jsonl")
    with pytest.raises(health_lib.SimulationDiverged,
                       match=r"^group g0:heat3d DIVERGED"):
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=8,
                          groups="heat3d@0-3,heat3d@4-7", health=True,
                          log_every=2, telemetry=tel))
    faults.reset()
    hv = [e for e in _read_events(tel) if e.get("kind") == "health"]
    div = [e for e in hv if e["verdict"] == "DIVERGED"]
    assert div and div[0]["group"] == "g0:heat3d"


def test_group_conflicts_are_named():
    with pytest.raises(ValueError, match="--overlap .*does not compose"):
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=2,
                          groups="heat3d@0-3,heat3d@4-7", overlap=True))
    with pytest.raises(ValueError, match="--mesh .*does not compose"):
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=2,
                          groups="heat3d@0-3,heat3d@4-7", mesh=(2,)))


# ------------------------------------------------- ledger / policy

def test_grp_signature_and_baseline_key_tail(tmp_path):
    """Two coupled runs with DIFFERENT splits share a label but never a
    baseline: the |grp:<sig> tail keeps them apart, so the gate says
    NO_BASELINE — a split change must never read as a regression."""
    import importlib.util

    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    split_b = "heat3d@0-3:z1/3:mesh1x4,heat3d@4-7:mesh1x4"
    sig_a = groups_signature("heat3d@0-3,heat3d@4-7")
    sig_b = groups_signature(split_b)
    assert sig_a and sig_a != sig_b
    # signature is canonical: whitespace/case never split identities
    assert groups_signature(" heat3d@0-3 , heat3d@4-7 ") == sig_a

    ledger = str(tmp_path / "ledger.jsonl")
    logs = {}
    for tag, gspec in (("a", "heat3d@0-3,heat3d@4-7"),
                       ("a2", "heat3d@0-3,heat3d@4-7"),
                       ("b", split_b)):
        tel = str(tmp_path / f"run_{tag}.jsonl")
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=4,
                          groups=gspec, log_every=2, telemetry=tel))
        logs[tag] = tel
    rows_a = ledger_lib.rows_from_log(logs["a"])
    rows_b = ledger_lib.rows_from_log(logs["b"])
    assert rows_a and rows_b
    # the run-level row (per-group cli_grp_ rows ride alongside since
    # round 23 — they carry the single-clause signature instead)
    run_a = next(r for r in rows_a
                 if not r["label"].startswith("cli_grp_"))
    run_b = next(r for r in rows_b
                 if not r["label"].startswith("cli_grp_"))
    assert run_a["label"] == run_b["label"]  # same grp2 label
    key_a = ledger_lib.baseline_key(run_a)
    key_b = ledger_lib.baseline_key(run_b)
    assert f"|grp:{sig_a}" in key_a and f"|grp:{sig_b}" in key_b
    assert key_a != key_b

    ledger_lib.append_rows(rows_a, ledger)
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "scripts", "perf_gate.py"))
    gate_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate_mod)
    verdicts, _ = gate_mod.gate(logs["b"], ledger, 0.10)
    vb = next(v for v in verdicts if v["label"] == run_b["label"])
    assert vb["verdict"] == "NO_BASELINE"  # never REGRESSED
    # same split IS a baseline: a twin run (distinct source, identical
    # |grp: signature) gets judged against run a's row, not NO_BASELINE
    verdicts, _ = gate_mod.gate(logs["a2"], ledger, 0.10)
    va = next(v for v in verdicts if v["label"] == run_a["label"])
    assert va["verdict"] in ("OK", "IMPROVED", "REGRESSED")


def test_policy_treats_group_layout_as_identity(tmp_path):
    """candidates() never enumerates modes OVER a coupled config and
    the roofline never predicts one; --auto-policy instead resolves
    WITHIN it, per group (measured-beats-default across
    MODE_CANDIDATES), records one group_decisions entry per clause,
    and perf_gate --policy-check replays that resolution — exiting 1
    exactly when some group's ledger winner has moved."""
    import copy
    import importlib.util

    from mpi_cuda_process_tpu.obs import ledger as ledger_lib
    from mpi_cuda_process_tpu.policy import select as policy_select

    gspec = "heat3d@0-3,heat3d@4-7"
    cfg = RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=4,
                    groups=gspec)
    cands = policy_select.candidates(cfg, "cpu", frozenset())
    assert cands == [cfg]
    assert policy_select._predict(cfg, make_stencil("heat3d"),
                                  "cpu") is None

    tel = str(tmp_path / "pol.jsonl")
    cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=4,
                      groups=gspec, auto_policy=True,
                      log_every=2, telemetry=tel))
    evs = _read_events(tel)
    pol = [e for e in evs if e.get("kind") == "policy"]
    assert pol
    ev = pol[-1]
    assert ev["requested_groups"] == gspec
    gds = ev["group_decisions"]
    assert [d["group"] for d in gds] == ["g0:heat3d", "g1:heat3d"]
    # empty ledger: nothing measured, every clause keeps its request
    assert all(d["provenance"] == "requested" and not d["locked"]
               and d["modes"] == [] for d in gds)
    pg = [e for e in evs if e.get("kind") == "policy_group"]
    assert [e["group"] for e in pg] == ["g0:heat3d", "g1:heat3d"]

    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "scripts", "perf_gate.py"))
    gate_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate_mod)
    assert gate_mod.policy_check(
        tel, str(tmp_path / "empty_ledger.jsonl")) == 0
    # the run's OWN rows can only confirm the decision — the measured
    # winner IS the clause that just ran
    own = str(tmp_path / "own.jsonl")
    ledger_lib.append_rows(ledger_lib.rows_from_log(tel), own)
    assert gate_mod.policy_check(tel, own) == 0
    # seed a faster measured row for group 0's :stream candidate: the
    # replayed per-group winner moves, so the check must trip even
    # though the run-level label is unchanged
    rows = ledger_lib.read_rows(own)
    grp = next(r for r in rows if r["label"] == "cli_grp_heat3d")
    seed = copy.deepcopy(grp)
    stream_clause = groups_lib.parse_groups(gspec)[0] \
        .with_modes(("stream",)).canonical()
    seed["key"]["flags"] = ledger_lib.group_flags(stream_clause)
    seed["key_id"] = ledger_lib.key_id(seed["key"])
    seed["value"] = float(grp["value"]) * 10.0
    seed["measured_at"] = float(grp.get("measured_at") or 1.0) + 60.0
    flipped = str(tmp_path / "flipped.jsonl")
    ledger_lib.append_rows(rows + [seed], flipped)
    assert gate_mod.policy_check(tel, flipped) == 1


def test_group_transport_splits_the_baseline(tmp_path):
    """Twin coupled runs that differ ONLY in --group-transport share a
    label but never a baseline: the |gtx:collective key tail keeps the
    ppermute wire from being judged against the device_put staging
    path (and vice versa), so the gate says NO_BASELINE."""
    import importlib.util

    from mpi_cuda_process_tpu.obs import ledger as ledger_lib

    gspec = "heat3d@0-3,heat3d@4-7"
    logs = {}
    for transport in groups_lib.TRANSPORTS:
        tel = str(tmp_path / f"run_{transport}.jsonl")
        cli.run(RunConfig(stencil="heat3d", grid=(30, 16, 16), iters=4,
                          groups=gspec, group_transport=transport,
                          log_every=2, telemetry=tel))
        logs[transport] = tel
    rows_d = ledger_lib.rows_from_log(logs["device_put"])
    rows_c = ledger_lib.rows_from_log(logs["collective"])
    assert rows_d and rows_c
    assert rows_d[0]["label"] == rows_c[0]["label"]
    key_d = ledger_lib.baseline_key(rows_d[0])
    key_c = ledger_lib.baseline_key(rows_c[0])
    assert "|gtx:" not in key_d          # the default stays tail-free
    assert "|gtx:collective" in key_c
    assert key_d != key_c
    # per-group rows split the same way
    gd = next(r for r in rows_d if r["label"].startswith("cli_grp_"))
    gc = next(r for r in rows_c if r["label"].startswith("cli_grp_"))
    assert "|gtx:collective" in ledger_lib.baseline_key(gc)
    assert ledger_lib.baseline_key(gd) != ledger_lib.baseline_key(gc)

    ledger = str(tmp_path / "ledger.jsonl")
    ledger_lib.append_rows(rows_d, ledger)
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "scripts", "perf_gate.py"))
    gate_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate_mod)
    verdicts, _ = gate_mod.gate(logs["collective"], ledger, 0.10)
    vc = next(v for v in verdicts if v["label"] == rows_c[0]["label"])
    assert vc["verdict"] == "NO_BASELINE"  # never REGRESSED


# ------------------------------------------------------ observability

def _group_manifest():
    from mpi_cuda_process_tpu.obs import trace

    return trace.build_manifest(
        "cli", {"grid": [24, 16, 16], "groups": HET_GROUPS},
        groups=[{"group": "g0:wave3d", "op": "wave3d", "ratio": 2,
                 "dtype": "float32", "devices": [0, 3],
                 "grid": [14, 32, 32]},
                {"group": "g1:heat3d", "op": "heat3d", "ratio": 1,
                 "dtype": "float32", "devices": [4, 7],
                 "grid": [19, 16, 16]}])


def test_metrics_group_rows_and_worst_verdict():
    from mpi_cuda_process_tpu.obs.metrics import RunMetrics

    rm = RunMetrics()
    rm.ingest(_group_manifest())
    rm.ingest({"kind": "group_chunk", "step": 2, "group": "g0:wave3d",
               "op": "wave3d", "ratio": 2, "dtype": "float32",
               "steps": 2, "wall_s": 0.1, "mcells_per_s": 123.0})
    rm.ingest({"kind": "group_chunk", "step": 2, "group": "g1:heat3d",
               "op": "heat3d", "ratio": 1, "dtype": "float32",
               "steps": 2, "wall_s": 0.1, "mcells_per_s": 45.0})
    rm.ingest({"kind": "health", "verdict": "HEALTHY", "step": 2,
               "group": "g0:wave3d"})
    rm.ingest({"kind": "health", "verdict": "DIVERGED", "step": 2,
               "reason": "nonfinite", "group": "g1:heat3d"})
    st = rm.status()
    grp = st["groups"]
    assert grp["n_groups"] == 2
    assert grp["worst_verdict"] == "DIVERGED"
    # worst-first ranking: the diverged group leads the panel
    assert grp["rows"][0]["group"] == "g1:heat3d"
    assert grp["rows"][0]["verdict"] == "DIVERGED"
    assert grp["rows"][1]["mcells_per_s"] == 123.0
    assert grp["rows"][0]["devices"] == [4, 7]
    # a diverged GROUP dominates the run verdict, like run-level health
    assert st["verdict"] == "DIVERGED"
    snap = rm.registry.snapshot()
    assert snap["obs_group_chunks_total"]["value"] == 2.0


def test_obs_top_renders_group_panel():
    import importlib.util

    from mpi_cuda_process_tpu.obs.metrics import RunMetrics

    spec = importlib.util.spec_from_file_location(
        "obs_top", os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))),
            "scripts", "obs_top.py"))
    obs_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_top)

    rm = RunMetrics()
    rm.ingest(_group_manifest())
    rm.ingest({"kind": "group_chunk", "step": 2, "group": "g0:wave3d",
               "op": "wave3d", "ratio": 2, "dtype": "float32",
               "steps": 2, "wall_s": 0.1, "mcells_per_s": 123.0})
    rm.ingest({"kind": "health", "verdict": "HEALTHY", "step": 2,
               "group": "g0:wave3d"})
    body = obs_top.run_frame({**rm.status(), "manifest": None},
                             "/nonexistent")
    assert "2 device groups coupled at interface faces" in body
    assert "g0:wave3d" in body and "fine x2" in body
    assert "0-3" in body  # device range rendering
