"""Explicit interior/boundary overlap stepper == plain stepper == unsharded.

The overlap path (SURVEY.md §7.3.1 option (b), the re-design of the
reference's two-stream trick) must be bit-identical to the default path —
it changes only the dependency structure, never the values.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_sharded_step,
    make_step,
    make_stencil,
    shard_fields,
)


# One case per distinct boundary-ring mechanism (each costs a shard_map
# compile): int bit-exactness (life), corner coupling (27-point), halo-2
# ring (4th-order), carry field (wave).  Plain heat2d/heat3d overlap is
# subsumed by these plus test_sharded.py's non-overlap ladder.  The
# 27-point and halo-2 boundary-ring programs are the two heaviest compiles
# in the whole suite (~110s/66s on the CPU backend) — slow tier.  The
# default-tier anchor is life on a (2, 2) mesh (round 5: the (2, 4)
# 8-device variant alone cost ~112s of the CI budget; the boundary-ring
# splice is per-axis code, so the 4-device mesh exercises the same ring
# with the same corner traffic).
@pytest.mark.parametrize("name,grid,mesh_shape,params", [
    ("life", (16, 16), (2, 2), {}),
    pytest.param("life", (16, 24), (2, 4), {},
                 marks=pytest.mark.slow),               # asymmetric, 8-dev
    pytest.param("heat3d27", (8, 8, 8), (2, 2), {"alpha": 0.1},
                 marks=pytest.mark.slow),
    pytest.param("heat3d4th", (8, 8, 8), (2, 2), {"alpha": 0.05},
                 marks=pytest.mark.slow),               # halo 2 ring
    pytest.param("wave3d", (8, 8, 8), (2, 2), {"c2dt2": 0.1},
                 marks=pytest.mark.slow),               # carry field
])
def test_overlap_matches_unsharded(name, grid, mesh_shape, params):
    st = make_stencil(name, **params)
    fields = init_state(st, grid, seed=7, density=0.3,
                        kind="random" if name == "life" else "auto")
    ref = fields
    ref_step = make_step(st, grid)
    for _ in range(5):
        ref = ref_step(ref)

    mesh = make_mesh(mesh_shape)
    # jit once: the un-jitted shard_map re-lowers on every call, which
    # made this 39 s of pure re-trace (round-6 tier-1 timing); every real
    # caller runs the step under jit (driver.make_runner)
    step = jax.jit(make_sharded_step(st, mesh, grid, overlap=True))
    got = shard_fields(fields, mesh, st.ndim)
    for _ in range(5):
        got = step(got)

    for r, g in zip(ref, got):
        if np.issubdtype(np.asarray(r).dtype, np.integer):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(r))
        else:
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(r), rtol=1e-5, atol=1e-5)


def test_overlap_periodic_matches_plain():
    st = make_stencil("life")
    g = np.random.default_rng(3).integers(0, 2, (8, 8)).astype(np.int32)
    mesh = make_mesh((2, 2))
    plain = jax.jit(make_sharded_step(st, mesh, (8, 8), periodic=True))
    over = jax.jit(make_sharded_step(st, mesh, (8, 8), periodic=True,
                                     overlap=True))
    fp = shard_fields((jnp.asarray(g),), mesh, 2)
    fo = shard_fields((jnp.asarray(g),), mesh, 2)
    for _ in range(4):
        fp = plain(fp)
        fo = over(fo)
    np.testing.assert_array_equal(np.asarray(fo[0]), np.asarray(fp[0]))
