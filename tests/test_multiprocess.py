"""Real multi-process distributed run: 2 CPU processes over DCN (gloo).

The reference's multi-process story is mpirun with 2 ranks (kernel.cu:175-178,
SURVEY.md C15).  This test is the TPU-framework equivalent executed for real:
two OS processes bootstrap via ``jax.distributed`` (coordinator + worker),
build one global 2-device mesh, run the SAME SPMD step function, and the
sharded multi-process result must match a single-process reference bit-for-bit
(int Life grid).  Covers: bootstrap_distributed (C15), cross-process ppermute
halo exchange (C16), shard-native init (no process holds the full grid).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]

from mpi_cuda_process_tpu.parallel.mesh import bootstrap_distributed, make_mesh
from mpi_cuda_process_tpu import make_sharded_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.utils.init import init_state_sharded

ok = bootstrap_distributed(coordinator_address=f"localhost:{{port}}",
                           num_processes=2, process_id=rank, init_timeout_s=120)
assert ok and jax.process_count() == 2 and jax.device_count() == 2

st = make_stencil("life")
grid = (16, 16)
mesh = make_mesh((2,))  # split grid axis 0 across the two processes
fields = init_state_sharded(st, grid, mesh, seed=7, density=0.3,
                            kind="random")
step = make_sharded_step(st, mesh, grid)
out = make_runner(step, 5)(fields)
total = int(jax.numpy.sum(out[0]))  # replicated global reduction
pop0 = int(jax.numpy.sum(init_state_sharded(
    st, grid, mesh, seed=7, density=0.3, kind="random")[0]))
print(f"RESULT rank={{rank}} pop0={{pop0}} total={{total}}", flush=True)

# Second leg: temporal blocking UNDER the cross-process decomposition —
# k fused Pallas micro-steps (interpret mode on CPU) per width-k exchange,
# the width-k ppermute slabs now crossing the process boundary over DCN.
from mpi_cuda_process_tpu.parallel.stepper import make_sharded_fused_step

st2 = make_stencil("heat3d")
grid2 = (16, 8, 128)
mesh2 = make_mesh((2, 1, 1))
f2 = init_state_sharded(st2, grid2, mesh2, seed=3, density=0.3, kind="pulse")
fused = make_sharded_fused_step(st2, mesh2, grid2, k=4, interpret=True)
assert fused is not None
out2 = make_runner(fused, 1)(f2)
fsum = float(jax.numpy.sum(out2[0].astype(jax.numpy.float64)))
print(f"FUSED rank={{rank}} fsum={{fsum:.6f}}", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# Worker for the multi-host fault-injection test (SURVEY.md §5.3): runs a
# 2-process sharded Life simulation in 10-step chunks, orbax-checkpointing
# after every chunk (each process writes its own shards).  With --resume it
# first restores the latest step onto this pair's sharding.  Stops at the
# step given by sys.argv[3] (0 = run "forever", i.e. until killed).
_FAULT_WORKER = r"""
import sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
rank = int(sys.argv[1]); port = sys.argv[2]
horizon = int(sys.argv[3]); resume = sys.argv[4] == "resume"

from mpi_cuda_process_tpu.parallel.mesh import bootstrap_distributed, make_mesh
from mpi_cuda_process_tpu.parallel.stepper import grid_partition_spec
from mpi_cuda_process_tpu import make_sharded_step, make_stencil
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.utils.init import init_state_sharded
from mpi_cuda_process_tpu.utils import checkpointing

ok = bootstrap_distributed(coordinator_address=f"localhost:{{port}}",
                           num_processes=2, process_id=rank,
                           init_timeout_s=120)
assert ok and jax.process_count() == 2

st = make_stencil("life")
grid = (16, 16)
mesh = make_mesh((2,))
step = make_sharded_step(st, mesh, grid)
run10 = make_runner(step, 10)

done = 0
if resume:
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, grid_partition_spec(st.ndim, mesh))
    targets = tuple(jax.ShapeDtypeStruct(grid, st.dtype, sharding=sharding)
                    for _ in range(st.num_fields))
    fields, done, _ = checkpointing.orbax_load_checkpoint(
        {ck!r}, target_fields=targets)
    print(f"RESUMED rank={{rank}} step={{done}}", flush=True)
else:
    fields = init_state_sharded(st, grid, mesh, seed=7, density=0.3,
                                kind="random")

while horizon == 0 or done < horizon:
    fields = run10(fields)
    done += 10
    checkpointing.orbax_save_checkpoint({ck!r}, fields, done)

total = int(jax.numpy.sum(fields[0]))
print(f"RESULT rank={{rank}} step={{done}} total={{total}}", flush=True)
"""


@pytest.mark.slow
def test_two_process_distributed_matches_single():
    port = _free_port()
    script = _WORKER.format(repo=_REPO)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 local device per process -> 2 global
    procs = [
        subprocess.Popen([sys.executable, "-c", script, str(r), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         env=env, text=True)
        for r in range(2)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
        outs.append(out)

    results = {}
    fused = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                kv = dict(p.split("=") for p in line.split()[1:])
                results[int(kv["rank"])] = (int(kv["pop0"]), int(kv["total"]))
            elif line.startswith("FUSED"):
                kv = dict(p.split("=") for p in line.split()[1:])
                fused[int(kv["rank"])] = float(kv["fsum"])
    assert set(results) == {0, 1}
    # both processes must agree on the global state
    assert results[0] == results[1]

    # single-process reference with the same seed/init
    from mpi_cuda_process_tpu import init_state, make_step, make_stencil
    from mpi_cuda_process_tpu.driver import make_runner

    st = make_stencil("life")
    fields = init_state(st, (16, 16), seed=7, density=0.3, kind="random")
    pop0_ref = int(np.asarray(fields[0]).sum())
    ref = make_runner(make_step(st, (16, 16)), 5)(fields)
    total_ref = int(np.asarray(ref[0]).sum())
    assert results[0] == (pop0_ref, total_ref)

    # fused leg: cross-process sharded fused == 4 plain single-process steps
    assert set(fused) == {0, 1}
    assert fused[0] == fused[1]
    st2 = make_stencil("heat3d")
    f2 = init_state(st2, (16, 8, 128), seed=3, density=0.3, kind="pulse")
    r2 = make_runner(make_step(st2, (16, 8, 128)), 4)(f2)
    ref_sum = float(np.asarray(r2[0], np.float64).sum())
    # f32 state summed over 16k cells: compare relatively (few-ULP FMA
    # differences between the fused and plain graphs scale with the sum)
    assert abs(fused[0] - ref_sum) < 1e-5 * max(1.0, abs(ref_sum)), (
        fused[0], ref_sum)
