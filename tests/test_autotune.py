"""policy/autotune.py: measured kernel-variant sweeps (ISSUE 16).

The kernel constants — remote-DMA ring depth / chunk preference,
streaming ``(bz, by)`` strip geometry — become a measured policy
dimension.  The contract, pinned:

* **variants change schedule, never results** — every swept variant is
  bit-exact against the default constants, per stencil x dtype x mesh
  family (the full product rides the slow tier; one case per family
  stays in the default tier).
* **validation before any compile** — an infeasible candidate is
  rejected with a NAMED reason (sublane misalignment, non-dividing
  strips, VMEM overflow, prefer_nc that cannot steer the geometry);
  ``--kernel-variant`` raises that reason, never a silent fallback to
  the default constants.
* **ledger identity** — a variant row carries a ``|var:<id>`` baseline
  key (the ``|ensN`` pattern): it can never baseline a default row
  (perf_gate says NO_BASELINE across variants), and pre-variant keys
  stay byte-identical.
* **policy resolution** — ``select.resolve`` ranks ``|var:`` rows like
  any measured candidate (measured beats predicted; an explicit
  ``--kernel-variant`` is locked and recorded as an override).
* **parameterized chunk geometry** — ``pick_chunks`` /
  ``ring_exchange_stats`` take the variant knobs, and their defaults
  reproduce the historical 2-slot ``(4, 2)`` ladder byte-for-byte.

Runs on 8 virtual CPU devices (conftest.py); sharded builds use
prefix submeshes of 2 or 4 devices.
"""

import dataclasses
import importlib.util
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.ops.pallas import remote  # noqa: E402
from mpi_cuda_process_tpu.policy import autotune  # noqa: E402
from mpi_cuda_process_tpu.policy import select as ps  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(**kw):
    kw.setdefault("stencil", "heat3d")
    kw.setdefault("grid", (96, 32, 128))
    kw.setdefault("mesh", (2, 1, 1))
    kw.setdefault("fuse", 2)
    kw.setdefault("fuse_kind", "stream")
    kw.setdefault("iters", 2)
    return RunConfig(**kw)


def _seed(ledger_path, cfg, value, backend="cpu", source="seed"):
    """One measured ``ok`` row whose identity matches ``cfg`` exactly."""
    label, _ = ps._ledger_identity(cfg, backend)
    ledger_lib.append_rows([ledger_lib.make_row(
        label, value, source=source, measured_at=time.time(),
        backend=backend,
        flags=ledger_lib._flags(dataclasses.asdict(cfg)))], ledger_path)
    return label


def _perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate_autotune_t", os.path.join(_REPO, "scripts",
                                             "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------- registry / campaign

def test_tune_variant_is_the_campaign_label_contract():
    """``tuneN`` labels index the sweep tuples 1-based — the registry
    order is the measure.py Tier-D13 label meaning, append-only."""
    assert autotune.tune_variant("stream", 1).id == autotune.STREAM_SWEEP[0]
    assert autotune.tune_variant("stream", 2).id == "bz8y8"
    assert autotune.tune_variant("rdma", 2).id == "ring4"
    with pytest.raises(ValueError, match="unknown variant family"):
        autotune.tune_variant("fused", 1)
    with pytest.raises(ValueError, match="swept variants"):
        autotune.tune_variant("stream", len(autotune.STREAM_SWEEP) + 1)
    with pytest.raises(ValueError, match="swept variants"):
        autotune.tune_variant("rdma", 0)


def test_registry_families_and_tiles():
    for v in autotune.VARIANTS.values():
        assert v.family in ("rdma", "stream", "tiled"), v
        assert v.id in (autotune.STREAM_SWEEP + autotune.RDMA_SWEEP
                        + autotune.TILED_SWEEP)
    assert autotune.VARIANTS["bz16y32"].tiles == (16, 32)
    assert autotune.VARIANTS["ring3"].tiles is None
    assert autotune.VARIANTS["tz8y128"].tiles == (8, 128)


# ------------------------------------------- validation: named reasons

@pytest.mark.parametrize("kw,fragment", [
    (dict(fuse=0, fuse_kind="auto"), "explicit --fuse"),
    (dict(fuse_kind="auto"), "streaming kernel family"),
    (dict(mesh=()), "needs --mesh"),
    (dict(grid=(96, 32, 128), mesh=(1, 1, 2)), "x-sharded"),
])
def test_family_prerequisites_named(kw, fragment):
    cfg = _cfg(**kw)
    ok, reason = autotune.validate_variant(
        autotune.VARIANTS["bz16y16"], cfg)
    assert not ok and fragment in reason, reason


def _tiled_cfg(**kw):
    """Unsharded tiled-window config — the tiled family's host."""
    kw.setdefault("stencil", "heat3d")
    kw.setdefault("grid", (32, 128, 128))
    kw.setdefault("fuse", 4)
    kw.setdefault("fuse_kind", "tiled")
    kw.setdefault("iters", 2)
    return RunConfig(**kw)


def test_tune_variant_tiled_family():
    """Round 23: the tiled sweep joins the tuneN label contract."""
    assert autotune.tune_variant("tiled", 1).id == autotune.TILED_SWEEP[0]
    assert autotune.tune_variant("tiled", 3).id == "tz128y32"
    with pytest.raises(ValueError, match="swept variants"):
        autotune.tune_variant("tiled", len(autotune.TILED_SWEEP) + 1)


def test_tiled_family_prerequisites_named():
    v = autotune.VARIANTS["tz8y128"]
    # a tiled variant needs the tiled kind...
    ok, why = autotune.validate_variant(
        v, _tiled_cfg(fuse_kind="stream", mesh=(2, 1, 1)))
    assert not ok and "--fuse-kind tiled" in why
    # ...and no mesh (the padded window kernel is unsharded-only)
    ok, why = autotune.validate_variant(v, _tiled_cfg(mesh=(2, 1, 1)))
    assert not ok and "unsharded-only" in why
    # and a stream variant cannot ride a tiled config
    ok, why = autotune.validate_variant(autotune.VARIANTS["bz16y16"],
                                        _tiled_cfg())
    assert not ok and "--fuse-kind stream" in why


def test_tiled_geometry_rejections_named():
    v = autotune.VARIANTS["tz8y128"]
    # non-dividing tiles
    ok, why = autotune.validate_variant(autotune.VARIANTS["tz128y32"],
                                        _tiled_cfg())
    assert not ok and "does not divide Z" in why
    # bf16 k=4: 2m=8 misses the 16-row sublane tile — named, no compile
    ok, why = autotune.validate_variant(v, _tiled_cfg(dtype="bfloat16"))
    assert not ok and "sublane" in why
    # tiles not multiples of 2*margin (k=8 f32: 2m=16 rejects bz=8)
    ok, why = autotune.validate_variant(
        v, _tiled_cfg(fuse=8, grid=(32, 128, 128)))
    assert not ok and "2*margin" in why
    # VMEM overflow named from the _pick_tiles cost model, pre-compile
    ok, why = autotune.validate_variant(
        autotune.VARIANTS["tz32y128"], _tiled_cfg(grid=(32, 128, 2048)))
    assert not ok and "VMEM overflow" in why


def test_sweep_ids_tiled_config():
    assert autotune.sweep_ids(_tiled_cfg()) == list(autotune.TILED_SWEEP)
    # a sharded run never proposes the tiled family (maybe_autotune's
    # prereq probe follows the config's own kind)
    with pytest.raises(ValueError, match="drop --mesh"):
        autotune.maybe_autotune(_tiled_cfg(mesh=(2, 1, 1)))


def test_2d_grids_have_no_variants():
    cfg = RunConfig(stencil="heat2d", grid=(64, 64), mesh=(2, 1),
                    fuse=2, fuse_kind="stream")
    ok, reason = autotune.validate_variant(
        autotune.VARIANTS["bz16y16"], cfg)
    assert not ok and "3D" in reason


def test_rdma_variant_needs_rdma_exchange():
    ok, reason = autotune.validate_variant(autotune.VARIANTS["ring3"],
                                           _cfg())
    assert not ok and "--exchange rdma" in reason


def test_sublane_misaligned_by_rejected_bf16():
    """by=8 under bf16 (sublane tile 16) is named, not silently run."""
    ok, reason = autotune.validate_variant(
        autotune.VARIANTS["bz8y8"], _cfg(dtype="bfloat16"))
    assert not ok and "sublane" in reason and "by=8" in reason


def test_non_dividing_bz_rejected():
    cfg = _cfg(grid=(80, 32, 128))  # local Z = 40, not a multiple of 16
    ok, reason = autotune.validate_variant(
        autotune.VARIANTS["bz16y16"], cfg)
    assert not ok and "does not divide local Z=40" in reason


def test_vmem_overflow_rejected_by_name():
    """A ring deep enough to blow the kernel VMEM budget is rejected
    with the byte arithmetic in the reason, before any compile."""
    deep = autotune.KernelVariant(id="ring4096", family="rdma",
                                  nslots=4096)
    cfg = _cfg(grid=(96, 64, 128), exchange="rdma")
    ok, reason = autotune.validate_variant(deep, cfg)
    assert not ok and "VMEM overflow" in reason and "4096" in reason


def test_prefer_nc_that_cannot_steer_rejected():
    """prefer_nc that no chunkable axis honors would silently run the
    default geometry — named rejection instead (z-only bf16: the wm
    slab's sublane axis can't host 8 tile-aligned chunks)."""
    cfg = _cfg(grid=(96, 64, 128), exchange="rdma", dtype="bfloat16")
    ok, reason = autotune.validate_variant(autotune.VARIANTS["nc8"], cfg)
    assert not ok and "prefer_nc=8" in reason


def test_resolve_variant_forced_flag_contract():
    with pytest.raises(ValueError, match="unknown"):
        autotune.resolve_variant(_cfg(kernel_variant="nope"))
    with pytest.raises(ValueError, match="sublane"):
        autotune.resolve_variant(_cfg(kernel_variant="bz8y8",
                                      dtype="bfloat16"))
    v = autotune.resolve_variant(_cfg(kernel_variant="bz8y8"))
    assert v.tiles == (8, 8)


def test_variant_for_config_is_a_pruning_predicate():
    assert autotune.variant_for_config(_cfg(kernel_variant="")) is None
    assert autotune.variant_for_config(
        _cfg(kernel_variant="bz8y8", dtype="bfloat16")) is None
    v = autotune.variant_for_config(_cfg(kernel_variant="bz16y16"))
    assert v is autotune.VARIANTS["bz16y16"]


def test_cli_build_raises_named_reason():
    """--kernel-variant surfaces the named reason through build()."""
    with pytest.raises(ValueError, match="sublane"):
        cli.build(_cfg(kernel_variant="bz8y8", dtype="bfloat16"))


# ------------------------------- chunk-geometry parameterization pins

def test_nc_ladder_scales_with_ring_depth():
    assert remote._nc_ladder(2) == (4, 2)   # the historical ladder
    assert remote._nc_ladder(3) == (6, 3)
    assert remote._nc_ladder(4) == (8, 4)


def test_pick_chunks_defaults_reproduce_historical_ladder():
    """No-knob calls are byte-for-byte the pre-variant behavior."""
    for slab in [(2, 32, 128), (2, 64, 128), (48, 2, 128),
                 (2, 2, 128), (2, 30, 128), (3, 7, 128)]:
        assert remote.pick_chunks(slab, 4) == \
            remote.pick_chunks(slab, 4, nslots=2, prefer_nc=0)
    assert remote.pick_chunks((2, 32, 128), 4) == (1, 4)
    assert remote.pick_chunks((2, 2, 128), 4) == (0, 2)
    assert remote.pick_chunks((3, 7, 128), 4) == (0, 1)  # nothing divides


def test_pick_chunks_variant_knobs():
    # an honored preference leads the ladder...
    assert remote.pick_chunks((2, 64, 128), 4, prefer_nc=8) == (1, 8)
    # ...an impossible one falls back to the same gates, never bypasses
    assert remote.pick_chunks((2, 32, 128), 4, prefer_nc=8) == (1, 4)
    # a deeper ring raises the ladder floor
    assert remote.pick_chunks((2, 64, 128), 4, nslots=4) == (1, 8)


def test_ring_exchange_stats_reads_the_same_knobs():
    """The analytic half and the kernel builder share pick_chunks, so
    the stats must move with the variant knobs."""
    base = remote.ring_exchange_stats((2, 64, 128), "float32")
    assert base["nslots"] == 2 and base["nchunks"] == 4
    deep = remote.ring_exchange_stats((2, 64, 128), "float32", nslots=4)
    assert deep["nslots"] == 4 and deep["nchunks"] == 8
    assert deep["remote_dma_per_call"] == 16
    pref = remote.ring_exchange_stats((2, 64, 128), "float32",
                                      prefer_nc=8)
    assert pref["nchunks"] == 8
    # same total bytes regardless of chunking
    assert deep["ici_bytes_per_call"] == base["ici_bytes_per_call"]


# ------------------------------------------- ledger |var: identity

def test_baseline_key_var_dimension():
    var = ledger_lib.make_row(
        "cli_heat3d_96x32x128_fuse2_stream_mesh2x1x1_varbz8y8", 10.0,
        source="autotune", backend="cpu",
        flags={"fuse": 2, "fuse_kind": "stream",
               "kernel_variant": "bz8y8"})
    default = ledger_lib.make_row(
        "cli_heat3d_96x32x128_fuse2_stream_mesh2x1x1", 10.0,
        source="autotune", backend="cpu",
        flags={"fuse": 2, "fuse_kind": "stream"})
    assert ledger_lib.baseline_key(var).endswith("|var:bz8y8")
    # pre-variant rows keep their historical key verbatim
    assert ledger_lib.baseline_key(default) == \
        "cli_heat3d_96x32x128_fuse2_stream_mesh2x1x1|cpu"
    assert ledger_lib.baseline_key(var) != ledger_lib.baseline_key(default)


def test_cli_label_and_flags_carry_the_variant():
    cfg = _cfg(kernel_variant="bz8y8")
    d = dataclasses.asdict(cfg)
    assert ledger_lib._cli_label(d).endswith("_varbz8y8")
    assert ledger_lib._flags(d)["kernel_variant"] == "bz8y8"
    d_def = dataclasses.asdict(_cfg())
    assert "kernel_variant" not in (ledger_lib._flags(d_def) or {})
    assert "var" not in ledger_lib._cli_label(d_def).rsplit("_", 1)[-1]


def test_perf_gate_no_baseline_across_variants(tmp_path):
    """A label measured only under the default constants must gate a
    variant manifest as NO_BASELINE, never REGRESSED — the constants
    are part of the baseline identity."""
    judge = _perf_gate().judge
    label = "cli_heat3d_96x32x128_fuse2_stream_mesh2x1x1"
    row_def = ledger_lib.make_row(label, 80.0, source="telemetry:/a",
                                  backend="cpu", flags={"fuse": 2})
    row_var = ledger_lib.make_row(
        label + "_varbz8y8", 40.0, source="telemetry:/b", backend="cpu",
        flags={"fuse": 2, "kernel_variant": "bz8y8"})
    path = str(tmp_path / "ledger.jsonl")
    ledger_lib.append_rows([row_def], path)
    baselines = ledger_lib.best_known(ledger_lib.read_rows(path))
    verdict, ratio = judge(
        row_var, baselines.get(ledger_lib.baseline_key(row_var)), 0.10)
    assert verdict == "NO_BASELINE" and ratio is None
    # same-variant rows still gate normally
    verdict_def, _ = judge(
        dict(row_def, value=40.0),
        baselines.get(ledger_lib.baseline_key(row_def)), 0.10)
    assert verdict_def == "REGRESSED"


# -------------------------------------------------- sweep ordering

def test_prioritize_sweep_follows_attribution():
    comm = {"attribution": "ok", "compute_us": 100.0,
            "exposed_comm_us": 100.0}
    compute = {"attribution": "ok", "compute_us": 900.0,
               "exposed_comm_us": 100.0}
    fams = ["stream", "rdma"]
    assert autotune.prioritize_sweep(comm, fams) == ["rdma", "stream"]
    assert autotune.prioritize_sweep(compute, fams) == ["stream", "rdma"]
    # no usable attribution: the caller's order stands
    assert autotune.prioritize_sweep(None, fams) == fams
    assert autotune.prioritize_sweep({"attribution": "degraded"},
                                     ["rdma", "stream"]) == \
        ["rdma", "stream"]
    # a single family has nothing to reorder
    assert autotune.prioritize_sweep(comm, ["stream"]) == ["stream"]


def test_sweep_ids_lead_with_the_transport_family():
    ids_pp = autotune.sweep_ids(_cfg())
    assert ids_pp == list(autotune.STREAM_SWEEP)
    ids_rdma = autotune.sweep_ids(_cfg(exchange="rdma"))
    assert ids_rdma == list(autotune.RDMA_SWEEP + autotune.STREAM_SWEEP)
    comm = {"attribution": "ok", "compute_us": 1.0,
            "exposed_comm_us": 9.0}
    assert autotune.sweep_ids(_cfg(exchange="rdma"), comm)[:3] == \
        list(autotune.RDMA_SWEEP)


# ------------------------------------- maybe_autotune sweep mechanics

def test_maybe_autotune_rejects_ineligible_configs():
    with pytest.raises(ValueError, match="--autotune.*--mesh"):
        autotune.maybe_autotune(_cfg(mesh=()))


def test_maybe_autotune_rows_winner_and_var_keys(tmp_path, monkeypatch):
    """The sweep records the default + every validated variant as
    ``|var:`` ledger rows and names the measured winner (probe
    monkeypatched — the mechanics, not the clock, are under test)."""
    values = {"": 50.0, "bz16y16": 40.0, "bz8y8": 75.0}

    def fake_probe(cfg, calls):
        return values[cfg.kernel_variant]

    monkeypatch.setattr(autotune, "_probe_mcells", fake_probe)
    path = str(tmp_path / "ledger.jsonl")
    out = autotune.maybe_autotune(_cfg(), ledger_path=path,
                                  ids=["bz16y16", "bz8y8"])
    assert [s["id"] for s in out["swept"]] == \
        ["default", "bz16y16", "bz8y8"]
    assert out["winner"] == "bz8y8" and out["rows"] == 3
    assert not out["skipped"]
    rows = ledger_lib.read_rows(path)
    keys = {ledger_lib.baseline_key(r) for r in rows}
    assert {k.split("|var:")[-1] if "|var:" in k else "" for k in keys} \
        == {"", "bz16y16", "bz8y8"}
    assert all(r["source"] == "autotune" for r in rows)
    # the default row's key is the plain cli identity a real run carries
    label, bk = ps._ledger_identity(_cfg(), "cpu")
    assert bk in keys and "|var:" not in bk


def test_maybe_autotune_skips_with_named_reasons(tmp_path, monkeypatch):
    """bf16 on a 32-row local Y: every stream variant is infeasible —
    the sweep still probes the default and names each rejection."""
    monkeypatch.setattr(autotune, "_probe_mcells", lambda c, n: 1.0)
    out = autotune.maybe_autotune(
        _cfg(dtype="bfloat16"), ledger_path=str(tmp_path / "l.jsonl"))
    assert [s["id"] for s in out["swept"]] == ["default"]
    reasons = {s["id"]: s["reason"] for s in out["skipped"]}
    assert "sublane" in reasons["bz8y8"]
    assert "y-strip window" in reasons["bz16y16"]
    assert out["winner"] == "default"


def test_maybe_autotune_survives_a_failed_probe(tmp_path, monkeypatch):
    """A crashing candidate is a named sweep result, never an abort."""
    def flaky(cfg, calls):
        if cfg.kernel_variant == "bz16y16":
            raise RuntimeError("probe wedged")
        return 10.0

    monkeypatch.setattr(autotune, "_probe_mcells", flaky)
    out = autotune.maybe_autotune(
        _cfg(), ledger_path=str(tmp_path / "l.jsonl"),
        ids=["bz16y16", "bz8y8"])
    assert [s["id"] for s in out["swept"]] == ["default", "bz8y8"]
    assert any(s["id"] == "bz16y16" and "probe failed" in s["reason"]
               for s in out["skipped"])


# --------------------------------------------- policy resolution

def test_resolve_picks_the_measured_variant_winner(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    cfg = _cfg()
    _seed(path, cfg, 1e6)
    _seed(path, dataclasses.replace(cfg, kernel_variant="bz8y8"), 9e6)
    dec = ps.resolve(cfg, backend="cpu", ledger_path=path, n_devices=2)
    assert dec.provenance == "measured"
    assert dec.config.kernel_variant == "bz8y8"
    assert dec.label.endswith("_varbz8y8")
    # the requested record keeps the pre-resolution value
    assert dec.requested["kernel_variant"] == ""


def test_explicit_variant_is_locked_and_recorded(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    cfg = _cfg(kernel_variant="bz16y16")
    assert "kernel_variant" in ps.locked_fields(cfg)
    _seed(path, _cfg(kernel_variant="bz8y8"), 9e6)  # faster, but locked out
    dec = ps.resolve(cfg, backend="cpu", ledger_path=path, n_devices=2)
    assert dec.config.kernel_variant == "bz16y16"
    assert dec.overrides["kernel_variant"] == "bz16y16"


def test_candidates_extend_feasible_variants_only():
    locked = ps.locked_fields(_cfg())
    cands = ps.candidates(_cfg(), "cpu", locked, None, 2)
    vids = {c.kernel_variant for c in cands}
    # z-only f32 on (96,32,128): bz16y32's y window does not fit, nor
    # do the mg16/mg32 widened margins (by + 2*margin > Y for every
    # tileable by); oxy has no x-windowed strip grid to permute; the
    # traversal-order variant orev is geometry-free and stays feasible
    assert vids == {"", "bz16y16", "bz8y8", "orev"}
    pinned = ps.candidates(_cfg(), "cpu",
                           locked | frozenset(["kernel_variant"]),
                           None, 2)
    assert {c.kernel_variant for c in pinned} == {""}


def test_kernel_variant_is_a_mode_and_adoptable_field():
    assert "kernel_variant" in ps.MODE_FIELDS
    assert "kernel_variant" in ps.ADOPTABLE_FIELDS


# ------------------------------------------------- bit-exactness

def _assert_variants_bit_exact(cfg, vids):
    _, step, fields, _ = cli.build(cfg)
    want = step(fields)
    for vid in vids:
        ok, why = autotune.validate_variant(autotune.VARIANTS[vid], cfg)
        assert ok, f"{vid} infeasible under {cfg.grid}/{cfg.mesh}: {why}"
        vcfg = dataclasses.replace(cfg, kernel_variant=vid)
        _, vstep, vfields, _ = cli.build(vcfg)
        assert getattr(vstep, "_kernel_variant", "") == vid
        got = vstep(vfields)
        for a, b in zip(want, got):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=vid)


def test_stream_variants_bit_exact_zonly_f32():
    _assert_variants_bit_exact(_cfg(), ("bz16y16", "bz8y8"))


def test_margin_order_named_rejections():
    """Round-18 sweep dims reject with named reasons, never compile."""
    # mg16's widened flank cannot fit Y=32 (by + 2*16 > 32 for every by)
    ok, why = autotune.validate_variant(autotune.VARIANTS["mg16"], _cfg())
    assert not ok and "margin 16" in why
    # oxy permutes a 2-d strip grid; whole-lane strips have none
    ok, why = autotune.validate_variant(autotune.VARIANTS["oxy"], _cfg())
    assert not ok and "order=xy" in why
    # a sublane-misaligned margin is named before any geometry check
    bad = autotune.KernelVariant(id="mg12", family="stream", margin=12)
    ok, why = autotune.validate_variant(bad, _cfg())
    assert not ok and "sublane-misaligned" in why
    # an unknown order token is named
    bad = autotune.KernelVariant(id="ozz", family="stream", order="zz")
    ok, why = autotune.validate_variant(bad, _cfg())
    assert not ok and "unknown strip order" in why


@pytest.mark.slow
def test_stream_margin_order_variants_bit_exact():
    """The widened-margin and traversal-order constants change DMA
    shapes and walk order only — fields stay bit-identical to the
    default kernel through the full build path."""
    _assert_variants_bit_exact(_cfg(), ("orev",))
    _assert_variants_bit_exact(_cfg(grid=(96, 96, 128)),
                               ("mg16", "mg32"))


def test_tiled_variant_bit_exact_unsharded_f32():
    """A swept window tile computes the exact default-picker fields
    through the full cli build (the rest of the tiled product is slow)."""
    _assert_variants_bit_exact(_tiled_cfg(), ("tz8y128",))


def test_candidates_extend_tiled_variants_unsharded():
    """The policy enumeration proposes the tiled family for unsharded
    tiled configs — same dimension the streaming mesh configs grew."""
    cfg = _tiled_cfg()
    locked = ps.locked_fields(cfg)
    cands = ps.candidates(cfg, "cpu", locked, None, 2)
    vids = {c.kernel_variant for c in cands}
    assert {"tz8y128", "tz32y128"} <= vids, vids
    # the infeasible tile is pruned by the _valid predicate, not listed
    assert "tz128y32" not in vids  # bz=128 cannot divide Z=32


@pytest.mark.slow
def test_tiled_variants_bit_exact_matrix():
    grid = (128, 128, 128)
    _assert_variants_bit_exact(_tiled_cfg(grid=grid),
                               autotune.TILED_SWEEP)
    _assert_variants_bit_exact(_tiled_cfg(stencil="wave3d", grid=grid),
                               autotune.TILED_SWEEP)
    # bf16 hosts k=8 (2m=16): bz=8 drops out of the sweep by name
    _assert_variants_bit_exact(
        _tiled_cfg(dtype="bfloat16", fuse=8, grid=grid),
        ("tz32y128", "tz128y32"))


def test_rdma_variant_bit_exact_zonly_f32():
    """A deeper ring computes the exact default-ring fields (the full
    rdma sweep and the other stencil/dtype/mesh combos ride slow)."""
    _assert_variants_bit_exact(
        _cfg(grid=(96, 64, 128), exchange="rdma"), ("ring3",))


_MATRIX = [
    # the full stencil x dtype x mesh-family product (ISSUE 16
    # acceptance); each row lists every feasible swept variant
    ("heat3d", "float32", (96, 32, 128), (2, 1, 1),
     ("bz16y16", "bz8y8")),
    ("heat3d", "bfloat16", (96, 64, 128), (2, 1, 1),
     ("bz16y16", "bz16y32")),
    ("heat3d", "float32", (96, 64, 128), (2, 2, 1),
     ("bz16y16", "bz8y8", "bz16y32")),
    ("heat3d", "bfloat16", (96, 128, 128), (2, 2, 1),
     ("bz16y16", "bz16y32")),
    ("wave3d", "float32", (96, 32, 128), (2, 1, 1),
     ("bz16y16", "bz8y8")),
    ("wave3d", "bfloat16", (96, 64, 128), (2, 1, 1),
     ("bz16y16", "bz16y32")),
    ("wave3d", "float32", (96, 64, 128), (2, 2, 1),
     ("bz16y16", "bz8y8", "bz16y32")),
    ("wave3d", "bfloat16", (96, 128, 128), (2, 2, 1),
     ("bz16y16", "bz16y32")),
]


@pytest.mark.slow
@pytest.mark.parametrize("stencil,dtype,grid,mesh,vids", _MATRIX)
def test_stream_variants_bit_exact_matrix(stencil, dtype, grid, mesh,
                                          vids):
    _assert_variants_bit_exact(
        _cfg(stencil=stencil, dtype=dtype, grid=grid, mesh=mesh), vids)


@pytest.mark.slow
@pytest.mark.parametrize("stencil,dtype,mesh,vids", [
    ("heat3d", "float32", (2, 1, 1), ("ring4", "nc8")),
    ("heat3d", "bfloat16", (2, 1, 1), ("ring3", "ring4")),
    ("wave3d", "float32", (2, 2, 1), ("ring3", "ring4")),
])
def test_rdma_variants_bit_exact_matrix(stencil, dtype, mesh, vids):
    _assert_variants_bit_exact(
        _cfg(stencil=stencil, dtype=dtype, grid=(96, 64, 128),
             mesh=mesh, exchange="rdma"), vids)
