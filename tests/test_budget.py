"""Per-device HBM budget guard (utils/budget.py).

The reference mallocs the FULL grid on every rank with no error checking
(kernel.cu:184-191); this framework refuses an over-HBM config up front
with the arithmetic in the error.  These tests pin the BASELINE config-5
budget table documented in docs/STATE.md.
"""

import pytest

from mpi_cuda_process_tpu import make_stencil
from mpi_cuda_process_tpu.utils import budget

GiB = 2**30
V5E_HBM = 16 * GiB


def _total(name, grid, mesh=(), fuse=0, ensemble=0, **kw):
    st = make_stencil(name, **kw)
    total, parts = budget.estimate_run_bytes(
        st, grid, mesh=mesh, fuse=fuse, ensemble=ensemble)
    assert total == sum(b for _, b in parts)
    return total


def test_config5_f32_jnp_refused_with_arithmetic():
    """4096^3 wave f32 on 64 chips WITHOUT temporal blocking does not
    fit (2x4 GiB state + 4 out + ~8 GiB exchange-padded jnp copies).
    The guard must say so, with numbers."""
    st = make_stencil("wave3d")
    with pytest.raises(ValueError) as e:
        budget.check_budget(st, (4096,) * 3, mesh=(8, 8, 1),
                            hbm_bytes=V5E_HBM)
    msg = str(e.value)
    assert "GiB per device" in msg and "state: 2 field(s)" in msg
    assert "bfloat16" in msg  # the actionable lever is named


def test_config5_f32_two_axis_mesh_padfree_fits():
    """The 2-axis headline budget row (docs/STATE.md table): wave3d
    4096^3 in FULL f32 on an 8x8x1 mesh FITS at fuse 4 — the 2-axis
    pad-free kernels (y-slab + corner operands) replace the ~8 GiB
    exchange-padded transient that used to push this config past HBM,
    and the estimate follows the constructible path (the wide-X 2-axis
    builder actually tiles wave at 4096 lanes).  Pinned to the byte:
    2x4 GiB state + 4 GiB out + 0.379 GiB slab+corner operands, +10%."""
    st = make_stencil("wave3d")
    total, parts = budget.check_budget(st, (4096,) * 3, mesh=(8, 8, 1),
                                       fuse=4, hbm_bytes=V5E_HBM)
    # independent arithmetic (not the module's own constants)
    lz, ly, lx, m, item, nf = 512, 512, 4096, 4, 4, 2
    state = 2 * lz * ly * lx * item
    out = lz * ly * lx * item
    slabs = (2 * m * ly * lx            # z slabs
             + 2 * (2 * m) * lz * lx    # 2m-duplicated y-slab operands
             + 4 * m * (2 * m) * lx     # 2m-duplicated corner pieces
             ) * item * nf
    assert total == int((state + out + slabs) * 1.10) == 14_620_924_313
    assert any("pad-free" in label for label, _ in parts)
    assert not any("pad transient" in label for label, _ in parts)
    assert not any("exchange" in label for label, _ in parts)


def test_config5_bf16_fits():
    """bf16 at k=8 on the 8x8x1 mesh: ~7.0 GiB/device (state 4 + out 2 +
    0.38 GiB slab+corner operands + overhead) — the 2-axis pad-free path
    replaced the round-5 exchange-padded estimate (11.3 GiB)."""
    st = make_stencil("wave3d", dtype="bfloat16")
    total, parts = budget.check_budget(st, (4096,) * 3, mesh=(8, 8, 1),
                                       fuse=8, hbm_bytes=V5E_HBM)
    # pinned tight: a regression reinflating the estimate (e.g. the pad
    # transient coming back) must fail here, not drift in a loose range
    assert 6.8 * GiB < total < 7.3 * GiB
    assert any("pad-free" in label for label, _ in parts)


def test_1024_padfree_fits_padded_does_not_appear():
    """1024^3 f32 fused: prefer_padfree kicks in, so no pad transient is
    counted and the config fits (~8.8 GiB) — the round-4 1024^3 design."""
    st = make_stencil("heat3d")
    total, parts = budget.estimate_run_bytes(st, (1024,) * 3, fuse=4)
    assert total < 9.5 * GiB
    assert any("pad-free" in label for label, _ in parts)


def test_1024_jnp_estimate_reflects_pad_transient():
    t_jnp = _total("heat3d", (1024,) * 3)
    t_fused = _total("heat3d", (1024,) * 3, fuse=4)
    assert t_jnp > t_fused  # the pad copy is the difference


def test_ensemble_scales_estimate():
    assert _total("heat3d", (256,) * 3, ensemble=8) > \
        7 * _total("heat3d", (256,) * 3)


def test_mesh_shrinks_local_block():
    assert _total("heat3d", (512,) * 3, mesh=(2, 2, 2)) < \
        _total("heat3d", (512,) * 3) / 4


def test_small_config_passes_guard():
    st = make_stencil("heat2d")
    total, _ = budget.check_budget(st, (512, 512), hbm_bytes=V5E_HBM)
    assert total < GiB


def test_cli_flag_parses_and_cpu_backend_skips():
    from mpi_cuda_process_tpu.cli import _check_mem_budget, config_from_args

    cfg = config_from_args(
        ["--stencil", "wave3d", "--grid", "4096,4096,4096",
         "--mesh", "8,8,1", "--fuse", "4", "--mem-check", "error"])
    assert cfg.mem_check == "error"
    # CPU backend: the guard is a no-op (virtual-device test meshes would
    # otherwise trip on host-RAM-sized grids)
    _check_mem_budget(cfg)


def test_raw_path_has_no_transient():
    """compute="raw" (whole-step kernels: the state is its own halo) must
    not be charged a pad transient — a fitting raw run was refused before
    this was threaded through (round-4 review finding)."""
    st = make_stencil("heat3d27")
    grid = (1152, 1152, 1152)
    t_raw, parts = budget.estimate_run_bytes(st, grid, compute="raw")
    t_jnp, _ = budget.estimate_run_bytes(st, grid)
    assert t_raw < t_jnp
    assert any("no pad transient" in label for label, _ in parts)
    # ~5.7 GiB state + 5.7 out + 10% — fits 16 GiB where jnp would not
    budget.check_budget(st, grid, compute="raw", hbm_bytes=16 * GiB)
    with pytest.raises(ValueError):
        budget.check_budget(st, grid, hbm_bytes=16 * GiB)


def test_stream_kind_has_no_transient_and_probes_buildability():
    """--fuse-kind stream: the ring lives in VMEM, so HBM holds state +
    output only; the estimate must probe construction so a 'fits' never
    describes an unconstructible run (the budget module's invariant)."""
    st = make_stencil("heat3d")
    total, parts = budget.estimate_run_bytes(
        st, (1024,) * 3, fuse=4, fuse_kind="stream")
    assert any("streaming fused: no pad transient" in label
               for label, _ in parts)
    # 4 GiB state + 4 out + 10% < 16 GiB: the 1024^3 f32 single-chip path
    budget.check_budget(st, (1024,) * 3, fuse=4, fuse_kind="stream",
                        hbm_bytes=16 * GiB)
    # unbuildable shape (too few z chunks): labeled, never silently 'fits'
    _, parts2 = budget.estimate_run_bytes(
        st, (16, 16, 128), fuse=4, fuse_kind="stream")
    assert any("UNBUILDABLE" in label for label, _ in parts2)
    # periodic: cli.build rejects stream (guard-frame), so the estimate
    # must label the path UNBUILDABLE rather than describe a kernel the
    # run never takes (round-4 advisor).  Ensemble runs are BUILDABLE
    # since round 15 (the batched streaming kernel) — priced, not
    # walled; pinned in tests/test_ensemble_engine.py.
    _, parts3 = budget.estimate_run_bytes(
        st, (256,) * 3, fuse=4, fuse_kind="stream", periodic=True)
    assert any("UNBUILDABLE" in label for label, _ in parts3)
    _, parts4 = budget.estimate_run_bytes(
        st, (256,) * 3, fuse=4, fuse_kind="stream", ensemble=2)
    assert not any("UNBUILDABLE" in label for label, _ in parts4)


def test_config5_stream_envelope_builder_verified():
    """Builder-verified config-5 streaming envelope (docs/STATE.md): at
    the local shape 64x4096x4096 (4096^3 on 64x1x1), single-field
    families tile whole-lane; two-field wave3d exceeds the whole-lane
    VMEM gate but tiles via an X-WINDOWED strip (~1.9x read amp vs the
    wide-X tiled kernel's 4.5x).  The picker must never admit a config
    the kernel can't host — a silent admit would compile-OOM a slice."""
    from mpi_cuda_process_tpu.ops.pallas.streamfused import (
        _stream_gates,
        build_stream_sharded_call,
    )

    local, g5 = (64, 4096, 4096), (4096, 4096, 4096)
    st = make_stencil("heat3d")
    assert build_stream_sharded_call(st, local, g5, 4,
                                     interpret=True) is not None
    wave = make_stencil("wave3d")
    assert build_stream_sharded_call(wave, local, g5, 4,
                                     interpret=True) is not None
    gates = _stream_gates(wave, 64, 4096, 4096, 4, None, sharded=True)
    assert gates[7] is not None  # wave needs the x window (bx set)
    # whole-lane tiles forced for wave at this shape must still decline
    assert build_stream_sharded_call(wave, local, g5, 4, tiles=(8, 16),
                                     interpret=True) is None


def test_sharded_stream_budget_slab_operands_only():
    """--fuse-kind stream --mesh: HBM holds state + output + slab
    operands (the VMEM ring is not HBM); config-5 wave in FULL f32 fits
    the same envelope as the zslab kernels, now on the streaming path."""
    st = make_stencil("wave3d")
    total, parts = budget.check_budget(
        st, (4096,) * 3, mesh=(64, 1, 1), fuse=4, fuse_kind="stream",
        hbm_bytes=V5E_HBM)
    assert any("sharded streaming: slab operands" in label
               for label, _ in parts)
    assert 14 * GiB < total < 15 * GiB  # 2x4 state + 4 out + 1 slabs +10%
    # unconstructible local shape: labeled, never a silent 'fits'
    _, parts2 = budget.estimate_run_bytes(
        st, (64, 64, 128), mesh=(16, 1, 1), fuse=4, fuse_kind="stream")
    assert any("UNBUILDABLE" in label for label, _ in parts2)


def test_forced_padfree_never_estimates_the_padded_transient():
    """fuse_kind='padfree' has no padded fallback in cli.build — the
    estimate must not charge padded-transient bytes the run would never
    allocate (it raises instead)."""
    st = make_stencil("heat3d")
    # a shape the padfree builder declines (odd extents)
    t_forced, parts = budget.estimate_run_bytes(
        st, (20, 20, 128), fuse=4, fuse_kind="padfree")
    assert any("pad-free fused" in label for label, _ in parts)
    assert not any("pad transient (+" in label for label, _ in parts)


def test_f32_at_4096_fits_on_z_only_mesh_padfree():
    """The round-4 headline budget row: 4096^3 in FULL f32 fits a 64-chip
    v5e on a z-only mesh with the z-slab pad-free kernel (~9.35 GiB) —
    for the single-field families, and ONLY because the builder actually
    tiles it (the estimate follows the constructible path)."""
    st = make_stencil("heat3d")
    total, parts = budget.check_budget(
        st, (4096,) * 3, mesh=(64, 1, 1), fuse=4, hbm_bytes=V5E_HBM)
    assert 9 * GiB < total < 10 * GiB
    assert any("pad-free" in label for label, _ in parts)


def test_config5_stream_budget_exact_bytes():
    """The launch-day arithmetic, pinned to the byte: config 5 (wave3d
    4096^3, 64x1x1 z-mesh, --fuse 4 --fuse-kind stream) per-device live
    bytes.  bf16: 2 fields x 2 GiB state + 2 GiB donated out + 0.5 GiB
    slab operands, +10% workspace = 7,677,254,041 B (7.150 GiB);
    f32 doubles it to 14.300 GiB.  Both fit 16 GiB v5e HBM — config 5
    is budget-clean in BOTH dtypes on the streaming path, and the
    breakdown the operator reads at launch is exactly this."""
    item = {"bfloat16": 2, "float32": 4}
    for dtype, total_expect in (("bfloat16", 7_677_254_041),
                                ("float32", 15_354_508_083)):
        st = make_stencil("wave3d", dtype=dtype)
        total, parts = budget.estimate_run_bytes(
            st, (4096,) * 3, mesh=(64, 1, 1), fuse=4, fuse_kind="stream")
        # independent arithmetic (not the module's own constants)
        lz, ly, lx = 64, 4096, 4096
        state = 2 * lz * ly * lx * item[dtype]
        out = lz * ly * lx * item[dtype]
        slabs = 2 * 4 * ly * lx * item[dtype] * 2  # 2 sides x m=4, 2 fields
        assert total == int((state + out + slabs) * 1.10) == total_expect
        assert any("slab operands only" in label for label, _ in parts)
        budget.check_budget(st, (4096,) * 3, mesh=(64, 1, 1), fuse=4,
                            fuse_kind="stream", hbm_bytes=16 * GiB)


def test_config5_stream_two_axis_budget_exact_bytes():
    """Round 8: config 5 on the BALANCED 8x8x1 mesh through the 2-AXIS
    streaming kernel — the kind x mesh matrix's last cell, pinned to the
    byte for BOTH dtypes.  HBM holds state + out + the slab/corner
    operand set (z slabs at width m; y slabs and corners at width m plus
    the call's wm_a-aligned copies — 8 for f32, 16 for bf16); the VMEM
    rings are not HBM.  Both dtypes fit 16 GiB v5e HBM, so mesh shape is
    now purely a measurement decision for the streaming kind too."""
    for dtype, item, m_a, total_expect in (
            ("float32", 4, 8, 14_770_870_681),
            ("bfloat16", 2, 16, 7_535_381_708)):
        st = make_stencil("wave3d", dtype=dtype)
        total, parts = budget.estimate_run_bytes(
            st, (4096,) * 3, mesh=(8, 8, 1), fuse=4, fuse_kind="stream")
        # independent arithmetic (not the module's own constants)
        lz, ly, lx, m, nf = 512, 512, 4096, 4, 2
        state = 2 * lz * ly * lx * item
        out = lz * ly * lx * item
        slabs = (2 * m * ly * lx                # z slabs
                 + 2 * (m + m_a) * lz * lx     # y slabs + aligned copies
                 + 4 * m * (m + m_a) * lx      # corners + aligned copies
                 ) * item * nf
        assert total == int((state + out + slabs) * 1.10) == total_expect
        assert any("2-axis stream" in label for label, _ in parts)
        assert not any("UNBUILDABLE" in label for label, _ in parts)
        assert not any("pad transient" in label for label, _ in parts)
        budget.check_budget(st, (4096,) * 3, mesh=(8, 8, 1), fuse=4,
                            fuse_kind="stream", hbm_bytes=V5E_HBM)


def test_config5_pipelined_stream_budget_exact_bytes():
    """Round 9: config 5 through the slab-carry PIPELINED exchange
    (--pipeline), pinned to the byte on BOTH mesh families and BOTH
    dtypes.  The carry adds exactly one slab set beyond the per-pass
    operands (this pass's slabs are consumed while the next pass's are
    in flight), and all four cells still fit 16 GiB v5e HBM — config 5
    stays budget-clean on the new schedule, including the VERDICT
    item-5 bf16-k4 stream rows."""
    from mpi_cuda_process_tpu.ops.pallas.fused import _sublane

    expect = {
        ("float32", (64, 1, 1)): 16_535_624_089,
        ("float32", (8, 8, 1)): 15_368_349_286,
        ("bfloat16", (64, 1, 1)): 8_267_812_044,
        ("bfloat16", (8, 8, 1)): 7_984_067_379,
    }
    for (dtype, mesh), total_expect in expect.items():
        st = make_stencil("wave3d", dtype=dtype)
        total, parts = budget.estimate_run_bytes(
            st, (4096,) * 3, mesh=mesh, fuse=4, fuse_kind="stream",
            pipeline=True)
        # independent arithmetic (not the module's own constants)
        item = {"bfloat16": 2, "float32": 4}[dtype]
        lz, ly, lx = (int(g) // c for g, c in zip((4096,) * 3, mesh))
        m, nf = 4, 2
        state = 2 * lz * ly * lx * item
        out = lz * ly * lx * item
        if mesh == (64, 1, 1):
            slab_set = 2 * m * ly * lx * item * nf
        else:
            m_a = _sublane(item)  # m=4 rounds up to one sublane tile
            slab_set = (2 * m * ly * lx
                        + 2 * (m + m_a) * lz * lx
                        + 4 * m * (m + m_a) * lx) * item * nf
        assert total == int((state + out + 2 * slab_set) * 1.10) \
            == total_expect, (dtype, mesh)
        assert any("pipelined carried slabs" in label
                   for label, _ in parts)
        budget.check_budget(st, (4096,) * 3, mesh=mesh, fuse=4,
                            fuse_kind="stream", pipeline=True,
                            hbm_bytes=V5E_HBM)


def test_pipelined_padfree_counts_carried_set_once():
    """The pad-free kinds: pipeline adds exactly ONE slab+corner set
    (the carry), on top of the per-pass operand set — and the padded
    sharded path is labeled UNSUPPORTED (cli raises; the estimate must
    describe the refusal, not a kernel the run never takes)."""
    st = make_stencil("wave3d")
    t_plain, _ = budget.estimate_run_bytes(
        st, (4096,) * 3, mesh=(8, 8, 1), fuse=4, fuse_kind="padfree")
    t_pipe, parts = budget.estimate_run_bytes(
        st, (4096,) * 3, mesh=(8, 8, 1), fuse=4, fuse_kind="padfree",
        pipeline=True)
    carried = [b for label, b in parts
               if "pipelined carried slabs" in label]
    slab = [b for label, b in parts if "sharded pad-free" in label]
    assert carried == slab  # one extra copy of the per-pass operand set
    assert t_pipe == t_plain + int(carried[0] * 1.10) or \
        abs(t_pipe - t_plain - carried[0] * 1.10) <= 1  # int rounding
    # padded sharded kind + pipeline: labeled UNSUPPORTED, zero bytes
    small = make_stencil("heat3d")
    _, parts2 = budget.estimate_run_bytes(
        small, (64, 64, 128), mesh=(2, 1, 1), fuse=4, pipeline=True)
    assert any("UNSUPPORTED" in label for label, _ in parts2)


def test_stream_two_axis_unbuildable_is_labeled():
    """An unconstructible 2-axis streaming config must be labeled, never
    a silent 'fits' (the budget module's invariant) — local z below the
    3-chunk gate here."""
    st = make_stencil("heat3d")
    _, parts = budget.estimate_run_bytes(
        st, (32, 64, 128), mesh=(2, 2, 1), fuse=4, fuse_kind="stream")
    assert any("UNBUILDABLE" in label for label, _ in parts)


def test_config5_wave_f32_fits_via_wide_x_kernel():
    """Two-field wave3d cannot tile the WHOLE-ROW z-slab window at X=4096
    (VMEM gate), but the wide-X variant windows the lane axis and tiles —
    so the budget charges slabs only and config 5 fits in FULL f32
    (~14.3 GiB/device).  The chain is builder-verified: a 'fits' row
    never describes an unconstructible execution (round-4 review)."""
    st = make_stencil("wave3d")
    total, parts = budget.check_budget(
        st, (4096,) * 3, mesh=(64, 1, 1), fuse=4, hbm_bytes=V5E_HBM)
    assert any("pad-free" in label for label, _ in parts)
    assert 13.5 * GiB < total < 15 * GiB


def test_config5_wave_bf16_k8_wide_x_headroom():
    """bf16 k=8 (margin 8, sublane-16-aligned) tiles wide-X too: config-5
    wave in bf16 with temporal blocking is ~7.7 GiB/device — deep
    headroom for larger tiles or deeper k once measured."""
    st = make_stencil("wave3d", dtype="bfloat16")
    total, parts = budget.check_budget(
        st, (4096,) * 3, mesh=(64, 1, 1), fuse=8, hbm_bytes=V5E_HBM)
    assert any("pad-free" in label for label, _ in parts)
    assert total < 8.5 * GiB


def test_2d_fuse_budget_counts_fullgrid_pad():
    t_plain = _total("life", (2048, 2048))
    t_fused = _total("life", (2048, 2048), fuse=16)
    assert t_fused > 0 and t_plain > 0  # both paths covered, no crash
