"""Numerics sentinel (obs/health.py): the correctness half of obs.

Layers, matching the module's design:

* **reduction** — ``make_health_fn``'s per-field stats + NaN/Inf
  counts and the per-op REGISTERED invariants (heat total heat with
  the wall-scale drift floor, wave's exactly-conserved leapfrog
  energy, SOR's one-sided decreasing residual, Life's track-only
  population);
* **trend detector** — ``HealthMonitor``'s chunk-0 baseline + drift
  rules, the hard NaN trigger, per-member divergence for ensembles;
* **fault site** — ``FAULT_INJECT=numerics:step=N:nan`` poisons one
  cell deterministically (gating, once-only, the driver's
  callback-replacement hook carries the corruption forward);
* **verdict flow** — DIVERGED everywhere WEDGED already flows: the
  CLI aborts, the supervisor gives up WITHOUT a restart (unit fake +
  real-subprocess e2e), the ledger quarantines with reason
  ``diverged`` (so perf_gate reports QUARANTINED and best_known can
  never baseline it), /status.json + obs_top render and exit nonzero,
  the engine handle surfaces the verdict, the root span carries the
  ``health`` attribute;
* **invariance** — the jitted step jaxpr is byte-identical with
  ``--health`` on vs off (the zero-ops acceptance pin).
"""

import importlib.util
import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu import cli, driver  # noqa: E402
from mpi_cuda_process_tpu.obs import health as health_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import metrics as metrics_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import trace as trace_lib  # noqa: E402
from mpi_cuda_process_tpu.ops.stencil import make_stencil  # noqa: E402
from mpi_cuda_process_tpu.resilience import faults  # noqa: E402
from mpi_cuda_process_tpu.resilience import supervisor as sup  # noqa: E402
from mpi_cuda_process_tpu.utils.init import init_state  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obs_top():
    return _load_script("obs_top_health_t", "scripts/obs_top.py")


def _events(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def _health_events(path):
    return [e for e in _events(path) if e.get("kind") == "health"]


# ---------------------------------------------------------- reduction

def test_health_fn_stats_and_nonfinite_counts():
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    fn = health_lib.make_health_fn(st)
    vals = jax.device_get(fn(fields))
    assert vals["field0_nonfinite"] == 0
    assert float(vals["field0_max"]) == pytest.approx(100.0)  # frame
    poisoned = (fields[0].at[(8, 16)].set(jnp.nan),)
    vals = jax.device_get(fn(poisoned))
    assert int(vals["field0_nonfinite"]) == 1
    assert math.isnan(float(vals["invariant"]))  # mean over a NaN cell


def test_registered_invariants_per_op():
    """The invariant is registered PER OP in ops/, never in obs."""
    assert make_stencil("heat3d").invariant.name == "total_heat"
    assert make_stencil("heat3d").invariant.scale == 100.0
    assert make_stencil("heat3d27").invariant.name == "total_heat"
    wave = make_stencil("wave3d").invariant
    assert wave.name == "discrete_energy" and wave.mode == "conserve"
    sor = make_stencil("sor3d").invariant
    assert sor.name == "residual_norm" and sor.mode == "decrease"
    life = make_stencil("life").invariant
    assert life.name == "population" and life.rtol is None
    # an invalid mode is rejected at registration time
    from mpi_cuda_process_tpu.ops.stencil import HealthInvariant

    with pytest.raises(ValueError):
        HealthInvariant("x", lambda f: 0.0, mode="sideways")


def test_wave_discrete_energy_is_exactly_conserved():
    """The registered wave invariant is the leapfrog scheme's conserved
    energy: 30 real steps move it by fp roundoff only."""
    st = make_stencil("wave2d")
    fields = init_state(st, (32, 64), seed=1, kind="pulse")
    step = driver.make_step(st, (32, 64))
    e0 = float(st.invariant.fn(fields))
    for _ in range(30):
        fields = step(fields)
    e1 = float(st.invariant.fn(tuple(jax.device_get(fields))))
    assert e0 > 0
    assert abs(e1 - e0) / e0 < 1e-4


def test_drift_modes_and_scale_floor():
    d = health_lib.drift
    assert d(1.0, 1.0, None, "conserve") == 0.0
    assert d(3.0, 1.0, None, "conserve") == pytest.approx(2.0)
    # decrease: shrinking is progress, never drift
    assert d(0.1, 1.0, None, "decrease") == 0.0
    assert d(2.0, 1.0, None, "decrease") == pytest.approx(1.0)
    # the scale floor: Dirichlet heat saturating toward bc=100 from a
    # near-zero baseline reads as drift < 1, a blow-up as huge drift
    assert d(90.0, 1.0, 100.0, "conserve") < 1.0
    assert d(1e6, 1.0, 100.0, "conserve") > 1e3
    assert d(float("nan"), 1.0, None, "conserve") == float("inf")


# ----------------------------------------------------- trend detector

def test_monitor_clean_then_nan_diverges():
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    mon = health_lib.HealthMonitor(st)
    rec = mon.check(0, fields, chunk=0)
    assert rec["verdict"] == "HEALTHY" and rec["baseline_step"] == 0
    poisoned = (fields[0].at[(8, 16)].set(jnp.inf),)
    with pytest.raises(health_lib.SimulationDiverged) as exc:
        mon.check_or_raise(10, poisoned, chunk=1)
    assert "non-finite" in str(exc.value)
    assert exc.value.record["nonfinite_total"] == 1
    assert mon.verdict == "DIVERGED"


def test_monitor_diverges_on_invariant_drift_without_nan():
    """Finite-but-wrong state: a x10 scale jump blows the conserved
    wave energy far past its 5% tolerance with zero NaNs."""
    st = make_stencil("wave2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    mon = health_lib.HealthMonitor(st)
    assert mon.check(0, fields)["verdict"] == "HEALTHY"
    scaled = (fields[0] * 10.0, fields[1])
    rec = mon.check(10, scaled)
    assert rec["verdict"] == "DIVERGED"
    assert rec["nonfinite_total"] == 0
    assert "discrete_energy" in rec["reason"]
    assert rec["invariant"]["drift"] > st.invariant.rtol


def test_monitor_track_only_invariant_never_diverges_on_drift():
    st = make_stencil("life")
    fields = init_state(st, (16, 32), seed=0, kind="random")
    mon = health_lib.HealthMonitor(st)
    assert mon.check(0, fields)["verdict"] == "HEALTHY"
    # population collapses to zero: tracked, never a verdict
    rec = mon.check(10, (jnp.zeros_like(fields[0]),))
    assert rec["verdict"] == "HEALTHY"
    assert rec["invariant"]["value"] == 0.0


def test_monitor_stamps_root_span_health_attr():
    class _Spans:
        root_attrs = {}

    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    mon = health_lib.HealthMonitor(st, spans=_Spans())
    mon.check(0, fields)
    assert mon.spans.root_attrs["health"] == "HEALTHY"
    mon.check(1, (fields[0].at[(8, 16)].set(jnp.nan),))
    assert mon.spans.root_attrs["health"] == "DIVERGED"


def test_monitor_ensemble_per_member_stats_and_spread():
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse", ensemble=3)
    mon = health_lib.HealthMonitor(st, ensemble=3)
    rec = mon.check(0, fields, chunk=0)
    assert rec["verdict"] == "HEALTHY"
    assert len(rec["invariant"]["value"]) == 3
    assert rec["ensemble"]["members"] == 3
    assert rec["ensemble"]["nonfinite_members"] == 0
    # poison ONE member: the run diverges and the record names it
    poisoned = (fields[0].at[(1, 8, 16)].set(jnp.nan),) + fields[1:]
    rec = mon.check(5, poisoned, chunk=1)
    assert rec["verdict"] == "DIVERGED"
    assert rec["ensemble"]["nonfinite_members"] == 1
    assert rec["fields"][0]["nonfinite"] == [0, 1, 0]


# ----------------------------------------------------------- poisoning

def test_apply_nan_poison_center_cell_and_int_raises():
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    out = health_lib.apply_nan_poison(fields)
    assert bool(jnp.isnan(out[0][8, 16]))
    assert int(jnp.sum(~jnp.isfinite(out[0]))) == 1
    life = init_state(make_stencil("life"), (16, 32), seed=0,
                      kind="random")
    with pytest.raises(ValueError):
        health_lib.apply_nan_poison(life)


def test_fault_spec_numerics_parsing():
    specs = faults.parse_specs("numerics:step=40:nan")
    assert specs[0].site == "numerics" and specs[0].action == "nan"
    assert specs[0].step == 40
    for bad in ("numerics:sigkill", "exchange:nan", "numerics:wedge"):
        with pytest.raises(ValueError):
            faults.parse_specs(bad)


def test_injected_numeric_poison_gating(monkeypatch):
    assert faults.injected_numeric_poison(100) is None
    monkeypatch.setenv("FAULT_INJECT", "numerics:step=40:nan")
    monkeypatch.setenv("FAULT_ATTEMPT", "1")
    assert faults.injected_numeric_poison(100) is None  # wrong attempt
    monkeypatch.setenv("FAULT_ATTEMPT", "0")
    assert faults.injected_numeric_poison(39) is None  # below the gate
    spec = faults.injected_numeric_poison(45)
    assert spec is not None and spec.raw == "numerics:step=40:nan"
    assert faults.injected_numeric_poison(50) is None  # one-shot


# ------------------------------------------------------------ CLI e2e

def test_cli_health_clean_run_emits_healthy_stream(tmp_path):
    path = str(tmp_path / "clean.jsonl")
    cli.run(cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
         "--log-every", "2", "--health", "--telemetry", path]))
    hs = _health_events(path)
    assert len(hs) == 4
    assert all(h["verdict"] == "HEALTHY" for h in hs)
    assert hs[0]["invariant"]["name"] == "total_heat"
    # a clean run's row is scoreable (health never quarantines HEALTHY)
    rows = ledger_lib.rows_from_log(path)
    assert rows and rows[0]["status"] == "ok"
    assert rows[0].get("health") == "HEALTHY"


def test_cli_health_synthesizes_cadence_without_log_every(tmp_path):
    """--health with no logging cadence must still observe boundaries
    (the synthesized ~8-chunk cadence), not silently check nothing."""
    path = str(tmp_path / "nocad.jsonl")
    cli.run(cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,64", "--iters", "16",
         "--health", "--telemetry", path]))
    assert len(_health_events(path)) >= 2


def test_cli_health_diverged_e2e_poison_to_quarantine(tmp_path,
                                                      monkeypatch):
    """The acceptance chain, in-process: numerics poison -> DIVERGED
    health event -> run aborts -> ledger row quarantined 'diverged' ->
    best_known structurally excludes it."""
    monkeypatch.setenv("FAULT_INJECT", "numerics:step=4:nan")
    path = str(tmp_path / "div.jsonl")
    with pytest.raises(health_lib.SimulationDiverged):
        cli.run(cli.config_from_args(
            ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
             "--log-every", "2", "--health", "--telemetry", path]))
    hs = _health_events(path)
    assert hs[-1]["verdict"] == "DIVERGED"
    assert hs[-1]["step"] == 4
    assert hs[-1]["nonfinite_total"] == 1
    # the error event landed too (the run recorded how it ended)
    kinds = [e.get("kind") for e in _events(path)]
    assert "error" in kinds and "summary" not in kinds
    # ledger: quarantined with reason 'diverged', never a baseline
    rows = ledger_lib.rows_from_log(path)
    assert len(rows) == 1
    assert rows[0]["status"] == "quarantined"
    assert rows[0]["quarantine"] == "diverged"
    assert rows[0]["health"] == "DIVERGED"
    assert ledger_lib.best_known(rows) == {}


def test_cli_health_diverged_without_poison_events_still_summarized(
        tmp_path, monkeypatch):
    """perf_gate's view: the diverged row is QUARANTINED, not scored."""
    monkeypatch.setenv("FAULT_INJECT", "numerics:step=2:nan")
    path = str(tmp_path / "gate.jsonl")
    with pytest.raises(health_lib.SimulationDiverged):
        cli.run(cli.config_from_args(
            ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
             "--log-every", "2", "--health", "--telemetry", path]))
    perf_gate = _load_script("perf_gate_health_t", "scripts/perf_gate.py")
    ledger = str(tmp_path / "ledger.jsonl")
    verdicts, fresh = perf_gate.gate(path, ledger, 0.10)
    assert len(verdicts) == 1
    assert verdicts[0]["verdict"] == "QUARANTINED"
    assert verdicts[0]["quarantine"] == "diverged"


def test_health_jaxpr_invariance_on_vs_off(tmp_path):
    """Acceptance pin: the jitted step jaxpr is byte-identical with
    --health on vs off — the sentinel is a separately-jitted reduction
    at chunk boundaries, never ops in the step."""
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 64), seed=0, kind="pulse")
    step = driver.make_step(st, (16, 64))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype)
                     for f in fields)
    jaxpr_before = str(jax.make_jaxpr(step)(abstract))
    runner_before = str(jax.make_jaxpr(
        driver.make_runner(step, 4, jit=False))(abstract))
    cli.run(cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
         "--log-every", "2", "--health",
         "--telemetry", str(tmp_path / "jx.jsonl")]))
    assert str(jax.make_jaxpr(step)(abstract)) == jaxpr_before
    assert str(jax.make_jaxpr(
        driver.make_runner(step, 4, jit=False))(abstract)) == \
        runner_before


def test_driver_callback_replacement_carries_state_forward():
    """The numerics fault's transport: a callback returning fields
    replaces the carried state (None keeps it)."""
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 32), seed=0, kind="pulse")
    step = driver.make_step(st, (16, 32))

    def poison_once(done, fs):
        if done == 2:
            return (fs[0].at[(8, 16)].set(jnp.nan),)
        return None

    out = driver.run_simulation(st, fields, 4, step_fn=step,
                                log_every=2, callback=poison_once)
    # the NaN spread from the poisoned cell: the replacement CONTINUED
    assert int(jnp.sum(~jnp.isfinite(out[0]))) > 1


# ------------------------------------------------------- verdict flow

def _health_event(verdict, reason=None, **extra):
    return {"kind": "health", "verdict": verdict, "reason": reason,
            "t": 1.0, "step": 40, **extra}


def test_watch_child_returns_fatal_on_diverged():
    class _Handle:
        def poll(self):
            return None

        def kill(self):
            pass

        def wait(self, timeout_s=30.0):
            return None

    class _Tail:
        def __init__(self):
            self._batches = [[], [_health_event("HEALTHY"),
                                  _health_event("DIVERGED",
                                                reason="nan blow-up")]]

        def poll(self):
            return self._batches.pop(0) if self._batches else []

    outcome, value, detail = sup.watch_child(
        _Handle(), [_Tail()], stall_timeout_s=60.0, poll_s=0.0,
        clock=lambda: 0.0, sleep=lambda s: None)
    assert outcome == "fatal" and value == "DIVERGED"
    assert "nan" in detail


def test_supervise_gives_up_without_restart_on_diverged(tmp_path):
    """The non-restartable contract: one attempt, zero restarts, a
    give_up event carrying the verdict — never a resume into the same
    blow-up."""
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "meta.json").write_text(json.dumps(
        {"step": 30, "num_fields": 0, "config": {}}))

    class _Handle:
        def __init__(self):
            self.killed = False

        def poll(self):
            return None

        def kill(self):
            self.killed = True

        def wait(self, timeout_s=30.0):
            return None

    class _Tail:
        def __init__(self):
            self._batches = [[_health_event("DIVERGED", reason="boom")]]

        def poll(self):
            return self._batches.pop(0) if self._batches else []

    class _Session:
        path = "fake.supervisor.jsonl"

        def __init__(self):
            self.events = []

        def event(self, kind, **payload):
            self.events.append({"kind": kind, **payload})

    session = _Session()
    handles = []

    def launcher(attempt, resume):
        h = _Handle()
        handles.append(h)
        return h, [_Tail()]

    res = sup.supervise(launcher, str(ck), max_restarts=2,
                        backoff_base_s=0.0, stall_timeout_s=60.0,
                        poll_s=0.0, session=session,
                        sleep=lambda s: None, clock=lambda: 0.0)
    assert not res.ok and res.gave_up
    assert res.attempts == 1 and res.restarts == []
    assert len(handles) == 1 and handles[0].killed
    kinds = [e["kind"] for e in session.events]
    assert "restart" not in kinds
    gu = [e for e in session.events if e["kind"] == "give_up"][0]
    assert gu["verdict"] == "DIVERGED"
    assert "non-restartable" in gu["reason"]


def test_supervised_diverged_e2e_gives_up_without_restart(tmp_path,
                                                          monkeypatch):
    """Real subprocess e2e: an injected numerics:step=40:nan under
    --supervise --health ends with supervisor give-up (rc 1) after ONE
    attempt — the DIVERGED half of the tier-1 acceptance pin."""
    monkeypatch.setenv("FAULT_INJECT", "numerics:step=40:nan")
    tel = str(tmp_path / "run.jsonl")
    cfg = cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "48,48", "--iters", "100",
         "--seed", "7", "--checkpoint-every", "10",
         "--checkpoint-dir", str(tmp_path / "ck"),
         "--telemetry", tel, "--health",
         "--supervise", "--max-restarts", "2",
         "--restart-backoff", "0.2", "--supervise-stall-s", "120"])
    rc = sup.run_supervised(cfg)
    assert rc == 1
    evs = _events(sup.sibling_path(tel, "supervisor"))
    kinds = [e.get("kind") for e in evs]
    assert "restart" not in kinds
    assert len([e for e in evs if e.get("kind") == "launch"]) == 1
    gu = [e for e in evs if e.get("kind") == "give_up"]
    assert gu and gu[0]["verdict"] == "DIVERGED"
    child = _health_events(sup.sibling_path(tel, "attempt0"))
    assert child[-1]["verdict"] == "DIVERGED"
    assert child[-1]["step"] == 40


def test_status_verdict_and_obs_top_probe(tmp_path, obs_top):
    rm = metrics_lib.RunMetrics()
    rm.ingest(_health_event("HEALTHY"))
    assert rm.status()["verdict"] == "ALIVE"
    assert rm.status()["health"]["verdict"] == "HEALTHY"
    rm.ingest(_health_event(
        "DIVERGED", reason="boom", nonfinite_total=3,
        invariant={"name": "total_heat", "drift": 9.0, "rtol": 2.0},
        worst_field={"field": 0, "drift": 9.0}))
    st = rm.status()
    assert st["verdict"] == "DIVERGED"
    snap = rm.registry.snapshot()
    assert snap["obs_health_diverged"]["value"] == 1.0
    assert snap["obs_health_nonfinite_values"]["value"] == 3
    assert snap["obs_health_invariant_drift"]["value"] == 9.0
    assert obs_top.health_rc(st) == 1
    # the rendered frame names the sentinel state
    body = obs_top.run_frame({**st, "manifest": None}, "/nonexistent")
    assert "DIVERGED" in body and "total_heat" in body


def test_obs_top_once_exits_nonzero_on_diverged_log(tmp_path, obs_top,
                                                    capsys):
    path = str(tmp_path / "div.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("health", verdict="DIVERGED", reason="boom", step=40,
                nonfinite_total=1)
    assert obs_top.main([path, "--once"]) == 1
    capsys.readouterr()


def test_aggregate_worst_verdict_includes_diverged(tmp_path):
    from mpi_cuda_process_tpu.obs import aggregate

    agg = aggregate.HostAggregator()
    m = trace_lib.build_manifest("cli", {})
    agg.ingest("a.jsonl", m)
    agg.ingest("a.jsonl", _health_event("DIVERGED", reason="boom"))
    st = agg.status()
    assert st["aggregate"]["verdict"] == "DIVERGED"


def test_engine_handle_surfaces_health_verdict(tmp_path, monkeypatch):
    """ROADMAP item-1 contract: a scheduler evicts diverged members
    from handle.status() alone — no log parsing."""
    from mpi_cuda_process_tpu.engine import SimulationEngine

    monkeypatch.setenv("FAULT_INJECT", "numerics:step=4:nan")
    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
         "--log-every", "2", "--health"]))
    with pytest.raises(health_lib.SimulationDiverged):
        h.result(timeout=120)
    st = h.status()
    assert st["verdict"] == "DIVERGED"
    assert st["health"]["verdict"] == "DIVERGED"
    assert st["request"]["phase"] == "failed"
    assert h.health_verdict() == "DIVERGED"
