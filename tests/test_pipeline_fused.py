"""Cross-pass pipelined halo exchange == the per-pass exchange, bit-exact.

``make_sharded_fused_step(pipeline=True)`` restructures WHEN the
width-m exchange is issued — the slabs ride the ``lax.scan`` carry, and
pass i+1's exchange is issued from pass i's boundary-shell outputs, one
full interior pass ahead of its consumer — but must never change a
value: the carried slabs hold exactly the bytes the per-pass exchange
would fetch, so the equivalence here is pinned BIT-EXACT (assert_array
_equal, bf16 included), not allclose.

Every equivalence case scans >= 3 iterations through the pipeline-aware
runner (driver.make_runner threads the carry), so the slabs are
exercised well past the prologue: iteration 3's shells consume slabs
exchanged from iteration 2's shell outputs — a stale-carry or
wrong-border bug cannot survive.

Structure (the perf claim) is asserted through the reusable helper
(utils/jaxprcheck.py, also invoked by scripts/tier1.sh): exactly one
exchange round per scan iteration, and — with overlap — the two-sided
interior/exchange independence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu import driver
from mpi_cuda_process_tpu.driver import make_runner
from mpi_cuda_process_tpu.parallel.stepper import (
    make_sharded_fused_step,
    make_sharded_temporal_step,
)
from mpi_cuda_process_tpu.utils.jaxprcheck import (
    assert_pipeline_body_structure,
    count_primitive,
)


def _pair(name, grid, mesh_shape, k, kind=None, padfree=None,
          overlap=False, kw=None):
    st = make_stencil(name, **(kw or {}))
    mesh = make_mesh(mesh_shape)
    mk = lambda pipe: make_sharded_fused_step(  # noqa: E731
        st, mesh, grid, k, interpret=True, kind=kind, padfree=padfree,
        overlap=overlap, pipeline=pipe)
    plain, pipe = mk(False), mk(True)
    assert plain is not None and pipe is not None, (name, grid, mesh_shape)
    assert getattr(pipe, "_pipeline_active", False)
    assert not getattr(plain, "_pipeline_active", False)
    if overlap:
        assert getattr(pipe, "_overlap_active", False), \
            "overlap geometry unexpectedly declined — fix the test shape"
    return st, mesh, plain, pipe


def _run_scanned(st, mesh, step, fields, steps):
    return make_runner(step, steps)(shard_fields(fields, mesh, 3))


def _assert_bitexact(got, ref):
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(r))


# ---------------------------------------------------------------------------
# The acceptance matrix: heat3d/wave3d/sor3d x (2,1,1)/(2,2,1)/(1,2,1)
# x padfree/stream x with/without overlap, >= 3 scan iterations.  The
# default tier keeps one anchor per ingredient (z-only overlap, 2-axis
# overlap, 2-axis stream with the wave carry field, non-overlap body);
# redundant combinations ride the slow tier — each slow case names what
# only it adds.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,grid,mesh_shape,k,kind,padfree,overlap", [
    # z-only pad-free, both bodies (the non-overlap body is a different
    # code path: next slabs exchanged from the kernel output itself)
    ("heat3d", (32, 16, 128), (2, 1, 1), 4, None, True, False),
    ("heat3d", (32, 16, 128), (2, 1, 1), 4, None, True, True),
    # 2-axis pad-free overlap: y shells + two-hop corner re-exchange
    ("heat3d", (32, 32, 128), (2, 2, 1), 4, None, True, True),
    # 2-axis stream overlap with the two-field leapfrog carry (slow: the
    # compiled-stream default pin is test_cli's config-5 rehearsal; the
    # structure gate rides tier1.sh)
    pytest.param("wave3d", (48, 32, 128), (2, 2, 1), 4, "stream", None,
                 True, marks=pytest.mark.slow),
    # 2-axis pad-free non-overlap body (full slab+corner set re-exchanged
    # from the output)
    pytest.param("heat3d", (32, 32, 128), (2, 2, 1), 4, None, True, False,
                 marks=pytest.mark.slow),
    # y-only degenerate mesh: z slabs are bc dummies every iteration
    pytest.param("heat3d", (32, 32, 128), (1, 2, 1), 4, None, True, True,
                 marks=pytest.mark.slow),
    # z-only stream (slab splice into the sliding window)
    pytest.param("heat3d", (48, 32, 128), (2, 1, 1), 4, "stream", None,
                 True, marks=pytest.mark.slow),
    # y-only stream (corner pieces substitute the z overhang)
    pytest.param("heat3d", (24, 32, 128), (1, 2, 1), 4, "stream", None,
                 False, marks=pytest.mark.slow),
    # wave3d z-only pad-free: carry-field slabs ride the carry too
    pytest.param("wave3d", (32, 16, 128), (2, 1, 1), 4, None, True, True,
                 marks=pytest.mark.slow),
    # red-black parity: m = 2k, shells re-offset, phase order preserved
    pytest.param("sor3d", (64, 16, 128), (2, 1, 1), 4, None, True, True,
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (64, 64, 128), (2, 2, 1), 4, None, True, True,
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (96, 32, 128), (2, 2, 1), 4, "stream", None,
                 False, marks=pytest.mark.slow),
])
def test_pipeline_matches_plain(name, grid, mesh_shape, k, kind, padfree,
                                overlap):
    st, mesh, plain, pipe = _pair(name, grid, mesh_shape, k, kind=kind,
                                  padfree=padfree, overlap=overlap)
    fields = init_state(st, grid, seed=9, kind="pulse")
    _assert_bitexact(_run_scanned(st, mesh, pipe, fields, 3),
                     _run_scanned(st, mesh, plain, fields, 3))


@pytest.mark.slow  # bf16-stream default pin: test_cli config-5 rehearsal
def test_pipeline_bf16_k4_stream_bitexact():
    """bf16 at k=4 (stream-only: the tiled kinds need k=8) through the
    slab-carry scan — bit-exact, not allclose: the carried slabs hold
    the same bf16 bytes the per-pass exchange would.  Non-overlap body:
    the overlap SHELLS are tiled-kernel instances whose 2m=8 extent
    misses the bf16 sublane tile (16), so bf16 k=4 has never hosted the
    split — the pipeline's k=4 bf16 story is the non-split body (the
    k=8 pad-free case below covers split+carry in bf16)."""
    st, mesh, plain, pipe = _pair("heat3d", (48, 32, 128), (2, 2, 1), 4,
                                  kind="stream", overlap=False,
                                  kw={"dtype": jnp.bfloat16})
    fields = init_state(st, (48, 32, 128), seed=9, kind="pulse")
    _assert_bitexact(_run_scanned(st, mesh, pipe, fields, 3),
                     _run_scanned(st, mesh, plain, fields, 3))


@pytest.mark.slow
def test_pipeline_bf16_k8_padfree_bitexact():
    """bf16 on the tiled pad-free kind needs k=8 (2m a multiple of the
    16-row bf16 sublane tile) — the deep-margin variant of the carry."""
    st, mesh, plain, pipe = _pair("heat3d", (64, 32, 128), (2, 1, 1), 8,
                                  padfree=True, overlap=True,
                                  kw={"dtype": jnp.bfloat16})
    fields = init_state(st, (64, 32, 128), seed=9, kind="pulse")
    _assert_bitexact(_run_scanned(st, mesh, pipe, fields, 3),
                     _run_scanned(st, mesh, plain, fields, 3))


# ---------------------------------------------------------------------------
# scan-boundary edge cases: prologue/epilogue at n_steps 0/1/2, and the
# K-chunked (log-cadence) path re-seeding the carry per chunk
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n_steps", [0, 1, 2])
def test_pipeline_scan_boundaries(n_steps):
    """n=0 must return the fields untouched (the prologue exchange is
    traced but its slabs are dropped by the empty scan); n=1 is pure
    prologue+epilogue (no carried iteration); n=2 exercises exactly one
    carry handoff."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    fields = init_state(st, (32, 16, 128), seed=5, kind="pulse")
    _assert_bitexact(_run_scanned(st, mesh, pipe, fields, n_steps),
                     _run_scanned(st, mesh, plain, fields, n_steps))


def test_pipeline_chunked_run_reseeds_carry():
    """run_simulation's log-cadence chunking (cli's scan-over-remaining/K
    path) builds one runner per chunk: each chunk re-seeds the carry
    with a fresh prologue exchange, and the values must still be
    bit-identical to one unchunked scan."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    fields = shard_fields(init_state(st, (32, 16, 128), seed=5,
                                     kind="pulse"), mesh, 3)
    seen = []
    chunked = driver.run_simulation(
        st, fields, 5, step_fn=pipe, log_every=2,
        callback=lambda done, fs: seen.append(done))
    assert seen == [2, 4, 5]  # 2+2+1 calls: three chunks, three prologues
    fields2 = shard_fields(init_state(st, (32, 16, 128), seed=5,
                                      kind="pulse"), mesh, 3)
    unchunked = driver.run_simulation(st, fields2, 5, step_fn=pipe)
    _assert_bitexact(chunked, unchunked)


def test_pipeline_run_until_threads_carry():
    """--tol's while_loop runner: the carried slabs thread through the
    fori chunk AND the while carry (one prologue per run), and the
    converged state equals the per-pass stepper's."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    f1 = shard_fields(init_state(st, (32, 16, 128), seed=5, kind="pulse"),
                      mesh, 3)
    out_p, n_p, res_p = driver.run_until(pipe, f1, tol=0.0, max_steps=3,
                                         check_every=2)
    f2 = shard_fields(init_state(st, (32, 16, 128), seed=5, kind="pulse"),
                      mesh, 3)
    out_r, n_r, res_r = driver.run_until(plain, f2, tol=0.0, max_steps=3,
                                         check_every=2)
    assert n_p == n_r and res_p == res_r
    _assert_bitexact(out_p, out_r)


def test_pipeline_checked_runner_divergence_tracker():
    """The sharded debug tracker (driver.make_checked_runner
    use_checkify=False) threads the slab carry alongside its
    (step, field) scalars and still reproduces the plain values."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    f1 = shard_fields(init_state(st, (32, 16, 128), seed=5, kind="pulse"),
                      mesh, 3)
    runner = driver.make_checked_runner(pipe, 3, use_checkify=False)
    out = runner(f1)
    ref = _run_scanned(st, mesh, plain,
                       init_state(st, (32, 16, 128), seed=5, kind="pulse"),
                       3)
    _assert_bitexact(out, ref)


# ---------------------------------------------------------------------------
# structure: one exchange round per iteration; two-sided independence
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("grid,mesh_shape,kind,padfree", [
    ((32, 16, 128), (2, 1, 1), None, True),
    pytest.param((32, 32, 128), (2, 2, 1), None, True,
                 marks=pytest.mark.slow),
    pytest.param((48, 32, 128), (2, 2, 1), "stream", None,
                 marks=pytest.mark.slow),
])
def test_pipeline_body_structure(grid, mesh_shape, kind, padfree):
    """The reusable helper (also run by scripts/tier1.sh): the body
    holds exactly one exchange round, interior(i) is unreachable from
    the ppermutes feeding pass i+1, and those ppermutes are unreachable
    from interior(i)."""
    st, mesh, plain, pipe = _pair("heat3d", grid, mesh_shape, 4,
                                  kind=kind, padfree=padfree,
                                  overlap=True)
    fields = shard_fields(init_state(st, grid, seed=3, kind="pulse"),
                          mesh, 3)
    local = tuple(g // c for g, c in zip(grid, mesh_shape))
    rep = assert_pipeline_body_structure(pipe, plain, fields, local,
                                         overlap=True)
    assert rep["interior_depends_on_exchange"] is False
    assert rep["exchange_depends_on_interior"] is False


def test_pipeline_nonoverlap_body_single_exchange_round():
    """Without the overlap split there is no separate interior kernel,
    but the one-round invariant still holds: the body's ppermute count
    equals the plain step's."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 32, 128), (2, 2, 1), 4,
                                  padfree=True, overlap=False)
    fields = shard_fields(init_state(st, (32, 32, 128), seed=3,
                                     kind="pulse"), mesh, 3)
    slabs = jax.eval_shape(pipe._pipeline_prologue, fields)
    n_body = count_primitive(
        jax.make_jaxpr(pipe._pipeline_body)(fields, slabs), "ppermute")
    n_plain = count_primitive(jax.make_jaxpr(plain)(fields), "ppermute")
    assert n_body == n_plain > 0


def test_pipeline_prologue_is_pure_exchange():
    """The prologue must be the seed exchange only — no kernel runs
    before the scan starts."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    fields = shard_fields(init_state(st, (32, 16, 128), seed=3,
                                     kind="pulse"), mesh, 3)
    closed = jax.make_jaxpr(pipe._pipeline_prologue)(fields)
    assert count_primitive(closed, "ppermute") > 0
    assert count_primitive(closed, "pallas_call") == 0


# ---------------------------------------------------------------------------
# a requested pipeline never silently falls back
# ---------------------------------------------------------------------------


def test_pipeline_declines_periodic_with_reason():
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    with pytest.raises(ValueError, match="guard-frame"):
        make_sharded_fused_step(st, mesh, (32, 16, 128), 4,
                                interpret=True, padfree=True,
                                periodic=True, pipeline=True)


def test_pipeline_declines_padded_kind_with_reason():
    """An auto configuration that would take the exchange-padded kernel
    (below the pad-free threshold, no forced kind) must raise, never
    silently run the padded kernel under a pipeline request."""
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    with pytest.raises(ValueError, match="slab-operand"):
        make_sharded_fused_step(st, mesh, (32, 16, 128), 4,
                                interpret=True, pipeline=True)
    with pytest.raises(ValueError, match="slab-operand"):
        make_sharded_fused_step(st, mesh, (32, 16, 128), 4,
                                interpret=True, padfree=False,
                                pipeline=True)


def test_pipeline_declines_2d_with_reason():
    st = make_stencil("life")
    mesh = make_mesh((2,))
    with pytest.raises(ValueError, match="3D-only"):
        make_sharded_temporal_step(st, mesh, (64, 128), 8,
                                   interpret=True, pipeline=True)


def test_pipeline_untileable_returns_none_not_plain():
    """Forced stream + pipeline on a geometry stream cannot tile: None
    (cli raises), never a silently non-pipelined or non-stream step."""
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 2, 1))
    assert make_sharded_fused_step(st, mesh, (16, 32, 128), 4,
                                   interpret=True, kind="stream",
                                   pipeline=True) is None


def test_pipeline_overlap_fallback_keeps_pipeline_active():
    """local z = 8 < 3m: the overlap split declines (plain-overlap
    contract), but the pipeline must STAY active on the non-split body —
    the carry is still legal, only the shell/interior split is not."""
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    grid = (16, 16, 128)
    pipe = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                   padfree=True, overlap=True,
                                   pipeline=True)
    assert pipe is not None
    assert getattr(pipe, "_pipeline_active", False)
    assert not getattr(pipe, "_overlap_active", False)
    plain = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                    padfree=True)
    fields = init_state(st, grid, seed=9, kind="pulse")
    _assert_bitexact(_run_scanned(st, mesh, pipe, fields, 3),
                     _run_scanned(st, mesh, plain, fields, 3))


def test_pipeline_plain_call_contract():
    """Calling the pipelined stepper as a plain fields->fields function
    (diagnostics, one-off steps) runs prologue + one body and matches
    the non-pipelined step exactly."""
    st, mesh, plain, pipe = _pair("heat3d", (32, 16, 128), (2, 1, 1), 4,
                                  padfree=True, overlap=True)
    fields = shard_fields(init_state(st, (32, 16, 128), seed=9,
                                     kind="pulse"), mesh, 3)
    _assert_bitexact(jax.jit(pipe)(fields), jax.jit(plain)(fields))
