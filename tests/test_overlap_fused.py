"""Communication-overlapped temporal blocking == plain temporal blocking.

``make_sharded_fused_step(overlap=True)`` / ``make_sharded_fullgrid_step
(overlap=True)`` change only the dependency structure (the width-m slab
``ppermute``s feed boundary-shell kernels instead of the whole update),
never the values: bit-exact for integer families, allclose(1e-6) for
float.  The interior kernel's independence from the exchange — the whole
point of the split — is asserted structurally: its jaxpr dependency path
contains no collective-permute.

Every equivalence case runs >= 2 consecutive steps, so the second step's
slabs come from the FIRST step's spliced outputs — a wrong-neighbor or
stale-shell bug cannot survive two exchanges.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_cuda_process_tpu import (
    init_state,
    make_mesh,
    make_stencil,
    shard_fields,
)
from mpi_cuda_process_tpu.parallel.stepper import (
    make_sharded_fullgrid_step,
    make_sharded_fused_step,
    make_sharded_temporal_step,
)


def _pair(name, grid, mesh_shape, k, kw=None, periodic=False, kind=None,
          padfree=None):
    st = make_stencil(name, **(kw or {}))
    mesh = make_mesh(mesh_shape)
    mk = lambda ov: make_sharded_fused_step(  # noqa: E731
        st, mesh, grid, k, interpret=True, periodic=periodic, kind=kind,
        padfree=padfree, overlap=ov)
    plain, over = mk(False), mk(True)
    assert plain is not None and over is not None
    assert getattr(over, "_overlap_active", False), \
        "overlap geometry unexpectedly declined — fix the test shape"
    fields = init_state(st, grid, seed=9,
                        kind="random" if periodic else "pulse",
                        periodic=periodic)
    return st, mesh, plain, over, fields


def _run_both(st, mesh, plain, over, fields, steps=2):
    fp = fo = shard_fields(fields, mesh, st.ndim)
    jp, jo = jax.jit(plain), jax.jit(over)
    for _ in range(steps):
        fp, fo = jp(fp), jo(fo)
    return fp, fo


def _assert_equiv(fp, fo):
    for p, o in zip(fp, fo):
        if np.issubdtype(np.asarray(p).dtype, np.integer):
            np.testing.assert_array_equal(np.asarray(o), np.asarray(p))
        else:
            np.testing.assert_allclose(np.asarray(o), np.asarray(p),
                                       rtol=0, atol=1e-6)


# ---------------------------------------------------------------------------
# 3D fused (padded kind): the headline equivalences.  Two consecutive
# steps everywhere (slab-from-correct-neighbor regression).  The heavier
# compiles (extra families, 4-shard, 2-axis, periodic) ride the slow tier;
# the default tier keeps one guard-frame anchor + the carry field.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,grid,mesh_shape,k,kw", [
    ("heat3d", (32, 16, 128), (2, 1, 1), 4, {}),
    pytest.param("heat3d", (64, 16, 128), (4, 1, 1), 4, {},
                 marks=pytest.mark.slow),        # 4-shard ring
    pytest.param("heat3d", (32, 32, 128), (2, 2, 1), 4, {},
                 marks=pytest.mark.slow),        # 2-axis mesh: y shells too
    ("wave3d", (32, 16, 128), (2, 1, 1), 4, {}),  # leapfrog carry field
    pytest.param("wave3d", (64, 16, 128), (4, 1, 1), 4, {},
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (64, 16, 128), (2, 1, 1), 4, {},
                 marks=pytest.mark.slow),        # red-black parity, m=8
    pytest.param("sor3d", (128, 16, 128), (4, 1, 1), 4, {},
                 marks=pytest.mark.slow),
])
def test_overlap_fused_matches_plain(name, grid, mesh_shape, k, kw):
    st, mesh, plain, over, fields = _pair(name, grid, mesh_shape, k, kw)
    _assert_equiv(*_run_both(st, mesh, plain, over, fields))


@pytest.mark.parametrize("name,grid,mesh_shape,k", [
    pytest.param("heat3d", (32, 16, 128), (2, 1, 1), 4),
    pytest.param("heat3d", (32, 32, 128), (2, 2, 1), 4,
                 marks=pytest.mark.slow),
    pytest.param("sor3d", (64, 16, 128), (2, 1, 1), 4,
                 marks=pytest.mark.slow),        # wrap parity consistency
])
def test_overlap_fused_periodic_matches_plain(name, grid, mesh_shape, k):
    st, mesh, plain, over, fields = _pair(name, grid, mesh_shape, k,
                                          periodic=True)
    _assert_equiv(*_run_both(st, mesh, plain, over, fields))


# ---------------------------------------------------------------------------
# pad-free / streaming kinds: dummy-slab interiors + the same shells
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind,padfree,grid,periodic", [
    (None, True, (32, 16, 128), False),
    pytest.param(None, True, (32, 16, 128), True, marks=pytest.mark.slow),
    pytest.param("stream", None, (48, 32, 128), False,
                 marks=pytest.mark.slow),
])
def test_overlap_zslab_kinds_match_plain(kind, padfree, grid, periodic):
    st, mesh, plain, over, fields = _pair(
        "heat3d", grid, (2, 1, 1), 4, periodic=periodic, kind=kind,
        padfree=padfree)
    _assert_equiv(*_run_both(st, mesh, plain, over, fields))


# ---------------------------------------------------------------------------
# 2D whole-local-block kernel: bit-exact including int Life
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,grid,mesh_shape,k,steps", [
    ("life", (64, 128), (2,), 8, 2),             # int32: bit-exact
    pytest.param("life", (128, 128), (4,), 8, 2, marks=pytest.mark.slow),
    pytest.param("heat2d", (64, 128), (2,), 8, 2, marks=pytest.mark.slow),
])
def test_overlap_fullgrid_matches_plain(name, grid, mesh_shape, k, steps):
    st = make_stencil(name)
    mesh = make_mesh(mesh_shape)
    plain = make_sharded_fullgrid_step(st, mesh, grid, k, interpret=True)
    over = make_sharded_fullgrid_step(st, mesh, grid, k, interpret=True,
                                      overlap=True)
    assert plain is not None and over is not None
    assert getattr(over, "_overlap_active", False)
    fields = init_state(st, grid, seed=7, density=0.3,
                        kind="random" if name == "life" else "auto")
    _assert_equiv(*_run_both(st, mesh, plain, over, fields, steps=steps))


@pytest.mark.slow
def test_overlap_fullgrid_periodic_life_bitmatch():
    st = make_stencil("life")
    grid = (64, 128)
    mesh = make_mesh((2,))
    plain = make_sharded_fullgrid_step(st, mesh, grid, 8, interpret=True,
                                       periodic=True)
    over = make_sharded_fullgrid_step(st, mesh, grid, 8, interpret=True,
                                      periodic=True, overlap=True)
    assert getattr(over, "_overlap_active", False)
    fields = init_state(st, grid, seed=3, density=0.3, kind="random",
                        periodic=True)
    _assert_equiv(*_run_both(st, mesh, plain, over, fields))


# ---------------------------------------------------------------------------
# structure: the interior consumes no ppermute output
# ---------------------------------------------------------------------------


def _all_jaxprs(jaxpr):
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            vals = v if isinstance(v, (list, tuple)) else (v,)
            for u in vals:
                if isinstance(u, jax.core.ClosedJaxpr):
                    yield from _all_jaxprs(u.jaxpr)
                elif isinstance(u, jax.core.Jaxpr):
                    yield from _all_jaxprs(u)


def _interior_depends_on_ppermute(step, fields, local_shape):
    """Walk the full step's jaxpr: locate the interior pallas_call (the
    one producing full local-shape outputs) and flood backwards through
    its transitive producers, asserting no collective-permute feeds it."""
    closed = jax.make_jaxpr(step)(fields)
    for jx in _all_jaxprs(closed.jaxpr):
        if not any(e.primitive.name == "ppermute" for e in jx.eqns):
            continue
        producer = {}
        for eqn in jx.eqns:
            for ov in eqn.outvars:
                producer[ov] = eqn
        interior = [
            e for e in jx.eqns
            if e.primitive.name == "pallas_call"
            and any(tuple(ov.aval.shape) == tuple(local_shape)
                    for ov in e.outvars)
        ]
        assert interior, "no interior pallas_call found in the jaxpr"
        seen, stack, hit = set(), list(interior), False
        while stack:
            eqn = stack.pop()
            if id(eqn) in seen:
                continue
            seen.add(id(eqn))
            if eqn.primitive.name == "ppermute":
                hit = True
            for iv in eqn.invars:
                if isinstance(iv, jax.core.Literal):
                    continue
                p = producer.get(iv)
                if p is not None:
                    stack.append(p)
        return hit
    raise AssertionError("no ppermute anywhere — overlap step did not "
                         "exchange at all")


@pytest.mark.parametrize("kind,padfree,grid", [
    (None, None, (32, 16, 128)),                  # padded kind
    pytest.param(None, True, (32, 16, 128), marks=pytest.mark.slow),
    pytest.param("stream", None, (48, 32, 128), marks=pytest.mark.slow),
])
def test_interior_free_of_collective_permute(kind, padfree, grid):
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    over = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                   kind=kind, padfree=padfree, overlap=True)
    assert getattr(over, "_overlap_active", False)
    fields = shard_fields(init_state(st, grid, seed=9, kind="pulse"),
                          mesh, 3)
    # (a) the exported interior path traces with no collective at all
    txt = str(jax.make_jaxpr(over._interior_step)(fields))
    assert "ppermute" not in txt
    # (b) the REAL step's interior pallas_call is unreachable from any
    # ppermute output, while the step as a whole does exchange
    local = (grid[0] // 2, grid[1], grid[2])
    assert not _interior_depends_on_ppermute(over, fields, local)
    assert "ppermute" in str(jax.make_jaxpr(over)(fields))


def test_interior_free_of_collective_permute_fullgrid():
    st = make_stencil("life")
    grid = (64, 128)
    mesh = make_mesh((2,))
    over = make_sharded_fullgrid_step(st, mesh, grid, 8, interpret=True,
                                      overlap=True)
    assert getattr(over, "_overlap_active", False)
    fields = shard_fields(
        init_state(st, grid, seed=7, density=0.3, kind="random"), mesh, 2)
    assert "ppermute" not in str(
        jax.make_jaxpr(over._interior_step)(fields))
    assert not _interior_depends_on_ppermute(over, fields, (32, 128))


# ---------------------------------------------------------------------------
# graceful fallback + dispatcher passthrough
# ---------------------------------------------------------------------------


def test_overlap_falls_back_when_block_too_small():
    # local z = 8 < 3m = 12: the shell strip does not fit — the builder
    # must return the plain step (correct values), not None / garbage
    st = make_stencil("heat3d")
    mesh = make_mesh((2, 1, 1))
    grid = (16, 16, 128)
    over = make_sharded_fused_step(st, mesh, grid, 4, interpret=True,
                                   overlap=True)
    plain = make_sharded_fused_step(st, mesh, grid, 4, interpret=True)
    assert over is not None
    assert not getattr(over, "_overlap_active", False)
    fields = init_state(st, grid, seed=9, kind="pulse")
    _assert_equiv(*_run_both(st, mesh, plain, over, fields, steps=1))


def test_temporal_dispatcher_threads_overlap():
    st3 = make_stencil("heat3d")
    mesh3 = make_mesh((2, 1, 1))
    s3 = make_sharded_temporal_step(st3, mesh3, (32, 16, 128), 4,
                                    interpret=True, overlap=True)
    assert getattr(s3, "_overlap_active", False)
    st2 = make_stencil("life")
    mesh2 = make_mesh((2,))
    s2 = make_sharded_temporal_step(st2, mesh2, (64, 128), 8,
                                    interpret=True, overlap=True)
    assert getattr(s2, "_overlap_active", False)
