"""Continuous-batching scheduler tests (serving/).

What the serving layer promises, pinned:

* **size-class identity** — jobs differing only in per-job fields
  (seed/density/init/iters) share a class; any class field splits it.
* **admission** — over-budget classes are refused BEFORE any build,
  with the pricing arithmetic attached; unsupported lifecycle modes
  are refused with the offending field named.
* **near-zero cold-compile** — the second job of an already-resident
  size class triggers ZERO backend compiles, asserted through the
  jax.monitoring compile listener (``obs/runtime.compile_events_seen``),
  and ``--compile-cache`` populates a persistent cache directory.
* **isolation + bit-exactness** — a slot's result is bit-identical to
  the job's solo ``cli.run``, including across a checkpoint preemption
  round-trip, and a co-tenant's NaN divergence (injected via the
  ``numerics`` fault site) evicts only the poisoned slot.
* **third terminal outcome** — cancel ends a run with a ``cancelled``
  event / phase / quarantine reason, never an error row, and the
  supervisor treats it as fatal-no-restart.
* **fairness** — weighted FIFO with a starvation bound: a low-priority
  job completes while higher-priority work keeps arriving.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu.cancellation import RunCancelled  # noqa: E402
from mpi_cuda_process_tpu.config import RunConfig  # noqa: E402
from mpi_cuda_process_tpu.engine import SimulationEngine  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import metrics as metrics_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import runtime as runtime_lib  # noqa: E402
from mpi_cuda_process_tpu.obs.health import SimulationDiverged  # noqa: E402
from mpi_cuda_process_tpu.resilience import faults  # noqa: E402
from mpi_cuda_process_tpu.resilience import supervisor as sup  # noqa: E402
from mpi_cuda_process_tpu import serving  # noqa: E402
from mpi_cuda_process_tpu.serving import (  # noqa: E402
    AdmissionController, AdmissionError, class_config, class_signature)


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def _events(path):
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _solo(cfg):
    fields, _ = cli.run(cfg)
    return tuple(np.asarray(f) for f in fields)


def _assert_bit_exact(got, cfg):
    want = _solo(cfg)
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), b), \
            "slot result differs from the job's solo run"


# ------------------------------------------------------- size classes

def test_class_signature_per_job_fields_do_not_split():
    a = RunConfig(stencil="heat2d", grid=(16, 16), iters=8, seed=0)
    b = RunConfig(stencil="heat2d", grid=(16, 16), iters=640, seed=9,
                  density=0.5, init="pulse", telemetry="/tmp/x.jsonl")
    assert class_signature(a) == class_signature(b)
    for variant in (dict(grid=(16, 32)), dict(stencil="life"),
                    dict(dtype="bfloat16"), dict(periodic=True),
                    dict(fuse=2)):
        c = RunConfig(**{**dict(stencil="heat2d", grid=(16, 16)),
                         **variant})
        assert class_signature(c) != class_signature(a), variant


def test_class_config_resets_per_job_and_opens_member_axis():
    j = RunConfig(stencil="heat2d", grid=(16, 16), iters=640, seed=9,
                  density=0.5, supervise=True, telemetry="/tmp/x.jsonl")
    bc = class_config(j, 4)
    assert bc.ensemble == 4
    assert bc.grid == (16, 16) and bc.stencil == "heat2d"
    d = RunConfig()
    assert (bc.seed, bc.density, bc.iters) == (d.seed, d.density, d.iters)
    assert not bc.supervise and bc.telemetry is None


# --------------------------------------------------------- admission

def test_admission_over_budget_rejects_with_arithmetic():
    ctl = AdmissionController(hbm_bytes=10_000)
    cfg = class_config(RunConfig(stencil="heat2d", grid=(256, 256)), 8)
    with pytest.raises(AdmissionError) as ei:
        ctl.admit_or_raise(cfg)
    e = ei.value
    assert e.reason == "over_budget"
    assert e.detail["total_bytes"] > e.detail["hbm_bytes"] == 10_000
    assert "parts" in e.detail and "GiB" in str(e)


def test_engine_rejects_over_budget_with_event(tmp_path):
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                hbm_bytes=10_000)
    with pytest.raises(AdmissionError) as ei:
        eng.submit(RunConfig(stencil="heat2d", grid=(256, 256), iters=8),
                   tenant="greedy")
    assert ei.value.reason == "over_budget"
    stats = eng.close()
    assert stats["rejects"] == 1 and stats["jobs_submitted"] == 0
    rejects = [e for e in _events(eng.telemetry_path)
               if e.get("kind") == "scheduler" and e.get("op") == "reject"]
    assert len(rejects) == 1
    assert rejects[0]["reason"] == "over_budget"
    assert rejects[0]["tenant"] == "greedy"


def test_engine_rejects_unsupported_fields(tmp_path):
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path))
    for bad in (dict(supervise=True), dict(tol=1e-6), dict(ensemble=4),
                dict(resume=True), dict(profile="/tmp/p"),
                dict(iters=0), dict(fuse=2, iters=9)):
        with pytest.raises(AdmissionError) as ei:
            eng.submit(RunConfig(stencil="heat2d", grid=(16, 16),
                                 iters=bad.pop("iters", 8), **bad))
        assert ei.value.reason == "unsupported"
    stats = eng.close()
    assert stats["rejects"] == 7


# ------------------------------------------ residency / zero compiles

def test_second_job_of_resident_class_compiles_nothing(tmp_path):
    """THE perf pin: a size class compiles when first built; the next
    job of the class rides the resident step — zero backend compiles,
    counted by the jax.monitoring listener the recorder registers."""
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(2,), cadence=8)
    base = dict(stencil="heat2d", grid=(16, 48), iters=8)
    before_a = runtime_lib.compile_events_seen()
    ha = eng.submit(RunConfig(seed=1, **base), tenant="a")
    ha.result(timeout=300)
    after_a = runtime_lib.compile_events_seen()
    assert after_a > before_a, \
        "the first build of a class must register backend compiles " \
        "(the listener is live — this assertion gives the zero below teeth)"
    hb = eng.submit(RunConfig(seed=2, density=0.4, **base), tenant="b")
    hb.result(timeout=300)
    assert runtime_lib.compile_events_seen() == after_a, \
        "second job of a resident size class must compile NOTHING"
    stats = eng.close()
    assert stats["jobs_done"] == 2
    assert len(stats["class_table"]) == 1


def test_compile_cache_flag_populates_persistent_cache(tmp_path):
    cache = tmp_path / "xla-cache"
    cfg = cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,16", "--iters", "4",
         "--compile-cache", str(cache)])
    assert cfg.compile_cache == str(cache)
    cli.run(cfg)
    assert cache.is_dir() and len(os.listdir(cache)) > 0, \
        "--compile-cache must land compiled executables on disk"


# ------------------------------------------------- results / isolation

def test_results_bit_exact_vs_solo_and_batched_together(tmp_path):
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(4,), cadence=8)
    base = dict(stencil="heat2d", grid=(16, 16), iters=16)
    cfgs = [RunConfig(seed=s, **base) for s in (3, 5, 8)]
    handles = [eng.submit(c, tenant=f"t{i}") for i, c in enumerate(cfgs)]
    results = [h.result(timeout=300)[0] for h in handles]
    stats = eng.close()
    assert stats["jobs_done"] == 3
    for got, cfg in zip(results, cfgs):
        _assert_bit_exact(got, cfg)


def test_diverged_slot_evicted_others_unharmed(tmp_path, monkeypatch):
    """PR 12's verdict as the eviction signal: poison one member slot
    (numerics fault site) — that job ends DIVERGED with a real health
    record; its co-tenant finishes bit-exact."""
    monkeypatch.setenv("FAULT_INJECT", "numerics:step=4:nan")
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(2,), cadence=8)
    base = dict(stencil="heat2d", grid=(16, 16), iters=16)
    victim = eng.submit(RunConfig(seed=1, **base), tenant="victim")
    survivor = eng.submit(RunConfig(seed=2, **base), tenant="survivor")
    got, _ = survivor.result(timeout=300)
    with pytest.raises(SimulationDiverged):
        victim.result(timeout=300)
    assert victim._phase() == "evicted"
    assert victim.health_verdict() == "DIVERGED"
    assert victim.status()["verdict"] == "DIVERGED"
    stats = eng.close()
    assert stats["jobs_evicted"] == 1 and stats["jobs_done"] == 1
    evs = [e for e in _events(eng.telemetry_path)
           if e.get("kind") == "scheduler" and e.get("op") == "evict"]
    assert len(evs) == 1 and evs[0]["tenant"] == "victim"
    faults.reset()  # the one-shot fired; solo replay must stay clean
    _assert_bit_exact(got, RunConfig(seed=2, **base))


def test_preemption_checkpoints_victim_and_resumes_bit_exact(tmp_path):
    """A higher-priority arrival preempts the lowest-priority runner
    through a checkpoint; the victim resumes and still finishes
    bit-identical to its solo run (no completed chunk lost)."""
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1,), cadence=8)
    low_cfg = RunConfig(stencil="heat2d", grid=(64, 64), iters=4096,
                        seed=4)
    low = eng.submit(low_cfg, tenant="low", priority=0)
    deadline = time.time() + 120
    while low.steps_done == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert low.steps_done > 0, "low-priority job never started"
    high = eng.submit(RunConfig(stencil="heat2d", grid=(64, 64),
                                iters=8, seed=5), tenant="high",
                      priority=5)
    high.result(timeout=300)
    got_low, _ = low.result(timeout=600)
    stats = eng.close()
    assert stats["preemptions"] >= 1
    assert low.preempt_count >= 1
    assert high.finished_at < low.finished_at
    _assert_bit_exact(got_low, low_cfg)


def test_starvation_bound_low_priority_completes(tmp_path):
    """Weighted FIFO would starve priority 0 behind a deep priority-5
    queue; the starvation bound serves it FIFO once it has waited
    ``starvation_rounds`` boundaries."""
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1,), cadence=8,
                                starvation_rounds=3)
    base = dict(stencil="heat2d", grid=(16, 16), iters=8)
    low = eng.submit(RunConfig(seed=0, **base), tenant="low", priority=0)
    highs = [eng.submit(RunConfig(seed=10 + i, **base), tenant="high",
                        priority=5) for i in range(6)]
    low.result(timeout=300)
    for h in highs:
        h.result(timeout=300)
    stats = eng.close()
    assert stats["jobs_done"] == 7
    assert low.finished_at < max(h.finished_at for h in highs), \
        "the starvation bound must serve the low-priority job before " \
        "the high-priority queue drains"


# ----------------------------------------------------------- cancel

def test_serving_cancel_queued_and_running(tmp_path):
    # starvation promotion off: the queued job must still be queued
    # when its cancel lands (otherwise this would race the scheduler)
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1,), cadence=8,
                                starvation_rounds=10**9)
    running = eng.submit(RunConfig(stencil="heat2d", grid=(64, 64),
                                   iters=65536), tenant="a")
    queued = eng.submit(RunConfig(stencil="heat2d", grid=(64, 64),
                                  iters=8, seed=2), tenant="b",
                        priority=0)
    deadline = time.time() + 120
    while running.steps_done == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert queued.cancel() and running.cancel()
    for h in (queued, running):
        h.wait(120)
        assert h.cancelled() and h._phase() == "cancelled"
        with pytest.raises(RunCancelled):
            h.result(timeout=1)
        kinds = [e.get("kind") for e in _events(h.telemetry_path)]
        assert "cancelled" in kinds and "error" not in kinds
    assert running._error.step > 0 and queued._error.step == 0
    stats = eng.close()
    assert stats["jobs_cancelled"] == 2 and stats["jobs_done"] == 0


def test_engine_cancel_is_third_outcome_everywhere(tmp_path):
    """RunHandle.cancel through the PR-10 engine: phase 'cancelled',
    a ``cancelled`` event (never ``error``), verdict CANCELLED on
    /status.json, quarantined 'cancelled' in the ledger."""
    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat2d", grid=(64, 64),
                             iters=262144, log_every=64))
    deadline = time.time() + 120
    while time.time() < deadline and not any(
            e.get("kind") == "chunk" for e in h.events()):
        time.sleep(0.02)
    assert h.cancel()
    assert h.wait(120)
    assert h.cancelled() and h._phase() == "cancelled"
    with pytest.raises(RunCancelled):
        h.result(timeout=1)
    kinds = [e.get("kind") for e in h.events()]
    assert "cancelled" in kinds and "error" not in kinds \
        and "summary" not in kinds
    st = h.status()
    assert st["verdict"] == "CANCELLED"
    assert st["cancelled"]["step"] > 0
    rows = ledger_lib.rows_from_log(h.telemetry_path)
    assert len(rows) == 1
    assert rows[0]["status"] == "quarantined"
    assert rows[0]["quarantine"] == "cancelled"
    assert eng.metrics.snapshot()[
        "engine_requests_cancelled_total"]["value"] == 1


def test_cancel_after_done_returns_false(tmp_path):
    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(RunConfig(stencil="heat2d", grid=(16, 16), iters=4))
    h.result(timeout=120)
    assert h.cancel() is False
    assert h._phase() == "done"


def test_supervisor_classifies_cancelled_fatal():
    kind, reason, detail = sup._classify_event(
        {"kind": "cancelled", "step": 40},
        ("WEDGED",), ("DIVERGED",))
    assert kind == "fatal" and reason == "CANCELLED"
    assert "40" in detail


# ------------------------------------------------- observability

def test_scheduler_events_fold_into_status(tmp_path):
    eng = serving.ServingEngine(telemetry_dir=str(tmp_path),
                                ladder=(1, 2), cadence=8)
    h = eng.submit(RunConfig(stencil="heat2d", grid=(16, 16), iters=8),
                   tenant="t0")
    h.result(timeout=300)
    eng.close()
    rm = metrics_lib.RunMetrics()
    for rec in _events(eng.telemetry_path):
        rm.ingest(rec)
    st = rm.status()
    sched = st["scheduler"]
    assert sched["counts"]["submit"] == 1
    assert sched["counts"]["retire"] == 1
    assert sched["tenants"]["t0"]["join"] == 1
    assert sched["queue_depth"] == 0
    prom = rm.registry.to_prometheus()
    assert "obs_sched_submit_total" in prom
    assert "obs_sched_tenant_ops" in prom
    # the scheduler session's summary carries the SLO numbers
    summary = [e for e in _events(eng.telemetry_path)
               if e.get("kind") == "summary"][-1]
    assert summary["jobs_done"] == 1
    assert summary["ttfc_p50_s"] is not None


def test_obs_top_renders_scheduler_panel():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "obs_top_serving_t", os.path.join(repo, "scripts/obs_top.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    status = {"scheduler": {
        "queue_depth": 3, "slots_total": 8, "slots_busy": 5,
        "classes": 2, "counts": {"submit": 9, "reject": 1, "evict": 1},
        "tenants": {"a": {"submit": 5, "join": 4}},
        "last_event": {"op": "join", "tenant": "a", "job": "job-1",
                       "size_class": "abc12345", "t": time.time()},
        "last_reject": {"tenant": "b", "reason": "over_budget",
                        "size_class": "abc12345"}}}
    lines = mod._scheduler_lines(status)
    text = "\n".join(lines)
    assert "queue_depth=3" in text and "slots_busy=5" in text
    assert "reject" in text and "over_budget" in text
    assert "tenant" in text
    assert mod._scheduler_lines({}) == []


def test_serve_engine_cli_flags_roundtrip(tmp_path):
    cfg = cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,16", "--iters", "8",
         "--serve-engine", "0", "--compile-cache",
         str(tmp_path / "cache")])
    assert cfg.serve_engine == 0
    assert cfg.compile_cache == str(tmp_path / "cache")
    # compile_cache round-trips through to_argv (the supervisor child
    # re-launch path); serve_engine is launcher-only and must not
    from mpi_cuda_process_tpu.config import to_argv

    argv = to_argv(cfg)
    assert "--compile-cache" in argv
    assert "--serve-engine" not in argv
