"""Halo-exchange audit (obs/health.HaloAuditor): bit-exact or localized.

The audit re-exchanges ghost slabs through the run's ACTUAL transport
and bit-compares every received slab against the neighbor interior it
must equal, computed independently from the global array view — the
two sides share no exchange code, so agreement is evidence, not
tautology.  Pinned here:

* **clean pass** — zero mismatches on z-only / y-only / 2-axis meshes
  x ppermute / rdma (interpret-emulated on CPU) x guard-frame /
  periodic x single-field (heat) / mixed-halo (wave: the halo-0 field
  is skipped) / batched-ensemble states;
* **localization** — a seeded single-bit corruption of one received
  slab (the ``_corrupt`` trace-time hook, targeted at one field, one
  axis, one direction, one ring-shard) is reported at EXACTLY that
  (site, direction, shard) — every other site stays clean — and the
  emitted ``halo_audit`` event carries the chunk;
* **CLI wiring** — ``--halo-audit K`` runs every K chunks on sharded
  runs, events land in the telemetry log, and unsharded runs refuse
  the flag loudly.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest
from jax import lax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu import cli  # noqa: E402
from mpi_cuda_process_tpu.obs import health as health_lib  # noqa: E402
from mpi_cuda_process_tpu.ops.stencil import make_stencil  # noqa: E402
from mpi_cuda_process_tpu.parallel import mesh as mesh_lib  # noqa: E402
from mpi_cuda_process_tpu.parallel import stepper  # noqa: E402
from mpi_cuda_process_tpu.utils.init import init_state  # noqa: E402

GRID = (8, 8, 16)


def _sharded(st, mesh, ensemble=0, kind="auto", periodic=False):
    fields = init_state(st, GRID, seed=3, kind=kind, periodic=periodic,
                        ensemble=ensemble)
    return stepper.shard_fields(fields, mesh, 3, ensemble=bool(ensemble))


@pytest.mark.parametrize("mesh_shape", [(2,), (1, 2), (2, 2), (2, 4)])
@pytest.mark.parametrize("exchange", ["ppermute", "rdma"])
def test_clean_pass_bitmatches_everywhere(mesh_shape, exchange):
    st = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh(mesh_shape)
    fields = _sharded(st, mesh)
    aud = health_lib.HaloAuditor(st, mesh, GRID, exchange=exchange)
    rec = aud.audit(fields, step=0, chunk=0)
    assert rec["ok"] and rec["mismatch_total"] == 0
    n_axes = sum(1 for c in mesh_shape if c > 1)
    assert rec["sites_checked"] == 2 * n_axes  # left+right per axis
    if exchange == "rdma":
        assert rec["backend"] in ("pallas-rdma", "interpret-emulated")


def test_clean_pass_mixed_halo_fields_and_periodic():
    # wave3d: u_prev has field_halo 0 and is skipped — only u audited
    st = make_stencil("wave3d")
    mesh = mesh_lib.make_mesh((2, 2))
    aud = health_lib.HaloAuditor(st, mesh, GRID)
    rec = aud.audit(_sharded(st, mesh, kind="pulse"), step=0)
    assert rec["ok"] and rec["sites_checked"] == 4
    assert all(s["field"] == 0 for s in rec["sites"])
    # periodic: the expected side wraps exactly like the exchange does
    stp = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh((2,))
    audp = health_lib.HaloAuditor(stp, mesh, GRID, periodic=True)
    rec = audp.audit(_sharded(stp, mesh, kind="random", periodic=True),
                     step=0)
    assert rec["ok"]


def test_clean_pass_wide_halo_field():
    """halo=2 (heat3d4th): two-row slabs, both rows must bit-match."""
    st = make_stencil("heat3d4th")
    mesh = mesh_lib.make_mesh((2,))
    aud = health_lib.HaloAuditor(st, mesh, GRID)
    rec = aud.audit(_sharded(st, mesh), step=0)
    assert rec["ok"]
    assert all(s["halo"] == 2 for s in rec["sites"])


def test_clean_pass_batched_ensemble():
    st = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh((2,))
    fields = _sharded(st, mesh, ensemble=2)
    aud = health_lib.HaloAuditor(st, mesh, GRID, ensemble=2)
    rec = aud.audit(fields, step=0)
    assert rec["ok"]


def _flip_bit(slab, axis_name, shard):
    """One-bit corruption of received-slab word 0 on one ring shard."""
    bits = jax.lax.bitcast_convert_type(slab, jnp.uint32)
    idx = (0,) * slab.ndim
    bad = jax.lax.bitcast_convert_type(
        bits.at[idx].set(bits[idx] ^ 1), slab.dtype)
    return jnp.where(lax.axis_index(axis_name) == shard, bad, slab)


@pytest.mark.parametrize("target", [
    (0, "left", 1), (0, "right", 0), (1, "left", 1)])
def test_seeded_corruption_localized_to_site_direction_shard(target):
    """The acceptance satellite: a single flipped bit in ONE received
    slab is reported at exactly that (site, direction, ring-shard) —
    and the event record carries the chunk."""
    t_axis, t_dir, t_shard = target
    st = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh((2, 2))

    def corrupt(field, axis, direction, slab, axis_name):
        if field == 0 and axis == t_axis and direction == t_dir:
            return _flip_bit(slab, axis_name, t_shard)
        return slab

    class _Trace:
        def __init__(self):
            self.events = []

        def event(self, kind, **payload):
            self.events.append({"kind": kind, **payload})

    tr = _Trace()
    aud = health_lib.HaloAuditor(st, mesh, GRID, trace=tr,
                                 _corrupt=corrupt)
    rec = aud.audit(_sharded(st, mesh), step=7, chunk=3)
    assert not rec["ok"]
    bad = [s for s in rec["sites"] if s["mismatch_count"]]
    assert len(bad) == 1
    assert (bad[0]["axis"], bad[0]["direction"]) == (t_axis, t_dir)
    assert bad[0]["field"] == 0
    assert bad[0]["mismatch_shards"] == [t_shard]
    # one word flipped per device in that ring shard: the OTHER mesh
    # axis has 2 shards, so the count is 2 (each corrupted its word 0)
    assert bad[0]["mismatch_count"] == 2
    # every other site is provably clean
    assert sum(s["mismatch_count"] for s in rec["sites"]) == \
        bad[0]["mismatch_count"]
    ev = tr.events[-1]
    assert ev["kind"] == "halo_audit" and ev["chunk"] == 3
    with pytest.raises(health_lib.SimulationDiverged) as exc:
        aud.audit_or_raise(_sharded(st, mesh), step=7, chunk=3)
    assert t_dir in str(exc.value)


def test_corruption_in_nan_payload_is_still_caught():
    """Bit-compare, not value-compare: NaN != NaN must not mask a slab
    that arrived byte-identical (clean pass over a NaN-bearing state)."""
    st = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh((2,))
    fields = _sharded(st, mesh)
    fields = (fields[0].at[(4, 4, 8)].set(jnp.nan),)
    aud = health_lib.HaloAuditor(st, mesh, GRID)
    rec = aud.audit(fields, step=0)
    assert rec["ok"]  # NaN transported bit-exactly is NOT a mismatch


def test_auditor_rejects_unsharded_and_unauditable():
    st = make_stencil("heat3d")
    mesh = mesh_lib.make_mesh(())
    with pytest.raises(ValueError):
        health_lib.HaloAuditor(st, mesh, GRID)
    with pytest.raises(ValueError):
        cli.run(cli.config_from_args(
            ["--stencil", "heat3d", "--grid", "8,8,16", "--iters", "4",
             "--halo-audit", "1"]))
    with pytest.raises(ValueError):
        cli.run(cli.config_from_args(
            ["--stencil", "heat3d", "--grid", "8,8,16", "--iters", "4",
             "--halo-audit", "-1", "--mesh", "2,1,1"]))


def test_cli_halo_audit_cadence_and_events(tmp_path):
    path = str(tmp_path / "audit.jsonl")
    cli.run(cli.config_from_args(
        ["--stencil", "heat3d", "--grid", "8,8,16", "--iters", "8",
         "--mesh", "2,1,1", "--log-every", "2", "--halo-audit", "2",
         "--health", "--telemetry", path]))
    recs = [json.loads(line) for line in open(path) if line.strip()]
    audits = [r for r in recs if r.get("kind") == "halo_audit"]
    # 4 chunks, K=2 -> audits at chunks 1 and 3
    assert len(audits) == 2
    assert all(a["ok"] for a in audits)
    assert [a["chunk"] for a in audits] == [1, 3]
    healths = [r for r in recs if r.get("kind") == "health"]
    assert len(healths) == 4  # --health composes at every boundary
