"""Run doctor (obs/anomaly.py + obs/flightrec.py): the performance half
of obs.

Layers, matching the modules' design:

* **detectors** — :class:`AnomalyMonitor`'s own-baseline throughput
  collapse, post-warmup recompile, memory creep, variance growth,
  roofline-gap band against the ledger's ``best_known``, boundary
  stall, and the zero-findings contract on a clean constant-throughput
  stream;
* **attribution** — per-member own-baseline straggler naming
  (:meth:`observe_members`: heterogeneous-but-stable members never
  flag) and the homogeneous peer-median :func:`attribute_straggler`;
* **verdict flow** — DEGRADED everywhere WEDGED/DIVERGED flow: the
  RunMetrics status verdict (outranking DONE, dominated by everything
  harder), the aggregate worst-verdict lattice
  (DIVERGED > WEDGED > STALLED > DEGRADED), the supervisor's
  ``--degraded-action`` policy, ledger rows flagged ``degraded=N``
  (honest, never quarantined), perf_gate's ``[degraded]``, obs_top's
  panel + nonzero ``--once``;
* **flight recorder** — the session ring mirror, self-validating
  bundle round-trips, verdict replay, and obs_report rendering a
  bundle with no telemetry dir;
* **invariance** — the jitted step jaxpr is byte-identical with
  ``--anomaly`` on vs off (the zero-ops acceptance pin).
"""

import copy
import importlib.util
import json
import os
import sys
import time

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from mpi_cuda_process_tpu import cli, driver  # noqa: E402
from mpi_cuda_process_tpu import config as config_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import aggregate as aggregate_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import anomaly as anomaly_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import flightrec as flightrec_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import ledger as ledger_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import metrics as metrics_lib  # noqa: E402
from mpi_cuda_process_tpu.obs import trace as trace_lib  # noqa: E402
from mpi_cuda_process_tpu.ops.stencil import make_stencil  # noqa: E402
from mpi_cuda_process_tpu.resilience import faults  # noqa: E402
from mpi_cuda_process_tpu.resilience import supervisor as sup  # noqa: E402
from mpi_cuda_process_tpu.utils.init import init_state  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    yield
    faults.reset()


def _load_script(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def obs_top():
    return _load_script("obs_top_anomaly_t", "scripts/obs_top.py")


@pytest.fixture(scope="module")
def obs_report():
    return _load_script("obs_report_anomaly_t", "scripts/obs_report.py")


def _events(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def _anomaly_events(path):
    return [e for e in _events(path) if e.get("kind") == "anomaly"]


def _chunk(n, ms, steps=20, recompiled=False, mem=None):
    """A RuntimeRecorder-shaped chunk record (obs/runtime.py)."""
    rec = {"chunk": n, "steps": steps, "ms_per_step": float(ms),
           "wall_s": round(float(ms) * steps / 1e3, 6),
           "recompiled": recompiled}
    if mem is not None:
        rec["memory"] = {"bytes_in_use": int(mem)}
    return rec


def _mon(**kw):
    """Monitor with a frozen clock: the boundary-stall detector reads
    real wall time, which a synthetic-record unit test must not."""
    kw.setdefault("clock", lambda: 0.0)
    return anomaly_lib.AnomalyMonitor(**kw)


# ---------------------------------------------------------- detectors

def test_clean_constant_throughput_zero_findings():
    """THE acceptance contract: a clean steady run produces nothing."""
    mon = _mon(cells=10**6, best_known=100.0)
    for n in range(40):
        mon.observe_chunk(_chunk(n, 10.0, recompiled=(n == 0),
                                 mem=10**9))
    assert mon.count == 0
    assert mon.findings == []


def test_throughput_collapse_flagged_at_the_slow_chunk():
    mon = _mon()
    for n in range(5):
        mon.observe_chunk(_chunk(n, 10.0))
    found = mon.observe_chunk(_chunk(5, 45.0))
    assert [f["anomaly"] for f in found] == ["throughput_collapse"]
    f = found[0]
    assert f["severity"] == "critical"
    assert f["chunk"] == 5
    assert f["evidence"]["ratio"] == pytest.approx(4.5)
    assert f["suspect"]["kind"] == "host"
    # a flagged chunk never poisons the baseline: the NEXT slow chunk
    # still measures against the healthy median
    again = mon.observe_chunk(_chunk(6, 45.0))
    assert [f["anomaly"] for f in again] == ["throughput_collapse"]


def test_collapse_needs_absolute_excess_not_just_ratio():
    """Microsecond chunks tripling is noise, not an anomaly."""
    mon = _mon()
    for n in range(5):
        mon.observe_chunk(_chunk(n, 0.001, steps=2))
    assert mon.observe_chunk(_chunk(5, 0.004, steps=2)) == []


def test_recompile_after_warmup_flagged_chunk0_not():
    mon = _mon()
    mon.observe_chunk(_chunk(0, 10.0, recompiled=True))
    assert mon.count == 0  # chunk 0 compiles are warmup
    found = mon.observe_chunk(_chunk(1, 10.0, recompiled=True))
    assert [f["anomaly"] for f in found] == ["recompile"]


def test_memory_creep_flagged_once_plateau_never():
    mon = _mon()
    base = 10**9
    for n in range(6):
        mon.observe_chunk(_chunk(n, 10.0, mem=base + n * base // 10))
    assert mon.counts.get("memory_creep") == 1  # one-shot
    flat = _mon()
    for n in range(10):
        flat.observe_chunk(_chunk(n, 10.0, mem=base))
    assert flat.count == 0


def test_variance_growth_flagged():
    mon = _mon()
    for n in range(1, 9):
        mon.observe_chunk(_chunk(n, 10.0))
    jitter = [6.0, 22.0] * 4
    for i, ms in enumerate(jitter):
        mon.observe_chunk(_chunk(9 + i, ms))
    assert mon.counts.get("variance_growth") == 1
    assert mon.findings[-1]["evidence"]["cv_recent"] > 0.35


def test_roofline_gap_two_steady_chunks_one_shot():
    # 1e6 cells, 20 steps, wall = ms*steps/1e3 -> tp = 1e3/ms Mcells/s;
    # ms=100 -> 10 Mcells/s, far below 0.25 * best_known=100
    mon = _mon(cells=10**6, best_known={"value": 100.0,
                                        "source": "ledger:r1"})
    for n in range(6):
        mon.observe_chunk(_chunk(n, 100.0))
    assert mon.counts.get("roofline_gap") == 1  # at the 2nd bad chunk
    f = [x for x in mon.findings if x["anomaly"] == "roofline_gap"][0]
    assert f["evidence"]["vs_best_known"] == pytest.approx(0.1)
    assert f["evidence"]["best_known_source"] == "ledger:r1"


def test_roofline_never_fires_without_ledger_or_cells():
    mon = _mon()  # no best_known, no cells
    for n in range(10):
        mon.observe_chunk(_chunk(n, 1000.0))
    assert mon.counts.get("roofline_gap") is None


def test_boundary_stall_detector_sees_untimed_host_gap():
    """The injected-sleep seam: faults fire OUTSIDE the fenced device
    window, so the stall shows up between records, not inside wall_s."""
    t = [0.0]
    mon = anomaly_lib.AnomalyMonitor(clock=lambda: t[0])
    for n in range(4):
        t[0] += 0.21  # chunk wall 0.2s + 10ms honest boundary overhead
        mon.observe_chunk(_chunk(n, 10.0))
    assert mon.count == 0
    t[0] += 0.2 + 0.5  # a 500ms host stall lands before this record
    found = mon.observe_chunk(_chunk(4, 10.0))
    assert [f["anomaly"] for f in found] == ["boundary_stall"]
    assert found[0]["evidence"]["stall_s"] == pytest.approx(0.51, abs=0.02)


def test_max_findings_bounds_the_list_not_the_counts():
    mon = _mon(max_findings=3)
    for n in range(5):
        mon.observe_chunk(_chunk(n, 10.0))
    for n in range(5, 15):
        mon.observe_chunk(_chunk(n, 60.0))
    assert len(mon.findings) == 3
    assert mon.count == 10


# -------------------------------------------------------- attribution

def test_observe_members_heterogeneous_stable_never_flags():
    mon = _mon()
    for _ in range(6):  # g1 is 5x slower than g0 every round: that's
        assert mon.observe_members(  # its physics, not a straggle
            None, [{"name": "g0", "ms_per_step": 10.0},
                   {"name": "g1", "ms_per_step": 50.0}]) is None
    assert mon.count == 0


def test_observe_members_own_baseline_straggler_named_once():
    mon = _mon()
    for step in range(4):
        mon.observe_members(step, [{"name": "g0", "ms_per_step": 10.0},
                                   {"name": "g1", "ms_per_step": 50.0}])
    f = mon.observe_members(9, [{"name": "g0", "ms_per_step": 32.0},
                                {"name": "g1", "ms_per_step": 50.0}])
    assert f is not None
    assert f["suspect"] == {"kind": "group", "name": "g0",
                            "lag_ratio": pytest.approx(3.2)}
    assert f["step"] == 9
    # once per name per run
    assert mon.observe_members(10, [{"name": "g0", "ms_per_step": 40.0},
                                    {"name": "g1", "ms_per_step": 50.0}]) \
        is None


def test_attribute_straggler_peer_median():
    entries = [{"name": "hostA", "slowness": 10.0},
               {"name": "hostB", "slowness": 10.0},
               {"name": "hostC", "slowness": 25.0}]
    s = anomaly_lib.attribute_straggler(entries)
    assert s == {"kind": "host", "name": "hostC", "lag_ratio": 2.5}
    assert anomaly_lib.attribute_straggler(entries[:1]) is None
    assert anomaly_lib.attribute_straggler(
        [{"name": "a", "slowness": 10.0},
         {"name": "b", "slowness": 11.0}]) is None


def test_findings_land_as_schema_valid_trace_events(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        mon = _mon(trace=w)
        for n in range(5):
            mon.observe_chunk(_chunk(n, 10.0))
        mon.observe_chunk(_chunk(5, 60.0))
    _, events = trace_lib.validate_log(path)  # schema gate
    anomalies = [e for e in events if e.get("kind") == "anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["anomaly"] == "throughput_collapse"
    assert anomalies[0]["suspect"]["kind"] == "host"


# ------------------------------------------------------- verdict flow

def _manifest_for(host):
    m = copy.deepcopy(trace_lib.build_manifest("cli", {}))
    m["provenance"]["hostname"] = host
    return m


_ANOMALY_EV = {"kind": "anomaly", "anomaly": "throughput_collapse",
               "severity": "critical", "chunk": 3, "t": time.time(),
               "suspect": {"kind": "host", "name": "h|p0"},
               "evidence": {"ratio": 4.0}}


def test_metrics_degraded_verdict_and_payload():
    rm = metrics_lib.RunMetrics()
    rm.ingest(trace_lib.build_manifest("cli", {}))
    rm.ingest(dict(_ANOMALY_EV))
    st = rm.status()
    assert st["verdict"] == "DEGRADED"
    assert st["anomalies"]["count"] == 1
    assert st["anomalies"]["kinds"] == {"throughput_collapse": 1}
    assert st["anomalies"]["suspect"]["name"] == "h|p0"


def test_metrics_degraded_outranks_done_but_nothing_harder():
    rm = metrics_lib.RunMetrics()
    rm.ingest(trace_lib.build_manifest("cli", {}))
    rm.ingest(dict(_ANOMALY_EV))
    rm.ingest({"kind": "summary", "t": time.time(), "steps": 8,
               "mcells_per_s": 1.0})
    assert rm.status()["verdict"] == "DEGRADED"  # finished slow, not DONE
    rm.ingest({"kind": "heartbeat", "t": time.time(),
               "verdict": "WEDGED", "detail": "no progress"})
    assert rm.status()["verdict"] == "WEDGED"


_VERDICT_EVENTS = {
    "DIVERGED": {"kind": "health", "verdict": "DIVERGED",
                 "reason": "boom", "step": 4, "t": time.time()},
    "WEDGED": {"kind": "heartbeat", "verdict": "WEDGED",
               "detail": "stuck", "t": time.time()},
    "STALLED": {"kind": "heartbeat", "verdict": "STALLED",
                "detail": "slow", "t": time.time()},
    "DEGRADED": dict(_ANOMALY_EV),
    "DONE": {"kind": "summary", "t": time.time(), "steps": 8,
             "mcells_per_s": 1.0},
}


@pytest.mark.parametrize("winner,loser", [
    ("DIVERGED", "WEDGED"), ("DIVERGED", "DEGRADED"),
    ("WEDGED", "STALLED"), ("WEDGED", "DEGRADED"),
    ("STALLED", "DEGRADED"), ("DEGRADED", "DONE")])
def test_aggregate_worst_verdict_pairwise_dominance(winner, loser):
    agg = aggregate_lib.HostAggregator()
    for i, verdict in enumerate((winner, loser)):
        src = f"{verdict.lower()}.jsonl"
        agg.ingest(src, _manifest_for(f"h{i}"))
        agg.ingest(src, dict(_VERDICT_EVENTS[verdict]))
    assert agg.status()["aggregate"]["verdict"] == winner


def test_aggregate_counts_anomalies_and_names_fleet_straggler():
    agg = aggregate_lib.HostAggregator()
    for i, ms in enumerate([10.0, 10.0, 30.0]):
        src = f"h{i}.jsonl"
        agg.ingest(src, _manifest_for(f"h{i}"))
        agg.ingest(src, {"kind": "chunk", "chunk": 1, "steps": 4,
                         "ms_per_step": ms, "wall_s": ms * 4 / 1e3,
                         "recompiled": False, "t": time.time()})
    agg.ingest("h2.jsonl", dict(_VERDICT_EVENTS["DEGRADED"]))
    st = agg.status()
    assert st["aggregate"]["anomalies"] == 1
    assert st["aggregate"]["straggler"]["kind"] == "host"
    assert st["aggregate"]["straggler"]["name"].startswith("h2")
    assert st["aggregate"]["straggler"]["lag_ratio"] == 3.0


@pytest.mark.parametrize("action,expected", [
    ("warn", None),
    ("restart", ("verdict", "DEGRADED")),
    ("abort", ("fatal", "DEGRADED"))])
def test_supervisor_degraded_action_policy(action, expected):
    hit = sup._classify_event(dict(_ANOMALY_EV), sup.KILL_VERDICTS,
                              sup.FATAL_VERDICTS,
                              degraded_action=action)
    if expected is None:
        assert hit is None
    else:
        assert (hit[0], hit[1]) == expected
        assert "throughput_collapse" in hit[2]
        assert "h|p0" in hit[2]


def test_supervisor_classify_event_default_stays_compatible():
    """Old 3-positional-arg callers (and old behavior) still work."""
    e = {"kind": "heartbeat", "verdict": "WEDGED", "detail": "x"}
    assert sup._classify_event(e, sup.KILL_VERDICTS,
                               sup.FATAL_VERDICTS)[1] == "WEDGED"
    assert sup._classify_event(dict(_ANOMALY_EV), sup.KILL_VERDICTS,
                               sup.FATAL_VERDICTS) is None


def test_config_anomaly_flows_to_children_degraded_action_does_not():
    cfg = cli.config_from_args(
        ["--stencil", "heat2d", "--grid", "16,64", "--iters", "8",
         "--anomaly", "--degraded-action", "abort"])
    assert cfg.anomaly is True
    assert cfg.degraded_action == "abort"
    argv = config_lib.to_argv(cfg)
    # the child must run the doctor (its anomaly events are what the
    # parent's policy watches); the POLICY itself is parent-side only
    assert "--anomaly" in argv
    assert "--degraded-action" not in argv
    assert {"anomaly", "degraded_action"} <= config_lib.LIFECYCLE_FIELDS


# ----------------------------------------------------- faults (sleep)

def test_fault_sleep_grammar():
    spec, = faults.parse_specs("exchange:step=40:sleep:500")
    assert (spec.site, spec.action, spec.step, spec.sleep_ms) == \
        ("exchange", "sleep", 40, 500)
    for bad in ("exchange:sleep",          # no duration
                "exchange:sleep:0",        # not positive
                "heartbeat:sleep:100",     # not a sleep site
                "numerics:sleep:100"):
        with pytest.raises(ValueError):
            faults.parse_specs(bad)


def test_fault_sleep_fires_once_and_returns(monkeypatch):
    monkeypatch.setenv("FAULT_INJECT", "exchange:step=4:sleep:30")
    t0 = time.perf_counter()
    faults.maybe_fire("exchange", step=2)      # below the gate
    assert time.perf_counter() - t0 < 0.02
    t0 = time.perf_counter()
    faults.maybe_fire("exchange", step=4)      # fires, sleeps, RETURNS
    assert time.perf_counter() - t0 >= 0.03
    t0 = time.perf_counter()
    faults.maybe_fire("exchange", step=6)      # one-shot
    assert time.perf_counter() - t0 < 0.02


# --------------------------------------------------- flight recorder

def test_flight_ring_mirrors_every_session_record(tmp_path):
    from mpi_cuda_process_tpu import obs as obs_lib

    path = str(tmp_path / "ring.jsonl")
    with obs_lib.open_session(path, "cli", {"stencil": "heat2d"}) as s:
        assert s.flight is not None
        s.event("chunk", chunk=0, steps=2, ms_per_step=1.0,
                wall_s=0.002, recompiled=False)
        s.event("anomaly", **{k: v for k, v in _ANOMALY_EV.items()
                              if k not in ("kind", "t")})
    assert s.flight.manifest["tool"] == "cli"
    kinds = [r.get("kind") for r in s.flight.ring]
    assert "chunk" in kinds and "anomaly" in kinds
    assert s.flight.events_seen == len(s.flight.ring)


def test_bundle_roundtrip_and_verdict_replay(tmp_path, monkeypatch):
    monkeypatch.delenv("OBS_BUNDLE_DIR", raising=False)
    monkeypatch.delenv("OBS_BUNDLE_TUNNEL", raising=False)
    path = str(tmp_path / "run.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("chunk", chunk=0, steps=2, ms_per_step=1.0,
                wall_s=0.002, recompiled=False)
        w.event("anomaly", **{k: v for k, v in _ANOMALY_EV.items()
                              if k not in ("kind", "t")})
    out = flightrec_lib.bundle_from_log(path, reason="unit")
    assert out == str(tmp_path / "run.bundle.json")
    assert flightrec_lib.is_bundle_file(out)
    assert not flightrec_lib.is_bundle_file(path)
    b = flightrec_lib.read_bundle(out)  # read implies validate
    assert b["reason"] == "unit"
    # verdict=None replays the events through RunMetrics: the anomaly
    # event makes the post-mortem verdict DEGRADED — one definition
    assert b["verdict"] == "DEGRADED"
    assert b["anomalies"][0]["anomaly"] == "throughput_collapse"
    assert b["tunnel"]["verdict"] == "NOT_RUN"  # opt-in, default off
    assert b["events_seen"] == 2


def test_bundle_validate_lists_problems():
    with pytest.raises(ValueError, match="schema"):
        flightrec_lib.validate_bundle({"kind": "flight_bundle"})
    with pytest.raises(ValueError, match="reason"):
        flightrec_lib.validate_bundle({
            "schema": flightrec_lib.BUNDLE_SCHEMA,
            "kind": "flight_bundle", "created_at": time.time(),
            "reason": "", "events": [], "events_seen": 0,
            "open_spans": [], "anomalies": [],
            "tunnel": {"verdict": "NOT_RUN"}, "env": {}})


def test_bundle_from_session_swallows_fake_sessions():
    class _Fake:
        pass
    assert flightrec_lib.bundle_from_session(_Fake(), "x") is None


def test_obs_bundle_script_and_report_render_without_telemetry_dir(
        tmp_path, obs_report, capsys):
    """The acceptance pin: a fresh session reads the post-mortem from
    the bundle alone, original telemetry dir deleted."""
    import shutil

    tel = tmp_path / "tel"
    tel.mkdir()
    path = str(tel / "run.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("anomaly", **{k: v for k, v in _ANOMALY_EV.items()
                              if k not in ("kind", "t")})
        w.event("error", error="RuntimeError: boom")
    obs_bundle = _load_script("obs_bundle_t", "scripts/obs_bundle.py")
    out = str(tmp_path / "post.bundle.json")
    assert obs_bundle.main([path, "-o", out, "--no-tunnel"]) == 0
    shutil.rmtree(tel)  # the log is GONE; the bundle must suffice
    assert obs_report.main([out, "--check"]) == 0
    printed = capsys.readouterr().out
    assert "flight bundle" in printed
    assert "DEGRADED" in printed
    assert "throughput_collapse" in printed
    assert "RuntimeError: boom" in printed
    assert "obs_report --check: ok (flight bundle" in printed


def test_obs_report_check_rejects_tampered_bundle(tmp_path, obs_report,
                                                 capsys):
    path = str(tmp_path / "run.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("chunk", chunk=0, steps=2, ms_per_step=1.0,
                wall_s=0.002, recompiled=False)
    out = flightrec_lib.bundle_from_log(path, reason="unit")
    b = json.load(open(out))
    b["events"] = [{"kind": "chunk"}]  # schema-invalid event
    json.dump(b, open(out, "w"))
    assert obs_report.main([out, "--check"]) == 1
    capsys.readouterr()


# ------------------------------------------------------------ CLI e2e

_CLEAN_ARGS = ["--stencil", "heat2d", "--grid", "16,64", "--iters", "16",
               "--log-every", "2", "--anomaly"]


def test_cli_clean_run_zero_findings_no_bundle(tmp_path):
    path = str(tmp_path / "clean.jsonl")
    cli.run(cli.config_from_args(_CLEAN_ARGS + ["--telemetry", path]))
    assert _anomaly_events(path) == []
    assert not os.path.exists(str(tmp_path / "clean.bundle.json"))
    rows = ledger_lib.rows_from_log(path)
    assert rows and rows[0]["status"] == "ok"
    assert "degraded" not in (rows[0].get("detail") or {})


@pytest.mark.parametrize("op,grid", [
    ("heat3d", "8,8,128"), ("heat3d27", "8,8,128"),
    ("heat3d4th", "12,8,128"), ("wave2d", "16,64"),
    ("wave3d", "8,8,128"), ("advect2d", "16,64"),
    ("advect3d", "8,8,128"), ("grayscott2d", "16,64"),
    ("grayscott3d", "8,8,128"), ("sor2d", "16,64"),
    ("sor3d", "8,8,128"), ("life", "16,64"), ("mdf", "16,64")])
def test_cli_clean_run_every_op_zero_findings(op, grid, tmp_path):
    """The acceptance contract is per-op: no op's natural chunk-time
    profile (first-boundary setup, per-op compile shape) may read as
    an anomaly.  heat2d is pinned by the test above."""
    path = str(tmp_path / f"{op}.jsonl")
    cli.run(cli.config_from_args(
        ["--stencil", op, "--grid", grid, "--iters", "16",
         "--log-every", "2", "--anomaly", "--telemetry", path]))
    assert _anomaly_events(path) == []


def test_cli_injected_slowdown_flagged_with_bundle_and_ledger_flag(
        tmp_path, monkeypatch):
    """The acceptance chain, in-process: injected sleep -> anomaly
    event within 2 boundaries -> DEGRADED bundle on exit -> ledger row
    flagged degraded=N (NOT quarantined) -> perf_gate [degraded]."""
    monkeypatch.setenv("FAULT_INJECT", "exchange:step=8:sleep:500")
    path = str(tmp_path / "slow.jsonl")
    cli.run(cli.config_from_args(_CLEAN_ARGS + ["--telemetry", path]))
    anomalies = _anomaly_events(path)
    assert anomalies, "the 500ms injected stall must be flagged"
    flagged_steps = [e.get("step") for e in anomalies
                     if e.get("step") is not None]
    assert flagged_steps and min(flagged_steps) <= 12  # within 2 chunks
    assert all(e["suspect"]["name"] for e in anomalies)
    # the run FINISHED (a slow run is not a dead run) with a summary...
    assert any(e.get("kind") == "summary" for e in _events(path))
    # ...and left the post-mortem bundle even though nothing aborted
    bundle_path = str(tmp_path / "slow.bundle.json")
    assert os.path.exists(bundle_path)
    b = flightrec_lib.read_bundle(bundle_path)
    assert b["verdict"] == "DEGRADED"
    assert b["reason"] == "degraded"
    assert b["anomalies"]
    # ledger: honest but flagged, still scoreable, still a baseline
    rows = ledger_lib.rows_from_log(path)
    main_rows = [r for r in rows if r.get("value")]
    assert main_rows[0]["status"] == "ok"
    assert main_rows[0]["detail"]["degraded"] == len(anomalies)
    assert ledger_lib.best_known(main_rows)
    perf_gate = _load_script("perf_gate_anomaly_t", "scripts/perf_gate.py")
    ledger = str(tmp_path / "ledger.jsonl")
    verdicts, _ = perf_gate.gate(path, ledger, 0.10)
    assert any(v.get("degraded") for v in verdicts)
    assert "[degraded]" in perf_gate._table(verdicts)


def test_anomaly_jaxpr_invariance_on_vs_off(tmp_path):
    """Acceptance pin: the jitted step jaxpr is byte-identical with
    --anomaly on vs off — the doctor is host Python at chunk
    boundaries, never ops in the step."""
    st = make_stencil("heat2d")
    fields = init_state(st, (16, 64), seed=0, kind="pulse")
    step = driver.make_step(st, (16, 64))
    abstract = tuple(jax.ShapeDtypeStruct(f.shape, f.dtype)
                     for f in fields)
    jaxpr_before = str(jax.make_jaxpr(step)(abstract))
    runner_before = str(jax.make_jaxpr(
        driver.make_runner(step, 4, jit=False))(abstract))
    cli.run(cli.config_from_args(
        _CLEAN_ARGS + ["--telemetry", str(tmp_path / "jx.jsonl")]))
    assert str(jax.make_jaxpr(step)(abstract)) == jaxpr_before
    assert str(jax.make_jaxpr(
        driver.make_runner(step, 4, jit=False))(abstract)) == \
        runner_before


def test_engine_handle_surfaces_anomalies(tmp_path):
    from mpi_cuda_process_tpu.engine import SimulationEngine

    eng = SimulationEngine(telemetry_dir=str(tmp_path))
    h = eng.submit(cli.config_from_args(_CLEAN_ARGS))
    h.result(timeout=120)
    assert h.anomalies() == []  # clean run: the doctor stays silent


# ------------------------------------------------------------ obs_top

def test_obs_top_health_rc_degraded_nonzero(obs_top):
    assert obs_top.health_rc({"verdict": "DEGRADED"}) == 1
    assert obs_top.health_rc({"verdict": "DONE"}) == 0


def test_obs_top_anomaly_panel(obs_top):
    lines = obs_top._anomaly_lines({"anomalies": {
        "count": 3, "kinds": {"straggler": 1, "recompile": 2},
        "last": {"anomaly": "straggler", "severity": "warn",
                 "suspect": {"kind": "group", "name": "g1:wave3d",
                             "lag_ratio": 2.4}},
        "suspect": {"kind": "group", "name": "g1:wave3d",
                    "lag_ratio": 2.4}}})
    body = "\n".join(lines)
    assert "3 anomaly finding(s)" in body
    assert "recompile=2" in body
    assert "suspect=group:g1:wave3d (x2.4)" in body
    assert obs_top._anomaly_lines({}) == []  # clean run: no panel


def test_obs_top_ledger_frame_flags_stale_baselines(tmp_path, obs_top):
    now = time.time()
    rows = [ledger_lib.make_row("old|cpu:x", 5.0, source="r1",
                                measured_at=now - 40 * 86400,
                                expected_backend="cpu"),
            ledger_lib.make_row("mid|cpu:x", 6.0, source="r2",
                                measured_at=now - 86400,
                                expected_backend="cpu"),
            ledger_lib.make_row("new|cpu:x", 7.0, source="r3",
                                measured_at=now,
                                expected_backend="cpu")]
    path = str(tmp_path / "ledger.jsonl")
    ledger_lib.append_rows(rows, path)
    body = obs_top.ledger_frame(path)
    assert "age_d" in body and "stale?" in body
    stale_lines = [ln for ln in body.splitlines() if "stale?" in ln]
    assert len(stale_lines) == 1  # only the 40-day row: latest 2
    assert "old|cpu" in stale_lines[0]  # measurement days stay fresh


# --------------------------------------------------------- obs_report

def test_obs_report_renders_anomaly_block_from_log(tmp_path, obs_report):
    path = str(tmp_path / "r.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("anomaly", **{k: v for k, v in _ANOMALY_EV.items()
                              if k not in ("kind", "t")})
    body = obs_report.render(path)
    assert "run-doctor findings (1)" in body
    assert "throughput_collapse" in body
    assert "host:h|p0" in body


# ------------------------------------------------------ trace export

def test_trace_export_group_tracks_and_anomaly_instants(tmp_path):
    exp = _load_script("obs_trace_export_anomaly_t",
                       "scripts/obs_trace_export.py")
    path = str(tmp_path / "g.jsonl")
    with trace_lib.TraceWriter(path) as w:
        w.write_manifest(trace_lib.build_manifest("cli", {}))
        w.event("policy_group", group="g0:heat2d", clause="heat2d",
                modes=["exchange=collective"], locked=False,
                provenance="measured")
        for grp in ("g0:heat2d", "g1:wave3d"):
            w.event("group_chunk", step=4, group=grp, op=grp.split(":")[1],
                    steps=4, wall_s=0.02, ready_ms_per_step=3.1,
                    mcells_per_s=12.5)
        w.event("health", group="g1:wave3d", verdict="HEALTHY",
                reason=None, step=4)
        w.event("migrate", step=8, n=2, label="x", provenance="measured")
        w.event("anomaly", **{k: v for k, v in _ANOMALY_EV.items()
                              if k not in ("kind", "t")})
    obj = exp.build_trace([path])
    assert exp.validate_export(obj) == []
    evs = obj["traceEvents"]
    names = [e["name"] for e in evs]
    # one synthetic track per group, named thread:group
    gtracks = [e for e in evs if e["ph"] == "M"
               and e["name"] == "thread_name"
               and ":" in (e["args"].get("name") or "")]
    assert {e["args"]["name"].split(":", 1)[1] for e in gtracks} == \
        {"g0:heat2d", "g1:wave3d"}  # track name = "<thread>:<group>"
    gslices = [e for e in evs if e.get("cat") == "group_chunk"]
    assert len(gslices) == 2
    assert {e["args"]["group"] for e in gslices} == \
        {"g0:heat2d", "g1:wave3d"}
    assert all(e["args"]["ready_ms_per_step"] == 3.1 for e in gslices)
    assert len({e["tid"] for e in gslices}) == 2  # distinct tracks
    assert "policy_group g0:heat2d" in names
    assert "health g1:wave3d HEALTHY" in names
    assert "migrate@8" in names
    anom = [e for e in evs if e.get("cat") == "anomaly"]
    assert anom[0]["name"] == "anomaly throughput_collapse"
    assert anom[0]["args"]["suspect"] == "host:h|p0"
