"""Test configuration: run everything on 8 virtual CPU devices.

The TPU-world equivalent of testing MPI code without mpirun (SURVEY.md §4.4):
``--xla_force_host_platform_device_count=8`` gives every mesh / sharding /
ppermute test 8 fake devices on one host.  The CPU-forcing recipe (env vars
plus the in-process ``jax.config.update`` that beats the axon sitecustomize)
lives in repo-root ``cpuforce.py`` — shared with ``__graft_entry__``'s
hermetic dryrun child — which deliberately does NOT import the package, so
env vars are set before any framework (and hence jax-backend) code runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cpuforce import force_cpu  # noqa: E402

# TPU smoke tier (docs/STATE.md runbook step 5): `TPU_SMOKE=1 pytest -m tpu`
# leaves the real backend in place so the smoke tests exercise the actual
# chip.  BOTH signals are required — the env var alone must not flip a
# plain `pytest tests -q` (with TPU_SMOKE still exported) onto the real
# backend, so the decision lives in pytest_configure where the final -m
# expression is known.  force_cpu there still precedes every test import
# (configure runs before collection), which is early enough for the
# backend override.


def _tpu_tier_selected(config) -> bool:
    markexpr = getattr(config.option, "markexpr", "") or ""
    return bool(os.environ.get("TPU_SMOKE")) and \
        "tpu" in markexpr and "not tpu" not in markexpr


def pytest_configure(config):
    # The benchmark drivers auto-ingest into the campaign ledger
    # (obs/ledger.py); tests that exercise them must never append to the
    # repo's committed benchmarks/ledger.jsonl.  An explicit pre-set
    # path (a test harness choosing its own) is left untouched.
    if "OBS_LEDGER_PATH" not in os.environ:
        import tempfile

        os.environ["OBS_LEDGER_PATH"] = os.path.join(
            tempfile.mkdtemp(prefix="obs-ledger-test-"), "ledger.jsonl")
    if _tpu_tier_selected(config):
        return  # real backend stays for the -m tpu smoke tier
    # Leave an explicit pre-set device count untouched so an outer harness
    # can choose its own count via XLA_FLAGS.
    _n = (
        None
        if "xla_force_host_platform_device_count"
        in os.environ.get("XLA_FLAGS", "")
        else 8
    )
    force_cpu(_n)
