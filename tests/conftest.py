"""Test configuration: run everything on 8 virtual CPU devices.

The TPU-world equivalent of testing MPI code without mpirun (SURVEY.md §4.4):
``--xla_force_host_platform_device_count=8`` gives every mesh / sharding /
ppermute test 8 fake devices on one host.  Must be set before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The axon TPU sitecustomize force-selects its platform via jax.config after
# register(), which overrides JAX_PLATFORMS — override it back to CPU here
# (before any backend is initialized, so XLA_FLAGS still applies).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

